// tario: threaded tar-shard sample reader (C ABI for ctypes).
//
// The reference delegated its host-side IO parallelism to 40 torch
// DataLoader worker *processes* (/root/reference/src/dataset.py:129-140) —
// heavyweight, fork-cost-heavy, and opaque. This native core gives the
// framework's Python loader an alternative substrate: N reader THREADS in
// one process stream disjoint stripes of tar shards, parse ustar headers,
// group members into samples (key = basename up to first dot), and push
// them into bounded queues the GIL-free way; Python pops raw
// (image-bytes, label) pairs and keeps decode/augment in cv2/numpy.
//
// DETERMINISTIC ORDER: thread t statically owns shards t, t+T, t+2T, ...
// and fills its own queue; the (single) consumer merges queues in strict
// round-robin, skipping exhausted threads at the deterministic point where
// their stripe ends. The output sequence is therefore a pure function of
// (shard list, thread count) — same contract as the Python worker path —
// which is what makes sample-exact resume possible on this substrate.
//
// Corrupt members/truncated shards are skipped (the reference's
// ignore_and_continue contract — deterministic too: same bytes, same
// skips). Supports plain files and "pipe:CMD" URLs (popen), matching
// data/tario.py.
//
// Build: g++ -O2 -shared -fPIC -o libtario.so tario.cc -lpthread

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Sample {
  std::string key;
  std::vector<uint8_t> image;
  int64_t label;  // -1 when no .cls member
};

struct BoundedQueue {
  std::deque<Sample*> items;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  size_t capacity;
  std::atomic<int> producers_left{0};
  std::atomic<bool> closed{false};

  explicit BoundedQueue(size_t cap) : capacity(cap) {}

  // returns false if the queue was closed (consumer shut down)
  bool push(Sample* s) {
    std::unique_lock<std::mutex> lk(mu);
    not_full.wait(lk, [&] { return items.size() < capacity || closed; });
    if (closed) return false;
    items.push_back(s);
    not_empty.notify_one();
    return true;
  }

  // nullptr => end of stream (all producers done) or closed
  Sample* pop() {
    std::unique_lock<std::mutex> lk(mu);
    not_empty.wait(lk, [&] {
      return !items.empty() || producers_left.load() == 0 || closed;
    });
    if (items.empty()) return nullptr;
    Sample* s = items.front();
    items.pop_front();
    not_full.notify_one();
    return s;
  }

  void close() {
    std::lock_guard<std::mutex> lk(mu);
    closed = true;
    not_full.notify_all();
    not_empty.notify_all();
  }

  void producer_done() {
    producers_left.fetch_sub(1);
    std::lock_guard<std::mutex> lk(mu);
    not_empty.notify_all();
  }
};

// ----------------------------------------------------------------- tar input
struct Stream {
  FILE* f = nullptr;
  bool piped = false;

  bool open(const std::string& url) {
    if (url.rfind("pipe:", 0) == 0) {
      f = popen(url.c_str() + 5, "r");
      piped = true;
    } else {
      f = fopen(url.c_str(), "rb");
      piped = false;
    }
    return f != nullptr;
  }
  size_t read(void* buf, size_t n) { return f ? fread(buf, 1, n, f) : 0; }
  void close() {
    if (!f) return;
    if (piped) pclose(f);
    else fclose(f);
    f = nullptr;
  }
};

int64_t parse_octal(const char* p, size_t n) {
  int64_t v = 0;
  for (size_t i = 0; i < n && p[i]; ++i) {
    if (p[i] < '0' || p[i] > '7') continue;
    v = v * 8 + (p[i] - '0');
  }
  return v;
}

// tar size field: octal, or GNU base-256 (high bit of byte 0 set) for
// members >= 8 GiB written by GNU tar
int64_t parse_size_field(const char* p, size_t n) {
  if (n > 0 && (unsigned char)p[0] & 0x80) {
    int64_t v = (unsigned char)p[0] & 0x7f;
    for (size_t i = 1; i < n; ++i) v = (v << 8) | (unsigned char)p[i];
    return v;
  }
  return parse_octal(p, n);
}

// read exactly n bytes; false on short read (truncated stream)
bool read_fully(Stream& in, void* buf, size_t n) {
  size_t got = 0;
  char* p = static_cast<char*>(buf);
  while (got < n) {
    size_t r = in.read(p + got, n - got);
    if (r == 0) return false;
    got += r;
  }
  return true;
}

bool skip_bytes(Stream& in, int64_t n) {
  char buf[4096];
  while (n > 0) {
    size_t r = in.read(buf, n > 4096 ? 4096 : (size_t)n);
    if (r == 0) return false;
    n -= (int64_t)r;
  }
  return true;
}

bool is_zero_block(const char* b) {
  for (int i = 0; i < 512; ++i)
    if (b[i]) return false;
  return true;
}

// split "dir/key.ext" -> (stem including dir, ext after FIRST dot of basename)
void split_name(const std::string& name, std::string* stem, std::string* ext) {
  size_t slash = name.find_last_of('/');
  size_t start = slash == std::string::npos ? 0 : slash + 1;
  size_t dot = name.find('.', start);
  if (dot == std::string::npos) {
    *stem = name;
    ext->clear();
  } else {
    *stem = name.substr(0, dot);
    *ext = name.substr(dot + 1);
  }
}

bool image_ext(const std::string& e) {
  return e == "jpg" || e == "jpeg" || e == "png" || e == "ppm" || e == "bmp" ||
         e == "webp";
}

struct Reader;

struct Handle {
  std::vector<std::string> urls;
  // one queue per reader thread: the consumer's round-robin merge over
  // these is what makes the output order deterministic
  std::vector<std::unique_ptr<BoundedQueue>> queues;
  std::vector<std::thread> threads;
  std::vector<bool> exhausted;  // consumer-side; single consumer only
  size_t rr = 0;
  bool loop;

  explicit Handle(bool loop_) : loop(loop_) {}
};

void reader_main(Handle* h, size_t tid) {
  BoundedQueue* q = h->queues[tid].get();
  size_t n_threads = h->queues.size();
  char header[512];
  // static stripe: tid, tid+T, tid+2T, ... (never work-stealing — the
  // stripe assignment must be a pure function of the shard list)
  for (size_t pos = tid;; pos += n_threads) {
    if (pos >= h->urls.size()) {
      if (!h->loop) break;
      pos = tid;
      if (pos >= h->urls.size()) break;  // more threads than shards
    }
    size_t idx = pos;
    Stream in;
    if (!in.open(h->urls[idx])) continue;

    std::string cur_stem;
    Sample* cur = nullptr;
    std::string pending_name;  // from a PAX 'x' / GNU 'L' header
    int64_t pending_size = -1;  // from a PAX "size=" record (>= 8 GiB members)
    for (;;) {  // breaks on end-of-archive or truncation (partial sample still flushes below)
      if (in.read(header, 512) != 512) break;
      if (is_zero_block(header)) break;  // end-of-archive marker
      // ustar: name at 0 (100), size at 124 (12), typeflag at 156,
      // optional prefix at 345 (155)
      std::string name(header, strnlen(header, 100));
      if (header[345]) {
        std::string prefix(header + 345, strnlen(header + 345, 155));
        name = prefix + "/" + name;
      }
      int64_t size = parse_size_field(header + 124, 12);
      char type = header[156];

      // PAX 'x' / GNU 'L' headers carry the REAL path (and, for >= 8 GiB
      // members, the real size) of the next member (python tarfile writes
      // PAX by default): the ustar fields are then truncated/zeroed, and
      // using them would mis-group samples or desync the stream. Parse
      // instead of skipping.
      if ((type == 'x' || type == 'L') && size >= 0 && size <= (1 << 20)) {
        std::string payload((size_t)size, '\0');
        if (!read_fully(in, payload.data(), (size_t)size)) break;
        if (!skip_bytes(in, ((size + 511) & ~511LL) - size)) break;
        if (type == 'L') {
          pending_name.assign(payload.c_str());  // NUL-terminated full name
        } else {
          // PAX records: "<len> key=value\n"; len covers the whole record
          size_t pos = 0;
          while (pos < payload.size()) {
            size_t sp = payload.find(' ', pos);
            if (sp == std::string::npos) break;
            long rec_len = strtol(payload.c_str() + pos, nullptr, 10);
            if (rec_len <= 0 || pos + (size_t)rec_len > payload.size()) break;
            std::string rec = payload.substr(sp + 1, pos + rec_len - sp - 2);
            if (rec.rfind("path=", 0) == 0) pending_name = rec.substr(5);
            if (rec.rfind("size=", 0) == 0)
              pending_size = strtoll(rec.c_str() + 5, nullptr, 10);
            pos += (size_t)rec_len;
          }
        }
        continue;
      }
      if (pending_size >= 0) {
        size = pending_size;
        pending_size = -1;
      }
      int64_t padded = (size + 511) & ~511LL;

      bool regular = (type == '0' || type == 0);
      if (!regular || size < 0) {  // skip payload of non-regular members
        pending_name.clear();  // overrides apply only to the NEXT member
        if (!skip_bytes(in, padded)) break;
        continue;
      }
      if (!pending_name.empty()) {
        name = pending_name;
        pending_name.clear();
      }

      std::vector<uint8_t> payload((size_t)size);
      if (!read_fully(in, payload.data(), (size_t)size)) break;
      if (!skip_bytes(in, padded - size)) break;

      std::string stem, ext;
      split_name(name, &stem, &ext);
      if (stem != cur_stem) {
        if (cur && !cur->image.empty()) {
          if (!q->push(cur)) { delete cur; in.close(); q->producer_done(); return; }
        } else {
          delete cur;
        }
        cur = new Sample();
        cur->label = -1;
        cur->key = stem;
        cur_stem = stem;
      }
      if (cur) {
        if (image_ext(ext)) {
          cur->image = std::move(payload);
        } else if (ext == "cls") {
          cur->label = strtoll(
              std::string(payload.begin(), payload.end()).c_str(), nullptr, 10);
        }
      }
    }
    if (cur && !cur->image.empty()) {
      if (!q->push(cur)) { delete cur; in.close(); q->producer_done(); return; }
    } else {
      delete cur;
    }
    in.close();
  }
  q->producer_done();
}

}  // namespace

extern "C" {

// urls: NUL-separated, double-NUL terminated. Returns opaque handle.
void* tario_open(const char* urls, int n_threads, int queue_capacity,
                 int loop) {
  auto* h = new Handle(loop != 0);
  const char* p = urls;
  while (*p) {
    h->urls.emplace_back(p);
    p += h->urls.back().size() + 1;
  }
  if (n_threads < 1) n_threads = 1;
  size_t per_q = (size_t)queue_capacity / (size_t)n_threads;
  if (per_q < 2) per_q = 2;
  for (int i = 0; i < n_threads; ++i) {
    h->queues.emplace_back(new BoundedQueue(per_q));
    h->queues.back()->producers_left = 1;
  }
  h->exhausted.assign((size_t)n_threads, false);
  for (int i = 0; i < n_threads; ++i)
    h->threads.emplace_back(reader_main, h, (size_t)i);
  return h;
}

// Pops one sample in deterministic round-robin order over the reader
// threads' queues. Returns 1 on success, 0 on end-of-stream. Single
// consumer only. On success *out_data/*out_len hold the image bytes
// (valid until tario_free), *out_label the class (-1 if absent).
int tario_next(void* handle, const uint8_t** out_data, int64_t* out_len,
               int64_t* out_label, void** out_token) {
  auto* h = static_cast<Handle*>(handle);
  size_t n = h->queues.size();
  for (;;) {
    bool all_done = true;
    for (size_t k = 0; k < n; ++k)
      if (!h->exhausted[k]) { all_done = false; break; }
    if (all_done) return 0;
    size_t i = h->rr;
    h->rr = (h->rr + 1) % n;
    if (h->exhausted[i]) continue;
    // blocks on THIS thread's queue even if others have data — strict
    // round-robin is the determinism contract, and per-queue prefetch
    // keeps the wait short in steady state
    Sample* s = h->queues[i]->pop();
    if (!s) {
      h->exhausted[i] = true;  // its stripe ended at a deterministic point
      continue;
    }
    *out_data = s->image.data();
    *out_len = (int64_t)s->image.size();
    *out_label = s->label;
    *out_token = s;
    return 1;
  }
}

void tario_free(void* token) { delete static_cast<Sample*>(token); }

void tario_close(void* handle) {
  auto* h = static_cast<Handle*>(handle);
  for (auto& q : h->queues) q->close();
  for (auto& t : h->threads) t.join();
  // drain anything left
  for (auto& q : h->queues) {
    std::lock_guard<std::mutex> lk(q->mu);
    for (Sample* s : q->items) delete s;
    q->items.clear();
  }
  delete h;
}

}  // extern "C"
