#!/usr/bin/env python3
"""Hyperparameter sweep driver — replaces the reference's bash for-loops
(``/root/reference/config/loop_1.sh``, ``loop_2.sh``: wd × lr grids at
layer-decay 0.65) with a python grid over config overrides.

Usage: python recipes/sweep_ft.py [--dry-run]
"""

import argparse
import itertools

WEIGHT_DECAYS = [0.06, 0.07, 0.08, 0.09]
LEARNING_RATES = [1e-3, 3e-3]
LAYER_DECAY = 0.65


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dry-run", action="store_true")
    parser.add_argument("--config", default="recipes/finetune_vit_b16.yaml")
    args = parser.parse_args()
    for wd, lr in itertools.product(WEIGHT_DECAYS, LEARNING_RATES):
        overrides = [
            f"optim.weight_decay={wd}",
            f"optim.learning_rate={lr}",
            f"optim.layer_decay={LAYER_DECAY}",
            f"run.name=ft_sweep_wd{wd}_lr{lr}",
        ]
        print("sweep:", overrides)
        if not args.dry_run:
            from jumbo_mae_tpu_tpu.cli.train import main as train_main

            train_main(["--config", args.config, "--set", *overrides])


if __name__ == "__main__":
    main()
