#!/usr/bin/env python
"""Offline perf diagnosis: regressions from BENCH_HISTORY.jsonl, or the
compiled-cost story of one training run's journal.

Ledger mode (the default — point it at a ``BENCH_HISTORY.jsonl`` written by
``bench.py`` / ``tools/bench_infer.py``):

- groups rows by (bench, metric, env_key) — rows are only ever baselined
  against history from the *same* environment fingerprint subset;
- the latest row of each group is compared leg-by-leg against the median of
  the previous ``--baseline-window`` rows, with a stated ``--noise`` band;
  leg direction is inferred from its name (``ms``/``latency``/``seconds``/
  ``p50``/``p99`` → lower is better, anything else → higher is better);
- each verdict names the regressed leg, the delta vs the trailing median,
  and the dominant roofline term of the row's cost-model prediction — the
  first question after "it got slower" is "was it compute- or
  bandwidth-bound when it did";
- predicted-vs-measured gap triage is advisory: on the CPU smoke backend
  the chip spec is an order-of-magnitude generic, so the gap classifies
  plumbing health, not capacity.

Journal mode (auto-detected when the path holds run-journal events): lists
every ``compiled_program`` event's XLA costs, its roofline bound, and any
published predict-vs-measured drift.

Exit codes: 0 = no regression (diagnosis written), 2 = regression detected
or nothing to diagnose. Like run_doctor/serve_doctor, needs only the
artifact — no backend, no live process.
"""

from __future__ import annotations

import argparse
import statistics
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from jumbo_mae_tpu_tpu.obs.doctor_common import fmt_num, write_report  # noqa: E402

# leg-name tokens meaning "lower is better"; matched on "_"-split tokens,
# not raw substrings, so ``imgs_per_sec`` ("_s"…) stays higher-is-better
_LOWER_BETTER = {"ms", "s", "latency", "seconds", "p50", "p90", "p99", "p999", "time"}
_HIGHER_BETTER = {"throughput", "qps", "speedup"}


def leg_lower_is_better(name: str) -> bool:
    tokens = set(name.lower().split("_"))
    if tokens & _HIGHER_BETTER or "per" in tokens:  # *_per_sec rates
        return False
    return bool(tokens & _LOWER_BETTER)


def _dominant_term(row: dict) -> str | None:
    pred = row.get("prediction")
    if isinstance(pred, dict):
        return pred.get("bound")
    return None


def _gap_triage(row: dict) -> tuple[float, str] | None:
    """measured / predicted for the row's headline step-time leg."""
    pred = row.get("prediction")
    if not isinstance(pred, dict) or not pred.get("step_time_s"):
        return None
    legs = row.get("legs", {})
    measured_s = None
    for name in ("ms_step_bf16", "ms_step", "p50_ms"):
        if legs.get(name):
            measured_s = float(legs[name]) / 1e3
            break
    if measured_s is None:
        return None
    ratio = measured_s / float(pred["step_time_s"])
    if ratio < 2.0:
        verdict = "near its roofline"
    elif ratio < 10.0:
        verdict = "loose vs its roofline (host/dispatch overhead or an untuned shape)"
    else:
        verdict = "detached from its roofline (generic chip spec, or a stall)"
    return ratio, verdict


def diagnose_ledger(
    rows: list[dict], *, baseline_window: int, noise: float
) -> tuple[str, bool]:
    """Markdown diagnosis + whether any leg regressed."""
    groups: dict[tuple, list[dict]] = {}
    for r in rows:
        groups.setdefault(
            (r.get("bench"), r.get("metric"), r.get("env_key")), []
        ).append(r)

    lines = [
        "# perf_doctor",
        "",
        f"- rows: {len(rows)} across {len(groups)} (bench, metric, env) group(s)",
        f"- baseline: median of the previous ≤{baseline_window} comparable "
        f"rows; noise band ±{noise:.0%}",
        "",
    ]
    regressions: list[str] = []
    improvements: list[str] = []
    for (bench, metric, env_key), grp in sorted(
        groups.items(), key=lambda kv: str(kv[0])
    ):
        latest, history = grp[-1], grp[:-1][-baseline_window:]
        lines.append(f"## {bench} · {metric}")
        lines.append("")
        lines.append(
            f"- env_key `{env_key}` · {len(grp)} row(s) · latest git "
            f"`{latest.get('git_sha') or '?'}`"
        )
        term = _dominant_term(latest)
        if term:
            lines.append(f"- dominant roofline term: **{term}**")
        gap = _gap_triage(latest)
        if gap:
            lines.append(
                f"- predicted-vs-measured: {fmt_num(gap[0], 3)}× — {gap[1]} "
                "(advisory)"
            )
        lines.append("")
        if not history:
            lines.append("- first row for this group — nothing to baseline against")
            lines.append("")
            continue
        lines.append("| leg | latest | trailing median | Δ | verdict |")
        lines.append("|---|---|---|---|---|")
        for leg, value in latest.get("legs", {}).items():
            base_vals = [
                float(h["legs"][leg])
                for h in history
                if isinstance(h.get("legs", {}).get(leg), (int, float))
            ]
            if not base_vals or not isinstance(value, (int, float)):
                continue
            base = statistics.median(base_vals)
            if base == 0:
                continue
            delta = float(value) / base - 1.0
            lower = leg_lower_is_better(leg)
            regressed = delta > noise if lower else delta < -noise
            improved = delta < -noise if lower else delta > noise
            verdict = "regressed" if regressed else ("improved" if improved else "ok")
            lines.append(
                f"| {leg} | {fmt_num(value)} | {fmt_num(base)} | "
                f"{delta:+.1%} | {verdict} |"
            )
            if regressed:
                regressions.append(
                    f"leg `{leg}` of {metric} regressed {delta:+.1%} vs the "
                    f"trailing median {fmt_num(base)} (noise band ±{noise:.0%})"
                    + (f"; dominant roofline term: {term}" if term else "")
                )
            elif improved:
                improvements.append(f"leg `{leg}` of {metric} improved {delta:+.1%}")
        lines.append("")

    lines.append("## Verdict")
    lines.append("")
    if regressions:
        for r in regressions:
            lines.append(f"- **REGRESSION**: {r}")
    else:
        lines.append(
            f"- no leg moved beyond the ±{noise:.0%} noise band against its "
            "trailing median — no regression"
        )
    for s in improvements:
        lines.append(f"- {s}")
    return "\n".join(lines) + "\n", bool(regressions)


def diagnose_journal(events: list[dict]) -> tuple[str, bool]:
    """Compiled-cost story of one run: programs, costs, roofline bounds."""
    programs = [e for e in events if e.get("type") == "compiled_program"]
    steps = [e for e in events if e.get("type") == "step"]
    lines = ["# perf_doctor (run journal)", ""]
    if programs:
        lines.append("| program | flops | bytes accessed | peak bytes | source |")
        lines.append("|---|---|---|---|---|")
        for p in programs:
            lines.append(
                f"| {p.get('program')} | {fmt_num(p.get('flops', 0))} | "
                f"{fmt_num(p.get('bytes_accessed', 0))} | "
                f"{fmt_num(p.get('peak_bytes', 0))} | {p.get('source')} |"
            )
        lines.append("")
    drift = [
        s["perf/predict_vs_measured"]
        for s in steps
        if isinstance(s.get("perf/predict_vs_measured"), (int, float))
    ]
    lines.append("## Verdict")
    lines.append("")
    if not programs:
        lines.append(
            "- no `compiled_program` events — this run predates the cost "
            "model or the backend reported no cost analysis"
        )
    else:
        lines.append(
            f"- {len(programs)} compiled program(s) with XLA cost accounting"
        )
    if drift:
        last = drift[-1]
        lines.append(
            f"- predicted-vs-measured drift over the run: last "
            f"{fmt_num(last, 3)}×, median {fmt_num(statistics.median(drift), 3)}× "
            "(advisory on non-TPU chip specs)"
        )
    return "\n".join(lines) + "\n", False


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument(
        "path",
        help="BENCH_HISTORY.jsonl (ledger mode) or a run dir / journal "
        "(journal mode, auto-detected)",
    )
    p.add_argument("--out", default="", help="write the markdown here (default stdout)")
    p.add_argument(
        "--baseline-window",
        type=int,
        default=5,
        help="trailing comparable rows the median baseline uses (default 5)",
    )
    p.add_argument(
        "--noise",
        type=float,
        default=0.08,
        help="relative noise band a leg must exceed to count (default 0.08)",
    )
    args = p.parse_args(argv)

    from jumbo_mae_tpu_tpu.obs.journal import read_journal
    from jumbo_mae_tpu_tpu.obs.perfledger import read_ledger

    try:
        rows = read_ledger(args.path)
    except FileNotFoundError:
        print(f"[perf_doctor] no ledger or journal at {args.path}", file=sys.stderr)
        return 2
    if rows:
        md, regressed = diagnose_ledger(
            rows, baseline_window=args.baseline_window, noise=args.noise
        )
    else:
        events = read_journal(args.path)
        if not any(e.get("type") for e in events):
            print(
                f"[perf_doctor] {args.path} holds neither ledger rows nor "
                "journal events",
                file=sys.stderr,
            )
            return 2
        md, regressed = diagnose_journal(events)
    rc = write_report(md, args.out or None, tool="perf_doctor")
    if regressed:
        print("[perf_doctor] perf regression detected", file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
