#!/usr/bin/env python3
"""Offline jumbo checkpoint converter (flax ↔ PyTorch).

Replaces the reference's stale plain-ViT converters
(``/root/reference/scripts/convert_flax_to_pytorch.py``,
``convert_pytorch_to_flax.py`` — SURVEY defect #4) with ones that understand
the jumbo layout.

    python tools/convert_checkpoint.py to-torch  ckpt.msgpack out.pth
    python tools/convert_checkpoint.py to-torch  runs/x/ckpt   out.pth
    python tools/convert_checkpoint.py to-flax   in.pth out.msgpack --heads 12
    python tools/convert_checkpoint.py to-flax   vit_base_patch16_224 out.msgpack \
        --heads 12 --from-timm [--exclude-head]

``--from-timm`` pulls pretrained weights from the timm hub by model name
(parity: ``/root/reference/scripts/convert_pytorch_to_flax.py:24-51``) and
adapts the plain-ViT layout into the jumbo one (CLS posemb folded + tiled
to ``--cls-tokens``; the shared jumbo MLP keeps fresh init on warm start).
"""

from __future__ import annotations

import argparse
from pathlib import Path


def main(argv: list[str] | None = None):
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)
    tt = sub.add_parser("to-torch")
    tt.add_argument("src", help=".msgpack params file or Orbax ckpt directory")
    tt.add_argument("dst", help="output .pth path")
    tf = sub.add_parser("to-flax")
    tf.add_argument("src", help="input .pth path, or a timm model name with --from-timm")
    tf.add_argument("dst", help="output .msgpack path")
    tf.add_argument("--heads", type=int, required=True, help="attention heads")
    tf.add_argument(
        "--from-timm",
        action="store_true",
        help="treat src as a timm model name and pull pretrained hub weights",
    )
    tf.add_argument(
        "--exclude-head",
        action="store_true",
        help="with --from-timm: drop the classification head (num_classes=0)",
    )
    tf.add_argument(
        "--cls-tokens",
        type=int,
        default=3,
        help="with --from-timm: tile the plain-ViT CLS token to this many "
        "jumbo CLS slots (default 3)",
    )
    args = parser.parse_args(argv)

    import torch

    from jumbo_mae_tpu_tpu.interop import flax_to_torch_state, torch_to_flax_params
    from jumbo_mae_tpu_tpu.train.checkpoint import (
        export_params_msgpack,
        import_params_msgpack,
        restore_params_any,
    )

    if args.cmd == "to-torch":
        src = Path(args.src)
        params = (
            restore_params_any(src) if src.is_dir() else import_params_msgpack(src)
        )
        state = flax_to_torch_state(params)
        torch.save({k: torch.from_numpy(v.copy()) for k, v in state.items()}, args.dst)
        print(f"wrote {len(state)} tensors → {args.dst}")
    else:
        if args.from_timm:
            sd = load_timm_state_dict(args.src, exclude_head=args.exclude_head)
            from jumbo_mae_tpu_tpu.interop import timm_plain_vit_to_jumbo_state

            sd = timm_plain_vit_to_jumbo_state(
                sd, num_cls_tokens=args.cls_tokens
            )
        else:
            sd = torch.load(args.src, map_location="cpu", weights_only=True)
            sd = {k: v.numpy() for k, v in sd.items()}
        tree = torch_to_flax_params(sd, heads=args.heads)
        tree.pop("__batch_stats__", None)
        export_params_msgpack({"model": tree}, args.dst)
        print(f"wrote flax params → {args.dst}")


def load_timm_state_dict(model_name: str, *, exclude_head: bool = False) -> dict:
    """Pull pretrained weights from the timm hub by model name, as numpy.
    Kept separate so tests can stub ``timm`` without network access."""
    try:
        import timm
    except ImportError as e:
        raise SystemExit(
            "--from-timm needs the `timm` package (and hub network access); "
            "install it or download the .pth and convert from the file"
        ) from e
    model = timm.create_model(
        model_name,
        pretrained=True,
        **({"num_classes": 0} if exclude_head else {}),
    )
    return {
        k: v.detach().cpu().numpy() for k, v in model.state_dict().items()
    }


if __name__ == "__main__":
    main()
