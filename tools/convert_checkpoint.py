#!/usr/bin/env python3
"""Offline jumbo checkpoint converter (flax ↔ PyTorch).

Replaces the reference's stale plain-ViT converters
(``/root/reference/scripts/convert_flax_to_pytorch.py``,
``convert_pytorch_to_flax.py`` — SURVEY defect #4) with ones that understand
the jumbo layout.

    python tools/convert_checkpoint.py to-torch  ckpt.msgpack out.pth
    python tools/convert_checkpoint.py to-torch  runs/x/ckpt   out.pth
    python tools/convert_checkpoint.py to-flax   in.pth out.msgpack --heads 12
"""

from __future__ import annotations

import argparse
from pathlib import Path


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)
    tt = sub.add_parser("to-torch")
    tt.add_argument("src", help=".msgpack params file or Orbax ckpt directory")
    tt.add_argument("dst", help="output .pth path")
    tf = sub.add_parser("to-flax")
    tf.add_argument("src", help="input .pth state-dict path")
    tf.add_argument("dst", help="output .msgpack path")
    tf.add_argument("--heads", type=int, required=True, help="attention heads")
    args = parser.parse_args()

    import torch

    from jumbo_mae_tpu_tpu.interop import flax_to_torch_state, torch_to_flax_params
    from jumbo_mae_tpu_tpu.train.checkpoint import (
        export_params_msgpack,
        import_params_msgpack,
        restore_params_any,
    )

    if args.cmd == "to-torch":
        src = Path(args.src)
        params = (
            restore_params_any(src) if src.is_dir() else import_params_msgpack(src)
        )
        state = flax_to_torch_state(params)
        torch.save({k: torch.from_numpy(v.copy()) for k, v in state.items()}, args.dst)
        print(f"wrote {len(state)} tensors → {args.dst}")
    else:
        sd = torch.load(args.src, map_location="cpu", weights_only=True)
        sd = {k: v.numpy() for k, v in sd.items()}
        tree = torch_to_flax_params(sd, heads=args.heads)
        tree.pop("__batch_stats__", None)
        export_params_msgpack({"model": tree}, args.dst)
        print(f"wrote flax params → {args.dst}")


if __name__ == "__main__":
    main()
