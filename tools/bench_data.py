"""Input-pipeline throughput benchmark (SURVEY §7 stage 4: "validate
throughput ≥ reference's torch pipeline").

Builds synthetic JPEG webdataset shards, then measures steady-state
imgs/sec for the same decode+augment work under each loader substrate:

- ``inline``  — single-stream Python reader (workers=0);
- ``workers`` — this framework's fresh-interpreter worker subprocesses;
- ``native``  — the C++ threaded tar reader (native/tario.cc) + thread-pool
  decode (GIL-releasing cv2/PIL);
- ``torch``   — the SAME sample stream wrapped in ``torch.utils.data
  .DataLoader`` with worker processes, i.e. the reference's loader machinery
  (``/root/reference/src/dataset.py:124-161``) with identical per-sample
  work (torchvision/timm aren't installed here; augmentation parity is
  tested separately in tests/test_transforms.py).

Usage: python tools/bench_data.py [--images 512] [--batches 20] [--batch 32]
Prints one JSON line per mode.
"""

from __future__ import annotations

import argparse
import io
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from jumbo_mae_tpu_tpu.data.loader import (  # noqa: E402
    DataConfig,
    TrainLoader,
    batch_train_samples,
    train_sample_stream,
)
from jumbo_mae_tpu_tpu.data.native import available as native_available  # noqa: E402
from jumbo_mae_tpu_tpu.data.tario import write_tar_samples  # noqa: E402


def shard_spec(root: Path, shards: int) -> str:
    return str(root / ("bench-{0000..%04d}.tar" % (shards - 1)))


def build_shards(root: Path, *, shards: int, per_shard: int, size: int) -> str:
    """Build the synthetic shard set, reusing an existing one only when it
    was built with identical parameters (recorded in a stamp file)."""
    from PIL import Image

    stamp = root / "bench-params.json"
    params = {"shards": shards, "per_shard": per_shard, "size": size}
    if (
        stamp.exists()
        and json.loads(stamp.read_text()) == params
        and all((root / f"bench-{s:04d}.tar").exists() for s in range(shards))
    ):
        return shard_spec(root, shards)

    rng = np.random.default_rng(0)
    for s in range(shards):
        samples = []
        for i in range(per_shard):
            arr = rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, "JPEG", quality=90)
            samples.append(
                {
                    "__key__": f"{s:04d}_{i:05d}",
                    "jpg": buf.getvalue(),
                    "cls": str(i % 1000).encode(),
                }
            )
        write_tar_samples(str(root / f"bench-{s:04d}.tar"), samples)
    stamp.write_text(json.dumps(params))
    return shard_spec(root, shards)


def drain(it, *, batches: int, warmup: int, batch_size: int) -> float:
    for _ in range(warmup):
        next(it)
    t0 = time.perf_counter()
    for _ in range(batches):
        next(it)
    return batches * batch_size / (time.perf_counter() - t0)


def bench_torch(
    cfg: DataConfig, batch_size: int, *, batches: int, warmup: int, workers: int
):
    from torch.utils import data as tdata

    class Stream(tdata.IterableDataset):
        def __iter__(self):
            info = tdata.get_worker_info()
            w, nw = (info.id, info.num_workers) if info else (0, 1)
            return train_sample_stream(cfg, worker_index=w, worker_count=nw)

    loader = tdata.DataLoader(
        Stream(),
        batch_size=batch_size,
        num_workers=workers,
        prefetch_factor=2 if workers else None,
        drop_last=True,
        collate_fn=lambda items: {
            "images": np.stack([i for i, _ in items]),
            "labels": np.array([l for _, l in items]),
        },
    )
    it = iter(loader)
    try:
        return drain(it, batches=batches, warmup=warmup, batch_size=batch_size)
    finally:
        del it, loader


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=512, help="total synthetic images")
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--batches", type=int, default=15)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--keep-dir", default=None, help="reuse/keep shard dir")
    args = ap.parse_args()

    root = Path(args.keep_dir) if args.keep_dir else Path(tempfile.mkdtemp(prefix="benchdata_"))
    root.mkdir(parents=True, exist_ok=True)
    shards = 4
    if args.images < shards:
        ap.error(f"--images must be ≥ {shards} (one sample per shard minimum)")
    if args.images % shards:
        ap.error(f"--images must be a multiple of {shards} (shard count)")
    spec = build_shards(
        root, shards=shards, per_shard=args.images // shards, size=args.size
    )

    base = dict(
        train_shards=spec,
        image_size=args.size,
        crop_mode="rrc",
        auto_augment="rand-m9-n2",
        shuffle_buffer=64,
        seed=0,
    )
    results = {}

    cfg = DataConfig(**base, workers=0)
    it = iter(TrainLoader(cfg, args.batch))
    results["inline"] = drain(
        it, batches=args.batches, warmup=args.warmup, batch_size=args.batch
    )

    if args.workers > 0:  # workers=0 would just re-measure the inline mode
        cfg = DataConfig(**base, workers=args.workers)
        loader = TrainLoader(cfg, args.batch)
        results["workers"] = drain(
            iter(loader), batches=args.batches, warmup=args.warmup, batch_size=args.batch
        )
        loader.close()

    if native_available():
        cfg = DataConfig(**base, use_native=True, decode_threads=args.workers)
        it = iter(TrainLoader(cfg, args.batch))
        results["native"] = drain(
            it, batches=args.batches, warmup=args.warmup, batch_size=args.batch
        )

    try:
        cfg = DataConfig(**base, workers=0)
        results["torch"] = bench_torch(
            cfg,
            args.batch,
            batches=args.batches,
            warmup=args.warmup,
            workers=args.workers,
        )
    except Exception as e:  # noqa: BLE001 — torch optional
        print(json.dumps({"error": f"torch comparison skipped: {e}"}))

    for mode, rate in results.items():
        print(
            json.dumps(
                {
                    "metric": f"data_pipeline_{mode}_imgs_per_sec",
                    "value": round(rate, 1),
                    "unit": "imgs/sec",
                }
            )
        )
    if not args.keep_dir:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
