"""Regenerate tests/golden/transforms_golden.npz.

The fixture pins the exact pixel output of every augmentation op
(tests/test_transforms_golden.py::golden_cases) so a PIL/cv2 upgrade or a
port edit that shifts pixel semantics fails the suite instead of silently
changing the training distribution. Run from the repo root:

    python tools/gen_transform_golden.py
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tests"))


def main():
    from test_transforms_golden import GOLDEN, golden_cases

    GOLDEN.parent.mkdir(parents=True, exist_ok=True)
    cases = golden_cases()
    np.savez_compressed(GOLDEN, **cases)
    print(f"wrote {GOLDEN} ({len(cases)} arrays)")


if __name__ == "__main__":
    main()
