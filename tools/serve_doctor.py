#!/usr/bin/env python3
"""Offline serving diagnosis: JSONL access log → markdown.

The serving counterpart of ``run_doctor``: the access log
(``cli/predict.py --serve --access-log DIR``, one crash-safe row per
finished request) is enough to reconstruct *what the callers experienced*
after the fact — no live process, no /metrics endpoint:

    python tools/serve_doctor.py runs/serve/access
    python tools/serve_doctor.py ... --slo 'p99_latency_ms<=250;success_rate>=0.99'
    python tools/serve_doctor.py ... --out diagnosis.md

The report answers, in order: what the traffic looked like (outcome mix,
exact latency quantiles); whether the SLO was breached and *when* (time
windows over ``--window-s`` buckets) and *which requests* (contiguous rid
clusters); which latency component dominated the slow requests (queue
wait vs coalescing vs compute vs fetch — the triage fork between "scale
out", "shrink max_delay", and "shrink the model"); how each batch bucket
behaved; and where sheds / deadline expiries / shutdown aborts clustered.

With a replica-pool access log (``--serve --replicas N``) the report adds
a per-replica latency/outcome table (keyed on each row's ``replica``
field), retry clusters naming the replica whose failure forced each
requeue (``requeued_from``) and who absorbed the retries, and a pool
event timeline — crashes, hangs, restarts, breaker flips, autoscale
resizes, and weight-swap verdicts (a ``swap_rollback`` also lands in the
Verdict line). Rows carrying ``tenant``/``class`` (the traffic-shaping
tier) add a per-tenant table — with device-seconds / FLOPs / pad-waste
columns when the cost meter stamped ``device_ms``/``cost_flops`` onto the
rows — plus a shaping-vs-starvation verdict: low classes shedding first
is the design working; a shed *interactive* tenant while lower classes
kept being served is priority inversion and is called out as starvation,
and a tenant hogging device-time over its implied share while cheaper
tenants shed is called out as a noisy neighbor (the full chargeback view
lives in ``tools/cost_doctor.py``).

Without ``--slo`` the slow-request threshold defaults to 4x the median ok
latency — a shape-based heuristic for "what would have annoyed a caller",
documented in the report so nobody mistakes it for a configured objective.

Exit codes: 0 = diagnosis written (healthy or not); 2 = no access log.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jumbo_mae_tpu_tpu.obs.doctor_common import (  # noqa: E402
    contiguous_windows,
    fmt_num,
    spans_text,
    write_report,
)
from jumbo_mae_tpu_tpu.obs.journal import read_journal  # noqa: E402
from jumbo_mae_tpu_tpu.obs.slo import SLOObjective, parse_slo  # noqa: E402

COMPONENTS = ("queue_wait_ms", "admission_ms", "compute_ms", "fetch_ms")


def _quantile(sorted_vals: list[float], q: float) -> float:
    """Exact nearest-rank quantile over already-sorted samples."""
    if not sorted_vals:
        return 0.0
    rank = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[rank]


def _breach_windows(
    rows: list[dict], obj: SLOObjective, t0: float, window_s: float
) -> list[tuple[int, int]]:
    """Time buckets (``window_s`` wide, relative to the first request)
    whose violation fraction exceeds the objective's error budget, merged
    into contiguous runs."""
    buckets: dict[int, list[bool]] = {}
    for r in rows:
        w = int((r.get("ts", t0) - t0) // window_s)
        if obj.percentile is not None:
            if r["outcome"] != "ok" or r.get("lat_ms") is None:
                continue
            bad = r["lat_ms"] > obj.threshold
        else:
            bad = r["outcome"] != "ok"
        buckets.setdefault(w, []).append(bad)
    breached = [
        w for w, flags in buckets.items()
        if flags and sum(flags) / len(flags) > obj.budget
    ]
    return contiguous_windows(breached)


def _windows_clock(windows: list[tuple[int, int]], window_s: float) -> str:
    return ", ".join(
        f"t+{int(a * window_s)}s–t+{int((b + 1) * window_s)}s"
        for a, b in windows
    )


def diagnose(
    rows: list[dict],
    objectives: list[SLOObjective],
    *,
    window_s: float,
    events: list[dict] | None = None,
) -> str:
    """Render the markdown diagnosis for one serve run's request rows
    (plus, when the log came from a replica pool, the non-request pool
    events — crashes, restarts, breaker flips, swap verdicts)."""
    lines: list[str] = ["# Serve doctor report", ""]
    ok_rows = [r for r in rows if r["outcome"] == "ok"]
    ok_lat = sorted(r["lat_ms"] for r in ok_rows if r.get("lat_ms") is not None)
    t0 = min(r.get("ts", 0) for r in rows)
    t1 = max(r.get("ts", 0) for r in rows)
    span = max(t1 - t0, 1e-9)

    # ------------------------------------------------------------- traffic
    outcomes: dict[str, int] = {}
    for r in rows:
        outcomes[r["outcome"]] = outcomes.get(r["outcome"], 0) + 1
    mix = ", ".join(f"{k}: {v}" for k, v in sorted(outcomes.items()))
    lines += [
        "## Traffic",
        "",
        f"- {len(rows)} request(s) over {span:.1f}s "
        f"({len(rows) / span:.1f} req/s) — outcomes: {mix}",
    ]
    if ok_lat:
        lines.append(
            f"- ok latency: p50 {fmt_num(_quantile(ok_lat, 0.50))} ms, "
            f"p99 {fmt_num(_quantile(ok_lat, 0.99))} ms, "
            f"max {fmt_num(ok_lat[-1])} ms (exact, from "
            f"{len(ok_lat)} samples)"
        )
    lines.append("")

    # auto-threshold when no SLO was configured: 4x the median ok latency
    auto = None
    if not objectives and ok_lat:
        auto = max(4.0 * _quantile(ok_lat, 0.50), 1e-3)
        objectives = [SLOObjective("p99_latency_ms", "<=", round(auto, 3))]

    # ------------------------------------------------------------- verdict
    verdict: list[str] = []
    slow_rows: list[dict] = []
    lines += ["## SLO analysis", ""]
    if auto is not None:
        lines.append(
            f"- no SLO configured — using the auto slow-request threshold "
            f"(4x median ok latency = {fmt_num(auto)} ms); pass --slo for "
            f"the configured objectives"
        )
    for obj in objectives:
        if obj.percentile is not None:
            viol = [
                r for r in ok_rows
                if r.get("lat_ms") is not None and r["lat_ms"] > obj.threshold
            ]
            frac = len(viol) / len(ok_lat) if ok_lat else 0.0
        else:
            viol = [r for r in rows if r["outcome"] != "ok"]
            frac = len(viol) / len(rows)
        breached = frac > obj.budget
        slow_rows.extend(v for v in viol if v["outcome"] == "ok")
        status = "**breached**" if breached else "met"
        lines.append(
            f"- `{obj.name}`: {status} — {len(viol)} violation(s), "
            f"{frac * 100:.1f}% of requests vs a "
            f"{obj.budget * 100:g}% error budget "
            f"(burn {fmt_num(frac / obj.budget)})"
        )
        if viol:
            wins = _breach_windows(rows, obj, t0, window_s)
            if wins:
                lines.append(
                    f"  - breach window(s) ({window_s:g}s buckets): "
                    f"{_windows_clock(wins, window_s)}"
                )
            rids = contiguous_windows(r["rid"] for r in viol)
            lines.append(
                f"  - violating {spans_text(rids, noun='request')}"
            )
        if breached:
            verdict.append(f"`{obj.name}` breached")
    if not verdict:
        verdict.append("all objectives met")
    lines.append("")

    # ------------------------------------------- dominant latency component
    focus = slow_rows if slow_rows else ok_rows
    dominant = None
    if focus:
        lines += ["## Latency decomposition", ""]
        which = "slow (violating)" if slow_rows else "ok"
        lines.append(
            f"- mean per-leg latency over the {len(focus)} {which} request(s):"
        )
        means = {}
        for comp in COMPONENTS:
            vals = [r[comp] for r in focus if r.get(comp) is not None]
            if vals:
                means[comp] = sum(vals) / len(vals)
        dominant = max(means, key=means.get) if means else None
        for comp in COMPONENTS:
            if comp in means:
                mark = " ← dominant" if comp == dominant else ""
                lines.append(
                    f"  - {comp[:-3]}: {fmt_num(means[comp])} ms{mark}"
                )
        if dominant is not None:
            name = dominant[:-3]
            verdict.append(f"dominant latency component: **{name}**")
            hint = {
                "queue_wait": "requests stalled before admission — add "
                "capacity / shed earlier (max_queue) / check submit-side "
                "stalls",
                "admission": "coalescing wait dominates — lower "
                "max_delay_ms or raise offered load",
                "compute": "the forward dominates — bigger buckets, a "
                "smaller model, or a faster device",
                "fetch": "device→host transfer dominates — fetch less "
                "(pool tokens on device) or overlap the copy",
            }[name]
            lines.append(f"  - triage: {hint}")
        lines.append("")

    # ------------------------------------------------------------- buckets
    by_bucket: dict[int, list[float]] = {}
    for r in ok_rows:
        if r.get("bucket") is not None and r.get("lat_ms") is not None:
            by_bucket.setdefault(int(r["bucket"]), []).append(r["lat_ms"])
    if by_bucket:
        lines += [
            "## Buckets",
            "",
            "| bucket | requests | p50 ms | p99 ms |",
            "|---|---|---|---|",
        ]
        worst, worst_p99 = None, -1.0
        for b in sorted(by_bucket):
            vals = sorted(by_bucket[b])
            p99 = _quantile(vals, 0.99)
            if p99 > worst_p99:
                worst, worst_p99 = b, p99
            lines.append(
                f"| {b} | {len(vals)} | {fmt_num(_quantile(vals, 0.50))} "
                f"| {fmt_num(p99)} |"
            )
        lines += ["", f"- worst bucket by p99: **{worst}** "
                  f"({fmt_num(worst_p99)} ms)", ""]

    # ------------------------------------------------------------ replicas
    rep_rows = [r for r in rows if r.get("replica") is not None]
    if rep_rows:
        by_rep: dict[str, list[dict]] = {}
        for r in rep_rows:
            by_rep.setdefault(str(r["replica"]), []).append(r)
        lines += [
            "## Replicas",
            "",
            "| replica | requests | ok | late | retried-in | p50 ms | p99 ms |",
            "|---|---|---|---|---|---|---|",
        ]
        for name in sorted(by_rep):
            sel = by_rep[name]
            oks = [r for r in sel if r["outcome"] == "ok"]
            lat = sorted(
                r["lat_ms"] for r in oks if r.get("lat_ms") is not None
            )
            late = sum(1 for r in sel if r["outcome"] == "late")
            retried = sum(1 for r in sel if r.get("retries"))
            lines.append(
                f"| {name} | {len(sel)} | {len(oks)} | {late} | {retried} "
                f"| {fmt_num(_quantile(lat, 0.50)) if lat else '-'} "
                f"| {fmt_num(_quantile(lat, 0.99)) if lat else '-'} |"
            )
        lines.append("")
        # retry clusters: which replica's failure forced the requeues —
        # the offline answer to "which replica died and who absorbed it"
        retried_rows = [r for r in rows if r.get("retries")]
        if retried_rows:
            by_src: dict[str, list[dict]] = {}
            for r in retried_rows:
                src = str(r.get("requeued_from") or "unknown")
                by_src.setdefault(src, []).append(r)
            lines += ["### Retry clusters (by failed replica)", ""]
            for src in sorted(by_src):
                sel = by_src[src]
                rids = contiguous_windows(r["rid"] for r in sel)
                served_on = sorted(
                    {str(r["replica"]) for r in sel if r.get("replica")}
                )
                ok_n = sum(1 for r in sel if r["outcome"] == "ok")
                lines.append(
                    f"- requeued off **{src}** ({len(sel)} request(s), "
                    f"{ok_n} recovered ok"
                    + (
                        f" on {', '.join(served_on)}" if served_on else ""
                    )
                    + f"): {spans_text(rids, noun='request')}"
                )
            lines.append("")

    # ------------------------------------------------------------- tenants
    ten_rows = [r for r in rows if r.get("tenant") is not None]
    if ten_rows:
        by_ten: dict[str, list[dict]] = {}
        for r in ten_rows:
            by_ten.setdefault(str(r["tenant"]), []).append(r)
        lines += [
            "## Tenants",
            "",
            "| tenant | class | requests | ok | shed | device s | GFLOPs "
            "| waste s | p50 ms | p99 ms |",
            "|---|---|---|---|---|---|---|---|---|---|",
        ]
        shed_by_ten: dict[str, int] = {}
        class_of: dict[str, str] = {}
        dev_by_ten: dict[str, float] = {}
        for name in sorted(by_ten):
            sel = by_ten[name]
            tclass = next(
                (str(r["class"]) for r in sel if r.get("class")), "?"
            )
            class_of[name] = tclass
            oks = [r for r in sel if r["outcome"] == "ok"]
            lat = sorted(
                r["lat_ms"] for r in oks if r.get("lat_ms") is not None
            )
            shed_n = sum(1 for r in sel if r["outcome"] == "shed")
            shed_by_ten[name] = shed_n
            # cost columns from the meter-stamped device_ms/cost_flops;
            # waste = device-time that bought pad rows (row share × pad)
            dev_s = sum(r.get("device_ms") or 0.0 for r in sel) / 1000.0
            gflops = sum(r.get("cost_flops") or 0.0 for r in sel) / 1e9
            waste_s = sum(
                (r.get("device_ms") or 0.0) * (r.get("pad") or 0.0)
                for r in sel
            ) / 1000.0
            dev_by_ten[name] = dev_s
            lines.append(
                f"| {name} | {tclass} | {len(sel)} | {len(oks)} | {shed_n} "
                f"| {fmt_num(dev_s) if dev_s else '-'} "
                f"| {fmt_num(gflops) if gflops else '-'} "
                f"| {fmt_num(waste_s) if waste_s else '-'} "
                f"| {fmt_num(_quantile(lat, 0.50)) if lat else '-'} "
                f"| {fmt_num(_quantile(lat, 0.99)) if lat else '-'} |"
            )
        lines.append("")
        # shaping vs starvation: shedding *low* classes under pressure is
        # the design working; a shed interactive tenant while lower
        # classes kept being served is priority inversion
        class_rank = {"interactive": 0, "batch": 1, "scavenger": 2}
        shed_tenants = [t for t, n in shed_by_ten.items() if n > 0]
        starved = [
            t for t in shed_tenants
            if class_rank.get(class_of[t], 1) == 0
            and any(
                class_rank.get(class_of[o], 1) > 0
                and sum(1 for r in by_ten[o] if r["outcome"] == "ok") > 0
                for o in by_ten
                if o != t
            )
        ]
        # noisy neighbor: a tenant well over its implied (equal) share of
        # metered device-time while a cheaper tenant was shedding — the
        # cost-accounting refinement of the starvation signal
        noisy: list[str] = []
        total_dev = sum(dev_by_ten.values())
        if total_dev > 0 and len(dev_by_ten) > 1 and shed_tenants:
            fair = 1.0 / len(dev_by_ten)
            for name, dev_s in dev_by_ten.items():
                share = dev_s / total_dev
                if share <= 1.25 * fair:
                    continue
                if any(
                    o != name and dev_by_ten[o] < dev_s
                    for o in shed_tenants
                ):
                    noisy.append(name)
        if starved:
            verdict.append(
                "**starvation**: interactive tenant(s) "
                + ", ".join(f"`{t}`" for t in sorted(starved))
                + " shed while lower classes were served"
            )
        elif shed_tenants:
            verdict.append(
                "shaping shed "
                + ", ".join(
                    f"`{t}` ({class_of[t]}, {shed_by_ten[t]})"
                    for t in sorted(shed_tenants)
                )
                + " — low classes gave way first"
            )
        if noisy:
            verdict.append(
                "noisy neighbor: "
                + ", ".join(
                    f"`{t}` ({dev_by_ten[t] / total_dev * 100:.0f}% of "
                    f"device-time)"
                    for t in sorted(noisy)
                )
                + " over its implied share while cheaper tenants shed"
            )

    # ------------------------------------------------- non-ok rid clusters
    bad = [r for r in rows if r["outcome"] not in ("ok",)]
    if bad:
        lines += ["## Shed / deadline / abort clusters", ""]
        for outcome in ("shed", "deadline", "late", "aborted", "shutdown"):
            sel = [r for r in bad if r["outcome"] == outcome]
            if sel:
                rids = contiguous_windows(r["rid"] for r in sel)
                lines.append(
                    f"- {outcome} ({len(sel)}): "
                    f"{spans_text(rids, noun='request')}"
                )
        lines.append("")

    # --------------------------------------------------- pool event timeline
    POOL_EVENTS = (
        "replica_crash", "replica_hang", "replica_restart",
        "replica_restart_failed", "breaker_open", "breaker_close",
        "swap_start", "swap_canary", "swap_rejected", "swap_rollback",
        "swap_promoted", "autoscale", "replica_added", "replica_removed",
    )
    pool_ev = [
        e for e in (events or []) if e.get("type") in POOL_EVENTS
    ]
    if pool_ev:
        lines += ["## Pool events", ""]
        for e in pool_ev:
            t_rel = e.get("ts", t0) - t0
            detail = ", ".join(
                f"{k}={v}"
                for k, v in e.items()
                if k not in ("ts", "seq", "type") and v is not None
            )
            lines.append(f"- t+{t_rel:.1f}s `{e['type']}` — {detail}")
        lines.append("")
        rollbacks = sum(1 for e in pool_ev if e["type"] == "swap_rollback")
        if rollbacks:
            verdict.append(
                f"{rollbacks} weight swap(s) **rolled back** "
                "(see Pool events)"
            )

    # verdict goes up front, rendered last (it needs everything above)
    lines[2:2] = ["## Verdict", "", f"- {'; '.join(verdict)}", ""]
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument(
        "path", help="access-log dir (or one journal-*.jsonl segment)"
    )
    parser.add_argument(
        "--slo",
        default="",
        help="objectives to judge against, e.g. 'p99_latency_ms<=250;"
        "success_rate>=0.99' (default: auto 4x-median threshold)",
    )
    parser.add_argument(
        "--window-s",
        type=float,
        default=10.0,
        help="time-bucket width for naming breach windows (default 10s)",
    )
    parser.add_argument(
        "--out", default=None, help="write the markdown here (default stdout)"
    )
    args = parser.parse_args(argv)

    try:
        events = read_journal(args.path)
    except FileNotFoundError as e:
        print(f"[serve_doctor] {e}", file=sys.stderr)
        return 2
    rows = [e for e in events if e.get("type") == "request"]
    if not rows:
        print(
            f"[serve_doctor] no request rows in the access log at {args.path}",
            file=sys.stderr,
        )
        return 2

    objectives = parse_slo(args.slo) if args.slo else []
    report = diagnose(rows, objectives, window_s=args.window_s, events=events)
    return write_report(report, args.out, tool="serve_doctor")


if __name__ == "__main__":
    sys.exit(main())
