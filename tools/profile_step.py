#!/usr/bin/env python3
"""Per-op device-trace breakdown of one bench train step.

Captures a ``jax.profiler`` trace of the bench step (same builder as
bench.py, so the profiled program IS the benched program) and aggregates
device-track op durations by ``hlo_category`` plus the top self-time ops —
the table PERF.md's "Where a step goes" is built from, as one command:

    python tools/profile_step.py --model vit_h14 --steps 5 --out /tmp/h14

Capture runs through the ``obs/trace.py`` helpers (the same ones
``run.profile_dir`` / ``run.chrome_trace`` use), so alongside the XLA
device trace it writes a host-side span timeline
(``<out>/host_spans.trace.json``) in the SAME chrome-trace format as a
training run's ``run.chrome_trace`` — and merges both onto ONE timeline
(``<out>/combined.trace.json``: device tracks + a 'host spans' track) so
a single Perfetto tab shows dispatch gaps against device programs.
``--journal RUN_DIR`` appends a ``profile`` event with the artifact paths
to the run's journal, so ``run_doctor`` can point at the capture.

The reference had no profiling surface at all (SURVEY §5).
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jumbo_mae_tpu_tpu.obs.trace import (  # noqa: E402
    export_chrome_trace,
    span_timer,
    start_chrome_trace,
    trace,
)


def capture(
    model: str, steps: int, out_dir: str, batch: int | None
) -> tuple[str, str]:
    """Returns ``(device_trace_path, host_span_trace_path)``."""
    import jax

    import bench

    if batch is not None:
        os.environ["BENCH_BATCH"] = str(batch)
    batch_size = int(
        os.environ.get("BENCH_BATCH", str(bench.MODELS[model]["batch"]))
    )
    step, state, batch_dev, _ = bench.build_step("bfloat16", batch_size, model)
    for _ in range(3):  # compile + warm
        state, metrics = step(state, batch_dev)
    jax.block_until_ready(metrics["loss"])

    # device trace + host spans through the shared obs/trace helpers: the
    # span timeline (dispatch per step, then the sync) lands in the same
    # chrome-trace JSON shape run.chrome_trace produces
    start_chrome_trace()
    sp_step = span_timer("profile_step")
    sp_sync = span_timer("block_until_ready")
    with trace(out_dir):
        for _ in range(steps):
            with sp_step:
                state, metrics = step(state, batch_dev)
        with sp_sync:
            jax.block_until_ready(metrics["loss"])
    host_trace = export_chrome_trace(
        os.path.join(out_dir, "host_spans.trace.json")
    )

    traces = glob.glob(
        os.path.join(out_dir, "**", "*.trace.json.gz"), recursive=True
    )
    if not traces:
        raise FileNotFoundError(f"no trace written under {out_dir}")
    return max(traces, key=os.path.getmtime), str(host_trace)


def merge_traces(device_trace: str, host_trace: str, out_path: str) -> str:
    """One combined chrome-trace JSON: the XLA device tracks plus the host
    span track on a single timeline.

    The two captures use different clock origins (host spans stamp
    ``time.perf_counter``; the device trace has its own epoch), so host
    events are shifted to share the device trace's origin — within-capture
    ordering is exact, cross-capture alignment is to the capture window.
    Host events land under their own pid with a process_name so Perfetto
    shows them as a separate 'host spans' track.
    """
    with gzip.open(device_trace, "rt") as f:
        combined = json.load(f)
    events = combined.setdefault("traceEvents", [])
    with open(host_trace) as f:
        host_events = [
            e for e in json.load(f).get("traceEvents", []) if e.get("ph") == "X"
        ]
    if host_events:
        dev_ts = [e["ts"] for e in events if e.get("ph") == "X" and "ts" in e]
        shift = (min(dev_ts) if dev_ts else 0.0) - min(
            e["ts"] for e in host_events
        )
        host_pid = max(
            [e.get("pid", 0) for e in events if isinstance(e.get("pid"), int)],
            default=0,
        ) + 1
        events.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": host_pid,
                "args": {"name": "host spans (obs/trace)"},
            }
        )
        for e in host_events:
            events.append({**e, "ts": e["ts"] + shift, "pid": host_pid})
    combined.setdefault("displayTimeUnit", "ms")
    out = os.path.join(out_path, "combined.trace.json") if os.path.isdir(
        out_path
    ) else out_path
    with open(out, "w") as f:
        json.dump(combined, f)
    return out


def aggregate(trace_path: str, steps: int) -> tuple[dict, list, list, list]:
    """Sum device-track event durations by hlo_category and by op name,
    plus per-source-line totals and per-tf_op (time, flops, bytes) rows
    for achieved-TF/s / GB/s attribution.

    Device tracks are the pids whose process names mention the accelerator
    (\"/device:TPU\" etc.); host/python tracks are excluded so the table is
    chip time, not dispatch time.
    """
    with gzip.open(trace_path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])

    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e["pid"]] = e.get("args", {}).get("name", "")
    device_pids = {
        pid
        for pid, pname in pid_names.items()
        if any(t in pname.lower() for t in ("tpu", "gpu", "device", "xla"))
        and "host" not in pname.lower()
    }
    if not device_pids:
        # No device track (CPU backend). Prefer tracks whose events carry an
        # hlo_category (real op events); failing that, fall back to all host
        # tracks — those spans NEST (parent+child both counted), so totals
        # overstate wall time and are smoke-test-only.
        cat_pids = {
            e["pid"]
            for e in events
            if e.get("ph") == "X" and e.get("args", {}).get("hlo_category")
        }
        device_pids = cat_pids or set(pid_names)
        kind = "hlo-op host" if cat_pids else "HOST (nested spans double-count)"
        print(
            f"[profile_step] no device track found (tracks: "
            f"{sorted(pid_names.values())}); aggregating {kind} tracks — "
            "smoke only, host time != chip time"
        )

    by_cat: dict[str, float] = collections.defaultdict(float)
    by_op: dict[str, float] = collections.defaultdict(float)
    by_src: dict[str, float] = collections.defaultdict(float)
    # tf_op → [device_us, model_flops, raw_bytes]: per-op achieved TF/s and
    # GB/s — tells FLOP-bound from HBM-bound apart op by op, which is what
    # actually picks the next optimization (PERF.md §Round 3 workflow)
    by_tf: dict[str, list] = collections.defaultdict(lambda: [0.0, 0.0, 0.0])
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        a = e.get("args", {})
        dur_ms = e.get("dur", 0) / 1e3 / steps
        cat = a.get("hlo_category") or "(uncategorized)"
        by_cat[cat] += dur_ms
        by_op[e.get("name", "?")] += dur_ms
        if a.get("hlo_category"):  # real op events only — module spans
            # carry no category and would double-count their children
            by_src[a.get("source") or "(no source)"] += dur_ms
            r = by_tf[a.get("tf_op") or "(no tf_op)"]
            r[0] += e.get("dur", 0)
            # some trace exporters emit formatted/empty strings here —
            # skip the stat rather than abort the whole aggregation
            for i, key in ((1, "model_flops"), (2, "raw_bytes_accessed")):
                try:
                    r[i] += float(a.get(key) or 0)
                except (TypeError, ValueError):
                    pass
    top_ops = sorted(by_op.items(), key=lambda kv: -kv[1])[:20]
    top_src = sorted(by_src.items(), key=lambda kv: -kv[1])[:15]
    top_tf = sorted(by_tf.items(), key=lambda kv: -kv[1][0])[:15]
    return dict(by_cat), top_ops, top_src, top_tf


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="vit_h14")
    parser.add_argument("--steps", type=int, default=5)
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--out", default="/tmp/profile_step")
    parser.add_argument(
        "--trace",
        default=None,
        help="skip capture; aggregate an existing .trace.json.gz",
    )
    parser.add_argument(
        "--journal",
        default=None,
        help="run dir / journal dir: append a 'profile' event with the "
        "artifact paths so run_doctor can point at this capture",
    )
    args = parser.parse_args(argv)

    host_path = combined = None
    if args.trace:
        path = args.trace
    else:
        path, host_path = capture(args.model, args.steps, args.out, args.batch)
        combined = merge_traces(path, host_path, args.out)
    if args.journal:
        from jumbo_mae_tpu_tpu.obs.journal import RunJournal, journal_dir

        loc = journal_dir(args.journal)
        jdir = loc if loc is not None and loc.is_dir() else args.journal
        with RunJournal(jdir) as j:
            j.event(
                "profile",
                model=args.model,
                steps=args.steps,
                device_trace=path,
                host_spans=host_path,
                combined_trace=combined,
            )
    by_cat, top_ops, top_src, top_tf = aggregate(path, args.steps)
    total = sum(by_cat.values())
    print(f"\ndevice time by hlo_category (ms/step, {args.steps} steps):")
    for cat, ms in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        print(f"  {cat:<28} {ms:8.2f}  {100 * ms / max(total, 1e-9):5.1f}%")
    print(f"  {'TOTAL':<28} {total:8.2f}")
    print("\ntop ops by self time (ms/step):")
    for name, ms in top_ops:
        print(f"  {ms:8.3f}  {name[:100]}")
    print("\ndevice time by source line (ms/step):")
    for src, ms in top_src:
        print(f"  {ms:8.2f}  {src}")
    print("\ntop tf_ops: ms/step, achieved TF/s, GB/s (FLOP- vs HBM-bound):")
    for op, (us, flops, nbytes) in top_tf:
        secs = us / 1e6
        tf = flops / secs / 1e12 if secs else 0.0
        gb = nbytes / secs / 1e9 if secs else 0.0
        print(f"  {us / 1e3 / args.steps:8.2f} ms {tf:7.1f} TF/s {gb:7.0f} GB/s  {op[:85]}")
    print(f"\ntrace: {path}")
    if host_path:
        print(f"host spans (chrome-trace, same format as run.chrome_trace): {host_path}")
    if combined:
        print(f"combined device+host timeline: {combined}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
