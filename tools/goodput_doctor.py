#!/usr/bin/env python3
"""Offline goodput diagnosis: merged journal → "where did my time go".

The run doctor explains *incidents*; this tool prices *time*. From the
crash-safe journal alone — including the per-generation journals an
elastic supervisor run leaves behind — it answers: what fraction of the
run's wall-clock was productive step compute, and where did the rest go
(compile, data wait, eval, checkpoint save/restore, rollback recompute,
restart downtime, hang-detection latency, idle)?

    python tools/goodput_doctor.py runs/my_run            # run dir
    python tools/goodput_doctor.py runs/my_run/journal    # journal dir
    python tools/goodput_doctor.py ... --out goodput.md

The report has three parts:

- **Verdict + attribution table** — goodput fraction, per-bucket seconds
  and shares, and the conservation check (buckets must sum to wall-clock;
  the stitcher's residual-idle construction makes over-attribution the
  detectable failure).
- **Restart-cost breakdown** — one row per supervisor restart: reason,
  detection latency, backoff, total downtime, and lost steps (executed −
  committed at the moment of death).
- **Checkpoint-interval advisor** — Young's optimal interval
  √(2·save_cost·MTBF) from the measured save cost and observed failure
  rate, as a concrete `run.ckpt_every` recommendation.

Exit codes: 0 = report written (healthy or not); 2 = no journal found.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jumbo_mae_tpu_tpu.obs.doctor_common import write_report  # noqa: E402
from jumbo_mae_tpu_tpu.obs.goodput import (  # noqa: E402
    GOODPUT_BUCKETS,
    advise_ckpt_interval,
    bucket_display,
    stitch_generations,
)
from jumbo_mae_tpu_tpu.obs.journal import read_merged_journal  # noqa: E402


def _pct(v: float, total: float) -> str:
    return f"{100.0 * v / total:.1f}%" if total > 0 else "–"


def diagnose(events: list[dict]) -> str:
    """Render the markdown goodput report for one run's journal events."""
    g = stitch_generations(events)
    wall = g["wall_s"]
    buckets = g["buckets"]
    lines: list[str] = ["# Goodput doctor report", ""]

    # ------------------------------------------------------------- verdict
    # idle is the unattributed residual, not a diagnosis — rank only the
    # attributed non-productive buckets for the verdict line
    nonprod = sorted(
        (
            (k, v)
            for k, v in buckets.items()
            if k not in ("productive", "idle") and v > 0
        ),
        key=lambda kv: kv[1],
        reverse=True,
    )
    conserved = g["conservation_error"] <= 0.01
    lines += [
        "## Verdict",
        "",
        f"- goodput: **{g['goodput_fraction'] * 100:.1f}%** of "
        f"{wall:.1f}s wall-clock was productive step compute "
        f"({g['steps_committed']} steps committed"
        + (f", {g['steps_lost']} lost to restarts" if g["steps_lost"] else "")
        + ")",
    ]
    if nonprod:
        top, top_s = nonprod[0]
        lines.append(
            f"- top non-productive bucket: **{bucket_display(top)}** "
            f"({top_s:.1f}s, {_pct(top_s, wall)} of wall-clock)"
        )
    idle_s = buckets.get("idle", 0.0)
    if wall > 0 and idle_s / wall >= 0.25:
        lines.append(
            f"- unattributed (idle) residual is large: {idle_s:.1f}s "
            f"({_pct(idle_s, wall)} of wall-clock) — host-side setup and "
            "gaps no ledger span covered"
        )
    if g["failures"]:
        lines.append(
            f"- {g['failures']} restart(s) observed; restart downtime "
            f"{buckets['restart_downtime']:.1f}s + hang-detection latency "
            f"{buckets['hang_latency']:.1f}s"
        )
    lines.append(
        f"- conservation: {'**OK**' if conserved else '**VIOLATED**'} "
        f"(attribution error {g['conservation_error'] * 100:.2f}% of "
        "wall-clock, tolerance 1%)"
    )
    if len(g["generations"]) > 1:
        lines.append(
            f"- stitched across {len(g['generations'])} process "
            "generation(s) of an elastic run"
        )
    lines.append("")

    # -------------------------------------------------- attribution table
    lines += [
        "## Wall-clock attribution",
        "",
        "| bucket | seconds | share |",
        "|---|---:|---:|",
    ]
    for b in GOODPUT_BUCKETS:
        v = buckets.get(b, 0.0)
        lines.append(f"| {bucket_display(b)} | {v:.1f} | {_pct(v, wall)} |")
    lines += [f"| **wall-clock** | **{wall:.1f}** | 100% |", ""]

    # ---------------------------------------------- restart-cost breakdown
    lines += ["## Restart costs", ""]
    if g["restarts"]:
        lines += [
            "| generation | reason | detection s | backoff s | downtime s "
            "| lost steps | lost s |",
            "|---:|---|---:|---:|---:|---:|---:|",
        ]
        for r in g["restarts"]:
            lines.append(
                f"| {r['generation']} | {r['reason']} | "
                f"{r['detection_s']:.1f} | {r['backoff_s']:.1f} | "
                f"{r['downtime_s']:.1f} | {r['lost_steps']} | "
                f"{r.get('lost_seconds', 0.0):.1f} |"
            )
        lines.append("")
    else:
        lines += ["(no supervisor restarts observed)", ""]

    # -------------------------------------------- checkpoint-interval advisor
    lines += ["## Checkpoint-interval advisor", ""]
    if g["save_cost_s"] is None or g["step_time_s"] is None:
        lines += [
            "(not enough data: need at least one measured checkpoint save "
            "and one productive step)",
            "",
        ]
    else:
        adv = advise_ckpt_interval(
            g["save_cost_s"],
            g["mtbf_s"] or 0.0,
            g["step_time_s"],
            observed_span_s=wall,
        )
        mtbf_note = (
            f"no failures observed — using the run span {adv['mtbf_s']:.0f}s "
            "as an MTBF lower bound (the optimal interval can only be longer)"
            if adv["mtbf_is_bound"]
            else f"MTBF {adv['mtbf_s']:.0f}s from {g['failures']} failure(s) "
            f"over {wall:.0f}s"
        )
        lines += [
            f"- measured save cost: {adv['save_cost_s']:.2f}s/checkpoint; "
            f"step time: {adv['step_time_s']:.3f}s",
            f"- {mtbf_note}",
            f"- Young's optimal interval √(2·save_cost·MTBF) ≈ "
            f"{adv['interval_s']:.0f}s",
            f"- **recommendation: `run.ckpt_every={adv['ckpt_every']}`** "
            f"(≈ one save every {adv['interval_s']:.0f}s at the measured "
            "step time)",
            "",
        ]
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument(
        "path",
        help="run dir, journal dir, or one journal-*.jsonl segment",
    )
    parser.add_argument(
        "--out", default=None, help="write the markdown here (default stdout)"
    )
    args = parser.parse_args(argv)

    try:
        events = read_merged_journal(args.path)
    except FileNotFoundError as e:
        print(f"[goodput_doctor] {e}", file=sys.stderr)
        return 2
    if not events:
        print(
            f"[goodput_doctor] journal at {args.path} is empty",
            file=sys.stderr,
        )
        return 2

    report = diagnose(events)
    return write_report(report, args.out, tool="goodput_doctor")


if __name__ == "__main__":
    sys.exit(main())
