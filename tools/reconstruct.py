#!/usr/bin/env python3
"""Render MAE reconstructions as a side-by-side image grid.

The canonical MAE demo figure (original | masked input | reconstruction |
reconstruction+visible pasted) — beyond the reference, which computes the
masked loss but never renders predictions. Pixel predictions come from the
model's ``return_reconstruction`` path (``models/mae.py``); with
``norm_pix_loss`` the per-patch normalization is inverted using the target
patch statistics (the standard MAE visualization convention, since the
model predicts in normalized-patch space).

    python tools/reconstruct.py recipes/pretrain_vit_l16_in1k_800ep.yaml \
        --ckpt runs/x/ckpt --out recon.png --n 8 \
        [--set data.valid_shards=... | run.synthetic_data=true] [--seed 0]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("recipe", nargs="?", default=None, help="YAML recipe path")
    p.add_argument(
        "--ckpt",
        default="",
        help="Orbax checkpoint dir or .msgpack params; random init if omitted",
    )
    p.add_argument("--out", required=True, help="output .png path")
    p.add_argument(
        "--n",
        type=int,
        default=None,
        help="images in the grid (default: 8, or all --images files)",
    )
    p.add_argument("--seed", type=int, default=0, help="masking seed")
    p.add_argument(
        "--images",
        nargs="+",
        default=[],
        metavar="FILE",
        help="image files (jpeg/png/...) to reconstruct instead of the "
        "recipe's validation stream; run through the eval transform "
        "(shorter-side resize to size/crop_ratio + center crop)",
    )
    p.add_argument(
        "--set",
        dest="overrides",
        metavar="KEY.PATH=VALUE",
        nargs="*",
        action="extend",
        default=[],
        help="dotted config overrides, same grammar as cli.train",
    )
    return p


def main(argv: list[str] | None = None) -> Path:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from PIL import Image

    from jumbo_mae_tpu_tpu.cli.train import build_model, make_valid_iterator
    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.ops.patches import extract_patches, merge_patches
    from jumbo_mae_tpu_tpu.ops.preprocess import (
        IMAGENET_MEAN,
        IMAGENET_STD,
        normalize_images,
    )
    from jumbo_mae_tpu_tpu.parallel import create_mesh
    from jumbo_mae_tpu_tpu.train.checkpoint import (
        load_pretrained_params,
        require_loaded,
    )

    if jax.process_count() > 1:
        raise SystemExit(
            "reconstruct is a single-process tool; run it on one host"
        )

    cfg = load_config(args.recipe, args.overrides)
    if cfg.run.mode != "pretrain":
        raise SystemExit("reconstruction needs a pretrain recipe (run.mode=pretrain)")
    model, enc_cfg, _ = build_model(cfg)
    patch = enc_cfg.patch_size

    size = cfg.data.image_size
    example = np.zeros((1, size, size, 3), np.uint8)
    variables = model.init(
        {
            "params": jax.random.PRNGKey(cfg.run.init_seed),
            "noise": jax.random.PRNGKey(0),
            "dropout": jax.random.PRNGKey(0),
        },
        example,
    )
    params = variables["params"]
    if args.ckpt:
        # whole-tree merge: the decoder/mask_token/pixel_proj weights are
        # exactly what reconstruction needs (the default "auto" subtree mode
        # would warm-start the encoder only and leave the decoder random)
        stats: dict = {}
        params = load_pretrained_params(
            args.ckpt, params, subtree=None, stats=stats
        )
        require_loaded(
            stats, args.ckpt, f"the {cfg.model.preset} pretrain model"
        )

    if args.images:
        from jumbo_mae_tpu_tpu.data.transforms import eval_transform

        n = max(1, args.n) if args.n is not None else len(args.images)
        if len(args.images) > n:
            print(
                f"[reconstruct] rendering the first {n} of "
                f"{len(args.images)} files (--n)"
            )
        images = np.stack(
            [
                eval_transform(
                    np.asarray(Image.open(f).convert("RGB"), np.uint8),
                    size,
                    crop_ratio=cfg.data.test_crop_ratio,
                )
                for f in args.images[:n]
            ]
        )
    else:
        n = args.n if args.n is not None else 8
        mesh = create_mesh(cfg.mesh)
        # the device-prefetch sharding needs the batch divisible by the
        # mesh's data axes — round up and slice the n requested rows
        n_dev = len(jax.devices())
        per_batch = -(-max(1, n) // n_dev) * n_dev
        valid_factory = make_valid_iterator(
            cfg, mesh, per_batch, num_labels=enc_cfg.labels or 1000
        )
        if valid_factory is None:
            raise SystemExit(
                "no data: pass --images, set data.valid_shards, or "
                "run.synthetic_data=true"
            )
        batch = next(iter(valid_factory()))
        images = np.asarray(jax.device_get(batch["images"]))[:n]
    if images.shape[0] == 0:
        raise SystemExit("empty validation stream")

    @jax.jit
    def recon(params, images, noise_key):
        out = model.apply(
            {"params": params},
            images,
            True,
            True,
            rngs={"noise": noise_key},
        )
        return out["reconstruction"], out["mask"]

    pred, mask = recon(params, images, jax.random.PRNGKey(args.seed))
    pred = np.asarray(pred, np.float32)  # (B, N, p*p*3), maybe norm-pix space
    mask = np.asarray(mask, np.float32)[..., None]  # (B, N, 1); 1 = masked

    norm = np.asarray(
        normalize_images(jnp.asarray(images), dtype=jnp.float32), np.float32
    )
    target = np.asarray(
        extract_patches(jnp.asarray(norm), patch), np.float32
    )  # (B, N, p*p*3)
    if cfg.model.norm_pix_loss:
        mean = target.mean(axis=-1, keepdims=True)
        var = target.var(axis=-1, keepdims=True)
        pred = pred * np.sqrt(var + 1e-6) + mean

    def to_uint8(patches: np.ndarray) -> np.ndarray:
        """(B, N, p*p*3) normalized patches → (B, H, W, 3) uint8 images."""
        img = np.asarray(merge_patches(jnp.asarray(patches), patch), np.float32)
        img = (img * IMAGENET_STD + IMAGENET_MEAN) * 255.0
        return np.clip(img, 0, 255).astype(np.uint8)

    panels = [
        images,  # original
        # zeroed normalized patches render as ImageNet-mean gray
        to_uint8(target * (1.0 - mask)),  # masked input
        to_uint8(pred),  # full reconstruction
        to_uint8(target * (1.0 - mask) + pred * mask),  # paste: visible + pred
    ]

    n, h, w = images.shape[0], images.shape[1], images.shape[2]
    pad = 2
    grid = np.full(
        (n * (h + pad) - pad, len(panels) * (w + pad) - pad, 3), 255, np.uint8
    )
    for row in range(n):
        for col, panel in enumerate(panels):
            y, x = row * (h + pad), col * (w + pad)
            grid[y : y + h, x : x + w] = panel[row]

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    Image.fromarray(grid).save(out)
    print(
        f"[reconstruct] wrote {n}x{len(panels)} grid "
        f"(original | masked | reconstruction | paste) -> {out}"
    )
    return out


if __name__ == "__main__":
    main()
