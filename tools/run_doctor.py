#!/usr/bin/env python3
"""Offline run diagnosis: journal (+ optional flight record) → markdown.

The sentinel makes a diverging run *survivable*; this tool makes it
*explainable* after the fact, from the crash-safe artifacts alone — no live
process, no /metrics endpoint, no device:

    python tools/run_doctor.py runs/my_run                 # run dir
    python tools/run_doctor.py runs/my_run/journal         # journal dir
    python tools/run_doctor.py ... --flightrec runs/my_run/flightrec-*.json
    python tools/run_doctor.py ... --out diagnosis.md

The report answers, in order: how did the run end; *when and where* did it
go non-finite (the bad step window, and the first layer group whose grad
norm blew up when per-layer-group diagnostics were on); what the grad-norm
trend looked like before the incident; whether throughput regressed or the
run became data-bound across log windows; and the full resilience timeline
(checkpoints, rollbacks, shard quarantines, flight records, fleet
straggler/lost/rejoined transitions).

Multi-host runs are handled via the merged journal reader: per-host segments
(`journal/` + `journal-host<i>/`) are interleaved by time, host-0 rows drive
the step/throughput analysis (every host journals its own `step` events —
counting them all would multiply throughput by the fleet size), and
flight records from any host appear in the timeline tagged with their host.
For the per-host health table, use tools/fleet_doctor.py.

Exit codes: 0 = diagnosis written (healthy or not); 2 = no journal found.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jumbo_mae_tpu_tpu.obs.doctor_common import (  # noqa: E402
    contiguous_windows,
    fmt_num as _fmt_num,
    spans_text,
    write_report,
)
from jumbo_mae_tpu_tpu.obs.journal import read_merged_journal  # noqa: E402


def _is_bad_loss(v) -> bool:
    if v in ("nan", "inf", "-inf"):
        return True
    try:
        f = float(v)
    except (TypeError, ValueError):
        return False
    return f != f or f in (float("inf"), float("-inf"))


def _bad_windows(events: list[dict]) -> list[tuple[int, int]]:
    """Contiguous runs of known-bad step indices, preferring the sentinel's
    exact per-step verdicts, falling back to the windowed step snapshots."""
    bad: set[int] = set()
    for e in events:
        if e.get("type") in ("sentinel_bad_step",) and "step" in e:
            bad.add(int(e["step"]))
        if e.get("type") == "step":
            for s in e.get("bad_steps", []) or []:
                bad.add(int(s))
            m = e.get("metrics", {}) or {}
            if _is_bad_loss(m.get("train/loss")) and "step" in e:
                bad.add(int(e["step"]))
    return contiguous_windows(bad)


def _grad_norm_series(events: list[dict]) -> list[tuple[int, float]]:
    out = []
    for e in events:
        if e.get("type") != "step":
            continue
        gn = (e.get("metrics", {}) or {}).get("train/grad_norm")
        if gn is None or _is_bad_loss(gn):
            continue
        try:
            out.append((int(e["step"]), float(gn)))
        except (TypeError, ValueError, KeyError):
            continue
    return out


def _first_nonfinite_group(events: list[dict], flight: dict | None) -> str | None:
    """Scan diag payloads (journal step events, then the flight record's
    per-step ring) for the first group with a non-finite grad norm."""
    def scan(diag: dict | None):
        if not isinstance(diag, dict):
            return None
        for grp, stats in diag.items():
            if isinstance(stats, dict) and _is_bad_loss(stats.get("grad_norm")):
                return grp
        return None

    for e in events:
        if e.get("type") == "step":
            found = scan(e.get("diag"))
            if found:
                return found
    if flight:
        for entry in flight.get("steps", []):
            found = scan(entry.get("diag"))
            if found:
                return found
    return None


def _host_of(e: dict) -> int:
    try:
        return int(e.get("host", 0))
    except (TypeError, ValueError):
        return 0


def _fmt_host(host_id) -> str:
    return f"host {host_id}" if host_id is not None else "host ?"


def diagnose(events: list[dict], flight: dict | None = None) -> str:
    """Render the markdown diagnosis for one run's journal events."""
    lines: list[str] = ["# Run doctor report", ""]
    # A merged multi-host journal repeats the lifecycle per host (every host
    # journals its own run_start/step/shutdown). Host-0 rows drive the
    # single-run analysis — counting every host's `step` events would
    # multiply throughput and rollbacks by the fleet size. Flight records
    # and fleet transitions keep all hosts (tagged below).
    hosts = sorted({_host_of(e) for e in events})
    multi = len(hosts) > 1
    h0 = [e for e in events if _host_of(e) == 0] if multi else events
    starts = [e for e in h0 if e.get("type") == "run_start"]
    steps = [e for e in h0 if e.get("type") == "step"]
    shutdowns = [e for e in h0 if e.get("type") == "shutdown"]
    rollbacks = [e for e in h0 if e.get("type") == "rollback"]
    quarantines = [e for e in h0 if e.get("type") == "quarantine"]
    ckpts = [e for e in h0 if e.get("type") == "checkpoint_save"]
    flights = [e for e in events if e.get("type") == "flight_record"]
    stragglers = [e for e in events if e.get("type") == "fleet_straggler"]
    lost = [e for e in events if e.get("type") == "fleet_host_lost"]

    # ---------------------------------------------------------- run summary
    if starts:
        s = starts[-1]
        cfg = s.get("config", {}) or {}
        run_cfg = cfg.get("run", {}) or {}
        env = s.get("env", {}) or {}
        lines += [
            "## Run",
            "",
            f"- name: `{run_cfg.get('name', '?')}`  mode: "
            f"`{run_cfg.get('mode', '?')}`  "
            f"steps: {run_cfg.get('training_steps', '?')}  "
            f"global batch: {run_cfg.get('train_batch_size', '?')}",
            f"- started at step {s.get('start_step', 0)}"
            + (" (resumed)" if s.get("resumed") else ""),
            f"- env: python {env.get('python', '?')}, jax {env.get('jax', '?')} "
            f"({env.get('backend', '?')}, {env.get('device_count', '?')} devices), "
            f"host `{env.get('hostname', '?')}` pid {env.get('pid', '?')}",
        ]
        if env.get("env"):
            lines.append(f"- notable env vars: `{env['env']}`")
        if s.get("diag_groups"):
            lines.append(
                f"- per-layer-group diagnostics ON every "
                f"{s.get('diag_every')} steps over {len(s['diag_groups'])} "
                f"groups: {', '.join(s['diag_groups'])}"
            )
        if len(starts) > 1:
            lines.append(f"- {len(starts)} run_start events (process restarts)")
        lines.append("")

    # -------------------------------------------------------------- verdict
    windows = _bad_windows(events)
    reason = shutdowns[-1].get("reason", "unknown") if shutdowns else "no shutdown event (crashed hard?)"
    verdict = []
    if windows:
        verdict.append(
            f"**non-finite step window: {spans_text(windows, noun='step')}**"
        )
    if rollbacks:
        verdict.append(f"{len(rollbacks)} sentinel rollback(s)")
    if quarantines:
        n = sum(len(q.get("shards", [])) for q in quarantines)
        verdict.append(f"{n} shard(s) quarantined")
    if stragglers or lost:
        fleet_bits = []
        if stragglers:
            who = sorted({_fmt_host(e.get("host_id")) for e in stragglers})
            fleet_bits.append(
                f"{len(stragglers)} straggler event(s) ({', '.join(who)})"
            )
        if lost:
            who = sorted({_fmt_host(e.get("host_id")) for e in lost})
            fleet_bits.append(f"host(s) lost: {', '.join(who)}")
        verdict.append("fleet: " + "; ".join(fleet_bits))
    if not verdict:
        verdict.append("no incidents recorded")
    if multi:
        verdict.append(
            f"merged journal across {len(hosts)} hosts "
            f"({', '.join(str(h) for h in hosts)}); host-0 rows drive the "
            "step analysis"
        )
    lines += [
        "## Verdict",
        "",
        f"- run ended: **{reason}**",
        f"- {'; '.join(verdict)}",
        "",
    ]

    # --------------------------------------------------- non-finite analysis
    if windows:
        lines += ["## Non-finite analysis", ""]
        first_lo, first_hi = windows[0]
        lines.append(
            f"- first incident: steps {first_lo}–{first_hi} "
            f"({first_hi - first_lo + 1} bad step(s))"
        )
        grp = _first_nonfinite_group(events, flight)
        if grp:
            lines.append(
                f"- first layer group to go non-finite (grad norm): **{grp}**"
            )
        else:
            lines.append(
                "- per-layer-group diag unavailable for the incident "
                "(run with `run.diag_every` > 0 to localize the blow-up)"
            )
        series = _grad_norm_series(h0)
        before = [(s, g) for s, g in series if s < first_lo][-5:]
        if len(before) >= 2:
            first_g, last_g = before[0][1], before[-1][1]
            trend = (
                "rising" if last_g > 1.5 * first_g
                else "falling" if last_g < first_g / 1.5
                else "flat"
            )
            pts = ", ".join(f"{s}:{_fmt_num(g)}" for s, g in before)
            lines.append(
                f"- grad-norm trend before the incident: **{trend}** "
                f"({_fmt_num(first_g)} → {_fmt_num(last_g)} over the "
                f"last {len(before)} snapshots: {pts})"
            )
        lines.append("")

    # ----------------------------------------------------------- throughput
    perf = [
        (
            int(e["step"]),
            (e.get("metrics", {}) or {}).get("perf/images_per_sec"),
            e.get("data_wait_fraction"),
        )
        for e in steps
        if "step" in e
    ]
    perf = [
        (s, float(i), None if w is None else float(w))
        for s, i, w in perf
        if isinstance(i, (int, float))
    ]
    if perf:
        lines += ["## Throughput & data waits", ""]
        best = max(i for _, i, _ in perf)
        last = perf[-1][1]
        lines.append(
            f"- images/sec across {len(perf)} windows: best {_fmt_num(best)}, "
            f"final {_fmt_num(last)}"
            + (
                f" — **{(1 - last / best) * 100:.0f}% below best**"
                if best > 0 and last < 0.8 * best
                else ""
            )
        )
        waits = [w for _, _, w in perf if w is not None]
        if waits:
            mean_w = sum(waits) / len(waits)
            note = " — **data-bound**" if max(waits) > 0.5 else ""
            lines.append(
                f"- data-wait fraction: mean {mean_w:.2f}, "
                f"max {max(waits):.2f}{note}"
            )
        lines.append("")

    # -------------------------------------------------------------- timeline
    lines += ["## Timeline", ""]
    t0 = events[0].get("ts", 0) if events else 0
    # lifecycle rows from host 0 only (merged journals repeat them per host);
    # flight records and fleet transitions from every host, host-tagged
    per_run_types = (
        "run_start",
        "checkpoint_save",
        "rollback",
        "quarantine",
        "profile",
        "compiled_program",
        "shutdown",
        # supervisor rows (train/elastic.py) live in host-0's journal dir
        "elastic_restart",
        "elastic_rejoin",
        "elastic_exhausted",
    )
    fleet_types = ("fleet_straggler", "fleet_host_lost", "fleet_host_rejoined")
    # events any host may emit about itself: keep every host's, host-tagged
    any_host_types = ("elastic_resize", "hang_detected", "ckpt_fallback")
    interesting = [
        e
        for e in events
        if (e.get("type") in per_run_types and (not multi or _host_of(e) == 0))
        or e.get("type") in fleet_types
        or e.get("type") in any_host_types
        or e.get("type") == "flight_record"
    ]
    if not interesting:
        lines.append("(no lifecycle events recorded)")
    for e in interesting:
        dt = e.get("ts", t0) - t0
        etype = e["type"]
        detail = ""
        if etype == "checkpoint_save":
            detail = f"step {e.get('step')}"
            if e.get("preemption"):
                detail += " (preemption)"
        elif etype == "rollback":
            detail = (
                f"step {e.get('from_step')} → {e.get('to_step')} "
                f"(#{e.get('rollbacks')})"
            )
        elif etype == "quarantine":
            detail = ", ".join(str(s) for s in e.get("shards", []))
        elif etype == "flight_record":
            detail = f"{e.get('reason')} → {e.get('path')}"
            if multi:
                detail = f"[host {_host_of(e)}] {detail}"
        elif etype == "fleet_straggler":
            detail = (
                f"{_fmt_host(e.get('host_id'))} at step {e.get('step')}, "
                f"lag {e.get('lag')}, symptom {e.get('symptom')}"
            )
        elif etype == "fleet_host_lost":
            detail = (
                f"{_fmt_host(e.get('host_id'))} "
                f"(last step {e.get('last_step')}, heartbeat "
                f"{e.get('heartbeat_age_s')}s stale)"
            )
        elif etype == "fleet_host_rejoined":
            detail = (
                f"{_fmt_host(e.get('host_id'))} at step {e.get('step')} "
                f"after {e.get('lost_for_s')}s"
            )
        elif etype == "elastic_restart":
            detail = (
                f"gen {e.get('generation')}: {e.get('reason')}, world "
                f"{e.get('old_world')} → {e.get('new_world')}, failed hosts "
                f"{e.get('failed_hosts')}, backoff {e.get('backoff_s')}s "
                f"(restart #{e.get('restarts_used')})"
            )
        elif etype == "elastic_rejoin":
            detail = (
                f"gen {e.get('generation')}: world {e.get('old_world')} → "
                f"{e.get('new_world')} (graceful restart back to full size)"
            )
        elif etype == "elastic_exhausted":
            detail = f"{e.get('verdict')} (reason {e.get('reason')})"
        elif etype == "elastic_resize":
            detail = (
                f"{e.get('cause')}: world {e.get('old_world')} → "
                f"{e.get('new_world')} at step {e.get('step')}, epoch "
                f"{e.get('epoch')} resumes with {e.get('shards_remaining')}/"
                f"{e.get('shards_total')} shards unconsumed"
            )
            if multi:
                detail = f"[host {_host_of(e)}] {detail}"
        elif etype == "hang_detected":
            detail = (
                f"step {e.get('step')}: no progress for "
                f"{e.get('stalled_s')}s (deadline {e.get('deadline_s')}s)"
            )
            if multi:
                detail = f"[host {_host_of(e)}] {detail}"
        elif etype == "ckpt_fallback":
            detail = (
                f"restore walked back step {e.get('from_step')} → "
                f"{e.get('to_step')} ({e.get('error')})"
            )
            if multi:
                detail = f"[host {_host_of(e)}] {detail}"
        elif etype == "shutdown":
            detail = f"{e.get('reason')} at step {e.get('step')}"
        elif etype == "run_start":
            detail = f"start_step {e.get('start_step', 0)}"
        elif etype == "profile":
            detail = f"trace capture → {e.get('combined_trace') or e.get('device_trace')}"
        elif etype == "compiled_program":
            detail = (
                f"{e.get('program')}: {_fmt_num(e.get('flops', 0))} flops, "
                f"{_fmt_num(e.get('bytes_accessed', 0))} bytes"
            )
        lines.append(f"- +{dt:8.1f}s  `{etype}`  {detail}")
    lines.append("")
    if flights and not flight:
        lines.append(
            f"(tip: {len(flights)} flight record(s) were written — pass one "
            "via --flightrec for per-step detail around the incident)"
        )
        lines.append("")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument(
        "path",
        help="run dir, journal dir, or one journal-*.jsonl segment",
    )
    parser.add_argument(
        "--flightrec",
        default=None,
        help="flight-record JSON for per-step detail around the incident",
    )
    parser.add_argument(
        "--out", default=None, help="write the markdown here (default stdout)"
    )
    args = parser.parse_args(argv)

    try:
        events = read_merged_journal(args.path)
    except FileNotFoundError as e:
        print(f"[run_doctor] {e}", file=sys.stderr)
        return 2
    if not events:
        print(f"[run_doctor] journal at {args.path} is empty", file=sys.stderr)
        return 2

    flight = None
    if args.flightrec:
        try:
            flight = json.loads(Path(args.flightrec).read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(
                f"[run_doctor] WARNING: unreadable flight record: {e}",
                file=sys.stderr,
            )

    report = diagnose(events, flight)
    return write_report(report, args.out, tool="run_doctor")


if __name__ == "__main__":
    sys.exit(main())
