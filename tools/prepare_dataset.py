"""Build webdataset tar shards from an image-folder dataset.

Counterpart of the reference's dataset prep
(``/root/reference/scripts/prepare-imagenet1k-dataset.sh``), which downloaded
ready-made ImageNet shards; this tool builds the same shard format from any
local ``class_name/image.jpg`` directory tree, so the framework's loaders
(``data/loader.py``) can stream it.

Layout expected:  root/<class_dir>/<image>.{jpg,jpeg,png}
Shard layout:     {out}/{prefix}-{idx:06d}.tar with members
                  ``<key>.jpg`` + ``<key>.cls`` (integer class index, by
                  sorted class-dir order — written to {out}/classes.json).

Usage:
    python tools/prepare_dataset.py --src /data/train --out /data/shards \
        --prefix train --shard-size 1000 [--seed 0]
"""

from __future__ import annotations

import argparse
import json
import random
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from jumbo_mae_tpu_tpu.data.tario import write_tar_samples  # noqa: E402

IMAGE_EXTS = {".jpg", ".jpeg", ".png", ".webp", ".bmp"}


def collect(src: Path) -> tuple[list[tuple[Path, int]], list[str]]:
    classes = sorted(p.name for p in src.iterdir() if p.is_dir())
    class_to_idx = {c: i for i, c in enumerate(classes)}
    files = [
        (f, class_to_idx[c])
        for c in classes
        for f in sorted((src / c).iterdir())
        if f.suffix.lower() in IMAGE_EXTS
    ]
    return files, classes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--src", required=True, help="image-folder root (class dirs)")
    ap.add_argument("--out", required=True, help="output shard directory")
    ap.add_argument("--prefix", default="train")
    ap.add_argument("--shard-size", type=int, default=1000, help="samples per shard")
    ap.add_argument(
        "--seed", type=int, default=0,
        help="shuffle seed (shards should mix classes; <0 keeps sorted order)",
    )
    args = ap.parse_args()
    if args.shard_size <= 0:
        ap.error("--shard-size must be positive")

    src, out = Path(args.src), Path(args.out)
    files, classes = collect(src)
    if not files:
        raise SystemExit(f"no images found under {src}")
    if args.seed >= 0:
        random.Random(args.seed).shuffle(files)
    out.mkdir(parents=True, exist_ok=True)
    (out / "classes.json").write_text(json.dumps(classes, indent=0))

    n_shards = -(-len(files) // args.shard_size)
    width = max(6, len(str(n_shards - 1)))
    for s in range(n_shards):
        chunk = files[s * args.shard_size : (s + 1) * args.shard_size]
        samples = [
            # key must be dot-free (tario splits members at the first dot of
            # the basename) and unique (same-stem .jpg/.png files would
            # otherwise merge into one sample) — sanitize and append a
            # global running index. decode_image sniffs the payload bytes,
            # so the member is always named "jpg" regardless of source
            # format.
            {
                "__key__": (
                    f"{path.parent.name}_{path.stem}".replace(".", "_")
                    + f"_{s * args.shard_size + j:07d}"
                ),
                "jpg": path.read_bytes(),
                "cls": str(label).encode(),
            }
            for j, (path, label) in enumerate(chunk)
        ]
        write_tar_samples(str(out / f"{args.prefix}-{s:0{width}d}.tar"), samples)

    spec = f"{out}/{args.prefix}-{{{'0' * width}..{n_shards - 1:0{width}d}}}.tar"
    print(
        json.dumps(
            {
                "samples": len(files),
                "classes": len(classes),
                "shards": n_shards,
                "spec": spec,
            }
        )
    )


if __name__ == "__main__":
    main()
