#!/usr/bin/env python3
"""Offline memory diagnosis: journaled ``mem_sample`` rows → markdown.

run_doctor explains a run's lifecycle and fleet_doctor the pod; this tool
explains the run's MEMORY — where the peak was, which component grew, and
whether the leak sentinel's live verdict holds up — from the crash-safe
journal alone. No live process, no /metrics endpoint:

    python tools/mem_doctor.py runs/my_run
    python tools/mem_doctor.py runs/my_run --out mem.md

The report covers:

- **Verdict** — the leak sentinel's journaled ``mem_leak_suspect`` (naming
  the fastest-growing component), plus the OOM-risk estimate: measured
  device peak / the ChipSpec HBM capacity recorded in the samples (skipped
  on backends with no capacity claim, e.g. CPU smoke).
- **Peak timeline** — RSS / device-peak per journaled sample.
- **Component attribution** — first→last bytes and growth per accounted
  component (``mem_component_bytes`` sources), fastest grower first.
- **HBM predict-vs-measured** — the last sample's per-program drift ratios.

Exit codes: 0 = healthy diagnosis written; 2 = incident (a leak suspect was
journaled) or nothing to diagnose (no ``mem_sample`` rows — run.memwatch
off, or the run died before its first log boundary).
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jumbo_mae_tpu_tpu.obs.doctor_common import (  # noqa: E402
    fmt_num as _fmt_num,
    write_report,
)
from jumbo_mae_tpu_tpu.obs.journal import read_merged_journal  # noqa: E402

MB = 1024 * 1024


def _mib(v) -> str:
    return f"{float(v) / MB:.1f} MiB"


def _timeline_rows(samples: list[dict], limit: int) -> list[dict]:
    """At most ``limit`` rows, always keeping the first and last sample —
    the report wants the trend, not a row per log window of a long run."""
    if len(samples) <= limit:
        return samples
    stride = max(1, (len(samples) - 1) // (limit - 1))
    picked = samples[::stride]
    if picked[-1] is not samples[-1]:
        picked.append(samples[-1])
    return picked


def diagnose(events: list[dict], args) -> tuple[str, int]:
    """Markdown report + exit code from one run's journal events."""
    samples = [e for e in events if e.get("type") == "mem_sample"]
    leaks = [e for e in events if e.get("type") == "mem_leak_suspect"]
    dumps = [
        e
        for e in events
        if e.get("type") == "flight_record" and e.get("reason") == "mem_leak"
    ]

    lines = ["# Memory doctor report", ""]
    rc = 0

    # -------------------------------------------------------------- verdict
    lines += ["## Verdict", ""]
    if leaks:
        rc = 2
        for e in leaks:
            lines.append(
                f"- leak suspected: **{e.get('component')}** — "
                f"+{_mib(e.get('robust_growth_bytes', 0))} robust RSS growth "
                f"over {e.get('window')} samples "
                f"({_fmt_num(e.get('window_span_s', 0))}s) at step "
                f"{e.get('step')}; component slope "
                f"{_mib(e.get('component_slope_bytes_per_sample', 0))}/sample"
            )
    else:
        lines.append("- no leak suspected (the sentinel never fired)")
    # OOM risk: measured device high-water vs the chip's HBM capacity. Only
    # when the run recorded both — generic CPU carries capacity 0 and gets
    # no made-up denominator.
    peak = max(
        (int(s.get("device_peak_bytes", 0) or 0) for s in samples), default=0
    )
    cap = max(
        (int(s.get("hbm_capacity_bytes", 0) or 0) for s in samples), default=0
    )
    if peak > 0 and cap > 0:
        frac = peak / cap
        risk = "HIGH" if frac >= 0.9 else "elevated" if frac >= 0.75 else "low"
        lines.append(
            f"- OOM risk **{risk}**: device peak {_mib(peak)} = "
            f"{frac:.1%} of {_mib(cap)} HBM capacity"
        )
    elif peak > 0:
        lines.append(
            f"- OOM risk not assessable: device peak {_mib(peak)} but no HBM "
            "capacity recorded (generic/CPU chip spec)"
        )
    else:
        lines.append(
            "- OOM risk not assessable: no device memory stats in the "
            "samples (backend degraded to host-only telemetry)"
        )
    lines.append("")

    # -------------------------------------------------------- peak timeline
    lines += [
        "## Peak timeline",
        "",
        "| step | rss | device in-use | device peak | py blocks |",
        "|---|---|---|---|---|",
    ]
    for s in _timeline_rows(samples, args.timeline_rows):
        lines.append(
            f"| {s.get('step', '—')} "
            f"| {_mib(s['rss_bytes']) if s.get('rss_bytes') else '—'} "
            f"| {_mib(s['device_bytes']) if s.get('device_bytes') else '—'} "
            f"| {_mib(s['device_peak_bytes']) if s.get('device_peak_bytes') else '—'} "
            f"| {s.get('py_alloc_blocks', '—')} |"
        )
    lines.append("")

    # ---------------------------------------------- component attribution
    lines += ["## Component attribution", ""]
    names: set[str] = set()
    for s in samples:
        names.update((s.get("components") or {}))
    if not names:
        lines.append("(no accounted components in the samples)")
    else:
        rows = []
        for name in names:
            series = [
                int((s.get("components") or {}).get(name, 0)) for s in samples
            ]
            rows.append((series[-1] - series[0], name, series[0], series[-1]))
        rows.sort(reverse=True)
        lines += [
            "| component | first | last | growth |",
            "|---|---|---|---|",
        ]
        for growth, name, first, last in rows:
            lines.append(
                f"| {name} | {_mib(first)} | {_mib(last)} | "
                f"{'+' if growth >= 0 else '−'}{_mib(abs(growth))} |"
            )
    lines.append("")

    # ------------------------------------------------ predict-vs-measured
    lines += ["## HBM predict vs measured", ""]
    drift = next(
        (s.get("hbm_drift") for s in reversed(samples) if s.get("hbm_drift")),
        None,
    )
    if not drift:
        lines.append(
            "(no drift ratios — no device memory stats or no predicted "
            "peaks recorded)"
        )
    else:
        lines += ["| program | measured peak / predicted |", "|---|---|"]
        for prog, ratio in sorted(drift.items()):
            lines.append(f"| {prog} | {_fmt_num(ratio)} |")
    lines.append("")

    # ------------------------------------------------------- flight records
    if dumps:
        lines += ["## Flight records", ""]
        for e in dumps:
            lines.append(f"- `{e.get('path')}`")
        lines.append("")
    return "\n".join(lines), rc


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("path", help="run dir (containing journal/ segments)")
    parser.add_argument(
        "--timeline-rows",
        type=int,
        default=24,
        help="max rows in the peak timeline (first/last always kept)",
    )
    parser.add_argument(
        "--out", default=None, help="write the markdown here (default stdout)"
    )
    args = parser.parse_args(argv)

    run_dir = Path(args.path)
    try:
        events = read_merged_journal(run_dir)
    except FileNotFoundError:
        events = []
    if not any(e.get("type") == "mem_sample" for e in events):
        print(
            f"[mem_doctor] no mem_sample rows in the journal under {run_dir} "
            "(run.memwatch off, or the run died before a log boundary?)",
            file=sys.stderr,
        )
        return 2

    report, rc = diagnose(events, args)
    write_report(report, args.out, tool="mem_doctor")
    return rc


if __name__ == "__main__":
    sys.exit(main())
