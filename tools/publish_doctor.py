#!/usr/bin/env python3
"""Offline publish-artifact verification: walk the delta chain, prove it.

The swap watcher verifies one artifact at a time as it lands; this tool
audits the whole ``--swap-watch`` / ``run.publish_dir`` directory after the
fact — every ``publish-NNNNNN`` artifact's payload hash and leaf digests,
every delta chain resolved back to a full tree, every resolved tree's
fingerprint recomputed against its manifest. A broken link is *named*
(which artifact, which base, what mismatched), so an operator knows what to
re-publish instead of re-shipping everything:

    python tools/publish_doctor.py /tmp/swap_push
    python tools/publish_doctor.py /tmp/swap_push --artifact publish-000003
    python tools/publish_doctor.py /tmp/swap_push --out publish.md

Quarantined artifacts (``.quarantine/`` — entries the live watcher already
rejected) are reported but do not fail the audit: quarantine working as
designed is health, not damage.

Exit codes: 0 = every artifact verified and every chain resolved;
2 = no artifacts found, or at least one broken artifact/chain.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jumbo_mae_tpu_tpu.serve.publisher import (  # noqa: E402
    PublishIntegrityError,
    is_publish_artifact,
    load_manifest,
    resolve_chain,
)


def audit_artifact(path: Path) -> dict:
    """One artifact's verdict: resolve its full chain (verifying every
    link) and recompute the parity fingerprint."""
    row: dict = {"name": path.name, "ok": False}
    try:
        m = load_manifest(path)
        row.update(
            step=m.get("step"),
            quant=m.get("quant"),
            base=(m.get("base") or {}).get("name"),
            delta_fraction=m.get("delta_fraction"),
        )
        params, batch_stats, _ = resolve_chain(path)
        n = sum(1 for _ in _walk_leaves(params))
        if batch_stats is not None:
            n += sum(1 for _ in _walk_leaves(batch_stats))
        row.update(ok=True, leaves=n, verdict="verified")
    except PublishIntegrityError as e:
        row["verdict"] = f"BROKEN: {e}"
    return row


def _walk_leaves(node):
    if isinstance(node, dict):
        for v in node.values():
            yield from _walk_leaves(v)
    elif node is not None:
        yield node


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("publish_dir", help="the swap-watch / publish directory")
    ap.add_argument(
        "--artifact",
        default="",
        help="audit one named artifact instead of the whole directory",
    )
    ap.add_argument("--out", default="", help="also write the report here")
    args = ap.parse_args(argv)

    root = Path(args.publish_dir)
    if args.artifact:
        names = [args.artifact]
    else:
        names = sorted(
            n
            for n in (os.listdir(root) if root.is_dir() else [])
            if not n.startswith(".") and is_publish_artifact(root / n)
        )
    if not names:
        print(f"publish_doctor: no publish artifacts under {root}")
        return 2

    rows = [audit_artifact(root / n) for n in names]
    qdir = root / ".quarantine"
    quarantined = sorted(p.name for p in qdir.iterdir()) if qdir.is_dir() else []

    lines = [f"# publish_doctor — {root}", ""]
    lines.append("| artifact | step | quant | base | delta | verdict |")
    lines.append("|---|---|---|---|---|---|")
    for r in rows:
        lines.append(
            f"| {r['name']} | {r.get('step', '?')} | {r.get('quant', '?')} "
            f"| {r.get('base') or 'full'} | {r.get('delta_fraction', '?')} "
            f"| {r['verdict']} |"
        )
    broken = [r for r in rows if not r["ok"]]
    lines.append("")
    if quarantined:
        lines.append(
            f"quarantined (rejected by the live watcher, as designed): "
            f"{', '.join(quarantined)}"
        )
    verdict = (
        f"BROKEN: {len(broken)}/{len(rows)} artifact(s) failed verification"
        if broken
        else f"OK: {len(rows)} artifact(s) verified, all chains resolve"
    )
    lines.append(f"verdict: {verdict}")
    report = "\n".join(lines)
    print(report)
    if args.out:
        Path(args.out).write_text(report + "\n")
    return 2 if broken else 0


if __name__ == "__main__":
    sys.exit(main())
