#!/usr/bin/env python3
"""Offline batch-job audit: job dir (journal + parts + manifest) → report.

``cost_doctor`` answers "who paid for the capacity"; this tool answers
"did the job produce exactly what it claims, and what did it survive
along the way". Input is a :class:`~jumbo_mae_tpu_tpu.batch.BatchJobRunner`
output directory::

    python tools/batch_doctor.py runs/batchjob
    python tools/batch_doctor.py runs/batchjob --out batch-report.md

The report, in order:

- **Verdict** — complete & reconciled, or the specific failures.
- **Progress** — shards total/done/quarantined, samples written, resumes
  observed (``job_start`` resumed_shards + ``job_cursor`` trail).
- **Lease timeline** — every ``job_lease`` grant in order; steals are
  flagged and **name the worker whose lease was stolen** (the dead or
  stalled holder) — the forensic trail for "who crashed and who rescued
  the shard".
- **Retry / quarantine attribution** — shards that finished
  ``status="quarantined"`` (the shard store gave up mid-pass) with their
  durable sample counts.
- **Reconciliation** — the manifest's word against the bytes on disk:
  every manifest entry's part must exist, match its recorded sha256, and
  contain exactly the recorded number of well-framed records; parts on
  disk that the manifest doesn't claim are orphans.

Exit codes: 0 = manifest present and reconciles 100%; 2 = no job dir /
no manifest (job incomplete or never ran) or any reconciliation failure
(sha mismatch, bad frame count, missing part, orphan part).
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jumbo_mae_tpu_tpu.batch.partfile import (  # noqa: E402
    file_sha256,
    read_manifest,
    scan_part,
)
from jumbo_mae_tpu_tpu.obs.doctor_common import fmt_num, write_report  # noqa: E402
from jumbo_mae_tpu_tpu.obs.journal import read_journal  # noqa: E402


def _events(job_dir: Path) -> list[dict]:
    try:
        return read_journal(job_dir / "journal")
    except FileNotFoundError:
        return []


def reconcile(job_dir: Path, manifest: dict) -> tuple[list[str], list[str]]:
    """(table rows, failures) of manifest-vs-disk — the exactly-once
    audit. Every failure string is a reason for exit 2."""
    rows: list[str] = []
    failures: list[str] = []
    parts_dir = job_dir / "parts"
    claimed: set[str] = set()
    for entry in manifest.get("shards", []):
        part = parts_dir / entry["part"]
        claimed.add(entry["part"])
        if not part.exists():
            rows.append(f"| `{entry['part']}` | missing | - | - | **FAIL** |")
            failures.append(f"part {entry['part']} missing from disk")
            continue
        n, good = scan_part(part)
        sha = file_sha256(part)
        ok_n = n == entry["samples"] and good == part.stat().st_size
        ok_sha = sha == entry["sha256"]
        status = "ok" if (ok_n and ok_sha) else "**FAIL**"
        rows.append(
            f"| `{entry['part']}` | {n}/{entry['samples']} "
            f"| {'match' if ok_sha else 'MISMATCH'} "
            f"| {fmt_num(part.stat().st_size)} B | {status} |"
        )
        if not ok_n:
            failures.append(
                f"part {entry['part']} holds {n} well-framed records "
                f"({good} good bytes of {part.stat().st_size}), manifest "
                f"says {entry['samples']}"
            )
        if not ok_sha:
            failures.append(f"part {entry['part']} sha256 mismatch")
    if parts_dir.is_dir():
        for p in sorted(parts_dir.glob("*.part")):
            if p.name not in claimed:
                rows.append(f"| `{p.name}` | orphan | - | - | **FAIL** |")
                failures.append(
                    f"orphan part {p.name} on disk but not in the manifest"
                )
    return rows, failures


def diagnose(job_dir: Path, manifest: dict, events: list[dict]) -> tuple[str, list[str]]:
    lines: list[str] = ["# Batch doctor report", ""]
    failures: list[str] = []

    # ------------------------------------------------------------ progress
    starts = [e for e in events if e.get("type") == "job_start"]
    completes = [e for e in events if e.get("type") == "job_complete"]
    cursors = [e for e in events if e.get("type") == "job_cursor"]
    shard_done = [e for e in events if e.get("type") == "job_shard_done"]
    quarantined = [e for e in shard_done if e.get("status") == "quarantined"]
    lines += ["## Progress", ""]
    lines.append(
        f"- manifest: {len(manifest.get('shards', []))} shard(s), "
        f"{fmt_num(manifest.get('total_samples', 0))} samples"
    )
    lines.append(
        f"- journal: {len(starts)} run(s) of this job "
        f"({max(0, len(starts) - 1)} resume(s)), "
        f"{len(shard_done)} shard completion(s), "
        f"{len(cursors)} progress cursor(s)"
    )
    resumed = sum(int(e.get("resumed_shards") or 0) for e in starts)
    if resumed:
        lines.append(
            f"- {resumed} shard(s) were already durable at (re)start "
            "and skipped recompute entirely"
        )
    if completes:
        c = completes[-1]
        lines.append(
            f"- completed with {fmt_num(c.get('total_samples', 0))} samples, "
            f"{int(c.get('lease_steals') or 0)} lease steal(s), "
            f"{int(c.get('quarantined') or 0)} quarantined shard(s)"
        )
    lines.append("")

    # ------------------------------------------------------ lease timeline
    leases = [e for e in events if e.get("type") == "job_lease"]
    if leases:
        lines += [
            "## Lease timeline",
            "",
            "| lease | shard | worker | note |",
            "|---|---|---|---|",
        ]
        for e in leases:
            shard = str(e.get("shard", "?")).rsplit("/", 1)[-1]
            note = (
                f"**stolen from `{e['stolen_from']}`** (lease expired — "
                "holder dead or stalled)"
                if e.get("stolen_from")
                else "claim"
            )
            lines.append(
                f"| {e.get('lease')} | `{shard}` | {e.get('worker')} "
                f"| {note} |"
            )
        lines.append("")

    # ------------------------------------- retry / quarantine attribution
    if quarantined:
        lines += ["## Quarantined shards", ""]
        for e in quarantined:
            lines.append(
                f"- `{e.get('shard')}`: store gave up mid-pass after "
                f"retries; {fmt_num(e.get('samples', 0))} sample(s) durable "
                "in its kept `.partial` (excluded from the manifest; a "
                "healed store resumes it next run)"
            )
        lines.append("")

    # ------------------------------------------------------ reconciliation
    lines += [
        "## Reconciliation (manifest vs disk)",
        "",
        "| part | records | sha256 | bytes | status |",
        "|---|---|---|---|---|",
    ]
    rows, failures = reconcile(job_dir, manifest)
    lines += rows or ["| - | - | - | - | - |"]
    lines.append("")

    verdict = (
        ["complete: manifest reconciles 100% against the bytes on disk"]
        if not failures
        else failures
    )
    steals = sum(1 for e in leases if e.get("stolen_from"))
    if steals and not failures:
        verdict.append(
            f"{steals} lease steal(s) survived without duplicating or "
            "dropping a sample"
        )
    lines[2:2] = ["## Verdict", ""] + [f"- {v}" for v in verdict] + [""]
    return "\n".join(lines), failures


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("path", help="batch job output dir (holds manifest.json)")
    parser.add_argument(
        "--out", default=None, help="write the markdown here (default stdout)"
    )
    args = parser.parse_args(argv)

    job_dir = Path(args.path)
    manifest = read_manifest(job_dir / "manifest.json")
    if manifest is None:
        print(
            f"[batch_doctor] no readable manifest under {job_dir} — job "
            "incomplete (resumable: re-run it) or wrong directory",
            file=sys.stderr,
        )
        return 2
    report, failures = diagnose(job_dir, manifest, _events(job_dir))
    rc = write_report(report, args.out, tool="batch_doctor")
    if failures:
        for f in failures:
            print(f"[batch_doctor] FAIL: {f}", file=sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
