#!/usr/bin/env python3
"""Time attention fwd+bwd on the real chip: Pallas flash vs XLA einsum.

One (shape, impl, knobs) cell per invocation — the Pallas kernel knobs
(JUMBO_PALLAS_MM_F32, JUMBO_PALLAS_PAD_TO_BLOCK, JUMBO_PALLAS_LANE) are
module-import constants, so each cell gets a fresh process. Use --matrix to
fan a sweep out over subprocesses and collect JSONL.

    python tools/flash_microbench.py --shape 128,199,16,32 --impl flash
    python tools/flash_microbench.py --matrix --out /tmp/flash_ab.jsonl

Shapes are (batch, seq, heads, head_dim) of the attention input; timing is
value_and_grad of a sum over the output — forward AND both backward
kernels in one number, matching how the train step exercises them.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# (batch, seq, heads, head_dim) — the production attention shapes:
#   dec224: ViT-L/16 MAE decoder at 224px (seq 196+3), the B-scale hot spot
#   enc448 / dec448: 448px long-context legs (encoder keeps 25% + CLS)
SHAPES = {
    "dec224": (128, 199, 16, 32),
    "enc448": (32, 199, 16, 64),
    "dec448": (32, 787, 16, 32),
    "dec448w": (16, 787, 16, 64),
}


def run_cell(args) -> dict:
    sys.path.insert(0, str(REPO))
    from bench import acquire_backend

    acquire_backend()

    import jax
    import jax.numpy as jnp

    from jumbo_mae_tpu_tpu.ops.flash_attention import xla_attention
    from jumbo_mae_tpu_tpu.ops.pallas.attention import pallas_flash_attention

    b, s, h, d = (int(x) for x in args.shape.split(","))
    dtype = jnp.float32 if args.f32_inputs else jnp.bfloat16
    ks = jax.random.split(jax.random.key(0), 3)
    q = (jax.random.normal(ks[0], (b, s, h, d)) * d**-0.5).astype(dtype)
    k = jax.random.normal(ks[1], (b, s, h, d)).astype(dtype)
    v = jax.random.normal(ks[2], (b, s, h, d)).astype(dtype)

    if args.impl == "flash":
        fn = lambda q, k, v: pallas_flash_attention(
            q, k, v, args.block_q, args.block_k
        ).astype(jnp.float32).sum()
    else:
        fn = lambda q, k, v: xla_attention(q, k, v).astype(jnp.float32).sum()

    # Over this remote tunnel, block_until_ready can return before the
    # dispatched programs finish (bench.py time_steps documents the same
    # failure mode), so independent timed calls measure dispatch, not
    # compute. Chain the iterations through a lax.scan carry instead — one
    # program whose N inner attention steps are data-dependent and cannot
    # overlap or be elided — and force a full host fetch of the outputs.
    grad_fn = jax.value_and_grad(fn, argnums=(0, 1, 2))

    @jax.jit
    def chained(q, k, v):
        def body(carry, _):
            val, grads = grad_fn(carry, k, v)
            return carry + (1e-6 * grads[0]).astype(carry.dtype), val
        _, vals = jax.lax.scan(body, q, None, length=args.iters)
        return vals

    vals = jax.device_get(chained(q, k, v))  # compile + warm, full fetch
    assert all(map(lambda x: x == x, vals)), "non-finite bench values"

    # 100%-MFU floor for the fwd+bwd attention matmuls (5 full score-shaped
    # matmuls' worth fwd+bwd: 2 fwd + ~5 bwd streams ≈ 7·2·b·h·s²·d, but be
    # conservative and floor on the forward pair only).
    floor_ms = (4 * b * h * s * s * d) / 197e12 * 1e3

    times = []
    for _ in range(args.rounds):
        t0 = time.perf_counter()
        vals = jax.device_get(chained(q, k, v))
        times.append((time.perf_counter() - t0) / args.iters * 1000)
    best = min(times)
    return {
        "impl": args.impl,
        "shape": [b, s, h, d],
        "block_q": args.block_q,
        "block_k": args.block_k,
        "mm_f32": os.environ.get("JUMBO_PALLAS_MM_F32") == "1",
        "pad_to_block": os.environ.get("JUMBO_PALLAS_PAD_TO_BLOCK") == "1",
        "ms_fwd_bwd": best,
        "ms_all_rounds": [round(t, 3) for t in times],
        "floor_ms": round(floor_ms, 4),
        "suspect": best < floor_ms,
    }


def run_matrix(args) -> int:
    cells = []
    for name, (b, s, h, d) in SHAPES.items():
        shape = f"{b},{s},{h},{d}"
        cells.append({"name": name, "shape": shape, "impl": "einsum"})
        for blocks in ((256, 256), (512, 512), (128, 128)):
            for mm_f32 in (False, True):
                for pad in (False, True):
                    cells.append(
                        {
                            "name": name,
                            "shape": shape,
                            "impl": "flash",
                            "block_q": blocks[0],
                            "block_k": blocks[1],
                            "mm_f32": mm_f32,
                            "pad": pad,
                        }
                    )
    out_path = Path(args.out) if args.out else None
    for cell in cells:
        env = dict(os.environ)
        env["JUMBO_PALLAS_MM_F32"] = "1" if cell.get("mm_f32") else "0"
        env["JUMBO_PALLAS_PAD_TO_BLOCK"] = "1" if cell.get("pad") else "0"
        cmd = [
            sys.executable, __file__,
            "--shape", cell["shape"],
            "--impl", cell["impl"],
            "--iters", str(args.iters),
            "--rounds", str(args.rounds),
        ]
        if cell["impl"] == "flash":
            cmd += [
                "--block-q", str(cell["block_q"]),
                "--block-k", str(cell["block_k"]),
            ]
        t0 = time.time()
        proc = subprocess.run(
            cmd, env=env, capture_output=True, text=True, timeout=900
        )
        line = None
        for out_line in reversed(proc.stdout.splitlines()):
            if out_line.startswith("{"):
                line = out_line
                break
        record = {
            "name": cell["name"],
            "wall_s": round(time.time() - t0, 1),
            **(json.loads(line) if line else {"error": proc.stderr[-800:]}),
        }
        print(json.dumps(record), flush=True)
        if out_path:
            with out_path.open("a") as f:
                f.write(json.dumps(record) + "\n")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--shape", default="128,199,16,32", help="b,s,h,d")
    ap.add_argument("--impl", choices=("flash", "einsum"), default="flash")
    ap.add_argument("--block-q", type=int, default=1024)
    ap.add_argument("--block-k", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--f32-inputs", action="store_true")
    ap.add_argument("--matrix", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.matrix:
        return run_matrix(args)
    print(json.dumps(run_cell(args)), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
