"""Shared AST plumbing for the graftlint checkers.

Everything here is deliberately *syntactic*: no imports of the scanned
code, no type inference. The checkers buy zero false positives by only
claiming what the AST states outright (a decorator literally named
``jax.jit``, a ``with self._lock:`` block, a string literal argument) and
leaving anything that would need dataflow analysis alone.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path


@dataclass
class SourceFile:
    path: Path           # absolute
    rel: str             # repo-relative, posix
    tree: ast.Module
    lines: list[str]

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def parse_file(path: Path, root: Path) -> SourceFile | None:
    try:
        text = path.read_text()
        tree = ast.parse(text, filename=str(path))
    except (SyntaxError, UnicodeDecodeError, OSError):
        return None
    annotate_parents(tree)
    rel = path.relative_to(root).as_posix() if root in path.parents or path == root else str(path)
    return SourceFile(path=path, rel=rel, tree=tree, lines=text.splitlines())


def annotate_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.graftlint_parent = node  # type: ignore[attr-defined]


def parents(node: ast.AST):
    cur = getattr(node, "graftlint_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "graftlint_parent", None)


def enclosing_scope(node: ast.AST) -> str:
    """``Class.method`` / ``function`` / ``<module>`` for a node."""
    names: list[str] = []
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.append(p.name)
    return ".".join(reversed(names)) or "<module>"


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def keyword_arg(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


@dataclass
class JitInfo:
    """One jitted function found in a module: the def plus jit-call facts."""

    func: ast.FunctionDef
    static_names: set[str] = field(default_factory=set)
    jit_call: ast.Call | None = None


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _static_names_of(call: ast.Call) -> set[str]:
    """Parameter names pinned static by a jit call's kwargs."""
    out: set[str] = set()
    kw = keyword_arg(call, "static_argnames")
    if kw is not None:
        if (s := str_const(kw)) is not None:
            out.add(s)
        elif isinstance(kw, (ast.Tuple, ast.List)):
            out |= {s for e in kw.elts if (s := str_const(e)) is not None}
    return out


def _jit_call_of_decorator(dec: ast.expr) -> ast.Call | None:
    """The jit Call carrying kwargs, for any of the decorator spellings:
    ``@jax.jit``, ``@jax.jit`` called, ``@partial(jax.jit, ...)``."""
    if dotted_name(dec) in _JIT_NAMES:
        return None  # bare decorator: jitted, but no kwargs to read
    if isinstance(dec, ast.Call):
        name = call_name(dec)
        if name in _JIT_NAMES:
            return dec
        if name in ("partial", "functools.partial") and dec.args:
            if dotted_name(dec.args[0]) in _JIT_NAMES:
                return dec
    return None


def _is_jit_decorator(dec: ast.expr) -> bool:
    if dotted_name(dec) in _JIT_NAMES:
        return True
    return _jit_call_of_decorator(dec) is not None


def find_jitted_functions(sf: SourceFile) -> list[JitInfo]:
    """Functions jitted in this module: by decorator, or by being passed
    (module-locally) as the first argument of a ``jax.jit(...)`` call."""
    by_name: dict[str, ast.FunctionDef] = {}
    jitted: dict[int, JitInfo] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, node)
            for dec in node.decorator_list:
                if _is_jit_decorator(dec):
                    info = jitted.setdefault(id(node), JitInfo(func=node))
                    call = _jit_call_of_decorator(dec)
                    if call is not None:
                        info.jit_call = call
                        info.static_names |= _static_names_of(call)
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and call_name(node) in _JIT_NAMES:
            if node.args and (target := dotted_name(node.args[0])):
                fn = by_name.get(target)
                if fn is not None:
                    info = jitted.setdefault(id(fn), JitInfo(func=fn))
                    info.jit_call = node
                    info.static_names |= _static_names_of(node)
    return list(jitted.values())


def param_names(func: ast.FunctionDef) -> list[str]:
    args = func.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def iter_py_files(paths: list[Path]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_dir():
            out.extend(
                f for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            out.append(p)
    return out
