"""CON — project contract drift between code, registries, and docs.

| Rule   | Claim |
|--------|-------|
| CON001 | A metric registered in code has no row in the README glossary
|        | (or a glossary row names a metric nothing registers). |
| CON002 | A journal event emitted with a literal name is not in the
|        | frozen ``obs.journal.JOURNAL_EVENTS`` schema list (or a README
|        | journal-table row names an event the schema doesn't). |
| CON003 | A ``fault_point(...)`` site, or a site named in a GRAFT_FAULTS
|        | plan string (code, tests, CI, README cookbook), is not in
|        | ``faults.inject.KNOWN_SITES``. |
| CON004 | A ``--set section.key=...`` reference or a ``cfg.<section>.<key>``
|        | attribute access names a config key the dataclasses don't have. |

The registries are read by *parsing* the defining modules (AST, no
imports), so the checker works on any tree that merely contains them.
Dynamic registrations (f-string metric names) are tracked as prefixes so
documented families like ``xla_*`` don't read as stale.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path

from tools.graftlint.astutil import (
    SourceFile,
    call_name,
    dotted_name,
    enclosing_scope,
    parents,
    str_const,
)
from tools.graftlint.findings import Finding

CHECKER = "contract drift"

_METRIC_METHODS = {"counter", "gauge", "histogram"}
_SECTION_FILES = {
    "run": ("jumbo_mae_tpu_tpu/config.py", "RunConfig"),
    "model": ("jumbo_mae_tpu_tpu/config.py", "ModelConfig"),
    "optim": ("jumbo_mae_tpu_tpu/train/optim.py", "OptimConfig"),
    "data": ("jumbo_mae_tpu_tpu/data/loader.py", "DataConfig"),
    "mesh": ("jumbo_mae_tpu_tpu/parallel/mesh.py", "MeshConfig"),
}
_CONFIG_REF_RE = re.compile(
    r"\b(run|model|optim|data|mesh)\.([a-z_][a-z0-9_]*)\s*="
)
_PLAN_SITE_RE = re.compile(r"^\s*([a-z]+\.[a-z_][a-z0-9_]*)\s*:")
_GRAFT_FAULTS_RE = re.compile(r"""GRAFT_FAULTS[=:]\s*["']?([^"'\s][^"'\n]*)""")
_NAME_TOKEN_RE = re.compile(r"`([a-z_][a-z0-9_]*)(?:\{[^}`]*\})?`")


def _dataclass_fields(tree: ast.Module, class_name: str) -> set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return {
                item.target.id
                for item in node.body
                if isinstance(item, ast.AnnAssign)
                and isinstance(item.target, ast.Name)
            }
    return set()


def _string_set(tree: ast.Module, var_name: str) -> set[str]:
    """Literal elements of ``VAR = frozenset({...})`` / tuple / set / list."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if var_name not in targets:
                continue
            value = node.value
            if isinstance(value, ast.Call) and value.args:
                value = value.args[0]
            if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
                return {
                    s for e in value.elts if (s := str_const(e)) is not None
                }
    return set()


@dataclass
class Registries:
    """The project's frozen contracts, parsed from their defining files."""

    known_sites: set[str] = field(default_factory=set)
    journal_events: set[str] = field(default_factory=set)
    config_fields: dict[str, set[str]] = field(default_factory=dict)
    readme_metrics: set[str] = field(default_factory=set)
    readme_dynamic: bool = False
    readme_journal_rows: list[tuple[str, int]] = field(default_factory=list)

    @classmethod
    def load(cls, root: Path) -> "Registries":
        regs = cls()
        inject = root / "jumbo_mae_tpu_tpu/faults/inject.py"
        if inject.exists():
            regs.known_sites = _string_set(
                ast.parse(inject.read_text()), "KNOWN_SITES"
            )
        journal = root / "jumbo_mae_tpu_tpu/obs/journal.py"
        if journal.exists():
            regs.journal_events = _string_set(
                ast.parse(journal.read_text()), "JOURNAL_EVENTS"
            )
        for section, (rel, class_name) in _SECTION_FILES.items():
            path = root / rel
            if path.exists():
                regs.config_fields[section] = _dataclass_fields(
                    ast.parse(path.read_text()), class_name
                )
        readme = root / "README.md"
        if readme.exists():
            regs._parse_readme(readme.read_text())
        return regs

    def _parse_readme(self, text: str) -> None:
        in_journal_table = False
        for lineno, line in enumerate(text.splitlines(), 1):
            cells = [c.strip() for c in line.split("|")]
            is_row = line.lstrip().startswith("|") and len(cells) >= 3
            if not is_row:
                in_journal_table = False
                continue
            first, second = cells[1], cells[2]
            if first == "`type`" and second.lower() == "when":
                in_journal_table = True
                continue
            if in_journal_table and not set(first) <= {"-", " "}:
                for name in _NAME_TOKEN_RE.findall(first):
                    self.readme_journal_rows.append((name, lineno))
                continue
            if re.search(r"\b(gauge|counter|histogram)\b", second):
                if "…" in first or "..." in first:
                    continue  # explicitly-dynamic row: prefix family
                self.readme_metrics |= set(_NAME_TOKEN_RE.findall(first))


def _finding(sf: SourceFile, rule: str, node: ast.AST, msg: str) -> Finding:
    return Finding(
        rule=rule,
        path=sf.rel,
        line=node.lineno,
        scope=enclosing_scope(node),
        message=msg,
        snippet=sf.snippet(node.lineno),
        checker=CHECKER,
    )


def _is_docstring(node: ast.Constant) -> bool:
    parent = getattr(node, "graftlint_parent", None)
    return isinstance(parent, ast.Expr)


def _fstring_prefix(node: ast.AST) -> str | None:
    """The literal prefix of an f-string like ``f"xla_{k}"``."""
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return head.value
    return None


def _module_literal_table(sf: SourceFile, expr: ast.expr):
    """Resolve ``expr`` to a literal tuple/list, following one module-level
    Name assignment (``_GAUGES = (...)`` then ``for ... in _GAUGES``)."""
    if isinstance(expr, (ast.Tuple, ast.List)):
        return expr
    if isinstance(expr, ast.Name):
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == expr.id
                for t in node.targets
            ):
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    return node.value
    return None


def _loop_table_names(name_arg: ast.Name, sf: SourceFile) -> set[str]:
    """Names a loop-variable registration can take, for the common
    table-driven idiom: ``for field, name, help in _TABLE: reg.gauge(name,
    ...)`` (statement loop or comprehension, table a module-level literal).
    Returns the string elements at the variable's tuple position."""
    for p in parents(name_arg):
        if isinstance(p, ast.For):
            loops = [(p.target, p.iter)]
        elif isinstance(p, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            loops = [(g.target, g.iter) for g in p.generators]
        else:
            continue
        for target, iter_expr in loops:
            idx: int | None = None
            if isinstance(target, ast.Name) and target.id == name_arg.id:
                idx = -1  # scalar loop var: every string in the table
            elif isinstance(target, ast.Tuple):
                for i, elt in enumerate(target.elts):
                    if isinstance(elt, ast.Name) and elt.id == name_arg.id:
                        idx = i
            if idx is None:
                continue
            table = _module_literal_table(sf, iter_expr)
            if table is None:
                continue
            out: set[str] = set()
            for row in table.elts:
                if idx == -1:
                    if (s := str_const(row)) is not None:
                        out.add(s)
                elif isinstance(row, (ast.Tuple, ast.List)) and idx < len(row.elts):
                    if (s := str_const(row.elts[idx])) is not None:
                        out.add(s)
            if out:
                return out
    return set()


def _plan_literals(sf: SourceFile):
    """(string, node) pairs that carry a fault-injection plan: arguments of
    ``install_plan``/``FaultPlan.parse``, ``faults=`` keywords, and values
    bound to a GRAFT_FAULTS env key (assignment or dict literal)."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name and name.split(".")[-1] == "install_plan":
                if node.args and (s := str_const(node.args[0])) is not None:
                    yield s, node
            for kw in node.keywords:
                if kw.arg == "faults" and (s := str_const(kw.value)) is not None:
                    yield s, kw.value
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and (key := str_const(tgt.slice)) == "GRAFT_FAULTS"
                    and (s := str_const(node.value)) is not None
                ):
                    yield s, node
        elif isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (
                    k is not None
                    and str_const(k) == "GRAFT_FAULTS"
                    and (s := str_const(v)) is not None
                ):
                    yield s, v


def _plan_sites(plan: str) -> list[str]:
    sites = []
    for part in plan.split(";"):
        m = _PLAN_SITE_RE.match(part)
        if m:
            sites.append(m.group(1))
    return sites


@dataclass
class ContractScan:
    findings: list[Finding] = field(default_factory=list)
    # literal metric registrations: name -> (rel, line) of first sight
    registered: dict[str, tuple[str, int]] = field(default_factory=dict)
    # f-string registrations: literal name prefixes ("xla_", "slo_")
    dynamic_prefixes: set[str] = field(default_factory=set)


def check_contracts_py(
    sf: SourceFile, regs: Registries, scan: ContractScan
) -> None:
    """File-anchored contract checks + metric-registration collection."""
    skip_metrics = sf.rel.endswith("obs/metrics.py")
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            # --- metric registrations ------------------------------------
            if (
                not skip_metrics
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS
                and len(node.args) >= 2
            ):
                name_arg = node.args[0]
                metric = str_const(name_arg)
                if metric is not None:
                    scan.registered.setdefault(metric, (sf.rel, node.lineno))
                    if metric not in regs.readme_metrics:
                        scan.findings.append(
                            _finding(
                                sf,
                                "CON001",
                                node,
                                f"metric `{metric}` is registered here but "
                                "has no row in the README metric glossary",
                            )
                        )
                elif (p := _fstring_prefix(name_arg)) is not None:
                    scan.dynamic_prefixes.add(p)
                elif isinstance(name_arg, ast.Name):
                    # table-driven loops (fleet beacons, xla_* gauges):
                    # resolve what the variable ranges over, don't flag —
                    # each resolved name counts as registered here
                    for resolved in _loop_table_names(name_arg, sf):
                        scan.registered.setdefault(
                            resolved, (sf.rel, node.lineno)
                        )
            # --- fault sites --------------------------------------------
            if (
                name
                and name.split(".")[-1] == "fault_point"
                and node.args
                and (site := str_const(node.args[0])) is not None
                and not sf.rel.endswith("faults/inject.py")
            ):
                if site not in regs.known_sites:
                    scan.findings.append(
                        _finding(
                            sf,
                            "CON003",
                            node,
                            f"fault site `{site}` is not in "
                            "faults.inject.KNOWN_SITES — a plan naming it "
                            "can never fire (or the registry is stale)",
                        )
                    )
            # --- journal events -----------------------------------------
            emits_journal = name == "_emit" or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "event"
                and "journal" in (dotted_name(node.func.value) or "").lower()
            )
            if (
                emits_journal
                and node.args
                and (etype := str_const(node.args[0])) is not None
                and not sf.rel.endswith("obs/journal.py")
            ):
                if etype not in regs.journal_events:
                    scan.findings.append(
                        _finding(
                            sf,
                            "CON002",
                            node,
                            f"journal event `{etype}` is not in "
                            "obs.journal.JOURNAL_EVENTS — readers and "
                            "doctors won't know this row",
                        )
                    )
        # --- config keys in attribute chains:  <cfg>.<section>.<key> -----
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr in regs.config_fields
            # a method call (cfg.mesh.validate_pipe()) is not a field read
            and not (
                isinstance(
                    (call := getattr(node, "graftlint_parent", None)), ast.Call
                )
                and call.func is node
            )
        ):
            base = node.value.value
            base_name = (dotted_name(base) or "").split(".")[-1]
            if base_name in ("cfg", "config", "_cfg"):
                section = node.value.attr
                fields = regs.config_fields.get(section, set())
                if fields and node.attr not in fields:
                    scan.findings.append(
                        _finding(
                            sf,
                            "CON004",
                            node,
                            f"config key `{section}.{node.attr}` is not a "
                            f"field of {_SECTION_FILES[section][1]} — "
                            "load_config would reject it",
                        )
                    )
    # --- plan strings and --set literals inside Python ------------------
    for plan, node in _plan_literals(sf):
        for site in _plan_sites(plan):
            if regs.known_sites and site not in regs.known_sites:
                scan.findings.append(
                    _finding(
                        sf,
                        "CON003",
                        node,
                        f"fault plan names unknown site `{site}` "
                        f"(plan: `{plan}`) — it will never fire",
                    )
                )
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and not _is_docstring(node)
        ):
            for m in _CONFIG_REF_RE.finditer(node.value):
                section, key = m.group(1), m.group(2)
                fields = regs.config_fields.get(section, set())
                if fields and key not in fields:
                    scan.findings.append(
                        _finding(
                            sf,
                            "CON004",
                            node,
                            f"`--set {section}.{key}=...` names a key "
                            f"{_SECTION_FILES[section][1]} doesn't have — "
                            "load_config raises on it",
                        )
                    )


def check_text_file(path: Path, rel: str, regs: Registries) -> list[Finding]:
    """CON003/CON004 over non-Python carriers: CI workflow, README."""
    findings: list[Finding] = []
    try:
        lines = path.read_text().splitlines()
    except OSError:
        return findings
    for lineno, line in enumerate(lines, 1):
        for m in _CONFIG_REF_RE.finditer(line):
            section, key = m.group(1), m.group(2)
            fields = regs.config_fields.get(section, set())
            if fields and key not in fields:
                findings.append(
                    Finding(
                        rule="CON004",
                        path=rel,
                        line=lineno,
                        scope="<text>",
                        message=(
                            f"`{section}.{key}` is not a "
                            f"{_SECTION_FILES[section][1]} field — this "
                            "override/recipe line would be rejected"
                        ),
                        snippet=line.strip()[:120],
                        checker=CHECKER,
                    )
                )
        for m in _GRAFT_FAULTS_RE.finditer(line):
            for site in _plan_sites(m.group(1)):
                if regs.known_sites and site not in regs.known_sites:
                    findings.append(
                        Finding(
                            rule="CON003",
                            path=rel,
                            line=lineno,
                            scope="<text>",
                            message=(
                                f"GRAFT_FAULTS plan names unknown site "
                                f"`{site}` — it will never fire"
                            ),
                            snippet=line.strip()[:120],
                            checker=CHECKER,
                        )
                    )
    return findings


def full_repo_contracts(
    root: Path, regs: Registries, scan: ContractScan
) -> list[Finding]:
    """Two-sided checks that only make sense over the whole tree."""
    findings: list[Finding] = []
    documented_only = regs.readme_metrics - set(scan.registered)
    for name in sorted(documented_only):
        if any(name.startswith(p) for p in scan.dynamic_prefixes):
            continue
        findings.append(
            Finding(
                rule="CON001",
                path="README.md",
                line=1,
                scope="<glossary>",
                message=(
                    f"README glossary documents metric `{name}` but "
                    "nothing registers it — stale row (delete it or "
                    "restore the metric)"
                ),
                snippet=name,
                checker=CHECKER,
            )
        )
    for name, lineno in regs.readme_journal_rows:
        if regs.journal_events and name not in regs.journal_events:
            findings.append(
                Finding(
                    rule="CON002",
                    path="README.md",
                    line=lineno,
                    scope="<journal-table>",
                    message=(
                        f"README journal table documents event `{name}` "
                        "which is not in obs.journal.JOURNAL_EVENTS"
                    ),
                    snippet=name,
                    checker=CHECKER,
                )
            )
    for rel in (".github/workflows/ci.yml", "README.md"):
        path = root / rel
        if path.exists():
            findings.extend(check_text_file(path, rel, regs))
    return findings
