"""graftlint — project-native static analysis for the jumbo-mae-tpu tree.

Three checker families, each conservative by construction (a finding is a
claim the AST supports outright, so the shipped tree lints clean without
suppression comments):

* ``check_tracing``  (TRC001-TRC004) — JAX tracing hazards inside jitted
  functions: Python control flow on traced values, host syncs, wall-clock
  and host RNG, config-shaped parameters without ``static_argnames``.
* ``check_locks``    (LCK001-LCK004) — lock discipline in the threaded
  serving/observability code: blocking while holding a known lock, the
  round-10 self-deadlock shape, global lock-order cycles, ``yield`` under
  a lock.
* ``check_contracts`` (CON001-CON004) — drift between code and the
  project's frozen contracts: metric names ↔ README glossary, journal
  events ↔ ``obs.journal.JOURNAL_EVENTS``, fault sites ↔
  ``faults.inject.KNOWN_SITES``, config keys ↔ the config dataclasses.

Run ``python -m tools.graftlint`` from the repo root. Exit 0 means clean
(every finding either fixed or baselined with a reason), exit 2 means
unbaselined findings — CI gates on it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from tools.graftlint.astutil import iter_py_files, parse_file
from tools.graftlint.check_contracts import (
    ContractScan,
    Registries,
    check_contracts_py,
    full_repo_contracts,
)
from tools.graftlint.check_locks import check_locks, order_graph_findings
from tools.graftlint.check_tracing import check_tracing
from tools.graftlint.findings import (
    EXIT_CLEAN,
    EXIT_FINDINGS,
    Baseline,
    Finding,
    render_report,
    split_by_baseline,
)

__all__ = [
    "Baseline",
    "Finding",
    "LintResult",
    "run_lint",
    "render_report",
    "split_by_baseline",
    "EXIT_CLEAN",
    "EXIT_FINDINGS",
    "DEFAULT_PATHS",
]

# What a bare ``python -m tools.graftlint`` scans, relative to the root.
DEFAULT_PATHS = ("jumbo_mae_tpu_tpu", "tools", "bench.py")


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)
    files_scanned: int = 0


def run_lint(
    root: Path,
    paths: list[Path] | None = None,
    *,
    full: bool | None = None,
) -> LintResult:
    """Lint ``paths`` (default: the project tree under ``root``).

    ``full`` additionally runs the repo-wide two-sided contract checks
    (stale README glossary rows, README journal table, CI workflow and
    README text carriers). It defaults to on exactly when no explicit
    paths were given — explicit paths mean "lint these files", and
    repo-wide documentation drift is not those files' fault.
    """
    if full is None:
        full = paths is None
    if paths is None:
        paths = [root / p for p in DEFAULT_PATHS]
    result = LintResult()
    regs = Registries.load(root)
    scan = ContractScan()
    order_edges: list[tuple[str, str, str, int]] = []
    for path in iter_py_files([p for p in paths if p.exists()]):
        sf = parse_file(path, root)
        if sf is None:
            continue
        result.files_scanned += 1
        result.findings.extend(check_tracing(sf))
        facts = check_locks(sf)
        result.findings.extend(facts.findings)
        order_edges.extend(facts.order_edges)
        check_contracts_py(sf, regs, scan)
    result.findings.extend(scan.findings)
    result.findings.extend(order_graph_findings(order_edges))
    if full:
        result.findings.extend(full_repo_contracts(root, regs, scan))
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
