"""Finding model, baseline mechanics, and the doctor-style report.

A finding's **baseline key** must survive unrelated edits: line numbers
drift every PR, so the key is built from what the finding *is* — rule id,
repo-relative path, the enclosing scope (``Class.method`` or
``<module>``), and a short hash of the stripped source line. Accepting a
finding means writing that key plus a human reason into
``.graftlint-baseline.json``; the entry silently expires when the
offending line changes or disappears (stale entries are reported so the
baseline can't accumulate dead weight).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path

EXIT_CLEAN = 0
EXIT_FINDINGS = 2


@dataclass(frozen=True)
class Finding:
    rule: str            # e.g. "LCK001"
    path: str            # repo-relative, posix separators
    line: int            # 1-based
    scope: str           # "Class.method", "function", or "<module>"
    message: str         # one-sentence defect statement
    snippet: str = ""    # stripped source line (keys the baseline hash)
    checker: str = field(default="", compare=False)  # family display name

    @property
    def key(self) -> str:
        digest = hashlib.sha1(self.snippet.encode()).hexdigest()[:12]
        return f"{self.rule}|{self.path}|{self.scope}|{digest}"

    def location(self) -> str:
        return f"{self.path}:{self.line}"


class Baseline:
    """Accepted findings: ``{key: {"reason": str}}`` under ``findings``."""

    def __init__(self, entries: dict[str, dict] | None = None):
        self.entries = entries or {}

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls()
        doc = json.loads(path.read_text())
        entries = doc.get("findings", {})
        missing = [k for k, v in entries.items() if not v.get("reason")]
        if missing:
            raise ValueError(
                f"{path}: baseline entries without a reason: {missing} — "
                "every accepted finding must say why"
            )
        return cls(entries)

    def accepts(self, finding: Finding) -> bool:
        return finding.key in self.entries

    def stale_keys(self, findings: list[Finding]) -> list[str]:
        live = {f.key for f in findings}
        return sorted(k for k in self.entries if k not in live)

    @staticmethod
    def render(findings: list[Finding], reason: str) -> str:
        doc = {
            "findings": {
                f.key: {
                    "reason": reason,
                    "location": f.location(),
                    "message": f.message,
                }
                for f in sorted(findings, key=lambda f: f.key)
            }
        }
        return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def split_by_baseline(
    findings: list[Finding], baseline: Baseline
) -> tuple[list[Finding], list[Finding]]:
    """(unbaselined, accepted) — unbaselined findings gate the exit code."""
    fresh = [f for f in findings if not baseline.accepts(f)]
    accepted = [f for f in findings if baseline.accepts(f)]
    return fresh, accepted


def render_report(
    fresh: list[Finding],
    accepted: list[Finding],
    stale: list[str],
    *,
    files_scanned: int,
) -> str:
    """Doctor-style markdown: verdict first, then findings grouped by rule."""
    lines = ["# graftlint report", ""]
    verdict = (
        "CLEAN" if not fresh else f"{len(fresh)} unbaselined finding(s)"
    )
    lines += [
        f"**Verdict: {verdict}** — {files_scanned} file(s) scanned, "
        f"{len(accepted)} baselined, {len(stale)} stale baseline entr"
        f"{'y' if len(stale) == 1 else 'ies'}.",
        "",
    ]
    if fresh:
        lines += ["## Findings", ""]
        by_rule: dict[str, list[Finding]] = {}
        for f in fresh:
            by_rule.setdefault(f.rule, []).append(f)
        for rule in sorted(by_rule):
            fs = sorted(by_rule[rule], key=lambda f: (f.path, f.line))
            lines.append(f"### {rule} — {fs[0].checker or 'graftlint'}")
            lines.append("")
            for f in fs:
                lines.append(f"- `{f.location()}` ({f.scope}): {f.message}")
                if f.snippet:
                    lines.append(f"  - `{f.snippet}`")
                lines.append(f"  - baseline key: `{f.key}`")
            lines.append("")
    if accepted:
        lines += ["## Baselined (accepted)", ""]
        for f in sorted(accepted, key=lambda f: (f.path, f.line)):
            lines.append(f"- `{f.location()}` {f.rule}: {f.message}")
        lines.append("")
    if stale:
        lines += [
            "## Stale baseline entries",
            "",
            "These keys no longer match any finding — the offending line "
            "changed or was fixed. Delete them from the baseline.",
            "",
        ]
        lines += [f"- `{k}`" for k in stale]
        lines.append("")
    return "\n".join(lines)
