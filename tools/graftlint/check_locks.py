"""LCK — lock discipline across the thread-heavy serving/observability code.

| Rule   | Claim |
|--------|-------|
| LCK001 | A blocking operation (XLA compile, ``Future.result``,
|        | ``block_until_ready``, file I/O, ``sleep``, subprocess, timed
|        | queue/event waits) runs while a known lock is held — every
|        | waiter on that lock now waits on the slow thing too, and if the
|        | blocked path ever re-enters the lock, it deadlocks. |
| LCK002 | While holding lock L, a method of the same class that itself
|        | acquires L is called — with non-reentrant ``threading.Lock``
|        | this is the exact round-10 warmup deadlock (compile under the
|        | engine lock calling back into ``_task()``, which takes it). |
| LCK003 | The global lock-acquisition order graph has a cycle: somewhere
|        | A is taken before B, somewhere else B before A — two threads on
|        | those paths can deadlock. |
| LCK004 | A ``yield`` inside a ``with <lock>:`` block — the lock stays
|        | held across arbitrary caller code for an unbounded time. |

Lock identity is syntactic and therefore conservative: ``self.X`` where
``X = threading.Lock()`` (or ``lockwatch.lock(...)``) in the same class,
module/local variables assigned the same way, and lock-returning helper
methods whose name contains ``lock`` (the engine's per-key
``_compile_lock(key)``). Only *known* locks produce findings.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from tools.graftlint.astutil import (
    SourceFile,
    call_name,
    dotted_name,
    enclosing_scope,
)
from tools.graftlint.findings import Finding

CHECKER = "lock discipline"

_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "Lock",
    "RLock",
    "lockwatch.lock",
}
_FILE_IO_ATTRS = {
    "write", "read", "flush", "fsync",
    "read_text", "write_text", "read_bytes", "write_bytes",
}
_SUBPROCESS_CALLS = {
    "subprocess.run", "subprocess.check_call", "subprocess.check_output",
    "subprocess.call",
}


def _is_lock_ctor(node: ast.expr) -> bool:
    return isinstance(node, ast.Call) and call_name(node) in _LOCK_CTORS


def blocking_reason(node: ast.Call) -> str | None:
    """Why this call blocks, or None. Names are chosen so that every hit
    is blocking by construction (``re.compile`` is carved out; ``.lower``
    and ``.join`` are skipped entirely for str false positives)."""
    name = call_name(node)
    if name in ("open", "sleep", "time.sleep"):
        return f"`{name}()`"
    if name in ("os.fsync", "os.fdatasync"):
        return f"`{name}()` (disk flush)"
    if name in _SUBPROCESS_CALLS:
        return f"`{name}()` (subprocess)"
    if name == "jax.block_until_ready":
        return "`jax.block_until_ready` (device sync)"
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr == "result":
            return "`.result()` (future wait)"
        if attr == "block_until_ready":
            return "`.block_until_ready()` (device sync)"
        if attr == "compile" and name != "re.compile":
            return "`.compile()` (XLA compile)"
        if attr in _FILE_IO_ATTRS:
            return f"`.{attr}()` (file I/O)"
        if attr == "wait":
            return "`.wait()` (blocking wait)"
        if attr == "get" and any(kw.arg == "timeout" for kw in node.keywords):
            return "`.get(timeout=...)` (blocking queue get)"
    return None


@dataclass
class _ModuleLocks:
    """Known locks in one file, resolvable from a ``with`` item."""

    rel: str
    class_attr: dict[tuple[str, str], str] = field(default_factory=dict)
    module_var: dict[str, str] = field(default_factory=dict)
    local_var: dict[tuple[str, str], str] = field(default_factory=dict)

    def resolve(self, expr: ast.expr, cls: str | None, scope: str) -> str | None:
        # with self.X:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
        ):
            return self.class_attr.get((cls, expr.attr))
        # with X:  (module or local lock)
        if isinstance(expr, ast.Name):
            return self.local_var.get((scope, expr.id)) or self.module_var.get(
                expr.id
            )
        # with self._compile_lock(key):  — a lock-returning helper
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute):
            base = expr.func.value
            if (
                isinstance(base, ast.Name)
                and base.id == "self"
                and "lock" in expr.func.attr.lower()
                and cls is not None
            ):
                return f"{self.rel}:{cls}.{expr.func.attr}()"
        return None


def _collect_locks(sf: SourceFile) -> _ModuleLocks:
    locks = _ModuleLocks(rel=sf.rel)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign) or not _is_lock_ctor(node.value):
            continue
        scope = enclosing_scope(node)
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                cls = scope.split(".")[0] if scope != "<module>" else ""
                if cls:
                    locks.class_attr[(cls, tgt.attr)] = (
                        f"{sf.rel}:{cls}.{tgt.attr}"
                    )
            elif isinstance(tgt, ast.Name):
                if scope == "<module>":
                    locks.module_var[tgt.id] = f"{sf.rel}:{tgt.id}"
                else:
                    locks.local_var[(scope, tgt.id)] = (
                        f"{sf.rel}:{scope}.{tgt.id}"
                    )
    return locks


@dataclass
class LockFacts:
    """Per-file facts the cross-file order graph is assembled from."""

    findings: list[Finding] = field(default_factory=list)
    # (holder_lock_id, acquired_lock_id, rel, line) — A held when B taken
    order_edges: list[tuple[str, str, str, int]] = field(default_factory=list)


class _FunctionScanner(ast.NodeVisitor):
    """Walk one function body tracking the stack of held (known) locks."""

    def __init__(self, sf, locks, cls, scope, facts, acquires_of):
        self.sf = sf
        self.locks = locks
        self.cls = cls
        self.scope = scope
        self.facts = facts
        self.acquires_of = acquires_of  # (cls, method) -> set[lock_id]
        self.held: list[str] = []

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.facts.findings.append(
            Finding(
                rule=rule,
                path=self.sf.rel,
                line=node.lineno,
                scope=self.scope,
                message=message,
                snippet=self.sf.snippet(node.lineno),
                checker=CHECKER,
            )
        )

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            lock_id = self.locks.resolve(
                item.context_expr, self.cls, self.scope
            )
            if lock_id is None:
                continue
            if lock_id in self.held:
                self._emit(
                    "LCK002",
                    node,
                    f"re-acquire of non-reentrant lock `{lock_id}` already "
                    "held by this frame — immediate self-deadlock",
                )
            for holder in self.held:
                self.facts.order_edges.append(
                    (holder, lock_id, self.sf.rel, node.lineno)
                )
            acquired.append(lock_id)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            reason = blocking_reason(node)
            if reason is not None:
                self._emit(
                    "LCK001",
                    node,
                    f"blocking {reason} while holding `{self.held[-1]}` — "
                    "every waiter on the lock now waits on this too",
                )
            # same-class method call while a lock of this class is held:
            # LCK002 if the callee (directly) takes a held lock — the
            # round-10 warmup-deadlock shape — plus order edges for any
            # other lock it takes.
            if (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and self.cls is not None
            ):
                callee = node.func.attr
                for lock_id in sorted(
                    self.acquires_of.get((self.cls, callee), ())
                ):
                    if lock_id in self.held:
                        self._emit(
                            "LCK002",
                            node,
                            f"`self.{callee}()` acquires `{lock_id}` which "
                            "this frame already holds — non-reentrant "
                            "deadlock (the round-10 warmup-hang class)",
                        )
                    else:
                        for holder in self.held:
                            self.facts.order_edges.append(
                                (holder, lock_id, self.sf.rel, node.lineno)
                            )
        self.generic_visit(node)

    def _yield_check(self, node: ast.AST) -> None:
        if self.held:
            self._emit(
                "LCK004",
                node,
                f"`yield` while holding `{self.held[-1]}` — the lock stays "
                "held across arbitrary caller code until the generator "
                "resumes",
            )

    def visit_Yield(self, node: ast.Yield) -> None:
        self._yield_check(node)
        self.generic_visit(node)

    def visit_YieldFrom(self, node: ast.YieldFrom) -> None:
        self._yield_check(node)
        self.generic_visit(node)

    # a nested def is a new frame: it does not inherit held locks at its
    # *definition* site (it may run anywhere), so scan it independently
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved, self.held = self.held, []
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef


def _direct_acquires(func: ast.FunctionDef, locks, cls, scope) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.With):
            for item in node.items:
                lock_id = locks.resolve(item.context_expr, cls, scope)
                if lock_id is not None:
                    out.add(lock_id)
    return out


def check_locks(sf: SourceFile) -> LockFacts:
    facts = LockFacts()
    locks = _collect_locks(sf)

    # pass 1: which locks does each (class, method) acquire directly?
    acquires_of: dict[tuple[str, str], set[str]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    scope = f"{node.name}.{item.name}"
                    acquires_of[(node.name, item.name)] = _direct_acquires(
                        item, locks, node.name, scope
                    )

    # pass 2: scan every function with the held-lock stack
    def scan(func: ast.FunctionDef, cls: str | None, scope: str) -> None:
        scanner = _FunctionScanner(sf, locks, cls, scope, facts, acquires_of)
        for stmt in func.body:
            scanner.visit(stmt)

    for node in sf.tree.body:
        if isinstance(node, ast.FunctionDef):
            scan(node, None, node.name)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    scan(item, node.name, f"{node.name}.{item.name}")
    return facts


def order_graph_findings(
    all_edges: list[tuple[str, str, str, int]]
) -> list[Finding]:
    """LCK003: cycles in the global (cross-file) acquisition order graph."""
    adj: dict[str, dict[str, tuple[str, int]]] = {}
    for a, b, rel, line in all_edges:
        if a != b:
            adj.setdefault(a, {}).setdefault(b, (rel, line))
    findings: list[Finding] = []
    seen_cycles: set[frozenset[str]] = set()

    def dfs(start: str) -> None:
        stack: list[str] = [start]
        on_path = {start}

        def walk(cur: str) -> None:
            for nxt in adj.get(cur, {}):
                if nxt == start and len(stack) > 1:
                    cyc = frozenset(stack)
                    if cyc not in seen_cycles:
                        seen_cycles.add(cyc)
                        rel, line = adj[stack[-1]][start]
                        chain = " → ".join(stack + [start])
                        findings.append(
                            Finding(
                                rule="LCK003",
                                path=rel,
                                line=line,
                                scope="<order-graph>",
                                message=(
                                    f"lock-order cycle: {chain} — two "
                                    "threads taking these locks in the "
                                    "two observed orders can deadlock"
                                ),
                                snippet=chain,
                                checker=CHECKER,
                            )
                        )
                elif nxt not in on_path:
                    stack.append(nxt)
                    on_path.add(nxt)
                    walk(nxt)
                    on_path.discard(nxt)
                    stack.pop()

        walk(start)

    for node in sorted(adj):
        dfs(node)
    return findings
