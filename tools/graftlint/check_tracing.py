"""TRC — JAX tracing hazards inside jit-compiled functions.

| Rule   | Claim |
|--------|-------|
| TRC001 | Python ``if``/``while``/``assert`` on a traced value (a non-static
|        | parameter of a jitted function) — tracing turns these into
|        | ``ConcretizationTypeError`` or, worse, a silently frozen branch. |
| TRC002 | Host sync inside jitted code: ``float()``/``int()``/``bool()`` on
|        | a traced value, ``.item()``, ``np.asarray``/``np.array`` of a
|        | traced value, ``jax.device_get`` — each blocks dispatch on device
|        | completion and bakes one traced value into the program. |
| TRC003 | Wall-clock or host RNG inside jitted code (``time.time`` etc.,
|        | ``random.*``, ``np.random.*``) — traced once, constant forever. |
| TRC004 | A jitted function with hashable config parameters (str/bool
|        | defaults) not pinned by ``static_argnames`` — passing a different
|        | value silently retraces (or fails) instead of recompiling once
|        | per config. |

Scope is deliberately *jitted bodies only* (decorated with ``jax.jit`` /
``partial(jax.jit, ...)`` or passed module-locally to ``jax.jit(...)``),
including defs nested inside them: that is where the claims above are
true by construction, so every hit is a real hazard, not a style nit.
"""

from __future__ import annotations

import ast

from tools.graftlint.astutil import (
    JitInfo,
    SourceFile,
    call_name,
    dotted_name,
    find_jitted_functions,
    param_names,
    parents,
)
from tools.graftlint.findings import Finding

CHECKER = "JAX tracing hazards"

_TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic", "time.time_ns"}
_CAST_CALLS = {"float", "int", "bool"}
_HOST_FETCH_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_DEVICE_GET = {"jax.device_get", "jax.block_until_ready"}


def _expr_roots(node: ast.AST) -> set[str]:
    """Base ``Name`` ids that Name/Attribute/Subscript chains hang off."""
    roots: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            roots.add(n.id)
    return roots


def _is_none_check(test: ast.expr) -> bool:
    """``x is None`` / ``x is not None`` — static under tracing."""
    if isinstance(test, ast.Compare):
        return all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ) and any(
            isinstance(c, ast.Constant) and c.value is None
            for c in test.comparators
        )
    return False


def _uses_isinstance(test: ast.expr) -> bool:
    return any(
        isinstance(n, ast.Call) and call_name(n) == "isinstance"
        for n in ast.walk(test)
    )


def _traced_names(info: JitInfo) -> set[str]:
    """Parameters carrying traced arrays: the jitted function's own plus
    any def nested inside it (closures stay traced), minus static ones."""
    traced = set(param_names(info.func))
    for node in ast.walk(info.func):
        if isinstance(node, ast.FunctionDef) and node is not info.func:
            traced |= set(param_names(node))
    return traced - info.static_names


def check_tracing(sf: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    for info in find_jitted_functions(sf):
        traced = _traced_names(info)
        top = info.func

        def emit(rule: str, node: ast.AST, message: str) -> None:
            findings.append(
                Finding(
                    rule=rule,
                    path=sf.rel,
                    line=node.lineno,
                    scope=f"{top.name}",
                    message=message,
                    snippet=sf.snippet(node.lineno),
                    checker=CHECKER,
                )
            )

        for node in ast.walk(top):
            # -- TRC001: control flow on traced values ------------------
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
                if (
                    not _is_none_check(test)
                    and not _uses_isinstance(test)
                    and _expr_roots(test) & traced
                ):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    emit(
                        "TRC001",
                        node,
                        f"Python `{kind}` on traced value(s) "
                        f"{sorted(_expr_roots(test) & traced)} inside "
                        f"jitted `{top.name}` — use lax.cond/lax.while_loop "
                        "or pin the argument with static_argnames",
                    )
            elif isinstance(node, ast.Assert):
                if _expr_roots(node.test) & traced:
                    emit(
                        "TRC001",
                        node,
                        f"`assert` on traced value(s) "
                        f"{sorted(_expr_roots(node.test) & traced)} inside "
                        f"jitted `{top.name}` — asserts concretize; use "
                        "checkify or validate before the jit boundary",
                    )
            # -- TRC002 / TRC003: host syncs and host clocks ------------
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name in _CAST_CALLS and node.args:
                    if _expr_roots(node.args[0]) & traced:
                        emit(
                            "TRC002",
                            node,
                            f"`{name}()` of a traced value inside jitted "
                            f"`{top.name}` forces a host sync (blocks on "
                            "device, concretizes the tracer)",
                        )
                elif name in _HOST_FETCH_CALLS and node.args:
                    if _expr_roots(node.args[0]) & traced:
                        emit(
                            "TRC002",
                            node,
                            f"`{name}` of a traced value inside jitted "
                            f"`{top.name}` copies device→host mid-program; "
                            "use jnp inside jit, fetch after dispatch",
                        )
                elif name in _DEVICE_GET:
                    emit(
                        "TRC002",
                        node,
                        f"`{name}` inside jitted `{top.name}` is a host "
                        "sync; move the fetch outside the jit boundary",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and not node.args
                ):
                    emit(
                        "TRC002",
                        node,
                        f"`.item()` inside jitted `{top.name}` blocks on "
                        "the device and concretizes — return the array and "
                        "fetch at the call site",
                    )
                elif name in _TIME_CALLS:
                    emit(
                        "TRC003",
                        node,
                        f"`{name}()` inside jitted `{top.name}` is traced "
                        "ONCE and frozen into the executable — time on the "
                        "host, pass values in as arguments",
                    )
                elif name and name.split(".")[0] == "random":
                    emit(
                        "TRC003",
                        node,
                        f"host `{name}` inside jitted `{top.name}` freezes "
                        "one draw into the program — use jax.random with a "
                        "traced key",
                    )
                elif name and name.split(".")[:2] in (
                    ["np", "random"],
                    ["numpy", "random"],
                ):
                    emit(
                        "TRC003",
                        node,
                        f"`{name}` inside jitted `{top.name}` freezes one "
                        "draw into the program — use jax.random with a "
                        "traced key",
                    )
        # -- TRC004: config-shaped params without static_argnames -------
        has_static_nums = info.jit_call is not None and any(
            kw.arg == "static_argnums" for kw in info.jit_call.keywords
        )
        if not has_static_nums:
            args = top.args
            pos = args.posonlyargs + args.args
            defaults = [None] * (len(pos) - len(args.defaults)) + list(
                args.defaults
            )
            for arg, default in list(zip(pos, defaults)) + list(
                zip(args.kwonlyargs, args.kw_defaults)
            ):
                if (
                    isinstance(default, ast.Constant)
                    and isinstance(default.value, (str, bool))
                    and arg.arg not in info.static_names
                ):
                    anchor = info.jit_call if info.jit_call is not None else top
                    findings.append(
                        Finding(
                            rule="TRC004",
                            path=sf.rel,
                            line=anchor.lineno,
                            scope=top.name,
                            message=(
                                f"jitted `{top.name}` takes config-shaped "
                                f"parameter `{arg.arg}` (default "
                                f"{default.value!r}) without "
                                "static_argnames — a non-array value "
                                "traces as a constant or fails; pin it "
                                f"static_argnames=('{arg.arg}',)"
                            ),
                            snippet=sf.snippet(anchor.lineno),
                            checker=CHECKER,
                        )
                    )
    return findings
