"""CLI: ``python -m tools.graftlint [paths...]``.

Exit codes: 0 clean, 2 unbaselined findings. The markdown report goes to
stdout (and to ``--report PATH`` for CI artifact upload).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.graftlint import DEFAULT_PATHS, run_lint
from tools.graftlint.findings import (
    EXIT_CLEAN,
    EXIT_FINDINGS,
    Baseline,
    render_report,
    split_by_baseline,
)

BASELINE_NAME = ".graftlint-baseline.json"


def _find_root(start: Path) -> Path:
    cur = start.resolve()
    while True:
        if (cur / "jumbo_mae_tpu_tpu").is_dir() or (cur / ".git").exists():
            return cur
        if cur.parent == cur:
            return start.resolve()
        cur = cur.parent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description=(
            "Project-native static analysis: JAX tracing hazards (TRC), "
            "lock discipline (LCK), contract drift (CON)."
        ),
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help=f"files/dirs to lint (default: {' '.join(DEFAULT_PATHS)} "
        "under the repo root, plus repo-wide contract checks)",
    )
    ap.add_argument("--root", help="repo root (default: walk up from cwd)")
    ap.add_argument(
        "--baseline",
        help=f"baseline file (default: <root>/{BASELINE_NAME})",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: report every finding",
    )
    ap.add_argument("--report", help="also write the markdown report here")
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept all current unbaselined findings into the baseline "
        "(requires --reason; refine per-entry reasons by editing the file)",
    )
    ap.add_argument(
        "--reason",
        help="reason string recorded for entries added by --write-baseline",
    )
    args = ap.parse_args(argv)

    root = Path(args.root).resolve() if args.root else _find_root(Path.cwd())
    paths = [Path(p).resolve() for p in args.paths] or None
    baseline_path = (
        Path(args.baseline) if args.baseline else root / BASELINE_NAME
    )
    try:
        baseline = (
            Baseline() if args.no_baseline else Baseline.load(baseline_path)
        )
    except ValueError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return EXIT_FINDINGS

    result = run_lint(root, paths)
    fresh, accepted = split_by_baseline(result.findings, baseline)
    stale = baseline.stale_keys(result.findings)

    if args.write_baseline:
        if not args.reason:
            print(
                "graftlint: --write-baseline requires --reason", file=sys.stderr
            )
            return EXIT_FINDINGS
        merged = dict(baseline.entries)
        import json

        new = json.loads(Baseline.render(fresh, args.reason))["findings"]
        merged.update(new)
        doc = Baseline.render([], "")  # shape only; replace entries
        payload = json.loads(doc)
        payload["findings"] = dict(sorted(merged.items()))
        baseline_path.write_text(
            json.dumps(payload, indent=2, sort_keys=True) + "\n"
        )
        print(
            f"graftlint: wrote {len(new)} entr"
            f"{'y' if len(new) == 1 else 'ies'} to {baseline_path}"
        )
        return EXIT_CLEAN

    report = render_report(
        fresh, accepted, stale, files_scanned=result.files_scanned
    )
    if args.report:
        Path(args.report).write_text(report)
    try:
        print(report)
    except BrokenPipeError:  # `| head` closed stdout; the verdict stands
        sys.stderr.close()
    return EXIT_CLEAN if not fresh else EXIT_FINDINGS


if __name__ == "__main__":
    sys.exit(main())
