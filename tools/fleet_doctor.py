#!/usr/bin/env python3
"""Offline fleet diagnosis: beacons + merged journal → per-host health table.

run_doctor explains ONE run's lifecycle; this tool explains the FLEET — which
host dragged the pod, which host died, and why — from the crash-safe
artifacts alone (the ``<run_dir>/fleet/`` beacon dir plus the per-host
journal segments). No live process, no /metrics endpoint:

    python tools/fleet_doctor.py runs/my_run
    python tools/fleet_doctor.py runs/my_run --out fleet.md
    python tools/fleet_doctor.py runs/my_run --lag-steps 2 --ratio 1.5

Because the run is usually *over* when this tool runs, heartbeat ages are
measured against the fleet-latest heartbeat, not the wall clock — a host
killed mid-run stays "lost" in the report forever, while a clean shutdown
(all beacons written within seconds of each other) stays healthy.

The verdict names each unhealthy host and its dominant symptom
(data-wait-dominant / compute-dominant / step-lag), cross-checked against
the journaled ``fleet_straggler`` / ``fleet_host_lost`` transitions the
live aggregator recorded.

Exit codes: 0 = diagnosis written (healthy or not); 2 = no beacons found.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jumbo_mae_tpu_tpu.obs.doctor_common import (  # noqa: E402
    fmt_num as _fmt_num,
    write_report,
)
from jumbo_mae_tpu_tpu.obs.fleet import read_beacons  # noqa: E402
from jumbo_mae_tpu_tpu.obs.journal import read_merged_journal  # noqa: E402

# journal symptom slug → operator-readable name (the CI smoke greps these)
SYMPTOMS = {
    "data_wait": "data-wait-dominant",
    "step_time": "compute-dominant",
    "step_lag": "step-lag",
}


def _fleet_dir(path: Path) -> Path | None:
    """Accept a run dir (``<run>/fleet``) or the beacon dir itself."""
    for cand in (path / "fleet", path):
        if cand.is_dir() and read_beacons(cand):
            return cand
    return None


def _mib(v) -> str:
    return f"{float(v) / (1024 * 1024):.0f} MiB"


def analyze(
    beacons: dict[int, dict],
    *,
    lag_steps: int = 2,
    ratio: float = 1.5,
    dead_after_s: float = 60.0,
    mem_ratio: float = 1.5,
    mem_floor_bytes: int = 256 * 1024 * 1024,
) -> dict:
    """Post-mortem status machine over a beacon snapshot.

    Mirrors FleetAggregator's verdicts but clocks heartbeat age off the
    fleet-latest beacon (``now`` is unusable after the run ends) and skips
    the transition bookkeeping — a report wants current state, not edges.
    """
    latest = max(
        (float(b.get("heartbeat", 0.0)) for b in beacons.values()), default=0.0
    )
    alive = {
        h: b
        for h, b in beacons.items()
        if latest - float(b.get("heartbeat", 0.0)) <= dead_after_s
    }
    max_step = max(
        (int(b.get("step", 0)) for b in (alive or beacons).values()), default=0
    )
    # lower-middle medians, matching FleetAggregator (an upper median would
    # blind the ratio check in an even fleet — see obs/fleet.py)
    emas = sorted(
        float(b["step_time_ema_s"])
        for b in alive.values()
        if b.get("step_time_ema_s")
    )
    median_ema = emas[(len(emas) - 1) // 2] if emas else 0.0
    waits = sorted(
        float(b["data_wait_fraction"])
        for b in alive.values()
        if b.get("data_wait_fraction") is not None
    )
    median_wait = waits[(len(waits) - 1) // 2] if waits else 0.0
    # optional memwatch beacon fields (older beacons simply lack them)
    rsses = sorted(
        float(b["rss_bytes"])
        for b in alive.values()
        if b.get("rss_bytes") is not None
    )
    median_rss = rsses[(len(rsses) - 1) // 2] if rsses else 0.0

    hosts: dict[int, dict] = {}
    for h, b in sorted(beacons.items()):
        age = max(0.0, latest - float(b.get("heartbeat", 0.0)))
        step = int(b.get("step", 0))
        lag = max(0, max_step - step)
        ema = b.get("step_time_ema_s")
        wait = b.get("data_wait_fraction")
        lost = age > dead_after_s
        slow_ema = (
            not lost
            and len(alive) >= 2
            and ema is not None
            and median_ema > 0
            and float(ema) >= ratio * median_ema
        )
        slow_wait = (
            not lost
            and len(alive) >= 2
            and wait is not None
            and float(wait) >= 0.3
            and float(wait) >= 2.0 * max(median_wait, 0.05)
        )
        straggler = (
            not lost
            and len(alive) >= 2
            and (lag >= lag_steps or slow_ema or slow_wait)
        )
        if wait is not None and float(wait) >= 0.3 and float(wait) >= 2.0 * max(
            median_wait, 0.05
        ):
            symptom = "data_wait"
        elif slow_ema:
            symptom = "step_time"
        else:
            symptom = "step_lag"
        rss = b.get("rss_bytes")
        mem_outlier = (
            not lost
            and len(alive) >= 2
            and rss is not None
            and median_rss > 0
            and float(rss) >= mem_ratio * median_rss
            and float(rss) - median_rss >= mem_floor_bytes
        )
        hosts[h] = {
            "status": "lost" if lost else "straggler" if straggler else "ok",
            "step": step,
            "lag": lag,
            "heartbeat_age_s": round(age, 3),
            "step_time_ema_s": ema,
            "data_wait_fraction": wait,
            "shard_retries": int(b.get("shard_retries", 0) or 0),
            "shard_quarantines": int(b.get("shard_quarantines", 0) or 0),
            "sentinel_bad_steps": int(b.get("sentinel_bad_steps", 0) or 0),
            "rss_bytes": None if rss is None else int(rss),
            "device_peak_bytes": (
                None
                if b.get("device_peak_bytes") is None
                else int(b["device_peak_bytes"])
            ),
            "mem_outlier": bool(mem_outlier),
            "symptom": symptom,
            "hostname": b.get("hostname"),
            "pid": b.get("pid"),
        }
    return {
        "hosts": hosts,
        "max_step": max_step,
        "median_step_s": median_ema,
        "median_wait": median_wait,
        "median_rss_bytes": median_rss,
    }


def _dominant_symptom(host_id: int, hosts: dict, stragglers: list[dict]) -> str:
    """Pick the most *informative* symptom across the journaled straggler
    events plus the final-beacon snapshot. Precedence (not frequency):
    data_wait > step_time > step_lag — the first straggler transition often
    fires before the slow host's first log boundary, so it journals the
    generic ``step_lag`` with no wait stats yet; a later event (or the final
    beacon) that attributes the lag to data starvation supersedes it."""
    candidates = [
        e.get("symptom") for e in stragglers if e.get("host_id") == host_id
    ]
    if host_id in hosts:
        candidates.append(hosts[host_id]["symptom"])
    for slug in ("data_wait", "step_time", "step_lag"):
        if slug in candidates:
            return SYMPTOMS[slug]
    return str(candidates[0]) if candidates else "step-lag"


def diagnose(beacons: dict[int, dict], events: list[dict], args) -> str:
    res = analyze(
        beacons,
        lag_steps=args.lag_steps,
        ratio=args.ratio,
        dead_after_s=args.dead_after_s,
        mem_ratio=args.mem_ratio,
        mem_floor_bytes=int(args.mem_floor_mb * 1024 * 1024),
    )
    hosts = res["hosts"]
    stragglers = [e for e in events if e.get("type") == "fleet_straggler"]
    lost_evs = [e for e in events if e.get("type") == "fleet_host_lost"]
    rejoins = [e for e in events if e.get("type") == "fleet_host_rejoined"]
    # elastic supervision trail (train/elastic.py + the hang watchdog)
    restart_evs = [
        e
        for e in events
        if e.get("type")
        in (
            "elastic_restart",
            "elastic_rejoin",
            "elastic_resize",
            "elastic_exhausted",
            "hang_detected",
            "host_lost",
            "ckpt_fallback",
        )
    ]

    lines = ["# Fleet doctor report", ""]

    # -------------------------------------------------------------- verdict
    bad_final = {h: s for h, s in hosts.items() if s["status"] != "ok"}
    # a host flagged straggler by the live aggregator but healthy in its
    # final beacon (incident resolved / run ended in lockstep) still gets
    # named — the operator asked "who dragged the run", not "who is slow now"
    journaled_stragglers = sorted(
        {
            e["host_id"]
            for e in stragglers
            if e.get("host_id") is not None and e["host_id"] not in bad_final
        }
    )
    lines += ["## Verdict", ""]
    if not bad_final and not journaled_stragglers and not lost_evs:
        lines.append(
            f"- **fleet healthy**: {len(hosts)} host(s), all ok at "
            f"step {res['max_step']}"
        )
    for h, s in sorted(bad_final.items()):
        sym = _dominant_symptom(h, hosts, stragglers)
        if s["status"] == "lost":
            was = (
                f"; was a {sym} straggler before it died"
                if any(e.get("host_id") == h for e in stragglers)
                else ""
            )
            lines.append(
                f"- lost: **host {h}** — last beacon at step {s['step']}, "
                f"heartbeat {_fmt_num(s['heartbeat_age_s'])}s behind the "
                f"fleet-latest{was}"
            )
        else:
            lines.append(
                f"- straggler: **host {h}** — {sym} "
                f"(lag {s['lag']}, data-wait "
                f"{_fmt_num(s['data_wait_fraction'] or 0)}, step-time EMA "
                f"{_fmt_num(s['step_time_ema_s'] or 0)}s vs fleet median "
                f"{_fmt_num(res['median_step_s'])}s)"
            )
    for h in journaled_stragglers:
        sym = _dominant_symptom(h, hosts, stragglers)
        n = sum(1 for e in stragglers if e.get("host_id") == h)
        lines.append(
            f"- straggler: **host {h}** — {sym} "
            f"({n} journaled straggler event(s); healthy in its final beacon)"
        )
    # supervisor verdict lines: who failed and what the supervisor did
    for e in restart_evs:
        if e["type"] == "elastic_restart":
            failed = ", ".join(
                f"host {h}" for h in (e.get("failed_hosts") or [])
            )
            lines.append(
                f"- restarted: **{failed or 'fleet'}** "
                f"({e.get('reason')}) — supervisor relaunched generation "
                f"{e.get('generation')} at world {e.get('new_world')} "
                f"(was {e.get('old_world')}; restart "
                f"{e.get('restarts_used')})"
            )
        elif e["type"] == "elastic_exhausted":
            lines.append(f"- **supervisor gave up**: {e.get('verdict')}")
    # memory outliers are a flag, not a status: a leaking host still makes
    # lockstep progress, so it's named alongside — not instead of — the
    # straggler/lost verdicts
    for h, s in sorted(hosts.items()):
        if s.get("mem_outlier"):
            lines.append(
                f"- memory outlier: **host {h}** — rss {_mib(s['rss_bytes'])} "
                f"vs fleet median {_mib(res['median_rss_bytes'])} "
                f"(>= {args.mem_ratio:g}x + {args.mem_floor_mb:g} MiB floor)"
            )
    lines.append("")

    # ------------------------------------------------------ per-host table
    lines += [
        "## Per-host health",
        "",
        "| host | status | step | lag | step-time EMA | data-wait | "
        "retries | quarantines | bad steps | rss | heartbeat age |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for h, s in sorted(hosts.items()):
        rss_cell = (
            "—"
            if s["rss_bytes"] is None
            else _mib(s["rss_bytes"])
            + (" ⚠ outlier" if s.get("mem_outlier") else "")
        )
        lines.append(
            f"| {h} | {s['status']} | {s['step']} | {s['lag']} | "
            f"{_fmt_num(s['step_time_ema_s']) if s['step_time_ema_s'] is not None else '—'} | "
            f"{_fmt_num(s['data_wait_fraction']) if s['data_wait_fraction'] is not None else '—'} | "
            f"{s['shard_retries']} | {s['shard_quarantines']} | "
            f"{s['sentinel_bad_steps']} | {rss_cell} | "
            f"{_fmt_num(s['heartbeat_age_s'])}s |"
        )
    lines.append("")

    # ------------------------------------------------------- fleet timeline
    fleet_evs = sorted(
        stragglers + lost_evs + rejoins, key=lambda e: e.get("ts", 0.0)
    )
    lines += ["## Fleet timeline", ""]
    if not fleet_evs:
        lines.append("(no fleet transitions journaled)")
    else:
        t0 = min(e.get("ts", 0.0) for e in fleet_evs)
        for e in fleet_evs:
            dt = e.get("ts", t0) - t0
            etype = e["type"]
            if etype == "fleet_straggler":
                detail = (
                    f"host {e.get('host_id')} at step {e.get('step')}, "
                    f"lag {e.get('lag')}, "
                    f"{SYMPTOMS.get(e.get('symptom'), e.get('symptom'))}"
                )
            elif etype == "fleet_host_lost":
                detail = (
                    f"host {e.get('host_id')} (last step {e.get('last_step')}, "
                    f"heartbeat {_fmt_num(e.get('heartbeat_age_s', 0))}s stale)"
                )
            else:
                detail = (
                    f"host {e.get('host_id')} at step {e.get('step')} "
                    f"after {_fmt_num(e.get('lost_for_s', 0))}s"
                )
            lines.append(f"- +{dt:8.1f}s  `{etype}`  {detail}")
    lines.append("")

    # ---------------------------------------------------- restart timeline
    # the elastic supervision trail: hangs detected, hosts lost, restarts,
    # resizes, rejoins, fallback restores — the "what did the supervisor
    # do about it" companion to the symptom timeline above
    lines += ["## Restart timeline", ""]
    if not restart_evs:
        lines.append("(no elastic supervision events journaled)")
    else:
        t0 = min(e.get("ts", 0.0) for e in restart_evs)
        for e in sorted(restart_evs, key=lambda e: e.get("ts", 0.0)):
            dt = e.get("ts", t0) - t0
            etype = e["type"]
            if etype == "elastic_restart":
                detail = (
                    f"{e.get('reason')}: host(s) "
                    f"{e.get('failed_hosts')} exit {e.get('exit_codes')} -> "
                    f"generation {e.get('generation')} at world "
                    f"{e.get('new_world')} (was {e.get('old_world')}), "
                    f"restart {e.get('restarts_used')}"
                )
            elif etype == "elastic_rejoin":
                detail = (
                    f"world {e.get('old_world')} -> {e.get('new_world')} "
                    f"(generation {e.get('generation')})"
                )
            elif etype == "elastic_resize":
                detail = (
                    f"host {e.get('host')} resumed step {e.get('step')} at "
                    f"world {e.get('new_world')} (saved at "
                    f"{e.get('old_world')}): {e.get('shards_remaining')}/"
                    f"{e.get('shards_total')} epoch-{e.get('epoch')} shards "
                    "left"
                )
            elif etype == "elastic_exhausted":
                detail = str(e.get("verdict"))
            elif etype == "hang_detected":
                detail = (
                    f"host {e.get('host')} stalled "
                    f"{_fmt_num(e.get('stalled_s', 0))}s at step "
                    f"{e.get('step')} (deadline "
                    f"{_fmt_num(e.get('deadline_s', 0))}s)"
                )
            elif etype == "host_lost":
                detail = (
                    f"host {e.get('host')} saw peer(s) {e.get('hosts')} "
                    f"lost via {e.get('detected_by')} at step {e.get('step')}"
                )
            else:  # ckpt_fallback
                detail = (
                    f"host {e.get('host')} walked back step "
                    f"{e.get('from_step')} -> {e.get('to_step')} "
                    f"({e.get('error')})"
                )
            lines.append(f"- +{dt:8.1f}s  `{etype}`  {detail}")
    lines.append("")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument("path", help="run dir (or the fleet beacon dir itself)")
    parser.add_argument(
        "--lag-steps",
        type=int,
        default=2,
        help="straggler threshold: steps behind the fleet max (default 2)",
    )
    parser.add_argument(
        "--ratio",
        type=float,
        default=1.5,
        help="straggler threshold: step-time EMA / fleet median (default 1.5)",
    )
    parser.add_argument(
        "--dead-after-s",
        type=float,
        default=60.0,
        help="lost threshold: heartbeat seconds behind fleet-latest "
        "(default 60)",
    )
    parser.add_argument(
        "--mem-ratio",
        type=float,
        default=1.5,
        help="memory-outlier threshold: host rss / fleet median (default 1.5)",
    )
    parser.add_argument(
        "--mem-floor-mb",
        type=float,
        default=256.0,
        help="memory-outlier absolute floor: MiB above the fleet median "
        "before the ratio counts (default 256)",
    )
    parser.add_argument(
        "--out", default=None, help="write the markdown here (default stdout)"
    )
    args = parser.parse_args(argv)

    path = Path(args.path)
    fleet_dir = _fleet_dir(path)
    if fleet_dir is None:
        print(
            f"[fleet_doctor] no fleet beacons under {path} "
            "(expected <run_dir>/fleet/host-*.json — run.fleet off?)",
            file=sys.stderr,
        )
        return 2
    beacons = read_beacons(fleet_dir)

    # journal is optional context: a run killed before its first journal
    # flush still gets a beacon-only report
    run_dir = fleet_dir.parent if fleet_dir.name == "fleet" else fleet_dir
    try:
        events = read_merged_journal(run_dir)
    except FileNotFoundError:
        events = []

    report = diagnose(beacons, events, args)
    return write_report(report, args.out, tool="fleet_doctor")


if __name__ == "__main__":
    sys.exit(main())
