#!/usr/bin/env python
"""Scripted load generator for the traffic-shaping tier — demo + CI harness.

Replays a deterministic (seeded) arrival schedule against an in-process
replica pool with the full serving stack in front of it: tenant-weighted
admission, the continuous scheduler (or the FIFO baseline for A/B), and
optionally the autoscaler. Three traffic profiles:

- ``steady``  — constant ``--base-rps``;
- ``diurnal`` — one sinusoidal day: base → peak → base across the run
  (the autoscaler's 2→N→2 script);
- ``flash``   — base rate with a flash crowd at ``--peak-rps`` through
  the middle 40–60% of the run (the shed-the-scavengers script).

The pool serves a *modeled* engine by default — per-batch service time
``overhead + k·per_item`` (so batching genuinely pays, and the A/B
occupancy win shows up in wall-clock) with power-of-2 bucket padding for
the pad-fraction accounting; ``--config`` swaps in a real
``InferenceEngine``. Results go three places: a JSON report (``--out``),
the access log (``--access-log``, readable by ``tools/serve_doctor.py``),
and one ``obs/perfledger`` row per run (``--bench-history``) so
``tools/perf_doctor.py`` regression-gates serving latency/throughput the
same way it gates training.

    python tools/loadgen.py --profile flash --duration-s 20 --seed 7 \
        --base-rps 12 --peak-rps 160 --replicas 2 --autoscale 2:4 \
        --tenants 'web=interactive,scrape=batch:rate=8' \
        --scheduler continuous --slo 'p99_latency_ms<=2000' \
        --access-log /tmp/lg/access --out /tmp/lg/result.json
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import tempfile
import time
from concurrent.futures import wait
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# ---------------------------------------------------------------- schedule


def rate_at(
    profile: str, t: float, duration_s: float, base_rps: float, peak_rps: float
) -> float:
    """Offered load (req/s) at offset ``t`` into the run."""
    if profile == "steady":
        return base_rps
    if profile == "diurnal":
        # one full day: trough at both ends, peak mid-run
        phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / duration_s))
        return base_rps + (peak_rps - base_rps) * phase
    if profile == "flash":
        lo, hi = 0.4 * duration_s, 0.6 * duration_s
        return peak_rps if lo <= t < hi else base_rps
    raise ValueError(f"unknown profile {profile!r}")


def build_schedule(
    profile: str,
    duration_s: float,
    base_rps: float,
    peak_rps: float,
    mix: list[tuple[str, float]],
    seed: int,
) -> list[tuple[float, str]]:
    """Deterministic arrival schedule: ``[(t_offset, tenant), ...]`` with
    exponential inter-arrivals at the profile's instantaneous rate and
    tenants drawn by their mix share."""
    rng = np.random.RandomState(seed)
    names = [name for name, _ in mix]
    shares = np.asarray([share for _, share in mix], dtype=np.float64)
    shares = shares / shares.sum()
    out: list[tuple[float, str]] = []
    t = 0.0
    while True:
        rate = max(rate_at(profile, t, duration_s, base_rps, peak_rps), 1e-3)
        t += float(rng.exponential(1.0 / rate))
        if t >= duration_s:
            return out
        out.append((t, names[int(rng.choice(len(names), p=shares))]))


def parse_mix(spec: str, tenant_names: list[str]) -> list[tuple[str, float]]:
    """``web=0.7,scrape=0.3`` → shares; default: equal across tenants."""
    if not spec:
        return [(n, 1.0) for n in tenant_names]
    mix = []
    for entry in spec.split(","):
        name, _, share = entry.partition("=")
        mix.append((name.strip(), float(share)))
    return mix


# ------------------------------------------------------------ model engine


def bucket_of(k: int, max_batch: int) -> int:
    b = 1
    while b < k:
        b *= 2
    return min(b, max_batch)


class _ModelEngine:
    """Service-time model standing in for an InferenceEngine: a flush of k
    items costs ``overhead + bucket(k)·per_item`` (padded rows compute
    too — that is exactly the waste the continuous scheduler removes)."""

    def __init__(self, overhead_s: float, per_item_s: float, max_batch: int):
        self.overhead_s = overhead_s
        self.per_item_s = per_item_s
        self.max_batch = max_batch
        self.last_k = 0

    def run(self, batch: np.ndarray) -> np.ndarray:
        k = len(batch)
        self.last_k = k
        b = bucket_of(k, self.max_batch)
        time.sleep(self.overhead_s + b * self.per_item_s)
        return batch * 2.0

    def breakdown(self) -> dict:
        k = self.last_k
        b = bucket_of(k, self.max_batch) if k else 0
        return {
            "compute_s": self.overhead_s + b * self.per_item_s,
            "bucket": b,
            "pad_fraction": (b - k) / b if b else 0.0,
        }


# -------------------------------------------------------------------- run


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--profile", choices=("steady", "diurnal", "flash"), default="steady"
    )
    p.add_argument("--duration-s", type=float, default=10.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--base-rps", type=float, default=20.0)
    p.add_argument("--peak-rps", type=float, default=120.0)
    p.add_argument(
        "--tenants",
        default="web=interactive,scrape=batch",
        help="name=class[:rate=N][:burst=N][:budget=D][:window=W],... "
        "(serve/admission.py spec; budget = device-seconds per window)",
    )
    p.add_argument(
        "--mix", default="", help="tenant arrival shares, e.g. web=0.7,scrape=0.3"
    )
    p.add_argument(
        "--scheduler",
        choices=("fifo", "continuous"),
        default="continuous",
        help="fifo = per-replica MicroBatcher coalescing (baseline); "
        "continuous = the serve/scheduler.py accumulator in front",
    )
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--autoscale", default="", metavar="MIN:MAX")
    p.add_argument("--autoscale-interval-s", type=float, default=1.0)
    p.add_argument(
        "--cooldown-s",
        type=float,
        default=0.0,
        help="idle time after the replay before teardown — lets the "
        "autoscaler observe the lull and complete the scale-down leg",
    )
    p.add_argument("--max-batch", type=int, default=16)
    p.add_argument("--max-delay-ms", type=float, default=10.0)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--deadline-ms", type=float, default=None)
    p.add_argument("--slo", default="", metavar="SPEC")
    p.add_argument("--slo-window-s", type=float, default=10.0)
    p.add_argument(
        "--service-overhead-ms",
        type=float,
        default=8.0,
        help="modeled per-flush fixed cost (dispatch + fetch)",
    )
    p.add_argument(
        "--service-per-item-ms",
        type=float,
        default=1.5,
        help="modeled per-bucket-row cost (padded rows pay too)",
    )
    p.add_argument(
        "--model-gflops-per-item",
        type=float,
        default=1.0,
        help="modeled executable cost: GFLOPs per bucket row (the cost "
        "meter's analytic basis when no real engine is attached)",
    )
    p.add_argument("--config", default="", help="YAML recipe: use a real engine")
    p.add_argument("--task", default="features")
    p.add_argument("--access-log", default="", metavar="DIR")
    p.add_argument(
        "--metrics-out",
        default="",
        metavar="PATH",
        help="dump the final Prometheus scrape (registry render) here",
    )
    p.add_argument(
        "--bench-history",
        default=None,
        metavar="PATH",
        help="perfledger path (default $BENCH_HISTORY; off/0/none disables)",
    )
    p.add_argument("--out", default="", help="JSON report path")
    return p


def main(argv: list[str] | None = None) -> dict:
    args = build_parser().parse_args(argv)

    from jumbo_mae_tpu_tpu.infer.replicaset import ReplicaSet
    from jumbo_mae_tpu_tpu.obs import AccessLog, RequestTracer
    from jumbo_mae_tpu_tpu.obs.journal import read_journal
    from jumbo_mae_tpu_tpu.obs.perfledger import (
        append_row,
        make_row,
        resolve_history_path,
    )
    from jumbo_mae_tpu_tpu.serve import (
        AdmissionController,
        Autoscaler,
        ContinuousScheduler,
        CostMeter,
        default_cost_fn,
        parse_tenants,
    )

    tenants = parse_tenants(args.tenants)
    mix = parse_mix(args.mix, [t.name for t in tenants])
    schedule = build_schedule(
        args.profile, args.duration_s, args.base_rps, args.peak_rps,
        mix, args.seed,
    )
    print(
        f"[loadgen] {args.profile}: {len(schedule)} arrivals over "
        f"{args.duration_s:g}s (seed {args.seed}, scheduler {args.scheduler})"
    )

    if not args.access_log:
        # latency quantiles and per-tenant stats are derived from the access
        # log, so always keep one — scratch dir when the caller didn't ask
        args.access_log = tempfile.mkdtemp(prefix="loadgen-access-")
    access = AccessLog(args.access_log)
    slo_tracker = None
    if args.slo:
        from jumbo_mae_tpu_tpu.obs import SLOTracker, parse_slo

        slo_tracker = SLOTracker(
            parse_slo(args.slo), window_s=args.slo_window_s
        )
    tracer = RequestTracer(
        access_log=access,
        on_finish=(
            slo_tracker.observe_trace if slo_tracker is not None else None
        ),
    )

    flush_sizes: list[int] = []
    if args.config:
        from jumbo_mae_tpu_tpu.config import load_config
        from jumbo_mae_tpu_tpu.infer import InferenceEngine

        cfg = load_config(args.config, [])

        def provider(idx):
            return InferenceEngine(cfg, max_batch=args.max_batch)

        def run(engine, batch, metas):
            flush_sizes.append(len(batch))
            return engine.predict(batch, task=args.task)

        def breakdown(engine):
            return engine.last_breakdown()

        cost_fn = default_cost_fn  # real executables publish cost_reports

        probe_engine = provider(0)
        size = probe_engine.image_size
        image = (
            np.random.RandomState(args.seed)
            .randint(0, 256, (size, size, 3))
            .astype(np.uint8)
        )
        capacity_fn = None
    else:
        overhead = args.service_overhead_ms / 1000.0
        per_item = args.service_per_item_ms / 1000.0

        def provider(idx):
            return _ModelEngine(overhead, per_item, args.max_batch)

        def run(engine, batch, metas):
            flush_sizes.append(len(batch))
            return engine.run(batch)

        def breakdown(engine):
            return engine.breakdown()

        flops_per_row = args.model_gflops_per_item * 1e9

        def cost_fn(engine, task, bucket):
            # the modeled executable: every bucket row costs the same
            return {"flops": bucket * flops_per_row}

        image = np.ones((8, 8), dtype=np.float32)

        def capacity_fn():
            # the model's own roofline: a full bucket amortizes overhead
            full = overhead + args.max_batch * per_item
            return args.max_batch / full

    # continuous mode: the scheduler's accumulator is the admission-visible
    # queue; the pool gets headroom above it so a dispatched group doesn't
    # race the pool's own hard cap (which would shed already-admitted
    # interactive requests)
    pool_queue = args.max_queue
    if args.scheduler == "continuous" and pool_queue is not None:
        pool_queue = pool_queue + 2 * args.max_batch
    meter = CostMeter(tenants, cost_fn=cost_fn, tracer=tracer)
    rs = ReplicaSet(
        provider,
        run,
        replicas=args.replicas,
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        max_queue=pool_queue,
        tracer=tracer,
        task=args.task,
        breakdown=breakdown,
        costmeter=meter,
    )
    admission = AdmissionController(tenants, meter=meter)
    sched = None
    if args.scheduler == "continuous":
        sched = ContinuousScheduler(
            rs.submit_group,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            max_queue=args.max_queue,
            admission=admission,
            tracer=tracer,
            task=args.task,
        )
        # combined pressure: the scheduler's accumulator AND the pool's
        # backlog — either one filling up should start shedding low classes
        # before interactive traffic hits a hard queue-full
        admission.set_pressure_fn(
            lambda: max(sched.pressure(), rs.pressure())
        )
    else:
        admission.set_pressure_fn(rs.pressure)

    autoscaler = None
    if args.autoscale:
        lo, hi = (int(x) for x in args.autoscale.split(":"))
        autoscaler = Autoscaler(
            rs,
            min_replicas=lo,
            max_replicas=hi,
            interval_s=args.autoscale_interval_s,
            slo=slo_tracker,
            capacity_fn=capacity_fn,
            tracer=tracer,
        )

    # ------------------------------------------------------------- replay
    futs = []
    shed = 0
    t0 = time.monotonic()
    for t_offset, tenant in schedule:
        delay = t0 + t_offset - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            if sched is not None:
                futs.append(
                    sched.submit(
                        image, deadline_ms=args.deadline_ms, tenant=tenant
                    )
                )
            else:
                sp = admission.admit(tenant)
                futs.append(
                    rs.submit(
                        image,
                        deadline_ms=args.deadline_ms,
                        tenant=tenant,
                        tclass=sp.tclass,
                    )
                )
        except Exception:  # noqa: BLE001 — typed sheds are the measurement
            shed += 1
    wall = time.monotonic() - t0
    done, not_done = wait(futs, timeout=30.0)
    if args.cooldown_s > 0:
        time.sleep(args.cooldown_s)
    ok = failed = 0
    for f in done:
        if f.exception() is None:
            ok += 1
        else:
            failed += 1
    if autoscaler is not None:
        autoscaler.close()
    if sched is not None:
        sched.close()
    rs.close()
    meter.flush()  # final tenant_usage rows before the log closes
    tracer.close()

    # ------------------------------------------------------------- report
    sizes = np.asarray(flush_sizes, dtype=np.float64)
    occupancy_mean = float(sizes.mean() / args.max_batch) if len(sizes) else 0.0
    # aggregate compute waste: fraction of device rows that were padding
    # (a per-batch mean would weight a 2-item flush equally with a full one)
    dev_rows = sum(bucket_of(int(k), args.max_batch) for k in flush_sizes)
    pad_mean = float((dev_rows - sizes.sum()) / dev_rows) if dev_rows else 0.0
    size_hist: dict[int, int] = {}
    for k in flush_sizes:
        size_hist[int(k)] = size_hist.get(int(k), 0) + 1

    per_tenant: dict[str, dict] = {}
    try:
        rows = read_journal(args.access_log) if args.access_log else []
    except FileNotFoundError:
        rows = []
    req_rows = [r for r in rows if r.get("type") == "request"]
    for r in req_rows:
        t = per_tenant.setdefault(
            r.get("tenant", "?"),
            {"class": r.get("class"), "requests": 0, "ok": 0, "shed": 0,
             "lat_ms": []},
        )
        t["requests"] += 1
        if r["outcome"] == "ok":
            t["ok"] += 1
            t["lat_ms"].append(r["lat_ms"])
        elif r["outcome"] == "shed":
            t["shed"] += 1
    for t in per_tenant.values():
        lats = sorted(t.pop("lat_ms"))
        t["p50_ms"] = round(lats[len(lats) // 2], 2) if lats else None
        t["p99_ms"] = (
            round(lats[min(len(lats) - 1, int(0.99 * len(lats)))], 2)
            if lats
            else None
        )
    cost = meter.snapshot()
    for name, bill in cost["tenants"].items():
        t = per_tenant.setdefault(
            name,
            {"class": bill["class"], "requests": 0, "ok": 0, "shed": 0,
             "p50_ms": None, "p99_ms": None},
        )
        t["device_s"] = round(bill["device_s"], 4)
        t["flops"] = bill["flops"]
        t["waste_device_s"] = round(bill["waste_device_s"], 4)
        t["cost_share"] = round(bill["share"], 4)
        if "budget_device_s" in bill:
            t["budget_device_s"] = bill["budget_device_s"]
            t["over_budget"] = bill["over_budget"]

    all_lat = sorted(
        r["lat_ms"] for r in req_rows if r["outcome"] == "ok"
    )

    def q(p: float):
        if not all_lat:
            return None
        return round(all_lat[min(len(all_lat) - 1, int(p * len(all_lat)))], 2)

    interactive_ok = True
    slo_report = None
    if slo_tracker is not None:
        slo_report = slo_tracker.evaluate()
        inter = {t.name for t in tenants if t.tclass == "interactive"}
        for obj in slo_tracker.objectives:
            if obj.percentile is None:
                continue
            for name in inter:
                p99 = per_tenant.get(name, {}).get("p99_ms")
                if p99 is not None and p99 > obj.threshold:
                    interactive_ok = False

    result = {
        "profile": args.profile,
        "scheduler": args.scheduler,
        "seed": args.seed,
        "duration_s": round(wall, 3),
        "offered": len(schedule),
        "ok": ok,
        "shed_at_submit": shed,
        "failed": failed,
        "unresolved": len(not_done),
        # in-flight drops: admitted requests the pool abandoned (anything
        # failed that is not an admission shed or a deadline miss)
        "dropped_in_flight": sum(
            1 for r in req_rows
            if r["outcome"] in ("aborted", "shutdown")
        ),
        "req_per_sec": round(ok / wall, 2) if wall > 0 else 0.0,
        "p50_ms": q(0.50),
        "p99_ms": q(0.99),
        "occupancy_mean": round(occupancy_mean, 4),
        "pad_mean": round(pad_mean, 4),
        "batches": len(flush_sizes),
        "size_hist": {k: size_hist[k] for k in sorted(size_hist)},
        "tenants": per_tenant,
        "cost": {
            "total_batches": cost["total_batches"],
            "total_device_s": round(cost["total_device_s"], 4),
            "total_flops": cost["total_flops"],
            "chip": cost.get("chip"),
        },
        "admission": admission.stats(),
        "autoscale_events": (
            list(autoscaler.events) if autoscaler is not None else []
        ),
        "interactive_slo_ok": interactive_ok,
        "slo": slo_report,
    }
    print(
        f"[loadgen] ok={ok} shed_at_submit={shed} "
        f"failed={failed} occ={result['occupancy_mean']} "
        f"pad={result['pad_mean']} p99={result['p99_ms']}ms "
        f"device_s={result['cost']['total_device_s']} "
        f"autoscale_events={len(result['autoscale_events'])}"
    )

    history = resolve_history_path(args.bench_history)
    if history is not None and ok:
        total_dev_s = cost["total_device_s"]
        legs = {
            "req_per_sec": result["req_per_sec"],
            "p50_ms": result["p50_ms"],
            "p99_ms": result["p99_ms"],
            "occupancy_mean": result["occupancy_mean"],
            # cost efficiency: work delivered per metered device-second —
            # perf_doctor gates this next to throughput
            "device_s_total": round(total_dev_s, 4),
            "ok_per_device_s": (
                round(ok / total_dev_s, 2) if total_dev_s > 0 else 0.0
            ),
        }
        for name, bill in cost["tenants"].items():
            legs[f"device_s_{name}"] = round(bill["device_s"], 4)
        row = make_row(
            bench="serve",
            metric=f"loadgen_{args.profile}_{args.scheduler}",
            legs=legs,
            quantiles={"p50_ms": result["p50_ms"], "p99_ms": result["p99_ms"]},
            extra={
                "pad_mean": result["pad_mean"],
                "waste_device_s": round(
                    sum(
                        b["waste_device_s"] for b in cost["tenants"].values()
                    ),
                    4,
                ),
                "profile": args.profile,
                "scheduler": args.scheduler,
                "seed": args.seed,
            },
        )
        if append_row(history, row):
            print(f"[loadgen] ledger row -> {history}")

    if args.metrics_out:
        from jumbo_mae_tpu_tpu.obs.metrics import get_registry

        mpath = Path(args.metrics_out)
        mpath.parent.mkdir(parents=True, exist_ok=True)
        mpath.write_text(get_registry().render())
        print(f"[loadgen] metrics -> {mpath}")

    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(result, indent=2, default=str))
        print(f"[loadgen] report -> {out}")
    return result


if __name__ == "__main__":
    main()
