#!/usr/bin/env python
"""Scripted load generator for the traffic-shaping tier — demo + CI harness.

Replays a deterministic (seeded) arrival schedule against an in-process
replica pool with the full serving stack in front of it: tenant-weighted
admission, the continuous scheduler (or the FIFO baseline for A/B), and
optionally the autoscaler. Three traffic profiles:

- ``steady``  — constant ``--base-rps``;
- ``diurnal`` — one sinusoidal day: base → peak → base across the run
  (the autoscaler's 2→N→2 script);
- ``flash``   — base rate with a flash crowd at ``--peak-rps`` through
  the middle 40–60% of the run (the shed-the-scavengers script).

The pool serves a *modeled* engine by default — per-batch service time
``overhead + k·per_item`` (so batching genuinely pays, and the A/B
occupancy win shows up in wall-clock) with power-of-2 bucket padding for
the pad-fraction accounting; ``--config`` swaps in a real
``InferenceEngine``. Results go three places: a JSON report (``--out``),
the access log (``--access-log``, readable by ``tools/serve_doctor.py``),
and one ``obs/perfledger`` row per run (``--bench-history``) so
``tools/perf_doctor.py`` regression-gates serving latency/throughput the
same way it gates training.

    python tools/loadgen.py --profile flash --duration-s 20 --seed 7 \
        --base-rps 12 --peak-rps 160 --replicas 2 --autoscale 2:4 \
        --tenants 'web=interactive,scrape=batch:rate=8' \
        --scheduler continuous --slo 'p99_latency_ms<=2000' \
        --access-log /tmp/lg/access --out /tmp/lg/result.json
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import tempfile
import time
from concurrent.futures import wait
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# ---------------------------------------------------------------- schedule


def rate_at(
    profile: str, t: float, duration_s: float, base_rps: float, peak_rps: float
) -> float:
    """Offered load (req/s) at offset ``t`` into the run."""
    if profile == "steady":
        return base_rps
    if profile == "diurnal":
        # one full day: trough at both ends, peak mid-run
        phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / duration_s))
        return base_rps + (peak_rps - base_rps) * phase
    if profile == "flash":
        lo, hi = 0.4 * duration_s, 0.6 * duration_s
        return peak_rps if lo <= t < hi else base_rps
    raise ValueError(f"unknown profile {profile!r}")


def build_schedule(
    profile: str,
    duration_s: float,
    base_rps: float,
    peak_rps: float,
    mix: list[tuple[str, float]],
    seed: int,
) -> list[tuple[float, str]]:
    """Deterministic arrival schedule: ``[(t_offset, tenant), ...]`` with
    exponential inter-arrivals at the profile's instantaneous rate and
    tenants drawn by their mix share."""
    rng = np.random.RandomState(seed)
    names = [name for name, _ in mix]
    shares = np.asarray([share for _, share in mix], dtype=np.float64)
    shares = shares / shares.sum()
    out: list[tuple[float, str]] = []
    t = 0.0
    while True:
        rate = max(rate_at(profile, t, duration_s, base_rps, peak_rps), 1e-3)
        t += float(rng.exponential(1.0 / rate))
        if t >= duration_s:
            return out
        out.append((t, names[int(rng.choice(len(names), p=shares))]))


def parse_mix(spec: str, tenant_names: list[str]) -> list[tuple[str, float]]:
    """``web=0.7,scrape=0.3`` → shares; default: equal across tenants."""
    if not spec:
        return [(n, 1.0) for n in tenant_names]
    mix = []
    for entry in spec.split(","):
        name, _, share = entry.partition("=")
        mix.append((name.strip(), float(share)))
    return mix


def parse_weighted(spec: str, cast=str) -> list[tuple, ...]:
    """``a:0.5,b:0.3`` → [(cast(a), 0.5), (cast(b), 0.3)] (weight 1 when
    omitted) — the ``--task-mix`` grammar."""
    out = []
    for entry in spec.split(","):
        val, _, w = entry.partition(":")
        out.append((cast(val.strip()), float(w or 1.0)))
    return out


def draw_weighted(rng, options: list[tuple], n: int) -> list:
    """Seeded draw of n values from [(value, weight), ...]."""
    vals = [v for v, _ in options]
    w = np.asarray([max(x, 0.0) for _, x in options], np.float64)
    w = w / w.sum()
    return [vals[int(i)] for i in rng.choice(len(vals), size=n, p=w)]


def parse_res_spec(spec: str) -> list[tuple[int, int, float]]:
    """``--resolutions`` grammar: ``lo-hi:weight`` or ``size:weight``
    entries → ``[(lo, hi, weight), ...]``. ``hi`` is the image-bucket
    control leg's resolution rung (requests are padded up to it); native
    sizes draw uniformly from the patch multiples in ``[lo, hi]`` — the
    token-packed leg serves those natively."""
    out = []
    for entry in spec.split(","):
        rng_part, _, w = entry.partition(":")
        lo_s, dash, hi_s = rng_part.partition("-")
        lo = int(lo_s)
        hi = int(hi_s) if dash else lo
        out.append((lo, hi, float(w or 1.0)))
    return out


def draw_sizes(
    rng, opts: list[tuple[int, int, float]], n: int, patch: int
) -> list[tuple[int, int]]:
    """Seeded per-arrival draw of ``(native, bucket)`` sizes: the bucket
    (range hi) by weight, then the native size uniform on the patch
    multiples in ``[lo, hi]``."""
    w = np.asarray([max(x, 0.0) for *_, x in opts], np.float64)
    w = w / w.sum()
    picks = rng.choice(len(opts), size=n, p=w)
    out = []
    for i in picks:
        lo, hi, _ = opts[int(i)]
        kmin = -(-lo // patch)  # ceil to the next patch multiple
        kmax = hi // patch
        out.append((patch * int(rng.randint(kmin, kmax + 1)), hi))
    return out


# ------------------------------------------------------------ model engine


def bucket_of(k: int, max_batch: int) -> int:
    """Report-side view of the engine's pad bucket — the shared ladder
    definition, so the pad accounting can't drift from what the device
    actually ran."""
    from jumbo_mae_tpu_tpu.infer.bucketing import bucket_for

    return bucket_for(k, max_batch)


class _ModelEngine:
    """Service-time model standing in for an InferenceEngine: a flush
    costs ``overhead + device_units·per_unit`` where the padded device
    units are what the real executable would run — that padding is exactly
    the waste the continuous scheduler (and the token-packed path) removes.

    Three pricing modes, selected by the constructor:

    - default — one unit per image row, ``bucket(k)`` device rows (the
      original model; padded rows compute too);
    - ``seq_len_fn`` — units are patch+CLS tokens; a homogeneous flush of
      k images at L tokens runs ``bucket(k)·L`` device tokens (image
      buckets pad whole rows of L tokens — the A/B control leg);
    - ``seq_len_fn`` + ``token_budget`` — token-packed dispatch: device
      tokens come from the REAL ``infer/packing.py`` planner
      (``ceil_pow2(rows)·budget``), so the modeled A/B win is the same
      geometry the engine's packed executables run.
    """

    def __init__(
        self,
        overhead_s: float,
        per_item_s: float,
        max_batch: int,
        *,
        seq_len_fn=None,
        token_budget: int | None = None,
    ):
        self.overhead_s = overhead_s
        self.per_item_s = per_item_s
        self.max_batch = max_batch
        self.seq_len_fn = seq_len_fn
        self.token_budget = token_budget
        self.last_k = 0
        self.last_req_units = 0
        self.last_dev_units = 0

    def _price(self, batch) -> tuple[int, int]:
        """(requested_units, device_units) for one flush."""
        k = len(batch)
        if self.seq_len_fn is None:
            return k, bucket_of(k, self.max_batch)
        lens = [int(self.seq_len_fn(im)) for im in batch]
        req = sum(lens)
        if self.token_budget:
            from jumbo_mae_tpu_tpu.infer.bucketing import ceil_pow2
            from jumbo_mae_tpu_tpu.infer.packing import (
                budget_rungs,
                choose_budget,
            )

            budget, plan = choose_budget(lens, budget_rungs(self.token_budget))
            return req, ceil_pow2(plan.rows) * budget
        # image buckets: one bucketed executable per resolution chunk —
        # every padded row costs that chunk's full sequence (flushes are
        # homogeneous behind the continuous scheduler; pricing per chunk
        # keeps this honest for any mixed flush too)
        by_len: dict[int, int] = {}
        for ln in lens:
            by_len[ln] = by_len.get(ln, 0) + 1
        dev = sum(
            bucket_of(n, self.max_batch) * ln for ln, n in by_len.items()
        )
        return req, dev

    def run(self, batch):
        self.last_k = len(batch)
        req, dev = self._price(batch)
        self.last_req_units, self.last_dev_units = req, dev
        time.sleep(self.overhead_s + dev * self.per_item_s)
        if isinstance(batch, list):  # mixed shapes: a token-packed group
            return [im * 2.0 for im in batch]
        return batch * 2.0

    def breakdown(self) -> dict:
        dev = self.last_dev_units
        return {
            "compute_s": self.overhead_s + dev * self.per_item_s,
            "bucket": dev,
            "pad_fraction": (dev - self.last_req_units) / dev if dev else 0.0,
        }


# -------------------------------------------------------------------- run


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--profile", choices=("steady", "diurnal", "flash"), default="steady"
    )
    p.add_argument("--duration-s", type=float, default=10.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--base-rps", type=float, default=20.0)
    p.add_argument("--peak-rps", type=float, default=120.0)
    p.add_argument(
        "--tenants",
        default="web=interactive,scrape=batch",
        help="name=class[:rate=N][:burst=N][:budget=D][:window=W],... "
        "(serve/admission.py spec; budget = device-seconds per window)",
    )
    p.add_argument(
        "--mix", default="", help="tenant arrival shares, e.g. web=0.7,scrape=0.3"
    )
    p.add_argument(
        "--scheduler",
        choices=("fifo", "continuous"),
        default="continuous",
        help="fifo = per-replica MicroBatcher coalescing (baseline); "
        "continuous = the serve/scheduler.py accumulator in front",
    )
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--autoscale", default="", metavar="MIN:MAX")
    p.add_argument("--autoscale-interval-s", type=float, default=1.0)
    p.add_argument(
        "--cooldown-s",
        type=float,
        default=0.0,
        help="idle time after the replay before teardown — lets the "
        "autoscaler observe the lull and complete the scale-down leg",
    )
    p.add_argument("--max-batch", type=int, default=16)
    p.add_argument("--max-delay-ms", type=float, default=10.0)
    p.add_argument("--max-queue", type=int, default=64)
    p.add_argument("--deadline-ms", type=float, default=None)
    p.add_argument("--slo", default="", metavar="SPEC")
    p.add_argument("--slo-window-s", type=float, default=10.0)
    p.add_argument(
        "--service-overhead-ms",
        type=float,
        default=8.0,
        help="modeled per-flush fixed cost (dispatch + fetch)",
    )
    p.add_argument(
        "--service-per-item-ms",
        type=float,
        default=1.5,
        help="modeled per-bucket-row cost (padded rows pay too)",
    )
    p.add_argument(
        "--model-gflops-per-item",
        type=float,
        default=1.0,
        help="modeled executable cost: GFLOPs per bucket row (the cost "
        "meter's analytic basis when no real engine is attached)",
    )
    p.add_argument("--config", default="", help="YAML recipe: use a real engine")
    p.add_argument("--task", default="features")
    p.add_argument(
        "--resolutions",
        default="",
        metavar="SPEC",
        help="mixed-resolution arrivals 'lo-hi:weight,...' (e.g. "
        "'160-224:0.5,320-448:0.3,640-896:0.2'), drawn per arrival from "
        "the run seed. Each arrival picks a range by weight, then a native "
        "size uniform on the patch multiples in [lo, hi]; the image-bucket "
        "control leg pads it up to hi (the resolution rung) while "
        "--pack-budget serves it natively. 'size:weight' pins lo = hi. "
        "Needs --scheduler continuous",
    )
    p.add_argument(
        "--task-mix",
        default="",
        metavar="SPEC",
        help="per-arrival task draw, e.g. 'features:0.7,logits:0.3'; "
        "needs --scheduler continuous",
    )
    p.add_argument(
        "--pack-budget",
        type=int,
        default=0,
        help=">0: token-packed dispatch — the continuous scheduler fills "
        "each dispatch to this many patch+CLS tokens instead of counting "
        "images (the infer/packing.py serving path). 0 = image buckets "
        "(the A/B control leg)",
    )
    p.add_argument(
        "--patch-size",
        type=int,
        default=16,
        help="token pricing for the modeled engine: "
        "tokens(s) = (s/patch)^2 + 1 (a real --config engine prices with "
        "its own seq_len)",
    )
    p.add_argument(
        "--service-per-token-us",
        type=float,
        default=20.0,
        help="modeled per-device-token cost, used instead of "
        "--service-per-item-ms when token mode (--resolutions or "
        "--pack-budget) is on",
    )
    p.add_argument("--access-log", default="", metavar="DIR")
    p.add_argument(
        "--metrics-out",
        default="",
        metavar="PATH",
        help="dump the final Prometheus scrape (registry render) here",
    )
    p.add_argument(
        "--bench-history",
        default=None,
        metavar="PATH",
        help="perfledger path (default $BENCH_HISTORY; off/0/none disables)",
    )
    p.add_argument("--out", default="", help="JSON report path")
    return p


def main(argv: list[str] | None = None) -> dict:
    args = build_parser().parse_args(argv)

    from jumbo_mae_tpu_tpu.infer.replicaset import ReplicaSet
    from jumbo_mae_tpu_tpu.obs import AccessLog, RequestTracer
    from jumbo_mae_tpu_tpu.obs.journal import read_journal
    from jumbo_mae_tpu_tpu.obs.perfledger import (
        append_row,
        make_row,
        resolve_history_path,
    )
    from jumbo_mae_tpu_tpu.serve import (
        AdmissionController,
        Autoscaler,
        ContinuousScheduler,
        CostMeter,
        default_cost_fn,
        parse_tenants,
    )

    tenants = parse_tenants(args.tenants)
    mix = parse_mix(args.mix, [t.name for t in tenants])
    schedule = build_schedule(
        args.profile, args.duration_s, args.base_rps, args.peak_rps,
        mix, args.seed,
    )

    res_opts = parse_res_spec(args.resolutions) if args.resolutions else []
    task_opts = parse_weighted(args.task_mix, str) if args.task_mix else []
    pack_budget = int(args.pack_budget or 0)
    token_mode = bool(res_opts or pack_budget)
    if (token_mode or task_opts) and args.scheduler != "continuous":
        raise SystemExit(
            "[loadgen] --resolutions/--task-mix/--pack-budget need "
            "--scheduler continuous (FIFO micro-batching stacks one shape "
            "and one task per flush)"
        )
    for lo, hi, _ in res_opts:
        if lo <= 0 or lo > hi or hi % args.patch_size:
            raise SystemExit(
                f"[loadgen] bad resolution range {lo}-{hi}: need "
                f"0 < lo <= hi with hi a multiple of --patch-size "
                f"{args.patch_size}"
            )
    sched_label = "packed" if pack_budget else args.scheduler
    print(
        f"[loadgen] {args.profile}: {len(schedule)} arrivals over "
        f"{args.duration_s:g}s (seed {args.seed}, scheduler {sched_label})"
    )

    if not args.access_log:
        # latency quantiles and per-tenant stats are derived from the access
        # log, so always keep one — scratch dir when the caller didn't ask
        args.access_log = tempfile.mkdtemp(prefix="loadgen-access-")
    access = AccessLog(args.access_log)
    slo_tracker = None
    if args.slo:
        from jumbo_mae_tpu_tpu.obs import SLOTracker, parse_slo

        slo_tracker = SLOTracker(
            parse_slo(args.slo), window_s=args.slo_window_s
        )
    tracer = RequestTracer(
        access_log=access,
        on_finish=(
            slo_tracker.observe_trace if slo_tracker is not None else None
        ),
    )

    flush_sizes: list[int] = []
    flush_tokens: list[tuple[int, int]] = []  # (requested, device) tokens
    seq_len_fn = None
    image = None
    image_by_size: dict[int, np.ndarray] = {}
    if args.config:
        from jumbo_mae_tpu_tpu.config import load_config
        from jumbo_mae_tpu_tpu.infer import InferenceEngine

        cfg = load_config(args.config, [])

        def provider(idx):
            # max_tokens is the packer's rung ceiling, kept above the
            # scheduler's fill target (--pack-budget) so flushes that merge
            # consecutive dispatch groups still pack efficiently
            return InferenceEngine(
                cfg,
                max_batch=args.max_batch,
                **(
                    {"max_tokens": max(pack_budget, 4096)}
                    if pack_budget
                    else {}
                ),
            )

        probe_engine = provider(0)
        size = probe_engine.image_size
        if token_mode:
            if not res_opts:
                res_opts = [(size, size, 1.0)]
            if not pack_budget and any(
                lo != size or hi != size for lo, hi, _ in res_opts
            ):
                raise SystemExit(
                    "[loadgen] a real engine's unpacked predict serves only "
                    f"its native {size}px — run the mixed-resolution control "
                    "leg on the modeled engine (drop --config) or add "
                    "--pack-budget"
                )

            def seq_len_fn(arr):
                return probe_engine.seq_len(arr.shape[0])

            def tokens_of(s):
                return probe_engine.seq_len(s)

        def run(engine, batch, metas):
            flush_sizes.append(len(batch))
            if pack_budget:
                tasks = (
                    [m["task"] for m in metas] if task_opts else args.task
                )
                out = engine.predict_packed(list(batch), tasks)
            else:
                task = (
                    metas[0]["task"]
                    if task_opts and metas and metas[0]
                    else args.task
                )
                out = engine.predict(batch, task=task)
            if token_mode:
                # requested = the arrival's NATIVE tokens (meta-stamped) —
                # resolution padding up to the bucket is device waste too
                req = sum(
                    int((m or {}).get("tok") or seq_len_fn(im))
                    for m, im in zip(metas, batch)
                )
                bd = engine.last_breakdown() or {}
                pad = float(bd.get("pad_fraction") or 0.0)
                if pack_budget:
                    # packed breakdowns are token-denominated: invert the
                    # pad fraction back to device tokens
                    dev = int(round(req / (1.0 - pad))) if pad < 1.0 else req
                else:
                    dev = int(bd.get("bucket") or len(batch)) * int(
                        seq_len_fn(batch[0])
                    )
                flush_tokens.append((req, dev))
            return out

        def breakdown(engine):
            return engine.last_breakdown()

        cost_fn = default_cost_fn  # real executables publish cost_reports

        rng_img = np.random.RandomState(args.seed)

        def image_for(s):
            if s not in image_by_size:
                image_by_size[s] = (
                    rng_img.randint(0, 256, (s, s, 3)).astype(np.uint8)
                )
            return image_by_size[s]

        image = image_for(size) if not token_mode else None
        capacity_fn = None
    else:
        overhead = args.service_overhead_ms / 1000.0
        per_unit = (
            args.service_per_token_us / 1e6
            if token_mode
            else args.service_per_item_ms / 1000.0
        )
        rung_ceiling = None
        if token_mode:
            if not res_opts:
                res_opts = [(224, 224, 1.0)]
            patch = args.patch_size

            def seq_len_fn(arr):
                return (arr.shape[0] // patch) ** 2 + 1

            def tokens_of(s):
                return (s // patch) ** 2 + 1

            def image_for(s):
                if s not in image_by_size:
                    image_by_size[s] = np.ones((s, s, 3), dtype=np.float32)
                return image_by_size[s]

            if pack_budget:
                # the packer's rung ceiling is NOT the scheduler's fill
                # target: a busy replica merges consecutive dispatch groups
                # into one flush, and capping the rungs at the fill target
                # would force pow2-row geometry (e.g. 5 rows -> 8x512) on
                # those merged flushes — headroom lets choose_budget keep
                # picking the cheapest rows x budget for whatever arrives
                from jumbo_mae_tpu_tpu.infer.bucketing import ceil_pow2

                max_seq = max(tokens_of(hi) for _, hi, _ in res_opts)
                rung_ceiling = max(
                    pack_budget, ceil_pow2(args.max_batch * max_seq)
                )
        else:
            image = np.ones((8, 8), dtype=np.float32)

        def provider(idx):
            return _ModelEngine(
                overhead,
                per_unit,
                args.max_batch,
                seq_len_fn=seq_len_fn,
                token_budget=rung_ceiling,
            )

        def run(engine, batch, metas):
            flush_sizes.append(len(batch))
            out = engine.run(batch)
            if token_mode:
                # requested = NATIVE tokens (meta-stamped): in the control
                # leg images arrive pre-padded to their resolution bucket,
                # and that padding is device waste the bill must carry
                req = sum(
                    int((m or {}).get("tok") or seq_len_fn(im))
                    for m, im in zip(metas, batch)
                )
                engine.last_req_units = min(req, engine.last_dev_units)
                flush_tokens.append(
                    (engine.last_req_units, engine.last_dev_units)
                )
            return out

        def breakdown(engine):
            return engine.breakdown()

        flops_per_row = args.model_gflops_per_item * 1e9

        def cost_fn(engine, task, bucket):
            # the modeled executable: every device unit (bucket row — or
            # device token in token mode) costs the same
            return {"flops": bucket * flops_per_row}

        def capacity_fn():
            # the model's own roofline: a full dispatch amortizes overhead
            if token_mode:
                w = sum(x for *_, x in res_opts) or 1.0
                # control pads each arrival up to its range's bucket (hi);
                # packed serves the native mid-range size — use the
                # matching token count per leg for the roofline
                p = args.patch_size

                def leg_tok(lo, hi):
                    s = hi if not pack_budget else (lo + hi) // 2
                    return (s // p) ** 2 + 1

                mean_tok = (
                    sum(leg_tok(lo, hi) * x for lo, hi, x in res_opts) / w
                )
                dev = (
                    pack_budget if pack_budget else args.max_batch * mean_tok
                )
                items = dev / mean_tok if pack_budget else args.max_batch
                return items / (overhead + dev * per_unit)
            full = overhead + args.max_batch * per_unit
            return args.max_batch / full

    if pack_budget and res_opts:
        for _, hi, _ in res_opts:
            need = int(tokens_of(hi))
            if need > pack_budget:
                raise SystemExit(
                    f"[loadgen] {hi}px needs {need} tokens > --pack-budget "
                    f"{pack_budget}"
                )

    # continuous mode: the scheduler's accumulator is the admission-visible
    # queue; the pool gets headroom above it so a dispatched group doesn't
    # race the pool's own hard cap (which would shed already-admitted
    # interactive requests)
    pool_queue = args.max_queue
    if args.scheduler == "continuous" and pool_queue is not None:
        pool_queue = pool_queue + 2 * args.max_batch
    meter = CostMeter(tenants, cost_fn=cost_fn, tracer=tracer)
    rs = ReplicaSet(
        provider,
        run,
        replicas=args.replicas,
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        max_queue=pool_queue,
        tracer=tracer,
        task=args.task,
        breakdown=breakdown,
        costmeter=meter,
    )
    admission = AdmissionController(tenants, meter=meter)
    sched = None
    if args.scheduler == "continuous":
        sched = ContinuousScheduler(
            rs.submit_group,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            max_queue=args.max_queue,
            admission=admission,
            tracer=tracer,
            task=args.task,
            packed=bool(pack_budget),
            token_budget=pack_budget or None,
            # pass seq_len_fn in control token mode too: the scheduler
            # stamps tr.tokens from it, and for the control leg those are
            # BUCKET tokens (the image arrives pre-padded) — exactly what
            # that customer is billed for
            seq_len_fn=seq_len_fn if token_mode else None,
        )
        # combined pressure: the scheduler's accumulator AND the pool's
        # backlog — either one filling up should start shedding low classes
        # before interactive traffic hits a hard queue-full
        admission.set_pressure_fn(
            lambda: max(sched.pressure(), rs.pressure())
        )
    else:
        admission.set_pressure_fn(rs.pressure)

    autoscaler = None
    if args.autoscale:
        lo, hi = (int(x) for x in args.autoscale.split(":"))
        autoscaler = Autoscaler(
            rs,
            min_replicas=lo,
            max_replicas=hi,
            interval_s=args.autoscale_interval_s,
            slo=slo_tracker,
            capacity_fn=capacity_fn,
            tracer=tracer,
        )

    # ------------------------------------------------------------- replay
    # per-arrival resolution/task draws are seeded separately from the
    # schedule so packed and control legs replay the identical mixed load
    rng_mix = np.random.RandomState(args.seed + 1)
    sizes_seq = (
        draw_sizes(rng_mix, res_opts, len(schedule), args.patch_size)
        if token_mode
        else None
    )
    tasks_seq = (
        draw_weighted(rng_mix, task_opts, len(schedule)) if task_opts else None
    )
    rid_info: dict[int, dict] = {}  # fut.rid -> arrival's size/task draw
    futs = []
    shed = 0
    t0 = time.monotonic()
    for i, (t_offset, tenant) in enumerate(schedule):
        delay = t0 + t_offset - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        if sizes_seq:
            native, bucket = sizes_seq[i]
            # the control leg pays the paper's waste here: the request is
            # padded up to its resolution bucket before it ever queues;
            # the packed leg submits the native size as-is
            img = image_for(native if pack_budget else bucket)
            meta = {"tok": int(tokens_of(native))}
        else:
            img, meta = image, {}
        tk = tasks_seq[i] if tasks_seq else None
        if tk:
            meta["task"] = tk
        try:
            if sched is not None:
                fut = sched.submit(
                    img,
                    deadline_ms=args.deadline_ms,
                    tenant=tenant,
                    task=tk,
                    meta=(meta or None),
                )
                rid = getattr(fut, "rid", None)
                if rid is not None and (sizes_seq or tasks_seq):
                    rid_info[rid] = {
                        "size": sizes_seq[i][1] if sizes_seq else None,
                        "native": sizes_seq[i][0] if sizes_seq else None,
                        "task": tk,
                    }
                futs.append(fut)
            else:
                sp = admission.admit(tenant)
                futs.append(
                    rs.submit(
                        image,
                        deadline_ms=args.deadline_ms,
                        tenant=tenant,
                        tclass=sp.tclass,
                    )
                )
        except Exception:  # noqa: BLE001 — typed sheds are the measurement
            shed += 1
    wall = time.monotonic() - t0
    done, not_done = wait(futs, timeout=30.0)
    if args.cooldown_s > 0:
        time.sleep(args.cooldown_s)
    ok = failed = 0
    for f in done:
        if f.exception() is None:
            ok += 1
        else:
            failed += 1
    if autoscaler is not None:
        autoscaler.close()
    if sched is not None:
        sched.close()
    rs.close()
    meter.flush()  # final tenant_usage rows before the log closes
    tracer.close()

    # ------------------------------------------------------------- report
    sizes = np.asarray(flush_sizes, dtype=np.float64)
    occupancy_mean = float(sizes.mean() / args.max_batch) if len(sizes) else 0.0
    # aggregate compute waste: fraction of device rows that were padding
    # (a per-batch mean would weight a 2-item flush equally with a full one)
    dev_rows = sum(bucket_of(int(k), args.max_batch) for k in flush_sizes)
    pad_mean = float((dev_rows - sizes.sum()) / dev_rows) if dev_rows else 0.0
    size_hist: dict[int, int] = {}
    for k in flush_sizes:
        size_hist[int(k)] = size_hist.get(int(k), 0) + 1
    # token-denominated waste: fraction of device tokens that were padding
    # (the packed-vs-bucketed A/B compares this, not row pad)
    tokens_requested = sum(r for r, _ in flush_tokens)
    tokens_device = sum(d for _, d in flush_tokens)
    token_pad_mean = (
        round((tokens_device - tokens_requested) / tokens_device, 4)
        if tokens_device
        else None
    )

    per_tenant: dict[str, dict] = {}
    try:
        rows = read_journal(args.access_log) if args.access_log else []
    except FileNotFoundError:
        rows = []
    req_rows = [r for r in rows if r.get("type") == "request"]
    for r in req_rows:
        t = per_tenant.setdefault(
            r.get("tenant", "?"),
            {"class": r.get("class"), "requests": 0, "ok": 0, "shed": 0,
             "lat_ms": []},
        )
        t["requests"] += 1
        if r["outcome"] == "ok":
            t["ok"] += 1
            t["lat_ms"].append(r["lat_ms"])
        elif r["outcome"] == "shed":
            t["shed"] += 1
    for t in per_tenant.values():
        lats = sorted(t.pop("lat_ms"))
        t["p50_ms"] = round(lats[len(lats) // 2], 2) if lats else None
        t["p99_ms"] = (
            round(lats[min(len(lats) - 1, int(0.99 * len(lats)))], 2)
            if lats
            else None
        )
    cost = meter.snapshot()
    for name, bill in cost["tenants"].items():
        t = per_tenant.setdefault(
            name,
            {"class": bill["class"], "requests": 0, "ok": 0, "shed": 0,
             "p50_ms": None, "p99_ms": None},
        )
        t["device_s"] = round(bill["device_s"], 4)
        t["flops"] = bill["flops"]
        t["waste_device_s"] = round(bill["waste_device_s"], 4)
        t["cost_share"] = round(bill["share"], 4)
        if "budget_device_s" in bill:
            t["budget_device_s"] = bill["budget_device_s"]
            t["over_budget"] = bill["over_budget"]

    # per-resolution columns: join the access log back to each arrival's
    # seeded size draw via the rid the scheduler stamped on the future
    per_resolution: dict[int, dict] = {}
    if rid_info:
        for r in req_rows:
            info = rid_info.get(r.get("rid"))
            if info is None or info["size"] is None:
                continue
            col = per_resolution.setdefault(
                info["size"],
                {"requests": 0, "ok": 0, "shed": 0, "tokens_billed": 0,
                 "lat_ms": []},
            )
            col["requests"] += 1
            if r["outcome"] == "ok":
                col["ok"] += 1
                col["lat_ms"].append(r["lat_ms"])
                col["tokens_billed"] += int(r.get("tokens") or 0)
            elif r["outcome"] == "shed":
                col["shed"] += 1
        for col in per_resolution.values():
            lats = sorted(col.pop("lat_ms"))
            col["p50_ms"] = round(lats[len(lats) // 2], 2) if lats else None
            col["p99_ms"] = (
                round(lats[min(len(lats) - 1, int(0.99 * len(lats)))], 2)
                if lats
                else None
            )
    task_hist: dict[str, int] = {}
    for info in rid_info.values():
        if info["task"]:
            task_hist[info["task"]] = task_hist.get(info["task"], 0) + 1

    all_lat = sorted(
        r["lat_ms"] for r in req_rows if r["outcome"] == "ok"
    )

    def q(p: float):
        if not all_lat:
            return None
        return round(all_lat[min(len(all_lat) - 1, int(p * len(all_lat)))], 2)

    interactive_ok = True
    slo_report = None
    if slo_tracker is not None:
        slo_report = slo_tracker.evaluate()
        inter = {t.name for t in tenants if t.tclass == "interactive"}
        for obj in slo_tracker.objectives:
            if obj.percentile is None:
                continue
            for name in inter:
                p99 = per_tenant.get(name, {}).get("p99_ms")
                if p99 is not None and p99 > obj.threshold:
                    interactive_ok = False

    result = {
        "profile": args.profile,
        "scheduler": sched_label,
        "seed": args.seed,
        "duration_s": round(wall, 3),
        "offered": len(schedule),
        "ok": ok,
        "shed_at_submit": shed,
        "failed": failed,
        "unresolved": len(not_done),
        # in-flight drops: admitted requests the pool abandoned (anything
        # failed that is not an admission shed or a deadline miss)
        "dropped_in_flight": sum(
            1 for r in req_rows
            if r["outcome"] in ("aborted", "shutdown")
        ),
        "req_per_sec": round(ok / wall, 2) if wall > 0 else 0.0,
        "p50_ms": q(0.50),
        "p99_ms": q(0.99),
        "occupancy_mean": round(occupancy_mean, 4),
        "pad_mean": round(pad_mean, 4),
        "token_pad_mean": token_pad_mean,
        "tokens_requested": tokens_requested or None,
        "tokens_device": tokens_device or None,
        "pack_budget": pack_budget or None,
        "batches": len(flush_sizes),
        "size_hist": {k: size_hist[k] for k in sorted(size_hist)},
        "per_resolution": {
            str(k): per_resolution[k] for k in sorted(per_resolution)
        },
        "task_hist": {k: task_hist[k] for k in sorted(task_hist)},
        "tenants": per_tenant,
        "cost": {
            "total_batches": cost["total_batches"],
            "total_device_s": round(cost["total_device_s"], 4),
            "total_flops": cost["total_flops"],
            "chip": cost.get("chip"),
        },
        "admission": admission.stats(),
        "autoscale_events": (
            list(autoscaler.events) if autoscaler is not None else []
        ),
        "interactive_slo_ok": interactive_ok,
        "slo": slo_report,
    }
    tok_note = (
        f" token_pad={result['token_pad_mean']}" if token_mode else ""
    )
    print(
        f"[loadgen] ok={ok} shed_at_submit={shed} "
        f"failed={failed} occ={result['occupancy_mean']} "
        f"pad={result['pad_mean']}{tok_note} p99={result['p99_ms']}ms "
        f"device_s={result['cost']['total_device_s']} "
        f"autoscale_events={len(result['autoscale_events'])}"
    )

    history = resolve_history_path(args.bench_history)
    if history is not None and ok:
        total_dev_s = cost["total_device_s"]
        legs = {
            "req_per_sec": result["req_per_sec"],
            "p50_ms": result["p50_ms"],
            "p99_ms": result["p99_ms"],
            "occupancy_mean": result["occupancy_mean"],
            # cost efficiency: work delivered per metered device-second —
            # perf_doctor gates this next to throughput
            "device_s_total": round(total_dev_s, 4),
            "ok_per_device_s": (
                round(ok / total_dev_s, 2) if total_dev_s > 0 else 0.0
            ),
        }
        for name, bill in cost["tenants"].items():
            legs[f"device_s_{name}"] = round(bill["device_s"], 4)
        extra = {
            "pad_mean": result["pad_mean"],
            "waste_device_s": round(
                sum(
                    b["waste_device_s"] for b in cost["tenants"].values()
                ),
                4,
            ),
            "profile": args.profile,
            "scheduler": sched_label,
            "seed": args.seed,
        }
        if token_mode:
            # the packed-vs-bucketed A/B legs: same seed, same mixed load,
            # compared on token pad + billed waste by tools/perf_doctor.py
            extra["token_pad_mean"] = result["token_pad_mean"]
            extra["pack_budget"] = pack_budget or None
            extra["resolutions"] = args.resolutions or None
        row = make_row(
            bench="serve",
            metric=f"loadgen_{args.profile}_{sched_label}",
            legs=legs,
            quantiles={"p50_ms": result["p50_ms"], "p99_ms": result["p99_ms"]},
            extra=extra,
        )
        if append_row(history, row):
            print(f"[loadgen] ledger row -> {history}")

    if args.metrics_out:
        from jumbo_mae_tpu_tpu.obs.metrics import get_registry

        mpath = Path(args.metrics_out)
        mpath.parent.mkdir(parents=True, exist_ok=True)
        mpath.write_text(get_registry().render())
        print(f"[loadgen] metrics -> {mpath}")

    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(result, indent=2, default=str))
        print(f"[loadgen] report -> {out}")
    return result


if __name__ == "__main__":
    main()
