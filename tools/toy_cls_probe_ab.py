"""Round-5 experiment driver: CLS-concat vs GAP linear probes on the toy
distribution, across pretraining lengths and probe optimizers.

The reference's reproduced ImageNet numbers flow through the CLS-concat
probe (/root/reference/src/modeling.py:269-274 — three CLS tokens
concatenated, BatchNorm, linear head), but round 4's toy learning proof
certified only GAP pooling (CLS read ~chance after 600 pretrain steps).
This script measures what it takes for the CLS probe to clear chance, so
the slow test can assert it with evidence-backed thresholds.

Usage: python tools/toy_cls_probe_ab.py [--steps 600,2400] [--out /tmp/ab]
Writes one JSON line per (pt_steps, pooling, optimizer) cell.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _base_overrides(tmp, shards):
    return [
        f"data.train_shards={shards['train']}",
        f"data.valid_shards={shards['val']}",
        "data.image_size=32",
        "data.crop_mode=none",
        "data.hflip=0.0",
        "data.workers=0",
        f"data.valid_cache={tmp}/valcache",
        "run.synthetic_data=false",
        "run.use_wandb=false",
        "run.sanity_eval=false",
        "model.preset=vit_t16",
    ]


def pretrain(
    tmp,
    shards,
    steps: int,
    *,
    dec_heads: int = 4,
    nu_dtype: str | None = None,
    seed: int = 0,
    name: str | None = None,
) -> tuple[str, float]:
    """MAE-pretrain on the toy shards; returns (ckpt path, final val loss).

    ``dec_heads`` / ``nu_dtype`` are the round-5 convergence-A/B knobs
    (VERDICT r4 #5): the production perf options under test are
    ``model.dec_heads=2`` (53–54% MFU ladder) and
    ``optim.nu_dtype=bfloat16``; the toy analog of the head ladder is
    4 → 1 at dec dim 64 (minimum heads / maximum head_dim, same params
    and FLOPs, like 16 → 2 at dim 512)."""
    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.data.toy import toy_pretrain_hparams

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    name = name or f"pt{steps}"
    extra = [
        f"run.output_dir={tmp}/{name}",
        f"run.name={name}",
    ] + toy_pretrain_hparams(
        steps, dec_heads=dec_heads, seed=seed, nu_dtype=nu_dtype
    )
    m = train(load_config(recipe, _base_overrides(tmp, shards) + extra))
    return f"{tmp}/{name}/{name}/ckpt", float(m["val/loss"])


def probe(
    tmp,
    shards,
    name: str,
    *,
    pooling: str,
    optimizer: str,
    lr: float,
    steps: int = 400,
    pretrained: str | None = None,
) -> float:
    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    extra = [
        f"run.output_dir={tmp}/{name}",
        f"run.name={name}",
        "run.mode=linear",
        f"run.training_steps={steps}",
        "run.train_batch_size=64",
        "run.valid_batch_size=64",
        f"run.eval_interval={steps}",
        "run.log_interval=200",
        "model.overrides={image_size: 32, patch_size: 4, layers: 4, "
        "posemb: sincos2d, dtype: float32, labels: 10, pooling: "
        + pooling
        + "}",
        "model.criterion=ce",
        f"optim.name={optimizer}",
        f"optim.learning_rate={lr}",
        "optim.lr_scaling=none",
        "optim.momentum=0.9",
        "optim.warmup_steps=0",
        f"optim.training_steps={steps}",
    ]
    if pretrained:
        extra.append(f"run.pretrained_ckpt={pretrained}")
    m = train(load_config(recipe, _base_overrides(tmp, shards) + extra))
    return float(m["val/acc1"])


def knob_ab(tmp, shards, seeds: list[int]) -> list[dict]:
    """Convergence A/B for the numerics-changing perf knobs (VERDICT r4
    #5): matched-steps toy pretrain per arm, GAP probe at the established
    400-step operating point, plus the pretrain val loss as a secondary
    signal. Arms: baseline (heads=4, f32 nu), min-heads (heads=1 — the
    toy analog of the production dec_heads=2 ladder), nu_dtype=bfloat16."""
    arms = [
        ("baseline_h4", dict(dec_heads=4)),
        ("minheads_h1", dict(dec_heads=1)),
        ("nu_bf16", dict(dec_heads=4, nu_dtype="bfloat16")),
    ]
    results = []
    for arm, kw in arms:
        for seed in seeds:
            name = f"{arm}_s{seed}"
            ckpt, val_loss = pretrain(
                tmp, shards, 600, seed=seed, name=name, **kw
            )
            acc = probe(
                tmp, shards, f"probe_{name}",
                pooling="gap", optimizer="sgd", lr=0.3, pretrained=ckpt,
            )
            row = {"arm": arm, "seed": seed, "pt_val_loss": val_loss, "gap_probe_acc1": acc}
            results.append(row)
            print("RESULT", json.dumps(row), flush=True)
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", default="600,2400")
    ap.add_argument("--out", default="/tmp/toy_cls_ab")
    ap.add_argument("--probes", default="cls:lars:0.3,cls:sgd:0.3,gap:sgd:0.3")
    ap.add_argument("--knob-ab", action="store_true",
                    help="run the dec_heads / nu_dtype convergence A/B instead")
    ap.add_argument("--seeds", default="0,1")
    args = ap.parse_args()

    from jumbo_mae_tpu_tpu.data.toy import write_toy_shards

    tmp = Path(args.out)
    tmp.mkdir(parents=True, exist_ok=True)
    shards = write_toy_shards(tmp / "shards", n_train=2048, n_val=512)

    if args.knob_ab:
        results = knob_ab(tmp, shards, [int(s) for s in args.seeds.split(",")])
        print(json.dumps(results, indent=2))
        return

    results = []
    for steps in [int(s) for s in args.steps.split(",")]:
        ckpt, _ = pretrain(tmp, shards, steps)
        for spec in args.probes.split(","):
            pooling, opt, lr = spec.split(":")
            acc = probe(
                tmp,
                shards,
                f"probe_{steps}_{pooling}_{opt}",
                pooling=pooling,
                optimizer=opt,
                lr=float(lr),
                pretrained=ckpt,
            )
            row = {
                "pt_steps": steps,
                "pooling": pooling,
                "optimizer": opt,
                "lr": float(lr),
                "acc1": acc,
            }
            results.append(row)
            print("RESULT", json.dumps(row), flush=True)

    print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
