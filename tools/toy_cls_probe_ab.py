"""Round-5 experiment driver: CLS-concat vs GAP linear probes on the toy
distribution, across pretraining lengths and probe optimizers.

The reference's reproduced ImageNet numbers flow through the CLS-concat
probe (/root/reference/src/modeling.py:269-274 — three CLS tokens
concatenated, BatchNorm, linear head), but round 4's toy learning proof
certified only GAP pooling (CLS read ~chance after 600 pretrain steps).
This script measures what it takes for the CLS probe to clear chance, so
the slow test can assert it with evidence-backed thresholds.

Usage: python tools/toy_cls_probe_ab.py [--steps 600,2400] [--out /tmp/ab]
Writes one JSON line per (pt_steps, pooling, optimizer) cell.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _base_overrides(tmp, shards):
    return [
        f"data.train_shards={shards['train']}",
        f"data.valid_shards={shards['val']}",
        "data.image_size=32",
        "data.crop_mode=none",
        "data.hflip=0.0",
        "data.workers=0",
        f"data.valid_cache={tmp}/valcache",
        "run.synthetic_data=false",
        "run.use_wandb=false",
        "run.sanity_eval=false",
        "model.preset=vit_t16",
    ]


def pretrain(tmp, shards, steps: int) -> str:
    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    name = f"pt{steps}"
    cfg = load_config(
        recipe,
        _base_overrides(tmp, shards)
        + [
            f"run.output_dir={tmp}/{name}",
            f"run.name={name}",
            "run.mode=pretrain",
            f"run.training_steps={steps}",
            "run.train_batch_size=64",
            "run.valid_batch_size=64",
            f"run.eval_interval={steps}",
            "run.log_interval=200",
            "model.overrides={image_size: 32, patch_size: 4, layers: 4, posemb: sincos2d, dtype: float32, mask_ratio: 0.75}",
            "model.dec_layers=2",
            "model.dec_dim=64",
            "model.dec_heads=4",
            "model.dec_dtype=float32",
            "optim.learning_rate=1.5e-3",
            "optim.lr_scaling=none",
            "optim.warmup_steps=40",
            f"optim.training_steps={steps}",
            "optim.b2=0.95",
            "optim.weight_decay=0.05",
        ],
    )
    train(cfg)
    return f"{tmp}/{name}/{name}/ckpt"


def probe(
    tmp,
    shards,
    name: str,
    *,
    pooling: str,
    optimizer: str,
    lr: float,
    steps: int = 400,
    pretrained: str | None = None,
) -> float:
    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    extra = [
        f"run.output_dir={tmp}/{name}",
        f"run.name={name}",
        "run.mode=linear",
        f"run.training_steps={steps}",
        "run.train_batch_size=64",
        "run.valid_batch_size=64",
        f"run.eval_interval={steps}",
        "run.log_interval=200",
        "model.overrides={image_size: 32, patch_size: 4, layers: 4, "
        "posemb: sincos2d, dtype: float32, labels: 10, pooling: "
        + pooling
        + "}",
        "model.criterion=ce",
        f"optim.name={optimizer}",
        f"optim.learning_rate={lr}",
        "optim.lr_scaling=none",
        "optim.momentum=0.9",
        "optim.warmup_steps=0",
        f"optim.training_steps={steps}",
    ]
    if pretrained:
        extra.append(f"run.pretrained_ckpt={pretrained}")
    m = train(load_config(recipe, _base_overrides(tmp, shards) + extra))
    return float(m["val/acc1"])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", default="600,2400")
    ap.add_argument("--out", default="/tmp/toy_cls_ab")
    ap.add_argument("--probes", default="cls:lars:0.3,cls:sgd:0.3,gap:sgd:0.3")
    args = ap.parse_args()

    from jumbo_mae_tpu_tpu.data.toy import write_toy_shards

    tmp = Path(args.out)
    tmp.mkdir(parents=True, exist_ok=True)
    shards = write_toy_shards(tmp / "shards", n_train=2048, n_val=512)

    results = []
    for steps in [int(s) for s in args.steps.split(",")]:
        ckpt = pretrain(tmp, shards, steps)
        for spec in args.probes.split(","):
            pooling, opt, lr = spec.split(":")
            acc = probe(
                tmp,
                shards,
                f"probe_{steps}_{pooling}_{opt}",
                pooling=pooling,
                optimizer=opt,
                lr=float(lr),
                pretrained=ckpt,
            )
            row = {
                "pt_steps": steps,
                "pooling": pooling,
                "optimizer": opt,
                "lr": float(lr),
                "acc1": acc,
            }
            results.append(row)
            print("RESULT", json.dumps(row), flush=True)

    print(json.dumps(results, indent=2))


if __name__ == "__main__":
    main()
