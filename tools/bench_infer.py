#!/usr/bin/env python3
"""Two-leg inference benchmark: naive per-request jit vs the batched engine.

Same discipline as the training bench (``bench.py``): both legs run the
identical forward (the feature head by default) on the identical request
stream — N single-image requests — and the JSON line reports throughput,
latency percentiles, and compile counts for each leg:

- **naive** — what a server without the engine does: one ``jax.jit``
  forward per request at the request's own shape, dispatched serially.
  Compiles lazily on the hot path (the first request pays it; a new shape
  would pay it again) and wastes the MXU on batch-1 matmuls.
- **engine** — requests submitted concurrently through the micro-batching
  queue (``max_delay_ms``, ``max_batch``), coalesced into power-of-two
  buckets served by AOT-compiled executables, all compiled during an
  explicit warmup; the measured window recompiles nothing
  (``recompiles_after_warmup`` is asserted into the JSON).
- **engine_int8** (``--quant int8``, the default) — the engine leg again
  with weight-only int8 kernels; the report carries the measured parity
  (feature cosine / top-1 agreement vs the f32 leg) next to the speedup,
  so the accuracy cost of the throughput win is never quoted separately.

``--warm-start on`` (default) additionally runs the persistent-warmup A/B:
two fresh subprocesses (``python -m jumbo_mae_tpu_tpu.infer.warmcache``)
against one empty cache dir — the first compiles and publishes, the second
must report ``compiles: 0`` — and records cold vs warm startup seconds.

    python tools/bench_infer.py                         # CPU smoke config
    python tools/bench_infer.py recipes/finetune_vit_b16.yaml --ckpt C \
        --task logits --requests 2048 --max-batch 64    # chip numbers

Env-free by design — every knob is a flag; PERF.md §Inference records the
methodology and numbers.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "recipe",
        nargs="?",
        default=None,
        help="YAML recipe (default: the CPU smoke profile — smoke_cpu.yaml "
        "at patch 16, a per-request-overhead-dominated micro config that "
        "isolates the coalescing mechanism on hosts where big batches are "
        "compute-bound; chip numbers use real recipes)",
    )
    p.add_argument("--ckpt", default="", help="checkpoint (random init if omitted)")
    p.add_argument(
        "--task", choices=("features", "logits", "reconstruct"), default="features"
    )
    p.add_argument("--requests", type=int, default=1024, help="stream length")
    p.add_argument("--clients", type=int, default=8, help="concurrent submitters")
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument(
        "--rounds",
        type=int,
        default=3,
        help="best-of-N throughput rounds per leg (same convention as the "
        "training bench — shields the ratio from scheduler noise)",
    )
    p.add_argument("--max-delay-ms", type=float, default=2.0)
    p.add_argument("--dtype", default=None, help="compute dtype override")
    p.add_argument(
        "--telemetry",
        choices=("on", "off"),
        default="on",
        help="off swaps the default registry for the no-op NullRegistry "
        "before any engine/batcher construction — the A/B leg PERF.md's "
        "exporter-overhead number comes from",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="expose /metrics + /healthz during the bench (0 = any free "
        "port); the final scrape is summarized into the JSON report",
    )
    p.add_argument("--naive-requests", type=int, default=0,
                   help="naive-leg stream length (default: min(requests, 128); "
                   "the serial leg is slow by construction)")
    p.add_argument(
        "--quant",
        choices=("int8", "off"),
        default="int8",
        help="run the third (weight-only quantized) engine leg and report "
        "its throughput + parity vs the f32/bf16 leg",
    )
    p.add_argument(
        "--parity-images",
        type=int,
        default=64,
        metavar="N",
        help="sample size for the quant parity check (capped at --requests)",
    )
    p.add_argument(
        "--warm-start",
        choices=("on", "off"),
        default="on",
        help="run the persistent-warmup A/B: two fresh subprocesses against "
        "one empty cache dir; the second must load every executable "
        "(compiles=0) instead of compiling",
    )
    p.add_argument("--out", default="", help="also write the JSON here")
    p.add_argument(
        "--history",
        default=None,
        metavar="PATH",
        help="perf ledger to append one schema-versioned row to (default: "
        "$BENCH_HISTORY or ./BENCH_HISTORY.jsonl; 'off' disables)",
    )
    p.add_argument(
        "--set",
        dest="overrides",
        metavar="KEY.PATH=VALUE",
        nargs="*",
        action="extend",
        default=[],
        help="dotted config overrides, same grammar as cli.train",
    )
    return p


def _percentiles(lat_s: list[float]) -> dict:
    import numpy as np

    ms = np.asarray(lat_s) * 1000.0
    return {
        # exact quantiles over the raw per-request samples — NOT the
        # LATENCY_BUCKETS-quantized Histogram.quantile readout, whose
        # bucket-edge resolution is fine for dashboards but too coarse for
        # a bench's A/B deltas
        "p50_ms": round(float(np.percentile(ms, 50)), 3),
        "p99_ms": round(float(np.percentile(ms, 99)), 3),
        "mean_ms": round(float(ms.mean()), 3),
        "quantile_source": "exact_samples",
    }


def _trace_summary(rows: list) -> dict:
    """Per-leg trace summary: outcome counts + mean per-leg milliseconds
    over the finished traces (queue wait / coalescing / compute / fetch)."""
    out: dict = {"requests": len(rows), "outcomes": {}}
    for tr in rows:
        out["outcomes"][tr.outcome] = out["outcomes"].get(tr.outcome, 0) + 1
    for name in ("queue_wait_s", "admission_s", "compute_s", "fetch_s"):
        vals = [getattr(tr, name) for tr in rows if getattr(tr, name) is not None]
        if vals:
            out[f"mean_{name[:-2]}_ms"] = round(
                sum(vals) / len(vals) * 1000.0, 3
            )
    return out


def main(argv: list[str] | None = None) -> dict:
    args = build_parser().parse_args(argv)

    import concurrent.futures

    import jax
    import numpy as np

    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.infer import InferenceEngine, MicroBatcher
    from jumbo_mae_tpu_tpu.obs import NULL_REGISTRY, TelemetryServer, set_registry

    if args.telemetry == "off":
        # must happen before the engine/batcher resolve their handles
        set_registry(NULL_REGISTRY)
    telemetry = None
    if args.metrics_port is not None:
        telemetry = TelemetryServer(port=args.metrics_port).start()
        print(f"[bench] exporter on :{telemetry.port}", file=sys.stderr)

    recipe = args.recipe
    overrides = list(args.overrides)
    if recipe is None:
        recipe = str(REPO / "recipes" / "smoke_cpu.yaml")
        # the smoke profile: few tokens per image, so per-request dispatch
        # and sub-SIMD batch-1 GEMMs — the costs coalescing removes — are
        # the dominant term even on a small CPU host
        overrides = ["model.overrides.patch_size=16"] + overrides
    cfg = load_config(recipe, overrides)
    # warm_cache=False everywhere: the bench measures the compile behavior
    # itself, so a populated host cache must not short-circuit the legs —
    # the persistent cache gets its own A/B below (--warm-start)
    engine = InferenceEngine(
        cfg,
        ckpt=args.ckpt,
        dtype=args.dtype,
        max_batch=args.max_batch,
        warm_cache=False,
    )
    size = engine.image_size
    rs = np.random.RandomState(0)
    images = rs.randint(0, 256, (args.requests, size, size, 3)).astype(np.uint8)
    kw = {"seed": 0} if args.task == "reconstruct" else {}

    # ---- naive leg: serial per-request jit dispatch at batch 1 ----------
    t = engine._task(args.task if args.task != "features" else "features")
    fn = engine._fn(args.task, "cls" if args.task == "features" else None)
    naive_fwd = jax.jit(fn)
    n_naive = args.naive_requests or min(args.requests, 128)
    extra = (np.int32(0),) if args.task == "reconstruct" else ()
    # one untimed call so the measured window shows steady-state dispatch
    # (the compile itself is reported separately below)
    t0 = time.perf_counter()
    jax.block_until_ready(naive_fwd(t["variables"], images[:1], *extra))
    naive_compile_s = time.perf_counter() - t0
    fetch = (
        (lambda o: {k: np.asarray(v) for k, v in o.items()})
        if args.task == "reconstruct"
        else np.asarray
    )
    lat = []
    naive_wall = float("inf")
    for _ in range(max(1, args.rounds)):
        t0 = time.perf_counter()
        for i in range(n_naive):
            r0 = time.perf_counter()
            fetch(naive_fwd(t["variables"], images[i : i + 1], *extra))
            lat.append(time.perf_counter() - r0)
        naive_wall = min(naive_wall, time.perf_counter() - t0)
    naive = {
        "requests": n_naive,
        "imgs_per_sec": round(n_naive / naive_wall, 2),
        **_percentiles(lat),
        "compiles": int(naive_fwd._cache_size()),
        "first_request_compile_ms": round(naive_compile_s * 1000.0, 1),
    }

    # ---- engine leg: request stream through the micro-batcher -----------
    # Two phases, because the two numbers answer different questions.
    # Throughput: open-loop — the full stream enqueued as it arrives (an
    # async server's event loop), wall time to drain it. Closed-loop
    # clients would measure THREAD WAKEUP cost, not the engine: on a
    # 1-core host, N blocking clients each pay a context switch per
    # response. Latency: closed-loop with --clients concurrent blocking
    # callers over a slice of the stream — each request's submit→result
    # time under moderate concurrency, the number an operator quotes.
    def engine_leg(eng_obj, *, traced: bool) -> dict:
        compiles_warm = eng_obj.warmup((args.task,), buckets=None)
        warm_counts = dict(eng_obj.compile_counts)

        def run_batch(batch):
            return eng_obj.predict(batch, task=args.task, **kw)

        # with telemetry on, the (traced) leg runs fully instrumented —
        # per-request contexts + engine breakdown; the measured cost IS the
        # tracing overhead the off leg A/Bs against
        trace_rows: list = []
        tracer = None
        if traced and args.telemetry == "on":
            from jumbo_mae_tpu_tpu.obs import RequestTracer

            tracer = RequestTracer(
                breakdown=eng_obj.last_breakdown, on_finish=trace_rows.append
            )

        with MicroBatcher(
            run_batch,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            tracer=tracer,
            task=args.task,
        ) as mb:
            engine_wall = float("inf")
            for _ in range(max(1, args.rounds)):
                t0 = time.perf_counter()
                futs = [mb.submit(img) for img in images]
                # FIFO batcher: the last future resolves last — one waiter
                # instead of one condition registration per request
                futs[-1].result()
                engine_wall = min(engine_wall, time.perf_counter() - t0)
            sizes = list(mb.batch_sizes)

            n_lat = min(args.requests, 256)
            lat = [0.0] * n_lat

            def client(idx):
                r0 = time.perf_counter()
                mb.submit(images[idx]).result()
                lat[idx] = time.perf_counter() - r0

            with concurrent.futures.ThreadPoolExecutor(args.clients) as pool:
                list(pool.map(client, range(n_lat)))

        recompiles = (
            sum(eng_obj.compile_counts.values()) - sum(warm_counts.values())
        )
        leg = {
            "requests": args.requests,
            "imgs_per_sec": round(args.requests / engine_wall, 2),
            **_percentiles(lat),
            "latency_requests": n_lat,
            "latency_clients": args.clients,
            "warmup_compiles": compiles_warm,
            "recompiles_after_warmup": recompiles,
            "mean_batch": round(float(np.mean(sizes)), 2),
            "batches": len(sizes),
        }
        if tracer is not None:
            leg["trace"] = _trace_summary(trace_rows)
        return leg

    eng = engine_leg(engine, traced=True)
    if "trace" in eng:
        # the registry's bucket-edge readout, kept alongside the exact
        # numbers and explicitly marked approximate
        from jumbo_mae_tpu_tpu.obs import get_registry

        hist = get_registry().histogram(
            "infer_request_latency_seconds",
            "request latency: submit() to resolved future",
        )
        for label, q in (("hist_p50_ms", 0.5), ("hist_p99_ms", 0.99)):
            v = hist.quantile(q) * 1000.0
            eng[label] = round(v, 3) if v != float("inf") else "inf"
        eng["hist_quantile_source"] = "bucket_edges_approximate"

    # ---- int8 leg: same stream, weight-only quantized kernels -----------
    eng_q = None
    parity = None
    if args.quant == "int8":
        from jumbo_mae_tpu_tpu.infer import parity_report

        engine_q = InferenceEngine(
            cfg,
            ckpt=args.ckpt,
            dtype=args.dtype,
            max_batch=args.max_batch,
            quant="int8",
            warm_cache=False,
        )
        eng_q = engine_leg(engine_q, traced=False)
        base = args.task.split(".", 1)[0]
        rep = engine_q._task(base).get("quant_report")
        if rep:
            eng_q["quant"] = {
                k: rep[k]
                for k in ("n_quantized", "n_kept", "bytes_before",
                          "bytes_after", "compression")
            }
        # parity is measured against the SAME reference engine the f32/bf16
        # leg ran — logits tasks compare top-1 agreement, everything else
        # compares pooled-feature cosine
        parity = parity_report(
            engine,
            engine_q,
            images[: min(args.parity_images, args.requests)],
            task="logits" if args.task == "logits" else "features",
        )

    # ---- persistent-warmup A/B: cold process vs restarted process -------
    warm_start = None
    if args.warm_start == "on":
        import subprocess
        import tempfile

        probe_cmd = [
            sys.executable, "-m", "jumbo_mae_tpu_tpu.infer.warmcache",
            "--task", args.task,
            "--max-batch", str(min(args.max_batch, 8)),
            "--recipe", str(recipe),
        ]
        if args.ckpt:
            probe_cmd += ["--ckpt", args.ckpt]
        if args.dtype:
            probe_cmd += ["--dtype", args.dtype]
        if overrides:
            probe_cmd += ["--set", *overrides]
        with tempfile.TemporaryDirectory(prefix="jumbo-warmstart-") as d:
            runs = {}
            for phase in ("cold", "warm"):
                proc = subprocess.run(
                    probe_cmd + ["--dir", d],
                    capture_output=True, text=True, timeout=900,
                )
                if proc.returncode != 0:
                    print(proc.stderr, file=sys.stderr)
                    raise SystemExit(
                        f"warm-start probe ({phase}) failed rc={proc.returncode}"
                    )
                rows = [
                    ln for ln in proc.stdout.splitlines()
                    if ln.startswith("{")
                ]
                runs[phase] = json.loads(rows[-1])
        cold, warm = runs["cold"], runs["warm"]
        keep = ("init_s", "warmup_s", "compiles", "warm_hits",
                "hot_path_compiles")
        warm_start = {
            "cold": {k: cold[k] for k in keep},
            "warm": {k: warm[k] for k in keep},
            # the contract CI asserts: a restarted replica performs zero
            # compiles — warmup and hot path both served from the cache
            "warm_reused": (
                warm["compiles"] == 0
                and warm["hot_path_compiles"] == 0
                and warm["warm_hits"] >= cold["compiles"]
            ),
            "warmup_speedup": round(
                cold["warmup_s"] / max(warm["warmup_s"], 1e-9), 2
            ),
        }

    report = {
        "bench": "infer",
        "task": args.task,
        "model": cfg.model.preset,
        "image_size": size,
        "backend": jax.default_backend(),
        "max_batch": args.max_batch,
        "max_delay_ms": args.max_delay_ms,
        "clients": args.clients,
        "telemetry": args.telemetry,
        "naive": naive,
        "engine": eng,
        "speedup": round(eng["imgs_per_sec"] / naive["imgs_per_sec"], 2),
    }
    if eng_q is not None:
        report["engine_int8"] = eng_q
        report["quant_parity"] = parity
        report["speedup_int8"] = round(
            eng_q["imgs_per_sec"] / naive["imgs_per_sec"], 2
        )
        report["int8_vs_base"] = round(
            eng_q["imgs_per_sec"] / eng["imgs_per_sec"], 3
        )
    if warm_start is not None:
        report["warm_start"] = warm_start
    if telemetry is not None:
        # scrape over the real socket — the same path an external Prometheus
        # takes — and record proof-of-life in the report
        from urllib.request import urlopen

        with urlopen(
            f"http://127.0.0.1:{telemetry.port}/metrics", timeout=10
        ) as resp:
            scrape = resp.read().decode()
        keys = (
            "infer_request_latency_seconds",
            "infer_batch_occupancy",
            "infer_bucket_cache_hits_total",
            "infer_bucket_cache_misses_total",
        )
        report["metrics"] = {
            "scrape_lines": len(scrape.splitlines()),
            "families_seen": [k for k in keys if k in scrape],
        }
        telemetry.close()
    _append_ledger(args, report, engine)
    line = json.dumps(report)
    print(line)
    if args.out:
        Path(args.out).write_text(line + "\n")
    return report


def _append_ledger(args, report: dict, engine) -> None:
    """One BENCH_HISTORY.jsonl row for this bench: per-leg throughput, the
    engine leg's exact latency quantiles, and the roofline prediction of the
    largest-bucket executable (from the engine's compile-time cost reports).
    Best-effort; the one-JSON-line stdout contract is unaffected."""
    try:
        from jumbo_mae_tpu_tpu.obs.perfledger import (
            append_row,
            make_row,
            resolve_history_path,
        )

        path = resolve_history_path(args.history)
        if path is None:
            return
        legs = {"naive_imgs_per_sec": report["naive"]["imgs_per_sec"],
                "engine_imgs_per_sec": report["engine"]["imgs_per_sec"]}
        if report.get("engine_int8"):
            legs["engine_int8_imgs_per_sec"] = report["engine_int8"][
                "imgs_per_sec"
            ]
        quantiles = {
            k: report["engine"][k]
            for k in ("p50_ms", "p99_ms", "mean_ms")
            if isinstance(report["engine"].get(k), (int, float))
        }
        prediction = None
        if getattr(engine, "cost_reports", None):
            from jumbo_mae_tpu_tpu.obs.costmodel import cost_asdict
            from jumbo_mae_tpu_tpu.obs.perfmodel import (
                detect_chip,
                prediction_asdict,
                roofline,
            )

            key = max(engine.cost_reports, key=lambda k: k[1])
            cost = engine.cost_reports[key]
            pred = roofline(
                cost.flops,
                cost.bytes_accessed,
                detect_chip(),
                batch=key[1],
                peak_hbm_bytes=cost.peak_bytes,
            )
            prediction = prediction_asdict(pred) | {
                "program": f"{key[0]}/b{key[1]}",
                "cost": cost_asdict(cost),
            }
        metric = (
            f"infer_{report['model']}_{report['image_size']}_"
            f"{report['task']}_imgs_per_sec"
        )
        row = make_row(
            bench="infer",
            metric=metric,
            legs=legs,
            quantiles=quantiles,
            prediction=prediction,
            extra={"max_batch": report["max_batch"]},
        )
        if append_row(path, row):
            print(f"bench_infer: ledger row -> {path}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — the ledger must not fail a bench
        print(f"bench_infer: ledger append failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
