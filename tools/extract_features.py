#!/usr/bin/env python3
"""Export frozen jumbo-encoder features for a dataset split.

Beyond the reference (which has no feature-export path — its frozen-trunk
consumers are the inline linear/finetune modes, ``/root/reference/src/
main_finetune.py``): restore a checkpoint once through the batched
inference engine (``jumbo_mae_tpu_tpu/infer``), run the encoder
deterministically (no masking, no dropout) over the validation split — or
synthetic data — and write an ``.npz`` of pooled features plus labels
where present.

    python tools/extract_features.py recipes/linear_sgd_vit_b16.yaml \
        --ckpt runs/pretrain/ckpt --out feats.npz --pool cls \
        [--set data.valid_shards=...]

``--pool cls`` is the reference's probe representation (the 3 CLS tokens
concatenated, ``/root/reference/src/modeling.py:269-274``); ``gap`` mean-pools
the patch tokens; ``tokens`` exports the full normed token sequence.
``--ckpt`` accepts an Orbax run/checkpoint directory or a ``.msgpack`` params
file (either a pretrain tree with an ``encoder`` subtree, a classification
tree with a ``model`` subtree, or a bare encoder tree).

``extract_arrays`` is the library surface — ``tools/knn_probe.py`` calls it
to extract either side of the probe on the fly from a recipe.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("recipe", nargs="?", default=None, help="YAML recipe path")
    p.add_argument(
        "--ckpt",
        default="",
        help="Orbax checkpoint dir or .msgpack params; random init if omitted",
    )
    p.add_argument("--out", required=True, help="output .npz path")
    p.add_argument("--pool", choices=("cls", "gap", "tokens"), default="cls")
    p.add_argument(
        "--set",
        dest="overrides",
        metavar="KEY.PATH=VALUE",
        nargs="*",
        action="extend",
        default=[],
        help="dotted config overrides, same grammar as cli.train",
    )
    return p


def extract_arrays(cfg, ckpt: str, pool: str):
    """Run the recipe's validation stream through the inference engine's
    feature head; returns ``(features, labels-or-None)`` with padded/invalid
    rows dropped. Raises SystemExit on an empty stream or a checkpoint that
    loads nothing (writing random-init features would be worse)."""
    import jax
    import numpy as np

    from jumbo_mae_tpu_tpu.cli.train import make_valid_iterator
    from jumbo_mae_tpu_tpu.infer import InferenceEngine, bucket_for
    from jumbo_mae_tpu_tpu.parallel import create_mesh

    # the recipe's label count — synthetic-data label export must match the
    # recipe's class space (the engine forces its own encoder headless)
    recipe_labels = cfg.model.overrides.get("labels")
    if cfg.mesh.pipe > 1:
        # a pipeline mesh only exists for the training step; the extraction
        # stream just needs batches sharded over the devices — flatten to
        # the default data×fsdp mesh instead of failing in create_mesh
        import dataclasses

        print(
            f"[extract] NOTE: recipe requests mesh.pipe={cfg.mesh.pipe}; "
            "extraction has no pipeline stage — flattening to a data mesh"
        )
        cfg = dataclasses.replace(
            cfg,
            mesh=dataclasses.replace(
                cfg.mesh, pipe=1, pipe_microbatches=0, pipe_decoder=False
            ),
        )
    mesh = create_mesh(cfg.mesh)
    # the device-prefetch sharding needs the batch divisible by the mesh's
    # data axes — round up to the device count (same rule as reconstruct.py;
    # a recipe batch of e.g. 6 on 4 devices previously died in an opaque
    # sharding error)
    n_dev = len(jax.devices())
    per_batch = -(-max(1, cfg.run.valid_batch_size) // n_dev) * n_dev
    engine = InferenceEngine(
        cfg, ckpt=ckpt, max_batch=bucket_for(min(per_batch, 1024), 1024)
    )
    valid_factory = make_valid_iterator(
        cfg, mesh, per_batch, num_labels=recipe_labels or 1000
    )
    if valid_factory is None:
        raise SystemExit(
            "no data: set data.valid_shards or run.synthetic_data=true"
        )

    all_feats: list[np.ndarray] = []
    all_labels: list[np.ndarray] = []
    for batch in valid_factory():
        images = np.asarray(jax.device_get(batch["images"]))
        feats = engine.features(images, pool=pool)
        valid = np.asarray(
            jax.device_get(batch.get("valid", np.ones(feats.shape[0], bool)))
        ).astype(bool)
        all_feats.append(feats[valid])
        if "labels" in batch:
            labels = np.asarray(jax.device_get(batch["labels"]))
            all_labels.append(labels[valid])

    if sum(f.shape[0] for f in all_feats) == 0:
        raise SystemExit(
            "no valid samples in the stream — check data.valid_shards "
            "matches non-empty shards (or run.synthetic_data=true)"
        )
    features = np.concatenate(all_feats, axis=0)
    labels = np.concatenate(all_labels, axis=0) if all_labels else None
    return features, labels


def main(argv: list[str] | None = None) -> Path:
    args = build_parser().parse_args(argv)

    import jax
    import numpy as np

    from jumbo_mae_tpu_tpu.config import load_config

    if jax.process_count() > 1:
        raise SystemExit(
            "extract_features is a single-process tool; run it on one host"
        )

    cfg = load_config(args.recipe, args.overrides)
    features, labels = extract_arrays(cfg, args.ckpt, args.pool)

    out = Path(args.out)
    payload = {"features": features, "pool": np.asarray(args.pool)}
    if labels is not None:
        payload["labels"] = labels
    out.parent.mkdir(parents=True, exist_ok=True)
    np.savez(out, **payload)
    n, shape = features.shape[0], features.shape[1:]
    print(f"[extract] wrote {n} x {shape} {args.pool} features -> {out}")
    return out


if __name__ == "__main__":
    main()
