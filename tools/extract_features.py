#!/usr/bin/env python3
"""Export frozen jumbo-encoder features for a dataset split.

Beyond the reference (which has no feature-export path — its frozen-trunk
consumers are the inline linear/finetune modes, ``/root/reference/src/
main_finetune.py``): restore a checkpoint, run the encoder deterministically
(no masking, no dropout) over the validation split — or synthetic data —
and write an ``.npz`` of pooled features plus labels where present.

    python tools/extract_features.py recipes/linear_sgd_vit_b16.yaml \
        --ckpt runs/pretrain/ckpt --out feats.npz --pool cls \
        [--set data.valid_shards=...]

``--pool cls`` is the reference's probe representation (the 3 CLS tokens
concatenated, ``/root/reference/src/modeling.py:269-274``); ``gap`` mean-pools
the patch tokens; ``tokens`` exports the full normed token sequence.
``--ckpt`` accepts an Orbax run/checkpoint directory or a ``.msgpack`` params
file (either a pretrain tree with an ``encoder`` subtree, a classification
tree with a ``model`` subtree, or a bare encoder tree).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("recipe", nargs="?", default=None, help="YAML recipe path")
    p.add_argument(
        "--ckpt",
        default="",
        help="Orbax checkpoint dir or .msgpack params; random init if omitted",
    )
    p.add_argument("--out", required=True, help="output .npz path")
    p.add_argument("--pool", choices=("cls", "gap", "tokens"), default="cls")
    p.add_argument(
        "--set",
        dest="overrides",
        metavar="KEY.PATH=VALUE",
        nargs="*",
        action="extend",
        default=[],
        help="dotted config overrides, same grammar as cli.train",
    )
    return p


def main(argv: list[str] | None = None) -> Path:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from jumbo_mae_tpu_tpu.cli.train import make_valid_iterator
    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.models import JumboViT, pool_tokens, preset
    from jumbo_mae_tpu_tpu.ops.preprocess import normalize_images
    from jumbo_mae_tpu_tpu.parallel import create_mesh
    from jumbo_mae_tpu_tpu.train.checkpoint import (
        _ENCODER_KEYS,
        load_params_tree,
        merge_pretrained_params,
        require_loaded,
    )

    if jax.process_count() > 1:
        raise SystemExit(
            "extract_features is a single-process tool; run it on one host"
        )

    cfg = load_config(args.recipe, args.overrides)
    m = cfg.model
    # the recipe's label count (read before the head is forced off below) —
    # synthetic-data label export must match the recipe's class space
    recipe_labels = m.overrides.get("labels")
    enc_cfg = preset(
        m.preset,
        # forced last so recipe overrides (labels, mask_ratio for pretrain
        # recipes, stochastic knobs) can't re-enable a head/masking/dropout
        **{
            **m.overrides,
            "labels": None,
            "mask_ratio": None,
            "dropout": 0.0,
            "droppath": 0.0,
        },
    )
    model = JumboViT(enc_cfg)
    mesh = create_mesh(cfg.mesh)

    per_batch = max(1, cfg.run.valid_batch_size)
    size = cfg.data.image_size
    example = jnp.zeros((1, size, size, 3), jnp.uint8)
    params = model.init(
        jax.random.PRNGKey(cfg.run.init_seed),
        normalize_images(example, dtype=enc_cfg.compute_dtype),
        True,
    )["params"]
    if args.ckpt:
        from flax import serialization

        # pretrain trees keep the encoder under "encoder", classification
        # trees under "model", a bare encoder export has neither — map any
        # of the three onto this bare encoder before merging
        tree = serialization.to_state_dict(load_params_tree(args.ckpt))
        src = next((key for key in _ENCODER_KEYS if key in tree), None)
        stats: dict = {}
        merged = merge_pretrained_params(
            tree[src] if src else tree,
            serialization.to_state_dict(params),
            stats=stats,
        )
        require_loaded(stats, args.ckpt, f"the {m.preset} encoder")
        params = serialization.from_state_dict(params, merged)

    k = enc_cfg.num_cls_tokens

    @jax.jit
    def fwd(params, images):
        x = normalize_images(images, dtype=enc_cfg.compute_dtype)
        tokens = model.apply({"params": params}, x, True)
        feats = tokens if args.pool == "tokens" else pool_tokens(tokens, k, args.pool)
        return feats.astype(jnp.float32)

    valid_factory = make_valid_iterator(
        cfg, mesh, per_batch, num_labels=recipe_labels or 1000
    )
    if valid_factory is None:
        raise SystemExit(
            "no data: set data.valid_shards or run.synthetic_data=true"
        )

    all_feats: list[np.ndarray] = []
    all_labels: list[np.ndarray] = []
    for batch in valid_factory():
        feats = np.asarray(jax.device_get(fwd(params, batch["images"])))
        valid = np.asarray(
            jax.device_get(batch.get("valid", np.ones(feats.shape[0], bool)))
        ).astype(bool)
        all_feats.append(feats[valid])
        if "labels" in batch:
            labels = np.asarray(jax.device_get(batch["labels"]))
            all_labels.append(labels[valid])

    total = sum(f.shape[0] for f in all_feats)
    if total == 0:
        raise SystemExit(
            "no valid samples in the stream — check data.valid_shards "
            "matches non-empty shards (or run.synthetic_data=true)"
        )
    out = Path(args.out)
    payload = {
        "features": np.concatenate(all_feats, axis=0),
        "pool": np.asarray(args.pool),
    }
    if all_labels:
        payload["labels"] = np.concatenate(all_labels, axis=0)
    out.parent.mkdir(parents=True, exist_ok=True)
    np.savez(out, **payload)
    n, shape = payload["features"].shape[0], payload["features"].shape[1:]
    print(f"[extract] wrote {n} x {shape} {args.pool} features -> {out}")
    return out


if __name__ == "__main__":
    main()
