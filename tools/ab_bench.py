#!/usr/bin/env python3
"""A/B matrix runner over bench.py env knobs.

Runs ``bench.py`` once per configuration (cartesian product of the swept
env knobs), one subprocess each — fresh backend, no cross-run state — and
appends every result line to a JSONL log with its knobs attached. This is
how PERF.md A/B tables are produced without babysitting:

    python tools/ab_bench.py --model vit_h14 \
        --sweep BENCH_DEC_REMAT_POLICY=,dots \
        --sweep BENCH_BATCH=64,96 \
        --sweep BENCH_MU_DTYPE=,bfloat16 \
        --skip-baseline --out /tmp/h14_ab.jsonl

Each --sweep is KNOB=v1,v2,... (empty string = unset → the MODEL'S
defaults, which for vit_h14's bf16 leg are the baked-in winners:
remat off, bf16 moments, onehot gather — bench.py MODELS). To put a
default-ON knob in its off state, sweep its explicit off spelling
instead of the empty string: BENCH_MU_DTYPE=float32,
BENCH_NU_DTYPE=float32, BENCH_GATHER_IMPL=take, BENCH_REMAT=1.
Failed runs are recorded with their error line (bench.py emits
machine-readable JSON even on failure) and the sweep continues.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def parse_sweep(spec: str) -> tuple[str, list[str]]:
    knob, _, values = spec.partition("=")
    if not knob or not _:
        raise SystemExit(f"bad --sweep {spec!r}; expected KNOB=v1,v2,...")
    return knob, values.split(",")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="vit_h14")
    parser.add_argument(
        "--sweep", action="append", default=[], help="KNOB=v1,v2,... (repeatable)"
    )
    parser.add_argument("--iters", type=int, default=None)
    parser.add_argument("--skip-baseline", action="store_true")
    parser.add_argument("--out", default=None, help="JSONL log path")
    parser.add_argument(
        "--timeout", type=float, default=1800, help="per-run seconds"
    )
    args = parser.parse_args(argv)

    sweeps = [parse_sweep(s) for s in args.sweep]
    knob_names = [k for k, _ in sweeps]
    dupes = {k for k in knob_names if knob_names.count(k) > 1}
    if dupes:
        raise SystemExit(
            f"knob(s) {sorted(dupes)} swept more than once — merge the "
            "values into one --sweep KNOB=v1,v2,..."
        )
    out_path = Path(args.out or f"/tmp/ab_{args.model}.jsonl")

    # no sweeps → one run at the defaults (product of zero iterables = [()])
    combos = list(itertools.product(*(vals for _, vals in sweeps)))
    print(f"[ab_bench] {len(combos)} configurations → {out_path}")
    results = []
    for combo in combos:
        env = dict(os.environ)
        env["BENCH_MODEL"] = args.model
        if args.iters is not None:
            env["BENCH_ITERS"] = str(args.iters)
        if args.skip_baseline:
            env["BENCH_SKIP_BASELINE"] = "1"
        setting = {}
        for (knob, _), value in zip(sweeps, combo):
            setting[knob] = value
            if value == "":
                env.pop(knob, None)
            else:
                env[knob] = value
        label = " ".join(f"{k}={v or '<unset>'}" for k, v in setting.items())
        print(f"[ab_bench] run: {label or '(defaults)'}", flush=True)
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, str(REPO / "bench.py")],
                env=env,
                cwd=str(REPO),
                capture_output=True,
                text=True,
                timeout=args.timeout,
            )
            lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
            try:
                parsed = json.loads(lines[-1]) if lines else None
            except json.JSONDecodeError:
                parsed = None
            record = {
                "knobs": setting,
                "rc": proc.returncode,
                "wall_s": round(time.monotonic() - t0, 1),
                "result": parsed,
            }
            if proc.returncode != 0 and parsed is None:
                record["stderr_tail"] = proc.stderr[-400:]
        except subprocess.TimeoutExpired as e:
            def _tail(buf):
                if not buf:
                    return ""
                s = buf if isinstance(buf, str) else buf.decode(errors="replace")
                return s[-400:]

            record = {
                "knobs": setting,
                "rc": "timeout",
                "wall_s": round(time.monotonic() - t0, 1),
                "result": None,
                # how far it got before the fuse — don't make reruns blind
                "stdout_tail": _tail(e.stdout),
                "stderr_tail": _tail(e.stderr),
            }
        results.append(record)
        with out_path.open("a") as f:
            f.write(json.dumps(record) + "\n")
        val = (record.get("result") or {}).get("value")
        print(f"[ab_bench]   → rc={record['rc']} value={val}", flush=True)

    # a failed run's error JSON can still carry the partial bf16-leg value —
    # only rc==0 rows count as successes
    ok = [
        r
        for r in results
        if r["rc"] == 0 and (r.get("result") or {}).get("value")
    ]
    if ok:
        best = max(ok, key=lambda r: r["result"]["value"])
        print(
            f"[ab_bench] best: {best['result']['value']} "
            f"({best['result'].get('unit', '')}) with {best['knobs']}"
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
