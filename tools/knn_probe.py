#!/usr/bin/env python3
"""k-nearest-neighbor probe over exported feature files.

Closes the loop on ``tools/extract_features.py``: a training-free accuracy
readout of frozen representations (the standard kNN-probe protocol —
cosine similarity, temperature-weighted vote over the k nearest training
features), without running the linear-probe optimizer. Beyond the
reference, whose only probe is the trained BatchNorm+linear head.

    python tools/extract_features.py cfg.yaml --ckpt C --out train.npz \
        --set data.valid_shards=<train shards>
    python tools/extract_features.py cfg.yaml --ckpt C --out val.npz
    python tools/knn_probe.py train.npz val.npz [--k 20] [--temp 0.07]

Each input is either an ``.npz`` file with ``features`` and ``labels``
arrays (as written by extract_features) or a ``.yaml`` recipe — recipe
inputs are extracted on the fly through the batched inference engine
(``extract_features.extract_arrays``), sharing one restored checkpoint:

    python tools/knn_probe.py train.yaml val.yaml --ckpt runs/x/ckpt \
        [--pool cls] [--set data.workers=0]

Prints one JSON line with top-1 accuracy.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def knn_predict(
    train_feats,
    train_labels,
    query_feats,
    *,
    k: int = 20,
    temp: float = 0.07,
    num_classes: int | None = None,
    block: int = 1024,
):
    """Cosine-similarity kNN with temperature-weighted voting.

    Pure numpy (host-side — feature tables are small relative to the
    model); returns predicted labels for ``query_feats``.
    """
    import numpy as np

    def l2norm(x):
        x = np.asarray(x, np.float32)
        return x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), 1e-12)

    train = l2norm(train_feats)
    query = l2norm(query_feats)
    if train.shape[0] == 0:
        # k would clamp to 0 and the [:, :0] slice below silently votes
        # class 0 for every query — a 0.1%-accuracy "result" from a bug
        raise SystemExit(
            "kNN probe: train feature set is empty — re-extract the "
            "reference split (check data.valid_shards / synthetic count)"
        )
    if k < 1:
        raise SystemExit(f"kNN probe: k must be >= 1, got {k}")
    labels = np.asarray(train_labels)
    classes = int(num_classes or labels.max() + 1)
    k = min(k, train.shape[0])

    preds = []
    for start in range(0, query.shape[0], block):
        sim = query[start : start + block] @ train.T  # (b, n_train)
        top = np.argpartition(-sim, k - 1, axis=1)[:, :k]
        top_sim = np.take_along_axis(sim, top, axis=1)
        top_lab = labels[top]
        weight = np.exp(top_sim / temp)
        votes = np.zeros((top.shape[0], classes), np.float32)
        rows = np.repeat(np.arange(top.shape[0]), k)
        np.add.at(votes, (rows, top_lab.reshape(-1)), weight.reshape(-1))
        preds.append(votes.argmax(axis=1))
    return np.concatenate(preds)


def _load_side(path: str, name: str, args) -> dict:
    """One probe side: a ready .npz, or a .yaml recipe extracted through the
    inference engine (features + labels, invalid rows already dropped)."""
    import numpy as np

    if path.endswith((".yaml", ".yml")):
        from extract_features import extract_arrays

        from jumbo_mae_tpu_tpu.config import load_config

        cfg = load_config(path, args.overrides)
        features, labels = extract_arrays(cfg, args.ckpt, args.pool)
        if labels is None:
            raise SystemExit(
                f"{name} recipe {path} yields no labels — probe needs a "
                "labeled split"
            )
        return {"features": features, "labels": labels}
    z = np.load(path)
    if "labels" not in z:
        raise SystemExit(
            f"{name} file has no labels — extract from a labeled split"
        )
    return z


def main(argv: list[str] | None = None) -> float:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("train_npz", help="reference set: .npz or .yaml recipe")
    p.add_argument("query_npz", help="set to evaluate: .npz or .yaml recipe")
    p.add_argument("--k", type=int, default=20)
    p.add_argument("--temp", type=float, default=0.07)
    p.add_argument(
        "--ckpt", default="", help="checkpoint for .yaml recipe inputs"
    )
    p.add_argument("--pool", choices=("cls", "gap"), default="cls")
    p.add_argument(
        "--set",
        dest="overrides",
        metavar="KEY.PATH=VALUE",
        nargs="*",
        action="extend",
        default=[],
        help="dotted config overrides for .yaml inputs, same grammar as cli.train",
    )
    args = p.parse_args(argv)

    train = _load_side(args.train_npz, "train", args)
    query = _load_side(args.query_npz, "query", args)
    preds = knn_predict(
        train["features"], train["labels"], query["features"],
        k=args.k, temp=args.temp,
    )
    acc = float((preds == query["labels"]).mean())
    print(
        json.dumps(
            {
                "metric": "knn_top1",
                "value": round(acc, 4),
                "k": args.k,
                "temp": args.temp,
                "n_train": int(train["features"].shape[0]),
                "n_query": int(query["features"].shape[0]),
            }
        )
    )
    return acc


if __name__ == "__main__":
    main()
