#!/usr/bin/env python3
"""k-nearest-neighbor probe over exported feature files.

Closes the loop on ``tools/extract_features.py``: a training-free accuracy
readout of frozen representations (the standard kNN-probe protocol —
cosine similarity, temperature-weighted vote over the k nearest training
features), without running the linear-probe optimizer. Beyond the
reference, whose only probe is the trained BatchNorm+linear head.

    python tools/extract_features.py cfg.yaml --ckpt C --out train.npz \
        --set data.valid_shards=<train shards>
    python tools/extract_features.py cfg.yaml --ckpt C --out val.npz
    python tools/knn_probe.py train.npz val.npz [--k 20] [--temp 0.07]

Both inputs must be ``.npz`` files with ``features`` and ``labels`` arrays
(as written by extract_features). Prints one JSON line with top-1 accuracy.
"""

from __future__ import annotations

import argparse
import json


def knn_predict(
    train_feats,
    train_labels,
    query_feats,
    *,
    k: int = 20,
    temp: float = 0.07,
    num_classes: int | None = None,
    block: int = 1024,
):
    """Cosine-similarity kNN with temperature-weighted voting.

    Pure numpy (host-side — feature tables are small relative to the
    model); returns predicted labels for ``query_feats``.
    """
    import numpy as np

    def l2norm(x):
        x = np.asarray(x, np.float32)
        return x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), 1e-12)

    train = l2norm(train_feats)
    query = l2norm(query_feats)
    labels = np.asarray(train_labels)
    classes = int(num_classes or labels.max() + 1)
    k = min(k, train.shape[0])

    preds = []
    for start in range(0, query.shape[0], block):
        sim = query[start : start + block] @ train.T  # (b, n_train)
        top = np.argpartition(-sim, k - 1, axis=1)[:, :k]
        top_sim = np.take_along_axis(sim, top, axis=1)
        top_lab = labels[top]
        weight = np.exp(top_sim / temp)
        votes = np.zeros((top.shape[0], classes), np.float32)
        rows = np.repeat(np.arange(top.shape[0]), k)
        np.add.at(votes, (rows, top_lab.reshape(-1)), weight.reshape(-1))
        preds.append(votes.argmax(axis=1))
    return np.concatenate(preds)


def main(argv: list[str] | None = None) -> float:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("train_npz", help="features+labels of the reference set")
    p.add_argument("query_npz", help="features+labels to evaluate")
    p.add_argument("--k", type=int, default=20)
    p.add_argument("--temp", type=float, default=0.07)
    args = p.parse_args(argv)

    import numpy as np

    train = np.load(args.train_npz)
    query = np.load(args.query_npz)
    for name, z in (("train", train), ("query", query)):
        if "labels" not in z:
            raise SystemExit(
                f"{name} file has no labels — extract from a labeled split"
            )
    preds = knn_predict(
        train["features"], train["labels"], query["features"],
        k=args.k, temp=args.temp,
    )
    acc = float((preds == query["labels"]).mean())
    print(
        json.dumps(
            {
                "metric": "knn_top1",
                "value": round(acc, 4),
                "k": args.k,
                "temp": args.temp,
                "n_train": int(train["features"].shape[0]),
                "n_query": int(query["features"].shape[0]),
            }
        )
    )
    return acc


if __name__ == "__main__":
    main()
