#!/usr/bin/env python3
"""Offline tenant chargeback: access log + journal → markdown cost report.

``serve_doctor`` answers "what did the callers experience"; this tool
answers "who consumed the capacity and what did it cost". Input is the
same crash-safe access-log directory (``--access-log``): request rows
carry the cost meter's per-row ``device_ms``/``cost_flops`` stamps, and
periodic ``tenant_usage`` events carry the meter's cumulative ledgers.

    python tools/cost_doctor.py runs/serve/access
    python tools/cost_doctor.py ... --out chargeback.md

A training journal works too: the gated weights publisher bills each
publish to a ``publish`` tenant as ``tenant_usage`` rows, which surface
as a *ledger-only* tenant in the chargeback table (no request rows — the
bill comes straight from the journaled ledger).

The report, in order:

- **Chargeback** — per-tenant cost table: requests, ok/shed, device-
  seconds billed, capacity share, GFLOPs, pad-waste, shed split by typed
  reason (quota/pressure/budget from the ``err`` column); names the top
  consumer.
- **Waste attribution** — how much of each tenant's bill bought bucket
  padding rather than work.
- **Budgets** — per-tenant budget vs window usage from the last
  ``tenant_usage`` rows, flagging exhausted tenants.
- **Reconciliation** — row-level sums vs the meter's journaled ledger
  totals (they disagree only when rows were lost — torn tail, shed before
  dispatch — so the delta is a data-quality signal, not rounding).
- **Verdict** — noisy-neighbor call: a tenant over its implied (equal)
  share of metered device-time while lower-cost tenants shed.

Exit codes: 0 = report written (healthy or not); 2 = no access log or no
costed rows to account.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from jumbo_mae_tpu_tpu.obs.doctor_common import fmt_num, write_report  # noqa: E402
from jumbo_mae_tpu_tpu.obs.journal import read_journal  # noqa: E402

# typed shed classes the scheduler stamps into the err column
_SHED_REASONS = {
    "TenantQuotaError": "quota",
    "TenantPressureError": "pressure",
    "TenantBudgetError": "budget",
}


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    rank = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[rank]


def _tenant_bills(rows: list[dict]) -> dict[str, dict]:
    """Aggregate request rows into per-tenant bills (row-level truth)."""
    bills: dict[str, dict] = {}
    for r in rows:
        name = str(r.get("tenant") or "_default")
        b = bills.setdefault(
            name,
            {
                "class": "?",
                "requests": 0,
                "ok": 0,
                "shed": 0,
                "shed_reasons": {},
                "device_s": 0.0,
                "flops": 0.0,
                "waste_s": 0.0,
                "lat_ms": [],
            },
        )
        if r.get("class"):
            b["class"] = str(r["class"])
        b["requests"] += 1
        if r["outcome"] == "ok":
            b["ok"] += 1
            if r.get("lat_ms") is not None:
                b["lat_ms"].append(r["lat_ms"])
        elif r["outcome"] == "shed":
            b["shed"] += 1
            reason = _SHED_REASONS.get(str(r.get("err")), "queue")
            b["shed_reasons"][reason] = b["shed_reasons"].get(reason, 0) + 1
        b["device_s"] += (r.get("device_ms") or 0.0) / 1000.0
        b["flops"] += r.get("cost_flops") or 0.0
        b["waste_s"] += (
            (r.get("device_ms") or 0.0) * (r.get("pad") or 0.0) / 1000.0
        )
    return bills


def diagnose(rows: list[dict], events: list[dict]) -> tuple[str, str | None]:
    """Render the chargeback markdown; returns (report, top_consumer)."""
    lines: list[str] = ["# Cost doctor report", ""]
    verdict: list[str] = []
    bills = _tenant_bills(rows)
    # last tenant_usage row per tenant = the meter's final cumulative word
    usage: dict[str, dict] = {}
    for e in events:
        if e.get("type") == "tenant_usage" and e.get("tenant"):
            usage[str(e["tenant"])] = e
    # ledger-only tenants never emit request rows — e.g. the train-side
    # ``publish`` tenant, billed per weights-publish straight into the
    # training journal — so their bill comes from the journaled ledger
    ledger_only = sorted(set(usage) - set(bills))
    for name in ledger_only:
        u = usage[name]
        bills[name] = {
            "class": str(u.get("class") or "?"),
            "requests": int(u.get("requests") or 0),
            "ok": int(u.get("requests") or 0),
            "shed": 0,
            "shed_reasons": {},
            "device_s": float(u.get("device_s") or 0.0),
            "flops": float(u.get("flops") or 0.0),
            "waste_s": float(u.get("waste_device_s") or 0.0),
            "lat_ms": [],
        }
    total_dev = sum(b["device_s"] for b in bills.values())
    total_flops = sum(b["flops"] for b in bills.values())

    # ---------------------------------------------------------- chargeback
    lines += [
        "## Chargeback",
        "",
        "| tenant | class | requests | ok | shed (reasons) | device s "
        "| share | GFLOPs | waste s | p99 ms |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    top = None
    for name in sorted(bills, key=lambda n: -bills[n]["device_s"]):
        b = bills[name]
        if top is None:
            top = name
        share = b["device_s"] / total_dev if total_dev > 0 else 0.0
        reasons = (
            " (" + ", ".join(
                f"{r}: {n}" for r, n in sorted(b["shed_reasons"].items())
            ) + ")"
            if b["shed_reasons"]
            else ""
        )
        lat = sorted(b["lat_ms"])
        lines.append(
            f"| {name} | {b['class']} | {b['requests']} | {b['ok']} "
            f"| {b['shed']}{reasons} "
            f"| {fmt_num(b['device_s'])} | {share * 100:.1f}% "
            f"| {fmt_num(b['flops'] / 1e9)} | {fmt_num(b['waste_s'])} "
            f"| {fmt_num(_quantile(lat, 0.99)) if lat else '-'} |"
        )
    lines += [
        "",
        f"- metered total: {fmt_num(total_dev)} device-s, "
        f"{fmt_num(total_flops / 1e9)} GFLOPs across "
        f"{sum(b['requests'] for b in bills.values())} request row(s)",
    ]
    if top is not None and total_dev > 0:
        lines.append(
            f"- top consumer: **{top}** "
            f"({bills[top]['device_s'] / total_dev * 100:.1f}% of "
            f"device-time)"
        )
    if ledger_only:
        lines.append(
            "- ledger-only tenant(s) (no request rows; billed from "
            "`tenant_usage`): " + ", ".join(f"`{t}`" for t in ledger_only)
        )
    lines.append("")

    # ---------------------------------------------------- waste attribution
    total_waste = sum(b["waste_s"] for b in bills.values())
    if total_dev > 0:
        lines += ["## Waste attribution", ""]
        lines.append(
            f"- {fmt_num(total_waste)} of {fmt_num(total_dev)} device-s "
            f"({total_waste / total_dev * 100:.1f}%) bought bucket padding"
        )
        for name in sorted(bills, key=lambda n: -bills[n]["waste_s"]):
            b = bills[name]
            if b["waste_s"] <= 0 or b["device_s"] <= 0:
                continue
            lines.append(
                f"- `{name}`: {fmt_num(b['waste_s'])} s "
                f"({b['waste_s'] / b['device_s'] * 100:.1f}% of its bill)"
            )
        lines.append("")

    # -------------------------------------------------------------- budgets
    budgeted = {
        t: u for t, u in usage.items() if u.get("budget_device_s") is not None
    }
    if budgeted:
        lines += [
            "## Budgets",
            "",
            "| tenant | budget (device s / window) | window usage | status |",
            "|---|---|---|---|",
        ]
        for name in sorted(budgeted):
            u = budgeted[name]
            over = bool(u.get("over_budget"))
            status = "**exhausted**" if over else "within budget"
            lines.append(
                f"| {name} | {fmt_num(u['budget_device_s'])} "
                f"| {fmt_num(u.get('window_device_s') or 0.0)} "
                f"| {status} |"
            )
            if over:
                verdict.append(
                    f"`{name}` exhausted its budget "
                    f"(degraded to scavenger-class shedding)"
                )
        lines.append("")

    # ------------------------------------------------------- reconciliation
    if usage:
        ledger_dev = sum(u.get("device_s") or 0.0 for u in usage.values())
        ledger_flops = sum(u.get("flops") or 0.0 for u in usage.values())
        lines += ["## Reconciliation (rows vs ledger)", ""]
        if ledger_dev > 0:
            delta = abs(total_dev - ledger_dev) / ledger_dev * 100.0
            agree = "agree" if delta <= 1.0 else "**disagree**"
            lines.append(
                f"- device-seconds: rows {fmt_num(total_dev)} vs ledger "
                f"{fmt_num(ledger_dev)} — {agree} (Δ {delta:.2f}%)"
            )
            if delta > 1.0:
                verdict.append(
                    f"ledger/rows disagree by {delta:.1f}% — request rows "
                    "were lost (torn tail or crash mid-batch)"
                )
        if ledger_flops > 0:
            delta_f = abs(total_flops - ledger_flops) / ledger_flops * 100.0
            lines.append(
                f"- FLOPs: rows {fmt_num(total_flops / 1e9)} vs ledger "
                f"{fmt_num(ledger_flops / 1e9)} GFLOPs (Δ {delta_f:.2f}%)"
            )
        lines.append("")

    # ------------------------------------------------------- noisy neighbor
    shed_tenants = [t for t, b in bills.items() if b["shed"] > 0]
    noisy: list[str] = []
    if total_dev > 0 and len(bills) > 1 and shed_tenants:
        fair = 1.0 / len(bills)
        for name, b in bills.items():
            share = b["device_s"] / total_dev
            if share <= 1.25 * fair:
                continue
            if any(
                o != name and bills[o]["device_s"] < b["device_s"]
                for o in shed_tenants
            ):
                noisy.append(name)
    if noisy:
        verdict.append(
            "noisy neighbor: "
            + ", ".join(
                f"`{t}` ({bills[t]['device_s'] / total_dev * 100:.0f}% of "
                f"device-time)"
                for t in sorted(noisy)
            )
            + " over its implied share while cheaper tenants shed"
        )
    if not verdict:
        verdict.append("no budget exhaustion or noisy-neighbor signal")

    lines[2:2] = ["## Verdict", "", f"- {'; '.join(verdict)}", ""]
    return "\n".join(lines), top


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    parser.add_argument(
        "path", help="access-log dir (or one journal-*.jsonl segment)"
    )
    parser.add_argument(
        "--out", default=None, help="write the markdown here (default stdout)"
    )
    args = parser.parse_args(argv)

    try:
        events = read_journal(args.path)
    except FileNotFoundError as e:
        print(f"[cost_doctor] {e}", file=sys.stderr)
        return 2
    rows = [e for e in events if e.get("type") == "request"]
    costed = [r for r in rows if r.get("device_ms") is not None]
    # a training journal has no request rows at all, but its tenant_usage
    # ledger (the `publish` tenant) is still chargeable
    if not costed and not any(
        e.get("type") == "tenant_usage" for e in events
    ):
        print(
            f"[cost_doctor] no costed request rows or tenant_usage events "
            f"in {args.path} — was a CostMeter attached?",
            file=sys.stderr,
        )
        return 2

    report, _top = diagnose(rows, events)
    return write_report(report, args.out, tool="cost_doctor")


if __name__ == "__main__":
    sys.exit(main())
