"""On-device (real TPU) kernel checks, run in a subprocess.

The suite pins everything else to a virtual CPU mesh (conftest.py), which
exercises the Pallas kernels only in interpreter mode. This test spawns a
fresh interpreter WITHOUT the CPU forcing so the kernels compile through
Mosaic and execute on the actual accelerator — gradient parity of the flash
forward+backward against the einsum path at MAE shapes, including a ragged
(non-tile-multiple) sequence length. Skips cleanly when no accelerator is
reachable (CI hosts, laptops).
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

_UNPROBED = object()
_probe_failure: object = _UNPROBED  # session-cached device-probe verdict

_DEVICE_PROBE_AND_CHECK = r"""
import sys
import jax, jax.numpy as jnp
import numpy as np

devs = jax.devices()
if jax.default_backend() != "tpu":
    # only a TPU runs the Mosaic kernels; any other accelerator would take
    # flash_attention's XLA fallback and this test would prove nothing
    print("NO-ACCELERATOR")
    sys.exit(0)

# call the kernel entry point directly (not the flash_attention dispatcher)
# so a dispatch-rule change can never silently route this test to XLA
from jumbo_mae_tpu_tpu.ops.flash_attention import xla_attention as einsum_attn
from jumbo_mae_tpu_tpu.ops.pallas.attention import pallas_flash_attention

def flash_attention(q, k, v):
    return pallas_flash_attention(q, k, v)

for (B, S, H, D) in [(4, 199, 4, 32), (2, 130, 2, 64)]:  # ragged lengths
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, S, H, D), jnp.bfloat16) * D**-0.5
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, H, D), jnp.bfloat16)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32))

    gf = jax.jit(jax.grad(loss(flash_attention), argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss(einsum_attn), argnums=(0, 1, 2)))(q, k, v)
    of = np.asarray(flash_attention(q, k, v), np.float32)
    orf = np.asarray(einsum_attn(q, k, v), np.float32)
    assert np.abs(of - orf).max() < 0.05, (S, D, "fwd mismatch")
    for a, b in zip(gf, gr):
        err = float(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)).max())
        assert err < 0.1, (S, D, "grad mismatch", err)
print("DEVICE-OK", devs[0].device_kind)
"""


_DEVICE_TRAIN_SMOKE = r"""
import sys
import jax
import numpy as np

if jax.default_backend() != "tpu":
    print("NO-ACCELERATOR")
    sys.exit(0)

from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
from jumbo_mae_tpu_tpu.parallel import MeshConfig, batch_sharding, create_mesh
from jumbo_mae_tpu_tpu.train import (
    OptimConfig, create_sharded_state, make_optimizer, make_train_step,
)

mesh = create_mesh(MeshConfig(data=1, fsdp=1), devices=jax.devices()[:1])
enc = preset("vit_t16", image_size=64, patch_size=8, mask_ratio=0.75,
             labels=None, posemb="sincos2d", dtype="bfloat16")
module = MAEPretrainModel(enc, DecoderConfig(layers=1, dim=64, heads=4,
                                             dtype="bfloat16"))
batch = {"images": np.random.RandomState(0).randint(
    0, 256, (16, 64, 64, 3), dtype=np.uint8)}
tx = make_optimizer(
    OptimConfig(name="adamw", learning_rate=1e-3, lr_scaling="none",
                warmup_steps=1, training_steps=10, mu_dtype="bfloat16"),
    16,
)
state, sharding = create_sharded_state(module, tx, batch, mesh, mode="pretrain")
step = make_train_step(mesh, sharding, mode="pretrain")
bd = jax.device_put(batch, batch_sharding(mesh))
losses = []
for _ in range(6):
    state, m = step(state, bd)
    losses.append(float(m["loss"]))
assert all(np.isfinite(losses)), losses
assert losses[-1] < losses[0], losses
print("DEVICE-OK", losses[0], "->", losses[-1])
"""


def _run_on_device(code: str) -> str:
    env = dict(os.environ)
    # undo the CPU forcing the rest of the suite (and this process) uses
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    )
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}{env.get('PYTHONPATH', '')}"
    # A wedged remote-TPU tunnel makes jax.devices() BLOCK rather than
    # fail, so probe reachability with a short-fused trivial op first and
    # skip (infra problem, not a code problem) instead of hanging the
    # suite for the full test timeout. One probe per session — both tests
    # share the verdict.
    global _probe_failure
    if _probe_failure is _UNPROBED:
        _probe_failure = None
        try:
            probe = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax, jax.numpy as jnp; "
                    "print(float(jax.jit(lambda x: x.sum())(jnp.ones(8))))",
                ],
                env=env,
                cwd=str(REPO),
                capture_output=True,
                text=True,
                timeout=180,
            )
            if probe.returncode != 0:
                _probe_failure = f"accelerator runtime broken: {probe.stderr[-300:]}"
        except subprocess.TimeoutExpired:
            _probe_failure = "accelerator runtime unreachable (device probe hung)"
    if _probe_failure is not None:
        pytest.skip(_probe_failure)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=str(REPO),
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    if "NO-ACCELERATOR" in proc.stdout:
        pytest.skip("no TPU reachable from this host")
    assert "DEVICE-OK" in proc.stdout, proc.stdout
    return proc.stdout


_DEVICE_GATHER_PARITY = r"""
import sys
import jax, jax.numpy as jnp
import numpy as np

if jax.default_backend() != "tpu":
    print("NO-ACCELERATOR")
    sys.exit(0)

from jumbo_mae_tpu_tpu.ops.masking import (
    index_sequence, unshuffle_with_mask_tokens,
)

# ViT-H/14 bench shapes (the config where gather_impl="onehot" is the
# DEFAULT): the bit-identity proven on CPU must also hold through the
# real MXU lowering, where the 0/1 matmuls run with HIGHEST precision.
B, S, D = 8, 259, 1280
KEEP = 65
key = jax.random.PRNGKey(0)
x = jax.random.normal(key, (B, S, D), jnp.bfloat16)
ids = jax.random.permutation(
    jax.random.fold_in(key, 1), jnp.arange(S)[None, :].repeat(B, 0), axis=1,
    independent=True,
)
take_fn = jax.jit(lambda x, i: index_sequence(x, i, impl="take"))
onehot_fn = jax.jit(lambda x, i: index_sequence(x, i, impl="onehot"))
a = np.asarray(take_fn(x, ids[:, :KEEP]))
b = np.asarray(onehot_fn(x, ids[:, :KEEP]))
assert a.dtype == b.dtype and (a == b).all(), "index_sequence mismatch on device"

ids_restore = jnp.argsort(ids, axis=1)
tok = jax.random.normal(jax.random.fold_in(key, 2), (B, KEEP, 512), jnp.bfloat16)
mask_token = jax.random.normal(jax.random.fold_in(key, 3), (1, 1, 512), jnp.bfloat16)
ua = jax.jit(lambda t, i: unshuffle_with_mask_tokens(
    t, mask_token, i, impl="take"))(tok, ids_restore)
ub = jax.jit(lambda t, i: unshuffle_with_mask_tokens(
    t, mask_token, i, impl="onehot"))(tok, ids_restore)
ua, ub = np.asarray(ua), np.asarray(ub)
assert ua.dtype == ub.dtype and (ua == ub).all(), "unshuffle mismatch on device"

# shared mode (1-D ids) — mask_mode="shared" is the config default the
# bench actually runs, and it lowers through the DIFFERENT einsum branch
# ('nk,bk...'); use the bench's true patch-grid shape (256 patches, 64 kept)
S2, KEEP2 = 256, 64
x2 = jax.random.normal(jax.random.fold_in(key, 4), (B, S2, D), jnp.bfloat16)
ids1d = jax.random.permutation(jax.random.fold_in(key, 5), jnp.arange(S2))
a = np.asarray(take_fn(x2, ids1d[:KEEP2]))
b = np.asarray(onehot_fn(x2, ids1d[:KEEP2]))
assert a.dtype == b.dtype and (a == b).all(), "shared-mode index_sequence mismatch"
restore1d = jnp.argsort(ids1d)
tok2 = jax.random.normal(jax.random.fold_in(key, 6), (B, KEEP2, 512), jnp.bfloat16)
ua = jax.jit(lambda t, i: unshuffle_with_mask_tokens(
    t, mask_token, i, impl="take"))(tok2, restore1d)
ub = jax.jit(lambda t, i: unshuffle_with_mask_tokens(
    t, mask_token, i, impl="onehot"))(tok2, restore1d)
ua, ub = np.asarray(ua), np.asarray(ub)
assert ua.dtype == ub.dtype and (ua == ub).all(), "shared-mode unshuffle mismatch"
print("DEVICE-OK gather parity at H/14 shapes (per-sample + shared modes)")
"""


@pytest.mark.slow
def test_flash_kernels_compile_and_match_on_device():
    _run_on_device(_DEVICE_PROBE_AND_CHECK)


@pytest.mark.slow
def test_onehot_gather_bit_identical_on_device():
    """gather_impl="onehot" is the ViT-H/14 bench DEFAULT on the claim of
    bit-identity with the take path; assert that identity through the real
    MXU lowering, not just the CPU backend the rest of the suite pins."""
    _run_on_device(_DEVICE_GATHER_PARITY)


@pytest.mark.slow
def test_train_step_on_device():
    """The full bf16 train step (bf16 score materialization, bf16 first
    moment, donated state) compiles and decreases a finite loss on the real
    accelerator — the configuration the bench measures, as a test."""
    _run_on_device(_DEVICE_TRAIN_SMOKE)
