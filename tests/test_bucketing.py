"""The bucket-ladder invariants, enforced at the one shared definition.

``infer/bucketing.py`` exists because three call sites (engine ceil,
scheduler floor, loadgen report) each grew their own copy of the
power-of-two walk and the honesty of the serving tier lives *between*
them.  These tests property-check the pair against each other across the
(n, max_batch) lattice, so any drift breaks here rather than silently in
pad accounting.
"""

import pytest

from jumbo_mae_tpu_tpu.infer.bucketing import (
    OversizedBatchError,
    bucket_for,
    ceil_pow2,
    floor_bucket,
    pow2_rungs,
)


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


class TestLadderInvariants:
    @pytest.mark.parametrize("max_batch", [1, 2, 3, 4, 7, 8, 16, 24, 64])
    def test_floor_k_ceil_sandwich(self, max_batch):
        # floor(k) <= k <= ceil(k) for every k the ladder serves
        for k in range(1, max_batch + 1):
            lo = floor_bucket(k, max_batch)
            hi = bucket_for(k, max_batch)
            assert lo <= k <= hi, (k, max_batch, lo, hi)

    @pytest.mark.parametrize("max_batch", [1, 2, 4, 8, 16, 24, 64])
    def test_floor_is_pad_free(self, max_batch):
        # a floor-aligned batch must pad to itself: ceil(floor(k)) == floor(k)
        for k in range(1, 4 * max_batch):
            lo = floor_bucket(k, max_batch)
            assert bucket_for(lo, max_batch) == lo, (k, max_batch, lo)

    @pytest.mark.parametrize("max_batch", [2, 4, 8, 16, 24])
    def test_ceil_is_pow2_or_top_rung(self, max_batch):
        for k in range(1, max_batch + 1):
            b = bucket_for(k, max_batch)
            assert _is_pow2(b) or b == max_batch

    def test_oversized_raises_typed(self):
        with pytest.raises(OversizedBatchError):
            bucket_for(9, 8)
        # the typed error is still a ValueError for legacy handlers
        with pytest.raises(ValueError):
            bucket_for(17, 16)

    def test_nonpositive_raises(self):
        with pytest.raises(ValueError):
            bucket_for(0, 8)
        with pytest.raises(ValueError):
            bucket_for(-3, 8)

    def test_non_pow2_max_batch_is_the_top_rung(self):
        # 24 is not a power of two: 17..24 all land on 24, never above
        assert bucket_for(16, 24) == 16
        for k in range(17, 25):
            assert bucket_for(k, 24) == 24
        assert floor_bucket(24, 24) == 24
        assert floor_bucket(100, 24) == 24


class TestPow2Helpers:
    def test_ceil_pow2_values(self):
        assert [ceil_pow2(n) for n in (1, 2, 3, 4, 5, 8, 9, 1023)] == [
            1, 2, 4, 4, 8, 8, 16, 1024,
        ]

    def test_ceil_pow2_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            ceil_pow2(0)

    def test_pow2_rungs_pow2_max(self):
        assert pow2_rungs(16) == (1, 2, 4, 8, 16)

    def test_pow2_rungs_appends_non_pow2_max(self):
        assert pow2_rungs(24) == (1, 2, 4, 8, 16, 24)
        assert pow2_rungs(1) == (1,)

    def test_pow2_rungs_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            pow2_rungs(0)

    @pytest.mark.parametrize("mv", [1, 2, 7, 8, 100, 4096])
    def test_rungs_cover_every_need(self, mv):
        # any n <= max_value has a rung >= n (choose_budget relies on this)
        rungs = pow2_rungs(mv)
        for n in range(1, mv + 1):
            assert any(b >= n for b in rungs)
