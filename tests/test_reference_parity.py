"""Direct numerical parity against the reference implementation.

The reference model zoo is importable flax (``/root/reference/src/modeling.py``,
reviewed read-only: pure module definitions, no import-time side effects).
These tests init the REFERENCE modules, convert their param trees with
``interop.reference_convert``, load them into this framework's modules, and
assert forward outputs match in float32 — upgrading the re-derived-oracle
parity story to direct proof (VERDICT round 1, item 3).

Import shims: reference ``utils.py`` imports ``webdataset`` and reference
``pretraining.py`` imports ``dataset`` (webdataset/torchvision/timm, not
installed here). Neither dependency is touched by the model code paths, so
minimal stub modules are injected. The normalization constants the stub
provides are asserted equal to this package's.
"""

from __future__ import annotations

import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.interop import (
    flax_to_torch_state,
    reference_encoder_to_jumbo,
    reference_pretrain_to_jumbo,
    torch_to_flax_params,
)
from jumbo_mae_tpu_tpu.models import (
    DecoderConfig,
    JumboViT,
    JumboViTConfig,
    MAEPretrainModel,
)
from jumbo_mae_tpu_tpu.ops.preprocess import IMAGENET_MEAN, IMAGENET_STD

REF_SRC = "/root/reference/src"


IMAGENET_DEFAULT_MEAN = np.array([0.485, 0.456, 0.406])
IMAGENET_DEFAULT_STD = np.array([0.229, 0.224, 0.225])


@pytest.fixture(scope="module")
def ref():
    """Import the reference modules with missing-dependency stubs; everything
    injected into sys.modules/sys.path is removed afterwards (the reference's
    top-level names — modeling, utils, dataset … — are too generic to leak)."""
    import os

    if not os.path.isdir(REF_SRC):
        pytest.skip(
            f"reference checkout not present at {REF_SRC} — direct-parity "
            "tests are environment-bound (the re-derived oracles in "
            "test_models/test_train_steps cover the same numerics)"
        )
    np.testing.assert_allclose(IMAGENET_MEAN, IMAGENET_DEFAULT_MEAN)
    np.testing.assert_allclose(IMAGENET_STD, IMAGENET_DEFAULT_STD)

    injected = [
        m for m in ("webdataset", "dataset") if m not in sys.modules
    ]
    if "webdataset" in injected:
        sys.modules["webdataset"] = types.ModuleType("webdataset")
    if "dataset" in injected:
        ds = types.ModuleType("dataset")
        ds.IMAGENET_DEFAULT_MEAN = IMAGENET_DEFAULT_MEAN
        ds.IMAGENET_DEFAULT_STD = IMAGENET_DEFAULT_STD
        sys.modules["dataset"] = ds
    sys.path.insert(0, REF_SRC)
    try:
        import modeling as ref_modeling
        import pretraining as ref_pretraining

        yield types.SimpleNamespace(
            modeling=ref_modeling, pretraining=ref_pretraining
        )
    finally:
        sys.path.remove(REF_SRC)
        # only the reference's generic top-level names + our stubs — not the
        # transitive third-party imports, which must stay singletons
        for m in injected + ["modeling", "pretraining", "utils", "utils_mae"]:
            sys.modules.pop(m, None)


# Tiny but structurally complete: multiple blocks (shared jumbo MLP reuse),
# layerscale on, learnable posemb in classify / sincos2d in MAE.
LAYERS, DIM, HEADS, LABELS = 2, 48, 4, 11
IMAGE, PATCH = 64, 16  # grid 4x4, N=16: int(N*.75)+int(N*.25) == N


def _my_cfg(**kw) -> JumboViTConfig:
    return JumboViTConfig(
        layers=LAYERS,
        dim=DIM,
        heads=HEADS,
        image_size=IMAGE,
        patch_size=PATCH,
        layerscale=True,
        dtype="float32",
        **kw,
    )


def _ref_vit(ref, **kw):
    return ref.modeling.ViT(
        layers=LAYERS,
        dim=DIM,
        heads=HEADS,
        image_size=IMAGE,
        patch_size=PATCH,
        layerscale=True,
        **kw,
    )


def test_classify_forward_parity(ref):
    """Converted reference weights → identical logits, incl. a round trip
    through the torch-layout converters on the way."""
    ref_model = _ref_vit(
        ref, labels=LABELS, posemb="learnable", image_mask_ratio=None
    )
    images = jax.random.normal(jax.random.key(0), (3, IMAGE, IMAGE, 3))
    variables = ref_model.init(jax.random.key(1), images)
    ref_logits = ref_model.apply(variables, images)

    params = reference_encoder_to_jumbo(variables["params"])
    # Chain through the torch converters too: proves the full migration path
    # reference-flax → jumbo-flax → torch → jumbo-flax is lossless.
    torch_state = flax_to_torch_state({"encoder": params})
    params_rt = torch_to_flax_params(torch_state, heads=HEADS)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_rt = jax.tree_util.tree_flatten_with_path(params_rt)[0]
    assert [p for p, _ in flat] == [p for p, _ in flat_rt]
    for (path, a), (_, b) in zip(flat, flat_rt):
        np.testing.assert_array_equal(
            np.asarray(a),
            np.asarray(b),
            err_msg=f"torch round trip altered {jax.tree_util.keystr(path)}",
        )

    my_model = JumboViT(_my_cfg(labels=LABELS, posemb="learnable"))
    my_logits = my_model.apply({"params": params_rt}, images)

    np.testing.assert_allclose(
        np.asarray(my_logits), np.asarray(ref_logits), atol=2e-5, rtol=2e-5
    )


def test_linear_probe_batchnorm_parity(ref):
    """Linear-probe mode: BatchNorm running stats and probe head convert and
    produce identical logits under ``deterministic`` inference."""
    from jumbo_mae_tpu_tpu.interop import reference_head_batch_stats_to_jumbo

    ref_model = _ref_vit(
        ref,
        labels=LABELS,
        posemb="learnable",
        image_mask_ratio=None,
        linear_probing=True,
        batch_norm=True,
    )
    images = jax.random.normal(jax.random.key(9), (3, IMAGE, IMAGE, 3))
    variables = ref_model.init(jax.random.key(10), images)
    # give the running stats non-trivial values so the test can't pass on
    # zero-mean/unit-var defaults
    batch_stats = jax.tree_util.tree_map(
        lambda x: x + 0.3, variables["batch_stats"]
    )
    ref_logits = ref_model.apply(
        {"params": variables["params"], "batch_stats": batch_stats}, images
    )

    params = reference_encoder_to_jumbo(variables["params"])
    my_stats = reference_head_batch_stats_to_jumbo(batch_stats)
    my_model = JumboViT(
        _my_cfg(
            labels=LABELS,
            posemb="learnable",
            linear_probing=True,
            batch_norm=True,
        )
    )
    my_logits = my_model.apply(
        {"params": params, "batch_stats": my_stats}, images
    )
    np.testing.assert_allclose(
        np.asarray(my_logits), np.asarray(ref_logits), atol=2e-5, rtol=2e-5
    )


def test_mae_encoder_masking_parity(ref):
    """MAE mode: same "noise" key at the root → identical mask, restore ids,
    and encoded visible tokens."""
    ref_model = _ref_vit(ref, labels=-1, posemb="sincos2d", image_mask_ratio=0.75)
    images = jax.random.normal(jax.random.key(2), (2, IMAGE, IMAGE, 3))
    variables = ref_model.init(
        {"params": jax.random.key(3), "noise": jax.random.key(4)}, images
    )
    noise_key = jax.random.key(5)
    ref_tokens, ref_mask, ref_restore = ref_model.apply(
        variables, images, rngs={"noise": noise_key}
    )

    params = reference_encoder_to_jumbo(variables["params"])
    my_model = JumboViT(
        _my_cfg(labels=None, posemb="sincos2d", mask_ratio=0.75)
    )
    my_tokens, my_mask, my_restore = my_model.apply(
        {"params": params}, images, rngs={"noise": noise_key}
    )

    np.testing.assert_array_equal(np.asarray(my_restore), np.asarray(ref_restore))
    np.testing.assert_array_equal(np.asarray(my_mask), np.asarray(ref_mask))
    np.testing.assert_allclose(
        np.asarray(my_tokens), np.asarray(ref_tokens), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("norm_pix_loss", [False, True])
def test_mae_pretrain_loss_parity(ref, norm_pix_loss):
    """Full pretrain pipeline: same weights + same mask permutation → same
    masked-MSE loss.

    The two implementations derive their internal mask RNG through different
    module paths (flax folds module names into ``make_rng``), so the
    reference's actually-used permutation is extracted via ``bind`` and
    injected into this model through ``mask_noise``.
    """
    ref_vit = _ref_vit(ref, labels=-1, posemb="sincos2d", image_mask_ratio=0.75)
    ref_dec = ref.modeling.MAEDecoder(
        dec_layers=2,
        dec_dim=32,
        dec_heads=4,
        dec_layerscale=True,
        image_size=IMAGE,
        patch_size=PATCH,
    )
    ref_module = ref.pretraining.PretrainModule(
        model=ref_vit,
        decoder_model=ref_dec,
        image_size=IMAGE,
        norm_pix_loss=norm_pix_loss,
    )
    images_nchw = np.random.RandomState(0).randint(
        0, 256, (2, 3, IMAGE, IMAGE), dtype=np.uint8
    )
    variables = ref_module.init(
        {"params": jax.random.key(6), "noise": jax.random.key(7)}, images_nchw
    )
    noise_key = jax.random.key(8)
    ref_loss = ref_module.apply(variables, images_nchw, rngs={"noise": noise_key})[
        "loss"
    ]

    # Recover the permutation the reference just used: bind replays the same
    # scope path + rng fold as the real apply.
    bound = ref_module.bind(variables, rngs={"noise": noise_key})
    normalized = jnp.moveaxis(images_nchw, 1, 3).astype(jnp.float32) / 0xFF
    normalized = (normalized - IMAGENET_DEFAULT_MEAN) / IMAGENET_DEFAULT_STD
    _, ref_mask, ref_restore = bound.model(normalized, det=False)
    # a noise vector whose argsort reproduces the permutation
    injected_noise = jnp.asarray(ref_restore, jnp.float32) / ref_restore.shape[0]

    params = reference_pretrain_to_jumbo(variables["params"])
    my_model = MAEPretrainModel(
        _my_cfg(labels=None, posemb="sincos2d", mask_ratio=0.75),
        DecoderConfig(
            layers=2, dim=32, heads=4, layerscale=True, dtype="float32"
        ),
        norm_pix_loss=norm_pix_loss,
    )
    images_nhwc = images_nchw.transpose(0, 2, 3, 1)
    out = my_model.apply(
        {"params": params},
        images_nhwc,
        return_reconstruction=True,
        mask_noise=injected_noise,
    )

    np.testing.assert_array_equal(np.asarray(out["mask"]), np.asarray(ref_mask))
    np.testing.assert_allclose(
        float(out["loss"]), float(ref_loss), atol=1e-5, rtol=1e-5
    )
