import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.ops import extract_patches, merge_patches, patch_mse_loss


def test_patch_round_trip():
    imgs = jax.random.normal(jax.random.key(0), (2, 32, 32, 3))
    patches = extract_patches(imgs, 8)
    assert patches.shape == (2, 16, 8 * 8 * 3)
    back = merge_patches(patches, 8)
    np.testing.assert_allclose(np.asarray(back), np.asarray(imgs))


def test_patch_order_is_row_major():
    # image whose pixel value encodes its (row, col) patch cell
    img = np.zeros((1, 4, 4, 1), np.float32)
    img[0, :2, 2:, 0] = 1.0  # patch cell (0, 1)
    patches = np.asarray(extract_patches(jnp.asarray(img), 2))
    np.testing.assert_array_equal(patches[0, 1], np.ones(4, np.float32))
    np.testing.assert_array_equal(patches[0, 0], np.zeros(4, np.float32))


def test_patch_mse_loss_against_dense_oracle():
    key = jax.random.key(1)
    out = jax.random.normal(key, (4, 10, 6))
    tgt = jax.random.normal(jax.random.key(2), (4, 10, 6))
    mask = (jax.random.uniform(jax.random.key(3), (4, 10)) > 0.5).astype(jnp.float32)
    # guarantee at least one masked patch per row
    mask = mask.at[:, 0].set(1.0)

    got = float(patch_mse_loss(out, tgt, mask))
    o, t, m = map(np.asarray, (out, tgt, mask))
    per_patch = ((o - t) ** 2).mean(-1)
    oracle = np.mean(
        [per_patch[b][m[b] > 0].mean() for b in range(4)]
    )
    np.testing.assert_allclose(got, oracle, rtol=1e-6)


def test_patch_mse_loss_no_mask_is_plain_mse():
    out = jnp.ones((2, 3, 4))
    tgt = jnp.zeros((2, 3, 4))
    assert float(patch_mse_loss(out, tgt)) == pytest.approx(1.0)


def test_patch_mse_ignores_unmasked_values():
    tgt = jnp.zeros((1, 4, 2))
    out = jnp.array([[[0.0, 0.0], [9.0, 9.0], [1.0, 1.0], [5.0, 5.0]]])
    mask = jnp.array([[0.0, 0.0, 1.0, 0.0]])  # only patch 2 masked
    assert float(patch_mse_loss(out, tgt, mask)) == pytest.approx(1.0)
