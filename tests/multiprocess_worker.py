"""Worker + shared builders for the REAL multi-process correctness test.

``tests/test_multiprocess.py`` launches this file in N separate processes
(`jax.distributed.initialize` over a local coordinator, gloo CPU collectives)
and also imports it to compute the single-process reference leg — so both
legs construct bit-identical models, optimizers, and batches.

What the multi-process leg exercises for real (claims that were untested in
round 1 — VERDICT item 2):

- ``prefetch_to_device`` assembling global arrays from per-process stripes
  via ``jax.make_array_from_process_local_data``;
- the jitted train step's collectives spanning two processes;
- ``cli.train.evaluate``'s ``process_allgather`` pad-batch protocol with
  genuinely uneven per-process batch counts (3 shards striped over 2 procs);
- per-process validation shard striping (``valid_loader`` with
  ``process_index``/``process_count`` from a live distributed runtime).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

GLOBAL_BATCH = 8
TRAIN_STEPS = 3
IMAGE = 32
LABELS = 10
EVAL_BATCH_PER_PROC = 4


def global_train_batch(step: int) -> dict[str, np.ndarray]:
    rs = np.random.RandomState(100 + step)
    return {
        "images": rs.randint(0, 256, (GLOBAL_BATCH, IMAGE, IMAGE, 3), np.uint8),
        "labels": rs.randint(0, LABELS, (GLOBAL_BATCH,)).astype(np.int32),
    }


def build(mesh):
    """(state, train_step, eval_step) — identical in both legs."""
    from jumbo_mae_tpu_tpu.models import ClassificationModel, preset
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_eval_step,
        make_optimizer,
        make_train_step,
    )

    model = ClassificationModel(
        preset(
            "vit_t16",
            image_size=IMAGE,
            patch_size=16,
            labels=LABELS,
            mask_ratio=None,
            dtype="float32",
        )
    )
    tx = make_optimizer(
        OptimConfig(
            name="adamw",
            learning_rate=1e-3,
            lr_scaling="none",
            warmup_steps=1,
            training_steps=TRAIN_STEPS + 1,
        ),
        global_batch_size=GLOBAL_BATCH,
    )
    example = {
        "images": np.zeros((GLOBAL_BATCH, IMAGE, IMAGE, 3), np.uint8),
        "labels": np.zeros((GLOBAL_BATCH,), np.int32),
    }
    state, sharding = create_sharded_state(
        model, tx, example, mesh, mode="classify"
    )
    train_step = make_train_step(mesh, sharding, mode="classify")
    eval_step = make_eval_step(mesh, sharding, mode="classify")
    return state, train_step, eval_step


def _data_cfg(shards: str):
    from jumbo_mae_tpu_tpu.data import DataConfig

    return DataConfig(valid_shards=shards, image_size=IMAGE, workers=0)


def _pad_batch(sharding):
    from jumbo_mae_tpu_tpu.data import prefetch_to_device

    host_pad = {
        "images": np.zeros((EVAL_BATCH_PER_PROC, IMAGE, IMAGE, 3), np.uint8),
        "labels": np.full((EVAL_BATCH_PER_PROC,), -1, np.int32),
        "valid": np.zeros((EVAL_BATCH_PER_PROC,), bool),
    }
    return next(prefetch_to_device(iter([host_pad]), sharding))


def run_leg(shards: str) -> dict:
    """Train a few steps on striped global batches, then evaluate over the
    striped tar pipeline. Runs in BOTH legs; jax.process_count() decides
    whether striping/padding actually happens."""
    import jax

    from jumbo_mae_tpu_tpu.cli.train import evaluate
    from jumbo_mae_tpu_tpu.data import prefetch_to_device, valid_loader
    from jumbo_mae_tpu_tpu.parallel import MeshConfig, batch_sharding, create_mesh

    n, pid = jax.process_count(), jax.process_index()
    mesh = create_mesh(
        MeshConfig(data=4, fsdp=1), devices=jax.devices()[:4]
    )
    state, train_step, eval_step = build(mesh)
    sharding = batch_sharding(mesh, accum=False)

    per = GLOBAL_BATCH // n

    def stripes():
        for step in range(TRAIN_STEPS):
            g = global_train_batch(step)
            yield {k: v[pid * per : (pid + 1) * per] for k, v in g.items()}

    losses = []
    for batch in prefetch_to_device(stripes(), sharding):
        state, metrics = train_step(state, batch)
        losses.append(float(metrics["loss"]))

    pad = _pad_batch(sharding) if n > 1 else None
    batches = prefetch_to_device(
        valid_loader(
            _data_cfg(shards),
            EVAL_BATCH_PER_PROC if n > 1 else EVAL_BATCH_PER_PROC * 2,
            process_index=pid,
            process_count=n,
        ),
        sharding,
    )
    val = evaluate(eval_step, state, batches, pad)

    # multi-host sample-exact-resume plumbing: every process contributes its
    # own (distinct) cursor to the gathered checkpoint payload, and the REAL
    # restore-side pick (_pick_process_cursor, the same function
    # make_train_iterator calls) returns exactly this process's entry —
    # while a topology mismatch drops to epoch resume
    cursor = None
    if n > 1:
        from jumbo_mae_tpu_tpu.cli.train import (
            _gather_data_cursor,
            _pick_process_cursor,
        )

        gathered = _gather_data_cursor({"workers": [[pid, 10 + pid]], "batches": 5})
        cursor = {
            "process_count": gathered["process_count"],
            "batches": gathered["batches"],
            "mine": _pick_process_cursor(gathered)["workers"],
            "all": gathered["per_process"],
            "mismatch_dropped": _pick_process_cursor(
                dict(gathered, process_count=n + 1)
            )
            is None,
        }
    return {"losses": losses, "val": val, "cursor": cursor}


def fleet_leg(outdir: str) -> dict:
    """2-process fleet-health protocol over a REAL shared run dir: every
    process writes its beacon + its own journal segment dir; after an
    allgather barrier guarantees both are on disk, host 0's aggregator must
    call host 1 (written 3 steps behind, data-wait heavy) a data-wait
    straggler, and the merged journal reader must see both hosts' rows."""
    import jax
    from jax.experimental.multihost_utils import process_allgather

    from jumbo_mae_tpu_tpu.obs.fleet import FleetAggregator, HostBeacon
    from jumbo_mae_tpu_tpu.obs.journal import RunJournal, read_merged_journal
    from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry

    n, pid = jax.process_count(), jax.process_index()
    run_dir = os.path.join(outdir, "fleet_run")
    step = 20 - 3 * pid
    beacon = HostBeacon(os.path.join(run_dir, "fleet"), host=pid)
    beacon.write(
        step=step,
        step_time_ema_s=0.1 * (1 + pid),
        data_wait_fraction=0.05 + 0.55 * pid,
    )
    jdir = os.path.join(
        run_dir, "journal" if pid == 0 else f"journal-host{pid}"
    )
    with RunJournal(jdir, host=pid) as journal:
        journal.event("step", step=step)
    process_allgather(np.asarray([pid]))  # barrier: all beacons+rows landed

    out: dict = {"beacon_step": step}
    if pid == 0:
        events: list[dict] = []
        agg = FleetAggregator(
            os.path.join(run_dir, "fleet"),
            expected_hosts=n,
            lag_steps=2,
            registry=MetricsRegistry(),
            on_event=lambda etype, **p: events.append({"type": etype, **p}),
        )
        summary = agg.scan()
        out["summary_hosts"] = {
            str(h): s["status"] for h, s in summary["hosts"].items()
        }
        out["stragglers"] = summary["stragglers"]
        out["events"] = events
        out["merged_step_hosts"] = sorted(
            e.get("host")
            for e in read_merged_journal(run_dir)
            if e.get("type") == "step"
        )
    process_allgather(np.asarray([pid]))  # host 1 outlives the scan
    return out


def build_fsdp(mesh=None):
    """(state, state_sharding, train_step, mesh) on a data=2 × fsdp=4 mesh
    over 8 global devices — identical in every topology (the single-process
    test leg and the 2-proc × 4-device workers build the same thing)."""
    import jax

    from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
    from jumbo_mae_tpu_tpu.models import ClassificationModel, preset
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
        make_train_step,
    )

    if mesh is None:
        mesh = create_mesh(MeshConfig(data=2, fsdp=4), devices=jax.devices()[:8])
    model = ClassificationModel(
        preset(
            "vit_t16", image_size=IMAGE, patch_size=16, labels=LABELS,
            mask_ratio=None, dtype="float32",
        )
    )
    tx = make_optimizer(
        OptimConfig(
            name="adamw", learning_rate=1e-3, lr_scaling="none",
            warmup_steps=1, training_steps=TRAIN_STEPS + 1,
        ),
        global_batch_size=GLOBAL_BATCH,
    )
    example = {
        "images": np.zeros((GLOBAL_BATCH, IMAGE, IMAGE, 3), np.uint8),
        "labels": np.zeros((GLOBAL_BATCH,), np.int32),
    }
    # min_shard_size=128 so the tiny model's params REALLY shard over fsdp
    state, state_sharding = create_sharded_state(
        model, tx, example, mesh, mode="classify", min_shard_size=128
    )
    train_step = make_train_step(mesh, state_sharding, mode="classify")
    return state, state_sharding, train_step, mesh


def run_leg_fsdp(ckpt_dir: str) -> dict:
    """DP×FSDP leg over 8 global devices (VERDICT r3 item 4: the actual
    pod-slice composition — multiple processes × multiple devices per
    process × parameter sharding). Trains 3 steps on striped global batches
    and Orbax-saves the full sharded state; the test restores it under a
    DIFFERENT process topology and checks it equals the single-process run.
    """
    import jax

    from jumbo_mae_tpu_tpu.parallel import batch_sharding
    from jumbo_mae_tpu_tpu.data import prefetch_to_device
    from jumbo_mae_tpu_tpu.train.checkpoint import CheckpointConfig, Checkpointer

    n, pid = jax.process_count(), jax.process_index()
    state, state_sharding, train_step, mesh = build_fsdp()
    specs = {
        str(s.spec)
        for s in jax.tree_util.tree_leaves(state_sharding.params)
    }
    assert any("fsdp" in s for s in specs), specs
    sharding = batch_sharding(mesh, accum=False)

    per = GLOBAL_BATCH // n

    def stripes():
        for step in range(TRAIN_STEPS):
            g = global_train_batch(step)
            yield {k: v[pid * per : (pid + 1) * per] for k, v in g.items()}

    losses = []
    for batch in prefetch_to_device(stripes(), sharding):
        state, metrics = train_step(state, batch)
        losses.append(float(metrics["loss"]))

    ckpt = Checkpointer(CheckpointConfig(ckpt_dir, async_save=False))
    ckpt.save(TRAIN_STEPS, state, metrics={"val/loss": losses[-1]})
    ckpt.close()
    return {"losses": losses, "fsdp_param_specs": sorted(specs)}


def main():
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    outdir, shards = sys.argv[4], sys.argv[5]
    mode = sys.argv[6] if len(sys.argv) > 6 else "dp"

    import jax

    jax.config.update("jax_platforms", "cpu")
    # cross-process CPU collectives need gloo, set before any backend touch
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=n, process_id=pid
    )
    assert jax.process_count() == n
    if mode == "fsdp":
        result = run_leg_fsdp(os.path.join(outdir, "ckpt"))
    else:
        result = run_leg(shards)
        result["fleet"] = fleet_leg(outdir)
    result |= {"pid": pid, "n_devices": len(jax.devices())}
    with open(os.path.join(outdir, f"proc{pid}.json"), "w") as f:
        json.dump(result, f)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
