"""Config-system tests + end-to-end CLI train smoke runs."""

import json
from pathlib import Path

import pytest

from jumbo_mae_tpu_tpu.config import (
    IMAGENET_TRAIN_SIZE,
    apply_overrides,
    config_from_dict,
    load_config,
    steps_from_epochs,
)

RECIPES = Path(__file__).resolve().parent.parent / "recipes"


def test_defaults_construct():
    cfg = config_from_dict({})
    assert cfg.run.mode == "pretrain"
    assert cfg.optim.name == "adamw"


def test_epochs_resolution():
    cfg = config_from_dict(
        {
            "run": {"train_batch_size": 4096, "epochs": 1600},
            "optim": {"warmup_epochs": 40},
        }
    )
    assert cfg.run.training_steps == IMAGENET_TRAIN_SIZE * 1600 // 4096
    assert cfg.optim.warmup_steps == IMAGENET_TRAIN_SIZE * 40 // 4096
    # optim.training_steps follows run.training_steps for the cosine decay
    assert cfg.optim.training_steps == cfg.run.training_steps


def test_dataset_size_single_source_of_truth():
    # data.dataset_size drives BOTH the epochs→steps math and the resume
    # cursor; the top-level shorthand feeds data.dataset_size too.
    cfg = config_from_dict(
        {
            "run": {"train_batch_size": 100, "epochs": 2},
            "data": {"dataset_size": 1000},
        }
    )
    assert cfg.run.training_steps == 1000 * 2 // 100
    assert cfg.data.dataset_size == 1000

    cfg2 = config_from_dict(
        {"dataset_size": 500, "run": {"train_batch_size": 100, "epochs": 2}}
    )
    assert cfg2.run.training_steps == 500 * 2 // 100
    assert cfg2.data.dataset_size == 500


def test_dataset_size_rejects_non_positive():
    for bad in (0, -5, 1.5, "lots", True):
        with pytest.raises(ValueError, match="dataset_size"):
            config_from_dict({"data": {"dataset_size": bad}})


def test_overrides_dotted_paths():
    doc = apply_overrides({}, ["optim.learning_rate=1e-3", "run.mode=finetune"])
    cfg = config_from_dict(doc)
    assert cfg.optim.learning_rate == 1e-3
    assert cfg.run.mode == "finetune"


def test_repeated_set_flags_accumulate():
    """`--set a=1 --set b=2` must apply BOTH (argparse nargs='*' without
    action='extend' silently drops all but the last --set group)."""
    from jumbo_mae_tpu_tpu.cli.train import build_parser

    ns = build_parser().parse_args(
        ["--set", "run.training_steps=30", "--set", "run.name=x", "b=2"]
    )
    assert ns.overrides == ["run.training_steps=30", "run.name=x", "b=2"]

    doc = apply_overrides({}, ["run.training_steps=30", "run.name=xyz"])
    cfg = config_from_dict(doc)
    assert cfg.run.training_steps == 30
    assert cfg.run.name == "xyz"


def test_dec_overrides_reach_decoder_config():
    """Recipe-surface parity with the reference's --dec-dropout /
    --dec-droppath / --dec-layerscale flags: every DecoderConfig field is
    reachable via model.dec_overrides dotted keys."""
    from jumbo_mae_tpu_tpu.cli.train import build_model

    doc = apply_overrides(
        {},
        [
            "model.dec_overrides.droppath=0.1",
            "model.dec_overrides.dropout=0.05",
            "model.dec_overrides.layerscale=true",
            "model.preset=vit_t16",
        ],
    )
    cfg = config_from_dict(doc)
    model, _, _ = build_model(cfg)
    assert model.decoder_cfg.droppath == 0.1
    assert model.decoder_cfg.dropout == 0.05
    assert model.decoder_cfg.layerscale is True
    # first-class fields still win unless overridden
    assert model.decoder_cfg.layers == cfg.model.dec_layers

    with pytest.raises(TypeError):
        build_model(
            config_from_dict(
                apply_overrides({}, ["model.dec_overrides.bogus=1"])
            )
        )


def test_unknown_key_rejected():
    with pytest.raises(ValueError, match="unknown"):
        config_from_dict({"run": {"bogus_key": 1}})
    with pytest.raises(ValueError, match="sections"):
        config_from_dict({"not_a_section": {}})


def test_all_recipes_parse():
    recipes = sorted(RECIPES.glob("*.yaml"))
    assert len(recipes) >= 8
    for r in recipes:
        cfg = load_config(r)
        assert cfg.run.training_steps > 0


def test_recipe_peak_lr_matches_reference_math():
    cfg = load_config(RECIPES / "pretrain_vit_b16_in1k_1600ep.yaml")
    # blr 1.5e-4 · 4096/256 = 2.4e-3 (SURVEY §6)
    assert abs(cfg.optim.peak_lr(cfg.run.train_batch_size) - 2.4e-3) < 1e-9


def test_checkpoint_config_mode_policy():
    pre = config_from_dict({"run": {"mode": "pretrain"}}).checkpoint_config()
    assert pre.best_mode == "min" and pre.metric_key == "val/loss"
    ft = config_from_dict({"run": {"mode": "finetune"}}).checkpoint_config()
    assert ft.best_mode == "max" and ft.metric_key == "val/acc1"


@pytest.mark.slow
def test_smoke_pretrain_end_to_end(tmp_path):
    """The 10-step CPU smoke: full loop incl. eval, ckpt, metrics JSONL."""
    from jumbo_mae_tpu_tpu.cli.train import train

    cfg = load_config(
        RECIPES / "smoke_cpu.yaml",
        [f"run.output_dir={tmp_path}", "run.eval_interval=5"],
    )
    metrics = train(cfg)
    assert "val/loss" in metrics and metrics["val/loss"] > 0
    out = tmp_path / "smoke_cpu"
    lines = (out / "smoke_cpu-metrics.jsonl").read_text().strip().splitlines()
    assert any("perf/mfu" in json.loads(l) for l in lines)
    assert (out / "ckpt" / "last").is_dir()


@pytest.mark.slow
def test_sample_exact_resume_end_to_end(tmp_path):
    """VERDICT #7 acceptance: train 6 steps straight through vs train 3 +
    restore + 3 more on REAL shards — final params identical, which only
    holds if the data stream resumes sample-exactly (the resume point is
    mid-epoch: 32 samples / batch 8 → step 3 is 24 samples into epoch 0, so
    a coarse epoch-granular cursor would replay epoch 0 and diverge)."""
    import io

    import numpy as np
    from PIL import Image

    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.data import write_tar_samples
    from jumbo_mae_tpu_tpu.train.checkpoint import restore_params_any

    rng = np.random.default_rng(0)
    shard_root = tmp_path / "shards"
    shard_root.mkdir()
    idx = 0
    for s in range(2):
        samples = []
        for _ in range(16):
            img = Image.fromarray(
                rng.integers(0, 256, (48, 48, 3), dtype=np.uint8), "RGB"
            )
            buf = io.BytesIO()
            img.save(buf, format="JPEG", quality=90)
            samples.append(
                {"__key__": f"s{idx:05d}", "jpg": buf.getvalue(),
                 "cls": str(idx % 10).encode()}
            )
            idx += 1
        write_tar_samples(str(shard_root / f"train-{s:04d}.tar"), samples)

    def overrides(out, steps):
        return [
            f"run.output_dir={out}",
            f"run.training_steps={steps}",
            "run.eval_interval=3",
            "run.log_interval=3",
            "run.sanity_eval=false",
            "run.synthetic_data=false",
            f"data.train_shards={shard_root}/train-{{0000..0001}}.tar",
            "data.valid_shards=",
            "data.dataset_size=32",
            "data.shuffle_buffer=8",
            "optim.training_steps=6",
        ]

    train(load_config(RECIPES / "smoke_cpu.yaml", overrides(tmp_path / "a", 6)))

    train(load_config(RECIPES / "smoke_cpu.yaml", overrides(tmp_path / "b", 3)))
    train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            overrides(tmp_path / "b", 6) + ["run.resume=true"],
        )
    )

    pa = restore_params_any(tmp_path / "a" / "smoke_cpu" / "ckpt")
    pb = restore_params_any(tmp_path / "b" / "smoke_cpu" / "ckpt")
    import jax

    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(pa),
        jax.tree_util.tree_leaves_with_path(pb),
    ):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0, rtol=0)


@pytest.mark.slow
def test_sigterm_checkpoints_and_exits_cleanly(tmp_path):
    """Graceful preemption: SIGTERM mid-run → the loop checkpoints at the
    next step boundary and exits 0; the checkpoint resumes normally."""
    import os
    import signal
    import subprocess
    import sys as _sys
    import time
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    cmd = [
        _sys.executable, "-m", "jumbo_mae_tpu_tpu.cli.train",
        "--config", str(RECIPES / "smoke_cpu.yaml"),
        "--set", f"run.output_dir={tmp_path}", "run.training_steps=100000",
        "run.eval_interval=100000", "run.log_interval=5",
        "run.sanity_eval=false",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(repo))
    proc = subprocess.Popen(
        cmd, cwd=str(repo), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        metrics = tmp_path / "smoke_cpu" / "smoke_cpu-metrics.jsonl"
        deadline = time.time() + 300
        while time.time() < deadline and not metrics.exists():
            if proc.poll() is not None:
                raise AssertionError(f"train died early:\n{proc.stdout.read()}")
            time.sleep(1)
        assert metrics.exists(), "training never produced metrics"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:  # never orphan a 100000-step child
            proc.kill()
            proc.wait()
    assert proc.returncode == 0, out
    assert "preemption checkpoint" in out
    last = tmp_path / "smoke_cpu" / "ckpt" / "last"
    steps = [int(p.name) for p in last.iterdir() if p.name.isdigit()]
    assert steps and max(steps) < 100000


@pytest.mark.slow
def test_smoke_finetune_resume(tmp_path):
    """Classify mode end-to-end + true resume continues the step counter."""
    from jumbo_mae_tpu_tpu.cli.train import train

    overrides = [
        f"run.output_dir={tmp_path}",
        "run.mode=finetune",
        "run.training_steps=4",
        "run.eval_interval=2",
        "run.log_interval=2",
        "model.mixup=0.8",
        "model.cutmix=1.0",
        "model.label_smoothing=0.1",
        "optim.warmup_steps=2",
        "optim.training_steps=4",
        "optim.layer_decay=0.75",
    ]
    cfg = load_config(RECIPES / "smoke_cpu.yaml", overrides)
    m1 = train(cfg)
    assert "val/acc1" in m1
    # resume: bump steps, expect continuation not restart
    cfg2 = load_config(
        RECIPES / "smoke_cpu.yaml",
        overrides + ["run.training_steps=6", "optim.training_steps=6", "run.resume=true"],
    )
    m2 = train(cfg2)
    assert "val/acc1" in m2


def test_gather_pick_cursor_preserves_native_marker(monkeypatch):
    """The multi-host gather/pick pair must carry the native-IO substrate
    marker; dropping it would make every pod-scale native resume fail (or
    worse, mis-resume on the worker path)."""
    import numpy as np

    from jumbo_mae_tpu_tpu.cli import train as cli_train

    snap = {"workers": [[0, 12]], "batches": 2, "native_threads": 2}

    class FakeMHU:
        @staticmethod
        def process_allgather(x):
            return np.stack([np.asarray(x), np.asarray(x)])

    monkeypatch.setattr(cli_train.jax, "process_count", lambda: 2)
    monkeypatch.setattr(cli_train.jax, "process_index", lambda: 1)
    import jax.experimental.multihost_utils as mhu

    monkeypatch.setattr(mhu, "process_allgather", FakeMHU.process_allgather)

    gathered = cli_train._gather_data_cursor(snap)
    assert gathered["native_threads"] == 2
    picked = cli_train._pick_process_cursor(gathered)
    assert picked["native_threads"] == 2
    assert picked["workers"] == [[0, 12]]


def test_sweep_ft_grid_matches_reference_loops():
    """recipes/sweep_ft.py replaces the reference's loop_*.sh wd x lr grids:
    the dry run must enumerate the full 4x2 grid and every override set
    must load cleanly against the finetune recipe."""
    import subprocess
    import sys

    repo = RECIPES.parent
    proc = subprocess.run(
        [sys.executable, str(RECIPES / "sweep_ft.py"), "--dry-run"],
        cwd=str(repo),
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("sweep:")]
    assert len(lines) == 8  # 4 weight decays x 2 learning rates
    import ast

    grid = set()
    for ln in lines:
        overrides = ast.literal_eval(ln.split("sweep:", 1)[1].strip())
        cfg = load_config(RECIPES / "finetune_vit_b16.yaml", overrides)
        assert cfg.optim.layer_decay == 0.65
        assert cfg.run.name.startswith("ft_sweep_wd")
        grid.add((cfg.optim.weight_decay, cfg.optim.learning_rate))
    # the reference's loop_1.sh/loop_2.sh grid, exactly
    assert grid == {
        (wd, lr)
        for wd in (0.06, 0.07, 0.08, 0.09)
        for lr in (1e-3, 3e-3)
    }


def test_pipe_mesh_undercoverage_raises(tmp_path):
    """mesh.pipe that strands devices must fail loudly, and the untouched
    data default must auto-fill the data axis (advisor round-4 finding)."""
    from jumbo_mae_tpu_tpu.cli.train import train

    # 8 devices, pipe=3: auto-filled data=2 covers 6 of 8 -> raise
    cfg = load_config(
        RECIPES / "smoke_cpu.yaml",
        [f"run.output_dir={tmp_path}", "mesh.pipe=3"],
    )
    with pytest.raises(ValueError, match="covers only"):
        train(cfg)


def test_synthetic_iterators_respect_model_label_count(devices):
    """Synthetic batches must draw labels from the MODEL's class count:
    out-of-range labels one-hot to all-zero rows, silently zeroing the CE
    loss and pinning accuracy at 1.0 (round-5 fix)."""
    import jax

    from jumbo_mae_tpu_tpu.cli.train import (
        make_train_iterator,
        make_valid_iterator,
    )
    from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh

    cfg = load_config(
        RECIPES / "smoke_cpu.yaml",
        [
            "run.mode=finetune",
            "model.overrides={mask_ratio: null, image_size: 32, patch_size: 4, labels: 10}",
        ],
    )
    mesh = create_mesh(MeshConfig(data=1, fsdp=1))
    it, _, _, _ = make_train_iterator(cfg, mesh, 8, num_labels=10)
    batch = next(it)
    labels = jax.device_get(batch["labels"])
    assert labels.max() < 10 and labels.min() >= 0, labels

    vit = make_valid_iterator(cfg, mesh, 8, num_labels=10)()
    vlabels = jax.device_get(next(vit)["labels"])
    assert vlabels.max() < 10 and vlabels.min() >= 0, vlabels
