"""REAL multi-process execution test: 2 jax.distributed processes (gloo CPU
collectives, local coordinator) vs a single-process reference on the same
global data. See tests/multiprocess_worker.py for exactly what is exercised.
"""

from __future__ import annotations

import io
import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

# subprocess-heavy end-to-end suites: excluded from the <5-min signal
# run (pytest -m "not slow")
pytestmark = pytest.mark.slow

import multiprocess_worker as worker
from jumbo_mae_tpu_tpu.data.tario import write_tar_samples

REPO = Path(__file__).resolve().parent.parent


def _jpeg_bytes(rng: np.random.Generator) -> bytes:
    from PIL import Image

    img = Image.fromarray(rng.integers(0, 256, (48, 48, 3), dtype=np.uint8), "RGB")
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=90)
    return buf.getvalue()


@pytest.fixture(scope="module")
def shards(tmp_path_factory) -> str:
    """3 shards × 8 samples — odd shard count so striping over 2 processes is
    UNEVEN (16 vs 8 samples) and the eval pad protocol actually fires."""
    root = tmp_path_factory.mktemp("mp_shards")
    rng = np.random.default_rng(7)
    idx = 0
    for s in range(3):
        samples = []
        for _ in range(8):
            samples.append(
                {
                    "__key__": f"val{idx:05d}",
                    "jpg": _jpeg_bytes(rng),
                    "cls": str(idx % worker.LABELS).encode(),
                }
            )
            idx += 1
        write_tar_samples(str(root / f"val-{s:04d}.tar"), samples)
    return str(root / "val-{0000..0002}.tar")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_workers(tmp_path, shards, *, devices_per_proc=2, mode="dp"):
    """Run 2 jax.distributed worker processes to completion; return their
    JSON results."""
    from jumbo_mae_tpu_tpu.utils.procenv import cpu_subprocess_env, host_cache_dir

    env = cpu_subprocess_env(devices_per_proc, compile_cache=host_cache_dir(REPO))
    env["PYTHONPATH"] = f"{REPO}:{Path(__file__).parent}"

    port = _free_port()
    # log to files, not PIPE: an undrained pipe buffer would deadlock a
    # chatty worker (XLA/gloo warnings) against the poll loop below
    logs = [open(tmp_path / f"worker{pid}.log", "w+") for pid in (0, 1)]
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                str(Path(__file__).parent / "multiprocess_worker.py"),
                str(pid),
                "2",
                str(port),
                str(tmp_path),
                shards,
                mode,
            ],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid, log in zip((0, 1), logs)
    ]
    # fail fast: if one worker dies (e.g. before reaching the distributed-init
    # barrier), kill the survivor instead of waiting out its timeout
    import time

    deadline = time.monotonic() + 600
    while any(p.poll() is None for p in procs):
        if any(p.poll() not in (None, 0) for p in procs) or (
            time.monotonic() > deadline
        ):
            for q in procs:
                if q.poll() is None:
                    q.kill()
            break
        time.sleep(0.5)
    outputs = []
    for p, log in zip(procs, logs):
        p.wait()
        log.seek(0)
        outputs.append(log.read())
        log.close()
    for p, out in zip(procs, outputs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"

    return [json.load(open(tmp_path / f"proc{pid}.json")) for pid in (0, 1)]


def test_two_process_train_and_eval_match_single_process(shards, tmp_path):
    results = _launch_workers(tmp_path, shards, devices_per_proc=2, mode="dp")
    # both processes saw 4 global devices and identical global losses
    for r in results:
        assert r["n_devices"] == 4
    np.testing.assert_allclose(
        results[0]["losses"], results[1]["losses"], rtol=1e-6
    )
    np.testing.assert_allclose(
        [results[0]["val"][k] for k in sorted(results[0]["val"])],
        [results[1]["val"][k] for k in sorted(results[1]["val"])],
        rtol=1e-6,
    )

    # multi-host cursor gather: host-0's saved payload carries BOTH
    # processes' distinct cursors, and each process picked its own back
    for pid, r in enumerate(results):
        c = r["cursor"]
        assert c["process_count"] == 2 and c["batches"] == 5
        assert c["mine"] == [[pid, 10 + pid]]
        assert c["all"] == [[[0, 10]], [[1, 11]]]
        assert c["mismatch_dropped"] is True

    # fleet protocol over the REAL shared run dir: host 1 wrote its beacon
    # 3 steps behind with a heavy data-wait fraction → host 0's aggregator
    # flags it a data-wait straggler, and the merged journal reader returns
    # both hosts' rows
    fleet = results[0]["fleet"]
    assert fleet["summary_hosts"] == {"0": "ok", "1": "straggler"}
    assert fleet["stragglers"] == [1]
    strag = [e for e in fleet["events"] if e["type"] == "fleet_straggler"]
    assert len(strag) == 1
    assert strag[0]["host_id"] == 1 and strag[0]["symptom"] == "data_wait"
    assert fleet["merged_step_hosts"] == [0, 1]
    assert results[1]["fleet"]["beacon_step"] == 17

    # single-process reference on the same global batches + full valid set
    ref = worker.run_leg(shards)
    np.testing.assert_allclose(
        results[0]["losses"], ref["losses"], atol=1e-5, rtol=1e-5
    )
    assert sorted(results[0]["val"]) == sorted(ref["val"])
    for k in ref["val"]:
        np.testing.assert_allclose(
            results[0]["val"][k], ref["val"][k], atol=1e-5, rtol=1e-5
        )


def test_two_process_four_device_fsdp_matches_single_process(tmp_path):
    """The pod-slice composition the r3 verdict flagged untested: 2
    jax.distributed processes × 4 devices each, params REALLY sharded over
    fsdp=4, vs the same global computation in one process over 8 virtual
    devices — identical losses. The workers' Orbax checkpoint (written under
    process_count=2) then restores in THIS single process (topology change)
    and equals the single-process leg's final state."""
    import jax

    results = _launch_workers(tmp_path, "unused", devices_per_proc=4, mode="fsdp")
    for r in results:
        assert r["n_devices"] == 8
        assert any("fsdp" in s for s in r["fsdp_param_specs"])
    np.testing.assert_allclose(
        results[0]["losses"], results[1]["losses"], rtol=1e-6
    )

    # same computation, one process (this one: 8 virtual devices)
    from jumbo_mae_tpu_tpu.parallel import batch_sharding

    state, state_sharding, train_step, mesh = worker.build_fsdp()
    sharding = batch_sharding(mesh, accum=False)
    losses = []
    for step in range(worker.TRAIN_STEPS):
        batch = jax.device_put(worker.global_train_batch(step), sharding)
        state, metrics = train_step(state, batch)
        losses.append(float(metrics["loss"]))
    np.testing.assert_allclose(results[0]["losses"], losses, atol=1e-5, rtol=1e-5)

    # cross-topology restore: 2-process checkpoint → 1-process state
    from jumbo_mae_tpu_tpu.train.checkpoint import (
        CheckpointConfig,
        Checkpointer,
    )

    ckpt = Checkpointer(
        CheckpointConfig(str(tmp_path / "ckpt"), async_save=False)
    )
    restored, _ = ckpt.restore(state, sharding=state_sharding)
    ckpt.close()
    assert int(restored.step) == worker.TRAIN_STEPS
    for a, b in zip(
        jax.tree_util.tree_leaves(restored.params),
        jax.tree_util.tree_leaves(state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        )
