import numpy as np
import pytest

from jumbo_mae_tpu_tpu.ops import sincos2d_positional_embedding


def test_shape_and_dtype():
    t = sincos2d_positional_embedding(14, 14, 512)
    assert t.shape == (14, 14, 512)
    assert t.dtype == np.float32


def test_matches_reference_formula():
    """Oracle re-derivation of /root/reference/src/utils.py:114-121 semantics:
    four dim//4 bands [sin(a), cos(a), sin(b), cos(b)] with an
    endpoint-inclusive linspace frequency ladder."""
    n, dim = 4, 16
    freqs = 1.0 / (10000.0 ** np.linspace(0, 1, dim // 4))
    a = np.outer(np.arange(n, dtype=np.float64), freqs)
    b = np.outer(np.arange(n, dtype=np.float64), freqs)
    a = np.broadcast_to(a[None, :, :], (n, n, dim // 4))
    b = np.broadcast_to(b[:, None, :], (n, n, dim // 4))
    oracle = np.concatenate([np.sin(a), np.cos(a), np.sin(b), np.cos(b)], axis=2)
    got = sincos2d_positional_embedding(n, n, dim)
    np.testing.assert_allclose(got, oracle.astype(np.float32), atol=1e-6)


def test_matches_reference_formula_non_square():
    """Non-square grid: pins the reference's swapped nrows/ncols broadcast
    layout that checkpoints depend on (see posemb.py module docstring)."""
    ncols, nrows, dim = 3, 5, 8
    freqs = 1.0 / (10000.0 ** np.linspace(0, 1, dim // 4))
    a = np.outer(np.arange(nrows, dtype=np.float64), freqs)
    b = np.outer(np.arange(ncols, dtype=np.float64), freqs)
    a = np.broadcast_to(a[None, :, :], (ncols, nrows, dim // 4))
    b = np.broadcast_to(b[:, None, :], (ncols, nrows, dim // 4))
    oracle = np.concatenate([np.sin(a), np.cos(a), np.sin(b), np.cos(b)], axis=2)
    got = sincos2d_positional_embedding(ncols, nrows, dim)
    np.testing.assert_allclose(got, oracle.astype(np.float32), atol=1e-6)


def test_distinct_positions_distinct_codes():
    t = sincos2d_positional_embedding(7, 7, 64).reshape(-1, 64)
    # pairwise distinct rows
    assert len({row.tobytes() for row in t}) == 49


def test_rejects_bad_dim():
    with pytest.raises(ValueError):
        sincos2d_positional_embedding(4, 4, 30)
