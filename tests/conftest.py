"""Test harness: run everything on a virtual 8-device CPU mesh.

The surrounding environment registers a remote-TPU ("axon") PJRT plugin via a
``sitecustomize.py`` that imports jax at interpreter start with
``JAX_PLATFORMS=axon`` — so mutating ``os.environ`` here is too late (the
config default was already captured). ``jax.config.update`` works as long as
no backend has been *initialized* yet, which holds at conftest import time.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (may already be imported by sitecustomize)

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache: the suite's cost is XLA compiles of tiny
# train steps, which are identical run-to-run — cache them across processes.
# Keyed per host (utils/procenv.py host_fingerprint): XLA:CPU AOT entries
# from another machine deserialize through a slow mismatch path that round 4
# showed can straggle collective rendezvous into its abort window.
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
import sys  # noqa: E402

sys.path.insert(0, _repo_root)
from jumbo_mae_tpu_tpu.utils.procenv import host_cache_dir  # noqa: E402

jax.config.update("jax_compilation_cache_dir", host_cache_dir(_repo_root))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.25)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
