"""Test harness: run everything on a virtual 8-device CPU mesh.

Must set the environment before the first ``import jax`` anywhere in the test
process — conftest import time is the earliest reliable hook pytest gives us.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
