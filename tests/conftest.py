"""Test harness: run everything on a virtual 8-device CPU mesh.

The surrounding environment registers a remote-TPU ("axon") PJRT plugin via a
``sitecustomize.py`` that imports jax at interpreter start with
``JAX_PLATFORMS=axon`` — so mutating ``os.environ`` here is too late (the
config default was already captured). ``jax.config.update`` works as long as
no backend has been *initialized* yet, which holds at conftest import time.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (may already be imported by sitecustomize)

jax.config.update("jax_platforms", "cpu")
_repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
import sys  # noqa: E402

sys.path.insert(0, _repo_root)

# Persistent compilation cache: OPT-IN for the main test process
# (JUMBO_COMPILE_CACHE=1). The seed enabled it unconditionally, and the
# round-6 seed triage traced the "seed tests failing" note to exactly that:
# with this jaxlib (0.4.36), executing a train step deserialized from the
# XLA:CPU AOT cache SIGABRTs the whole pytest session, load-order
# dependently (reproduced at test_checkpoint.py::test_resume_equals_
# uninterrupted and test_tools_eval_extract.py::test_eval_only_which_best;
# every test passes with the cache off). Correctness beats the compile-time
# saving, so the default is off. When opted in, the directory is claimed
# crash-safe (utils/procenv.claim_compile_cache): a process killed
# mid-cache-write — the tier-1 gate's own `timeout -k` — leaves permanently
# truncated entries (jax's LRUCache.put is non-atomic and never
# overwrites), and the claim purges the cache after any unclean shutdown.
if os.environ.get("JUMBO_COMPILE_CACHE"):
    from jumbo_mae_tpu_tpu.utils.procenv import (
        claim_compile_cache,
        host_cache_dir,
    )

    jax.config.update(
        "jax_compilation_cache_dir",
        claim_compile_cache(host_cache_dir(_repo_root)),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.25)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

# The serving warm-start cache (infer/warmcache.py) is default-ON for real
# processes but must be inert under test: engines constructed by unrelated
# tests would otherwise share executables through ~/.cache and the
# compile-count contracts (compiles-exactly-once, warmup totals) would
# depend on which test ran first. Tests that exercise the cache pass an
# explicit warm_cache=<tmp dir>, which overrides this.
os.environ.setdefault("JUMBO_WARMCACHE", "0")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
