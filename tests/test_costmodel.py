"""Compiled-cost observability contracts (ISSUE round 12).

Four layers, one extraction: XLA's own cost/memory accounting read off every
compiled executable (never triggering a compile), the analytic roofline
capacity model over those costs, the schema-versioned bench ledger
(BENCH_HISTORY.jsonl), and ``tools/perf_doctor.py``'s regression verdicts.

The load-bearing invariants:

- the train step dispatches through ONE AOT executable — cost extraction is
  a free readout, never a second compile of the hot path;
- engine bucket executables publish per-bucket costs, flops grow with the
  bucket, and the int8 variant's argument bytes shrink vs f32;
- extraction degrades to ``None`` on backends that report nothing (PJRT
  plugins may legally return empty analyses) — it must never raise;
- two bench runs on the same host get the SAME ledger ``env_key`` (the CI
  smoke asserts this across real subprocesses), and perf_doctor exits 2
  exactly when a leg moves beyond the noise band, naming the leg AND the
  dominant roofline term.
"""

import json

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.obs.costmodel import (
    COST_SCHEMA_VERSION,
    ProgramCost,
    cost_asdict,
    extract_cost,
    publish_cost,
    utilization_report,
)
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry
from jumbo_mae_tpu_tpu.obs.perfledger import (
    append_row,
    comparable_env,
    env_key,
    make_row,
    read_ledger,
    resolve_history_path,
)
from jumbo_mae_tpu_tpu.obs.perfmodel import (
    chip_spec,
    detect_chip,
    dp_comm_bytes,
    fsdp_comm_bytes,
    prediction_asdict,
    publish_drift,
    roofline,
)

COST_KEYS = {
    "cost_schema",
    "program",
    "flops",
    "bytes_accessed",
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
    "peak_bytes",
    "generated_code_bytes",
    "source",
}


# ------------------------------------------------------- train-step costs


@pytest.fixture(scope="module")
def train_step_cost():
    """One tiny pretrain step on the CPU mesh, stepped twice, plus its
    extracted cost — shared across the class below (the compile is the
    expensive part)."""
    import jax

    from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
    from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
        make_train_step,
    )

    tiny = preset("vit_t16", image_size=32, patch_size=8, dtype="float32")
    module = MAEPretrainModel(
        tiny.replace(mask_ratio=0.75, labels=None),
        DecoderConfig(layers=1, dim=32, heads=2, dtype="float32"),
    )
    opt = OptimConfig(
        name="adamw",
        learning_rate=1e-3,
        lr_scaling="none",
        warmup_steps=2,
        training_steps=20,
    )
    batch = {
        "images": np.random.RandomState(0)
        .randint(0, 256, (4, 32, 32, 3))
        .astype(np.uint8)
    }
    mesh = create_mesh(MeshConfig(data=1, fsdp=1))
    state, sharding = create_sharded_state(
        module,
        make_optimizer(opt, global_batch_size=256),
        batch,
        mesh,
        mode="pretrain",
        init_seed=0,
        rng_seed=0,
    )
    step = make_train_step(mesh, sharding, mode="pretrain")
    for _ in range(2):
        state, metrics = step(state, batch)
    jax.block_until_ready(metrics["loss"])
    execs = step.executables
    cost = extract_cost(next(iter(execs.values())), "train_step")
    return step, execs, cost


class TestTrainStepCost:
    def test_single_aot_executable_no_hot_path_recompile(self, train_step_cost):
        """Two steps at one batch shape → exactly one executable. The AOT
        handle IS the dispatched program, so reading its cost_analysis can
        never add a compile to the hot path."""
        _, execs, _ = train_step_cost
        assert len(execs) == 1

    def test_cost_extraction_nonzero(self, train_step_cost):
        _, _, cost = train_step_cost
        assert cost is not None and cost.program == "train_step"
        assert cost.flops > 0 and cost.bytes_accessed > 0
        assert cost.source in ("compiled", "lowered")
        if cost.source == "compiled":
            # peak is live-at-once: at least the scratch, at most the sum
            assert cost.peak_bytes >= cost.temp_bytes
            assert cost.peak_bytes <= (
                cost.argument_bytes + cost.output_bytes + cost.temp_bytes
            )

    def test_cost_asdict_schema_stable(self, train_step_cost):
        """Journal events and ledger rows carry this dict — the key set is
        the offline-reader contract and only moves with COST_SCHEMA_VERSION."""
        _, _, cost = train_step_cost
        d = cost_asdict(cost)
        assert set(d) == COST_KEYS
        assert d["cost_schema"] == COST_SCHEMA_VERSION
        json.dumps(d)  # journal-serializable as-is

    def test_publish_cost_sets_labeled_gauges(self, train_step_cost):
        _, _, cost = train_step_cost
        reg = MetricsRegistry()
        publish_cost(cost, bucket="", dtype="float32", registry=reg)
        fam = reg.gauge(
            "xla_flops", labels=("program", "bucket", "dtype")
        )
        assert fam.labels("train_step", "", "float32").value == cost.flops
        peak = reg.gauge("xla_peak_bytes", labels=("program", "bucket", "dtype"))
        assert peak.labels("train_step", "", "float32").value == cost.peak_bytes

    def test_utilization_split_hfu_vs_mfu(self, train_step_cost):
        """HFU counts what XLA actually scheduled (remat recompute included),
        MFU what the math requires — with XLA flops above analytic flops the
        split must order the same way."""
        _, _, cost = train_step_cost
        rep = utilization_report(
            cost.flops * 0.8, cost.flops, steps_per_sec=10.0, peak_tflops=275.0
        )
        assert rep.hardware_flops_utilization > rep.model_flops_utilization > 0
        assert rep.achieved_hardware_tflops == pytest.approx(
            cost.flops * 10.0 / 1e12
        )


class TestExtractionDegrades:
    """A backend that reports nothing yields None/partial — never a raise."""

    def test_cost_analysis_raises(self):
        class Ex:
            def cost_analysis(self):
                raise NotImplementedError("plugin says no")

        assert extract_cost(Ex(), "p") is None

    def test_cost_analysis_empty(self):
        class Ex:
            def cost_analysis(self):
                return []

        assert extract_cost(Ex(), "p") is None

    def test_memory_analysis_missing_degrades_to_lowered(self):
        class Ex:
            def cost_analysis(self):
                return [{"flops": 42.0, "bytes accessed": 7.0}]

            def memory_analysis(self):
                raise NotImplementedError

        cost = extract_cost(Ex(), "p")
        assert cost.source == "lowered"
        assert cost.flops == 42.0 and cost.bytes_accessed == 7.0
        assert cost.peak_bytes == 0.0

    def test_publish_none_is_noop(self):
        publish_cost(None, registry=MetricsRegistry())


# ---------------------------------------------------------- engine costs


def _tiny_cfg(extra=()):
    from pathlib import Path

    from jumbo_mae_tpu_tpu.config import load_config

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    return load_config(
        recipe,
        [
            "model.overrides.dtype=float32",
            "model.dec_layers=1",
            "model.dec_dim=32",
            "model.dec_heads=2",
            "model.dec_dtype=float32",
        ]
        + list(extra),
    )


def _images(n, size=32, seed=0):
    return (
        np.random.RandomState(seed).randint(0, 256, (n, size, size, 3))
    ).astype(np.uint8)


@pytest.fixture(scope="module")
def engine_f32(tmp_path_factory):
    from jumbo_mae_tpu_tpu.infer import InferenceEngine

    reg = MetricsRegistry()
    cache = tmp_path_factory.mktemp("warmcache")
    eng = InferenceEngine(
        _tiny_cfg(), max_batch=8, warm_cache=cache, registry=reg
    )
    eng.features(_images(1))
    eng.features(_images(2))
    return eng, reg, cache


class TestEngineCosts:
    def test_per_bucket_costs_and_flops_ordering(self, engine_f32):
        eng, _, _ = engine_f32
        keys = sorted(eng.cost_reports, key=lambda k: k[1])
        assert [k[1] for k in keys] == [1, 2]
        c1, c2 = (eng.cost_reports[k] for k in keys)
        assert 0 < c1.flops <= c2.flops

    def test_bucket_gauges_published(self, engine_f32):
        eng, reg, _ = engine_f32
        flops = reg.gauge("xla_flops", labels=("program", "bucket", "dtype"))
        child = flops.labels("features:cls", "2", "float32")
        assert child.value == eng.cost_reports[("features:cls", 2)].flops
        compile_g = reg.gauge(
            "infer_bucket_compile_seconds", labels=("task", "bucket")
        )
        assert compile_g.labels("features:cls", "2").value > 0
        size_g = reg.gauge("infer_executable_bytes", labels=("task", "bucket"))
        assert size_g.labels("features:cls", "2").value > 0

    def test_drift_gauge_after_dispatch(self, engine_f32):
        eng, reg, _ = engine_f32
        drift = reg.gauge("perf_predict_vs_measured", labels=("program",))
        assert drift.labels("features:cls/b2").value > 0

    def test_warmcache_entry_meta(self, engine_f32):
        """The cache sidecar carries compile seconds, blob size, and the
        cost snapshot — a warm start can account for what it skipped."""
        eng, _, _ = engine_f32
        meta = eng.warmcache.entry_meta(eng._entry_name("features:cls", 1))
        assert meta is not None
        assert meta["compile_seconds"] > 0
        assert meta["executable_bytes"] > 0
        assert meta["cost"]["cost_schema"] == COST_SCHEMA_VERSION

    def test_warm_start_publishes_cost_and_saved_seconds(self, engine_f32):
        """A second engine over the same cache loads instead of compiling —
        and still publishes per-bucket costs plus the compile time it saved."""
        from jumbo_mae_tpu_tpu.infer import InferenceEngine

        eng, _, cache = engine_f32
        reg2 = MetricsRegistry()
        compiles = []
        eng2 = InferenceEngine(
            _tiny_cfg(),
            max_batch=8,
            warm_cache=cache,
            registry=reg2,
            on_compile=lambda task, bucket: compiles.append((task, bucket)),
        )
        eng2.features(_images(2))
        assert compiles == []  # served from the warm cache
        assert (("features:cls", 2)) in eng2.cost_reports
        saved = reg2.counter("infer_warmcache_saved_seconds_total", labels=("task",))
        assert saved.labels("features:cls").value > 0

    def test_int8_argument_bytes_below_f32(self, engine_f32):
        from jumbo_mae_tpu_tpu.infer import InferenceEngine

        eng, _, _ = engine_f32
        eng8 = InferenceEngine(
            _tiny_cfg(),
            max_batch=8,
            quant="int8",
            warm_cache=False,
            registry=MetricsRegistry(),
        )
        eng8.features(_images(1))
        (key,) = [k for k in eng8.cost_reports if k[1] == 1]
        c8 = eng8.cost_reports[key]
        cf = eng.cost_reports[("features:cls", 1)]
        if c8.source == "compiled" and cf.source == "compiled":
            assert c8.argument_bytes < cf.argument_bytes


# -------------------------------------------------------------- roofline


class TestRoofline:
    CHIP = chip_spec("TPU v4")

    def test_chip_spec_normalizes_and_defaults(self):
        assert chip_spec("TPU v5 lite").name == "v5e"
        assert chip_spec("TPU v4").peak_tflops == 275.0
        assert chip_spec("mystery accelerator").name == "cpu"
        assert detect_chip().name  # never raises, whatever the backend

    def test_bound_transitions(self):
        """Small flops at big bytes → bandwidth-bound; scale flops up and
        the same program goes compute-bound; add enough comm and it flips
        again."""
        lo = roofline(1e9, 1e9, self.CHIP)
        assert lo.bound == "bandwidth"
        hi = roofline(1e15, 1e9, self.CHIP)
        assert hi.bound == "compute"
        comm = roofline(1e9, 1e9, self.CHIP, comm_bytes=1e12)
        assert comm.bound == "comm"

    def test_step_time_monotone_in_flops_and_bytes(self):
        t = [
            roofline(f, 1e9, self.CHIP).step_time_s
            for f in (1e12, 1e13, 1e14, 1e15)
        ]
        assert t == sorted(t)
        t = [
            roofline(1e9, b, self.CHIP).step_time_s
            for b in (1e9, 1e10, 1e11)
        ]
        assert t == sorted(t)

    def test_throughput_scales_with_batch(self):
        """Per-item cost fixed → throughput grows linearly with batch."""
        p1 = roofline(1e12, 1e10, self.CHIP, batch=1)
        p8 = roofline(8e12, 8e10, self.CHIP, batch=8)
        assert p8.throughput_per_sec == pytest.approx(
            p1.throughput_per_sec, rel=1e-6
        )
        assert p8.step_time_s == pytest.approx(8 * p1.step_time_s, rel=1e-6)

    def test_comm_terms(self):
        # FSDP: all-gather fwd + all-gather bwd + reduce-scatter = 3·P·(n-1)/n
        assert fsdp_comm_bytes(1e9, fsdp=4) == pytest.approx(3e9 * 3 / 4)
        assert fsdp_comm_bytes(1e9, fsdp=1) == 0.0
        # DP ring all-reduce = 2·P·(n-1)/n
        assert dp_comm_bytes(1e9, dp=2) == pytest.approx(2e9 * 1 / 2)
        assert dp_comm_bytes(1e9, dp=1) == 0.0

    def test_prediction_asdict_round_trips(self):
        d = prediction_asdict(roofline(1e12, 1e10, self.CHIP, batch=4))
        json.dumps(d)
        assert d["bound"] in ("compute", "bandwidth", "comm")
        assert d["step_time_s"] > 0

    def test_publish_drift(self):
        reg = MetricsRegistry()
        ratio = publish_drift(0.010, 0.020, program="train_step", registry=reg)
        assert ratio == pytest.approx(2.0)
        fam = reg.gauge("perf_predict_vs_measured", labels=("program",))
        assert fam.labels("train_step").value == pytest.approx(2.0)
        pred = reg.gauge("perf_predicted_step_seconds", labels=("program",))
        assert pred.labels("train_step").value == pytest.approx(0.010)


class TestDeviceKindNormalizer:
    def test_known_spellings_collapse(self):
        from jumbo_mae_tpu_tpu.obs.mfu import (
            PEAK_TFLOPS,
            lookup_peak_tflops,
            normalize_device_kind,
        )

        assert normalize_device_kind("TPU v4") == "v4"
        assert normalize_device_kind("TPU v5 lite") == "v5e"
        assert normalize_device_kind("TPU v5litepod-8") == "v5e"
        assert normalize_device_kind("TPU v6 lite") == "v6e"
        assert normalize_device_kind("Tesla T4") is None
        assert lookup_peak_tflops("TPU v5 lite") == PEAK_TFLOPS["v5e"]

    def test_unknown_kind_warns_and_sets_gauge(self, capsys):
        from jumbo_mae_tpu_tpu.obs import metrics as M
        from jumbo_mae_tpu_tpu.obs.mfu import lookup_peak_tflops

        reg = MetricsRegistry()
        old = M.get_registry()
        M.set_registry(reg)
        try:
            assert lookup_peak_tflops("weird-chip-x1", default=1.5) == 1.5
        finally:
            M.set_registry(old)
        assert "weird-chip-x1" in capsys.readouterr().err
        fam = reg.gauge("mfu_peak_unknown", labels=("kind",))
        assert fam.labels("weird-chip-x1").value == 1


# ------------------------------------------------------------ perf ledger


class TestPerfLedger:
    def test_row_shape_and_env_key_stability(self):
        r1 = make_row(bench="train", metric="m", legs={"ms": 1.0})
        r2 = make_row(bench="train", metric="m", legs={"ms": 2.0})
        for r in (r1, r2):
            assert r["schema"] == 1 and r["bench"] == "train"
            assert "env" in r and "env_key" in r and "legs" in r
        # same process, same host → identical comparability key (the CI
        # smoke asserts this across two real bench subprocesses)
        assert r1["env_key"] == r2["env_key"]
        assert r1["env_key"] == env_key(comparable_env())
        # per-process noise must NOT leak into comparability
        assert "pid" not in r1["env"] and "argv" not in r1["env"]

    def test_append_read_round_trip(self, tmp_path):
        path = tmp_path / "hist.jsonl"
        for v in (1.0, 2.0):
            assert append_row(path, make_row(bench="train", metric="m", legs={"ms": v}))
        rows = read_ledger(path)
        assert [r["legs"]["ms"] for r in rows] == [1.0, 2.0]

    def test_torn_lines_tolerated(self, tmp_path):
        """A crash mid-write leaves a torn line — possibly with NO trailing
        newline. The next append must land intact and the reader must skip
        only the torn fragment."""
        path = tmp_path / "hist.jsonl"
        append_row(path, make_row(bench="train", metric="m", legs={"ms": 1.0}))
        with open(path, "a") as f:
            f.write('{"torn": tru')  # no newline: worst-case torn write
        assert append_row(path, make_row(bench="train", metric="m", legs={"ms": 2.0}))
        rows = read_ledger(path)
        assert [r["legs"]["ms"] for r in rows] == [1.0, 2.0]

    def test_append_never_raises(self, tmp_path):
        target = tmp_path / "dir_not_file"
        target.mkdir()
        assert append_row(target, {"schema": 1}) is False

    def test_resolve_history_path(self, monkeypatch):
        monkeypatch.delenv("BENCH_HISTORY", raising=False)
        assert resolve_history_path("x.jsonl").name == "x.jsonl"
        assert str(resolve_history_path(None)) == "BENCH_HISTORY.jsonl"
        monkeypatch.setenv("BENCH_HISTORY", "/tmp/h.jsonl")
        assert str(resolve_history_path(None)) == "/tmp/h.jsonl"
        assert resolve_history_path("off") is None
        monkeypatch.setenv("BENCH_HISTORY", "off")
        assert resolve_history_path(None) is None


# ------------------------------------------------------------ perf_doctor


def _ledger(tmp_path, values, *, leg="ms_step_bf16", metric="ms_step"):
    import tools.perf_doctor  # noqa: F401 - ensures tools is importable

    path = tmp_path / "BENCH_HISTORY.jsonl"
    pred = prediction_asdict(roofline(5e10, 2e9, chip_spec("cpu"), batch=8))
    for v in values:
        append_row(
            path,
            make_row(
                bench="train",
                metric=metric,
                legs={leg: v},
                quantiles={"p50_ms": v},
                prediction=pred,
            ),
        )
    return path


class TestPerfDoctor:
    def test_exit_0_on_steady_history(self, tmp_path):
        import tools.perf_doctor as doctor

        path = _ledger(tmp_path, [100.0, 102.0, 98.0, 101.0])
        assert doctor.main([str(path)]) == 0

    def test_exit_2_names_leg_and_roofline_term(self, tmp_path):
        import tools.perf_doctor as doctor

        path = _ledger(tmp_path, [100.0, 102.0, 98.0, 160.0])
        out = tmp_path / "report.md"
        assert doctor.main([str(path), "--out", str(out)]) == 2
        report = out.read_text()
        assert "ms_step_bf16" in report and "REGRESSION" in report
        assert "roofline term: bandwidth" in report

    def test_higher_is_better_legs_regress_on_drop(self, tmp_path):
        import tools.perf_doctor as doctor

        path = _ledger(
            tmp_path,
            [1000.0, 990.0, 1010.0, 600.0],
            leg="engine_imgs_per_sec",
            metric="imgs_per_sec",
        )
        out = tmp_path / "report.md"
        assert doctor.main([str(path), "--out", str(out)]) == 2
        assert "engine_imgs_per_sec" in out.read_text()

    def test_improvement_is_not_a_regression(self, tmp_path):
        import tools.perf_doctor as doctor

        path = _ledger(tmp_path, [100.0, 102.0, 98.0, 60.0])
        assert doctor.main([str(path)]) == 0

    def test_noise_band_is_respected(self, tmp_path):
        import tools.perf_doctor as doctor

        path = _ledger(tmp_path, [100.0, 102.0, 98.0, 106.0])
        assert doctor.main([str(path), "--noise", "0.08"]) == 0
        assert doctor.main([str(path), "--noise", "0.02"]) == 2

    def test_exit_2_on_missing_or_empty(self, tmp_path):
        import tools.perf_doctor as doctor

        assert doctor.main([str(tmp_path / "nope.jsonl")]) == 2
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert doctor.main([str(empty)]) == 2

    def test_journal_fallback_reports_compiled_programs(self, tmp_path):
        """Pointed at a run journal instead of a ledger, the doctor renders
        the compiled-program table (cost basis of the run) instead of
        exiting confused."""
        import tools.perf_doctor as doctor

        from jumbo_mae_tpu_tpu.obs.journal import RunJournal

        with RunJournal(tmp_path) as j:
            j.event(
                "compiled_program",
                program="train_step",
                flops=1e9,
                bytes_accessed=1e8,
                cost_schema=COST_SCHEMA_VERSION,
            )
        out = tmp_path / "report.md"
        assert doctor.main([str(tmp_path), "--out", str(out)]) == 0
        assert "train_step" in out.read_text()
