"""Execute the multislice hybrid-mesh branch with fake multi-slice devices.

``create_hybrid_device_mesh`` is reachable only with devices that carry a
``slice_index`` — real CPU devices never do, so before this test the one
GSPMD-wiring branch that would first run on a production pod had zero
execution coverage (VERDICT r2 weak #4). Fake device objects are enough:
``mesh_utils`` and ``jax.sharding.Mesh`` only read ``id`` /
``process_index`` / ``slice_index`` / ``platform`` here.
"""

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.parallel.mesh import MeshConfig, create_mesh


class FakeDevice:
    def __init__(self, i: int, slice_index: int, ndev_per_slice: int):
        self.id = i
        self.slice_index = slice_index
        self.process_index = slice_index
        self.platform = "cpu"
        self.device_kind = "fake"

    def __repr__(self):
        return f"fake:{self.id}@slice{self.slice_index}"


def two_slices(n_per_slice: int = 8):
    return [
        FakeDevice(i, i // n_per_slice, n_per_slice)
        for i in range(2 * n_per_slice)
    ]


def slice_of(dev) -> int:
    return dev.slice_index


def test_hybrid_mesh_data_axis_spans_dcn_fsdp_stays_intra_slice():
    mesh = create_mesh(MeshConfig(data=2, fsdp=8), devices=two_slices())
    assert dict(mesh.shape) == {"data": 2, "fsdp": 8, "tensor": 1, "seq": 1}
    arr = mesh.devices  # (data, fsdp, tensor, seq)
    # each data coordinate is exactly one slice → fsdp collectives ride ICI
    per_data_slices = [
        {slice_of(d) for d in arr[i].flat} for i in range(arr.shape[0])
    ]
    assert all(len(s) == 1 for s in per_data_slices)
    # and the data axis crosses the slice (DCN) boundary
    assert {next(iter(s)) for s in per_data_slices} == {0, 1}


def test_hybrid_mesh_data_axis_folds_ici_and_dcn():
    """data=4 over 2 slices: the data axis carries both the DCN hop and an
    intra-slice factor; fsdp groups must still never straddle a slice."""
    mesh = create_mesh(MeshConfig(data=4, fsdp=4), devices=two_slices())
    arr = mesh.devices
    for i in range(arr.shape[0]):
        assert len({slice_of(d) for d in arr[i].flat}) == 1
    assert {slice_of(d) for d in arr.flat} == {0, 1}


def test_misaligned_config_warns_and_falls_back_flat(capsys):
    """data=1 can't span 2 slices → warned flat mesh, not a hard failure."""
    mesh = create_mesh(MeshConfig(data=1, fsdp=16), devices=two_slices())
    out = capsys.readouterr().out
    assert "WARNING" in out and "flat" in out
    assert dict(mesh.shape)["fsdp"] == 16


def test_truncated_submesh_straddling_slices_falls_back_flat(capsys):
    """12 of 16 devices: slice populations 8+4 are unequal → flat."""
    devs = two_slices()[:12]
    mesh = create_mesh(MeshConfig(data=2, fsdp=6), devices=devs)
    out = capsys.readouterr().out
    assert "WARNING" in out
    assert dict(mesh.shape) == {"data": 2, "fsdp": 6, "tensor": 1, "seq": 1}


def test_single_slice_devices_build_flat_without_warning(capsys):
    devs = [FakeDevice(i, 0, 8) for i in range(8)]
    mesh = create_mesh(MeshConfig(data=2, fsdp=4), devices=devs)
    assert "WARNING" not in capsys.readouterr().out
    assert dict(mesh.shape) == {"data": 2, "fsdp": 4, "tensor": 1, "seq": 1}


def test_resolve_rejects_pipe_gt_one():
    """pipe>1 must route through create_pipeline_mesh; a flat mesh would
    silently drop the knob (advisor round-4 finding)."""
    with pytest.raises(ValueError, match="create_pipeline_mesh"):
        MeshConfig(pipe=2).resolve(8)
    with pytest.raises(ValueError, match="create_pipeline_mesh"):
        create_mesh(MeshConfig(pipe=4), devices=[FakeDevice(i, 0, 8) for i in range(8)])
