"""Training-dynamics diagnostics: per-layer-group model stats, the crash-safe
run journal, the flight recorder, and the offline run doctor.

Covers the PR-5 acceptance surface:

- param-leaf → layer-group mapping and the stacked on-device stats (values
  checked against a numpy recompute);
- ``make_train_step(diag=True)`` returns the ``(groups, 3)`` stats array +
  ``finite_frac`` and does NOT retrace between calls; ``diag=False`` keeps
  the metrics schema exactly as before (no diag keys anywhere);
- journal crash-safety: a torn final line is skipped on read, mid-file
  damage doesn't abort, rotation preserves ordering, restart opens a new
  segment, non-finite floats survive the JSON round trip;
- flight recorder: bounded ring, dump file shape, excepthook/signal
  chaining installs and uninstalls cleanly;
- ``tools/run_doctor.py`` exits 0 on a synthetic incident journal and names
  the bad-step window and the first non-finite layer group;
- exporter satellite: ``process_uptime_seconds`` + ``build_info`` appear on
  a real scrape;
- e2e: a short CPU train run with ``run.diag_every`` writes per-layer-group
  snapshots into a journal the doctor can read back.
"""

import json
import math
import signal
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
from jumbo_mae_tpu_tpu.obs.flightrec import FlightRecorder
from jumbo_mae_tpu_tpu.obs.journal import (
    RunJournal,
    env_fingerprint,
    read_journal,
)
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry
from jumbo_mae_tpu_tpu.obs.modelstats import (
    STAT_NAMES,
    first_nonfinite_group,
    group_layout,
    group_of,
    group_stats,
    publish_group_stats,
    stats_dict,
)
from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
from jumbo_mae_tpu_tpu.train import (
    OptimConfig,
    create_sharded_state,
    make_optimizer,
    make_train_step,
)

RECIPES = Path(__file__).resolve().parent.parent / "recipes"

TINY = preset("vit_t16", image_size=32, patch_size=8, dtype="float32")
TINY_DEC = DecoderConfig(layers=1, dim=32, heads=2, dtype="float32")
OPT = OptimConfig(
    name="adamw",
    learning_rate=1e-3,
    lr_scaling="none",
    warmup_steps=2,
    training_steps=20,
)


# ------------------------------------------------------------- model stats


class TestGrouping:
    def test_group_of_both_model_trees(self):
        # MAE pretrain tree (encoder/... + decoder-side leaves at top level)
        assert group_of(["encoder", "embed", "kernel"]) == "patch_embed"
        assert group_of(["encoder", "block_3", "attn", "w"]) == "blocks.3"
        assert group_of(["encoder", "cls_tokens"]) == "cls"
        assert group_of(["encoder", "jumbo_mlp", "fc1"]) == "jumbo_mlp"
        assert group_of(["encoder", "ln", "scale"]) == "norm"
        for top in ("decoder", "decoder_proj", "mask_token", "pixel_proj"):
            assert group_of([top, "x"]) == "decoder"
        # classification tree (everything under model/, incl. head)
        assert group_of(["model", "head", "kernel"]) == "head"
        assert group_of(["model", "block_0", "mlp"]) == "blocks.0"
        assert group_of(["something_else"]) == "other"

    def test_group_layout_order_and_membership(self):
        params = {
            "encoder": {
                "embed": {"k": np.ones(2)},
                "block_0": {"k": np.ones(2)},
                "block_1": {"k": np.ones(2)},
                "cls_tokens": np.ones(2),
                "jumbo_mlp": {"k": np.ones(2)},
                "ln": {"s": np.ones(2)},
            },
            "decoder": {"k": np.ones(2)},
            "mask_token": np.ones(2),
        }
        assert group_layout(params) == (
            "patch_embed", "cls", "blocks.0", "blocks.1",
            "jumbo_mlp", "norm", "decoder",
        )

    def test_group_stats_values_match_numpy(self):
        old = {
            "encoder": {
                "embed": {"k": np.full((2, 3), 2.0, np.float32)},
                "block_0": {"w": np.full((4,), 1.0, np.float32)},
            },
            "mask_token": np.full((3,), 0.5, np.float32),
        }
        grads = jax.tree_util.tree_map(lambda x: x * 0.1, old)
        new = jax.tree_util.tree_map(lambda x, g: x - g, old, grads)
        names = group_layout(old)
        assert names == ("patch_embed", "blocks.0", "decoder")
        arr = np.asarray(jax.jit(group_stats)(old, grads, new))
        assert arr.shape == (3, 3)
        for gi, (leaf, n) in enumerate(
            [(old["encoder"]["embed"]["k"], 6), (old["encoder"]["block_0"]["w"], 4),
             (old["mask_token"], 3)]
        ):
            g_norm = np.sqrt(np.sum((leaf * 0.1) ** 2))
            p_norm = np.sqrt(np.sum(leaf**2))
            np.testing.assert_allclose(arr[gi, 0], g_norm, rtol=1e-5)
            np.testing.assert_allclose(arr[gi, 1], p_norm, rtol=1e-5)
            # update == grad here, so ratio == g_norm / p_norm
            np.testing.assert_allclose(arr[gi, 2], g_norm / p_norm, rtol=1e-5)

    def test_stats_dict_and_nonfinite_group(self):
        names = ("patch_embed", "decoder")
        arr = np.array([[1.0, 2.0, 0.5], [np.nan, 1.0, 0.1]], np.float32)
        d = stats_dict(names, arr)
        assert d["patch_embed"]["grad_norm"] == pytest.approx(1.0)
        assert d["decoder"]["grad_norm"] == "nan"  # JSON-safe encoding
        assert first_nonfinite_group(names, arr) == "decoder"
        assert first_nonfinite_group(names, np.ones((2, 3))) is None

    def test_publish_group_stats_gauges(self):
        reg = MetricsRegistry()
        names = ("patch_embed", "blocks.0")
        arr = np.array([[1.0, 2.0, 0.5], [3.0, 4.0, 0.75]])
        publish_group_stats(names, arr, registry=reg)
        for si, stat in enumerate(STAT_NAMES):
            fam = reg.gauge(f"model_{stat}", labels=("group",))
            assert fam.labels("patch_embed").value == pytest.approx(arr[0, si])
            assert fam.labels("blocks.0").value == pytest.approx(arr[1, si])


class TestTrainStepDiag:
    """One compiled step serves every diag assertion (each build pays a full
    jit compile — tier-1 budget). diag=False coverage rides on the whole of
    ``test_train_steps.py``, which builds every step WITHOUT the flag and
    pins the metrics schema — ``diag``/``finite_frac`` appearing there would
    fail those tests, so the off-path needs no extra compile here."""

    def test_diag_step_stats_no_retrace_and_nan_localization(self):
        module = MAEPretrainModel(
            TINY.replace(mask_ratio=0.75, labels=None), TINY_DEC
        )
        mesh = create_mesh(MeshConfig(data=1, fsdp=-1))
        tx = make_optimizer(OPT, global_batch_size=256)
        rng = np.random.RandomState(0)
        batch = {
            "images": jnp.asarray(
                rng.randint(0, 256, (8, 32, 32, 3)).astype(np.uint8)
            )
        }
        state, sharding = create_sharded_state(
            module, tx, batch, mesh, mode="pretrain", init_seed=0, rng_seed=0
        )
        step = make_train_step(
            mesh, sharding, mode="pretrain", guard_nonfinite=True, diag=True
        )
        names = group_layout(state.params)
        assert "patch_embed" in names and "decoder" in names
        state, metrics = step(state, batch)
        assert metrics["diag"].shape == (len(names), len(STAT_NAMES))
        arr = np.asarray(metrics["diag"])
        assert np.all(np.isfinite(arr))
        assert np.all(arr[:, 0] > 0)  # every group received gradient
        # params are non-zero except zero-initialized groups (cls tokens)
        zeroable = {"cls"}
        for gi, grp in enumerate(names):
            if grp not in zeroable:
                assert arr[gi, 1] > 0, grp
        assert float(metrics["finite_frac"]) == 1.0
        # a clean second call reuses the same executable (no retrace)
        state, m2 = step(state, batch)
        assert m2["diag"].shape == arr.shape
        # an injected-NaN call (traced input — still no retrace): NaN grads
        # blow up every group's grad norm; the guard skipped the update so
        # update_ratio stays 0 everywhere
        _, m3 = step(state, batch, np.asarray([math.nan, math.nan], np.float32))
        assert first_nonfinite_group(names, m3["diag"]) == names[0]
        assert float(m3["skipped"]) == 1.0
        np.testing.assert_allclose(np.asarray(m3["diag"])[:, 2], 0.0, atol=1e-12)


# ------------------------------------------------------------------ journal


class TestJournal:
    def test_roundtrip_and_seq(self, tmp_path):
        with RunJournal(tmp_path / "j") as j:
            j.event("run_start", config={"a": 1})
            j.event("step", step=5, loss=1.5)
        evs = read_journal(tmp_path / "j")
        assert [e["type"] for e in evs] == ["run_start", "step"]
        assert [e["seq"] for e in evs] == [0, 1]
        assert evs[1]["loss"] == 1.5
        # reader also resolves the run dir (parent of journal/)
        (tmp_path / "j").rename(tmp_path / "journal")
        assert len(read_journal(tmp_path)) == 2

    def test_nonfinite_values_survive(self, tmp_path):
        with RunJournal(tmp_path / "j") as j:
            j.event("step", loss=float("nan"), diag={"g": float("inf")})
        e = read_journal(tmp_path / "j")[0]
        assert e["loss"] == "nan" and e["diag"]["g"] == "inf"

    def test_torn_final_line_skipped(self, tmp_path):
        j = RunJournal(tmp_path / "j")
        j.event("run_start")
        j.event("step", step=1)
        j.close()
        # simulate SIGKILL mid-write: a partial JSON line at the tail
        with open(j.path, "a") as f:
            f.write('{"ts": 1.0, "seq": 2, "type": "step", "st')
        evs = read_journal(tmp_path / "j")
        assert [e["type"] for e in evs] == ["run_start", "step"]

    def test_mid_file_damage_does_not_abort(self, tmp_path):
        j = RunJournal(tmp_path / "j")
        j.event("a")
        j.event("b")
        j.close()
        text = j.path.read_text().splitlines()
        text.insert(1, "GARBAGE NOT JSON")
        j.path.write_text("\n".join(text) + "\n")
        assert [e["type"] for e in read_journal(tmp_path / "j")] == ["a", "b"]

    def test_rotation_preserves_ordering(self, tmp_path):
        j = RunJournal(tmp_path / "j", max_bytes=200, keep=50)
        for i in range(30):
            j.event("step", step=i)
        j.close()
        segments = sorted((tmp_path / "j").glob("journal-*.jsonl"))
        assert len(segments) > 1  # actually rotated
        evs = read_journal(tmp_path / "j")
        assert [e["step"] for e in evs] == list(range(30))
        assert [e["seq"] for e in evs] == list(range(30))

    def test_rotation_prunes_to_keep(self, tmp_path):
        j = RunJournal(tmp_path / "j", max_bytes=120, keep=2)
        for i in range(40):
            j.event("step", step=i)
        j.close()
        segments = sorted((tmp_path / "j").glob("journal-*.jsonl"))
        assert len(segments) <= 3  # keep=2 closed + 1 active
        # the SURVIVING events are still in order
        steps = [e["step"] for e in read_journal(tmp_path / "j")]
        assert steps == sorted(steps)

    def test_restart_opens_new_segment(self, tmp_path):
        j1 = RunJournal(tmp_path / "j")
        j1.event("run_start")
        j1.close()
        j2 = RunJournal(tmp_path / "j")
        j2.event("run_start", restart=True)
        j2.close()
        assert j2.path != j1.path
        evs = read_journal(tmp_path / "j")
        assert len(evs) == 2 and evs[1].get("restart") is True

    def test_missing_journal_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_journal(tmp_path / "nope")

    def test_env_fingerprint_keys(self):
        fp = env_fingerprint()
        assert {"version", "python", "hostname", "pid", "jax"} <= set(fp)


# ----------------------------------------------------------- flight recorder


class TestFlightRecorder:
    def test_ring_is_bounded_and_dump_shape(self, tmp_path):
        fr = FlightRecorder(tmp_path, capacity=4, event_capacity=2)
        for i in range(10):
            fr.record_step(i, {"loss": float(i)})
        fr.record_event({"type": "a"})
        fr.record_event({"type": "b"})
        fr.record_event({"type": "c"})
        path = fr.dump("nonfinite_step", extra={"bad_steps": [7]})
        data = json.loads(path.read_text())
        assert data["reason"] == "nonfinite_step"
        assert [s["step"] for s in data["steps"]] == [6, 7, 8, 9]
        assert [e["type"] for e in data["events"]] == ["b", "c"]
        assert data["extra"]["bad_steps"] == [7]
        assert path.name.startswith("flightrec-") and path.suffix == ".json"

    def test_nonfinite_payloads_dump_cleanly(self, tmp_path):
        fr = FlightRecorder(tmp_path)
        fr.record_step(1, {"loss": float("nan")})
        data = json.loads(fr.dump("x").read_text())
        assert data["steps"][0]["loss"] == "nan"

    def test_each_dump_is_a_new_file(self, tmp_path):
        fr = FlightRecorder(tmp_path)
        p1, p2 = fr.dump("a"), fr.dump("a")
        assert p1 != p2 and p1.exists() and p2.exists()
        assert fr.dumps == [str(p1), str(p2)]

    def test_excepthook_chains_and_uninstalls(self, tmp_path):
        fr = FlightRecorder(tmp_path)
        seen = []
        orig = sys.excepthook
        sys.excepthook = lambda *a: seen.append(a)
        try:
            fr.install(signals=())
            sys.excepthook(ValueError, ValueError("boom"), None)
            assert len(seen) == 1  # chained through
            assert any("exception" in d for d in fr.dumps)
            fr.uninstall()
            assert sys.excepthook is not fr._excepthook
        finally:
            sys.excepthook = orig

    def test_sigterm_handler_chains_to_previous(self, tmp_path):
        fr = FlightRecorder(tmp_path)
        hits = []
        prev = signal.getsignal(signal.SIGTERM)
        try:
            signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
            assert fr.install()
            handler = signal.getsignal(signal.SIGTERM)
            handler(signal.SIGTERM, None)  # invoke without killing pytest
            assert hits == [signal.SIGTERM]  # previous handler still ran
            assert any("signal" in d for d in fr.dumps)
            fr.uninstall()
            assert signal.getsignal(signal.SIGTERM) not in (handler,)
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_atexit_fallback_only_when_abnormal_and_undumped(self, tmp_path):
        fr = FlightRecorder(tmp_path)
        fr.record_step(1, {"loss": 1.0})
        fr._atexit()  # clean run: nothing written
        assert not list(tmp_path.glob("flightrec-*.json"))
        fr.mark_abnormal()
        fr._atexit()
        assert len(list(tmp_path.glob("flightrec-*.json"))) == 1
        fr._atexit()  # already dumped: no duplicate
        assert len(list(tmp_path.glob("flightrec-*.json"))) == 1


# --------------------------------------------------------------- run doctor


def _synthetic_incident_journal(tmp_path: Path) -> Path:
    j = RunJournal(tmp_path / "journal")
    j.event(
        "run_start",
        config={"run": {"name": "t", "mode": "pretrain", "training_steps": 12,
                        "train_batch_size": 16}},
        env={"python": "3.10", "jax": "0.4", "backend": "cpu",
             "device_count": 1, "hostname": "h", "pid": 1},
        start_step=0,
        diag_every=1,
        diag_groups=["patch_embed", "jumbo_mlp", "decoder"],
    )
    for s in (1, 2, 3, 4):
        j.event(
            "step", step=s,
            metrics={"train/loss": 1.0, "train/grad_norm": 0.3 + 0.01 * s,
                     "perf/images_per_sec": 300.0},
            data_wait_fraction=0.05,
        )
    for s in (5, 6, 7):
        j.event("sentinel_bad_step", step=s, loss="nan",
                reason="device_skip", streak=s - 4)
    j.event(
        "step", step=7,
        metrics={"train/loss": "nan"},
        data_wait_fraction=0.04,
        bad_steps=[5, 6, 7],
        diag_step=7,
        diag={"patch_embed": {"grad_norm": "nan", "param_norm": 1.0,
                              "update_ratio": 0.0},
              "jumbo_mlp": {"grad_norm": 2.0, "param_norm": 3.0,
                            "update_ratio": 0.001},
              "decoder": {"grad_norm": 1.0, "param_norm": 2.0,
                          "update_ratio": 0.001}},
    )
    j.event("rollback", from_step=7, to_step=4, rollbacks=1, bad_steps=[5, 6, 7])
    j.event("flight_record", reason="sentinel_rollback", path="x.json")
    j.event("quarantine", shards=["s3.tar"])
    j.event("shutdown", reason="completed", step=12)
    j.close()
    return tmp_path


class TestRunDoctor:
    def test_exit_zero_and_names_incident(self, tmp_path, capsys):
        import tools.run_doctor as doctor

        run_dir = _synthetic_incident_journal(tmp_path)
        out = tmp_path / "report.md"
        assert doctor.main([str(run_dir), "--out", str(out)]) == 0
        report = out.read_text()
        assert "steps 5–7" in report        # the injected fault window
        assert "patch_embed" in report      # the first non-finite group
        assert "1 sentinel rollback" in report
        assert "quarantined" in report
        assert "completed" in report

    def test_exit_two_without_journal(self, tmp_path):
        import tools.run_doctor as doctor

        assert doctor.main([str(tmp_path)]) == 2

    def test_tolerates_torn_journal(self, tmp_path):
        import tools.run_doctor as doctor

        run_dir = _synthetic_incident_journal(tmp_path)
        seg = sorted((run_dir / "journal").glob("journal-*.jsonl"))[-1]
        with open(seg, "a") as f:
            f.write('{"torn": tr')
        assert doctor.main([str(run_dir)]) == 0

    def test_healthy_run_reports_no_incidents(self, tmp_path, capsys):
        import tools.run_doctor as doctor

        j = RunJournal(tmp_path / "journal")
        j.event("run_start", config={}, env={}, start_step=0)
        j.event("step", step=5, metrics={"train/loss": 0.9})
        j.event("shutdown", reason="completed", step=5)
        j.close()
        assert doctor.main([str(tmp_path)]) == 0
        assert "no incidents recorded" in capsys.readouterr().out


# ------------------------------------------------------- exporter satellite


def test_exporter_uptime_and_build_info(tmp_path):
    import urllib.request

    from jumbo_mae_tpu_tpu import __version__
    from jumbo_mae_tpu_tpu.obs.exporter import TelemetryServer

    reg = MetricsRegistry()
    with TelemetryServer(registry=reg, host="127.0.0.1", port=0) as srv:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5
        ).read().decode()
    assert "process_uptime_seconds" in body
    # a scrape refreshes the value: it must be > 0 once rendered
    line = next(
        ln for ln in body.splitlines()
        if ln.startswith("process_uptime_seconds ")
    )
    assert float(line.split()[-1]) > 0
    assert f'build_info{{version="{__version__}"' in body
    assert "jax_version=" in body


# ------------------------------------------------------------------- e2e


def test_train_run_writes_diag_journal(tmp_path):
    """Acceptance: a short CPU run with run.diag_every > 0 produces a journal
    whose step snapshots carry per-layer-group grad/param norms, and the
    doctor reads it back with exit 0."""
    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config

    import tools.run_doctor as doctor

    cfg = load_config(
        RECIPES / "smoke_cpu.yaml",
        [
            f"run.output_dir={tmp_path}",
            "run.training_steps=4",
            "optim.training_steps=4",
            "optim.warmup_steps=2",
            "run.log_interval=2",
            # no eval leg: nothing below asserts on eval, and the eval
            # step's extra XLA compile is pure tier-1 wall-clock
            "run.eval_interval=100000",
            "run.sanity_eval=false",
            "run.diag_every=2",
        ],
    )
    metrics = train(cfg)
    assert math.isfinite(metrics["train/loss"])
    run_dir = tmp_path / "smoke_cpu"
    evs = read_journal(run_dir)
    types = [e["type"] for e in evs]
    assert types[0] == "run_start" and types[-1] == "shutdown"
    assert evs[-1]["reason"] == "completed"
    step_evs = [e for e in evs if e["type"] == "step" and "diag" in e]
    assert step_evs, "no diag-bearing step snapshots in the journal"
    diag = step_evs[-1]["diag"]
    assert "patch_embed" in diag and "decoder" in diag
    for stats in diag.values():
        assert set(stats) == set(STAT_NAMES)
        assert stats["grad_norm"] > 0
    assert diag["patch_embed"]["param_norm"] > 0
    # finite_frac flowed through the meter into the logged summary
    assert step_evs[-1]["metrics"]["train/finite_frac"] == 1.0
    assert doctor.main([str(run_dir)]) == 0
