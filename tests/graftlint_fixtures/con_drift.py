# graftlint fixture: seeded CON true positives. NEVER imported — parsed only.
import os

from jumbo_mae_tpu_tpu.faults.inject import fault_point
from jumbo_mae_tpu_tpu.obs.metrics import get_registry


def drifted(cfg, journal):
    reg = get_registry()
    reg.counter("orphan_widget_total", "not in the README glossary")  # CON001
    journal.event("bogus_event", step=1)  # CON002: not in JOURNAL_EVENTS
    fault_point("serve.bogus")  # CON003: not a registered fault site
    os.environ["GRAFT_FAULTS"] = "data.shard_opne:raise"  # CON003: typo'd site
    argv = ["--set", "run.not_a_field=1"]  # CON004: unknown run.* key
    return cfg.run.bogus_field, argv  # CON004: unknown RunConfig attribute
