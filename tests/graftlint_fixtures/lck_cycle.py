# graftlint fixture: seeded LCK003 lock-order cycle. NEVER imported — parsed only.
import threading

_ALPHA = threading.Lock()
_BETA = threading.Lock()


def alpha_then_beta():
    with _ALPHA:
        with _BETA:  # edge ALPHA -> BETA
            return 1


def beta_then_alpha():
    with _BETA:
        with _ALPHA:  # edge BETA -> ALPHA: LCK003 cycle
            return 2
