# graftlint fixture: seeded LCK true positives. NEVER imported — parsed only.
# Engine.warmup reproduces the round-10 warmup deadlock shape: compile work
# held under the master lock while a callee re-acquires the same lock.
import threading
import time


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._execs = {}

    def _task(self):
        with self._lock:
            return dict(self._execs)

    def warmup(self, fn, x):
        with self._lock:
            self._execs["warm"] = fn.lower()
            self._task()  # LCK002: callee re-acquires self._lock (round-10 shape)

    def slow_refresh(self):
        with self._lock:
            time.sleep(0.5)  # LCK001: blocking sleep while holding the lock

    def reenter(self):
        with self._lock:
            with self._lock:  # LCK002: direct re-acquire of a non-reentrant lock
                pass

    def locked_iter(self):
        with self._lock:
            for k in self._execs:
                yield k  # LCK004: generator yields while holding the lock
