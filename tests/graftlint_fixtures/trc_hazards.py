# graftlint fixture: seeded TRC true positives. NEVER imported — parsed only.
# Each marked line must be reported by tools.graftlint (see test_graftlint.py).
import random
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def branchy(x):
    if x.sum() > 0:  # TRC001: python `if` on a traced value
        return x
    return -x


@jax.jit
def spinny(x):
    while x.min() < 0:  # TRC001: python `while` on a traced value
        x = x + 1
    return x


@jax.jit
def asserty(x):
    assert x.min() >= 0  # TRC001: `assert` on a traced value
    return x


@jax.jit
def hosty(x):
    s = float(x.mean())  # TRC002: float() forces a host sync
    return x * s


@jax.jit
def itemy(x):
    return x.sum().item()  # TRC002: .item() forces a host sync


@jax.jit
def asarr(x):
    y = np.asarray(x)  # TRC002: np.asarray materializes the tracer
    return jnp.asarray(y)


@jax.jit
def clocky(x):
    t = time.time()  # TRC003: wall clock baked in at trace time
    return x + t


@jax.jit
def randy(x):
    return x * random.random()  # TRC003: python RNG baked in at trace time


@partial(jax.jit)  # TRC004: str-default arg below, no static_argnames
def config_shaped(x, mode="fast"):
    del mode
    return x
