# graftlint fixture: repo idioms that must produce ZERO findings.
# NEVER imported — parsed only.
import re
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp

from jumbo_mae_tpu_tpu.faults.inject import fault_point
from jumbo_mae_tpu_tpu.obs.metrics import get_registry


@partial(jax.jit, static_argnames=("mode",))
def static_branch(x, mode="fast"):
    # branching on a static arg is fine under tracing
    if mode == "fast":
        return x
    return -x


@jax.jit
def none_gate(x, extra=None):
    # is-None structure checks resolve at trace time
    if extra is not None:
        x = x + extra
    return x


def make_step(use_extra):
    # closure config flag: resolved at trace time, not a traced value
    @jax.jit
    def step(x):
        if use_extra:
            return x * 2
        return jnp.abs(x)

    return step


def host_side(x, flag):
    # not jitted: host control flow and host syncs are fine here
    if flag:
        return float(x.mean())
    return x.item() if hasattr(x, "item") else x


_LOCK = threading.Lock()


def quick_critical_section(parts):
    # cheap str/regex work under a lock is not blocking
    with _LOCK:
        joined = ",".join(parts)
        pat = re.compile("a+")
    time.sleep(0)  # blocking OUTSIDE the lock is fine
    return joined, pat


def known_contracts(cfg, journal):
    reg = get_registry()
    reg.counter("infer_requests_total", "documented in the README glossary")
    journal.event("step", step=1)
    fault_point("train.loss")
    argv = ["--set", "run.training_steps=10"]
    return cfg.run.training_steps, argv
