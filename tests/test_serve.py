"""Traffic-shaping tier contracts (serve/ + the pool's scaling surface).

What this tier must guarantee:

- **weighted admission**: token-bucket quotas shed a tenant that exceeds
  its contracted rate; under pool pressure the *low* priority classes
  shed first (scavenger at half load, batch at heavy load, interactive
  only at a genuinely full queue) — never the other way around;
- **continuous batching**: concurrent arrivals coalesce into one
  dispatched group that lands on ONE replica as one flush; partial
  batches dispatch bucket-aligned (power-of-2, zero pad rows) when no
  due entry would be held back; over-full accumulators admit the highest
  class first (the priority queue-jump);
- **exactly-once through the stack**: every future from
  ``ContinuousScheduler.submit`` resolves exactly once — ok, typed shed,
  deadline, or shutdown — under replica crash storms, priority
  reordering, racing scale-downs, and close();
- **elastic pool**: ``scale_to`` adds/removes replica slots live;
  scale-down drains (never kills in-flight work) and refuses rather than
  waits forever; the autoscaler steps up immediately on demand/burn and
  down conservatively (``down_hold``), journaling every resize;
- **occupancy telemetry is honest**: ``stats()["batch_occupancy"]`` is a
  windowed EWMA over recent flushes, not whatever the last flush alone
  happened to be (the regression that motivated ``OccupancyWindow``).
"""

import threading
import time
from concurrent.futures import wait

import numpy as np
import pytest

from jumbo_mae_tpu_tpu import faults
from jumbo_mae_tpu_tpu.infer import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    ReplicaSet,
    ShutdownError,
)
from jumbo_mae_tpu_tpu.infer.batching import OccupancyWindow
from jumbo_mae_tpu_tpu.obs import AccessLog, RequestTracer
from jumbo_mae_tpu_tpu.obs.journal import read_journal
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry
from jumbo_mae_tpu_tpu.serve import (
    AdmissionController,
    Autoscaler,
    ContinuousScheduler,
    TenantPressureError,
    TenantQuotaError,
    TenantSpec,
    parse_tenants,
    roofline_capacity,
)
from jumbo_mae_tpu_tpu.serve.scheduler import floor_bucket


@pytest.fixture
def fault_plan():
    yield faults.install_plan
    faults.clear_plan()


def _img(v=0.0):
    return np.full((2, 2, 3), v, np.float32)


def run_echo(eng, batch, metas):
    return {"y": batch[:, 0, 0, 0].astype(np.float64)}


class StubEngine:
    def __init__(self, idx):
        self.idx = idx


def make_pool(reg, tracer=None, *, replicas=2, run=run_echo, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_delay_ms", 1.0)
    kw.setdefault("supervise_interval_s", 0.02)
    kw.setdefault("restart_backoff_s", 0.05)
    return ReplicaSet(
        lambda i: StubEngine(i), run, replicas=replicas, registry=reg,
        tracer=tracer, **kw,
    )


# ----------------------------------------------------- occupancy telemetry


def test_occupancy_window_ewma_and_window_mean():
    w = OccupancyWindow(8, alpha=0.5, window=4)
    snap = w.snapshot()
    assert snap["ewma"] == 0.0 and snap["batches"] == 0
    w.observe(8)  # occ 1.0
    w.observe(4)  # occ 0.5 -> ewma 0.75
    snap = w.snapshot()
    assert snap["ewma"] == pytest.approx(0.75)
    assert snap["window_mean"] == pytest.approx(0.75)
    assert snap["last"] == pytest.approx(0.5)
    assert snap["batches"] == 2


def test_microbatcher_occupancy_is_windowed_not_last_flush():
    """Regression: batch_occupancy fed from the last flush alone made one
    trailing single-request flush erase a history of full batches."""
    done = threading.Event()

    def run(batch):
        return {"y": batch[:, 0, 0, 0].astype(np.float64)}

    mb = MicroBatcher(run, max_batch=4, max_delay_ms=1.0)
    try:
        # one full batch, then one singleton
        futs = [mb.submit(_img(i)) for i in range(4)]
        wait(futs, timeout=10)
        futs = [mb.submit(_img(9))]
        wait(futs, timeout=10)
        for _ in range(200):
            if len(mb.batch_sizes) >= 2:
                break
            time.sleep(0.005)
        s = mb.stats()
        assert s["last_batch_occupancy"] == pytest.approx(0.25)
        # the headline number remembers the full flush
        assert s["batch_occupancy"] > 0.25
        assert s["window_batch_occupancy"] == pytest.approx(0.625)
    finally:
        done.set()
        mb.close()


# ------------------------------------------------------------- admission


def test_parse_tenants_specs_and_errors():
    ts = parse_tenants("web=interactive:rate=50:burst=100,scrape=batch:rate=5")
    assert ts[0] == TenantSpec("web", "interactive", 50.0, 100.0)
    assert ts[1] == TenantSpec("scrape", "batch", 5.0, None)
    with pytest.raises(ValueError, match="unknown tenant class"):
        parse_tenants("web=interacttive")
    with pytest.raises(ValueError, match="duplicate"):
        parse_tenants("a=batch,a=batch")
    with pytest.raises(ValueError, match="unknown tenant option"):
        parse_tenants("a=batch:rte=5")
    with pytest.raises(ValueError, match="empty tenant spec"):
        parse_tenants(" , ")


def test_quota_bucket_sheds_and_refills():
    t = {"now": 100.0}
    adm = AdmissionController(
        parse_tenants("s=batch:rate=2:burst=2"),
        registry=MetricsRegistry(),
        clock=lambda: t["now"],
    )
    assert adm.admit("s").tclass == "batch"
    adm.admit("s")
    with pytest.raises(TenantQuotaError):
        adm.admit("s")
    t["now"] += 1.0  # refill 2 tokens
    adm.admit("s")
    adm.admit("s")
    with pytest.raises(TenantQuotaError):
        adm.admit("s")
    st = adm.stats()
    assert st["admitted"]["s"] == 4
    assert st["shed"]["s:quota"] == 2


def test_pressure_sheds_low_classes_first():
    p = {"v": 0.0}
    adm = AdmissionController(
        parse_tenants("web=interactive,crawl=batch,fill=scavenger"),
        pressure_fn=lambda: p["v"],
        registry=MetricsRegistry(),
    )
    for name in ("web", "crawl", "fill"):
        adm.admit(name)
    p["v"] = 0.6  # scavenger gives way at half load
    adm.admit("web")
    adm.admit("crawl")
    with pytest.raises(TenantPressureError):
        adm.admit("fill")
    p["v"] = 0.9  # batch gives way at heavy load
    adm.admit("web")
    with pytest.raises(TenantPressureError):
        adm.admit("crawl")
    p["v"] = 1.0  # a full queue sheds everyone
    with pytest.raises(TenantPressureError):
        adm.admit("web")
    assert adm.stats()["shed"] == {
        "fill:pressure": 1, "crawl:pressure": 1, "web:pressure": 1
    }


def test_unknown_and_none_tenant_default_to_batch_unmetered():
    adm = AdmissionController(
        parse_tenants("web=interactive"), registry=MetricsRegistry()
    )
    assert adm.admit(None).name == "_default"
    sp = adm.admit("stranger")
    assert (sp.tclass, sp.rate) == ("batch", None)
    for _ in range(50):  # no quota on unknown tenants
        adm.admit("stranger")


def test_broken_pressure_probe_fails_open():
    def boom():
        raise RuntimeError("probe died")

    adm = AdmissionController(
        parse_tenants("fill=scavenger"),
        pressure_fn=boom,
        registry=MetricsRegistry(),
    )
    adm.admit("fill")  # pressure reads 0.0, not an exception


# ------------------------------------------------------------- scheduler


def test_floor_bucket_ladder():
    assert [floor_bucket(k, 16) for k in (1, 2, 3, 5, 8, 11, 16, 40)] == [
        1, 2, 2, 4, 8, 8, 16, 16
    ]


class DispatchStub:
    """Backend standing in for ReplicaSet.submit_group: records batches,
    resolves futures inline (optionally gated on an event)."""

    def __init__(self, gate=None, fail=None):
        self.batches = []
        self.gate = gate
        self.fail = fail
        self.lock = threading.Lock()

    def __call__(self, items):
        if self.gate is not None:
            assert self.gate.wait(timeout=10)
        if self.fail is not None:
            raise self.fail
        with self.lock:
            self.batches.append(items)
        futs = []
        from concurrent.futures import Future

        for image, deadline, meta, tr in items:
            f = Future()
            f.set_result({"y": float(image[0, 0, 0])})
            futs.append(f)
        return futs


def test_scheduler_coalesces_concurrent_arrivals_into_one_flush():
    stub = DispatchStub()
    sched = ContinuousScheduler(
        stub, max_batch=8, max_delay_ms=30.0, registry=MetricsRegistry()
    )
    with sched:
        futs = [sched.submit(_img(i)) for i in range(8)]
        done, _ = wait(futs, timeout=10)
        assert len(done) == 8
    assert len(stub.batches[0]) == 8  # full batch dispatched as one group
    assert all(f.result()["y"] == float(i) for i, f in enumerate(futs))


def test_scheduler_bucket_aligned_partial_dispatch():
    """3 due entries in an accumulator of 6 dispatch as a zero-pad bucket
    of 4, holding the 2 youngest to seed the next batch."""
    stub = DispatchStub()
    sched = ContinuousScheduler(
        stub, max_batch=16, max_delay_ms=80.0, registry=MetricsRegistry()
    )
    with sched:
        futs = [sched.submit(_img(i)) for i in range(3)]
        time.sleep(0.04)
        futs += [sched.submit(_img(10 + i)) for i in range(3)]
        done, _ = wait(futs, timeout=10)
        assert len(done) == 6
    sizes = [len(b) for b in stub.batches]
    assert sizes[0] == 4  # floor_bucket(6) covering the 3 due entries
    assert sum(sizes) == 6


def test_scheduler_priority_jumps_overfull_accumulator():
    gate = threading.Event()
    stub = DispatchStub(gate=gate)
    reg = MetricsRegistry()
    adm = AdmissionController(
        parse_tenants("vip=interactive,fill=scavenger"), registry=reg
    )
    sched = ContinuousScheduler(
        stub, max_batch=2, max_delay_ms=5.0, admission=adm, registry=reg
    )
    try:
        # first full batch blocks the dispatcher on the gate...
        first = [sched.submit(_img(0), tenant="fill") for _ in range(2)]
        time.sleep(0.05)
        # ...while an over-full accumulator builds: scavengers first
        late = [sched.submit(_img(1), tenant="fill") for _ in range(2)]
        time.sleep(0.02)
        vips = [sched.submit(_img(2), tenant="vip") for _ in range(2)]
        gate.set()
        done, _ = wait(first + late + vips, timeout=10)
        assert len(done) == 6
    finally:
        sched.close()
    # batch 2 is the vips jumping the earlier-arrived scavengers
    assert [float(i[0][0, 0, 0]) for i in stub.batches[1]] == [2.0, 2.0]
    assert "serve_sched_priority_jumps_total 2" in reg.render()


def test_scheduler_deadline_expires_while_pending():
    gate = threading.Event()
    stub = DispatchStub(gate=gate)
    sched = ContinuousScheduler(
        stub, max_batch=2, max_delay_ms=5.0, registry=MetricsRegistry()
    )
    try:
        blockers = [sched.submit(_img()) for _ in range(2)]
        time.sleep(0.02)
        doomed = sched.submit(_img(), deadline_ms=30.0)
        time.sleep(0.08)  # deadline passes while the dispatcher is gated
        gate.set()
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=10)
        wait(blockers, timeout=10)
    finally:
        sched.close()
    assert sched.stats()["expired"] == 1


def test_scheduler_queue_full_sheds_with_trace(tmp_path):
    gate = threading.Event()
    stub = DispatchStub(gate=gate)
    log = AccessLog(tmp_path / "access")
    reg = MetricsRegistry()
    tracer = RequestTracer(registry=reg, access_log=log)
    adm = AdmissionController(
        parse_tenants("web=interactive"), registry=reg
    )
    sched = ContinuousScheduler(
        stub, max_batch=4, max_delay_ms=5.0, max_queue=2,
        admission=adm, tracer=tracer, registry=reg,
    )
    try:
        keep = [sched.submit(_img(), tenant="web") for _ in range(2)]
        with pytest.raises(QueueFullError):
            sched.submit(_img(), tenant="web")
        gate.set()
        wait(keep, timeout=10)
    finally:
        sched.close()
        tracer.close()
    rows = read_journal(tmp_path / "access")
    shed = [r for r in rows if r["outcome"] == "shed"]
    assert len(shed) == 1
    assert (shed[0]["tenant"], shed[0]["class"]) == ("web", "interactive")


def test_scheduler_close_drain_fails_pending_with_shutdown():
    gate = threading.Event()
    stub = DispatchStub(gate=gate)
    sched = ContinuousScheduler(
        stub, max_batch=8, max_delay_ms=500.0, registry=MetricsRegistry()
    )
    pending = [sched.submit(_img()) for _ in range(3)]
    gate.set()
    sched.close(drain=True)
    for f in pending:
        with pytest.raises(ShutdownError):
            f.result(timeout=5)
    with pytest.raises(ShutdownError):
        sched.submit(_img())


def test_scheduler_close_no_drain_dispatches_leftovers():
    stub = DispatchStub()
    sched = ContinuousScheduler(
        stub, max_batch=8, max_delay_ms=500.0, registry=MetricsRegistry()
    )
    pending = [sched.submit(_img(i)) for i in range(3)]
    sched.close(drain=False)
    done, _ = wait(pending, timeout=10)
    assert len(done) == 3 and all(f.exception() is None for f in pending)


def test_scheduler_dispatch_error_fails_the_batch_futures():
    stub = DispatchStub(fail=RuntimeError("backend down"))
    sched = ContinuousScheduler(
        stub, max_batch=2, max_delay_ms=2.0, registry=MetricsRegistry()
    )
    with sched:
        futs = [sched.submit(_img()) for _ in range(2)]
        for f in futs:
            with pytest.raises(RuntimeError, match="backend down"):
                f.result(timeout=10)


# ---------------------------------------------- scheduler -> pool, end to end


def test_scheduler_batch_lands_on_one_replica_as_one_flush(tmp_path):
    reg = MetricsRegistry()
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=reg, access_log=log)
    rs = make_pool(reg, tracer, replicas=3, max_delay_ms=20.0)
    sched = ContinuousScheduler(
        rs.submit_group, max_batch=8, max_delay_ms=20.0,
        tracer=tracer, registry=reg,
    )
    try:
        futs = [sched.submit(_img(i)) for i in range(8)]
        done, _ = wait(futs, timeout=10)
        assert len(done) == 8
        assert [f.result()["y"] for f in futs] == [float(i) for i in range(8)]
    finally:
        sched.close()
        rs.close()
        tracer.close()
    rows = [
        r for r in read_journal(tmp_path / "access")
        if r.get("type") == "request"
    ]
    assert len(rows) == 8
    # the whole group ran on one replica, as one batch of 8
    assert len({r["replica"] for r in rows}) == 1
    assert {r["batch"] for r in rows} == {8}


def test_exactly_once_under_crash_storm_and_priority_reorder(
    tmp_path, fault_plan
):
    """8 threads x 25 requests from mixed-class tenants through the
    continuous scheduler into a 3-replica pool whose r1 dies on every
    batch: every future resolves exactly once (ok, typed shed, deadline,
    or retried error) and access rows match resolved traces 1:1."""
    fault_plan("serve.replica:raise(RuntimeError)@key~r1")
    reg = MetricsRegistry()
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=reg, access_log=log)

    def run(eng, batch, metas):
        time.sleep(0.002)
        return {"y": batch[:, 0, 0, 0].astype(np.float64)}

    rs = make_pool(reg, tracer, replicas=3, run=run, max_queue=None)
    adm = AdmissionController(
        parse_tenants("vip=interactive,crawl=batch,fill=scavenger"),
        registry=reg,
    )
    sched = ContinuousScheduler(
        rs.submit_group, max_batch=8, max_delay_ms=2.0, max_queue=None,
        admission=adm, tracer=tracer, registry=reg,
    )
    tenants = ("vip", "crawl", "fill")
    futures, submit_errors = [], []
    lock = threading.Lock()

    def client(tid):
        rng = np.random.RandomState(tid)
        for i in range(25):
            dl = None if i % 3 else float(rng.uniform(50.0, 500.0))
            try:
                f = sched.submit(
                    _img(tid), deadline_ms=dl, tenant=tenants[i % 3]
                )
            except (QueueFullError, ShutdownError) as e:
                with lock:
                    submit_errors.append(e)
            else:
                with lock:
                    futures.append(f)

    threads = [
        threading.Thread(target=client, args=(t,)) for t in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    done, not_done = wait(futures, timeout=60)
    assert not not_done, f"{len(not_done)} future(s) never resolved"
    sched.close()
    rs.close()
    tracer.close()
    ok = sum(1 for f in futures if f.exception() is None)
    assert ok > 0  # survivors absorbed the storm
    assert len(futures) + len(submit_errors) == 8 * 25
    rows = [
        r for r in read_journal(tmp_path / "access")
        if r.get("type") == "request"
    ]
    # every resolved future produced exactly one trace row
    assert len(rows) == len(futures)
    assert len({r["rid"] for r in rows}) == len(rows)
    assert {r["tenant"] for r in rows} <= set(tenants)


# -------------------------------------------------------------- scale_to


def test_scale_to_up_and_down_updates_pool(tmp_path):
    reg = MetricsRegistry()
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=reg, access_log=log)
    rs = make_pool(reg, tracer, replicas=2)
    try:
        report = rs.scale_to(4)
        assert (report["from"], report["to"]) == (2, 4)
        assert len(rs.stats()["replicas"]) == 4
        futs = [rs.submit(_img(i)) for i in range(8)]
        done, _ = wait(futs, timeout=10)
        assert len(done) == 8
        report = rs.scale_to(2, drain_timeout_s=5.0)
        assert report["to"] == 2
        assert len(rs.stats()["replicas"]) == 2
        # the shrunk pool still serves
        f = rs.submit(_img(5.0))
        assert f.result(timeout=10)["y"] == 5.0
    finally:
        rs.close()
        tracer.close()
    ev = [
        r["type"] for r in read_journal(tmp_path / "access")
        if r.get("type") in ("replica_added", "replica_removed")
    ]
    assert ev.count("replica_added") == 2
    assert ev.count("replica_removed") == 2


def test_scale_down_drains_never_kills_in_flight():
    reg = MetricsRegistry()

    def slow_run(eng, batch, metas):
        time.sleep(0.1)
        return {"y": batch[:, 0, 0, 0].astype(np.float64)}

    rs = make_pool(reg, replicas=3, run=slow_run, max_delay_ms=1.0)
    try:
        futs = [rs.submit(_img(i)) for i in range(12)]
        report = rs.scale_to(1, drain_timeout_s=10.0)
        assert report["to"] == 1
        done, not_done = wait(futs, timeout=30)
        assert not not_done
        assert all(f.exception() is None for f in futs)
    finally:
        rs.close()


def test_scale_down_refuses_below_one_and_times_out_busy():
    reg = MetricsRegistry()
    release = threading.Event()

    def stuck_run(eng, batch, metas):
        release.wait(timeout=10)
        return {"y": batch[:, 0, 0, 0].astype(np.float64)}

    rs = make_pool(reg, replicas=2, run=stuck_run)
    try:
        with pytest.raises(ValueError):
            rs.scale_to(0)
        futs = [rs.submit(_img()) for _ in range(4)]
        # both replicas busy: a tiny drain budget can't free the last slot
        report = rs.scale_to(1, drain_timeout_s=0.05)
        assert report["to"] == 2  # refused, not forced
        release.set()
        done, _ = wait(futs, timeout=10)
        assert len(done) == 4
        assert all(f.exception() is None for f in futs)
    finally:
        release.set()
        rs.close()


@pytest.mark.slow  # 8-thread storm starves on the 1-CPU gate runner and
# loses futures to timeouts that are load, not logic — slow lane only
def test_scale_races_submit_storm_every_future_resolves():
    """Scale 3->1->3 repeatedly under an 8-thread submit storm: no future
    is lost to a removed slot (the retired-queue rescue) and the pool
    ends at the commanded size."""
    reg = MetricsRegistry()

    def run(eng, batch, metas):
        time.sleep(0.001)
        return {"y": batch[:, 0, 0, 0].astype(np.float64)}

    rs = make_pool(reg, replicas=3, run=run, max_queue=None)
    futures, submit_errors = [], []
    lock = threading.Lock()
    stop = threading.Event()

    def client(tid):
        while not stop.is_set():
            try:
                f = rs.submit(_img(tid))
            except (QueueFullError, ShutdownError) as e:
                with lock:
                    submit_errors.append(e)
            else:
                with lock:
                    futures.append(f)
            time.sleep(0.0005)

    threads = [
        threading.Thread(target=client, args=(t,)) for t in range(8)
    ]
    for t in threads:
        t.start()
    for _ in range(3):
        rs.scale_to(1, drain_timeout_s=5.0)
        rs.scale_to(3)
    stop.set()
    for t in threads:
        t.join()
    done, not_done = wait(futures, timeout=60)
    assert not not_done, f"{len(not_done)} future(s) lost in scaling"
    bad = [
        f for f in futures
        if f.exception() is not None
        and not isinstance(f.exception(), (QueueFullError, ShutdownError))
    ]
    assert not bad, f"unexpected failures: {bad[:3]}"
    assert len(rs.stats()["replicas"]) == 3
    rs.close()


# ------------------------------------------------------------- autoscaler


class FakePool:
    """Scripted ReplicaSet facade: the autoscaler sees exactly the
    signals the test sets."""

    def __init__(self, n=2):
        self.n = n
        self.submitted = 0
        self.served = 0
        self.queue_depth = 0
        self.breaker = False
        self.calls = []

    def stats(self):
        return {
            "requests_submitted": self.submitted,
            "queue_depth": self.queue_depth,
            "breaker_open": self.breaker,
            "healthy": self.n,
            "batch_occupancy": 0.5,
            "replicas": {
                f"r{i}": {"served": self.served // self.n}
                for i in range(self.n)
            },
        }

    def scale_to(self, target, *, drain_timeout_s=10.0):
        report = {"from": self.n, "to": target}
        self.calls.append(target)
        self.n = target
        return report


def test_autoscaler_scales_up_on_demand_down_after_hold():
    pool = FakePool(n=2)
    asc = Autoscaler(
        pool, min_replicas=2, max_replicas=4, interval_s=1.0,
        capacity_fn=lambda: 100.0, down_hold=3, start=False,
        registry=MetricsRegistry(), clock=lambda: 0.0,
    )
    asc.tick(now=0.0)  # baseline sample
    pool.submitted += 300  # 300 req/s arrives
    pool.queue_depth = 150
    d = asc.tick(now=1.0)
    assert d["target"] > 2 and d["reason"] == "demand"
    assert pool.calls and pool.calls[-1] == d["target"]
    assert asc.events[-1]["current"] == 2
    # demand collapses: down only after down_hold consecutive low ticks,
    # one step at a time
    pool.queue_depth = 0
    t, start_n = 2.0, pool.n
    for _ in range(asc.down_hold - 1):
        asc.tick(now=t)
        t += 1.0
    assert pool.n == start_n  # held
    asc.tick(now=t)
    assert pool.n == start_n - 1  # exactly one step
    assert asc.events[-1]["reason"] == "demand"


def test_autoscaler_burn_and_breaker_force_step_up():
    class HotSLO:
        def worst_burn(self, now=None):
            return 5.0

    pool = FakePool(n=2)
    asc = Autoscaler(
        pool, min_replicas=1, max_replicas=4, slo=HotSLO(),
        capacity_fn=lambda: 1000.0, start=False,
        registry=MetricsRegistry(), clock=lambda: 0.0,
    )
    d = asc.tick(now=0.0)
    assert d["reason"] == "burn" and pool.n == 3
    pool2 = FakePool(n=2)
    pool2.breaker = True
    asc2 = Autoscaler(
        pool2, min_replicas=1, max_replicas=4,
        capacity_fn=lambda: 1000.0, start=False,
        registry=MetricsRegistry(), clock=lambda: 0.0,
    )
    d2 = asc2.tick(now=0.0)
    assert d2["reason"] == "breaker" and pool2.n == 3


def test_autoscaler_respects_bounds_and_validates():
    with pytest.raises(ValueError):
        Autoscaler(FakePool(), min_replicas=3, max_replicas=2, start=False)
    pool = FakePool(n=4)
    asc = Autoscaler(
        pool, min_replicas=2, max_replicas=4, capacity_fn=lambda: 1.0,
        down_hold=1, start=False, registry=MetricsRegistry(),
        clock=lambda: 0.0,
    )
    asc.tick(now=0.0)
    pool.submitted += 10_000  # way past max capacity
    d = asc.tick(now=1.0)
    assert d["target"] == 4  # clamped to max


def test_roofline_capacity_positive_and_derated():
    full = roofline_capacity(1e9, 1e7, utilization=1.0)
    half = roofline_capacity(1e9, 1e7, utilization=0.5)
    assert full > 0
    assert half == pytest.approx(full * 0.5)


# ---------------------------------------------------- loadgen (pure parts)


def test_loadgen_schedule_deterministic_and_profiled():
    import sys
    from pathlib import Path

    sys.path.insert(
        0, str(Path(__file__).resolve().parent.parent / "tools")
    )
    import loadgen

    mix = [("web", 0.5), ("scrape", 0.5)]
    a = loadgen.build_schedule("flash", 10.0, 10.0, 200.0, mix, seed=3)
    b = loadgen.build_schedule("flash", 10.0, 10.0, 200.0, mix, seed=3)
    assert a == b  # same seed, same schedule
    c = loadgen.build_schedule("flash", 10.0, 10.0, 200.0, mix, seed=4)
    assert a != c
    # the flash crowd concentrates arrivals in the middle window
    mid = sum(1 for t, _ in a if 4.0 <= t < 6.0)
    edge = sum(1 for t, _ in a if t < 2.0)
    assert mid > 4 * edge
    # diurnal peaks mid-run, steady doesn't
    assert loadgen.rate_at("diurnal", 5.0, 10.0, 10.0, 200.0) == 200.0
    assert loadgen.rate_at("diurnal", 0.0, 10.0, 10.0, 200.0) == 10.0
    assert loadgen.rate_at("steady", 5.0, 10.0, 10.0, 200.0) == 10.0
    with pytest.raises(ValueError):
        loadgen.rate_at("tsunami", 0.0, 1.0, 1.0, 1.0)
    assert {t for _, t in a} == {"web", "scrape"}


# ------------------------------------------------- token-packed scheduling


def _sq(size, v=0.0):
    """A square image whose side doubles as its token count via
    ``seq_len_fn=lambda a: a.shape[0]``."""
    return np.full((size, size, 3), v, np.float32)


_tok = staticmethod(lambda arr: arr.shape[0])


def test_packed_scheduler_fills_token_budget_not_image_count():
    """Mixed 'resolutions' accumulate into ONE packed group that fires
    when the token budget fills — image count alone never would."""
    stub = DispatchStub()
    sched = ContinuousScheduler(
        stub, max_batch=64, max_delay_ms=500.0, registry=MetricsRegistry(),
        packed=True, token_budget=100, seq_len_fn=lambda a: a.shape[0],
    )
    with sched:
        futs = [sched.submit(_sq(s)) for s in (40, 30, 30)]  # = 100 tokens
        done, _ = wait(futs, timeout=10)
        assert len(done) == 3
    # one dispatch, all three sizes, long before the 500ms cutoff
    assert [i[0].shape[0] for i in stub.batches[0]] == [40, 30, 30]


def test_packed_scheduler_skims_past_overflowing_entry():
    """An entry that would overflow the remaining budget is skipped, not a
    wall: smaller entries behind it top up the rung, and the skip counts
    as a priority jump."""
    gate = threading.Event()
    stub = DispatchStub(gate=gate)
    reg = MetricsRegistry()
    sched = ContinuousScheduler(
        stub, max_batch=64, max_delay_ms=40.0, registry=reg,
        packed=True, token_budget=100, seq_len_fn=lambda a: a.shape[0],
    )
    try:
        # a budget-filling decoy parks the dispatcher on the gate so all
        # three contested entries are in the accumulator before any take
        decoy = sched.submit(_sq(100))
        time.sleep(0.05)
        futs = [sched.submit(_sq(s)) for s in (60, 50, 30)]  # 140 > budget
        time.sleep(0.02)
        gate.set()
        done, _ = wait([decoy] + futs, timeout=10)
        assert len(done) == 4
    finally:
        sched.close()
    sizes = [[i[0].shape[0] for i in b] for b in stub.batches]
    assert sizes[0] == [100]
    assert sizes[1] == [60, 30], "50 should be skimmed past, 30 taken"
    assert sizes[2] == [50], "skipped entry ships next (head of order)"
    assert "serve_sched_priority_jumps_total 1" in reg.render()


def test_packed_scheduler_rejects_oversized_and_requires_seq_len_fn():
    stub = DispatchStub()
    with pytest.raises(ValueError, match="seq_len_fn"):
        ContinuousScheduler(
            stub, max_batch=8, registry=MetricsRegistry(),
            packed=True, token_budget=100,
        )
    sched = ContinuousScheduler(
        stub, max_batch=8, max_delay_ms=5.0, registry=MetricsRegistry(),
        packed=True, token_budget=100, seq_len_fn=lambda a: a.shape[0],
    )
    with sched:
        with pytest.raises(ValueError, match="token_budget"):
            sched.submit(_sq(101))


@pytest.mark.parametrize("packed", [True, False])
def test_scheduler_stamps_token_counts_on_traces(tmp_path, packed):
    """With a seq_len_fn the scheduler prices every entry and stamps
    ``tr.tokens`` — packed or not (the image-bucket control leg bills its
    padded token count pro-rata through the same field)."""
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=MetricsRegistry(), access_log=log)
    stub = DispatchStub()
    sched = ContinuousScheduler(
        stub, max_batch=8, max_delay_ms=5.0, registry=MetricsRegistry(),
        tracer=tracer, packed=packed,
        token_budget=100 if packed else None,
        seq_len_fn=lambda a: a.shape[0],
    )
    try:
        futs = [sched.submit(_sq(40)), sched.submit(_sq(40))]
        wait(futs, timeout=10)
    finally:
        sched.close()
        tracer.close()
    traces = [tr for b in stub.batches for (_, _, _, tr) in b]
    assert sorted(tr.tokens for tr in traces) == [40, 40]


def test_loadgen_resolution_grammar_and_size_draws():
    import sys
    from pathlib import Path

    sys.path.insert(
        0, str(Path(__file__).resolve().parent.parent / "tools")
    )
    import loadgen

    # 'lo-hi:w' and 'size:w' entries; bare weight defaults to 1
    assert loadgen.parse_res_spec("160-224:0.5,448:0.3,896") == [
        (160, 224, 0.5), (448, 448, 0.3), (896, 896, 1.0),
    ]
    rng = np.random.RandomState(7)
    draws = loadgen.draw_sizes(rng, [(24, 32, 1.0), (52, 64, 2.0)], 400, 4)
    assert all(b in (32, 64) for _, b in draws)
    for native, bucket in draws:
        lo = 24 if bucket == 32 else 52
        assert lo <= native <= bucket and native % 4 == 0
    # weighted: the 52-64 range should dominate ~2:1
    hi = sum(1 for _, b in draws if b == 64)
    assert 200 < hi < 340
    # seeded determinism: same seed, same draws
    again = loadgen.draw_sizes(
        np.random.RandomState(7), [(24, 32, 1.0), (52, 64, 2.0)], 400, 4
    )
    assert draws == again
