"""Memory observability contracts (obs/memwatch.py, tools/mem_doctor.py).

The properties the subsystem stands on:

- backends without ``memory_stats()`` (XLA:CPU) degrade to host-only
  telemetry: the device/drift gauges are *absent from the scrape* (never
  zero-valued), nothing crashes, and exactly one journal-able note marks
  the degradation;
- the accountant never lets a broken probe take down sampling;
- the leak sentinel fires on sustained robust growth, stays quiet on flat
  series with one-off spikes, names the fastest-growing component, and
  demotes a minor grower to ``unaccounted``;
- the ``host.leak`` chaos site grows/clears ballast exactly per the plan
  grammar, so CI can inject a leak the sentinel must catch;
- ``mem_doctor`` exits 2 naming the component on a leak incident, 0 on a
  healthy run, 2 when there is nothing to diagnose.
"""

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.faults import (
    clear_plan,
    host_leak_tick,
    install_plan,
    leak_ballast_bytes,
)
from jumbo_mae_tpu_tpu.obs.journal import RunJournal
from jumbo_mae_tpu_tpu.obs.memwatch import (
    MB,
    LeakSentinel,
    MemAccountant,
    MemoryWatcher,
    _theil_sen_slope,
    tree_nbytes,
)
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry

# ------------------------------------------------------------- primitives


def test_theil_sen_slope_linear_and_robust():
    assert _theil_sen_slope([]) == 0.0
    assert _theil_sen_slope([5.0]) == 0.0
    assert _theil_sen_slope([3.0, 3.0, 3.0, 3.0]) == 0.0
    assert _theil_sen_slope([0.0, 2.0, 4.0, 6.0]) == pytest.approx(2.0)
    # one-off spike (an eval temp buffer) barely moves the median pairwise
    # slope — the reason the sentinel uses it over least squares
    spiked = [0.0, 1.0, 2.0, 100.0, 4.0, 5.0, 6.0, 7.0]
    assert _theil_sen_slope(spiked) == pytest.approx(1.0, abs=0.35)


def test_tree_nbytes_counts_arrays_ignores_scalars():
    tree = {"a": np.zeros((4, 4), np.float32), "b": [np.zeros(8, np.int8), 3]}
    assert tree_nbytes(tree) == 4 * 4 * 4 + 8
    assert tree_nbytes(None) == 0


# ------------------------------------------------------------- accountant


def test_accountant_skips_broken_probes_and_publishes_gauge():
    reg = MetricsRegistry()
    acc = MemAccountant(registry=reg)
    acc.register("good", lambda: 123)
    acc.register("unknown", lambda: None)
    acc.register("broken", lambda: 1 / 0)
    assert acc.components() == ["broken", "good", "unknown"]
    assert acc.sample() == {"good": 123}
    assert 'mem_component_bytes{component="good"} 123' in reg.render()
    acc.unregister("good")
    assert acc.sample() == {}


# -------------------------------------------------- watcher (CPU degrade)


def test_watcher_degrades_on_cpu_without_device_gauges():
    """Acceptance: on a backend without memory_stats, sampling works, host
    gauges publish, device/drift gauges never appear in the scrape, and
    the degradation note is one-shot."""
    reg = MetricsRegistry()
    acc = MemAccountant(registry=reg)
    acc.register("ballast", leak_ballast_bytes)
    w = MemoryWatcher(accountant=acc, registry=reg)
    snap = w.sample()
    assert w.device_stats_degraded  # XLA:CPU has no usable memory_stats
    assert snap["rss_bytes"] > 0
    assert snap["py_alloc_blocks"] > 0
    assert "device_bytes" not in snap and "hbm_drift" not in snap
    assert "memory_stats() unavailable" in snap["note"]
    text = reg.render()
    assert "mem_host_rss_bytes" in text
    assert "mem_py_alloc_blocks" in text
    assert "mem_device_bytes" not in text
    assert "mem_hbm_predict_vs_measured" not in text
    # the note is journaled once, not per sample
    assert "note" not in w.sample()
    assert w.last_sample()["rss_bytes"] > 0


def test_watcher_publishes_device_and_drift_when_stats_exist(monkeypatch):
    """With a backend that reports memory_stats (faked here), the lazy
    device/drift gauges register and the drift ratio is measured/predicted."""
    from jumbo_mae_tpu_tpu.obs import memwatch

    monkeypatch.setattr(
        memwatch,
        "_device_memory_stats",
        lambda: [("tpu:0", 600 * MB, 900 * MB), ("tpu:1", 500 * MB, 800 * MB)],
    )
    reg = MetricsRegistry()
    w = MemoryWatcher(registry=reg)
    w.record_predicted_peak("train_step", 1000 * MB)
    w.record_predicted_peak("zero_is_ignored", 0)
    w.record_predicted_peak("none_is_ignored", None)
    snap = w.sample()
    assert not w.device_stats_degraded
    assert snap["device_bytes"] == 1100 * MB
    assert snap["device_peak_bytes"] == 900 * MB
    assert snap["hbm_drift"] == {"train_step": 0.9}
    text = reg.render()
    assert 'mem_device_peak_bytes{device="tpu:0"}' in text
    assert 'mem_hbm_predict_vs_measured{program="train_step"} 0.9' in text
    assert "zero_is_ignored" not in text


def test_headroom_check(monkeypatch):
    from jumbo_mae_tpu_tpu.obs import memwatch

    w = MemoryWatcher(registry=MetricsRegistry())
    monkeypatch.setattr(memwatch, "host_available_bytes", lambda: 1000 * MB)
    assert w.headroom_check(100 * MB) is None
    refusal = w.headroom_check(950 * MB)
    assert refusal is not None and "950 MiB" in refusal
    # unknowable headroom is not a refusal
    monkeypatch.setattr(memwatch, "host_available_bytes", lambda: None)
    assert w.headroom_check(10**15) is None


# ----------------------------------------------------------- leak sentinel


def _snaps(rss_series, components=None, t0=1000.0):
    for i, rss in enumerate(rss_series):
        snap = {"ts": t0 + 10.0 * i, "rss_bytes": int(rss)}
        if components:
            snap["components"] = {
                name: int(series[i]) for name, series in components.items()
            }
        yield snap


def test_sentinel_fires_once_names_component_and_latches():
    reg = MetricsRegistry()
    s = LeakSentinel(window=8, min_samples=4, min_growth_mb=32.0, registry=reg)
    rss = [1000 * MB + i * 8 * MB for i in range(8)]
    comps = {
        "cache": [i * 7 * MB for i in range(8)],
        "steady": [64 * MB] * 8,
    }
    fired = [s.observe(snap) for snap in _snaps(rss, comps)]
    hits = [f for f in fired if f is not None]
    assert len(hits) == 1
    v = hits[0]
    assert v["component"] == "cache"
    assert v["robust_growth_bytes"] >= 32 * MB
    assert v["window_span_s"] == pytest.approx(10.0 * (v["window"] - 1))
    assert s.degraded() and s.suspect["component"] == "cache"
    assert 'mem_leak_suspect{component="cache"} 1' in reg.render()
    # latched: further growth does not re-fire
    assert s.observe({"ts": 2000.0, "rss_bytes": 5000 * MB}) is None


def test_sentinel_quiet_on_flat_rss_with_spike():
    s = LeakSentinel(window=8, min_samples=4, min_growth_mb=32.0,
                     registry=MetricsRegistry())
    rss = [1000 * MB] * 8
    rss[4] = 1400 * MB  # one eval window's temp buffer
    assert all(s.observe(snap) is None for snap in _snaps(rss))
    assert not s.degraded()


def test_sentinel_demotes_minor_component_to_unaccounted():
    """A mildly warming cache (<20% of the RSS slope) must not eat the
    verdict for a native leak outside the accountant's reach."""
    s = LeakSentinel(window=6, min_samples=4, min_growth_mb=32.0,
                     registry=MetricsRegistry())
    rss = [1000 * MB + i * 20 * MB for i in range(6)]
    comps = {"cache": [i * MB for i in range(6)]}  # 1 MB/sample vs 20
    hits = [f for f in _map_observe(s, rss, comps) if f]
    assert len(hits) == 1 and hits[0]["component"] == "unaccounted"


def _map_observe(s, rss, comps):
    return [s.observe(snap) for snap in _snaps(rss, comps)]


def test_sentinel_rejects_degenerate_window():
    with pytest.raises(ValueError):
        LeakSentinel(window=1, registry=MetricsRegistry())


# --------------------------------------------------------- host.leak site


def test_host_leak_fault_grows_and_clears_ballast():
    try:
        install_plan("host.leak:corrupt(2)")
        assert host_leak_tick(key="0") == 2 * MB
        assert host_leak_tick(key="1") == 4 * MB
        assert leak_ballast_bytes() == 4 * MB
        # a `raise` action means "the leak got fixed": ballast clears
        install_plan("host.leak:raise(RuntimeError)")
        assert host_leak_tick(key="2") == 0
        # deactivation heals too
        install_plan("host.leak:corrupt(2)")
        host_leak_tick(key="3")
        install_plan(None)
        assert leak_ballast_bytes() == 0
    finally:
        clear_plan()


def test_sentinel_catches_injected_host_leak():
    """End-to-end on the library layer: the chaos site leaks, the
    accountant attributes it, the sentinel names ``fault_ballast``."""
    reg = MetricsRegistry()
    acc = MemAccountant(registry=reg)
    acc.register("fault_ballast", leak_ballast_bytes)
    s = LeakSentinel(window=8, min_samples=4, min_growth_mb=32.0,
                     registry=reg)
    try:
        install_plan("host.leak:corrupt(8)")
        base = 2000 * MB
        hit = None
        for i in range(8):
            ballast = host_leak_tick(key=str(i))
            snap = {
                "ts": 100.0 + i,
                "rss_bytes": base + ballast,  # RSS tracks the ballast
                "components": acc.sample(),
            }
            hit = s.observe(snap) or hit
        assert hit is not None and hit["component"] == "fault_ballast"
    finally:
        clear_plan()


# -------------------------------------------------------------- mem_doctor


def _doctor_run_dir(tmp_path, *, leak: bool, with_device: bool = True):
    with RunJournal(tmp_path / "journal", host=0) as j:
        j.event("run_start", config={}, env={}, start_step=0)
        for i in range(6):
            fields = {
                "step": 5 * (i + 1),
                "rss_bytes": 1000 * MB + (i * 64 * MB if leak else 0),
                "py_alloc_blocks": 100000 + i,
                "components": {
                    "fault_ballast": i * 60 * MB if leak else 0,
                    "journal_file": 4096,
                },
            }
            if with_device:
                fields.update(
                    device_bytes=700 * MB,
                    device_peak_bytes=800 * MB,
                    hbm_drift={"train_step": 0.8},
                    hbm_capacity_bytes=8192 * MB,
                )
            j.event("mem_sample", **fields)
        if leak:
            j.event(
                "mem_leak_suspect",
                step=30,
                component="fault_ballast",
                rss_growth_bytes=320 * MB,
                robust_growth_bytes=320 * MB,
                slope_bytes_per_sample=64 * MB,
                component_slope_bytes_per_sample=60 * MB,
                window=6,
                window_span_s=50.0,
            )
        j.event("shutdown", reason="completed", step=30)
    return tmp_path


class TestMemDoctor:
    def test_leak_incident_exits_two_and_names_component(self, tmp_path, capsys):
        import tools.mem_doctor as doctor

        run_dir = _doctor_run_dir(tmp_path, leak=True)
        assert doctor.main([str(run_dir)]) == 2
        report = capsys.readouterr().out
        assert "leak suspected: **fault_ballast**" in report
        assert "| fault_ballast |" in report  # attribution table row
        assert "OOM risk **low**" in report  # 800 MiB of 8 GiB
        assert "| train_step | 0.8 |" in report

    def test_healthy_run_exits_zero(self, tmp_path, capsys):
        import tools.mem_doctor as doctor

        run_dir = _doctor_run_dir(tmp_path, leak=False)
        assert doctor.main([str(run_dir), "--out", str(tmp_path / "m.md")]) == 0
        report = (tmp_path / "m.md").read_text()
        assert "no leak suspected" in report
        assert "OOM risk **low**" in report

    def test_cpu_run_skips_oom_math(self, tmp_path, capsys):
        import tools.mem_doctor as doctor

        run_dir = _doctor_run_dir(tmp_path, leak=False, with_device=False)
        assert doctor.main([str(run_dir)]) == 0
        report = capsys.readouterr().out
        assert "OOM risk not assessable" in report
        assert "no drift ratios" in report

    def test_nothing_to_diagnose_exits_two(self, tmp_path, capsys):
        import tools.mem_doctor as doctor

        assert doctor.main([str(tmp_path)]) == 2  # no journal at all
        with RunJournal(tmp_path / "journal", host=0) as j:
            j.event("run_start", config={}, env={}, start_step=0)
        assert doctor.main([str(tmp_path)]) == 2  # journal, no mem samples
        assert "no mem_sample rows" in capsys.readouterr().err
