"""The designed answer to a dead data worker, proven end-to-end.

The loader refuses to skip a dead worker (``data/loader.py`` raises
"deterministic stream lost") because skipping would silently fork the batch
sequence — the reference instead skipped samples silently on stream errors
(``/root/reference/src/dataset.py:113-119``). That crash-don't-drift call is
only an availability story if the full chain works:

    SIGKILL a worker mid-run → run aborts with the deterministic-stream
    error → restart with ``run.resume=true`` → final params bit-identical
    to a never-interrupted run.

This test drives that chain through the real CLI in subprocesses (the
worker processes are fresh-interpreter children of the CLI process).
"""

import io
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

# subprocess-heavy end-to-end suites: excluded from the <5-min signal
# run (pytest -m "not slow")
pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent


def _write_shards(root: Path, n_shards: int = 2, per_shard: int = 32) -> int:
    from PIL import Image

    from jumbo_mae_tpu_tpu.data import write_tar_samples

    rng = np.random.default_rng(0)
    root.mkdir(parents=True, exist_ok=True)
    idx = 0
    for s in range(n_shards):
        samples = []
        for _ in range(per_shard):
            img = Image.fromarray(
                rng.integers(0, 256, (48, 48, 3), dtype=np.uint8), "RGB"
            )
            buf = io.BytesIO()
            img.save(buf, format="JPEG", quality=90)
            samples.append(
                {
                    "__key__": f"s{idx:05d}",
                    "jpg": buf.getvalue(),
                    "cls": str(idx % 10).encode(),
                }
            )
            idx += 1
        write_tar_samples(str(root / f"train-{s:04d}.tar"), samples)
    return idx


def _cli_env() -> dict:
    from jumbo_mae_tpu_tpu.utils.procenv import cpu_subprocess_env, host_cache_dir

    env = cpu_subprocess_env(8, compile_cache=host_cache_dir(REPO))
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _cli_cmd(shard_root: Path, out: Path, steps: int, resume: bool) -> list[str]:
    return [
        sys.executable,
        "-m",
        "jumbo_mae_tpu_tpu.cli.train",
        "--config",
        str(REPO / "recipes" / "smoke_cpu.yaml"),
        "--set",
        f"run.output_dir={out}",
        f"run.training_steps={steps}",
        f"optim.training_steps={steps}",
        "run.train_batch_size=8",
        "run.eval_interval=3",
        "run.log_interval=3",
        "run.sanity_eval=false",
        "run.synthetic_data=false",
        f"run.resume={'true' if resume else 'false'}",
        f"data.train_shards={shard_root}/train-{{0000..0001}}.tar",
        "data.valid_shards=",
        "data.dataset_size=64",
        "data.shuffle_buffer=8",
        "data.workers=2",
        "data.image_size=32",
    ]


def _worker_pids(cli_pid: int) -> list[int]:
    """Children of the CLI process running the data-worker module."""
    pids = []
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            cmdline = (Path("/proc") / entry / "cmdline").read_bytes()
            status = (Path("/proc") / entry / "status").read_text()
        except OSError:
            continue
        if b"jumbo_mae_tpu_tpu.data._worker" not in cmdline:
            continue
        for line in status.splitlines():
            if line.startswith("PPid:") and int(line.split()[1]) == cli_pid:
                pids.append(int(entry))
    return sorted(pids)


STEPS = 24  # saves at 3, 6, ... — killed long before 24 so death is certain


@pytest.mark.slow
def test_worker_death_then_resume_is_bit_identical(tmp_path):
    _write_shards(tmp_path / "shards")
    env = _cli_env()

    # --- leg A: never interrupted -------------------------------------
    a = subprocess.run(
        _cli_cmd(tmp_path / "shards", tmp_path / "a", STEPS, resume=False),
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert a.returncode == 0, a.stdout[-2000:] + a.stderr[-2000:]

    # --- leg B: SIGKILL one worker after the first checkpoint ---------
    proc = subprocess.Popen(
        _cli_cmd(tmp_path / "shards", tmp_path / "b", STEPS, resume=False),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    # drain the pipes CONCURRENTLY with the watcher loop: the child's
    # startup chatter (XLA cpu_aot_loader E-lines, one per cached program,
    # ~3.5 KB each) can exceed the 64 KB pipe buffer, and an undrained
    # pipe blocks the child mid-run — the watcher then waits forever for a
    # checkpoint that can't be written
    import threading

    bufs: dict[str, list[str]] = {"out": [], "err": []}

    def _drain(stream, key):
        for line in stream:
            bufs[key].append(line)

    readers = [
        threading.Thread(target=_drain, args=(proc.stdout, "out"), daemon=True),
        threading.Thread(target=_drain, args=(proc.stderr, "err"), daemon=True),
    ]
    for t in readers:
        t.start()
    ckpt_step3 = tmp_path / "b" / "smoke_cpu" / "ckpt" / "last" / "3"
    deadline = time.monotonic() + 300
    killed = None
    try:
        while time.monotonic() < deadline and proc.poll() is None:
            if ckpt_step3.exists():
                workers = _worker_pids(proc.pid)
                if workers:
                    killed = workers[0]
                    os.kill(killed, signal.SIGKILL)
                    break
            time.sleep(0.05)
        assert killed is not None, "never saw checkpoint step 3 + live workers"
        proc.wait(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        for t in readers:
            t.join(timeout=30)
        proc.stdout.close()
        proc.stderr.close()
    out, err = "".join(bufs["out"]), "".join(bufs["err"])
    assert proc.returncode != 0, f"run survived a dead worker: {out[-1500:]}"
    assert "deterministic stream lost" in err, err[-2000:]

    # --- leg B resumed: must land exactly where leg A landed ----------
    b2 = subprocess.run(
        _cli_cmd(tmp_path / "shards", tmp_path / "b", STEPS, resume=True),
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert b2.returncode == 0, b2.stdout[-2000:] + b2.stderr[-2000:]

    from jumbo_mae_tpu_tpu.train.checkpoint import restore_params_any

    import jax

    pa = restore_params_any(tmp_path / "a" / "smoke_cpu" / "ckpt")
    pb = restore_params_any(tmp_path / "b" / "smoke_cpu" / "ckpt")
    leaves_a = jax.tree_util.tree_leaves(pa)
    leaves_b = jax.tree_util.tree_leaves(pb)
    assert len(leaves_a) == len(leaves_b)
    for x, y in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
