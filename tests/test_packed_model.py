"""Token-packed serving contracts: the model forward and the engine path.

What the packed rollout stands on (see ``infer/packing.py`` docstring):

- **segment isolation is bit-exact**: with an identical pack plan,
  perturbing one request's pixels cannot move any other segment's output
  by a single bit — the block-diagonal mask is the only cross-token op;
- **padding is inert**: garbage in pad token positions (segment id 0)
  produces bit-identical pooled outputs to zero padding, and row-bucketed
  all-pad rows change nothing;
- **packed == unpacked**: per-request numeric parity against the plain
  forward on the same tree, across resolutions and mixed tasks, at the
  same thresholds the int8 quant gate uses (cosine >= 0.999, top-1 >=
  0.98);
- a wrong-resolution *unpacked* predict raises the typed
  ``ResolutionMismatchError`` so a router can re-route to the packed path.
"""

from pathlib import Path

import jax
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.config import load_config
from jumbo_mae_tpu_tpu.infer import InferenceEngine, ResolutionMismatchError
from jumbo_mae_tpu_tpu.infer import packing
from jumbo_mae_tpu_tpu.models import JumboViT, preset

RECIPE_OVERRIDES = [
    "model.overrides.dtype=float32",
    "model.dec_layers=1",
    "model.dec_dim=32",
    "model.dec_heads=2",
    "model.dec_dtype=float32",
]


def tiny_cfg(extra=()):
    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    return load_config(recipe, RECIPE_OVERRIDES + list(extra))


@pytest.fixture(scope="module")
def engine():
    # smoke recipe: 32px native, patch 4, sincos2d posemb (resolution-agile)
    return InferenceEngine(tiny_cfg(), max_batch=8, max_tokens=512)


@pytest.fixture(scope="module")
def engine_labels():
    return InferenceEngine(tiny_cfg(), max_batch=8, max_tokens=512, labels=11)


def _images(sizes, seed=0):
    rng = np.random.RandomState(seed)
    return [
        rng.randint(0, 256, (s, s, 3)).astype(np.uint8) for s in sizes
    ]


# ------------------------------------------------- model-level inertness


class TestPackedForward:
    """Direct ``serve_packed`` applies on a tiny float32 JumboViT — the
    mask/pooling properties, independent of the engine pipeline."""

    @classmethod
    def setup_class(cls):
        cls.cfg = preset(
            "vit_t16", image_size=32, patch_size=8, dtype="float32",
            labels=None, posemb="sincos2d",
        )
        cls.model = JumboViT(cls.cfg)
        cls.vars_ = cls.model.init(
            {"params": jax.random.key(0)},
            np.zeros((1, 32, 32, 3), np.float32),
        )
        cls.k = cls.cfg.num_cls_tokens

    def _pack(self, imgs):
        # per-resolution patchify, the way the engine's stage 1 does it:
        # sincos2d posemb is parameter-free, so one params tree serves a
        # model variant at any patch-aligned image_size
        k = self.k
        toks = []
        for im in imgs:
            model_r = JumboViT(self.cfg.replace(image_size=im.shape[0]))
            toks.append(
                np.asarray(
                    model_r.apply(
                        self.vars_, im[None].astype(np.float32),
                        method=JumboViT.patchify,
                    )
                )[0]
            )
        lens = [t.shape[0] + k for t in toks]
        plan = packing.pack_ffd(lens, 64)
        arrs = packing.build_arrays(plan, k)
        buf = packing.place_tokens(plan, toks, k)
        return plan, arrs, buf

    def _serve(self, arrs, buf):
        out = self.model.apply(
            self.vars_, buf, arrs["segment_ids"], arrs["cls_pos"],
            arrs["cls_index"], method=self.model.serve_packed,
        )
        return np.asarray(out["pooled"])

    def test_segment_isolation_is_bit_exact(self):
        # same plan geometry, different pixels in request 1 only
        a = _images([16, 16, 16], seed=1)
        b = [a[0], _images([16], seed=99)[0], a[2]]
        plan_a, arrs_a, buf_a = self._pack(a)
        plan_b, arrs_b, buf_b = self._pack(b)
        assert plan_a == plan_b  # identical lengths -> identical plan
        out_a = self._serve(arrs_a, buf_a)
        out_b = self._serve(arrs_b, buf_b)
        for s in plan_a.segments:
            same = np.array_equal(
                out_a[s.row, s.slot], out_b[s.row, s.slot]
            )
            if s.request == 1:
                assert not same, "perturbed request must actually change"
            else:
                assert same, f"request {s.request} leaked across segments"

    def test_pad_tokens_are_inert(self):
        imgs = _images([16, 16], seed=2)
        plan, arrs, buf = self._pack(imgs)
        clean = self._serve(arrs, buf)
        # garbage everywhere the plan owns nothing (segment id 0)
        dirty = buf.copy()
        pad = arrs["segment_ids"] == 0
        dirty[pad] = 1e6
        noisy = self._serve(arrs, dirty)
        for s in plan.segments:
            assert np.array_equal(
                clean[s.row, s.slot], noisy[s.row, s.slot]
            ), "pad values reached a real segment"

    def test_bucketed_extra_rows_are_inert(self):
        # the executable runs row-bucketed (rows=4 for a 2-row plan); the
        # extra all-pad rows must be bit-inert WITHIN that fixed shape —
        # garbage there cannot move any real segment. (Comparing across
        # different row counts is a different XLA program and only agrees
        # to ULP, so the bit-exact claim is same-shape.)
        imgs = _images([16, 16], seed=3)
        plan, _, buf1 = self._pack(imgs)
        arrs4 = packing.build_arrays(
            plan, self.k, rows=4, max_segments=plan.max_segments
        )
        buf4 = np.zeros((4,) + buf1.shape[1:], buf1.dtype)
        buf4[: buf1.shape[0]] = buf1
        clean = self._serve(arrs4, buf4)
        dirty = buf4.copy()
        dirty[plan.rows :] = 1e6  # entire bucketed rows are garbage
        noisy = self._serve(arrs4, dirty)
        for s in plan.segments:
            assert np.array_equal(
                clean[s.row, s.slot], noisy[s.row, s.slot]
            ), "bucketed pad rows reached a real segment"


# ------------------------------------------------- engine pipeline


class TestPredictPacked:
    def test_end_to_end_mixed_resolutions(self, engine):
        imgs = _images([24, 32, 32, 40], seed=4)
        out = engine.predict_packed(imgs, "features")
        assert len(out) == 4
        dim = out[0].shape[-1]
        assert all(o.shape[-1] == dim for o in out)
        bd = engine.last_breakdown()
        assert 0.0 <= bd["pad_fraction"] < 1.0

    def test_parity_features_two_resolutions(self, engine):
        rep = engine.packed_parity(_images([24, 24, 32, 32, 40], seed=5))
        assert rep["pass"], rep
        assert rep["feature_cosine_min"] >= 0.999

    def test_parity_mixed_tasks(self, engine_labels):
        imgs = _images([24, 32, 32, 40], seed=6)
        tasks = ["features", "logits", "features", "logits"]
        rep = engine_labels.packed_parity(imgs, tasks)
        assert rep["pass"], rep
        assert rep["logits_top1_agree"] >= 0.98
        out = engine_labels.predict_packed(imgs, tasks)
        assert out[1].shape[-1] == 11  # logits rows carry label logits
        assert out[0].shape[-1] != 11 or out[0].ndim != out[1].ndim

    def test_unaligned_size_rejected(self, engine):
        with pytest.raises(ValueError, match="patch"):
            engine.seq_len(30)  # not a multiple of patch 4
        with pytest.raises(ValueError):
            engine.predict_packed(_images([30], seed=7))

    def test_resolution_mismatch_is_typed_on_unpacked_path(self, engine):
        with pytest.raises(ResolutionMismatchError) as ei:
            engine.predict(np.stack(_images([24, 24], seed=8)))
        assert ei.value.expected == 32
        assert ei.value.got == (24, 24)
        # and the packed path accepts exactly that request
        out = engine.predict_packed(_images([24, 24], seed=8))
        assert len(out) == 2

    def test_warmup_packed_precompiles(self):
        eng = InferenceEngine(tiny_cfg(), max_batch=4, max_tokens=512)
        n = eng.warmup_packed([24, 32, 32], ("features",))
        assert n > 0
        before = sum(eng.compile_counts.values())
        eng.predict_packed(_images([24, 32, 32], seed=9), "features")
        assert sum(eng.compile_counts.values()) == before, (
            "hot path compiled after warmup_packed"
        )
