"""Ring attention vs full attention on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.utils import compat
from jumbo_mae_tpu_tpu.ops.flash_attention import xla_attention
from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
from jumbo_mae_tpu_tpu.parallel.ring_attention import (
    ring_attention_sharded,
    ring_self_attention,
)


def _qkv(b=2, s=64, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    return mk() * (d**-0.5), mk(), mk()


@pytest.mark.parametrize("seq_parallel", [2, 4, 8])
def test_ring_matches_full_attention(devices, seq_parallel):
    mesh = create_mesh(MeshConfig(data=1, fsdp=1, seq=seq_parallel))
    q, k, v = _qkv()
    expected = xla_attention(q, k, v)
    out = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5)


def test_ring_with_batch_sharding(devices):
    mesh = create_mesh(MeshConfig(data=2, fsdp=1, seq=4))
    q, k, v = _qkv(b=4, s=32)
    expected = xla_attention(q, k, v)
    out = ring_attention_sharded(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5)


def test_ring_gradients_match(devices):
    """Ring attention must be differentiable and match full-attention grads."""
    mesh = create_mesh(MeshConfig(data=1, fsdp=1, seq=4))
    q, k, v = _qkv(s=32)

    def loss_ring(q, k, v):
        return ring_attention_sharded(q, k, v, mesh).sum()

    def loss_full(q, k, v):
        return xla_attention(q, k, v).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("s", [19, 197])
def test_ring_self_attention_uneven_seq(devices, s):
    """Ambient-mesh wrapper pads odd sequence lengths and masks pad keys."""
    mesh = create_mesh(MeshConfig(data=2, fsdp=1, seq=4))
    q, k, v = _qkv(b=4, s=s)
    expected = xla_attention(q, k, v)
    with compat.set_mesh(mesh):
        out = jax.jit(ring_self_attention)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
    )


def test_ring_self_attention_no_mesh_fallback():
    """Without an ambient mesh (or with seq=1) it degrades to xla_attention."""
    q, k, v = _qkv(s=16)
    out = ring_self_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(xla_attention(q, k, v)), rtol=1e-6
    )


def test_vit_forward_ring_equals_einsum(devices):
    """Full Jumbo ViT forward with attn_impl='ring' under a seq-sharded mesh
    must match the einsum implementation (uneven 3+16-token sequence)."""
    from jumbo_mae_tpu_tpu.models import JumboViT, preset

    mesh = create_mesh(MeshConfig(data=2, fsdp=1, seq=4))
    images = jnp.asarray(
        np.random.default_rng(0).integers(0, 255, (4, 32, 32, 3)), jnp.float32
    ) / 255.0
    cfg = preset("vit_t16", image_size=32, patch_size=8, labels=10, dtype="float32")
    model_ein = JumboViT(cfg.replace(attn_impl="einsum"))
    params = model_ein.init(jax.random.key(0), images)
    want = model_ein.apply(params, images)
    model_ring = JumboViT(cfg.replace(attn_impl="ring"))
    with compat.set_mesh(mesh):
        got = jax.jit(model_ring.apply)(params, images)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_ring_long_sequence_jit(devices):
    """jit + mesh sharding compiles and runs for a longer sequence."""
    mesh = create_mesh(MeshConfig(data=1, fsdp=1, seq=8))
    q, k, v = _qkv(b=1, s=1024, h=2, d=16)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh))(q, k, v)
    expected = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5)


def test_ring_flash_inner_matches_full_attention(devices):
    """inner="flash" (round 5): O(chunk)-memory Pallas hops with a
    differentiable lse merge must match full attention — forward AND
    gradients (the lse cotangent path through the merge weights is the
    part a naive stopped-lse merge would get wrong). interpret=True forces
    the kernel path on this CPU host (off-TPU the default falls back to
    the einsum inner, which would make this test vacuous)."""
    mesh = create_mesh(MeshConfig(data=1, fsdp=1, seq=2))
    q, k, v = _qkv(s=64)
    expected = xla_attention(q, k, v)
    out = ring_attention_sharded(q, k, v, mesh, inner="flash", interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
    )

    g_ring = jax.grad(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, mesh, inner="flash", interpret=True
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_full = jax.grad(
        lambda q, k, v: xla_attention(q, k, v).sum(), argnums=(0, 1, 2)
    )(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5
        )


def test_ring_flash_inner_rejects_uneven_split(devices):
    mesh = create_mesh(MeshConfig(data=1, fsdp=1, seq=4))
    q, k, v = _qkv(s=19)
    with pytest.raises(ValueError, match="divide"):
        ring_self_attention(q, k, v, mesh=mesh, inner="flash")


def test_ring_flash_inner_falls_back_off_tpu(devices):
    """Without interpret=True, a non-TPU backend silently uses the einsum
    inner (never the orders-of-magnitude-slower Pallas interpreter)."""
    mesh = create_mesh(MeshConfig(data=1, fsdp=1, seq=2))
    q, k, v = _qkv(s=32)
    out = ring_attention_sharded(q, k, v, mesh, inner="flash")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(xla_attention(q, k, v)),
        rtol=2e-5, atol=2e-5,
    )
