"""Fleet observability: beacons, the host-0 aggregator's status machine,
multi-host journal merging, host-selected fault injection, host-tagged
flight records, and the two offline doctors over fleet artifacts.

Covers the PR-11 acceptance surface without any networking — the protocol's
shared medium is a plain directory, so every behavior (straggler by lag,
straggler by step-time ratio with data-wait attribution, lost/rejoined
transitions, /healthz degradation, torn-line tolerance in a merged read) is
driven by synthetic beacon/journal files plus one short real train run.
"""

import json
import math
from pathlib import Path

import pytest

from jumbo_mae_tpu_tpu.faults import (
    clear_plan,
    current_host_index,
    fault_point,
    install_plan,
    set_host_index,
)
from jumbo_mae_tpu_tpu.obs.fleet import FleetAggregator, HostBeacon, read_beacons
from jumbo_mae_tpu_tpu.obs.flightrec import FlightRecorder
from jumbo_mae_tpu_tpu.obs.journal import RunJournal, read_merged_journal
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry

RECIPES = Path(__file__).resolve().parent.parent / "recipes"

T0 = 1_700_000_000.0  # fixed fleet epoch: every scan passes `now` explicitly


# ----------------------------------------------------------------- beacons


class TestHostBeacon:
    def test_write_read_roundtrip(self, tmp_path):
        b = HostBeacon(tmp_path, host=3)
        payload = b.write(
            step=17,
            step_time_ema_s=0.25,
            data_wait_fraction=0.1,
            shard_retries=2,
            sentinel_bad_steps=1,
            now=T0,
        )
        assert payload["heartbeat"] == T0
        got = read_beacons(tmp_path)
        assert set(got) == {3}
        assert got[3]["step"] == 17
        assert got[3]["step_time_ema_s"] == 0.25
        assert got[3]["shard_retries"] == 2
        assert got[3]["host"] == 3 and got[3]["pid"] == b.pid

    def test_rewrite_is_atomic_no_tmp_left(self, tmp_path):
        b = HostBeacon(tmp_path, host=0)
        for step in range(5):
            b.write(step=step, now=T0 + step)
        assert b.writes == 5
        # only the beacon itself remains — the tmp was renamed away
        assert [p.name for p in tmp_path.iterdir()] == ["host-0.json"]
        assert read_beacons(tmp_path)[0]["step"] == 4

    def test_corrupt_and_foreign_files_skipped(self, tmp_path):
        HostBeacon(tmp_path, host=0).write(step=1, now=T0)
        (tmp_path / "host-1.json").write_text('{"step": 5, "heart')  # torn copy
        (tmp_path / "host-x.json").write_text("{}")  # unparseable index
        (tmp_path / "host-2.json").write_text("[1, 2]")  # not a dict
        got = read_beacons(tmp_path)
        assert set(got) == {0}

    def test_missing_dir_reads_empty(self, tmp_path):
        assert read_beacons(tmp_path / "nope") == {}


# -------------------------------------------------------------- aggregator


def _fleet(tmp_path, **kw):
    """Aggregator over tmp_path with an isolated registry + captured events."""
    events: list[dict] = []
    kw.setdefault("registry", MetricsRegistry())
    agg = FleetAggregator(
        tmp_path,
        on_event=lambda etype, **p: events.append({"type": etype, **p}),
        **kw,
    )
    return agg, events


class TestAggregator:
    def test_straggler_by_step_lag(self, tmp_path):
        for h, step in ((0, 10), (1, 10), (2, 7)):
            HostBeacon(tmp_path, host=h).write(step=step, now=T0)
        reg = MetricsRegistry()
        agg, events = _fleet(tmp_path, expected_hosts=3, lag_steps=2, registry=reg)
        s = agg.scan(now=T0 + 1)
        assert s["alive"] == 3 and s["max_step"] == 10 and s["missing"] == []
        assert s["stragglers"] == [2] and s["lost"] == []
        assert s["hosts"][2]["status"] == "straggler"
        assert s["hosts"][2]["lag"] == 3
        assert s["degraded"] is True
        # gauges carry per-host values with string labels
        assert reg.gauge("fleet_step_lag", labels=("host",)).labels(host="2").value == 3
        assert reg.gauge("fleet_step", labels=("host",)).labels(host="0").value == 10
        assert reg.gauge("fleet_straggler", labels=("host",)).labels(host="2").value == 1
        assert reg.gauge("fleet_hosts_alive").value == 3
        # the transition event fired exactly once, not once per scan
        assert [e["type"] for e in events] == ["fleet_straggler"]
        assert events[0]["host_id"] == 2 and events[0]["lag"] == 3
        agg.scan(now=T0 + 2)
        assert len(events) == 1

    def test_straggler_by_ema_with_data_wait_symptom(self, tmp_path):
        HostBeacon(tmp_path, host=0).write(
            step=10, step_time_ema_s=0.1, data_wait_fraction=0.02, now=T0
        )
        HostBeacon(tmp_path, host=1).write(
            step=10, step_time_ema_s=0.4, data_wait_fraction=0.7, now=T0
        )
        agg, events = _fleet(tmp_path, expected_hosts=2, ratio=1.5)
        s = agg.scan(now=T0 + 1)
        # no step lag at all — the EMA ratio alone trips the straggler flag,
        # and the outsized wait fraction attributes it to data starvation
        assert s["stragglers"] == [1]
        assert events[0]["type"] == "fleet_straggler"
        assert events[0]["symptom"] == "data_wait"
        assert s["hosts"][1]["symptom"] == "data_wait"

    def test_lockstep_fleet_straggler_by_data_wait_alone(self, tmp_path):
        # a fully synchronous fleet is lockstep: the slow host drags every
        # step, so steps AND wall-clock EMAs equalize fleet-wide — the only
        # distinguishing signal left is the data-wait share (this is exactly
        # what the 2-process CPU chaos smoke observes)
        HostBeacon(tmp_path, host=0).write(
            step=80, step_time_ema_s=0.7, data_wait_fraction=0.01, now=T0
        )
        HostBeacon(tmp_path, host=1).write(
            step=80, step_time_ema_s=0.7, data_wait_fraction=0.45, now=T0
        )
        agg, events = _fleet(tmp_path, expected_hosts=2)
        s = agg.scan(now=T0 + 1)
        assert s["stragglers"] == [1]
        assert s["hosts"][1]["symptom"] == "data_wait"
        assert events[0]["type"] == "fleet_straggler"
        assert events[0]["symptom"] == "data_wait"

    def test_single_host_never_straggles(self, tmp_path):
        HostBeacon(tmp_path, host=0).write(step=3, now=T0)
        agg, events = _fleet(tmp_path, expected_hosts=1)
        s = agg.scan(now=T0 + 1)
        assert s["stragglers"] == [] and s["degraded"] is False

    def test_lost_then_rejoined(self, tmp_path):
        HostBeacon(tmp_path, host=0).write(step=50, now=T0 + 100)
        HostBeacon(tmp_path, host=1).write(step=48, now=T0)
        agg, events = _fleet(tmp_path, expected_hosts=2, dead_after_s=60.0)
        s = agg.scan(now=T0 + 101)
        assert s["lost"] == [1] and s["alive"] == 1
        assert s["hosts"][1]["status"] == "lost"
        assert s["degraded"] is True
        assert [e["type"] for e in events] == ["fleet_host_lost"]
        assert events[0]["host_id"] == 1 and events[0]["last_step"] == 48
        # a fresh beacon (restarted process) flips it back with a rejoin event
        HostBeacon(tmp_path, host=1).write(step=49, now=T0 + 102)
        s = agg.scan(now=T0 + 103)
        assert s["lost"] == [] and s["alive"] == 2
        assert [e["type"] for e in events][-1] == "fleet_host_rejoined"
        assert events[-1]["host_id"] == 1

    def test_missing_host_reported_without_lost_event(self, tmp_path):
        HostBeacon(tmp_path, host=0).write(step=5, now=T0)
        agg, events = _fleet(tmp_path, expected_hosts=4)
        s = agg.scan(now=T0 + 1)
        # hosts that never beaconed are *missing*, not lost — no heartbeat
        # history exists to age, so no transition event fires
        assert s["missing"] == [1, 2, 3]
        assert s["lost"] == [] and events == []

    def test_degraded_rescans_stale_summary(self, tmp_path):
        import time as _time

        HostBeacon(tmp_path, host=0).write(step=5)
        HostBeacon(tmp_path, host=1).write(step=5)
        agg, _ = _fleet(tmp_path, expected_hosts=2, dead_after_s=60.0)
        assert agg.degraded() is False  # both hearts fresh (real clock)
        # hand-write a stale heartbeat: host 1 died 120s "ago"
        p = tmp_path / "host-1.json"
        rec = json.loads(p.read_text())
        rec["heartbeat"] = _time.time() - 120.0
        p.write_text(json.dumps(rec))
        agg._last_scan = 0.0  # force the freshness check to rescan
        assert agg.degraded() is True
        assert agg.summary()["lost"] == [1]


# --------------------------------------------------- multi-host journal merge


class TestMergedJournal:
    def _write(self, d, host, rows):
        with RunJournal(d, host=host) as j:
            for ts, etype, fields in rows:
                rec = j.event(etype, **fields)
                # pin ts deterministically (event() stamps real time)
                self._patch_ts(j.path, rec["seq"], ts)

    @staticmethod
    def _patch_ts(path, seq, ts):
        lines = path.read_text().splitlines()
        out = []
        for ln in lines:
            rec = json.loads(ln)
            if rec.get("seq") == seq:
                rec["ts"] = ts
            out.append(json.dumps(rec, separators=(",", ":")))
        path.write_text("\n".join(out) + "\n")

    def test_merge_orders_by_ts_host_seq(self, tmp_path):
        self._write(
            tmp_path / "journal", 0,
            [(1.0, "run_start", {}), (3.0, "step", {"step": 2})],
        )
        self._write(
            tmp_path / "journal-host1", 1,
            [(1.0, "run_start", {}), (2.0, "step", {"step": 1})],
        )
        evs = read_merged_journal(tmp_path)
        assert [(e["ts"], e["host"], e["type"]) for e in evs] == [
            (1.0, 0, "run_start"),
            (1.0, 1, "run_start"),
            (2.0, 1, "step"),
            (3.0, 0, "step"),
        ]

    def test_torn_line_in_one_host_costs_only_that_line(self, tmp_path):
        self._write(tmp_path / "journal", 0, [(1.0, "run_start", {})])
        self._write(tmp_path / "journal-host1", 1, [(1.5, "run_start", {})])
        seg = sorted((tmp_path / "journal-host1").glob("journal-*.jsonl"))[-1]
        with open(seg, "a") as f:
            f.write('{"ts": 2.0, "seq": 1, "type": "step", "ho')  # SIGKILL
        evs = read_merged_journal(tmp_path)
        assert [(e["host"], e["type"]) for e in evs] == [
            (0, "run_start"),
            (1, "run_start"),
        ]

    def test_host_inferred_from_dir_name_for_legacy_rows(self, tmp_path):
        # rows written WITHOUT host= (pre-multi-host journals) inherit the
        # index encoded in the directory name on a merged read
        self._write(tmp_path / "journal", None, [(1.0, "step", {"step": 1})])
        self._write(
            tmp_path / "journal-host2", None, [(2.0, "step", {"step": 1})]
        )
        evs = read_merged_journal(tmp_path)
        assert [e["host"] for e in evs] == [0, 2]

    def test_single_journal_dir_and_file_still_work(self, tmp_path):
        self._write(tmp_path / "journal", 0, [(1.0, "run_start", {})])
        assert read_merged_journal(tmp_path / "journal")[0]["host"] == 0
        seg = sorted((tmp_path / "journal").glob("journal-*.jsonl"))[0]
        assert read_merged_journal(seg)[0]["type"] == "run_start"

    def test_missing_journal_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_merged_journal(tmp_path)


# --------------------------------------------------- host-selected injection


@pytest.fixture
def _clean_host_identity():
    yield
    set_host_index(None)
    clear_plan()


class TestHostSelector:
    def test_fires_only_on_matching_host(self, _clean_host_identity):
        install_plan("data.decode:nan@host=1")
        set_host_index(0)
        assert fault_point("data.decode", data=1.0) == 1.0
        set_host_index(1)
        assert math.isnan(fault_point("data.decode", data=1.0))

    def test_env_fallback_for_worker_subprocesses(
        self, _clean_host_identity, monkeypatch
    ):
        # set_host_index mirrors into GRAFT_HOST; a fresh resolution (as in a
        # spawned data worker that never called set_host_index) reads it back
        set_host_index(3)
        import os

        assert os.environ["GRAFT_HOST"] == "3"
        set_host_index(None)  # forget the pin, keep resolving lazily
        monkeypatch.setenv("GRAFT_HOST", "1")
        install_plan("data.decode:nan@host=1")
        assert math.isnan(fault_point("data.decode", data=1.0))

    def test_combines_with_other_selectors(self, _clean_host_identity):
        set_host_index(1)
        install_plan("data.decode:nan@host=1,n<1")
        assert math.isnan(fault_point("data.decode", data=1.0))
        assert fault_point("data.decode", data=1.0) == 1.0  # n<1 exhausted


# -------------------------------------------------- host-tagged flight rec


class TestFlightRecorderHostTag:
    def test_nonzero_host_tags_filename_and_payload(self, tmp_path):
        fr = FlightRecorder(tmp_path, host=2)
        fr.record_step(1, {"loss": 1.0})
        path = fr.dump("sigterm")
        assert path.name.startswith("flightrec-h2-")
        assert json.loads(path.read_text())["host"] == 2

    def test_host_zero_keeps_legacy_names(self, tmp_path):
        fr = FlightRecorder(tmp_path, host=0)
        path = fr.dump("x")
        assert path.name.startswith("flightrec-") and "h0" not in path.name
        assert json.loads(path.read_text())["host"] == 0


# ------------------------------------------------------------ fleet doctor


def _incident_fleet_dir(tmp_path: Path) -> Path:
    """Run dir with host 1 straggling (data-wait) and journaled transitions."""
    fleet = tmp_path / "fleet"
    HostBeacon(fleet, host=0).write(
        step=40, step_time_ema_s=0.1, data_wait_fraction=0.03, now=T0 + 40
    )
    HostBeacon(fleet, host=1).write(
        step=30, step_time_ema_s=0.35, data_wait_fraction=0.8, now=T0 + 40
    )
    with RunJournal(tmp_path / "journal", host=0) as j:
        j.event("run_start", config={}, env={}, start_step=0)
        j.event(
            "fleet_straggler",
            host_id=1,
            step=32,
            lag=4,
            symptom="data_wait",
            step_time_ema_s=0.35,
            fleet_median_step_s=0.1,
            data_wait_fraction=0.8,
        )
        j.event("shutdown", reason="completed", step=40)
    return tmp_path


class TestFleetDoctor:
    def test_exit_zero_and_names_straggler(self, tmp_path, capsys):
        import tools.fleet_doctor as doctor

        run_dir = _incident_fleet_dir(tmp_path)
        assert doctor.main([str(run_dir)]) == 0
        report = capsys.readouterr().out
        assert "straggler: **host 1**" in report
        assert "data-wait-dominant" in report
        assert "| 1 | straggler |" in report
        assert "fleet_straggler" in report  # timeline row

    def test_lost_host_named(self, tmp_path, capsys):
        fleet = tmp_path / "fleet"
        HostBeacon(fleet, host=0).write(step=100, now=T0 + 200)
        HostBeacon(fleet, host=1).write(step=80, now=T0)  # 200s stale
        import tools.fleet_doctor as doctor

        assert doctor.main([str(tmp_path), "--dead-after-s", "60"]) == 0
        report = capsys.readouterr().out
        assert "lost: **host 1**" in report
        assert "last beacon at step 80" in report

    def test_healthy_fleet(self, tmp_path, capsys):
        fleet = tmp_path / "fleet"
        for h in (0, 1):
            HostBeacon(fleet, host=h).write(step=10, now=T0)
        import tools.fleet_doctor as doctor

        assert doctor.main([str(tmp_path), "--out", str(tmp_path / "f.md")]) == 0
        assert "fleet healthy" in (tmp_path / "f.md").read_text()

    def test_exit_two_without_beacons(self, tmp_path):
        import tools.fleet_doctor as doctor

        assert doctor.main([str(tmp_path)]) == 2


# ------------------------------------------- run doctor over merged journals


def _merged_run_dir(tmp_path: Path) -> Path:
    """Both hosts journal the same 2 step windows; only host 0's may count."""
    for host in (0, 1):
        d = tmp_path / ("journal" if host == 0 else f"journal-host{host}")
        with RunJournal(d, host=host) as j:
            j.event("run_start", config={}, env={}, start_step=0)
            for s in (2, 4):
                j.event(
                    "step",
                    step=s,
                    metrics={
                        "train/loss": 1.0,
                        "perf/images_per_sec": 100.0 * (1 + host),
                    },
                    data_wait_fraction=0.05,
                )
            j.event("shutdown", reason="completed", step=4)
    with RunJournal(tmp_path / "journal", host=0) as j:
        j.event("fleet_straggler", host_id=1, step=3, lag=2, symptom="data_wait")
        j.event("fleet_host_lost", host_id=1, last_step=4, heartbeat_age_s=70.0)
    return tmp_path


class TestRunDoctorMerged:
    def test_no_double_counted_steps_and_fleet_timeline(self, tmp_path, capsys):
        import tools.run_doctor as doctor

        run_dir = _merged_run_dir(tmp_path)
        assert doctor.main([str(run_dir)]) == 0
        report = capsys.readouterr().out
        # host 0's 2 windows drive throughput — 4 windows would mean host 1's
        # rows were double-counted (and "best 200" would leak host 1's rate)
        assert "images/sec across 2 windows" in report
        assert "best 100" in report
        # fleet transitions render in the timeline with the affected host
        assert "fleet_straggler" in report
        assert "host 1 at step 3, lag 2" in report
        assert "fleet_host_lost" in report
        assert "merged journal across 2 hosts" in report

    def test_single_host_journal_unchanged(self, tmp_path, capsys):
        import tools.run_doctor as doctor

        with RunJournal(tmp_path / "journal", host=0) as j:
            j.event("run_start", config={}, env={}, start_step=0)
            j.event("step", step=5, metrics={"train/loss": 0.9})
            j.event("shutdown", reason="completed", step=5)
        assert doctor.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "no incidents recorded" in out
        assert "merged journal" not in out


# ------------------------------------------------- /healthz degraded compose


def test_healthz_degraded_predicates_compose():
    from jumbo_mae_tpu_tpu.obs.exporter import HealthState

    h = HealthState()
    h.set_ready(True)
    flags = {"a": False, "b": False}
    h.degraded_when(lambda: flags["a"])
    h.degraded_when(lambda: flags["b"])  # must OR, not replace
    assert h.report()[1]["degraded"] is False
    flags["b"] = True
    assert h.report()[1]["degraded"] is True
    flags["b"] = False
    flags["a"] = True
    assert h.report()[1]["degraded"] is True

    def boom():
        raise RuntimeError("probe died")

    h2 = HealthState()
    h2.set_ready(True)
    h2.degraded_when(boom)
    assert "probe error" in str(h2.report()[1]["degraded"])


# ------------------------------------------------------------------- e2e


def test_train_run_writes_beacon_and_fleet_doctor_reads_it(tmp_path):
    """Acceptance: a short CPU run (single host) leaves a fresh beacon under
    <run_dir>/fleet/ with real step/step-time/data-wait numbers, and
    fleet_doctor exits 0 on the run dir calling the 1-host fleet healthy."""
    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config

    import tools.fleet_doctor as doctor

    cfg = load_config(
        RECIPES / "smoke_cpu.yaml",
        [
            f"run.output_dir={tmp_path}",
            "run.training_steps=4",
            "optim.training_steps=4",
            "optim.warmup_steps=2",
            "run.log_interval=2",
            # no eval leg: the beacon/doctor asserts below never look at
            # eval, and the eval step's extra XLA compile is pure wall-clock
            "run.eval_interval=100000",
            "run.sanity_eval=false",
        ],
    )
    metrics = train(cfg)
    assert math.isfinite(metrics["train/loss"])
    run_dir = tmp_path / "smoke_cpu"
    beacons = read_beacons(run_dir / "fleet")
    assert set(beacons) == {0}
    b = beacons[0]
    assert b["step"] == 4
    assert b["step_time_ema_s"] > 0
    assert 0.0 <= b["data_wait_fraction"] <= 1.0
    assert b["sentinel_bad_steps"] == 0
    assert doctor.main([str(run_dir), "--out", str(tmp_path / "fleet.md")]) == 0
    assert "fleet healthy" in (tmp_path / "fleet.md").read_text()


# ------------------------------------------------------ memory beacon fields


class TestFleetMemory:
    def test_old_schema_beacons_parse_without_memory_fields(self, tmp_path):
        """Forward-compat: beacons from writers that predate the memory
        fields (no rss_bytes/device_peak_bytes) flow through the live
        aggregator AND fleet_doctor's analyze without crashing/flagging."""
        for h in (0, 1):
            HostBeacon(tmp_path, host=h).write(step=10, now=T0)
        for b in read_beacons(tmp_path).values():
            assert "rss_bytes" not in b and "device_peak_bytes" not in b

        agg, _ = _fleet(tmp_path, expected_hosts=2)
        s = agg.scan(now=T0 + 1)
        assert s["alive"] == 2 and s["mem_outliers"] == []
        assert all(h["rss_bytes"] is None for h in s["hosts"].values())

        import tools.fleet_doctor as doctor

        res = doctor.analyze(read_beacons(tmp_path))
        assert res["median_rss_bytes"] == 0
        assert not any(h["mem_outlier"] for h in res["hosts"].values())

    def test_memory_outlier_flagged_not_statused(self, tmp_path):
        """A host far above the fleet-median RSS (>= ratio x median AND
        past the absolute floor) is flagged as a memory outlier, while its
        fleet status stays ok — memory skew is a flag, not a lifecycle."""
        mib = 1024 * 1024
        for h, rss in ((0, 1000 * mib), (1, 1000 * mib), (2, 2000 * mib)):
            HostBeacon(tmp_path, host=h).write(
                step=10, rss_bytes=rss, device_peak_bytes=rss // 2, now=T0
            )
        reg = MetricsRegistry()
        agg, _ = _fleet(tmp_path, expected_hosts=3, registry=reg)
        s = agg.scan(now=T0 + 1)
        assert s["mem_outliers"] == [2]
        assert s["hosts"][2]["mem_outlier"] and s["hosts"][2]["status"] == "ok"
        assert not s["degraded"]  # outlier alone does not degrade the fleet
        g = reg.gauge("fleet_mem_outlier", "x", labels=("host",))
        assert g.labels(host="2").value == 1
        assert g.labels(host="0").value == 0
        # below the absolute floor the same ratio stays quiet (tiny fleet)
        for h, rss in ((0, 10 * mib), (1, 10 * mib), (2, 20 * mib)):
            HostBeacon(tmp_path, host=h).write(step=11, rss_bytes=rss, now=T0 + 2)
        assert agg.scan(now=T0 + 3)["mem_outliers"] == []

    def test_fleet_doctor_reports_memory_outlier(self, tmp_path, capsys):
        mib = 1024 * 1024
        fleet = tmp_path / "fleet"
        for h, rss in ((0, 1000 * mib), (1, 1000 * mib), (2, 2000 * mib)):
            HostBeacon(fleet, host=h).write(step=10, rss_bytes=rss, now=T0)
        import tools.fleet_doctor as doctor

        assert doctor.main([str(tmp_path)]) == 0
        report = capsys.readouterr().out
        assert "memory outlier: **host 2**" in report
        assert "2000 MiB" in report and "1000 MiB" in report
        assert "⚠ outlier" in report
