"""Elastic fleet training: hang watchdog, supervised restart, and
resize-consistent resume.

Everything here except the subprocess chaos test runs without processes or
threads: the watchdog exposes ``check(now)`` for fake-clock driving, the
supervisor takes injectable ``clock``/``sleep_fn``/``launch``, and the
resize assignment is a pure function. The ``slow``-marked chaos test is the
real thing — a 2-process gloo fleet under ``--elastic 2``, one host
SIGKILLed, supervisor restarts at world 1 and rejoins at world 2.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.data.resize import (
    ShardLedger,
    epoch_shard_order,
    merge_shard_states,
    resize_assignment,
)
from jumbo_mae_tpu_tpu.obs import hangwatch as hw_mod
from jumbo_mae_tpu_tpu.obs.hangwatch import HangWatchdog
from jumbo_mae_tpu_tpu.train.elastic import ElasticSupervisor
from jumbo_mae_tpu_tpu.train.engine import (
    EXIT_ELASTIC,
    EXIT_FATAL,
    EXIT_HANG,
    EXIT_OK,
    exit_code_for,
)

REPO = Path(__file__).resolve().parent.parent


# ------------------------------------------------------------ fakes


class FakeClock:
    """Monotonic clock advanced only by the supervisor's own sleeps."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def sleep(self, dt: float) -> None:
        self.t += dt


class FakeProc:
    """Popen surface: scripted self-death plus signal bookkeeping."""

    def __init__(self, clock, *, dies_at=None, rc=None, pid=1000):
        self._clock = clock
        self.dies_at = dies_at
        self._rc = rc
        self.returncode = None
        self.pid = pid
        self.signals: list = []

    def poll(self):
        if (
            self.returncode is None
            and self.dies_at is not None
            and self._clock() >= self.dies_at
        ):
            self.returncode = self._rc
        return self.returncode

    def send_signal(self, sig):
        self.signals.append(sig)
        if self.returncode is None:
            self.returncode = 0  # graceful: checkpoint + clean exit

    def kill(self):
        self.signals.append("KILL")
        if self.returncode is None:
            self.returncode = -9

    def wait(self, timeout=None):
        return self.returncode


class ScriptedLaunch:
    """launch(world, gen) factory that replays scripted fleets in order
    and records the (world, gen) of every call."""

    def __init__(self, fleets):
        self._fleets = list(fleets)
        self.calls: list[tuple[int, int]] = []

    def __call__(self, world: int, gen: int) -> list:
        self.calls.append((world, gen))
        fleet = self._fleets.pop(0)
        return fleet(world, gen) if callable(fleet) else fleet


class FakeJournal:
    def __init__(self):
        self.events: list[dict] = []

    def event(self, etype, **fields):
        self.events.append({"type": etype, **fields})

    def of(self, etype):
        return [e for e in self.events if e["type"] == etype]


def make_supervisor(tmp_path, launch, clock, **kw):
    kw.setdefault("world_size", 2)
    kw.setdefault("max_restarts", 3)
    kw.setdefault("backoff_s", 0.1)
    kw.setdefault("backoff_cap_s", 0.4)
    kw.setdefault("rejoin_after_s", 1e9)  # off unless a test opts in
    kw.setdefault("grace_s", 1.0)
    kw.setdefault("poll_s", 0.05)
    journal = kw.pop("journal", FakeJournal())
    sup = ElasticSupervisor(
        run_dir=tmp_path,
        launch=launch,
        journal=journal,
        clock=clock,
        sleep_fn=clock.sleep,
        **kw,
    )
    return sup, journal


# ----------------------------------------------------- exit-code protocol


class TestExitProtocol:
    def test_exit_codes_distinct(self):
        codes = {EXIT_OK, EXIT_ELASTIC, EXIT_FATAL, EXIT_HANG}
        assert len(codes) == 4 and EXIT_OK == 0

    def test_hangwatch_default_pinned_to_engine(self):
        # obs must not import train; this test is the cross-layer pin
        # keeping the two constants equal.
        assert hw_mod.DEFAULT_EXIT_CODE == EXIT_HANG
        src = (
            REPO / "jumbo_mae_tpu_tpu" / "obs" / "hangwatch.py"
        ).read_text()
        assert "from jumbo_mae_tpu_tpu.train" not in src

    def test_exit_code_for_reasons(self):
        for reason in ("completed", "preempted", "stopped"):
            assert exit_code_for(reason) == EXIT_OK
        assert exit_code_for("host_lost") == EXIT_ELASTIC
        assert exit_code_for("hang") == EXIT_HANG
        assert exit_code_for("diverged") == EXIT_FATAL
        assert exit_code_for("anything_else") == EXIT_FATAL


# ------------------------------------------------------------- hangwatch


class TestHangWatchdog:
    def wd(self, deadline=10.0, **kw):
        clock = FakeClock()
        kw.setdefault("exit_fn", lambda code: None)
        return HangWatchdog(deadline, clock=clock, **kw), clock

    def test_unarmed_never_fires(self):
        wd, clock = self.wd()
        clock.t = 1e6
        assert not wd.check() and not wd.fired

    def test_fires_after_deadline_and_latches(self):
        exits = []
        wd, clock = self.wd(exit_fn=exits.append)
        wd.arm()
        clock.t = 9.9
        assert not wd.check()
        clock.t = 10.0
        assert wd.check() and wd.fired
        assert exits == [EXIT_HANG]
        # latched: a racing second check must not re-fire
        clock.t = 50.0
        assert not wd.check()
        assert exits == [EXIT_HANG]

    def test_beat_resets_deadline(self):
        wd, clock = self.wd()
        wd.arm()
        for t in (6.0, 12.0, 18.0):
            clock.t = t
            wd.beat(step=int(t))
            assert not wd.check()
        clock.t = 28.5
        assert wd.check()

    def test_expected_window_suspends_and_restarts_clock(self):
        wd, clock = self.wd()
        wd.arm()
        with wd.expected("eval"):
            clock.t = 100.0  # way past the deadline, but inside the window
            assert not wd.check()
        # the window close restarted the clock: no instant fire...
        assert not wd.check()
        clock.t = 109.0
        assert not wd.check()
        # ...but the deadline is live again afterwards
        clock.t = 110.0
        assert wd.check()

    def test_expected_is_reentrant(self):
        wd, clock = self.wd()
        wd.arm()
        with wd.expected("outer"):
            with wd.expected("inner"):
                clock.t = 99.0
            clock.t = 199.0  # inner closed; outer still open
            assert not wd.check()
        clock.t = 208.0
        assert not wd.check()
        clock.t = 209.0
        assert wd.check()

    def test_on_fire_info_and_callback_exceptions_swallowed(self):
        infos, exits = [], []
        wd, clock = self.wd(exit_fn=exits.append)

        @wd.on_fire
        def boom(info):
            infos.append(info)
            raise RuntimeError("must not block the exit")

        wd.arm()
        wd.beat(step=7)
        clock.t = 25.0
        assert wd.check()
        assert exits == [EXIT_HANG]
        (info,) = infos
        assert info["step"] == 7 and info["deadline_s"] == 10.0
        assert info["stalled_s"] == pytest.approx(25.0)

    def test_drain_runs_before_exit_and_is_bounded(self):
        order = []
        wd, clock = self.wd(
            drain=lambda: order.append("drain"),
            exit_fn=lambda code: order.append(("exit", code)),
        )
        wd.arm()
        clock.t = 11.0
        assert wd.check()
        assert order == ["drain", ("exit", EXIT_HANG)]

        # a wedged drain cannot turn the watchdog into a hang
        order2 = []
        wd2, clock2 = self.wd(
            drain=lambda: time.sleep(60),
            drain_timeout_s=0.1,
            exit_fn=lambda code: order2.append(("exit", code)),
        )
        wd2.arm()
        clock2.t = 11.0
        t0 = time.monotonic()
        assert wd2.check()
        assert time.monotonic() - t0 < 5.0
        assert order2 == [("exit", EXIT_HANG)]

    def test_disarm_stops_enforcement(self):
        wd, clock = self.wd()
        wd.arm()
        wd.disarm()
        clock.t = 1e6
        assert not wd.check()

    def test_custom_exit_code(self):
        exits = []
        wd, clock = self.wd(exit_code=97, exit_fn=exits.append)
        wd.arm()
        clock.t = 11.0
        wd.check()
        assert exits == [97]


# ------------------------------------------------- resize pure functions


def _order(n=11, seed=3, epoch=0):
    return epoch_shard_order(
        [f"shard-{i:04d}.tar" for i in range(n)], seed=seed, epoch=epoch
    )


class TestResizeAssignment:
    def test_epoch_order_deterministic_and_epoch_varying(self):
        a, b = _order(epoch=1), _order(epoch=1)
        assert a == b and sorted(a) == sorted(_order(epoch=2))
        assert a != _order(epoch=2)  # different epoch, different order

    @pytest.mark.parametrize("world", [1, 2, 3, 5])
    def test_partition_disjoint_and_exhaustive(self, world):
        order = _order()
        consumed = {0, 4, 7}
        got = [
            resize_assignment(
                order, consumed, world_size=world, process_id=p
            )
            for p in range(world)
        ]
        flat = list(itertools.chain.from_iterable(got))
        assert len(flat) == len(set(i for i, _ in flat))  # disjoint
        assert {i for i, _ in flat} == set(range(len(order))) - consumed
        for i, url in flat:
            assert order[i] == url

    def test_worker_substriping_partitions_the_process_slice(self):
        order = _order()
        whole = resize_assignment(order, {1}, world_size=2, process_id=0)
        parts = [
            resize_assignment(
                order, {1}, world_size=2, process_id=0,
                worker_index=w, worker_count=3,
            )
            for w in range(3)
        ]
        assert sorted(itertools.chain.from_iterable(parts)) == sorted(whole)

    def test_conservation_across_resize(self):
        # ISSUE acceptance: consumed-before + assigned-after covers every
        # shard of the epoch exactly once, for any old/new world pair.
        order = _order(n=13)
        consumed = {0, 2, 5, 12}
        for new_world in (1, 2, 4):
            after = set()
            for p in range(new_world):
                after |= {
                    i
                    for i, _ in resize_assignment(
                        order, consumed, world_size=new_world, process_id=p
                    )
                }
            assert consumed | after == set(range(13))
            assert consumed & after == set()

    def test_bad_inputs_raise(self):
        order = _order()
        with pytest.raises(ValueError):
            resize_assignment(order, set(), world_size=2, process_id=2)
        with pytest.raises(ValueError):
            resize_assignment(
                order, set(), world_size=1, process_id=0,
                worker_index=1, worker_count=1,
            )
        with pytest.raises(ValueError, match="out of range"):
            resize_assignment(order, {len(order)}, world_size=1, process_id=0)

    def test_all_consumed_yields_empty(self):
        order = _order(n=4)
        assert (
            resize_assignment(order, {0, 1, 2, 3}, world_size=2, process_id=0)
            == []
        )


class TestShardLedger:
    def test_promotes_only_when_reads_done_and_yielded(self):
        led = ShardLedger()
        for _ in range(3):
            led.note_read(0, 5)
        led.note_yield(0, 5)
        led.note_yield(0, 5)
        assert led.consumed == {}  # reads not done
        led.note_read_done(0, 5)
        assert led.consumed == {}  # one sample still in the buffer
        led.note_yield(0, 5)
        assert led.consumed == {0: [5]}

    def test_empty_shard_promotes_on_read_done(self):
        led = ShardLedger()
        led.note_read_done(1, 9)  # quarantined/empty: zero samples
        assert led.consumed == {1: [9]}

    def test_snapshot_shape_and_merge(self):
        a = ShardLedger()
        a.note_read_done(0, 1)
        a.note_read_done(1, 0)
        b = ShardLedger()
        b.note_read_done(0, 2)
        snap = a.snapshot()
        assert snap == {"epochs": {"0": [1], "1": [0]}}
        merged = merge_shard_states([snap, b.snapshot(), None, {}])
        assert merged == {0: {1, 2}, 1: {0}}

    def test_preconsumed_seed_makes_snapshots_cumulative(self):
        # a resized resume seeds the new generation's ledger with the
        # merged set it subtracted — snapshots must cover BOTH
        led = ShardLedger(preconsumed={"epochs": {"0": [3, 1], "2": [0]}})
        assert led.snapshot() == {"epochs": {"0": [1, 3], "2": [0]}}
        led.note_read_done(0, 5)
        assert led.snapshot()["epochs"]["0"] == [1, 3, 5]
        # re-promoting a seeded shard must not duplicate it
        led.note_read_done(0, 3)
        assert led.snapshot()["epochs"]["0"] == [1, 3, 5]

    def test_second_resize_conserves_with_seeded_ledger(self):
        # the double-resize regime: gen0 (world 2) consumes, resize to
        # world 1 with a SEEDED ledger, gen1 consumes more, resize to
        # world 2 off gen1's shard_cursor alone — conservation must hold
        # because gen1's snapshots are cumulative across generations.
        order = _order(n=12)
        gen0 = merge_shard_states(
            [{"epochs": {"0": [0, 2]}}, {"epochs": {"0": [1]}}]
        )[0]
        led = ShardLedger(preconsumed={"epochs": {"0": sorted(gen0)}})
        pairs = resize_assignment(order, gen0, world_size=1, process_id=0)
        for g, _ in pairs[:2]:  # gen1 consumes two shards of its stripe
            led.note_read_done(0, g)
        consumed = merge_shard_states([led.snapshot()])[0]
        assert consumed == gen0 | {g for g, _ in pairs[:2]}
        after = set()
        for p in range(2):
            after |= {
                i
                for i, _ in resize_assignment(
                    order, consumed, world_size=2, process_id=p
                )
            }
        assert consumed | after == set(range(12))
        assert consumed & after == set()


class TestLoaderOverrideResume:
    """The loader-side contracts a mid-override restart depends on: the
    snapshot carries ``override_epoch`` while any stream is inside the
    override stripe (so a SAME-world restart re-derives the assignment
    from the journal instead of replaying offsets against the topology
    stripe), and ``shard_preconsumed`` seeds the ledger so shard cursors
    are cumulative across generations."""

    def _cfg(self, tmp_path):
        from jumbo_mae_tpu_tpu.data import DataConfig
        from jumbo_mae_tpu_tpu.data.toy import write_toy_shards

        urls = write_toy_shards(
            tmp_path / "toy", n_train=32, n_val=8, shard_size=8, image_size=16
        )
        return DataConfig(
            train_shards=urls["train"],
            image_size=16,
            workers=0,
            shuffle_buffer=4,
            seed=7,
        )

    def test_marker_present_inside_override_epoch_then_drops(self, tmp_path):
        from jumbo_mae_tpu_tpu.data import TrainLoader

        cfg = self._cfg(tmp_path)
        order = epoch_shard_order(cfg.train_shards, seed=cfg.seed, epoch=0)
        consumed = {0}
        override = resize_assignment(
            order, consumed, world_size=1, process_id=0
        )
        loader = TrainLoader(
            cfg,
            batch_size=8,
            epoch_shard_override=override,
            shard_preconsumed={"epochs": {"0": sorted(consumed)}},
        )
        try:
            next(loader)
            snap = loader.snapshot()
            # offsets were measured on the override stripe: marker present
            assert snap["override_epoch"] == 0
            # seeded ledger: gen0's consumed shard rides every cursor
            shards = loader.shard_snapshot()
            assert 0 in {int(i) for i in shards["epochs"]["0"]}
            # override epoch has 3 shards x 8 samples = 24 samples; after
            # batch 4 the stream is in epoch 1 (normal stripe) and the
            # sample cursor is trustworthy again
            for _ in range(2):
                next(loader)
            assert loader.snapshot()["override_epoch"] == 0
            next(loader)
            assert "override_epoch" not in loader.snapshot()
        finally:
            loader.close()

    def test_plain_loader_has_no_marker(self, tmp_path):
        from jumbo_mae_tpu_tpu.data import TrainLoader

        loader = TrainLoader(self._cfg(tmp_path), batch_size=8)
        try:
            next(loader)
            assert "override_epoch" not in loader.snapshot()
        finally:
            loader.close()


# ------------------------------------------------- supervisor state machine


class TestSupervisorClassify:
    def test_priority_fatal_over_signal_over_hang_over_elastic(self):
        c = ElasticSupervisor._classify
        assert c({0: -9, 1: EXIT_FATAL}) == ("fatal", [1])
        assert c({0: -9, 1: EXIT_HANG}) == ("host_dead", [0])
        assert c({0: EXIT_HANG, 1: EXIT_ELASTIC}) == ("hang", [0])
        assert c({0: EXIT_ELASTIC}) == ("host_lost", [0])
        assert c({0: 1, 1: 2}) == ("crash", [0, 1])


class TestSupervisorLoop:
    def test_clean_completion_returns_zero(self, tmp_path):
        clock = FakeClock()
        launch = ScriptedLaunch(
            [lambda w, g: [FakeProc(clock, dies_at=0.2, rc=0) for _ in range(w)]]
        )
        sup, journal = make_supervisor(tmp_path, launch, clock)
        assert sup.run() == 0
        assert launch.calls == [(2, 0)]
        assert sup.restarts_used == 0
        assert journal.of("elastic_restart") == []

    def test_sigkill_downsizes_and_drains_survivor(self, tmp_path):
        clock = FakeClock()
        survivor = FakeProc(clock, pid=11)
        fleets = [
            lambda w, g: [survivor, FakeProc(clock, dies_at=0.0, rc=-9)],
            lambda w, g: [FakeProc(clock, dies_at=clock() + 0.1, rc=0)],
        ]
        sup, journal = make_supervisor(tmp_path, ScriptedLaunch(fleets), clock)
        launch = sup._launch
        assert sup.run() == 0
        assert launch.calls == [(2, 0), (1, 1)]
        # the survivor was torn down (SIGTERM), not classified as failed
        assert signal.SIGTERM in survivor.signals
        (ev,) = journal.of("elastic_restart")
        assert ev["reason"] == "host_dead"
        assert ev["failed_hosts"] == [1]
        assert ev["exit_codes"] == {"1": -9}
        assert (ev["old_world"], ev["new_world"]) == (2, 1)
        assert ev["generation"] == 1 and ev["restarts_used"] == 1

    def test_crash_restarts_at_same_world(self, tmp_path):
        clock = FakeClock()
        fleets = [
            lambda w, g: [
                FakeProc(clock, dies_at=0.0, rc=1),
                FakeProc(clock),
            ],
            lambda w, g: [
                FakeProc(clock, dies_at=clock() + 0.1, rc=0) for _ in range(w)
            ],
        ]
        sup, journal = make_supervisor(tmp_path, ScriptedLaunch(fleets), clock)
        assert sup.run() == 0
        assert sup._launch.calls == [(2, 0), (2, 1)]  # no downsize for crash
        (ev,) = journal.of("elastic_restart")
        assert ev["reason"] == "crash" and ev["new_world"] == 2

    def test_fatal_exit_never_retried(self, tmp_path):
        clock = FakeClock()
        fleets = [
            lambda w, g: [
                FakeProc(clock, dies_at=0.0, rc=EXIT_FATAL),
                FakeProc(clock),
            ],
        ]
        sup, journal = make_supervisor(tmp_path, ScriptedLaunch(fleets), clock)
        assert sup.run() == EXIT_FATAL
        assert sup._launch.calls == [(2, 0)]  # no relaunch
        (ev,) = journal.of("elastic_exhausted")
        assert "not retryable" in ev["verdict"]
        assert journal.of("elastic_restart") == []

    def test_restart_budget_exhaustion(self, tmp_path):
        clock = FakeClock()
        crash = lambda w, g: [  # noqa: E731
            FakeProc(clock, dies_at=clock(), rc=1) for _ in range(w)
        ]
        sup, journal = make_supervisor(
            tmp_path, ScriptedLaunch([crash, crash]), clock, max_restarts=1
        )
        assert sup.run() == EXIT_FATAL
        assert len(sup._launch.calls) == 2  # initial + the one budgeted retry
        (ev,) = journal.of("elastic_exhausted")
        assert "budget exhausted" in ev["verdict"]
        assert ev["restarts_used"] == 1

    def test_backoff_doubles_to_cap(self, tmp_path):
        clock = FakeClock()
        crash = lambda w, g: [  # noqa: E731
            FakeProc(clock, dies_at=clock(), rc=1) for _ in range(w)
        ]
        sup, journal = make_supervisor(
            tmp_path,
            ScriptedLaunch([crash] * 5),
            clock,
            max_restarts=4,
            backoff_s=0.1,
            backoff_cap_s=0.4,
        )
        sup.run()
        backoffs = [e["backoff_s"] for e in journal.of("elastic_restart")]
        # journaled value is the delay actually slept before each relaunch
        assert backoffs == [0.1, 0.2, 0.4, 0.4]

    def test_host_lost_downsizes_to_detector_count(self, tmp_path):
        # world 3, one peer's beacon goes stale: the TWO healthy detectors
        # exit EXIT_ELASTIC. The next world is the detector count (2), not
        # world - len(detectors) = 1, which would idle a healthy host.
        clock = FakeClock()
        lost_peer = FakeProc(clock, pid=40)  # alive but its beacon is stale
        fleets = [
            lambda w, g: [
                FakeProc(clock, dies_at=0.0, rc=EXIT_ELASTIC),
                FakeProc(clock, dies_at=0.0, rc=EXIT_ELASTIC),
                lost_peer,
            ],
            lambda w, g: [
                FakeProc(clock, dies_at=clock() + 0.1, rc=0) for _ in range(w)
            ],
        ]
        sup, journal = make_supervisor(
            tmp_path, ScriptedLaunch(fleets), clock, world_size=3
        )
        assert sup.run() == 0
        assert sup._launch.calls == [(3, 0), (2, 1)]
        # the still-running lost peer was torn down with the generation
        assert lost_peer.signals
        (ev,) = journal.of("elastic_restart")
        assert ev["reason"] == "host_lost"
        assert (ev["old_world"], ev["new_world"]) == (3, 2)

    def test_downsize_clamped_to_valid_world(self, tmp_path):
        # batch size divisible by 4 and 2 but not 3: a 4->3 downsize must
        # clamp to 2 instead of relaunching children that all die on the
        # same config error until the budget is exhausted
        clock = FakeClock()
        fleets = [
            lambda w, g: [FakeProc(clock, dies_at=0.0, rc=-9)]
            + [FakeProc(clock) for _ in range(3)],
            lambda w, g: [
                FakeProc(clock, dies_at=clock() + 0.1, rc=0) for _ in range(w)
            ],
        ]
        sup, journal = make_supervisor(
            tmp_path,
            ScriptedLaunch(fleets),
            clock,
            world_size=4,
            world_ok=lambda w: 8 % w == 0,
        )
        assert sup.run() == 0
        assert sup._launch.calls == [(4, 0), (2, 1)]
        (ev,) = journal.of("elastic_restart")
        assert ev["new_world"] == 2 and ev["requested_world"] == 3

    def test_rejoin_after_timer(self, tmp_path):
        clock = FakeClock()
        healthy = FakeProc(clock, pid=20)
        fleets = [
            lambda w, g: [FakeProc(clock), FakeProc(clock, dies_at=0.0, rc=-9)],
            lambda w, g: [healthy],
            lambda w, g: [
                FakeProc(clock, dies_at=clock() + 0.1, rc=0) for _ in range(w)
            ],
        ]
        sup, journal = make_supervisor(
            tmp_path, ScriptedLaunch(fleets), clock, rejoin_after_s=2.0
        )
        assert sup.run() == 0
        assert [w for w, _ in sup._launch.calls] == [2, 1, 2]
        # the down-sized generation was drained gracefully for the rejoin
        assert signal.SIGTERM in healthy.signals
        (ev,) = journal.of("elastic_rejoin")
        assert (ev["old_world"], ev["new_world"]) == (1, 2)
        assert ev["generation"] == 2

    def test_wedged_host_killed_and_restarted(self, tmp_path):
        clock = FakeClock()
        fleet = tmp_path / "fleet"
        fleet.mkdir()
        wedged = FakeProc(clock, pid=30)

        def gen0(w, g):
            # beacon written "long ago" relative to wall time: the host
            # heartbeated once and then stopped stepping
            (fleet / "host-0.json").write_text(
                json.dumps({"host": 0, "heartbeat": time.time() - 3600})
            )
            return [wedged]

        fleets = [gen0, lambda w, g: [FakeProc(clock, dies_at=clock(), rc=0)]]
        sup, journal = make_supervisor(
            tmp_path,
            ScriptedLaunch(fleets),
            clock,
            world_size=1,
            wedge_after_s=1.0,
        )
        assert sup.run() == 0
        assert "KILL" in wedged.signals
        (ev,) = journal.of("elastic_restart")
        assert ev["reason"] == "wedged" and ev["failed_hosts"] == [0]
        # stale beacons were cleaned before each relaunch
        assert list(fleet.glob("host-*.json")) == []

    def test_request_stop_drains_and_exits_zero(self, tmp_path):
        clock = FakeClock()
        proc = FakeProc(clock)
        sup, journal = make_supervisor(
            tmp_path, ScriptedLaunch([[proc]]), clock, world_size=1
        )
        sup.request_stop()
        assert sup.run() == 0
        assert signal.SIGTERM in proc.signals
        assert journal.of("shutdown")[0]["reason"] == "supervisor_stop"

    def test_teardown_escalates_to_kill(self, tmp_path):
        clock = FakeClock()

        class Stubborn(FakeProc):
            def send_signal(self, sig):
                self.signals.append(sig)  # ignores SIGTERM

        proc = Stubborn(clock)
        sup, _ = make_supervisor(
            tmp_path, ScriptedLaunch([[proc]]), clock, world_size=1, grace_s=0.2
        )
        sup._teardown([proc])
        assert signal.SIGTERM in proc.signals and "KILL" in proc.signals
        assert proc.returncode == -9


# ---------------------------------------------- checkpoint restore fallback


class TestRestoreFallback:
    def _ckpt(self, tmp_path, keep=8):
        from jumbo_mae_tpu_tpu.train.checkpoint import (
            CheckpointConfig,
            Checkpointer,
        )

        return Checkpointer(
            CheckpointConfig(
                str(tmp_path), async_save=False, max_keep_last=keep
            )
        )

    def _state(self, x: float):
        import jax.numpy as jnp

        return {"w": jnp.full((4,), x, jnp.float32)}

    def test_walks_back_past_bad_step(self, tmp_path):
        from jumbo_mae_tpu_tpu import faults

        ckpt = self._ckpt(tmp_path)
        for s in (2, 4, 6):
            ckpt.save(s, self._state(float(s)))
        hops = []
        # first ckpt.load attempt (step 6) raises; the walk lands on 4
        faults.install_plan("ckpt.load:raise@n<1")
        try:
            state, extra = ckpt.restore(
                self._state(0.0),
                fallback_steps=2,
                on_fallback=lambda frm, to, err: hops.append((frm, to, err)),
            )
        finally:
            faults.clear_plan()
        np.testing.assert_allclose(np.asarray(state["w"]), 4.0)
        assert [(f, t) for f, t, _ in hops] == [(6, 4)]
        assert hops[0][2] is not None

    def test_walk_is_bounded(self, tmp_path):
        from jumbo_mae_tpu_tpu import faults

        ckpt = self._ckpt(tmp_path)
        for s in (2, 4, 6):
            ckpt.save(s, self._state(float(s)))
        # every attempt fails: the bounded walk (6 -> 4) must still raise
        faults.install_plan("ckpt.load:raise")
        try:
            with pytest.raises(Exception):
                ckpt.restore(self._state(0.0), fallback_steps=1)
        finally:
            faults.clear_plan()

    def test_no_fallback_by_default(self, tmp_path):
        from jumbo_mae_tpu_tpu import faults

        ckpt = self._ckpt(tmp_path)
        ckpt.save(2, self._state(2.0))
        ckpt.save(4, self._state(4.0))
        faults.install_plan("ckpt.load:raise@n<1")
        try:
            with pytest.raises(Exception):
                ckpt.restore(self._state(0.0))
        finally:
            faults.clear_plan()


# ------------------------------------------------------ subprocess chaos


def _train_cmd(*extra: str) -> list[str]:
    return [
        sys.executable,
        "-m",
        "jumbo_mae_tpu_tpu.cli.train",
        "--config",
        str(REPO / "recipes" / "smoke_cpu.yaml"),
        *extra,
    ]


def _cpu_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _journal_events(run_dir: Path) -> list[dict]:
    from jumbo_mae_tpu_tpu.obs.journal import read_merged_journal

    try:
        return read_merged_journal(run_dir)
    except Exception:
        return []


@pytest.mark.slow
def test_hangwatch_converts_wedge_to_exit_hang(tmp_path):
    """fleet.wedge delays step 5 past the deadline; the watchdog journals
    hang_detected, drains, and dies EXIT_HANG — the wedge never outlives
    the deadline by more than the poll+drain slack."""
    proc = subprocess.run(
        _train_cmd(
            "--set",
            f"run.output_dir={tmp_path}",
            "run.name=wedge",
            "run.training_steps=8",
            "optim.training_steps=8",
            "optim.warmup_steps=1",
            "run.log_interval=2",
            "run.eval_interval=100",
            "run.sanity_eval=false",
            "run.hangwatch_deadline_s=4",
            "run.faults=fleet.wedge:delay(300)@key~5,n<1",
        ),
        env=_cpu_env(),
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == EXIT_HANG, proc.stdout[-2000:] + proc.stderr[-2000:]
    evs = _journal_events(tmp_path / "wedge")
    hangs = [e for e in evs if e.get("type") == "hang_detected"]
    assert hangs, "hang_detected not journaled"
    assert hangs[0]["stalled_s"] >= 4.0
    assert "HANG" in proc.stdout


@pytest.mark.slow
def test_supervisor_sigkill_restart_and_rejoin(tmp_path):
    """The full elastic loop, live: 2-process gloo fleet under --elastic 2,
    host 1 SIGKILLed after the first committed checkpoint → supervisor
    restarts at world 1 (resize-consistent resume from the world-2
    checkpoint) → rejoins at world 2 → run completes, supervisor exits 0."""
    from jumbo_mae_tpu_tpu.data.toy import write_toy_shards
    from jumbo_mae_tpu_tpu.obs.fleet import read_beacons

    urls = write_toy_shards(
        tmp_path / "toy", n_train=256, n_val=32, shard_size=32, image_size=32
    )
    run_dir = tmp_path / "runs" / "el"
    # children inherit the supervisor's stdout — log to a file, not a pipe
    # the test never drains (a full pipe buffer would wedge the fleet)
    sup_log = tmp_path / "sup.log"
    log_f = sup_log.open("w")
    sup = subprocess.Popen(
        _train_cmd(
            "--elastic",
            "2",
            "--set",
            f"run.output_dir={tmp_path / 'runs'}",
            "run.name=el",
            "run.training_steps=24",
            "optim.training_steps=24",
            "optim.warmup_steps=1",
            "run.log_interval=2",
            "run.eval_interval=8",
            "run.sanity_eval=false",
            "run.synthetic_data=false",
            f"data.train_shards={urls['train']}",
            "data.dataset_size=256",
            "data.shuffle_buffer=16",
            "data.workers=0",
            "mesh.data=-1",
            "mesh.fsdp=1",
            # generous dead/hang thresholds: on a loaded 1-CPU runner a
            # healthy host's beacon can go stale for >10s across the
            # post-rejoin recompile, and a false host_lost strands the
            # survivor in gloo finalize for its full 300s timeout. The
            # SIGKILL itself is seen immediately via the child's rc, so
            # none of these slow the restart under test.
            "run.fleet_dead_after_s=30",
            "run.hangwatch_deadline_s=90",
            "run.elastic_wedge_after_s=60",
            "run.elastic_rejoin_after_s=15",
            "run.elastic_backoff_s=0.5",
        ),
        env=_cpu_env(),
        cwd=REPO,
        stdout=log_f,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        # kill host 1 only once a checkpoint is COMMITTED — that is the
        # restart's resume point; killing mid-compile just restarts fresh
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if any(
                e.get("type") == "checkpoint_save"
                for e in _journal_events(run_dir)
            ) and 1 in read_beacons(run_dir / "fleet"):
                break
            assert sup.poll() is None, sup_log.read_text()[-3000:]
            time.sleep(2)
        else:
            pytest.fail("no checkpoint_save journaled within 240s")
        pid = read_beacons(run_dir / "fleet")[1]["pid"]
        os.kill(pid, signal.SIGKILL)
        sup.wait(timeout=600)
    except BaseException:
        sup.kill()
        raise
    finally:
        log_f.close()
    assert sup.returncode == 0, sup_log.read_text()[-3000:]

    evs = _journal_events(run_dir)
    restarts = [e for e in evs if e.get("type") == "elastic_restart"]
    assert restarts and restarts[0]["reason"] == "host_dead"
    assert restarts[0]["failed_hosts"] == [1]
    assert (restarts[0]["old_world"], restarts[0]["new_world"]) == (2, 1)
    # the down-sized generation resumed the world-2 checkpoint via the
    # journal cursor, with exact shard accounting
    resizes = [e for e in evs if e.get("type") == "elastic_resize"]
    assert resizes, "no elastic_resize journaled on the world-2->1 resume"
    assert 0 <= resizes[0]["shards_remaining"] <= resizes[0]["shards_total"]
    rejoins = [e for e in evs if e.get("type") == "elastic_rejoin"]
    assert rejoins and rejoins[0]["new_world"] == 2

    # the offline doctor names the dead host and the supervisor's response
    doc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "fleet_doctor.py"), str(run_dir)],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=60,
    )
    assert doc.returncode == 0
    assert "elastic_restart" in doc.stdout and "host_dead" in doc.stdout
