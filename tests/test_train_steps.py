"""Train-step tests on the virtual 8-device CPU mesh.

The key invariants (SURVEY §4 implication list): a DP/FSDP-sharded step must
equal the single-device step to numerical tolerance; grad-accum over k micro
batches must equal one big batch; eval aggregation must respect the valid
mask.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.utils import compat
from jumbo_mae_tpu_tpu.models import (
    ClassificationModel,
    DecoderConfig,
    MAEPretrainModel,
    preset,
)
from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
from jumbo_mae_tpu_tpu.train import (
    OptimConfig,
    create_sharded_state,
    make_eval_step,
    make_optimizer,
    make_train_step,
)

TINY = preset("vit_t16", image_size=32, patch_size=8, dtype="float32")
TINY_DEC = DecoderConfig(layers=1, dim=32, heads=2, dtype="float32")
OPT = OptimConfig(
    name="adamw",
    learning_rate=1e-3,
    lr_scaling="none",
    warmup_steps=2,
    training_steps=20,
    weight_decay=0.05,
)


def pretrain_module():
    return MAEPretrainModel(TINY.replace(mask_ratio=0.75, labels=None), TINY_DEC)


def classify_module(**kw):
    return ClassificationModel(TINY.replace(labels=10), **kw)


def batch_of(n, seed=0, labels=None):
    rng = np.random.RandomState(seed)
    b = {"images": rng.randint(0, 256, (n, 32, 32, 3)).astype(np.uint8)}
    if labels is not None:
        b["labels"] = np.asarray(labels, np.int32)
    return jax.tree_util.tree_map(jnp.asarray, b)


def build(mesh_cfg, module, mode, grad_accum=1, batch=None, opt=OPT):
    mesh = create_mesh(mesh_cfg)
    tx = make_optimizer(opt, global_batch_size=256)
    example = (
        batch
        if grad_accum == 1
        else jax.tree_util.tree_map(lambda x: x[0], batch)
    )
    state, sharding = create_sharded_state(
        module, tx, example, mesh, mode=mode, init_seed=0, rng_seed=0
    )
    step = make_train_step(mesh, sharding, mode=mode, grad_accum=grad_accum)
    return mesh, state, sharding, step


class TestMeshPlanning:
    def test_hybrid_mesh_plan_splits_data_axis_over_dcn(self):
        """Multislice planning: only the data axis spans slices; fsdp/
        tensor/seq stay intra-slice on ICI."""
        from jumbo_mae_tpu_tpu.parallel.mesh import plan_hybrid_mesh

        per_slice, dcn = plan_hybrid_mesh((32, 4, 1, 1), n_slices=4)
        assert per_slice == (8, 4, 1, 1)
        assert dcn == (4, 1, 1, 1)
        # elementwise product reconstructs the global mesh shape
        assert tuple(a * b for a, b in zip(per_slice, dcn)) == (32, 4, 1, 1)

    def test_hybrid_mesh_plan_rejects_indivisible_data_axis(self):
        from jumbo_mae_tpu_tpu.parallel.mesh import plan_hybrid_mesh

        with pytest.raises(ValueError, match="data axis"):
            plan_hybrid_mesh((6, 2, 1, 1), n_slices=4)

    def test_mesh_strategy_decision(self):
        """Hybrid only when slice-aligned; everything else falls back to a
        flat mesh (the pre-multislice behavior) so a default config never
        hard-fails on multislice hardware."""
        from jumbo_mae_tpu_tpu.parallel.mesh import mesh_strategy

        two_slices = [0] * 4 + [1] * 4
        assert mesh_strategy([0] * 8, (1, 8, 1, 1)) == "flat"  # single slice
        assert mesh_strategy(two_slices, (2, 4, 1, 1)) == "hybrid"
        # default config (data=1) on 2 slices: flat, not an error
        assert mesh_strategy(two_slices, (1, 8, 1, 1)) == "flat"
        # truncation straddling a slice boundary: flat
        assert mesh_strategy([0, 0, 0, 0, 1, 1], (2, 3, 1, 1)) == "flat"


class TestPretrainStep:
    def test_loss_decreases(self):
        batch = batch_of(16)
        _, state, _, step = build(
            MeshConfig(data=1, fsdp=1, tensor=1, seq=1), pretrain_module(), "pretrain", batch=batch
        )
        losses = []
        for _ in range(8):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses

    @pytest.mark.slow  # heavy compile; full suite covers it
    def test_sharded_equals_single_device(self):
        batch = batch_of(16)
        _, s1, _, step1 = build(
            MeshConfig(data=1, fsdp=1), pretrain_module(), "pretrain", batch=batch
        )
        _, s8, _, step8 = build(
            MeshConfig(data=2, fsdp=4), pretrain_module(), "pretrain", batch=batch
        )
        for i in range(3):
            s1, m1 = step1(s1, batch)
            s8, m8 = step8(s8, batch)
            np.testing.assert_allclose(
                float(m1["loss"]), float(m8["loss"]), rtol=2e-5
            )
        # params agree after 3 steps (requires partitionable threefry —
        # compat.ensure_partitionable_rng — or the sharded init itself
        # draws different values on jax 0.4.x; measured drift with it on:
        # ~1e-7)
        p1 = jax.tree_util.tree_leaves(s1.params)
        p8 = jax.tree_util.tree_leaves(s8.params)
        for a, b in zip(p1, p8):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    @pytest.mark.slow  # heavy compile; full suite covers it
    def test_tensor_parallel_matches_single_device(self):
        # dp=2 × fsdp=2 × tp=2: heads and MLP hidden dims shard over
        # "tensor"; the step must still equal the single-device step.
        batch = batch_of(16)
        _, s1, _, step1 = build(
            MeshConfig(data=1, fsdp=1), pretrain_module(), "pretrain", batch=batch
        )
        _, s8, sh8, step8 = build(
            MeshConfig(data=2, fsdp=2, tensor=2), pretrain_module(), "pretrain",
            batch=batch,
        )
        specs = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda s: s.spec, sh8.params)
        )
        assert any("tensor" in str(spec) for spec in specs), specs
        for _ in range(3):
            s1, m1 = step1(s1, batch)
            s8, m8 = step8(s8, batch)
            np.testing.assert_allclose(
                float(m1["loss"]), float(m8["loss"]), rtol=2e-5
            )
        for a, b in zip(
            jax.tree_util.tree_leaves(s1.params),
            jax.tree_util.tree_leaves(s8.params),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)

    @pytest.mark.slow  # heavy compile; full suite covers it
    def test_seq_parallel_ring_matches_single_device(self):
        # Sequence parallelism: same model weights, attn_impl="ring" on a
        # (data=2, seq=4) mesh vs einsum on one device. Identical RNG streams
        # → identical masking → losses must agree.
        batch = batch_of(16)
        _, s1, _, step1 = build(
            MeshConfig(data=1, fsdp=1), pretrain_module(), "pretrain", batch=batch
        )
        ring_module = MAEPretrainModel(
            TINY.replace(mask_ratio=0.75, labels=None, attn_impl="ring"),
            TINY_DEC.replace(attn_impl="ring"),
        )
        ref_losses = []
        for _ in range(2):
            s1, m1 = step1(s1, batch)
            ref_losses.append(float(m1["loss"]))

        mesh = create_mesh(MeshConfig(data=2, fsdp=1, seq=4))
        tx = make_optimizer(OPT, global_batch_size=256)
        with compat.set_mesh(mesh):
            s_ring, sharding = create_sharded_state(
                ring_module, tx, batch, mesh, mode="pretrain", init_seed=0, rng_seed=0
            )
            step_ring = make_train_step(mesh, sharding, mode="pretrain")
            for want in ref_losses:
                s_ring, m_ring = step_ring(s_ring, batch)
                np.testing.assert_allclose(
                    float(m_ring["loss"]), want, rtol=1e-4
                )

    @pytest.mark.slow  # heavy compile; full suite covers it
    def test_all_axes_composed_matches_single_device(self):
        # fsdp=2 × tensor=2 × seq=2 on one mesh, ring attention active —
        # every implemented parallelism at once must still equal the
        # single-device step.
        batch = batch_of(16)
        _, s1, _, step1 = build(
            MeshConfig(data=1, fsdp=1), pretrain_module(), "pretrain", batch=batch
        )
        s1, m1 = step1(s1, batch)
        want = float(m1["loss"])

        module = MAEPretrainModel(
            TINY.replace(mask_ratio=0.75, labels=None, attn_impl="ring"),
            TINY_DEC.replace(attn_impl="ring"),
        )
        mesh = create_mesh(MeshConfig(data=1, fsdp=2, tensor=2, seq=2))
        tx = make_optimizer(OPT, global_batch_size=256)
        with compat.set_mesh(mesh):
            st, sharding = create_sharded_state(
                module, tx, batch, mesh, mode="pretrain", init_seed=0,
                rng_seed=0, min_shard_size=128,
            )
            specs = str(jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(lambda s: s.spec, sharding.params)
            ))
            assert "tensor" in specs and "fsdp" in specs, specs
            step = make_train_step(mesh, sharding, mode="pretrain")
            st, m = step(st, batch)
        np.testing.assert_allclose(float(m["loss"]), want, rtol=1e-4)

    def test_learning_rate_logged(self):
        batch = batch_of(8)
        _, state, _, step = build(
            MeshConfig(data=1, fsdp=1), pretrain_module(), "pretrain", batch=batch
        )
        state, metrics = step(state, batch)
        assert "learning_rate" in metrics
        assert 0 < float(metrics["learning_rate"]) <= 1e-3

    @pytest.mark.slow  # heavy compile; full suite covers it
    def test_grad_accum_matches_full_batch(self):
        full = batch_of(16, seed=3)
        split = jax.tree_util.tree_map(
            lambda x: x.reshape(2, 8, *x.shape[1:]), full
        )
        # disable schedule differences: fixed LR, plain sgd-like adamw
        opt = OPT
        _, s_full, _, step_full = build(
            MeshConfig(data=1, fsdp=1), pretrain_module(), "pretrain",
            batch=full, opt=opt,
        )
        _, s_acc, _, step_acc = build(
            MeshConfig(data=1, fsdp=1), pretrain_module(), "pretrain",
            grad_accum=2, batch=split, opt=opt,
        )
        # NOTE: not bitwise — the accum path draws different masking noise per
        # micro batch. Check both run and produce finite, comparable losses.
        s_full, m_full = step_full(s_full, full)
        s_acc, m_acc = step_acc(s_acc, split)
        assert np.isfinite(float(m_full["loss"]))
        assert np.isfinite(float(m_acc["loss"]))

    def test_rng_varies_by_step_and_micro(self):
        batch = batch_of(8)
        _, state, _, step = build(
            MeshConfig(data=1, fsdp=1), pretrain_module(), "pretrain", batch=batch
        )
        r0 = state.step_rngs(micro=0)
        r1 = state.step_rngs(micro=1)
        assert not np.array_equal(
            jax.random.key_data(r0["noise"]), jax.random.key_data(r1["noise"])
        )
        state2, _ = step(state, batch)
        r0b = state2.step_rngs(micro=0)
        assert not np.array_equal(
            jax.random.key_data(r0["noise"]), jax.random.key_data(r0b["noise"])
        )


class TestClassifyStep:
    def test_finetune_loss_decreases(self):
        batch = batch_of(16, labels=np.arange(16) % 10)
        module = classify_module(mixup_alpha=0.0, cutmix_alpha=0.0)
        _, state, _, step = build(
            MeshConfig(data=2, fsdp=4), module, "classify", batch=batch
        )
        losses = []
        for _ in range(10):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], losses

    def test_linear_probe_updates_only_head(self):
        cfg = TINY.replace(labels=10, linear_probing=True, batch_norm=True)
        module = ClassificationModel(cfg)
        batch = batch_of(16, labels=np.arange(16) % 10)
        _, state, _, step = build(
            MeshConfig(data=1, fsdp=1), module, "classify", batch=batch
        )
        before = jax.tree_util.tree_map(np.asarray, state.params)
        state2, _ = step(state, batch)
        after = jax.tree_util.tree_map(np.asarray, state2.params)

        flat_b = jax.tree_util.tree_leaves_with_path(before)
        flat_a = dict(jax.tree_util.tree_leaves_with_path(after))
        changed, frozen_ok = [], True
        for path, b in flat_b:
            a = flat_a[path]
            name = jax.tree_util.keystr(path)
            if "head" in name:
                if not np.allclose(a, b):
                    changed.append(name)
            else:
                frozen_ok &= np.allclose(a, b)
        assert changed, "head params did not move"
        assert frozen_ok, "trunk params moved under linear probing"

    def test_batch_stats_updated(self):
        cfg = TINY.replace(labels=10, linear_probing=True, batch_norm=True)
        module = ClassificationModel(cfg)
        batch = batch_of(16, labels=np.arange(16) % 10)
        _, state, _, step = build(
            MeshConfig(data=1, fsdp=1), module, "classify", batch=batch
        )
        assert state.batch_stats is not None
        before = jax.tree_util.tree_map(np.asarray, state.batch_stats)
        state2, _ = step(state, batch)
        after = state2.batch_stats
        diffs = jax.tree_util.tree_map(
            lambda a, b: float(np.abs(np.asarray(a) - b).sum()), after, before
        )
        assert sum(jax.tree_util.tree_leaves(diffs)) > 0


class TestEvalStep:
    def test_classify_eval_respects_valid_mask(self):
        batch = batch_of(16, labels=np.arange(16) % 10)
        module = classify_module()
        mesh, state, sharding, _ = build(
            MeshConfig(data=2, fsdp=4), module, "classify", batch=batch
        )
        eval_step = make_eval_step(mesh, sharding, mode="classify")

        full = dict(batch, valid=jnp.ones(16, bool))
        out_full = eval_step(state, full)
        assert float(out_full["num_samples"]) == 16

        # pad last 8: metrics must equal the first-8-only aggregation
        padded = {
            "images": batch["images"],
            "labels": batch["labels"].at[8:].set(-1),
            "valid": jnp.arange(16) < 8,
        }
        out_padded = eval_step(state, padded)
        assert float(out_padded["num_samples"]) == 8

        first8 = {
            "images": batch["images"][:8],
            "labels": batch["labels"][:8],
            "valid": jnp.ones(8, bool),
        }
        out_first8 = eval_step(state, first8)
        np.testing.assert_allclose(
            float(out_padded["loss"]), float(out_first8["loss"]), rtol=1e-5
        )

    def test_pretrain_eval_sums_per_sample(self):
        batch = batch_of(16)
        module = pretrain_module()
        mesh, state, sharding, _ = build(
            MeshConfig(data=1, fsdp=1), module, "pretrain", batch=batch
        )
        eval_step = make_eval_step(mesh, sharding, mode="pretrain")
        out = eval_step(state, batch)
        assert float(out["num_samples"]) == 16
        assert np.isfinite(float(out["loss"]))
        # deterministic given state: same batch → same metrics
        out2 = eval_step(state, batch)
        np.testing.assert_allclose(float(out["loss"]), float(out2["loss"]))

    def test_pretrain_eval_stream_pinned(self):
        """Consecutive evals of an UNCHANGED model must report identical
        val/loss — the eval mask RNG is a pure function of (state.rng,
        state.step, batch index), with no hidden counter (VERDICT weak #8:
        the reference's det=False eval re-drew masks every pass). A
        different batch index must still draw a different mask."""
        module = pretrain_module()
        mesh, state, sharding, _ = build(
            MeshConfig(data=1, fsdp=1), module, "pretrain", batch=batch_of(8)
        )
        eval_step = make_eval_step(mesh, sharding, mode="pretrain")
        batches = [batch_of(8, seed=s) for s in range(3)]

        def run_eval():
            total = n = 0.0
            for i, b in enumerate(batches):
                out = eval_step(state, b, i)
                total += float(out["loss"])
                n += float(out["num_samples"])
            return total / n

        first, second = run_eval(), run_eval()
        assert first == second  # bitwise: same program, same inputs

        # the per-batch mask stream varies: same data, different batch_idx
        a = float(eval_step(state, batches[0], 0)["loss"])
        b = float(eval_step(state, batches[0], 1)["loss"])
        assert a != b


class TestOptim:
    def test_schedule_warmup_peak_end(self):
        from jumbo_mae_tpu_tpu.train.optim import make_schedule

        cfg = OptimConfig(
            learning_rate=1.5e-4,
            lr_scaling="batch",
            warmup_steps=10,
            training_steps=100,
            init_lr=1e-6,
            end_lr=1e-5,
        )
        sched = make_schedule(cfg, global_batch_size=4096)
        peak = 1.5e-4 * 4096 / 256
        np.testing.assert_allclose(float(sched(0)), 1e-6, rtol=1e-5)
        np.testing.assert_allclose(float(sched(10)), peak, rtol=1e-5)
        np.testing.assert_allclose(float(sched(100)), 1e-5, rtol=1e-3)

    def test_lr_scaling_rules(self):
        assert OptimConfig(
            learning_rate=0.1, lr_scaling="batch"
        ).peak_lr(16384) == pytest.approx(0.1 * 64)
        assert OptimConfig(
            learning_rate=3.0, lr_scaling="none"
        ).peak_lr(4096) == pytest.approx(3.0)

    def test_layer_index_mapping(self):
        import jax.tree_util as jtu

        from jumbo_mae_tpu_tpu.train.optim import layer_index

        def path_of(*keys):
            return tuple(jtu.DictKey(k) for k in keys)

        assert layer_index(path_of("model", "embed", "proj"), num_layers=12) == 0
        assert layer_index(path_of("model", "block_0", "attn"), num_layers=12) == 1
        assert layer_index(path_of("model", "block_11", "mlp"), num_layers=12) == 12
        assert layer_index(path_of("model", "head", "fc"), num_layers=12) == 12
        assert layer_index(path_of("model", "cls_tokens"), num_layers=12) == 12

    def test_scale_by_adam_dtyped_matches_optax_in_f32(self):
        """With no dtype casts the custom core is bit-identical to optax."""
        import jax
        import jax.numpy as jnp
        import optax

        from jumbo_mae_tpu_tpu.train.optim import scale_by_adam_dtyped

        params = {
            "kernel": jnp.linspace(-1.0, 1.0, 12).reshape(3, 4),
            "bias": jnp.arange(4, dtype=jnp.float32),
        }
        ref = optax.scale_by_adam(b1=0.9, b2=0.95, eps=1e-8)
        got = scale_by_adam_dtyped(0.9, 0.95, 1e-8)
        s_ref, s_got = ref.init(params), got.init(params)
        g = jax.tree.map(lambda p: 0.01 * (p + 1.0), params)
        for _ in range(3):
            u_ref, s_ref = ref.update(g, s_ref)
            u_got, s_got = got.update(g, s_got)
        for a, b in zip(jax.tree.leaves(u_ref), jax.tree.leaves(u_got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(s_ref.nu), jax.tree.leaves(s_got.nu)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_nu_dtype_casts_state_and_tracks_f32(self):
        """nu_dtype=bfloat16 stores bf16 moments; updates stay close to the
        f32 chain (the EMA is computed in f32, only storage is cast)."""
        import jax
        import jax.numpy as jnp

        from jumbo_mae_tpu_tpu.train.optim import scale_by_adam_dtyped

        params = {"kernel": jnp.linspace(-0.5, 0.5, 64).reshape(8, 8)}
        f32 = scale_by_adam_dtyped(0.9, 0.95, 1e-8)
        cast = scale_by_adam_dtyped(
            0.9, 0.95, 1e-8, mu_dtype="bfloat16", nu_dtype="bfloat16"
        )
        s32, sc = f32.init(params), cast.init(params)
        assert sc.mu["kernel"].dtype == jnp.bfloat16
        assert sc.nu["kernel"].dtype == jnp.bfloat16
        g = jax.tree.map(lambda p: 0.02 * jnp.cos(7.0 * p), params)
        for _ in range(5):
            u32, s32 = f32.update(g, s32)
            uc, sc = cast.update(g, sc)
        assert sc.nu["kernel"].dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(uc["kernel"], np.float32),
            np.asarray(u32["kernel"], np.float32),
            rtol=2e-2,
            atol=2e-2,
        )

    def test_make_optimizer_nu_dtype_wires_through(self):
        import jax
        import jax.numpy as jnp

        opt = OptimConfig(
            name="adamw",
            learning_rate=1e-3,
            lr_scaling="none",
            warmup_steps=0,
            training_steps=10,
            mu_dtype="bfloat16",
            nu_dtype="bfloat16",
        )
        tx = make_optimizer(opt, 256)
        params = {"kernel": jnp.ones((4, 4))}
        state = tx.init(params)
        dtypes = {
            str(leaf.dtype)
            for leaf in jax.tree.leaves(state)
            if hasattr(leaf, "dtype") and leaf.ndim == 2
        }
        assert "bfloat16" in dtypes
        g = {"kernel": jnp.full((4, 4), 0.01)}
        updates, state = tx.update(g, state, params)
        assert np.all(np.isfinite(np.asarray(updates["kernel"], np.float32)))

    def test_with_master_weights_f32_master_is_exact(self):
        """Master copy updates in f32; stored params are an EXACT bf16
        downcast of the master after every step."""
        import optax

        from jumbo_mae_tpu_tpu.train.optim import with_master_weights

        params = {
            "kernel": jnp.linspace(-0.5, 0.5, 64).reshape(8, 8).astype(jnp.bfloat16)
        }
        tx = with_master_weights(optax.adamw(1e-2))
        state = tx.init(params)
        assert state.master["kernel"].dtype == jnp.float32
        for i in range(4):
            g = jax.tree.map(
                lambda p: (0.05 * jnp.sin(3.0 * p.astype(jnp.float32) + i)).astype(p.dtype),
                params,
            )
            updates, state = tx.update(g, state, params)
            params = optax.apply_updates(params, updates)
            assert params["kernel"].dtype == jnp.bfloat16
            np.testing.assert_array_equal(
                np.asarray(params["kernel"], np.float32),
                np.asarray(
                    state.master["kernel"].astype(jnp.bfloat16), np.float32
                ),
            )

    @pytest.mark.slow  # heavy compile; full suite covers it
    def test_param_dtype_bf16_step_tracks_f32_run(self):
        """optim.param_dtype=bfloat16 end-to-end: params stored bf16, the
        f32 master lives in opt_state, loss trajectory tracks the f32 run."""
        from dataclasses import replace

        batch = batch_of(16)
        opt_bf16 = replace(OPT, param_dtype="bfloat16")
        mesh = create_mesh(MeshConfig(data=1, fsdp=2))
        losses = {}
        for tag, opt, pdt in (
            ("f32", OPT, None),
            ("bf16", opt_bf16, "bfloat16"),
        ):
            tx = make_optimizer(opt, global_batch_size=256)
            state, sharding = create_sharded_state(
                pretrain_module(), tx, batch, mesh, mode="pretrain",
                init_seed=0, rng_seed=0, min_shard_size=128,
                param_dtype=pdt,
            )
            step = make_train_step(mesh, sharding, mode="pretrain")
            run = []
            for _ in range(5):
                state, m = step(state, batch)
                run.append(float(m["loss"]))
            losses[tag] = run
            if tag == "bf16":
                leaf = jax.tree.leaves(state.params)[0]
                assert leaf.dtype == jnp.bfloat16
                master = state.opt_state.inner_state.master
                for p, mw in zip(
                    jax.tree.leaves(state.params), jax.tree.leaves(master)
                ):
                    assert mw.dtype == jnp.float32
                    np.testing.assert_array_equal(
                        np.asarray(p, np.float32),
                        np.asarray(mw.astype(jnp.bfloat16), np.float32),
                    )
        np.testing.assert_allclose(
            losses["bf16"], losses["f32"], rtol=3e-2
        )
        assert losses["bf16"][-1] < losses["bf16"][0]

    def test_param_dtype_bf16_with_grad_accum(self):
        """bf16 params + scan grad accumulation: micro-grads accumulate in
        f32 and the composed step still learns."""
        from dataclasses import replace

        opt = replace(OPT, param_dtype="bfloat16")
        micro = batch_of(16)
        batch = jax.tree_util.tree_map(
            lambda x: jnp.stack([x[:8], x[8:]]), micro
        )
        mesh = create_mesh(MeshConfig(data=1, fsdp=1))
        tx = make_optimizer(opt, global_batch_size=256)
        state, sharding = create_sharded_state(
            pretrain_module(), tx, jax.tree_util.tree_map(lambda x: x[0], batch),
            mesh, mode="pretrain", param_dtype="bfloat16",
        )
        step = make_train_step(mesh, sharding, mode="pretrain", grad_accum=2)
        losses = []
        for _ in range(5):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    @pytest.mark.slow  # heavy compile; full suite covers it
    def test_warm_start_resyncs_master_weights(self):
        """Swapping pretrained params into a param_dtype=bfloat16 state must
        re-init the optimizer state (the CLI does): otherwise the f32 master
        still holds the random init and the first step silently reverts the
        warm start (round-4 review finding)."""
        from dataclasses import replace

        batch = batch_of(16)
        opt = replace(OPT, param_dtype="bfloat16")
        mesh = create_mesh(MeshConfig(data=1, fsdp=1))
        tx = make_optimizer(opt, global_batch_size=256)
        # "pretrained" weights: a differently-seeded init, offset so they are
        # far from the fresh init
        donor, _ = create_sharded_state(
            pretrain_module(), tx, batch, mesh, mode="pretrain",
            init_seed=7, param_dtype="bfloat16",
        )
        pretrained = jax.tree_util.tree_map(
            lambda p: (p.astype(jnp.float32) + 0.5).astype(p.dtype), donor.params
        )
        state, sharding = create_sharded_state(
            pretrain_module(), tx, batch, mesh, mode="pretrain",
            init_seed=0, param_dtype="bfloat16",
        )
        # the CLI's warm-start sequence (cli/train.py): merge in f32 so the
        # master keeps the checkpoint's full precision, store the downcast
        pretrained_f32 = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32) * (1.0 + 1e-4), pretrained
        )  # perturb so values carry mantissa bits beyond bf16
        opt_state = jax.jit(
            state.tx.init, out_shardings=sharding.opt_state
        )(pretrained_f32)
        pretrained = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype), pretrained_f32, state.params
        )
        state = state.replace(params=pretrained, opt_state=opt_state)
        # the master must be the EXACT f32 checkpoint values, not a bf16
        # round-trip of them
        for m, v in zip(
            jax.tree_util.tree_leaves(state.opt_state.inner_state.master),
            jax.tree_util.tree_leaves(pretrained_f32),
        ):
            np.testing.assert_array_equal(np.asarray(m), np.asarray(v))
        for p, mw in zip(
            jax.tree_util.tree_leaves(state.params),
            jax.tree_util.tree_leaves(state.opt_state.inner_state.master),
        ):
            np.testing.assert_array_equal(
                np.asarray(p, np.float32),
                np.asarray(mw.astype(jnp.bfloat16), np.float32),
            )
        step = make_train_step(mesh, sharding, mode="pretrain")
        # snapshot first: the step donates the state's buffers
        before_leaves = [
            np.asarray(p, np.float32)
            for p in jax.tree_util.tree_leaves(state.params)
        ]
        new_state, _ = step(state, batch)
        # one small-LR step must stay near the warm start, not revert to init
        for before, after in zip(
            before_leaves, jax.tree_util.tree_leaves(new_state.params)
        ):
            delta = np.abs(np.asarray(after, np.float32) - before).max()
            assert delta < 0.1, delta

    @pytest.mark.parametrize("name", ["adamw", "lamb", "lars", "sgd"])
    def test_all_optimizers_step(self, name):
        batch = batch_of(8, labels=np.arange(8) % 10)
        opt = OptimConfig(
            name=name,
            learning_rate=1e-3,
            lr_scaling="none",
            warmup_steps=0,
            training_steps=10,
            layer_decay=0.75 if name == "adamw" else 1.0,
        )
        module = classify_module()
        mesh = create_mesh(MeshConfig(data=1, fsdp=1))
        tx = make_optimizer(opt, 256, num_layers=TINY.layers)
        state, sharding = create_sharded_state(
            module, tx, batch, mesh, mode="classify"
        )
        step = make_train_step(mesh, sharding, mode="classify")
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
