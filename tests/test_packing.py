"""Token-packing planner contracts (``infer/packing.py``).

The planner is pure numpy and must be *fully deterministic* — the packed
executable cache is keyed by (rows, max_segments, budget), so a plan that
wobbles between runs is a recompile storm.  Beyond determinism: segments
never overlap, never cross a row's token budget, and the device-side plan
arrays round-trip each request's tokens exactly.
"""

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.infer.packing import (
    PackPlan,
    budget_rungs,
    build_arrays,
    choose_budget,
    pack_ffd,
    place_tokens,
    unpack_rows,
)


def _occupancy(plan: PackPlan) -> np.ndarray:
    """(rows, budget) int matrix counting how many segments claim each
    token position — the no-overlap witness."""
    occ = np.zeros((plan.rows, plan.budget), np.int32)
    for s in plan.segments:
        occ[s.row, s.offset : s.offset + s.length] += 1
    return occ


class TestPackFFD:
    def test_deterministic_same_plan_every_time(self):
        lens = [65, 17, 130, 65, 5, 257, 17, 64]
        plans = [pack_ffd(lens, 512) for _ in range(5)]
        assert all(p == plans[0] for p in plans[1:])

    def test_ties_break_by_request_index(self):
        # three equal lengths: request order must be the placement order
        plan = pack_ffd([10, 10, 10], 32)
        by_req = {s.request: s for s in plan.segments}
        assert (by_req[0].row, by_req[0].offset) == (0, 0)
        assert (by_req[1].row, by_req[1].offset) == (0, 10)
        assert (by_req[2].row, by_req[2].offset) == (0, 20)

    def test_no_overlap_and_within_budget(self):
        rng = np.random.RandomState(0)
        for _ in range(20):
            lens = rng.randint(1, 200, size=rng.randint(1, 40)).tolist()
            plan = pack_ffd(lens, 256)
            occ = _occupancy(plan)
            assert occ.max() <= 1, "two segments share a token position"
            assert occ.sum() == sum(lens)
            # per-row fill never exceeds the budget (occ shape enforces it,
            # but assert the fill explicitly for the error message)
            assert occ.sum(axis=1).max() <= 256

    def test_every_request_placed_exactly_once(self):
        lens = [3, 5, 8, 13, 21, 34]
        plan = pack_ffd(lens, 64)
        assert sorted(s.request for s in plan.segments) == list(range(6))
        assert [s.length for s in plan.segments] == lens  # request order

    def test_slots_are_dense_per_row(self):
        plan = pack_ffd([30, 30, 30, 30, 30], 64)
        for r in range(plan.rows):
            slots = sorted(s.slot for s in plan.segments if s.row == r)
            assert slots == list(range(len(slots)))
        assert plan.max_segments == max(
            sum(1 for s in plan.segments if s.row == r)
            for r in range(plan.rows)
        )

    def test_empty_and_error_cases(self):
        assert pack_ffd([], 64).rows == 0
        with pytest.raises(ValueError):
            pack_ffd([10], 0)
        with pytest.raises(ValueError):
            pack_ffd([0], 64)
        with pytest.raises(ValueError):
            pack_ffd([65], 64)  # segment > budget is a planning error

    def test_pad_fraction(self):
        plan = pack_ffd([48, 48], 64)  # 2 rows, 96/128 tokens
        assert plan.pad_fraction() == pytest.approx(32 / 128)
        # the device may run more (row-bucketed) rows than the plan
        assert plan.pad_fraction(rows=4) == pytest.approx(
            (4 * 64 - 96) / (4 * 64)
        )


class TestChooseBudget:
    def test_prefers_tighter_total_device_tokens(self):
        # 4 x 65 tokens: budget 128 -> 4 rows (wasteful), 256 -> 2 rows,
        # both 512 device tokens; tie breaks toward the smaller budget
        budget, plan = choose_budget([65, 65, 65, 65], (128, 256, 512))
        assert budget == 128
        assert plan.rows * 1 <= 4

    def test_needs_a_rung_fitting_the_largest_segment(self):
        with pytest.raises(ValueError):
            choose_budget([300], (64, 128, 256))

    def test_deterministic(self):
        lens = [65, 17, 130, 65, 5, 257, 17, 64]
        picks = [choose_budget(lens, budget_rungs(512)) for _ in range(3)]
        assert all(p == picks[0] for p in picks[1:])


class TestBudgetRungs:
    def test_pow2_ladder_from_min(self):
        assert budget_rungs(512) == (64, 128, 256, 512)

    def test_non_pow2_max_appended(self):
        assert budget_rungs(600) == (64, 128, 256, 512, 600)

    def test_tiny_max_still_usable(self):
        assert budget_rungs(32) == (32,)


class TestPlanArrays:
    def test_build_arrays_matches_plan(self):
        k = 3
        plan = pack_ffd([10, 7, 10], 32)
        arrs = build_arrays(plan, k)
        seg, cls_pos, cls_index = (
            arrs["segment_ids"], arrs["cls_pos"], arrs["cls_index"],
        )
        assert seg.shape == (plan.rows, 32)
        for s in plan.segments:
            span = seg[s.row, s.offset : s.offset + s.length]
            assert (span == s.slot + 1).all()
            assert (
                cls_pos[s.row, s.offset : s.offset + k]
                == np.arange(k)
            ).all()
            assert (
                cls_index[s.row, s.slot] == s.offset + np.arange(k)
            ).all()
        # padding: id 0, cls_pos -1
        assert (seg[cls_pos == -1] == 0).sum() == (seg == 0).sum()

    def test_build_arrays_bucketed_extra_rows_are_pad(self):
        plan = pack_ffd([10, 10], 32)
        arrs = build_arrays(plan, 1, rows=4, max_segments=4)
        assert arrs["segment_ids"].shape == (4, 32)
        assert (arrs["segment_ids"][plan.rows :] == 0).all()
        assert (arrs["cls_pos"][plan.rows :] == -1).all()

    def test_build_arrays_refuses_shrink(self):
        plan = pack_ffd([10, 10, 10, 10], 16)  # 4 rows
        with pytest.raises(ValueError):
            build_arrays(plan, 1, rows=2)

    def test_place_unpack_roundtrip(self):
        k, dim = 2, 4
        lens = [k + 5, k + 9, k + 3]
        plan = pack_ffd(lens, 16)
        rng = np.random.RandomState(1)
        toks = [rng.randn(n - k, dim).astype(np.float32) for n in lens]
        buf = place_tokens(plan, toks, k)
        # each request's patch tokens land contiguously after its CLS slots
        for s in plan.segments:
            got = buf[s.row, s.offset + k : s.offset + s.length]
            assert np.array_equal(got, toks[s.request])
            # CLS slots stay zero (the encoder injects its parameter)
            assert (buf[s.row, s.offset : s.offset + k] == 0).all()
        # unpack_rows gathers per-slot results back in request order
        fake = np.zeros((plan.rows, plan.max_segments, dim), np.float32)
        for s in plan.segments:
            fake[s.row, s.slot] = s.request + 1
        out = unpack_rows(plan, fake)
        for i in range(len(lens)):
            assert (out[i] == i + 1).all()

    def test_place_tokens_length_mismatch_raises(self):
        plan = pack_ffd([8], 16)
        with pytest.raises(ValueError):
            place_tokens(plan, [np.zeros((3, 4), np.float32)], 2)
