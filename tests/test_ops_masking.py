import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.ops import (
    index_sequence,
    random_masking,
    unshuffle_with_mask_tokens,
)


@pytest.mark.parametrize("mode", ["shared", "per_sample"])
def test_masking_shapes_and_mask_count(mode):
    x = jnp.arange(4 * 16 * 8, dtype=jnp.float32).reshape(4, 16, 8)
    kept, mask, ids_restore = random_masking(
        x, jax.random.key(0), keep_len=4, mode=mode
    )
    assert kept.shape == (4, 4, 8)
    assert mask.shape == (4, 16)
    # exactly length-keep_len masked positions per sample
    np.testing.assert_array_equal(np.asarray(mask.sum(-1)), np.full(4, 12.0))


def test_shared_mode_same_permutation_across_batch():
    x = jnp.broadcast_to(jnp.arange(16.0)[None, :, None], (3, 16, 2))
    kept, mask, ids_restore = random_masking(x, jax.random.key(1), 5, mode="shared")
    assert ids_restore.ndim == 1
    # every batch row kept the same token ids
    np.testing.assert_array_equal(np.asarray(kept[0]), np.asarray(kept[1]))
    np.testing.assert_array_equal(np.asarray(mask[0]), np.asarray(mask[2]))


def test_per_sample_mode_differs_across_batch():
    x = jnp.broadcast_to(jnp.arange(64.0)[None, :, None], (8, 64, 2))
    kept, mask, _ = random_masking(x, jax.random.key(2), 16, mode="per_sample")
    assert not np.array_equal(np.asarray(mask[0]), np.asarray(mask[1]))


@pytest.mark.parametrize("mode", ["shared", "per_sample"])
def test_mask_marks_exactly_the_dropped_tokens(mode):
    # token value == token index, so membership is checkable
    x = jnp.broadcast_to(jnp.arange(32.0)[None, :, None], (2, 32, 1))
    kept, mask, _ = random_masking(x, jax.random.key(3), 9, mode=mode)
    for b in range(2):
        kept_ids = set(np.asarray(kept[b, :, 0]).astype(int).tolist())
        unmasked_ids = set(np.flatnonzero(np.asarray(mask[b]) == 0.0).tolist())
        assert kept_ids == unmasked_ids


@pytest.mark.parametrize("mode", ["shared", "per_sample"])
def test_unshuffle_round_trip(mode):
    """unshuffle(kept, mask_token) restores kept tokens at their original
    positions and the mask token everywhere else."""
    x = jax.random.normal(jax.random.key(4), (2, 20, 3))
    kept, mask, ids_restore = random_masking(x, jax.random.key(5), 7, mode=mode)
    token = jnp.full((1, 1, 3), -100.0)
    full = unshuffle_with_mask_tokens(kept, token, ids_restore)
    assert full.shape == x.shape
    restored = np.asarray(full)
    orig = np.asarray(x)
    m = np.asarray(mask)
    for b in range(2):
        np.testing.assert_allclose(restored[b][m[b] == 0], orig[b][m[b] == 0])
        assert (restored[b][m[b] == 1] == -100.0).all()


def test_masking_deterministic_given_key():
    x = jax.random.normal(jax.random.key(6), (2, 50, 4))
    a = random_masking(x, jax.random.key(7), 12)
    b = random_masking(x, jax.random.key(7), 12)
    for u, v in zip(a, b):
        np.testing.assert_array_equal(np.asarray(u), np.asarray(v))


def test_index_sequence_1d_and_2d():
    x = jnp.arange(2 * 5 * 3, dtype=jnp.float32).reshape(2, 5, 3)
    ids1 = jnp.array([4, 0, 2])
    out1 = index_sequence(x, ids1)
    np.testing.assert_array_equal(np.asarray(out1[0, 0]), np.asarray(x[0, 4]))
    ids2 = jnp.array([[1, 3], [0, 2]])
    out2 = index_sequence(x, ids2)
    np.testing.assert_array_equal(np.asarray(out2[1, 1]), np.asarray(x[1, 2]))


def test_mask_algebra():
    """Parity: the m3ae mask helpers (/root/reference/src/utils_mae.py:24-49)."""
    from jumbo_mae_tpu_tpu.ops import (
        all_mask,
        mask_intersection,
        mask_not,
        mask_select,
        mask_union,
        no_mask,
    )

    x = jnp.zeros((2, 5, 3))
    z, o = no_mask(x), all_mask(x)
    np.testing.assert_array_equal(np.asarray(z), np.zeros((2, 5)))
    np.testing.assert_array_equal(np.asarray(o), np.ones((2, 5)))

    a = jnp.array([[0.0, 1.0, 0.0, 1.0, 0.0]])
    b = jnp.array([[0.0, 0.0, 1.0, 1.0, 0.0]])
    np.testing.assert_array_equal(
        np.asarray(mask_union(a, b)), [[0, 1, 1, 1, 0]]
    )
    np.testing.assert_array_equal(
        np.asarray(mask_intersection(a, b)), [[0, 0, 0, 1, 0]]
    )
    np.testing.assert_array_equal(np.asarray(mask_not(a)), [[1, 0, 1, 0, 1]])
    # de Morgan: not(a ∪ b) == not(a) ∩ not(b)
    np.testing.assert_array_equal(
        np.asarray(mask_not(mask_union(a, b))),
        np.asarray(mask_intersection(mask_not(a), mask_not(b))),
    )

    # reference argument order: second arg is the UNMASKED value
    when_unmasked = jnp.zeros((1, 5, 2))
    when_masked = jnp.full((1, 5, 2), 9.0)
    sel = mask_select(a, when_unmasked, when_masked)
    np.testing.assert_array_equal(np.asarray(sel[0, :, 0]), [0, 9, 0, 9, 0])

    # soft/weighted masks binarize like the reference ((>0) semantics)
    np.testing.assert_array_equal(
        np.asarray(mask_union(jnp.array([[0.3, 0.0]]), jnp.array([[0.2, 0.0]]))),
        [[1.0, 0.0]],
    )
    np.testing.assert_array_equal(
        np.asarray(
            mask_intersection(jnp.array([[2.0, 0.5]]), jnp.array([[0.5, 0.0]]))
        ),
        [[1.0, 0.0]],
    )
    # ...but mask_not is pure 1-x (reference semantics): 0.3 inverts to 0.7
    np.testing.assert_allclose(
        np.asarray(mask_not(jnp.array([[0.3, 0.0]]))), [[0.7, 1.0]], rtol=1e-6
    )


# --------------------------------------------------------------------------
# onehot (MXU-matmul) gather lowering — must be BIT-identical to take
# --------------------------------------------------------------------------


def test_onehot_index_sequence_bit_identical():
    from jumbo_mae_tpu_tpu.ops.masking import index_sequence

    x = jax.random.normal(jax.random.key(0), (4, 12, 8), jnp.float32)
    ids1 = jnp.asarray([3, 0, 11, 7, 5])
    np.testing.assert_array_equal(
        np.asarray(index_sequence(x, ids1, impl="onehot")),
        np.asarray(index_sequence(x, ids1, impl="take")),
    )
    ids2 = jnp.stack([jnp.roll(jnp.arange(12), s)[:6] for s in range(4)])
    np.testing.assert_array_equal(
        np.asarray(index_sequence(x, ids2, impl="onehot")),
        np.asarray(index_sequence(x, ids2, impl="take")),
    )
    # bf16 too: 0/1 matmul is exact in any dtype
    xb = x.astype(jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(index_sequence(xb, ids1, impl="onehot"), np.float32),
        np.asarray(index_sequence(xb, ids1, impl="take"), np.float32),
    )


@pytest.mark.parametrize("mode", ["shared", "per_sample"])
def test_onehot_unshuffle_bit_identical(mode):
    from jumbo_mae_tpu_tpu.ops.masking import (
        random_masking,
        unshuffle_with_mask_tokens,
    )

    x = jax.random.normal(jax.random.key(1), (4, 16, 8), jnp.bfloat16)
    kept, mask, ids_restore = random_masking(
        x, jax.random.key(2), 6, mode=mode
    )
    token = jax.random.normal(jax.random.key(3), (1, 1, 8), jnp.bfloat16)
    a = unshuffle_with_mask_tokens(kept, token, ids_restore, impl="take")
    b = unshuffle_with_mask_tokens(kept, token, ids_restore, impl="onehot")
    np.testing.assert_array_equal(
        np.asarray(a, np.float32), np.asarray(b, np.float32)
    )


def test_gather_impl_end_to_end_same_loss():
    """The model-level knob: identical loss under jit for both lowerings."""
    from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset

    imgs = np.random.RandomState(0).randint(0, 256, (2, 32, 32, 3), np.uint8)
    rngs = {"params": jax.random.key(0), "noise": jax.random.key(1)}
    losses = {}
    for impl in ("take", "onehot"):
        enc = preset(
            "vit_t16",
            image_size=32,
            patch_size=8,
            mask_ratio=0.75,
            labels=None,
            dtype="float32",
            gather_impl=impl,
        )
        dec = DecoderConfig(layers=1, dim=32, heads=2, dtype="float32")
        model = MAEPretrainModel(enc, dec)
        variables = model.init(rngs, imgs)
        out = jax.jit(
            lambda v, m=model: m.apply(
                v, imgs, rngs={"noise": jax.random.key(7)}
            )
        )(variables)
        losses[impl] = float(out["loss"])
    assert losses["take"] == losses["onehot"], losses


def test_gather_impl_validated():
    from jumbo_mae_tpu_tpu.ops.masking import index_sequence

    x = jnp.zeros((2, 4, 3))
    with pytest.raises(ValueError, match="gather impl"):
        index_sequence(x, jnp.array([0, 1]), impl="one_hot")
    with pytest.raises(ValueError, match="gather impl"):
        unshuffle_with_mask_tokens(
            x[:, :2], jnp.zeros((1, 1, 3)), jnp.array([0, 1, 2, 3]), impl="gather"
        )
