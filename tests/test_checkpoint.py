"""Checkpoint subsystem tests: full-state round trip on a sharded mesh state,
best/last policy, true resume, pretrained merge (incl. posemb resize), and
msgpack interop. (SURVEY §5: capability gap in the reference — no resume.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
from jumbo_mae_tpu_tpu.train import (
    OptimConfig,
    create_sharded_state,
    make_optimizer,
    make_train_step,
)
from jumbo_mae_tpu_tpu.train.checkpoint import (
    CheckpointConfig,
    Checkpointer,
    export_params_msgpack,
    import_params_msgpack,
    load_pretrained_params,
    merge_pretrained_params,
    resize_posemb,
)

TINY = preset(
    "vit_t16", image_size=32, patch_size=8, mask_ratio=0.75, labels=None,
    dtype="float32",
)
TINY_DEC = DecoderConfig(layers=1, dim=32, heads=2, dtype="float32")
OPT = OptimConfig(
    name="adamw", learning_rate=1e-3, lr_scaling="none", warmup_steps=2,
    training_steps=20,
)


def build(mesh):
    module = MAEPretrainModel(TINY, TINY_DEC)
    tx = make_optimizer(OPT, global_batch_size=16)
    batch = {
        "images": jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (16, 32, 32, 3)), jnp.uint8
        )
    }
    state, sharding = create_sharded_state(
        module, tx, batch, mesh, mode="pretrain", min_shard_size=128
    )
    step = make_train_step(mesh, sharding, mode="pretrain")
    return state, sharding, step, batch


def tree_allclose(a, b):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        if jnp.issubdtype(jnp.asarray(x).dtype, jax.dtypes.prng_key):
            x, y = jax.random.key_data(x), jax.random.key_data(y)
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=0, atol=0)


@pytest.fixture(scope="module")
def mesh(devices):
    return create_mesh(MeshConfig(data=2, fsdp=4))


def test_full_state_roundtrip_sharded(tmp_path, mesh):
    state, sharding, step, batch = build(mesh)
    state, _ = step(state, batch)
    state, _ = step(state, batch)

    ckpt = Checkpointer(CheckpointConfig(str(tmp_path), async_save=False))
    ckpt.save(int(state.step), state, metrics={"val/loss": 1.0}, extra={"cursor": 7})
    ckpt.wait()

    restored, extra = ckpt.restore(state, sharding=sharding)
    assert extra["cursor"] == 7
    assert int(restored.step) == 2
    tree_allclose(restored.params, state.params)
    tree_allclose(restored.opt_state, state.opt_state)
    # restored arrays land on the mesh with the same shardings
    flat_r = jax.tree_util.tree_leaves(restored.params)
    flat_s = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda s: s, sharding.params)
    )
    for arr, sh in zip(flat_r, flat_s):
        assert arr.sharding == sh
    ckpt.close()


def test_resume_equals_uninterrupted(tmp_path, mesh):
    state, sharding, step, batch = build(mesh)
    state, _ = step(state, batch)

    ckpt = Checkpointer(CheckpointConfig(str(tmp_path), async_save=False))
    ckpt.save(int(state.step), state)
    ckpt.wait()

    # uninterrupted: two more steps
    direct = state
    for _ in range(2):
        direct, _ = step(direct, batch)

    # resumed: restore then two more steps
    resumed, _ = ckpt.restore(state, sharding=sharding)
    for _ in range(2):
        resumed, _ = step(resumed, batch)

    tree_allclose(direct.params, resumed.params)
    assert int(direct.step) == int(resumed.step) == 3
    ckpt.close()


def test_best_last_policy(tmp_path, mesh):
    state, sharding, step, batch = build(mesh)
    ckpt = Checkpointer(
        CheckpointConfig(str(tmp_path), async_save=False, best_mode="min")
    )
    assert ckpt.save(1, state, metrics={"val/loss": 5.0}) is True
    assert ckpt.save(2, state, metrics={"val/loss": 6.0}) is False  # worse
    assert ckpt.save(3, state, metrics={"val/loss": 4.0}) is True
    ckpt.wait()
    assert ckpt.latest_step() == 3
    _, extra = ckpt.restore(state, sharding=sharding, which="best")
    assert extra["_best_metric"] == 4.0
    ckpt.close()

    # a fresh manager over the same dir recovers the best metric
    ckpt2 = Checkpointer(
        CheckpointConfig(str(tmp_path), async_save=False, best_mode="min")
    )
    assert ckpt2.best_metric == 4.0
    assert ckpt2.save(4, state, metrics={"val/loss": 4.5}) is False
    ckpt2.close()


def test_msgpack_roundtrip(tmp_path, mesh):
    state, *_ = build(mesh)
    path = tmp_path / "params.msgpack"
    export_params_msgpack(state.params, str(path), background=True)
    from jumbo_mae_tpu_tpu.train.checkpoint import _join_background_writers

    _join_background_writers()
    restored = import_params_msgpack(str(path))
    flat_a = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, state.params)
    )
    flat_b = jax.tree_util.tree_leaves(restored)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(a, b)


def test_restore_warns_on_optimizer_dtype_cast(tmp_path, mesh, capsys):
    """Resuming an f32-moment checkpoint with a bf16-moment template silently
    casts the moments (abstract-template restore); the restore path must
    surface that. Pins the Orbax item_metadata integration — if an Orbax
    upgrade changes the metadata layout, this test (not a user's silent
    mid-run numerics change) is what breaks."""
    state, sharding, _, batch = build(mesh)
    ckpt = Checkpointer(CheckpointConfig(str(tmp_path), async_save=False))
    ckpt.save(0, state, metrics={"val/loss": 1.0})
    ckpt.wait()
    module = MAEPretrainModel(TINY, TINY_DEC)
    tx_cast = make_optimizer(
        OptimConfig(
            name="adamw", learning_rate=1e-3, lr_scaling="none",
            warmup_steps=2, training_steps=20, nu_dtype="bfloat16",
        ),
        global_batch_size=16,
    )
    tmpl, tmpl_sharding = create_sharded_state(
        module, tx_cast, batch, mesh, mode="pretrain", min_shard_size=128
    )
    capsys.readouterr()
    restored, _ = ckpt.restore(tmpl, sharding=tmpl_sharding)
    out = capsys.readouterr().out
    ckpt.close()
    assert "WARNING: restore is casting" in out, out
    assert "nu" in out
    # and the same-dtype restore stays quiet
    ckpt2 = Checkpointer(CheckpointConfig(str(tmp_path / "b"), async_save=False))
    ckpt2.save(0, state, metrics={"val/loss": 1.0})
    ckpt2.wait()
    capsys.readouterr()
    ckpt2.restore(state, sharding=sharding)
    out = capsys.readouterr().out
    ckpt2.close()
    assert "WARNING: restore is casting" not in out, out


def test_resize_posemb():
    grid = np.random.RandomState(0).rand(1, 4, 4, 8).astype(np.float32)
    out = resize_posemb(grid, (1, 8, 8, 8))
    assert out.shape == (1, 8, 8, 8)
    # 3-D (H, W, D) grids — the framework's actual pos_embed layout
    out3 = resize_posemb(grid[0], (6, 6, 8))
    assert out3.shape == (6, 6, 8)
    # constant fields stay constant under bilinear resize
    const = np.ones((1, 4, 4, 8), np.float32) * 3.5
    np.testing.assert_allclose(resize_posemb(const, (1, 7, 7, 8)), 3.5, rtol=1e-6)


@pytest.mark.slow  # heavy compile; full suite covers it
def test_warm_start_resizes_real_pos_embed(tmp_path):
    """End-to-end: pretrain at 32px learnable posemb, warm-start a 48px
    model — pos_embed must be resized, not silently re-initialized."""
    small = preset(
        "vit_t16", image_size=32, patch_size=8, mask_ratio=0.75, labels=None,
        posemb="learnable", dtype="float32",
    )
    big = small.replace(image_size=48)
    imgs = jnp.zeros((2, 32, 32, 3), jnp.uint8)
    rngs = {"params": jax.random.key(0), "noise": jax.random.key(1)}
    params_small = MAEPretrainModel(small, TINY_DEC).init(rngs, imgs)["params"]
    path = tmp_path / "small.msgpack"
    export_params_msgpack(params_small, str(path))

    imgs_big = jnp.zeros((2, 48, 48, 3), jnp.uint8)
    params_big = MAEPretrainModel(big, TINY_DEC).init(rngs, imgs_big)["params"]
    merged = load_pretrained_params(str(path), params_big, verbose=False)
    got = np.asarray(merged["encoder"]["embed"]["pos_embed"])
    want = resize_posemb(
        np.asarray(params_small["encoder"]["embed"]["pos_embed"]), (6, 6, got.shape[-1])
    )
    assert got.shape == (6, 6, got.shape[-1])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_merge_pretrained_params():
    init = {
        "model": {
            "embed": {"wpe": np.zeros((1, 8, 8, 4), np.float32)},
            "block_0": {"w": np.zeros((4, 4), np.float32)},
            "head": {"kernel": np.zeros((4, 10), np.float32)},
        }
    }
    pre = {
        "model": {
            "embed": {"wpe": np.ones((1, 4, 4, 4), np.float32)},
            "block_0": {"w": np.full((4, 4), 2.0, np.float32)},
            "head": {"kernel": np.ones((4, 21), np.float32)},  # label mismatch
            "decoder_only": {"w": np.ones((2, 2), np.float32)},  # unused
        }
    }
    merged = merge_pretrained_params(pre["model"], init["model"], verbose=False)
    np.testing.assert_allclose(merged["block_0"]["w"], 2.0)
    np.testing.assert_allclose(merged["embed"]["wpe"], 1.0)  # resized ones
    assert merged["embed"]["wpe"].shape == (1, 8, 8, 4)
    np.testing.assert_allclose(merged["head"]["kernel"], 0.0)  # kept fresh
    assert "decoder_only" not in merged


def test_load_pretrained_from_msgpack(tmp_path, mesh):
    state, *_ = build(mesh)
    path = tmp_path / "pre.msgpack"
    export_params_msgpack(state.params, str(path))
    # fresh init with a different seed: params differ, then merge restores
    module = MAEPretrainModel(TINY, TINY_DEC)
    tx = make_optimizer(OPT, global_batch_size=16)
    batch = {
        "images": jnp.asarray(
            np.random.RandomState(1).randint(0, 256, (16, 32, 32, 3)), jnp.uint8
        )
    }
    fresh, _ = create_sharded_state(
        module, tx, batch, mesh, mode="pretrain", init_seed=123
    )
    merged = load_pretrained_params(str(path), fresh.params, verbose=False)
    tree_allclose(merged["encoder"], state.params["encoder"])


# --------------------------------------------------------------------------
# Remote-URL checkpoint IO (VERDICT r2 gap: gs:// dirs were Path-mangled)
# --------------------------------------------------------------------------


def test_gs_directory_reaches_manager_unmangled(monkeypatch):
    """A gs:// checkpoint directory must arrive at the Orbax manager with its
    scheme intact — pathlib would collapse it to the local path gs:/b/x."""
    import orbax.checkpoint as ocp

    from jumbo_mae_tpu_tpu.train import checkpoint as ckpt_mod

    seen = []

    class Recorder:
        def __init__(self, directory, *a, **k):
            seen.append(str(directory))

        def latest_step(self):
            return None

    monkeypatch.setattr(ocp, "CheckpointManager", Recorder)
    ckpt_mod.Checkpointer(
        ckpt_mod.CheckpointConfig(directory="gs://bucket/run1")
    )
    assert seen == ["gs://bucket/run1/last", "gs://bucket/run1/best"]


def test_checkpoint_root_local_is_absolute(tmp_path):
    from jumbo_mae_tpu_tpu.train.checkpoint import checkpoint_root

    root = checkpoint_root(str(tmp_path / "ck"))
    assert str(root).startswith("/")
    assert "://" not in str(root)


def test_msgpack_pipe_roundtrip(tmp_path, mesh):
    """pipe:-scheme write + read (the escape hatch that makes every remote
    store work; no GCS in this sandbox)."""
    state, _, _, _ = build(mesh)
    target = tmp_path / "remote" / "params.msgpack"
    target.parent.mkdir()
    export_params_msgpack(state.params, f"pipe:cat > {target}")
    assert target.exists() and target.stat().st_size > 0
    restored = import_params_msgpack(f"pipe:cat {target}")
    flat_a = jax.tree_util.tree_leaves(state.params)
    flat_b = jax.tree_util.tree_leaves(restored)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_pretrained_from_pipe_url(tmp_path, mesh):
    state, _, _, _ = build(mesh)
    path = tmp_path / "enc.msgpack"
    export_params_msgpack(state.params, str(path))
    loaded = load_pretrained_params(
        f"pipe:cat {path}", state.params, verbose=False
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(loaded),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_root_rejects_pipe_and_unwraps_file():
    from jumbo_mae_tpu_tpu.train.checkpoint import checkpoint_root

    with pytest.raises(ValueError, match="stream-only"):
        checkpoint_root("pipe:cat > /tmp/x")
    assert str(checkpoint_root("file:///tmp/ck")) == "/tmp/ck"


def test_load_pretrained_routes_gs_dir_to_orbax(monkeypatch, mesh):
    """A gs:// checkpoint *directory* must restore via Orbax, not be piped
    through gsutil cat as if it were a msgpack file."""
    from jumbo_mae_tpu_tpu.train import checkpoint as ckpt_mod

    state, _, _, _ = build(mesh)
    calls = {}

    def fake_restore(directory):
        calls["dir"] = str(directory)
        return jax.tree_util.tree_map(np.asarray, state.params)

    monkeypatch.setattr(ckpt_mod, "restore_params_any", fake_restore)
    monkeypatch.setattr(
        ckpt_mod, "checkpoint_root", lambda s: _FakeDir(s)
    )
    ckpt_mod.load_pretrained_params(
        "gs://bucket/run1", state.params, verbose=False
    )
    assert calls["dir"] == "gs://bucket/run1"


class _FakeDir:
    def __init__(self, s):
        self._s = str(s)

    def is_dir(self):
        return True

    def __str__(self):
        return self._s


def test_load_pretrained_routes_gs_msgpack_to_stream(monkeypatch, mesh):
    from jumbo_mae_tpu_tpu.train import checkpoint as ckpt_mod

    state, _, _, _ = build(mesh)
    calls = {}

    def fake_import(path):
        calls["path"] = str(path)
        return jax.tree_util.tree_map(np.asarray, state.params)

    monkeypatch.setattr(ckpt_mod, "import_params_msgpack", fake_import)
    ckpt_mod.load_pretrained_params(
        "gs://bucket/enc.msgpack", state.params, verbose=False
    )
    assert calls["path"] == "gs://bucket/enc.msgpack"


def test_export_file_scheme_gets_mkdir_and_atomic_commit(tmp_path, mesh):
    """file:// targets are LOCAL: they must keep the parent-mkdir and the
    tmp+rename commit, not be streamed through open_url."""
    state, _, _, _ = build(mesh)
    target = tmp_path / "new_dir" / "p.msgpack"  # parent does not exist yet
    export_params_msgpack(state.params, f"file://{target}")
    assert target.exists()
    restored = import_params_msgpack(str(target))
    assert len(jax.tree_util.tree_leaves(restored)) == len(
        jax.tree_util.tree_leaves(state.params)
    )
