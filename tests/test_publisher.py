"""Continuous-deployment contracts (serve/publisher.py + publish_doctor).

What the publish path must guarantee:

- **round-trip**: a published tree resolves back bit-exact for f32
  transport and within int8 parity for quantized transport, batch_stats
  included;
- **delta chain**: unchanged leaves ride the base by digest, the chain
  resolves through multiple links, a full tree is forced on the
  ``full_every`` cadence, and the chain survives a publisher restart;
- **integrity**: a corrupted payload, a torn (truncated) payload, a
  swapped base, and a missing base are each *named* failures — never a
  silently wrong tree — and the ``publish.export`` fault site produces
  exactly those artifacts for the chaos harness;
- **gates**: bad steps, a sentinel rollback, the min-interval floor, and
  the eval-metric floor each skip the publish with a journaled reason;
  an export failure journals ``publish_failed`` and never propagates
  into the engine (continuous deployment cannot kill training);
- **billing**: every publish lands a ``publish``-tenant ``tenant_usage``
  journal row through the costmeter;
- **doctor**: ``tools/publish_doctor.py`` exits 0 on a healthy directory
  and 2 on a broken one, naming the broken link.
"""

import json

import numpy as np
import pytest

from jumbo_mae_tpu_tpu import faults
from jumbo_mae_tpu_tpu.serve.publisher import (
    MANIFEST,
    PAYLOAD,
    CheckpointPublisher,
    PublishIntegrityError,
    is_publish_artifact,
    latest_artifact,
    resolve_chain,
    verify_artifact,
)
from jumbo_mae_tpu_tpu.train.engine import RunEngine


@pytest.fixture
def inject():
    yield faults.install_plan
    faults.clear_plan()


def make_params(scale=1.0):
    rng = np.random.default_rng(0)
    return {
        "encoder": {
            "layer0": {
                "kernel": (rng.normal(size=(16, 8)) * scale).astype(np.float32),
                "bias": np.zeros(8, np.float32),
            }
        },
        "pos": np.full((4, 16), scale, np.float32),
    }


def events_of(log, etype):
    return [f for t, f in log if t == etype]


# ------------------------------------------------------------- round-trip


def test_f32_round_trip_is_bit_exact(tmp_path):
    pub = CheckpointPublisher(tmp_path, quant="none")
    params = make_params()
    stats = {"head": {"mean": np.arange(8, dtype=np.float32)}}
    art = pub.publish(4, params, batch_stats=stats)
    assert is_publish_artifact(art)
    got, got_stats, m = resolve_chain(art)
    np.testing.assert_array_equal(
        got["encoder"]["layer0"]["kernel"], params["encoder"]["layer0"]["kernel"]
    )
    np.testing.assert_array_equal(got_stats["head"]["mean"], stats["head"]["mean"])
    assert m["step"] == 4 and m["quant"] == "none"


def test_int8_round_trip_within_parity(tmp_path):
    pub = CheckpointPublisher(tmp_path, quant="int8")
    params = make_params()
    got, got_stats, m = resolve_chain(pub.publish(1, params))
    assert got_stats is None
    ref = params["encoder"]["layer0"]["kernel"]
    q = got["encoder"]["layer0"]["kernel"]
    cos = float((ref * q).sum() / (np.linalg.norm(ref) * np.linalg.norm(q)))
    assert cos > 0.999
    # non-kernel leaves are untouched by PTQ
    np.testing.assert_array_equal(got["pos"], params["pos"])
    assert m["quant_report"]["n_quantized"] == 1


def test_delta_chain_resolves_through_multiple_links(tmp_path):
    pub = CheckpointPublisher(tmp_path, quant="none", full_every=100)
    params = make_params()
    pub.publish(1, params)
    params["pos"] = params["pos"] * 2
    a2 = pub.publish(2, params)
    params["encoder"]["layer0"]["bias"] = np.ones(8, np.float32)
    a3 = pub.publish(3, params)
    m3 = json.loads((a3 / MANIFEST).read_text())
    assert m3["base"]["name"] == a2.name
    assert m3["delta_fraction"] < 1.0
    got, _, _ = resolve_chain(a3)  # pos from a2, kernel from a1, bias from a3
    np.testing.assert_array_equal(got["pos"], params["pos"])
    np.testing.assert_array_equal(
        got["encoder"]["layer0"]["bias"], np.ones(8, np.float32)
    )


def test_full_every_bounds_the_chain(tmp_path):
    pub = CheckpointPublisher(tmp_path, quant="none", full_every=2)
    params = make_params()
    for step in (1, 2, 3):
        params["pos"] = params["pos"] + 1
        pub.publish(step, params)
    # seq 0 full, seq 1 delta, seq 2 full again (2 % full_every == 0)
    m = json.loads((tmp_path / "publish-000002" / MANIFEST).read_text())
    assert m["base"] is None
    assert all(r["where"] == "payload" for r in m["leaves"].values())


def test_chain_survives_publisher_restart(tmp_path):
    params = make_params()
    CheckpointPublisher(tmp_path, quant="none", full_every=100).publish(1, params)
    pub2 = CheckpointPublisher(tmp_path, quant="none", full_every=100)
    params["pos"] = params["pos"] * 3
    a2 = pub2.publish(2, params)
    assert a2.name == "publish-000001"  # sequence resumed, not restarted
    m2 = json.loads((a2 / MANIFEST).read_text())
    assert m2["base"]["name"] == "publish-000000"
    got, _, _ = resolve_chain(a2)
    np.testing.assert_array_equal(got["pos"], params["pos"])


# -------------------------------------------------------------- integrity


def test_corrupted_payload_is_named(tmp_path):
    art = CheckpointPublisher(tmp_path, quant="none").publish(1, make_params())
    pay = art / PAYLOAD
    raw = bytearray(pay.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    pay.write_bytes(bytes(raw))
    with pytest.raises(PublishIntegrityError, match="sha256 mismatch"):
        verify_artifact(art)


def test_torn_payload_is_named(tmp_path):
    art = CheckpointPublisher(tmp_path, quant="none").publish(1, make_params())
    pay = art / PAYLOAD
    pay.write_bytes(pay.read_bytes()[:-10])
    with pytest.raises(PublishIntegrityError, match="torn payload"):
        verify_artifact(art)


def test_missing_base_breaks_the_chain_by_name(tmp_path):
    import shutil

    pub = CheckpointPublisher(tmp_path, quant="none", full_every=100)
    params = make_params()
    pub.publish(1, params)
    params["pos"] = params["pos"] * 2
    a2 = pub.publish(2, params)
    shutil.rmtree(tmp_path / "publish-000000")
    with pytest.raises(PublishIntegrityError, match="publish-000000.*missing"):
        resolve_chain(a2)


def test_swapped_base_fingerprint_is_caught(tmp_path):
    import shutil

    pub = CheckpointPublisher(tmp_path, quant="none", full_every=100)
    params = make_params()
    pub.publish(1, params)
    params["pos"] = params["pos"] * 2
    a2 = pub.publish(2, params)
    # an attacker (or a re-run) replaces the base with a different tree
    shutil.rmtree(tmp_path / "publish-000000")
    other = make_params(scale=7.0)
    CheckpointPublisher(tmp_path / "other", quant="none").publish(9, other)
    (tmp_path / "other" / "publish-000000").rename(tmp_path / "publish-000000")
    with pytest.raises(PublishIntegrityError, match="fingerprint mismatch"):
        resolve_chain(a2)


def test_fault_corrupt_ships_a_poisoned_artifact_verification_catches(
    tmp_path, inject
):
    inject("publish.export:corrupt(4)")
    art = CheckpointPublisher(tmp_path, quant="none").publish(1, make_params())
    # the atomic commit happened — but the manifest seals the pre-fault
    # digests, so verification refuses the bytes before any restore
    with pytest.raises(PublishIntegrityError):
        verify_artifact(art)


def test_fault_raise_is_a_torn_export_nothing_ships(tmp_path, inject):
    inject("publish.export:raise@n<1")
    pub = CheckpointPublisher(tmp_path, quant="none")
    with pytest.raises(OSError):
        pub.publish(1, make_params())
    assert latest_artifact(tmp_path) is None
    # the site fires per-invocation: the retry (next checkpoint) succeeds
    art = pub.publish(2, make_params())
    verify_artifact(art)


# ------------------------------------------------------------------ gates


def run_engine_with_publisher(tmp_path, *, dispatch=None, emit=None, **kw):
    """A 8-step engine with a minimal checkpoint saver + the publisher."""
    params = {"w": {"kernel": np.ones((4, 4), np.float32)}}

    def _dispatch(state, batch, step):
        return state, {"loss": 1.0}

    eng = RunEngine(
        training_steps=8,
        log_interval=2,
        eval_interval=4,
        next_batch=lambda s: s,
        dispatch=dispatch or _dispatch,
        fetch=lambda ms: ms,
    )
    eng.state = type("S", (), {"params": params, "batch_stats": None})()
    log = []
    pub = CheckpointPublisher(
        tmp_path, quant="none", emit=emit or (lambda t, **f: log.append((t, f))), **kw
    )
    pub.register(eng)
    return eng, pub, log


def test_gate_passes_on_clean_windows(tmp_path):
    eng, pub, log = run_engine_with_publisher(tmp_path)
    eng.run(eng.state)
    assert [f["step"] for f in events_of(log, "publish")] == [4, 8]
    assert events_of(log, "publish_skipped") == []
    # billing: the publish tenant appears in the journal
    usage = events_of(log, "tenant_usage")
    assert usage and all(u["tenant"] == "publish" for u in usage)


def test_gate_skips_bad_step_windows(tmp_path):
    def dispatch(state, batch, step):
        return state, {"loss": float("nan") if step == 3 else 1.0}

    eng, pub, log = run_engine_with_publisher(tmp_path, dispatch=dispatch)

    # the train loop's log-window hook computes bad_steps; emulate it
    def classify(e, win):
        win.bad_steps = [
            s for s, m in win.fetched if not np.isfinite(m["loss"])
        ]

    eng._on_log_window.insert(0, classify)
    eng.run(eng.state)
    skipped = events_of(log, "publish_skipped")
    assert [(f["step"], f["reason"]) for f in skipped] == [(4, "bad_steps")]
    assert [f["step"] for f in events_of(log, "publish")] == [8]


def test_gate_skips_after_rollback(tmp_path):
    eng, pub, log = run_engine_with_publisher(tmp_path)
    rolled = []

    def window(e, win):
        if win.step == 2 and not rolled:
            e.request_rollback()

    def restore(e, step, win):
        rolled.append(step)
        return 0

    eng.on_log_window(window)
    eng.on_rollback(restore)
    eng.run(eng.state)
    skipped = events_of(log, "publish_skipped")
    assert skipped and skipped[0]["reason"] == "rollback"


def test_gate_min_interval(tmp_path):
    eng, pub, log = run_engine_with_publisher(tmp_path, min_interval_steps=8)
    eng.run(eng.state)
    assert [f["step"] for f in events_of(log, "publish")] == [4]
    assert [(f["step"], f["reason"]) for f in events_of(log, "publish_skipped")] == [
        (8, "min_interval")
    ]


def test_gate_metric_floor(tmp_path):
    eng, pub, log = run_engine_with_publisher(
        tmp_path, metric_key="val/loss", metric_floor=0.5, metric_sense="below"
    )
    eng.on_eval(lambda e, s, st: {"val/loss": 0.9 if s == 4 else 0.1})
    eng.run(eng.state)
    assert [(f["step"], f["reason"]) for f in events_of(log, "publish_skipped")] == [
        (4, "metric_floor")
    ]
    assert [f["step"] for f in events_of(log, "publish")] == [8]


def test_gate_metric_missing(tmp_path):
    eng, pub, log = run_engine_with_publisher(tmp_path, metric_key="val/loss")
    eng.run(eng.state)  # no eval hook registered → no metrics at all
    assert all(
        f["reason"] == "metric_missing" for f in events_of(log, "publish_skipped")
    )


def test_export_failure_never_kills_training(tmp_path, inject):
    inject("publish.export:raise")
    eng, pub, log = run_engine_with_publisher(tmp_path)
    eng.run(eng.state)  # must complete despite every export failing
    assert eng.exit_reason == "completed"
    failed = events_of(log, "publish_failed")
    assert [f["step"] for f in failed] == [4, 8]
    assert "OSError" in failed[0]["error"]


def test_preemption_checkpoint_never_publishes(tmp_path):
    eng, pub, log = run_engine_with_publisher(tmp_path)
    eng.on_log_window(
        lambda e, win: e.request_stop() if win.step == 2 else None
    )
    eng.run(eng.state)
    assert events_of(log, "publish") == []
    assert events_of(log, "publish_skipped") == []


# ----------------------------------------------------------------- doctor


def test_publish_doctor_ok_and_broken(tmp_path, capsys):
    import sys

    sys.path.insert(0, "tools")
    try:
        import publish_doctor
    finally:
        sys.path.pop(0)

    pub = CheckpointPublisher(tmp_path, quant="none", full_every=100)
    params = make_params()
    pub.publish(1, params)
    params["pos"] = params["pos"] * 2
    a2 = pub.publish(2, params)
    assert publish_doctor.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "OK: 2 artifact(s) verified" in out

    pay = a2 / PAYLOAD
    raw = bytearray(pay.read_bytes())
    raw[0] ^= 0xFF
    pay.write_bytes(bytes(raw))
    assert publish_doctor.main([str(tmp_path)]) == 2
    out = capsys.readouterr().out
    assert "publish-000001" in out and "BROKEN" in out

    assert publish_doctor.main([str(tmp_path / "empty")]) == 2


def test_cost_doctor_surfaces_publish_tenant(tmp_path):
    import sys

    sys.path.insert(0, "tools")
    try:
        import cost_doctor
    finally:
        sys.path.pop(0)

    # a training journal: tenant_usage ledger rows only, no request rows —
    # exactly what a publishing train run leaves behind
    jdir = tmp_path / "journal"
    jdir.mkdir()
    rec = {
        "ts": 1.0,
        "seq": 0,
        "type": "tenant_usage",
        "tenant": "publish",
        "class": "batch",
        "requests": 2,
        "device_s": 0.25,
        "flops": 0.0,
        "waste_device_s": 0.0,
        "window_device_s": 0.25,
        "share": 1.0,
    }
    (jdir / "journal-00000.jsonl").write_text(json.dumps(rec) + "\n")
    out = tmp_path / "chargeback.md"
    assert cost_doctor.main([str(jdir), "--out", str(out)]) == 0
    report = out.read_text()
    assert "| publish | batch | 2 |" in report
    assert "ledger-only tenant(s)" in report
    assert "top consumer: **publish**" in report


@pytest.mark.slow
def test_engine_cold_start_from_publish_artifact(tmp_path):
    """``InferenceEngine(ckpt=<publish artifact>)`` must resolve the chain
    and serve the published weights — a pool cold-starts straight from the
    newest publish, bit-identical to hot-swapping the same artifact in.

    slow: two engine builds + feature compiles; the CI publish-loop smoke
    drives the same cold-start path end to end."""
    from pathlib import Path

    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.infer import InferenceEngine

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    cfg = load_config(
        recipe,
        [
            "model.overrides.dtype=float32",
            "model.dec_layers=1",
            "model.dec_dim=32",
            "model.dec_heads=2",
            "model.dec_dtype=float32",
        ],
    )
    imgs = np.random.RandomState(7).randint(0, 256, (2, 32, 32, 3)).astype(np.uint8)
    a = InferenceEngine(cfg, warm_cache=False)
    ref = np.asarray(a.features(imgs))
    params = a._tasks["features"]["variables"]["params"]

    art = CheckpointPublisher(tmp_path, quant="none", full_every=100).publish(
        1, params
    )
    b = InferenceEngine(cfg, ckpt=str(art), warm_cache=False)
    np.testing.assert_array_equal(np.asarray(b.features(imgs)), ref)
