"""bench.py failure-path contract: the round artifact must be a parseable
JSON line (with an ``error`` field) even when the accelerator backend is
down or the process would otherwise hang — round 2 lost its perf evidence
to an unguarded crash (``BENCH_r02.json`` rc=1, ``parsed: null``).

These tests run bench.py as a real subprocess, the way the driver does,
with ``BENCH_FORCE_PROBE_FAIL`` standing in for the wedged/absent tunnel.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run_bench(extra_env: dict, timeout: float = 60) -> tuple[int, str, str]:
    env = dict(os.environ)
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        env=env,
        cwd=str(REPO),
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    return proc.returncode, proc.stdout, proc.stderr


def _last_json_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    assert lines, f"bench printed nothing: {stdout!r}"
    return json.loads(lines[-1])


def test_permanent_backend_failure_emits_json_error():
    t0 = time.monotonic()
    rc, out, err = _run_bench({"BENCH_FORCE_PROBE_FAIL": "permanent"})
    assert rc == 1, (out, err)
    line = _last_json_line(out)
    assert "error" in line and "permanently unusable" in line["error"]
    assert line["value"] is None  # nothing was measured
    assert "metric" in line and "unit" in line
    # permanent failures must not burn the retry budget
    assert time.monotonic() - t0 < 30


def test_transient_backend_failure_retries_then_emits_json_error():
    rc, out, err = _run_bench(
        {
            "BENCH_FORCE_PROBE_FAIL": "transient",
            "BENCH_ACQUIRE_DEADLINE": "3",
        }
    )
    assert rc == 1, (out, err)
    line = _last_json_line(out)
    assert "error" in line and "unavailable" in line["error"].lower()
    # the retry loop announced itself on stderr at least once
    assert "retrying" in err or "still unavailable" in line["error"]


def test_watchdog_converts_hang_into_json_error():
    # transient failures + an effectively-infinite acquire deadline would
    # spin past any driver budget; the watchdog must cut in first with a
    # machine-readable line instead of an opaque rc=124
    rc, out, err = _run_bench(
        {
            "BENCH_FORCE_PROBE_FAIL": "transient",
            "BENCH_ACQUIRE_DEADLINE": "600",
            "BENCH_WATCHDOG_SECS": "3",
        },
        timeout=45,
    )
    assert rc == 1, (out, err)
    line = _last_json_line(out)
    assert "error" in line and "watchdog" in line["error"]


@pytest.mark.slow
def test_bench_success_path_on_cpu():
    """The bench machinery end-to-end on the CPU backend (smoke model, no
    baseline leg): one valid JSON success line, rc 0. Keeps the success
    path from rotting between on-chip rounds."""
    from jumbo_mae_tpu_tpu.utils.procenv import cpu_subprocess_env, host_cache_dir

    env = cpu_subprocess_env(1, compile_cache=host_cache_dir(REPO))
    env.update(
        {
            "BENCH_MODEL": "vit_t16",
            "BENCH_ITERS": "2",
            "BENCH_SKIP_BASELINE": "1",
        }
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        env=env,
        cwd=str(REPO),
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout[-800:] + proc.stderr[-800:]
    line = _last_json_line(proc.stdout)
    assert "error" not in line
    assert line["metric"].startswith("mae_vit_t16")
    assert line["value"] and line["value"] > 0
    assert line["ms_step_bf16"] > 0


def test_entry_guard_raises_instead_of_hanging():
    """entry() reuses bench's hang-proof backend acquisition: on an
    unusable backend it must raise a clear error (never block the driver's
    compile check). The forced-failure hook covers both its branches."""
    env = dict(os.environ)
    env["BENCH_FORCE_PROBE_FAIL"] = "permanent"
    proc = subprocess.run(
        [sys.executable, "-c", "import __graft_entry__ as g; g.entry()"],
        env=env,
        cwd=str(REPO),
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode != 0
    assert "permanently unusable" in proc.stderr


def test_leg_config_f32_leg_is_env_proof():
    """The f32 leg is the FIXED reference-style baseline: neither BENCH_*
    env knobs nor the spec's bf16-leg defaults may leak into it — otherwise
    a sweep silently re-tunes its own baseline and the ratio is garbage."""
    import bench

    hostile_env = {
        "BENCH_REMAT": "0",
        "BENCH_REMAT_POLICY": "dots_no_batch",
        "BENCH_GATHER_IMPL": "onehot",
        "BENCH_MU_DTYPE": "bfloat16",
        "BENCH_NU_DTYPE": "bfloat16",
        "BENCH_DEC_REMAT_POLICY": "dots",
    }
    hostile_env["BENCH_ATTN_IMPL"] = "flash"
    got = bench.leg_config("vit_h14", "float32", env=hostile_env)
    assert got == dict(
        grad_ckpt=True,  # spec remat (f32@32 needs dots to fit 16 GB)
        remat_policy="dots",
        gather_impl="take",
        dec_remat=None,
        mu_dtype=None,
        nu_dtype=None,
        param_dtype=None,
        attn_impl="auto",
        dec_heads=0,
    )


def test_leg_config_bf16_defaults_and_overrides():
    import bench

    # vit_h14 bf16 leg, clean env: the baked-in A/B winners
    got = bench.leg_config("vit_h14", "bfloat16", env={})
    assert got == dict(
        grad_ckpt=False,
        remat_policy="dots",  # policy string only matters when ckpt is on
        gather_impl="onehot",
        dec_remat=None,
        mu_dtype="bfloat16",
        nu_dtype="bfloat16",
        param_dtype=None,
        attn_impl="auto",
        dec_heads=0,
    )
    # param storage dtype: env-only knob until an A/B promotes a default;
    # "float32" is the explicit off-spelling and normalizes to None
    got = bench.leg_config("vit_h14", "bfloat16", env={"BENCH_PARAM_DTYPE": "bfloat16"})
    assert got["param_dtype"] == "bfloat16"
    got = bench.leg_config("vit_h14", "bfloat16", env={"BENCH_PARAM_DTYPE": "float32"})
    assert got["param_dtype"] is None
    # malformed BENCH_REMAT dies with a clear message, not a ValueError
    import pytest as _pytest

    with _pytest.raises(SystemExit, match="BENCH_REMAT"):
        bench.leg_config("vit_h14", "bfloat16", env={"BENCH_REMAT": "true"})
    # explicit off-spellings flip every default-on knob back off
    off = {
        "BENCH_REMAT": "1",
        "BENCH_GATHER_IMPL": "take",
        "BENCH_MU_DTYPE": "float32",
        "BENCH_NU_DTYPE": "float32",
    }
    got = bench.leg_config("vit_h14", "bfloat16", env=off)
    assert got["grad_ckpt"] is True
    assert got["gather_impl"] == "take"
    assert got["mu_dtype"] == "float32"
    assert got["nu_dtype"] == "float32"
    # vit_l16 bf16 leg: bf16 moments, but take gather (onehot loses on L)
    got = bench.leg_config("vit_l16", "bfloat16", env={})
    assert got["gather_impl"] == "take"
    assert got["mu_dtype"] == "bfloat16"
    assert got["grad_ckpt"] is False
    # BENCH_REMAT_POLICY alone must turn remat ON for a remat=False model
    got = bench.leg_config("vit_l16", "bfloat16", env={"BENCH_REMAT_POLICY": "dots"})
    assert got["grad_ckpt"] is True and got["remat_policy"] == "dots"


def test_measure_leg_retries_transient_tunnel_faults(monkeypatch):
    """A remote compile served over the tunnel can drop mid-body (seen
    live: 'remote_compile: read body: ...'); the leg must retry on a fresh
    build instead of turning the round artifact into an error line. OOMs
    (RESOURCE_EXHAUSTED) must NOT retry."""
    import bench

    calls = {"n": 0}

    def flaky_build(dtype, batch_size, model):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError(
                "INTERNAL: http://127.0.0.1:8103/remote_compile: read body:"
                " response body closed before all bytes were read"
            )
        return "step", "state", "batch", 0.0

    monkeypatch.setattr(bench, "build_step", flaky_build)
    monkeypatch.setattr(
        bench, "time_steps", lambda *a, **k: 0.123
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._measure_leg("float32", 8, "vit_t16", 2) == 0.123
    assert calls["n"] == 2

    def oom_build(dtype, batch_size, model):
        calls["n"] += 1
        raise RuntimeError("RESOURCE_EXHAUSTED: Allocation type: HLO temp")

    calls["n"] = 0
    monkeypatch.setattr(bench, "build_step", oom_build)
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        bench._measure_leg("bfloat16", 8, "vit_t16", 2)
    assert calls["n"] == 1  # no retry on a permanent failure
