"""Weight-only int8 PTQ contracts (infer/quant.py).

What serving relies on:

- the quantization RULE is structural — matmul/projection kernels become
  int8 + per-output-channel f32 scales; embeddings, norms, biases, tokens
  stay f32 untouched;
- the round-trip error is bounded by construction (|w - deq| ≤ scale/2);
- engine parity vs the f32 reference is inside the published tolerance
  (feature cosine / logits top-1) — the same check bench_infer and CI run;
- padded-bucket inference stays provably inert THROUGH the quantized
  executables: dequant is per-channel (row-independent), so the padding
  bit-identity contract survives quantization unchanged.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.config import load_config
from jumbo_mae_tpu_tpu.infer import InferenceEngine, QuantizedTensor, parity_report
from jumbo_mae_tpu_tpu.infer.quant import (
    FEATURE_COSINE_MIN,
    TOP1_AGREEMENT_MIN,
    dequantize_tree,
    feature_cosine,
    is_quantized,
    quantize_params,
    quantize_tensor,
    top1_agreement,
)

RECIPE_OVERRIDES = [
    "model.overrides.dtype=float32",
    "model.dec_layers=1",
    "model.dec_dim=32",
    "model.dec_heads=2",
    "model.dec_dtype=float32",
]


def tiny_cfg(extra=()):
    from pathlib import Path

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    return load_config(recipe, RECIPE_OVERRIDES + list(extra))


def _images(n, size=32, seed=0):
    return (
        np.random.RandomState(seed).randint(0, 256, (n, size, size, 3))
    ).astype(np.uint8)


# ------------------------------------------------------------ tensor level


def test_quantize_tensor_round_trip_bound():
    w = np.random.RandomState(0).randn(64, 32).astype(np.float32)
    qt = quantize_tensor(jnp.asarray(w), axes=(0,))
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 32)
    deq = np.asarray(qt.dequantize(jnp.float32))
    # symmetric rounding: per-element error is at most half a step
    err = np.abs(deq - w)
    bound = np.asarray(qt.scale) / 2 + 1e-7
    assert (err <= bound).all()


def test_quantize_tensor_zero_channel_safe():
    """An all-zero output channel must not divide by zero — scale falls back
    to 1.0 and the channel round-trips to exact zeros."""
    w = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    w[:, 2] = 0.0
    qt = quantize_tensor(jnp.asarray(w), axes=(0,))
    assert float(np.asarray(qt.scale)[0, 2]) == 1.0
    deq = np.asarray(qt.dequantize(jnp.float32))
    np.testing.assert_array_equal(deq[:, 2], 0.0)


def test_quantized_tensor_is_jit_argument():
    """QuantizedTensor is a registered pytree — it crosses the jit boundary
    as an argument (the property the warmcache-shared executables need)."""
    w = np.random.RandomState(2).randn(8, 8).astype(np.float32)
    qt = quantize_tensor(jnp.asarray(w), axes=(0,))

    @jax.jit
    def apply(qt, x):
        return x @ qt.dequantize(jnp.float32)

    x = np.random.RandomState(3).randn(2, 8).astype(np.float32)
    out = np.asarray(apply(qt, x))
    ref = x @ np.asarray(qt.dequantize(jnp.float32))
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


# -------------------------------------------------------------- tree level


def test_quantize_params_rule_is_structural():
    """Only ndim≥2 'kernel' leaves quantize; everything else passes through
    untouched (same object class, same values)."""
    eng = InferenceEngine(tiny_cfg(), max_batch=2, warm_cache=False)
    params = eng._task("features")["variables"]["params"]
    qtree, report = quantize_params(params)

    flat = jax.tree_util.tree_flatten_with_path(
        qtree, is_leaf=is_quantized
    )[0]
    n_q = n_f = 0
    for path, leaf in flat:
        names = [getattr(k, "key", getattr(k, "name", "")) for k in path]
        if is_quantized(leaf):
            n_q += 1
            assert names[-1] == "kernel" and leaf.q.ndim >= 2
            # per-output-channel: the scale broadcasts over reduction axes
            # only — the last axis (or last two for fused qkv heads) keeps
            # its full extent
            assert leaf.scale.shape[-1] == leaf.q.shape[-1]
        else:
            n_f += 1
            assert jnp.asarray(leaf).dtype != jnp.int8
    assert n_q == report["n_quantized"] and n_f == report["n_kept"]
    assert n_q > 0 and report["compression"] > 3.0

    # dequantize_tree reproduces the full tree structure with f32 leaves
    deq = dequantize_tree(qtree)
    assert jax.tree_util.tree_structure(deq) == jax.tree_util.tree_structure(
        params
    )


def test_quantize_params_idempotent_on_quantized_tree():
    """Running the quantizer over an already-quantized tree must refuse
    rather than double-quantize."""
    eng = InferenceEngine(tiny_cfg(), max_batch=2, warm_cache=False)
    params = eng._task("features")["variables"]["params"]
    qtree, _ = quantize_params(params)
    with pytest.raises(ValueError, match="already quantized"):
        quantize_params(qtree)


# ------------------------------------------------------------ engine level


def test_engine_int8_parity_within_tolerance():
    """The published parity contract: pooled-feature cosine ≥
    FEATURE_COSINE_MIN and logits top-1 agreement ≥ TOP1_AGREEMENT_MIN
    against the f32 engine on the same checkpoint."""
    cfg = tiny_cfg()
    ref = InferenceEngine(cfg, max_batch=4, labels=13, warm_cache=False)
    q = InferenceEngine(
        cfg, max_batch=4, labels=13, quant="int8", warm_cache=False
    )
    imgs = _images(8, seed=6)

    feats = parity_report(ref, q, imgs, task="features", pool="cls")
    assert feats["within_tolerance"], feats
    assert feats["cosine_min"] >= FEATURE_COSINE_MIN

    logits = parity_report(ref, q, imgs, task="logits")
    assert logits["within_tolerance"], logits
    assert logits["top1_agreement"] >= TOP1_AGREEMENT_MIN


def test_engine_int8_padding_inert():
    """The padded-bucket bit-identity contract holds through the int8
    executables: dequant is per-channel, so pad rows cannot leak."""
    eng = InferenceEngine(
        tiny_cfg(), max_batch=8, quant="int8", warm_cache=False
    )
    imgs8 = _images(8, seed=7)
    f5 = eng.features(imgs8[:5])  # bucket 8, rows 5..7 zero-padded
    f8 = eng.features(imgs8)  # same bucket, rows 5..7 real images
    np.testing.assert_array_equal(f5, f8[:5])


def test_engine_rejects_unknown_quant():
    with pytest.raises(ValueError, match="quant"):
        InferenceEngine(tiny_cfg(), max_batch=2, quant="int4")


# --------------------------------------------------------------- reporting


def test_parity_helpers():
    a = np.eye(4, dtype=np.float32)
    assert feature_cosine(a, a).min() >= 1.0 - 1e-12
    logits = np.asarray([[0.1, 0.9], [0.8, 0.2]], np.float32)
    flipped = logits[:, ::-1]
    assert top1_agreement(logits, logits) == 1.0
    assert top1_agreement(logits, flipped) == 0.0
