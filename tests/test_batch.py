"""Resumable offline batch inference contracts (batch/ + cli/batch.py).

The exactly-once story this suite proves, each property in isolation and
then end-to-end under injected and real (SIGKILL) faults:

- **part files are torn-tail-tolerant**: a frame cut anywhere scans back
  to the durable prefix, and the prefix is the resume cursor;
- **leases expire and steal**: a worker that dies mid-shard stops
  renewing; a survivor steals the shard (journaled with ``stolen_from``)
  and the fencing token keeps a slow zombie from ever writing again;
- **byte-identical output**: a job killed by the ``batch.worker`` fault,
  a torn partial, a graceful preemption stop, or a SIGKILL'd process
  produces — after resume — a manifest byte-identical to a fault-free
  control run (no sample dropped, duplicated, or reordered);
- **quarantined shards don't wedge the job**: the store giving up on a
  shard excludes it from the manifest and the job still completes;
- **the doctor is honest**: exit 0 only when the manifest reconciles
  100% against the bytes on disk, exit 2 on corruption/orphans/absence,
  and its report names the worker a stolen lease was rescued from.
"""

import json
import os
import signal
import struct
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from pathlib import Path

import numpy as np
import pytest

from jumbo_mae_tpu_tpu import faults
from jumbo_mae_tpu_tpu.batch import (
    BatchJobRunner,
    JobSpec,
    LeaseTable,
    part_stem,
    read_manifest,
    scan_part,
)
from jumbo_mae_tpu_tpu.batch.partfile import (
    MAGIC,
    append_record,
    encode_record,
    iter_records,
)
from jumbo_mae_tpu_tpu.data.tario import QUARANTINE, RetryPolicy, write_tar_samples
from jumbo_mae_tpu_tpu.obs.journal import fsync_dir, read_journal
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry
from jumbo_mae_tpu_tpu.serve.admission import (
    AdmissionController,
    TenantPressureError,
    parse_tenants,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture
def fault_plan():
    yield faults.install_plan
    faults.clear_plan()
    QUARANTINE.clear()


# ----------------------------------------------------------- stub harness


def stub_submit(image, *, task=None, deadline_ms=None, meta=None, tenant=None):
    """Deterministic ContinuousScheduler.submit stand-in: the result
    depends only on the input bytes (the byte-identity tests need it)."""
    f = Future()
    f.set_result({"sum": int(image.astype(np.int64).sum())})
    return f


def make_shards(root: Path, n_shards=3, n_samples=8) -> list[str]:
    urls = []
    for i in range(n_shards):
        url = str(root / f"shard{i}.tar")
        write_tar_samples(
            url,
            [
                {"__key__": f"s{i}-{j}", "bin": bytes([i, j] * 16)}
                for j in range(n_samples)
            ],
        )
        urls.append(url)
    return urls


def run_job(shards, out, **kw) -> tuple[dict, BatchJobRunner]:
    spec_kw = dict(workers=2, submit_window=3, lease_s=0.3)
    spec_kw.update(kw)
    spec = JobSpec(shards=tuple(shards), output_dir=str(out), **spec_kw)
    runner = BatchJobRunner(spec, stub_submit, registry=MetricsRegistry())
    return runner.run(), runner


# -------------------------------------------------------------- partfile


class TestPartFile:
    def test_scan_truncates_torn_tail_not_prefix(self, tmp_path):
        p = tmp_path / "x.partial"
        with open(p, "wb") as f:
            for i in range(5):
                append_record(f, encode_record(f"k{i}", {"v": i}))
        whole = p.stat().st_size
        n, good = scan_part(p)
        assert (n, good) == (5, whole)
        # tear the last frame mid-payload: prefix survives exactly
        with open(p, "r+b") as f:
            f.truncate(whole - 3)
        n, good = scan_part(p)
        assert n == 4
        assert [r["key"] for r in iter_records(p)][:4] == ["k0", "k1", "k2", "k3"]
        # corrupt a payload byte (digest mismatch): scan stops there
        data = bytearray(p.read_bytes())
        data[good - 2] ^= 0xFF
        p.write_bytes(bytes(data))
        assert scan_part(p)[0] == 3

    def test_bad_magic_stops_scan(self, tmp_path):
        p = tmp_path / "x.partial"
        with open(p, "wb") as f:
            append_record(f, encode_record("k", {"v": 1}))
            f.write(b"GARBAGEGARBAGE")
        assert scan_part(p)[0] == 1
        assert MAGIC == b"JMB1" and struct.calcsize("<4sI8s") == 16

    def test_encode_is_deterministic_and_numpy_safe(self):
        out = {"b": np.float32(1.5), "a": np.arange(3), "flag": np.bool_(True)}
        assert encode_record("k", out) == encode_record("k", dict(reversed(out.items())))


# ---------------------------------------------------------------- leases


class TestLeaseTable:
    def test_claim_order_renew_complete(self):
        t = LeaseTable(["a", "b"], lease_s=10.0)
        s1, l1 = t.claim("w0")
        assert s1 == "a" and t.holds("a", "w0", l1)
        assert t.renew("a", "w0", l1)
        assert t.claim("w1") == ("b", 2)
        assert t.complete("a", "w0", l1)
        assert t.counts() == {"pending": 0, "leased": 1, "done": 1}

    def test_expiry_steal_fences_old_holder(self, tmp_path):
        now = [0.0]
        journal_events = []

        class J:
            def event(self, etype, **f):
                journal_events.append({"type": etype, **f})

        t = LeaseTable(["a"], lease_s=1.0, clock=lambda: now[0], journal=J())
        _, l1 = t.claim("w0")
        assert t.claim("w1") is None  # still held
        now[0] = 2.0  # past expiry
        s2, l2 = t.claim("w1")
        assert (s2, t.steals) == ("a", 1)
        # the zombie is fenced: holds/renew/complete all refuse it
        assert not t.holds("a", "w0", l1)
        assert not t.renew("a", "w0", l1)
        assert not t.complete("a", "w0", l1)
        assert t.complete("a", "w1", l2)
        steal = [e for e in journal_events if e.get("stolen_from")]
        assert steal and steal[0]["stolen_from"] == "w0"

    def test_release_makes_claimable_immediately(self):
        t = LeaseTable(["a"], lease_s=100.0)
        _, l1 = t.claim("w0")
        assert t.release("a", "w0", l1)
        assert t.claim("w1") is not None


# ------------------------------------------------------------- job runner


def test_job_completes_and_rerun_is_noop(tmp_path):
    shards = make_shards(tmp_path)
    s, _ = run_job(shards, tmp_path / "out")
    assert s["complete"] and s["total_samples"] == 24
    m = read_manifest(tmp_path / "out" / "manifest.json")
    assert [e["shard"] for e in m["shards"]] == shards  # spec order
    s2, _ = run_job(shards, tmp_path / "out")
    assert s2["already_complete"]
    events = [e["type"] for e in read_journal(tmp_path / "out" / "journal")]
    assert {"job_start", "job_lease", "job_shard_done", "job_complete"} <= set(events)


def test_worker_killed_by_fault_steal_and_byte_identical(tmp_path, fault_plan):
    """The tentpole proof: ``batch.worker`` kills w0 mid-shard WITHOUT a
    lease release; w1 steals after expiry, resumes from the durable
    partial, and the manifest is byte-identical to the fault-free run."""
    shards = make_shards(tmp_path)
    run_job(shards, tmp_path / "ctrl")
    fault_plan("batch.worker:raise@key~w0,n<1")
    s, _ = run_job(shards, tmp_path / "flt")
    faults.clear_plan()
    assert s["complete"] and s["lease_steals"] >= 1
    a = (tmp_path / "ctrl" / "manifest.json").read_bytes()
    b = (tmp_path / "flt" / "manifest.json").read_bytes()
    assert a == b
    leases = [
        e for e in read_journal(tmp_path / "flt" / "journal")
        if e["type"] == "job_lease" and e.get("stolen_from")
    ]
    assert leases and leases[0]["stolen_from"] == "w0"


def test_torn_partial_resumes_byte_identical(tmp_path):
    """Kill simulated at the filesystem: a .partial with a torn tail (the
    exact artifact of SIGKILL mid-append) resumes to identical bytes."""
    shards = make_shards(tmp_path, n_shards=1, n_samples=10)
    run_job(shards, tmp_path / "ctrl", workers=1)
    # build the torn state: run once, demote the part to a torn partial
    run_job(shards, tmp_path / "flt", workers=1)
    parts = tmp_path / "flt" / "parts"
    part = next(parts.glob("*.part"))
    partial = parts / (part.name[: -len(".part")] + ".partial")
    part.rename(partial)
    with open(partial, "r+b") as f:
        f.truncate(partial.stat().st_size - 5)  # torn final frame
    (tmp_path / "flt" / "manifest.json").unlink()
    s, runner = run_job(shards, tmp_path / "flt", workers=1)
    assert s["complete"]
    assert (tmp_path / "ctrl" / "manifest.json").read_bytes() == (
        tmp_path / "flt" / "manifest.json"
    ).read_bytes()
    # the resume skipped the durable prefix instead of recomputing it
    assert runner._m_resumed.value >= 9


def test_graceful_stop_resumes_to_identical_manifest(tmp_path):
    """request_stop() (the SIGTERM path) mid-run: leases released, job
    exits incomplete-but-resumable; the next run finishes byte-identically."""
    shards = make_shards(tmp_path, n_shards=4, n_samples=12)
    run_job(shards, tmp_path / "ctrl")

    slow = threading.Event()

    def slow_submit(image, **kw):
        if not slow.is_set():
            time.sleep(0.01)
        return stub_submit(image, **kw)

    spec = JobSpec(
        shards=tuple(shards), output_dir=str(tmp_path / "flt"),
        workers=1, submit_window=2, lease_s=5.0,
    )
    runner = BatchJobRunner(spec, slow_submit, registry=MetricsRegistry())
    t = threading.Thread(target=runner.run)
    t.start()
    time.sleep(0.08)
    runner.request_stop()
    t.join(timeout=10.0)
    assert not t.is_alive()
    assert read_manifest(tmp_path / "flt" / "manifest.json") is None
    slow.set()
    s, _ = run_job(shards, tmp_path / "flt")
    assert s["complete"]
    assert (tmp_path / "ctrl" / "manifest.json").read_bytes() == (
        tmp_path / "flt" / "manifest.json"
    ).read_bytes()


def test_quarantined_shard_excluded_job_completes(tmp_path, fault_plan):
    shards = make_shards(tmp_path, n_shards=2)
    bad = str(tmp_path / "bad.tar")
    Path(bad).write_bytes(b"not a tar at all")
    s, _ = run_job(
        [shards[0], bad, shards[1]], tmp_path / "out",
        retry=RetryPolicy(attempts=2, backoff_s=0.01),
    )
    assert s["complete"]
    assert s["quarantined"] == [bad]
    m = read_manifest(tmp_path / "out" / "manifest.json")
    assert [e["shard"] for e in m["shards"]] == shards  # bad one excluded
    done = [
        e for e in read_journal(tmp_path / "out" / "journal")
        if e["type"] == "job_shard_done" and e.get("status") == "quarantined"
    ]
    assert len(done) == 1 and done[0]["shard"] == bad


def test_job_spec_validation(tmp_path):
    with pytest.raises(ValueError):
        JobSpec(shards=(), output_dir=str(tmp_path))
    with pytest.raises(ValueError):
        JobSpec(shards=("a", "a"), output_dir=str(tmp_path))
    with pytest.raises(ValueError):
        JobSpec(shards=("a",), output_dir=str(tmp_path), workers=0)
    assert part_stem("gs://b/p/train-0001.tar") != part_stem("gs://b/q/train-0001.tar")


# --------------------------------------------------- SIGKILL (subprocess)


def _batch_cmd(shards, out, per_item_ms) -> list[str]:
    return [
        sys.executable, "-m", "jumbo_mae_tpu_tpu.cli.batch",
        *shards, "--out", str(out), "--workers", "2",
        "--lease-s", "1.0", "--service-per-item-ms", str(per_item_ms),
    ]


def _subproc_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("GRAFT_FAULTS", None)
    return env


def test_sigkill_midrun_restart_manifest_byte_identical(tmp_path):
    """The whole-process chaos leg: SIGKILL the job (no handler can run,
    torn partials and leaked leases on disk), restart the same command,
    and the manifest must match a never-killed control run byte for byte."""
    shards = make_shards(tmp_path, n_shards=3, n_samples=10)
    env = _subproc_env()
    ctrl = subprocess.run(
        _batch_cmd(shards, tmp_path / "ctrl", 0.2), env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert ctrl.returncode == 0, ctrl.stdout[-2000:] + ctrl.stderr[-2000:]

    # leg B: slow service so the kill lands mid-shard with work in flight
    proc = subprocess.Popen(
        _batch_cmd(shards, tmp_path / "flt", 30.0), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + 60.0
    parts = tmp_path / "flt" / "parts"
    while time.monotonic() < deadline:
        if parts.is_dir() and any(
            p.stat().st_size > 0 for p in parts.glob("*.partial")
        ):
            break  # durable progress exists; the kill now tears real state
        time.sleep(0.05)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)
    assert read_manifest(tmp_path / "flt" / "manifest.json") is None

    resumed = subprocess.run(
        _batch_cmd(shards, tmp_path / "flt", 0.2), env=env,
        capture_output=True, text=True, timeout=120,
    )
    assert resumed.returncode == 0, resumed.stdout[-2000:] + resumed.stderr[-2000:]
    summary = json.loads(resumed.stdout.strip().splitlines()[-1])
    assert summary["complete"]
    assert (tmp_path / "ctrl" / "manifest.json").read_bytes() == (
        tmp_path / "flt" / "manifest.json"
    ).read_bytes()


# ---------------------------------------------------------------- doctor


def test_batch_doctor_exit_codes_and_steal_attribution(tmp_path, fault_plan, capsys):
    import tools.batch_doctor as doctor

    shards = make_shards(tmp_path)
    fault_plan("batch.worker:raise@key~w0,n<1")
    run_job(shards, tmp_path / "job")
    faults.clear_plan()
    assert doctor.main([str(tmp_path / "job")]) == 0
    report = capsys.readouterr().out
    assert "stolen from `w0`" in report
    assert "reconciles 100%" in report

    # corrupt one byte of a part: reconciliation must fail
    part = next((tmp_path / "job" / "parts").glob("*.part"))
    data = bytearray(part.read_bytes())
    data[-1] ^= 0xFF
    part.write_bytes(bytes(data))
    assert doctor.main([str(tmp_path / "job")]) == 2

    # no manifest at all (incomplete or wrong dir)
    assert doctor.main([str(tmp_path / "nowhere")]) == 2


def test_batch_doctor_flags_orphan_part(tmp_path):
    import tools.batch_doctor as doctor

    shards = make_shards(tmp_path, n_shards=1)
    run_job(shards, tmp_path / "job")
    orphan = tmp_path / "job" / "parts" / "stray-deadbeef.part"
    orphan.write_bytes(b"")
    assert doctor.main([str(tmp_path / "job")]) == 2


# ----------------------------------------------------- admission blocking


def test_admit_wait_rides_out_transient_pressure():
    pressures = [1.0, 1.0, 0.0]
    adm = AdmissionController(
        parse_tenants("job=batch"),
        pressure_fn=lambda: pressures.pop(0) if pressures else 0.0,
    )
    sp = adm.admit_wait("job", timeout_s=5.0)
    assert sp.tclass == "batch"
    # permanent pressure: the last typed shed surfaces at the deadline
    adm2 = AdmissionController(
        parse_tenants("job=batch"), pressure_fn=lambda: 1.0
    )
    with pytest.raises(TenantPressureError):
        adm2.admit_wait("job", timeout_s=0.1)


# ------------------------------------------------------------- durability


def test_fsync_dir_tolerates_missing_and_plain_paths(tmp_path):
    fsync_dir(tmp_path)  # real directory: must not raise
    fsync_dir(tmp_path / "does-not-exist")  # vanished: silently tolerated
