"""Persistent warm-start cache contracts (infer/warmcache.py).

The failure the design exists to prevent is documented in conftest: this
jaxlib SIGABRTs the whole process deserializing a truncated XLA:CPU cache
entry, and jax's internal cache writes non-atomically. So the properties
under test are exactly the crash-safety ones:

- a corrupt entry (truncated, flipped bytes, bad magic, garbage pickle) is
  a MISS plus a quarantine move — never an exception, never a crash;
- writes are atomic and uniquely-tmp'd — concurrent writers cannot leave a
  partial entry, and no ``.tmp`` debris survives;
- a second engine against a populated cache serves real traffic with ZERO
  compiles (the restart contract CI asserts end-to-end via the probe CLI).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.config import load_config
from jumbo_mae_tpu_tpu.infer import InferenceEngine, WarmCache
from jumbo_mae_tpu_tpu.infer.warmcache import MAGIC, entry_name, fingerprint

RECIPE_OVERRIDES = [
    "model.overrides.dtype=float32",
    "model.dec_layers=1",
    "model.dec_dim=32",
    "model.dec_heads=2",
    "model.dec_dtype=float32",
]


def tiny_cfg(extra=()):
    from pathlib import Path

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    return load_config(recipe, RECIPE_OVERRIDES + list(extra))


def _images(n, size=32, seed=0):
    return (
        np.random.RandomState(seed).randint(0, 256, (n, size, size, 3))
    ).astype(np.uint8)


def _tiny_executable(mul=2.0):
    fn = jax.jit(lambda x: x * mul)
    return fn.lower(jnp.zeros((2, 3), jnp.float32)).compile()


# ------------------------------------------------------------- key schema


def test_fingerprint_stable_and_sensitive():
    spec = {"dim": 192, "depth": 12, "backend": "cpu"}
    assert fingerprint(spec) == fingerprint(dict(reversed(spec.items())))
    assert fingerprint(spec) != fingerprint({**spec, "dim": 384})


def test_entry_name_schema_and_sanitization():
    name = entry_name("abc123", "features:cls", 8, "float32", None)
    assert name == "abc123-features_cls-b8-float32-none.exe"
    assert entry_name("f", "logits", 4, "bfloat16", "int8").endswith(
        "-b4-bfloat16-int8.exe"
    )
    # path metacharacters cannot escape the cache dir
    hostile = entry_name("../..", "a/b\\c", 1, "f32 ", "x\n")
    assert "/" not in hostile and "\\" not in hostile and "\n" not in hostile


# ---------------------------------------------------------- put/get cycle


def test_put_get_round_trip(tmp_path):
    wc = WarmCache(tmp_path)
    ex = _tiny_executable(3.0)
    assert wc.put("t-b2-f32-none.exe", ex)
    loaded = wc.get("t-b2-f32-none.exe")
    assert loaded is not None
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.testing.assert_array_equal(np.asarray(loaded(x)), x * 3.0)
    assert wc.stats()["entries"] == 1 and wc.stats()["hits"] == 1
    # no tmp debris from the atomic write
    assert not list(tmp_path.glob("*.tmp")) and not list(tmp_path.glob(".*"))


def test_missing_entry_is_a_miss(tmp_path):
    wc = WarmCache(tmp_path)
    assert wc.get("nope.exe") is None
    assert wc.stats()["misses"] == 1 and wc.stats()["quarantined"] == 0


@pytest.mark.parametrize(
    "corruption",
    ["truncate", "flip_payload", "bad_magic", "garbage"],
)
def test_corrupt_entry_quarantined_not_fatal(tmp_path, corruption):
    """Every corruption mode degrades to a miss + quarantine move; nothing
    reaches XLA's deserializer (the SIGABRT path) without a digest match."""
    wc = WarmCache(tmp_path)
    name = "t-b2-f32-none.exe"
    assert wc.put(name, _tiny_executable())
    path = tmp_path / name
    blob = bytearray(path.read_bytes())
    if corruption == "truncate":
        blob = blob[: len(blob) // 2]
    elif corruption == "flip_payload":
        blob[-1] ^= 0xFF
    elif corruption == "bad_magic":
        blob[:4] = b"XXXX"
    else:
        blob = bytearray(b"not a cache entry")
    path.write_bytes(bytes(blob))

    assert wc.get(name) is None
    assert wc.stats()["quarantined"] == 1
    assert not path.exists()  # moved aside, not retried forever
    assert len(list((tmp_path / "quarantine").iterdir())) == 1
    # the slot is writable again after quarantine
    assert wc.put(name, _tiny_executable())
    assert wc.get(name) is not None


def test_quarantine_bounded_by_count_and_age_at_claim_time(tmp_path):
    """Regression: ``quarantine/`` must not grow without bound. Claiming
    the cache directory prunes entries past the count cap (newest kept)
    and past the age cap, and each new quarantine re-enforces the bound."""
    import os
    import time

    from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry

    qdir = tmp_path / "quarantine"
    qdir.mkdir(parents=True)
    now = time.time()
    for i in range(3):  # fresh, staggered mtimes: fresh2 newest
        p = qdir / f"fresh{i}.exe"
        p.write_bytes(b"x")
        os.utime(p, (now - 30 + i, now - 30 + i))
    for i in range(2):  # well past the age cap
        p = qdir / f"ancient{i}.exe"
        p.write_bytes(b"x")
        os.utime(p, (now - 7200, now - 7200))

    reg = MetricsRegistry()
    wc = WarmCache(
        tmp_path, registry=reg, quarantine_keep=2, quarantine_max_age_s=3600.0
    )
    # 2 newest fresh entries survive; fresh0 loses the count cap, both
    # ancient entries lose the age cap
    assert sorted(p.name for p in qdir.iterdir()) == ["fresh1.exe", "fresh2.exe"]
    assert wc.quarantine_pruned == 3
    assert wc.stats()["quarantine_pruned"] == 3
    assert reg.counter("infer_warmcache_quarantine_pruned_total", "x").value == 3

    # a new quarantine event re-enforces the cap immediately
    name = "t-b2-f32-none.exe"
    assert wc.put(name, _tiny_executable())
    (tmp_path / name).write_bytes(b"not a cache entry")
    assert wc.get(name) is None
    assert wc.stats()["quarantined"] == 1
    assert len(list(qdir.iterdir())) == 2  # still at quarantine_keep
    assert wc.stats()["quarantine_pruned"] == 4


def test_digest_guards_payload_not_just_length(tmp_path):
    """A same-length bit flip inside the payload must fail the sha256 check
    (length checks alone would hand XLA corrupt bytes)."""
    wc = WarmCache(tmp_path)
    name = "t-b1-f32-none.exe"
    wc.put(name, _tiny_executable())
    path = tmp_path / name
    blob = bytearray(path.read_bytes())
    mid = len(MAGIC) + 32 + (len(blob) - len(MAGIC) - 32) // 2
    blob[mid] ^= 0x01
    path.write_bytes(bytes(blob))
    assert wc.get(name) is None and wc.stats()["quarantined"] == 1


def test_concurrent_writers_last_writer_wins(tmp_path):
    """N threads publishing the same entry name race safely: afterwards the
    entry is complete and loadable and no tmp files remain."""
    wc = WarmCache(tmp_path)
    ex = _tiny_executable(5.0)
    errs = []

    def writer():
        try:
            assert wc.put("race-b2-f32-none.exe", ex)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=writer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    loaded = wc.get("race-b2-f32-none.exe")
    x = np.ones((2, 3), np.float32)
    np.testing.assert_array_equal(np.asarray(loaded(x)), x * 5.0)
    assert not list(tmp_path.glob("*.tmp")) and not list(tmp_path.glob(".*tmp"))


# ------------------------------------------------------------ engine level


def test_restarted_engine_compiles_nothing(tmp_path):
    """The restart contract: engine A compiles + publishes; engine B (same
    config, same cache dir — a restarted replica) warms up and serves real
    traffic with zero compiles, and its outputs match A's bit-for-bit."""
    cfg = tiny_cfg()
    imgs = _images(5, seed=30)

    a = InferenceEngine(cfg, max_batch=4, warm_cache=str(tmp_path))
    n_cold = a.warmup(("features",))
    assert n_cold == 3  # buckets 1, 2, 4
    ref = a.features(imgs)
    assert a.warmcache.stats()["puts"] == n_cold

    b = InferenceEngine(cfg, max_batch=4, warm_cache=str(tmp_path))
    n_warm = b.warmup(("features",))
    assert n_warm == 0
    assert sum(b.warm_hits.values()) == n_cold
    out = b.features(imgs)
    assert sum(b.compile_counts.values()) == 0  # hot path compiled nothing
    np.testing.assert_array_equal(out, ref)


def test_quant_and_dtype_key_separate_entries(tmp_path):
    """int8 and f32 engines sharing one cache dir must not collide — quant
    mode is part of the entry key."""
    cfg = tiny_cfg()
    f32 = InferenceEngine(cfg, max_batch=2, warm_cache=str(tmp_path))
    f32.warmup(("features",), buckets=(2,))
    q = InferenceEngine(
        cfg, max_batch=2, quant="int8", warm_cache=str(tmp_path)
    )
    n = q.warmup(("features",), buckets=(2,))
    assert n == 1  # the f32 entry was not (wrongly) reused
    names = {p.name for p in tmp_path.glob("*.exe")}
    assert len(names) == 2
    assert any("-int8" in n for n in names)
    assert any("-none" in n for n in names)


def test_corrupt_cache_entry_degrades_to_compile(tmp_path):
    """An engine pointed at a poisoned cache recompiles and republishes —
    serving survives, the bad entry lands in quarantine/."""
    cfg = tiny_cfg()
    a = InferenceEngine(cfg, max_batch=2, warm_cache=str(tmp_path))
    a.warmup(("features",), buckets=(2,))
    entry = next(tmp_path.glob("*.exe"))
    entry.write_bytes(b"JWC1" + b"\0" * 40)  # valid-looking, corrupt

    b = InferenceEngine(cfg, max_batch=2, warm_cache=str(tmp_path))
    assert b.warmup(("features",), buckets=(2,)) == 1  # recompiled
    assert b.warmcache.stats()["quarantined"] == 1
    out = b.features(_images(2, seed=31))
    assert np.isfinite(out).all()


def test_main_dir_bounded_by_cache_max_bytes(tmp_path):
    """``cache_max_bytes`` is LRU by mtime over the main dir: the oldest
    entry AND its metadata sidecar go first, the disk gauge/stats track."""
    import os
    import time

    from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry

    # measure one entry+sidecar footprint against an unbounded cache
    probe = WarmCache(tmp_path / "probe", registry=MetricsRegistry())
    n1, n2 = "t-b1-f32-none.exe", "t-b2-f32-none.exe"
    assert probe.put(n1, _tiny_executable())
    entry_bytes = probe.disk_bytes()
    assert entry_bytes > 0

    reg = MetricsRegistry()
    cap = int(entry_bytes * 1.5)  # room for one resident entry, not two
    main = tmp_path / "main"
    wc = WarmCache(main, registry=reg, cache_max_bytes=cap)
    assert wc.put(n1, _tiny_executable())
    # backdate the first entry so LRU-by-mtime picks it deterministically
    old = time.time() - 3600
    for p in (main / n1, main / f"{n1}.meta.json"):
        os.utime(p, (old, old))
    assert wc.put(n2, _tiny_executable())

    assert sorted(p.name for p in main.iterdir()) == [n2, f"{n2}.meta.json"]
    st = wc.stats()
    assert st["main_pruned"] == 1 and st["entries"] == 1
    assert 0 < st["disk_bytes"] <= cap
    assert wc.get(n1) is None and wc.get(n2) is not None  # survivor serves
    assert reg.gauge("infer_warmcache_disk_bytes", "x").value == st["disk_bytes"]
    assert reg.counter("infer_warmcache_events_total", "x", labels=("event",)
                       ).labels("pruned").value == 1
