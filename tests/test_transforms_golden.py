"""Golden-array augmentation parity (VERDICT r3 item 3).

timm/torchvision are not installed in this sandbox, so parity is pinned
against what they are built FROM, plus committed golden fixtures:

- the color ops (numpy/cv2 re-implementations in ``data/transforms.py``)
  are compared against **PIL ImageEnhance directly** — the exact backend
  timm and PIL-mode torchvision delegate to
  (``/root/reference/src/dataset.py:41-53`` composes timm transforms over
  PIL images);
- crop/erase geometry is compared against **independent transcriptions of
  the torchvision algorithms** (RandomResizedCrop.get_params,
  RandomErasing.get_params) driven by the same rng stream — both sides
  consume draws in torchvision's documented order, so any deviation in
  sampling order, rounding, or bounds shows up as a pixel diff;
- every RandAugment/AugMix op and color op is additionally pinned to
  committed golden arrays (``tests/golden/transforms_golden.npz``) so a
  PIL upgrade or a port edit that shifts pixel semantics fails loudly
  rather than silently changing the training distribution.
"""

from __future__ import annotations

import math
from pathlib import Path

import numpy as np
import pytest
from PIL import Image, ImageEnhance

from jumbo_mae_tpu_tpu.data.transforms import (
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    random_erasing,
    random_resized_crop,
    resize,
    simple_resize_crop,
)

GOLDEN = Path(__file__).parent / "golden" / "transforms_golden.npz"

FACTORS = [0.1, 0.35, 0.7, 1.0, 1.31, 1.9]


def _img(seed=0, size=(24, 32)):
    return np.random.RandomState(seed).randint(
        0, 256, (*size, 3), dtype=np.uint8
    )


# --------------------------------------------------------------------------
# Color ops vs PIL ImageEnhance — the backend timm/torchvision-PIL wrap
# --------------------------------------------------------------------------


@pytest.mark.parametrize("factor", FACTORS)
def test_brightness_matches_pil(factor):
    img = _img(1)
    ours = adjust_brightness(img, factor)
    pil = np.asarray(ImageEnhance.Brightness(Image.fromarray(img)).enhance(factor))
    assert np.abs(ours.astype(int) - pil.astype(int)).max() <= 1


@pytest.mark.parametrize("factor", FACTORS)
def test_contrast_matches_pil(factor):
    img = _img(2)
    ours = adjust_contrast(img, factor)
    pil = np.asarray(ImageEnhance.Contrast(Image.fromarray(img)).enhance(factor))
    assert np.abs(ours.astype(int) - pil.astype(int)).max() <= 2


@pytest.mark.parametrize("factor", FACTORS)
def test_saturation_matches_pil(factor):
    img = _img(3)
    ours = adjust_saturation(img, factor)
    pil = np.asarray(ImageEnhance.Color(Image.fromarray(img)).enhance(factor))
    assert np.abs(ours.astype(int) - pil.astype(int)).max() <= 2


@pytest.mark.parametrize("delta", [-0.4, -0.1, 0.1, 0.25, 0.5])
def test_hue_tracks_float_reference(delta):
    """cv2's H is quantized to 180 steps (PIL-HSV uses 256) — exact parity
    is impossible across backends, so pin against an exact float colorsys
    rotation with a quantization-sized tolerance."""
    import colorsys

    pytest.importorskip("cv2")
    img = _img(4, size=(12, 12))
    ours = adjust_hue(img, delta).astype(float)
    ref = np.empty_like(ours)
    for y in range(img.shape[0]):
        for x in range(img.shape[1]):
            r, g, b = img[y, x] / 255.0
            h, s, v = colorsys.rgb_to_hsv(r, g, b)
            r2, g2, b2 = colorsys.hsv_to_rgb((h + delta) % 1.0, s, v)
            ref[y, x] = np.array([r2, g2, b2]) * 255.0
    # tolerance: one cv2 hue bin is 2 degrees; saturated pixels can move a
    # few RGB units per bin
    assert np.abs(ours - ref).mean() < 6.0
    assert np.abs(ours - ref).max() < 40.0


# --------------------------------------------------------------------------
# Geometry vs independent transcriptions of the torchvision algorithms
# --------------------------------------------------------------------------


def _tv_rrc_params(rng, h, w, scale, ratio):
    """Transcription of torchvision RandomResizedCrop.get_params: 10
    attempts of (uniform area, log-uniform aspect), w from *aspect, h from
    /aspect, top-left uniform; else aspect-clamped center fallback."""
    area = h * w
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(*log_ratio))
        cw = int(round(math.sqrt(target_area * aspect)))
        ch = int(round(math.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            top = int(rng.integers(0, h - ch + 1))
            left = int(rng.integers(0, w - cw + 1))
            return top, left, ch, cw
    in_ratio = w / h
    if in_ratio < ratio[0]:
        cw, ch = w, int(round(w / ratio[0]))
    elif in_ratio > ratio[1]:
        ch, cw = h, int(round(h * ratio[1]))
    else:
        cw, ch = w, h
    return (h - ch) // 2, (w - cw) // 2, ch, cw


@pytest.mark.parametrize(
    "shape,scale",
    [
        ((64, 48), (0.2, 1.0)),
        ((48, 64), (0.2, 1.0)),
        ((100, 20), (0.9, 1.0)),  # extreme aspect → fallback path fires
        ((20, 100), (0.9, 1.0)),
        ((32, 32), (0.08, 1.0)),
    ],
)
def test_random_resized_crop_geometry_matches_torchvision_algorithm(shape, scale):
    """Run the port and the transcription from identical rng states over
    many seeds; outputs must be pixel-identical (same draws, same rounding,
    same fallback)."""
    img = np.arange(shape[0] * shape[1] * 3, dtype=np.uint8).reshape(
        (*shape, 3)
    )  # position-coded pixels: geometry differences cannot cancel
    for seed in range(50):
        ours = random_resized_crop(
            np.random.default_rng(seed), img, 16, scale=scale
        )
        top, left, ch, cw = _tv_rrc_params(
            np.random.default_rng(seed), *shape, scale, (3 / 4, 4 / 3)
        )
        want = resize(img[top : top + ch, left : left + cw], (16, 16), "bicubic")
        np.testing.assert_array_equal(ours, want, err_msg=f"seed {seed}")


def _tv_erasing_params(rng, h, w, scale, ratio):
    """Transcription of torchvision RandomErasing.get_params (h from
    *aspect, w from /aspect, strict < bounds) with value='random'."""
    area = h * w
    log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
    for _ in range(10):
        target = area * rng.uniform(scale[0], scale[1])
        aspect = math.exp(rng.uniform(*log_ratio))
        eh = int(round(math.sqrt(target * aspect)))
        ew = int(round(math.sqrt(target / aspect)))
        if 0 < eh < h and 0 < ew < w:
            top = int(rng.integers(0, h - eh + 1))
            left = int(rng.integers(0, w - ew + 1))
            noise = rng.integers(0, 256, (eh, ew, 3), dtype=np.uint8)
            return top, left, eh, ew, noise
    return None


def test_random_erasing_geometry_matches_torchvision_algorithm():
    img = _img(7, size=(40, 40))
    hits = 0
    for seed in range(50):
        ours = random_erasing(np.random.default_rng(seed), img, p=1.0)
        rng = np.random.default_rng(seed)
        assert rng.random() < 1.0  # the p-gate draw our port consumes first
        params = _tv_erasing_params(rng, 40, 40, (0.02, 1 / 3), (0.3, 3.3))
        if params is None:
            np.testing.assert_array_equal(ours, img)
            continue
        top, left, eh, ew, noise = params
        want = img.copy()
        want[top : top + eh, left : left + ew] = noise
        np.testing.assert_array_equal(ours, want, err_msg=f"seed {seed}")
        hits += 1
    assert hits > 40  # the geometry path, not the give-up path, was tested


def test_simple_resize_crop_reflect_padding_semantics():
    """SRC = Resize(short side) + reflect-pad 4 + RandomCrop — the reflect
    border must equal torchvision's padding_mode='reflect' (edge-exclusive
    mirror), pinned here via np.pad semantics on a position-coded image."""
    img = np.arange(16 * 16 * 3, dtype=np.uint8).reshape(16, 16, 3)
    out = simple_resize_crop(np.random.default_rng(0), img, 16)
    assert out.shape == (16, 16, 3)
    padded = np.pad(img, ((4, 4), (4, 4), (0, 0)), mode="reflect")
    # edge-exclusive mirror: row -1 of the pad equals row 1 of the image
    np.testing.assert_array_equal(padded[3, 4:-4], img[1])
    np.testing.assert_array_equal(padded[-4, 4:-4], img[-2])
    # the crop is a window of the padded plane
    found = any(
        np.array_equal(out, padded[t : t + 16, l : l + 16])
        for t in range(9)
        for l in range(9)
    )
    assert found


# --------------------------------------------------------------------------
# Committed golden fixtures: pin every op's exact pixels
# --------------------------------------------------------------------------


def golden_cases():
    """(name, fn) pairs — deterministic op applications over a fixed image."""
    from jumbo_mae_tpu_tpu.data import randaugment as ra

    img = _img(11, size=(24, 24))
    pil = Image.fromarray(img)
    cases = {}
    for name, fn in ra._OPS.items():
        rng = np.random.default_rng(99)
        args = ra._level_args(name, rng, 9.0, False)
        cases[f"ra_{name}"] = np.asarray(fn(pil, *args))
        rng = np.random.default_rng(100)
        args = ra._level_args(name, rng, 5.0, True)
        cases[f"ra_inc_{name}"] = np.asarray(fn(pil, *args))
    for f in (0.35, 1.9):
        cases[f"brightness_{f}"] = adjust_brightness(img, f)
        cases[f"contrast_{f}"] = adjust_contrast(img, f)
        cases[f"saturation_{f}"] = adjust_saturation(img, f)
    cases["hue_0.25"] = adjust_hue(img, 0.25)
    cases["rrc_seed3"] = random_resized_crop(
        np.random.default_rng(3), img, 16
    )
    cases["erase_seed5"] = random_erasing(
        np.random.default_rng(5), img, p=1.0
    )
    cases["randaugment_m9"] = ra.RandAugment(magnitude=9.0, mstd=0.5)(
        np.random.default_rng(21), img
    )
    cases["augmix_m3"] = ra.AugMix(magnitude=3.0)(
        np.random.default_rng(22), img
    )
    cases["autoaugment"] = ra.AutoAugment()(np.random.default_rng(23), img)
    return cases


def test_ops_match_committed_golden_arrays():
    assert GOLDEN.exists(), (
        f"{GOLDEN} missing — regenerate with "
        "python tools/gen_transform_golden.py"
    )
    stored = np.load(GOLDEN)
    cases = golden_cases()
    assert sorted(stored.files) == sorted(cases), (
        sorted(set(stored.files) ^ set(cases))
    )
    for name, arr in cases.items():
        np.testing.assert_array_equal(
            arr, stored[name], err_msg=f"golden drift in {name}"
        )
