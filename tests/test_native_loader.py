"""Native C++ tar reader vs the pure-Python tario path."""

import io

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.data import iter_shards_samples, write_tar_samples
from jumbo_mae_tpu_tpu.data.native import NativeShardReader, available

pytestmark = pytest.mark.skipif(not available(), reason="no native toolchain")


def _png_bytes(rng, h=8, w=8):
    from PIL import Image

    img = Image.fromarray(rng.integers(0, 256, (h, w, 3), dtype=np.uint8), "RGB")
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    root = tmp_path_factory.mktemp("native_shards")
    rng = np.random.default_rng(0)
    urls = []
    idx = 0
    for s in range(3):
        samples = []
        for _ in range(5):
            samples.append(
                {
                    "__key__": f"k{idx:04d}",
                    "png": _png_bytes(rng),
                    "cls": str(idx).encode(),
                }
            )
            idx += 1
        url = str(root / f"shard-{s}.tar")
        write_tar_samples(url, samples)
        urls.append(url)
    return urls


def test_native_reads_all_samples(shards):
    with NativeShardReader(shards, threads=2) as reader:
        got = sorted(label for _, label in reader)
    assert got == list(range(15))


def test_native_payloads_match_python(shards):
    python_side = {}
    for s in iter_shards_samples(shards):
        python_side[int(s["cls"])] = s["png"]
    native_side = {}
    with NativeShardReader(shards, threads=1) as reader:
        for payload, label in reader:
            native_side[label] = payload
    assert native_side == python_side


def test_native_skips_corrupt_shard(shards, tmp_path):
    bad = tmp_path / "bad.tar"
    bad.write_bytes(b"garbage" * 100)
    with NativeShardReader([*shards, str(bad)], threads=2) as reader:
        labels = sorted(label for _, label in reader)
    assert labels == list(range(15))


def test_native_early_close(shards):
    reader = NativeShardReader(shards, threads=2, loop=True)
    for _ in range(3):
        next(reader)
    reader.close()  # must not deadlock with producer threads blocked on push


def test_native_pipe_url(shards):
    with NativeShardReader([f"pipe:cat {shards[0]}"], threads=1) as reader:
        labels = sorted(label for _, label in reader)
    assert labels == list(range(5))


def test_native_train_loader_end_to_end(shards):
    from jumbo_mae_tpu_tpu.data import DataConfig, TrainLoader

    cfg = DataConfig(
        train_shards=list(shards),
        image_size=16,
        use_native=True,
        native_io_threads=2,
        decode_threads=2,
        shuffle_buffer=4,
        seed=3,
    )
    loader = TrainLoader(cfg, batch_size=6)
    for _ in range(3):
        batch = next(loader)
        assert batch["images"].shape == (6, 16, 16, 3)
        assert batch["images"].dtype == np.uint8
