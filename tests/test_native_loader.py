"""Native C++ tar reader vs the pure-Python tario path."""

import io
from pathlib import Path

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.data import iter_shards_samples, write_tar_samples
from jumbo_mae_tpu_tpu.data.native import NativeShardReader, available

pytestmark = pytest.mark.skipif(not available(), reason="no native toolchain")


def _png_bytes(rng, h=8, w=8):
    from PIL import Image

    img = Image.fromarray(rng.integers(0, 256, (h, w, 3), dtype=np.uint8), "RGB")
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


@pytest.fixture(scope="module")
def shards(tmp_path_factory):
    root = tmp_path_factory.mktemp("native_shards")
    rng = np.random.default_rng(0)
    urls = []
    idx = 0
    for s in range(3):
        samples = []
        for _ in range(5):
            samples.append(
                {
                    "__key__": f"k{idx:04d}",
                    "png": _png_bytes(rng),
                    "cls": str(idx).encode(),
                }
            )
            idx += 1
        url = str(root / f"shard-{s}.tar")
        write_tar_samples(url, samples)
        urls.append(url)
    return urls


def test_native_reads_all_samples(shards):
    with NativeShardReader(shards, threads=2) as reader:
        got = sorted(label for _, label in reader)
    assert got == list(range(15))


def test_native_payloads_match_python(shards):
    python_side = {}
    for s in iter_shards_samples(shards):
        python_side[int(s["cls"])] = s["png"]
    native_side = {}
    with NativeShardReader(shards, threads=1) as reader:
        for payload, label in reader:
            native_side[label] = payload
    assert native_side == python_side


def test_native_skips_corrupt_shard(shards, tmp_path):
    bad = tmp_path / "bad.tar"
    bad.write_bytes(b"garbage" * 100)
    with NativeShardReader([*shards, str(bad)], threads=2) as reader:
        labels = sorted(label for _, label in reader)
    assert labels == list(range(15))


def test_native_early_close(shards):
    reader = NativeShardReader(shards, threads=2, loop=True)
    for _ in range(3):
        next(reader)
    reader.close()  # must not deadlock with producer threads blocked on push


def test_native_pipe_url(shards):
    with NativeShardReader([f"pipe:cat {shards[0]}"], threads=1) as reader:
        labels = sorted(label for _, label in reader)
    assert labels == list(range(5))


def test_native_train_loader_end_to_end(shards):
    from jumbo_mae_tpu_tpu.data import DataConfig, TrainLoader

    cfg = DataConfig(
        train_shards=list(shards),
        image_size=16,
        use_native=True,
        native_io_threads=2,
        decode_threads=2,
        shuffle_buffer=4,
        seed=3,
    )
    loader = TrainLoader(cfg, batch_size=6)
    for _ in range(3):
        batch = next(loader)
        assert batch["images"].shape == (6, 16, 16, 3)
        assert batch["images"].dtype == np.uint8


def test_native_order_is_deterministic(shards):
    """Two readers over the same shard list + thread count must produce the
    SAME sequence (not just the same set) — per-thread static shard
    ownership + strict round-robin merge in native/tario.cc."""
    def order(threads):
        with NativeShardReader(shards, threads=threads) as reader:
            return [label for _, label in reader]

    a, b = order(2), order(2)
    assert a == b
    assert sorted(a) == list(range(15))
    # and single-thread order is the plain stripe order
    assert order(1) == order(1)


def test_native_loader_sample_exact_resume(shards):
    """Snapshot after 3 batches, rebuild with the cursor: the next batches
    must be bit-identical to an uninterrupted run — the native substrate is
    now a first-class peer of the subprocess-worker path."""
    from jumbo_mae_tpu_tpu.data import DataConfig, TrainLoader

    def mk(cursor=None):
        cfg = DataConfig(
            train_shards=list(shards),
            image_size=16,
            use_native=True,
            native_io_threads=2,
            decode_threads=2,
            shuffle_buffer=4,
            seed=3,
        )
        return TrainLoader(cfg, batch_size=5, cursor=cursor)

    straight = mk()
    uninterrupted = [next(straight) for _ in range(6)]
    straight.close()

    first = mk()
    for _ in range(3):
        next(first)
    snap = first.snapshot()
    first.close()
    assert snap is not None and snap["native_threads"] == 2

    resumed = mk(cursor=snap)
    for want in uninterrupted[3:]:
        got = next(resumed)
        np.testing.assert_array_equal(got["images"], want["images"])
        np.testing.assert_array_equal(got["labels"], want["labels"])
    resumed.close()


def test_native_cursor_substrate_guards(shards):
    from jumbo_mae_tpu_tpu.data import DataConfig, TrainLoader

    base = dict(
        train_shards=list(shards), image_size=16, shuffle_buffer=4, seed=3
    )
    native_cfg = DataConfig(
        **base, use_native=True, native_io_threads=2, decode_threads=2
    )
    python_cursor = {"workers": [[0, 5]], "batches": 1}
    with pytest.raises(ValueError, match="subprocess-worker"):
        TrainLoader(native_cfg, batch_size=5, cursor=python_cursor)

    native_cursor = {"workers": [[0, 5]], "batches": 1, "native_threads": 2}
    with pytest.raises(ValueError, match="native-IO"):
        TrainLoader(DataConfig(**base), batch_size=5, cursor=native_cursor)

    wrong_threads = {"workers": [[0, 5]], "batches": 1, "native_threads": 4}
    with pytest.raises(ValueError, match="native_io_threads"):
        TrainLoader(native_cfg, batch_size=5, cursor=wrong_threads)


@pytest.mark.parametrize("fmt", ["pax", "gnu"])
def test_native_reads_long_member_names(tmp_path, fmt):
    """Names >100 chars ride PAX 'x' (python tarfile default) or GNU 'L'
    headers; the reader must key samples on the REAL path, not the
    truncated ustar field."""
    import tarfile as tf

    rng = np.random.default_rng(1)
    url = tmp_path / f"long-{fmt}.tar"
    tar_format = tf.PAX_FORMAT if fmt == "pax" else tf.GNU_FORMAT
    keys = [("deep/dir/" + "x" * 110 + f"-{i:03d}") for i in range(4)]
    with tf.open(url, "w", format=tar_format) as tar:
        for i, key in enumerate(keys):
            png = _png_bytes(rng)
            for ext, payload in (("png", png), ("cls", str(i).encode())):
                info = tf.TarInfo(f"{key}.{ext}")
                info.size = len(payload)
                import io as _io

                tar.addfile(info, _io.BytesIO(payload))
    with NativeShardReader([str(url)], threads=1) as reader:
        got = sorted(label for _, label in reader)
    # truncated names would collide all members into one bogus sample
    assert got == [0, 1, 2, 3]


def test_native_honors_pax_size_override(tmp_path):
    """A PAX 'size=' record overrides a zeroed ustar size field (how tar
    encodes >=8GiB members); ignoring it would desync the whole stream.
    Crafted by hand — python tarfile only writes the record at 8 GiB."""
    import io as _io
    import tarfile as tf

    rng = np.random.default_rng(2)
    png = _png_bytes(rng)

    def ustar_header(name, size_field, typeflag):
        h = bytearray(512)
        h[0 : len(name)] = name.encode()
        h[100:108] = b"0000644\x00"
        h[108:116] = h[116:124] = b"0000000\x00"
        h[124:136] = size_field
        h[136:148] = b"00000000000\x00"
        h[156] = ord(typeflag)
        h[257:263] = b"ustar\x00"
        h[263:265] = b"00"
        h[148:156] = b" " * 8
        chk = sum(h)
        h[148:156] = f"{chk:06o}\x00 ".encode()
        return bytes(h)

    def pax_member(records):
        body = b""
        for k, v in records:
            rec = f" {k}={v}\n".encode()
            n = len(rec)
            while len(str(n + len(str(n)))) != len(str(n)):
                n += 1
            rec = str(n + len(str(n))).encode() + rec
            body += rec
        pad = (-len(body)) % 512
        return (
            ustar_header("paxhdr", f"{len(body):011o}\x00".encode(), "x")
            + body
            + b"\0" * pad
        )

    raw = _io.BytesIO()
    # member 1: real size ONLY in the PAX record; ustar field says 0
    raw.write(pax_member([("size", str(len(png)))]))
    raw.write(ustar_header("a.png", b"00000000000\x00", "0"))
    raw.write(png + b"\0" * ((-len(png)) % 512))
    raw.write(ustar_header("a.cls", f"{1:011o}\x00".encode(), "0"))
    raw.write(b"7" + b"\0" * 511)
    # member 2: normal, proves the stream stayed aligned past member 1
    raw.write(ustar_header("b.png", f"{len(png):011o}\x00".encode(), "0"))
    raw.write(png + b"\0" * ((-len(png)) % 512))
    raw.write(ustar_header("b.cls", f"{2:011o}\x00".encode(), "0"))
    raw.write(b"2" + b"\0" * 511)
    raw.write(b"\0" * 1024)

    url = tmp_path / "paxsize.tar"
    url.write_bytes(raw.getvalue())
    # sanity: python's tarfile agrees this is a valid archive
    with tf.open(url) as t:
        assert [m.name for m in t if m.isreg()] == [
            "a.png", "a.cls", "b.png", "b.cls",
        ]
    with NativeShardReader([str(url)], threads=1) as reader:
        got = [(label, payload) for payload, label in reader]
    assert [label for label, _ in got] == [7, 2]
    assert all(payload == png for _, payload in got)


def test_native_truncation_fuzz(shards, tmp_path):
    """ignore_and_continue, deterministically: whatever prefix of a shard
    survives, the reader must not crash, must not return corrupt payloads,
    and must yield the same result for the same truncation point."""
    raw = Path(shards[0]).read_bytes()
    python_side = {}
    for s in iter_shards_samples([shards[0]]):
        python_side[int(s["cls"])] = s["png"]
    valid_payloads = set(python_side.values())

    for cut in [0, 100, 511, 512, 513, 1024, len(raw) // 2, len(raw) - 700]:
        url = tmp_path / f"cut{cut}.tar"
        url.write_bytes(raw[:cut])

        def read_all():
            with NativeShardReader([str(url)], threads=1) as reader:
                return [(label, payload) for payload, label in reader]

        a, b = read_all(), read_all()
        assert a == b, f"non-deterministic at cut={cut}"
        for label, payload in a:
            # any sample that DOES come out must carry an intact payload;
            # label -1 is legitimate (its .cls member fell past the cut)
            assert payload in valid_payloads, f"corrupt payload at cut={cut}"
            if label >= 0:
                assert python_side[label] == payload, f"mislabeled at cut={cut}"


def test_native_reader_lifecycle_stress(shards):
    """Many open/iterate-a-bit/close cycles (incl. loop mode with blocked
    producers) must neither deadlock nor crash."""
    for i in range(30):
        reader = NativeShardReader(
            shards, threads=2, queue_capacity=4, loop=(i % 2 == 0)
        )
        for _ in range(i % 5):
            next(reader)
        reader.close()


def test_native_multi_process_striping_disjoint_and_deterministic(shards):
    """Two native-IO processes must stream disjoint shard stripes whose
    union is the dataset, deterministically — same contract the python
    path proves in test_data_pipeline."""
    from jumbo_mae_tpu_tpu.data import DataConfig
    from jumbo_mae_tpu_tpu.data.loader import native_train_stream

    def one_epoch_labels(process_index):
        cfg = DataConfig(
            train_shards=list(shards),
            image_size=16,
            use_native=True,
            native_io_threads=2,
            decode_threads=1,
            shuffle_buffer=2,
            seed=9,
        )
        stream = native_train_stream(
            cfg, process_index=process_index, process_count=2
        )
        # 3 shards split 2 ways -> stripes of 2 and 1 shards (10/5 samples)
        n = 10 if process_index == 0 else 5
        out = [label for _, label in (next(stream) for _ in range(n))]
        stream.close()
        return out

    a0, a1 = one_epoch_labels(0), one_epoch_labels(1)
    assert one_epoch_labels(0) == a0  # deterministic
    assert set(a0).isdisjoint(a1)
    assert sorted(a0 + a1) == list(range(15))
