"""Chaos suite: the resilience layer proven by deterministic fault injection.

Every recovery path the robustness PR added is exercised here through the
seeded fault plan (``faults/inject.py``) — no monkeypatching of internals,
the same hooks a ``GRAFT_FAULTS=`` run uses:

- plan grammar + deterministic selector semantics;
- an injected-NaN train step is SKIPPED on device (params bit-unchanged,
  step advanced, counter bumped) while a clean step still updates;
- K consecutive NaN steps trigger rollback-to-last-checkpoint and the run
  continues to a finite final loss where the unguarded run ends in NaN;
- a shard that fails twice then succeeds yields the identical sample
  sequence as a fault-free read; a permanently failing shard is
  quarantined without killing the epoch;
- an overloaded MicroBatcher sheds with QueueFullError while accepted
  requests stay bounded; deadlines expire queued requests; close() resolves
  every pending future (no caller can hang);
- corrupt/truncated tar streams are counted, not just logged;
- SIGTERM mid-run checkpoints at a step boundary, exits cleanly, and the
  resume continues from that exact step (tier-1, in-process).
"""

import math
import os
import signal
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from jumbo_mae_tpu_tpu import faults
from jumbo_mae_tpu_tpu.config import load_config
from jumbo_mae_tpu_tpu.data.tario import (
    QUARANTINE,
    RetryPolicy,
    iter_shards_samples,
    iter_tar_samples,
    write_tar_samples,
)
from jumbo_mae_tpu_tpu.faults import (
    DivergenceSentinel,
    FaultPlan,
    SentinelConfig,
    fault_point,
)
from jumbo_mae_tpu_tpu.infer.batching import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    ShutdownError,
)
from jumbo_mae_tpu_tpu.obs.metrics import get_registry

RECIPES = Path(__file__).resolve().parent.parent / "recipes"


@pytest.fixture
def fault_plan():
    """Install-and-always-clear: plans are process-global by design."""
    yield faults.install_plan
    faults.clear_plan()
    QUARANTINE.clear()


def counter_value(name: str, *labels) -> float:
    fam = get_registry()._families.get(name)
    if fam is None:
        return 0.0
    child = fam._children.get(tuple(labels))
    return 0.0 if child is None else child.value


# ------------------------------------------------------------ plan grammar


class TestFaultPlan:
    def test_parse_and_selectors(self):
        plan = FaultPlan.parse(
            "data.shard_open:raise(OSError)@n<2;"
            "train.loss:nan@n=4..6;"
            "serve.submit:delay(0.001)@n%3=0;"
            "data.decode:corrupt(4)@key~bad"
        )
        assert plan.sites() == [
            "data.decode", "data.shard_open", "serve.submit", "train.loss",
        ]
        # n<2 → exactly the first two invocations raise
        with pytest.raises(OSError, match="fault injected"):
            plan.fire("data.shard_open", "s0", None)
        with pytest.raises(OSError):
            plan.fire("data.shard_open", "s1", None)
        plan.fire("data.shard_open", "s2", None)  # third call: clean
        # nan at invocations 4..6 only
        vals = [plan.fire("train.loss", None, 1.0) for _ in range(8)]
        assert [math.isnan(v) for v in vals] == [
            False, False, False, False, True, True, True, False,
        ]
        # key~ selector gates corruption on the sample key
        clean = plan.fire("data.decode", "good-sample", b"payload00")
        assert clean == b"payload00"
        dirty = plan.fire("data.decode", "bad-sample", b"payload00")
        assert dirty != b"payload00" and len(dirty) == len(b"payload00")

    def test_key_filter_gates_the_invocation_counter(self):
        # counting selectors index the rule's FILTERED stream: calls from
        # other keys are invisible to it, so `key~r1,n<1` fires on r1's
        # first call even when another key reaches the site first. (The
        # old global counter made such rules race against interleaving —
        # a worker/replica crash plan could silently never fire.)
        plan = FaultPlan.parse("s:raise(RuntimeError)@key~r1,n<1")
        for _ in range(3):  # r0 hammers the site first — doesn't count
            plan.fire("s", "r0", None)
        with pytest.raises(RuntimeError, match="fault injected"):
            plan.fire("s", "r1", None)  # r1's first call still fires
        plan.fire("s", "r1", None)  # r1's second call is clean
        assert plan.counts() == {"s:raise": (2, 1)}

    def test_unknown_site_is_free(self):
        plan = FaultPlan.parse("train.loss:nan")
        assert plan.fire("some.other.site", None, b"x") == b"x"

    def test_seeded_probability_is_deterministic(self):
        # two identically-seeded plans make identical decisions
        a = FaultPlan.parse("seed=7;s:nan@p=0.5")
        b = FaultPlan.parse("seed=7;s:nan@p=0.5")
        seq_a = [math.isnan(a.fire("s", None, 1.0)) for _ in range(32)]
        seq_b = [math.isnan(b.fire("s", None, 1.0)) for _ in range(32)]
        assert seq_a == seq_b
        assert any(seq_a) and not all(seq_a)  # actually Bernoulli, not 0/1

    def test_bad_specs_rejected(self):
        for bad in (
            "siteonly", "s:explode", "s:raise(Exception)", "s:nan@q=3",
            "s:nan@p=2.0",
        ):
            with pytest.raises(ValueError):
                FaultPlan.parse(bad)

    def test_env_and_install_roundtrip(self, fault_plan):
        fault_plan("ckpt.save:raise(RuntimeError)@n=0")
        assert faults.faults_active()
        with pytest.raises(RuntimeError):
            fault_point("ckpt.save", key="1")
        fault_point("ckpt.save", key="2")  # second call clean
        faults.clear_plan()
        assert not faults.faults_active()
        assert fault_point("ckpt.save", data=b"x") == b"x"

    def test_injected_counter(self, fault_plan):
        before = counter_value("faults_injected_total", "x.y", "delay")
        fault_plan("x.y:delay(0.0)")
        fault_point("x.y")
        assert counter_value("faults_injected_total", "x.y", "delay") == before + 1


# ------------------------------------------------- device guard / sentinel


def _tiny_train_setup(guard: bool, steps: int = 20):
    from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
    from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
        make_train_step,
    )

    enc = preset(
        "vit_t16", image_size=32, patch_size=8, mask_ratio=0.75, labels=None,
        dtype="float32",
    )
    module = MAEPretrainModel(
        enc, DecoderConfig(layers=1, dim=32, heads=2, dtype="float32")
    )
    tx = make_optimizer(
        OptimConfig(
            name="adamw", learning_rate=1e-3, lr_scaling="none",
            warmup_steps=2, training_steps=steps,
        ),
        global_batch_size=16,
    )
    batch = {
        "images": np.random.RandomState(0)
        .randint(0, 256, (16, 32, 32, 3))
        .astype(np.uint8)
    }
    mesh = create_mesh(MeshConfig(data=1, fsdp=1))
    state, sharding = create_sharded_state(
        module, tx, batch, mesh, mode="pretrain"
    )
    step = make_train_step(mesh, sharding, mode="pretrain", guard_nonfinite=guard)
    return state, step, batch


def _host_params(state):
    import jax

    return jax.tree_util.tree_map(np.asarray, jax.device_get(state.params))


def _params_equal(a, b) -> bool:
    import jax

    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    return all(np.array_equal(x, y) for x, y in zip(leaves_a, leaves_b))


class TestDeviceGuard:
    # One compiled setup per guard flavor for the whole class — the ~10 s
    # XLA compile is paid once instead of per test. The train step DONATES
    # its input state, so the fixture keeps a pristine host snapshot and
    # hands every caller a fresh device copy via fresh().
    @staticmethod
    def _shared(guard):
        import jax

        state, step, batch = _tiny_train_setup(guard=guard)
        snap = jax.device_get(state)
        return (lambda: jax.device_put(snap)), step, batch

    @pytest.fixture(scope="class")
    def guarded(self):
        return self._shared(guard=True)

    @pytest.fixture(scope="class")
    def unguarded(self):
        return self._shared(guard=False)

    def test_nan_loss_step_is_skipped(self, guarded):
        """Injected NaN loss: params bit-unchanged, step still advances,
        skipped flag raised; the same batch applies cleanly afterwards."""
        fresh, step_fn, batch = guarded
        state = fresh()
        p0 = _host_params(state)
        s0 = int(state.step)

        nan_inject = np.asarray([np.nan, 1.0], np.float32)
        state, metrics = step_fn(state, batch, nan_inject)
        assert float(metrics["skipped"]) == 1.0
        assert int(state.step) == s0 + 1  # data/schedule stay aligned
        assert _params_equal(p0, _host_params(state))
        # raw loss metric stays finite — the injection hit the scaled value
        assert math.isfinite(float(metrics["loss"]))

        state, metrics = step_fn(state, batch)  # clean step: update applies
        assert float(metrics["skipped"]) == 0.0
        assert math.isfinite(float(metrics["grad_norm"]))
        assert not _params_equal(p0, _host_params(state))

    def test_nan_grad_step_is_skipped(self, guarded):
        fresh, step_fn, batch = guarded
        state = fresh()
        p0 = _host_params(state)
        state, metrics = step_fn(
            state, batch, np.asarray([1.0, np.nan], np.float32)
        )
        assert float(metrics["skipped"]) == 1.0
        assert _params_equal(p0, _host_params(state))

    def test_unguarded_nan_poisons_params(self, unguarded):
        """The counterfactual the guard exists for."""
        fresh, step_fn, batch = unguarded
        state = fresh()
        state, _ = step_fn(state, batch, np.asarray([np.nan, 1.0], np.float32))
        import jax

        any_nan = any(
            not np.isfinite(np.asarray(leaf)).all()
            for leaf in jax.tree_util.tree_leaves(_host_params(state))
        )
        assert any_nan

    def test_guard_off_matches_pre_guard_numerics(self, unguarded):
        """inject=None (the default every existing caller uses) multiplies
        by exactly 1.0 — bit-identical to the pre-injection step. Both legs
        start from value-identical initial states, so any difference is the
        injection multiply itself."""
        fresh, step_fn, batch = unguarded
        sa, ma = step_fn(fresh(), batch)
        sb, mb = step_fn(fresh(), batch, np.ones(2, np.float32))
        assert float(ma["loss"]) == float(mb["loss"])
        assert _params_equal(_host_params(sa), _host_params(sb))


class TestHostSentinel:
    def test_streak_and_spike_detection(self):
        s = DivergenceSentinel(
            SentinelConfig(patience=3, spike_factor=5.0, ema_beta=0.5)
        )
        assert not s.observe(1, {"loss": 1.0, "skipped": 0.0})
        assert not s.observe(2, {"loss": 1.1, "skipped": 1.0})
        assert not s.observe(3, {"loss": 1.0, "skipped": 1.0})
        assert s.observe(4, {"loss": 1.0, "skipped": 1.0})  # 3rd in a row
        # a good step resets the streak
        s2 = DivergenceSentinel(SentinelConfig(patience=2, spike_factor=5.0))
        assert not s2.observe(1, {"loss": 1.0, "skipped": 1.0})
        assert not s2.observe(2, {"loss": 1.0, "skipped": 0.0})
        assert not s2.observe(3, {"loss": 1.0, "skipped": 1.0})
        # spikes count as bad steps too
        s3 = DivergenceSentinel(
            SentinelConfig(patience=2, spike_factor=3.0, ema_beta=0.9)
        )
        assert not s3.observe(1, {"loss": 1.0})
        assert not s3.observe(2, {"loss": 50.0})   # spike 1
        assert s3.observe(3, {"loss": 50.0})       # spike 2 → patience

    def test_rollback_budget(self):
        s = DivergenceSentinel(SentinelConfig(max_rollbacks=1))
        s.record_rollback()
        with pytest.raises(faults.DivergenceError, match="diverged"):
            s.record_rollback()


def _smoke_overrides(tmp_path, steps, extra=()):
    return [
        f"run.output_dir={tmp_path}",
        f"run.training_steps={steps}",
        f"optim.training_steps={steps}",
        "run.sanity_eval=false",
        *extra,
    ]


@pytest.mark.slow
def test_rollback_recovers_where_unguarded_diverges(tmp_path, fault_plan):
    """E2E acceptance: NaN injected at steps 5-7. Guarded: the skids are
    skipped, the sentinel rolls back to the step-4 checkpoint, the run
    finishes with a finite loss — AND the incident is fully explainable
    offline: the journal carries the rollback + per-step sentinel verdicts,
    and the flight recorder left a black-box dump (PR 5). Unguarded: params
    are poisoned and the final loss is NaN."""
    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.obs.journal import read_journal

    skipped0 = counter_value("train_steps_skipped_total")
    rollbacks0 = counter_value("train_rollbacks_total")

    plan = "train.loss:nan@n=4..6"  # call n is 0-based → steps 5,6,7
    guarded = train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(
                tmp_path / "guarded",
                12,
                [
                    f"run.faults={plan}",
                    "run.log_interval=1",
                    "run.eval_interval=4",
                    "run.sentinel_patience=3",
                ],
            ),
        )
    )
    assert math.isfinite(guarded["train/loss"])
    assert counter_value("train_steps_skipped_total") - skipped0 >= 3
    assert counter_value("train_rollbacks_total") - rollbacks0 == 1

    # the rollback left a durable journal trail...
    run_dir = tmp_path / "guarded" / "smoke_cpu"
    events = read_journal(run_dir)
    rb = [e for e in events if e["type"] == "rollback"]
    assert len(rb) == 1 and rb[0]["to_step"] == 4
    bad = [e["step"] for e in events if e["type"] == "sentinel_bad_step"]
    assert set(bad) >= {5, 6, 7}  # exact injected steps, durably recorded
    assert events[-1]["type"] == "shutdown"
    # ...and a flight-record black box (dump journaled with its path)
    dumps = sorted(run_dir.glob("flightrec-*-sentinel_rollback.json"))
    assert dumps, "sentinel rollback left no flight-record dump"
    assert any(
        e["type"] == "flight_record" and e["reason"] == "sentinel_rollback"
        for e in events
    )

    faults.clear_plan()
    unguarded = train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(
                tmp_path / "unguarded",
                12,
                [
                    f"run.faults={plan}",
                    "run.sentinel=false",
                    "run.log_interval=1",
                    "run.eval_interval=4",
                ],
            ),
        )
    )
    # the guarded run ends strictly better than the poisoned one
    assert not math.isfinite(unguarded["train/loss"])


# --------------------------------------------------------------- shard I/O


def _make_shards(root: Path, n_shards=3, per_shard=4):
    urls = []
    for s in range(n_shards):
        url = str(root / f"train-{s:04d}.tar")
        write_tar_samples(
            url,
            [
                {
                    "__key__": f"s{s}_{i}",
                    "jpg": bytes([s, i]) * 10,
                    "cls": str(s * per_shard + i).encode(),
                }
                for i in range(per_shard)
            ],
        )
        urls.append(url)
    return urls


class TestShardRetry:
    def test_transient_failure_heals_with_identical_samples(
        self, tmp_path, fault_plan
    ):
        urls = _make_shards(tmp_path)
        baseline = [s["__key__"] for s in iter_shards_samples(urls)]
        retries0 = counter_value("data_shard_retries_total")
        q_before = len(QUARANTINE)

        # first two opens fail (shard 0, attempts 1+2), third succeeds
        fault_plan("data.shard_open:raise(OSError)@n<2")
        policy = RetryPolicy(attempts=3, backoff_s=0.001)
        healed = [s["__key__"] for s in iter_shards_samples(urls, retry=policy)]
        assert healed == baseline  # identical sequence, nothing lost/duped
        assert counter_value("data_shard_retries_total") - retries0 == 2
        assert len(QUARANTINE) == q_before  # healed, never quarantined

    def test_mid_stream_failure_resumes_exactly(self, tmp_path, fault_plan):
        """A failure after some samples were already consumed must not
        duplicate them on the retry pass."""
        urls = _make_shards(tmp_path, n_shards=1, per_shard=6)
        baseline = [s["__key__"] for s in iter_tar_samples(urls[0])]

        calls = {"n": 0}

        # simulate a mid-stream OSError on the first pass only, via a
        # flaky stream wrapper under open_url
        from jumbo_mae_tpu_tpu.data import tario

        orig_open = tario.open_url

        class Flaky:
            def __init__(self, inner):
                self.inner = inner
                self.read_calls = 0

            def read(self, *a):
                self.read_calls += 1
                if calls["n"] == 0 and self.read_calls == 3:
                    calls["n"] += 1
                    raise OSError("simulated mid-stream failure")
                return self.inner.read(*a)

            def close(self):
                self.inner.close()

        from contextlib import contextmanager

        @contextmanager
        def flaky_open(url, mode="rb"):
            with orig_open(url, mode) as s:
                yield Flaky(s) if mode == "rb" else s

        tario.open_url = flaky_open
        try:
            healed = [
                s["__key__"]
                for s in iter_tar_samples(
                    urls[0], retry=RetryPolicy(attempts=3, backoff_s=0.001)
                )
            ]
        finally:
            tario.open_url = orig_open
        assert healed == baseline

    def test_permanent_failure_quarantines_not_kills(
        self, tmp_path, fault_plan
    ):
        urls = _make_shards(tmp_path)
        q0 = counter_value("data_shards_quarantined_total")
        fault_plan("data.shard_open:raise(OSError)@key~train-0001")
        policy = RetryPolicy(attempts=2, backoff_s=0.001)
        got = [s["__key__"] for s in iter_shards_samples(urls, retry=policy)]
        # shard 1's samples are lost; shards 0 and 2 stream fine
        assert got == [f"s0_{i}" for i in range(4)] + [f"s2_{i}" for i in range(4)]
        assert counter_value("data_shards_quarantined_total") - q0 == 1
        snap = QUARANTINE.snapshot()
        assert any("train-0001" in url for url in snap)
        assert all("OSError" in reason for reason in snap.values())

    def test_truncated_shard_counted_and_survives(self, tmp_path, fault_plan):
        urls = _make_shards(tmp_path, n_shards=2)
        whole = Path(urls[0]).read_bytes()
        # cut mid-archive: keep the header+payload of the first member only
        Path(urls[0]).write_bytes(whole[: 512 + 20])
        t0 = counter_value("data_truncated_shards_total")
        got = [
            s["__key__"]
            for s in iter_shards_samples(
                urls, retry=RetryPolicy(attempts=2, backoff_s=0.001)
            )
        ]
        # shard 1 streams in full; truncation was counted (strict re-reads
        # count once per attempt)
        assert [k for k in got if k.startswith("s1")] == [
            f"s1_{i}" for i in range(4)
        ]
        assert counter_value("data_truncated_shards_total") > t0

    def test_loader_stream_with_faulty_shard(self, tmp_path, fault_plan):
        """End to end through train_sample_stream: a transiently-failing
        shard heals invisibly — the batch stream is identical."""
        from jumbo_mae_tpu_tpu.data.loader import DataConfig, train_sample_stream

        root = tmp_path / "shards"
        root.mkdir()
        # real (tiny) jpegs so decode succeeds
        import io as _io

        from PIL import Image

        urls = []
        for s in range(2):
            samples = []
            for i in range(3):
                buf = _io.BytesIO()
                Image.fromarray(
                    np.full((8, 8, 3), 40 * s + i, np.uint8)
                ).save(buf, format="JPEG")
                samples.append(
                    {
                        "__key__": f"s{s}_{i}",
                        "jpg": buf.getvalue(),
                        "cls": str(i).encode(),
                    }
                )
            url = str(root / f"train-{s:04d}.tar")
            write_tar_samples(url, samples)
            urls.append(url)

        cfg = DataConfig(
            train_shards=urls,
            image_size=8,
            crop_mode="none",
            hflip=0.0,
            shuffle_buffer=0,
            workers=0,
            shard_retries=3,
            shard_retry_backoff_s=0.001,
        )
        take = 6

        def first_labels():
            stream = train_sample_stream(cfg)
            out = [label for _, label in (next(stream) for _ in range(take))]
            stream.close()
            return out

        baseline = first_labels()
        fault_plan("data.shard_open:raise(OSError)@n<1")
        healed = first_labels()
        assert healed == baseline


# ------------------------------------------------------------- serving


class TestBoundedServing:
    def test_overload_sheds_and_accepted_stay_bounded(self):
        shed0 = counter_value("infer_requests_shed_total")

        def run_fn(batch):
            time.sleep(0.02)  # ~ a 20ms forward under load
            return batch.sum(axis=(1, 2, 3))

        accepted = []
        shed = 0
        t_submit = {}
        with MicroBatcher(
            run_fn, max_batch=4, max_delay_ms=1.0, max_queue=4
        ) as mb:
            for i in range(60):
                try:
                    fut = mb.submit(np.ones((2, 2, 1)))
                    t_submit[id(fut)] = time.monotonic()
                    accepted.append(fut)
                except QueueFullError:
                    shed += 1
            lat = []
            for fut in accepted:
                assert fut.result(timeout=10) == 4.0
                lat.append(time.monotonic() - t_submit[id(fut)])
        assert shed > 0, "overload must shed, not buffer"
        assert len(accepted) + shed == 60
        assert counter_value("infer_requests_shed_total") - shed0 == shed
        # bounded queue ⇒ bounded wait: every accepted request waits at most
        # ~(max_queue/max_batch + 1) in-flight batches ≈ 60ms; 2s is a very
        # loose bound for a loaded CI box
        assert np.percentile(np.asarray(lat), 99) < 2.0

    def test_deadline_expires_queued_request(self):
        gate = threading.Event()
        expired0 = counter_value("infer_deadline_exceeded_total")

        def run_fn(batch):
            gate.wait(10)
            return batch.sum(axis=(1, 2, 3))

        mb = MicroBatcher(run_fn, max_batch=1, max_delay_ms=1.0)
        try:
            f1 = mb.submit(np.ones((2, 2, 1)))          # occupies run_fn
            time.sleep(0.05)                             # let it start
            f2 = mb.submit(np.ones((2, 2, 1)), deadline_ms=10.0)
            time.sleep(0.05)                             # deadline passes
            gate.set()
            assert f1.result(timeout=10) == 4.0
            with pytest.raises(DeadlineExceededError):
                f2.result(timeout=10)
            assert (
                counter_value("infer_deadline_exceeded_total") - expired0 == 1
            )
        finally:
            gate.set()
            mb.close()

    def test_close_fails_pending_futures(self):
        """Satellite bugfix: close() must resolve every queued future —
        a submit() caller can never block forever."""
        gate = threading.Event()

        def run_fn(batch):
            gate.wait(10)
            return batch.sum(axis=(1, 2, 3))

        mb = MicroBatcher(run_fn, max_batch=1, max_delay_ms=1.0)
        f1 = mb.submit(np.ones((2, 2, 1)))   # in flight, holding run_fn
        time.sleep(0.05)
        f2 = mb.submit(np.ones((2, 2, 1)))   # queued behind it
        closer = threading.Thread(target=mb.close)
        closer.start()
        time.sleep(0.05)
        gate.set()                            # release the in-flight batch
        closer.join(timeout=10)
        assert not closer.is_alive()
        assert f1.result(timeout=1) == 4.0    # flushed batch completed
        with pytest.raises(ShutdownError):
            f2.result(timeout=1)              # pending → failed, not hung
        with pytest.raises(RuntimeError, match="closed"):
            mb.submit(np.ones((2, 2, 1)))

    def test_close_graceful_drain_still_flushes(self):
        """drain=False keeps the old graceful semantics: already-queued
        requests run; nothing hangs either way."""
        done = []

        def run_fn(batch):
            done.append(batch.shape[0])
            return batch.sum(axis=(1, 2, 3))

        mb = MicroBatcher(run_fn, max_batch=8, max_delay_ms=50.0)
        futs = [mb.submit(np.ones((2, 2, 1))) for _ in range(3)]
        mb.close(drain=False)
        assert [f.result(timeout=5) for f in futs] == [4.0, 4.0, 4.0]

    def test_submit_fault_site(self, fault_plan):
        fault_plan("serve.submit:raise(RuntimeError)@n=1")
        with MicroBatcher(
            lambda b: b.sum(axis=(1, 2, 3)), max_batch=2, max_delay_ms=1.0
        ) as mb:
            f = mb.submit(np.ones((2, 2, 1)))
            with pytest.raises(RuntimeError, match="fault injected"):
                mb.submit(np.ones((2, 2, 1)))
            assert f.result(timeout=5) == 4.0


# ----------------------------------------------------- checkpoint + decode


def test_ckpt_save_fault_site(tmp_path, fault_plan):
    import jax.numpy as jnp

    from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
    from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
    )
    from jumbo_mae_tpu_tpu.train.checkpoint import CheckpointConfig, Checkpointer

    enc = preset(
        "vit_t16", image_size=32, patch_size=8, mask_ratio=0.75, labels=None,
        dtype="float32",
    )
    module = MAEPretrainModel(
        enc, DecoderConfig(layers=1, dim=32, heads=2, dtype="float32")
    )
    tx = make_optimizer(
        OptimConfig(name="adamw", learning_rate=1e-3, lr_scaling="none",
                    warmup_steps=1, training_steps=4),
        global_batch_size=8,
    )
    batch = {"images": jnp.zeros((8, 32, 32, 3), jnp.uint8)}
    mesh = create_mesh(MeshConfig(data=1, fsdp=1))
    state, _ = create_sharded_state(module, tx, batch, mesh, mode="pretrain")
    ckpt = Checkpointer(CheckpointConfig(str(tmp_path), async_save=False))
    fault_plan("ckpt.save:raise(OSError)@n=0")
    with pytest.raises(OSError, match="fault injected"):
        ckpt.save(0, state)
    ckpt.save(1, state)  # second attempt clean
    ckpt.close()
    assert ckpt.latest_step("last") == 1


def test_decode_corruption_dropped_and_counted(fault_plan):
    """A corrupted image payload fails decode; the sample is dropped and
    counted instead of crashing the stream."""
    import io as _io

    from PIL import Image

    from jumbo_mae_tpu_tpu.data.decode import decode_image

    buf = _io.BytesIO()
    Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(buf, format="PNG")
    payload = buf.getvalue()
    assert decode_image(payload) is not None
    fault_plan("seed=3;data.decode:corrupt(64)")
    corrupted = fault_point("data.decode", data=payload)
    assert corrupted != payload
    assert decode_image(corrupted) is None


# ---------------------------------------------------------------- SIGTERM


def test_sigterm_checkpoint_and_resume_inprocess(tmp_path, capsys):
    """Tier-1 graceful-preemption coverage, in-process and deterministic:
    SIGTERM lands mid-loop (raised by a watcher thread once the step gauge
    moves), the loop checkpoints at the next step boundary and returns;
    a resume run continues from exactly that step to completion."""
    from jumbo_mae_tpu_tpu.cli.train import train

    # 24 steps, not hundreds: the contract is SIGTERM-at-step>=3 →
    # checkpoint → resume-to-completion, and post-compile smoke steps are
    # ~150 ms each on the 1-core CI box — any larger total only burns the
    # tier-1 wall-clock budget without widening coverage.
    total = 24
    overrides = _smoke_overrides(
        tmp_path, total, ["run.eval_interval=100000", "run.log_interval=8"]
    )
    cfg = load_config(RECIPES / "smoke_cpu.yaml", overrides)

    # safety net: if the watcher misfires before the PreemptionGuard is
    # installed, a stray SIGTERM must not kill the pytest process
    prev_term = signal.signal(signal.SIGTERM, lambda *a: None)
    prev_int = signal.getsignal(signal.SIGINT)
    g_step = get_registry().gauge("train_step")
    g_step.set(0)  # earlier tests may have left a stale value
    stop = threading.Event()

    def watcher():
        while not stop.is_set():
            if g_step.value >= 3:
                os.kill(os.getpid(), signal.SIGTERM)
                return
            time.sleep(0.01)

    t = threading.Thread(target=watcher, daemon=True)
    t.start()
    try:
        train(cfg)
    finally:
        stop.set()
        t.join(timeout=5)
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)

    out = capsys.readouterr().out
    assert "preemption checkpoint" in out
    last = tmp_path / "smoke_cpu" / "ckpt" / "last"
    steps = [int(p.name) for p in last.iterdir() if p.name.isdigit()]
    assert steps, "no checkpoint written on SIGTERM"
    saved = max(steps)
    assert 3 <= saved < total

    # resume continues at the saved step and completes the run
    cfg2 = load_config(
        RECIPES / "smoke_cpu.yaml", overrides + ["run.resume=true"]
    )
    try:
        metrics = train(cfg2)
    finally:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        signal.signal(signal.SIGINT, signal.default_int_handler)
    out = capsys.readouterr().out
    assert f"resumed from step {saved}" in out
    assert math.isfinite(metrics["train/loss"])
    final_steps = [int(p.name) for p in last.iterdir() if p.name.isdigit()]
    assert max(final_steps) == total
