"""Replicated serving tier contracts (infer/replicaset.py).

The invariants this tier stands on:

- **exactly-once**: every submitted future resolves exactly once — ok,
  ok-with-retry attribution, or a typed error — under replica crashes,
  hangs, requeues, zombie wakeups, and a racing close(); access-log rows
  match futures one-to-one by rid;
- **crash isolation**: a raising / fault-injected / hung replica loses
  only itself — its queued and in-flight requests ride to survivors with
  the failed replica excluded, attributed via ``retries``/``requeued_from``;
- **self-healing**: the supervisor restarts down replicas with capped
  exponential backoff, and the quorum circuit breaker (soft degraded in
  /healthz) opens below quorum and closes on recovery;
- **gated hot-swap**: a weight push is promoted only through the parity
  gate (feature cosine vs live weights) and a live canary window; a
  corrupt push or a breaching canary rolls back automatically with the
  previous weights restored and ``serve_swap_rollbacks_total`` bumped.

Stub engines keep the pool mechanics fast; two real-engine tests prove the
chaos/swap story end-to-end on ``InferenceEngine`` (restart warms from the
persistent executable cache with zero compiles; a corrupt checkpoint push
is rejected at parity while a faithful one promotes).
"""

import threading
import time
from pathlib import Path

import numpy as np
import pytest

from jumbo_mae_tpu_tpu import faults
from jumbo_mae_tpu_tpu.infer import (
    DeadlineExceededError,
    PoolUnhealthyError,
    QueueFullError,
    ReplicaSet,
    RetriesExhaustedError,
    ShutdownError,
    WeightSwapController,
)
from jumbo_mae_tpu_tpu.obs import AccessLog, RequestTracer
from jumbo_mae_tpu_tpu.obs.journal import read_journal
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry


@pytest.fixture
def fault_plan():
    yield faults.install_plan
    faults.clear_plan()


# ----------------------------------------------------------- stub harness


class StubEngine:
    """Versioned stand-in for InferenceEngine: swap/restore move a string."""

    def __init__(self, idx, version="v0"):
        self.idx = idx
        self.version = version

    def swap_weights(self, params, batch_stats=None, *, ckpt=""):
        snap = {"version": self.version}
        self.version = params
        return snap

    def restore_snapshot(self, snap):
        self.version = snap["version"]


def _img(v=0.0):
    return np.full((2, 2, 3), v, np.float32)


def run_echo(eng, batch, metas):
    return {"y": batch[:, 0, 0, 0].astype(np.float64)}


def _pool(run=run_echo, *, provider=None, tracer=None, **kw):
    reg = MetricsRegistry()
    kw.setdefault("replicas", 2)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_delay_ms", 1.0)
    kw.setdefault("supervise_interval_s", 0.02)
    rs = ReplicaSet(
        provider or (lambda idx: StubEngine(idx)),
        run,
        registry=reg,
        tracer=tracer,
        **kw,
    )
    return rs, reg


def _rows(log):
    log.close()
    return [e for e in read_journal(log.path) if e["type"] == "request"]


def _counter(reg, name, labels=(), **lbl):
    fam = reg.counter(name, "x", labels=labels)
    return (fam.labels(*lbl.values()) if labels else fam).value


# --------------------------------------------------------------- routing


def test_pool_routes_and_resolves():
    with _pool()[0] as rs:
        futs = [rs.submit(_img(i)) for i in range(20)]
        vals = sorted(f.result(timeout=5)["y"] for f in futs)
    assert vals == sorted(float(i) for i in range(20))
    st = rs.stats()
    assert st["healthy"] == 2
    assert sum(r["served"] for r in st["replicas"].values()) == 20
    # least-loaded routing actually spread the work
    assert all(r["served"] > 0 for r in st["replicas"].values())


def test_pool_shed_shutdown_and_validation(tmp_path):
    with pytest.raises(ValueError):
        ReplicaSet(lambda i: StubEngine(i), run_echo, replicas=0)
    gate = threading.Event()

    def run_block(eng, batch, metas):
        gate.wait(5.0)
        return {"y": np.zeros(len(batch))}

    rs, _ = _pool(run_block, replicas=1, max_queue=1)
    first = rs.submit(_img())  # occupies the worker
    time.sleep(0.05)
    held = rs.submit(_img())  # sits in the queue: depth == max_queue
    with pytest.raises(QueueFullError):
        rs.submit(_img())
    gate.set()
    assert first.result(timeout=5) is not None
    assert held.result(timeout=5) is not None
    rs.close()
    with pytest.raises(ShutdownError):
        rs.submit(_img())


def test_close_resolves_everything_bounded():
    """A wedged replica cannot hang close(): its requests are swept with
    ShutdownError inside the join bound."""
    gate = threading.Event()

    def run_wedge(eng, batch, metas):
        gate.wait(30.0)  # simulates a stuck predict
        return {"y": np.zeros(len(batch))}

    rs, reg = _pool(run_wedge, replicas=1, hang_timeout_s=60.0)
    futs = [rs.submit(_img()) for _ in range(6)]
    time.sleep(0.05)
    t0 = time.monotonic()
    rs.close(timeout_s=0.5)
    assert time.monotonic() - t0 < 5.0
    for f in futs:
        assert f.done()
        assert isinstance(f.exception(timeout=0), ShutdownError)
    gate.set()


# ------------------------------------------------------- crash isolation


def test_crash_requeues_to_survivor_with_attribution(tmp_path):
    """r1 always raises: every request still resolves ok on r0, with the
    retry attributed to r1 in the access log and metrics."""
    log = AccessLog(tmp_path / "access")
    reg = MetricsRegistry()
    tracer = RequestTracer(registry=reg, access_log=log)

    def run(eng, batch, metas):
        if eng.idx == 1:
            raise RuntimeError("boom")
        return {"y": batch[:, 0, 0, 0].astype(np.float64)}

    rs = ReplicaSet(
        lambda i: StubEngine(i), run, replicas=2, max_batch=4,
        max_delay_ms=1.0, registry=reg, tracer=tracer,
        restart_backoff_s=30.0,  # keep r1 down for the whole test
        supervise_interval_s=0.02,
    )
    futs = [rs.submit(_img(i)) for i in range(16)]
    for f in futs:
        assert f.result(timeout=5) is not None
    rs.close()
    rows = _rows(log)
    assert len(rows) == 16
    assert all(r["outcome"] == "ok" for r in rows)
    retried = [r for r in rows if r.get("retries")]
    assert retried, "some requests must have routed to r1 first"
    assert all(r["requeued_from"] == "r1" for r in retried)
    assert all(r["replica"] == "r0" for r in retried)
    assert _counter(reg, "serve_replica_requeued_total",
                    labels=("replica",), replica="r1") == len(retried)
    assert _counter(reg, "serve_replica_crashes_total",
                    labels=("replica", "kind"), r="r1", k="crash") >= 1


def test_retries_exhausted_typed_error():
    def run(eng, batch, metas):
        raise RuntimeError("always")

    rs, reg = _pool(run, replicas=2, max_retries=0, restart_backoff_s=30.0)
    f = rs.submit(_img())
    with pytest.raises(RetriesExhaustedError):
        f.result(timeout=5)
    rs.close()


def test_pool_unhealthy_when_every_replica_excluded():
    def run(eng, batch, metas):
        raise RuntimeError("always")

    rs, reg = _pool(run, replicas=2, max_retries=5, restart_backoff_s=30.0)
    f = rs.submit(_img())
    with pytest.raises(PoolUnhealthyError):
        f.result(timeout=5)
    # ...and a fresh submit against a fully-down pool is refused up front
    time.sleep(0.1)
    with pytest.raises(PoolUnhealthyError):
        rs.submit(_img())
    rs.close()


def test_restart_backoff_recovery_and_generation():
    crashed = threading.Event()

    def run(eng, batch, metas):
        if eng.idx == 0 and not crashed.is_set():
            crashed.set()
            raise RuntimeError("first batch dies")
        return {"y": np.zeros(len(batch))}

    rs, reg = _pool(run, replicas=1, restart_backoff_s=0.05, max_retries=0)
    with pytest.raises(RetriesExhaustedError):
        rs.submit(_img()).result(timeout=5)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if rs.stats()["replicas"]["r0"]["state"] == "up":
            break
        time.sleep(0.02)
    assert rs.generation(0) == 1  # new incarnation
    assert rs.submit(_img()).result(timeout=5) is not None
    assert _counter(reg, "serve_replica_restarts_total",
                    labels=("replica",), replica="r0") == 1
    rs.close()


def test_restart_provider_failure_backs_off_then_recovers():
    builds = {"n": 0}

    def provider(idx):
        builds["n"] += 1
        if builds["n"] in (2, 3):  # the first two rebuilds fail
            raise RuntimeError("provider down")
        return StubEngine(idx)

    first = threading.Event()

    def run(eng, batch, metas):
        if not first.is_set():
            first.set()
            raise RuntimeError("die once")
        return {"y": np.zeros(len(batch))}

    rs, reg = _pool(run, provider=provider, replicas=1,
                    restart_backoff_s=0.03, max_retries=0)
    with pytest.raises(RetriesExhaustedError):
        rs.submit(_img()).result(timeout=5)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if rs.stats()["replicas"]["r0"]["state"] == "up":
            break
        time.sleep(0.02)
    assert rs.stats()["replicas"]["r0"]["state"] == "up"
    assert _counter(reg, "serve_replica_crashes_total",
                    labels=("replica", "kind"), r="r0",
                    k="restart_error") == 2
    assert rs.submit(_img()).result(timeout=5) is not None
    rs.close()


def test_quorum_breaker_opens_and_closes():
    healthy_again = threading.Event()

    def run(eng, batch, metas):
        if eng.idx == 1 and not healthy_again.is_set():
            raise RuntimeError("r1 sick")
        return {"y": np.zeros(len(batch))}

    rs, reg = _pool(run, replicas=2, quorum=2, restart_backoff_s=0.05,
                    max_retries=2)
    assert not rs.degraded()
    futs = [rs.submit(_img()) for _ in range(8)]
    for f in futs:
        f.result(timeout=5)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not rs.degraded():
        time.sleep(0.01)
    assert rs.degraded()  # healthy=1 < quorum=2 while r1 is down
    g = reg.gauge("serve_replica_breaker_open", "x")
    assert g.value == 1
    healthy_again.set()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and rs.degraded():
        time.sleep(0.02)
    assert not rs.degraded()
    assert g.value == 0
    assert _counter(reg, "serve_replica_breaker_trips_total") >= 1
    rs.close()


def test_hang_detected_requeued_and_zombie_loses_settle(tmp_path):
    """A hung predict is declared dead by the supervisor and its in-flight
    requests rescued onto the survivor; when the zombie thread finally
    wakes, it loses the settle race — no double resolution, no extra
    access-log row."""
    log = AccessLog(tmp_path / "access")
    reg = MetricsRegistry()
    tracer = RequestTracer(registry=reg, access_log=log)
    hang = threading.Event()

    def run(eng, batch, metas):
        if eng.idx == 0 and not hang.is_set():
            hang.set()
            time.sleep(1.2)  # >> hang_timeout_s
        return {"y": batch[:, 0, 0, 0].astype(np.float64)}

    rs = ReplicaSet(
        lambda i: StubEngine(i), run, replicas=2, max_batch=2,
        max_delay_ms=1.0, registry=reg, tracer=tracer,
        hang_timeout_s=0.15, supervise_interval_s=0.03,
        restart_backoff_s=30.0,
    )
    futs = [rs.submit(_img(i)) for i in range(8)]
    vals = [f.result(timeout=10)["y"] for f in futs]
    assert sorted(vals) == sorted(float(i) for i in range(8))
    time.sleep(1.3)  # let the zombie wake and try to re-resolve
    rs.close()
    rows = _rows(log)
    assert len(rows) == 8  # exactly one row per request, zombie added none
    assert all(r["outcome"] == "ok" for r in rows)
    rescued = [r for r in rows if r.get("requeued_from") == "r0"]
    assert rescued, "the hung batch must have been rescued"
    assert _counter(reg, "serve_replica_crashes_total",
                    labels=("replica", "kind"), r="r0", k="hang") == 1


def test_late_deadline_after_admission_is_late_not_ok(tmp_path):
    log = AccessLog(tmp_path / "access")
    reg = MetricsRegistry()
    tracer = RequestTracer(registry=reg, access_log=log)

    def run(eng, batch, metas):
        time.sleep(0.2)
        return {"y": np.zeros(len(batch))}

    rs = ReplicaSet(
        lambda i: StubEngine(i), run, replicas=1, max_batch=4,
        max_delay_ms=1.0, registry=reg, tracer=tracer,
    )
    f = rs.submit(_img(), deadline_ms=50.0)
    with pytest.raises(DeadlineExceededError):
        f.result(timeout=5)
    rs.close()
    rows = _rows(log)
    assert [r["outcome"] for r in rows] == ["late"]
    assert _counter(reg, "infer_requests_late_total") == 1


# -------------------------------------------- satellite: exactly-once storm


def test_stress_mid_stream_kill_every_future_exactly_once(tmp_path):
    """8 threads x 40 requests against a 3-replica pool while r1 is killed
    mid-stream through the ``serve.replica`` fault site: every future
    resolves exactly once (ok, retried-ok, or typed error), access-log
    rows match futures 1:1 by rid, and teardown joins bounded."""
    faults.install_plan("serve.replica:raise(RuntimeError)@key~r1")
    try:
        log = AccessLog(tmp_path / "access")
        reg = MetricsRegistry()
        tracer = RequestTracer(registry=reg, access_log=log)

        def run(eng, batch, metas):
            time.sleep(0.002)
            return {"y": batch[:, 0, 0, 0].astype(np.float64)}

        rs = ReplicaSet(
            lambda i: StubEngine(i), run, replicas=3, max_batch=8,
            max_delay_ms=1.0, max_queue=None, registry=reg, tracer=tracer,
            restart_backoff_s=0.05, supervise_interval_s=0.02,
        )
        futures, submit_errors = [], []
        lock = threading.Lock()
        n_threads, per_thread = 8, 40

        def client(tid):
            rng = np.random.RandomState(tid)
            for i in range(per_thread):
                dl = None if i % 3 else float(rng.uniform(50.0, 500.0))
                try:
                    f = rs.submit(_img(tid), deadline_ms=dl)
                except (QueueFullError, PoolUnhealthyError,
                        ShutdownError) as e:
                    with lock:
                        submit_errors.append(e)
                else:
                    with lock:
                        futures.append(f)
                if i % 16 == 15:
                    time.sleep(0.001)

        threads = [
            threading.Thread(target=client, args=(t,))
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        t0 = time.monotonic()
        for f in futures:
            f.result(timeout=30) if f.exception(timeout=30) is None else None
        rs.close()
        assert time.monotonic() - t0 < 60.0  # bounded join

        ok = retried_ok = typed = 0
        for f in futures:
            assert f.done(), "a future was left unresolved"
            exc = f.exception(timeout=0)
            if exc is None:
                ok += 1
            else:
                assert isinstance(
                    exc,
                    (DeadlineExceededError, RetriesExhaustedError,
                     PoolUnhealthyError, ShutdownError),
                ), f"untyped failure leaked: {exc!r}"
                typed += 1
        assert ok > 0
        rows = _rows(log)
        assert len(rows) == len(futures) + len(submit_errors)
        rids = [r["rid"] for r in rows]
        assert len(set(rids)) == len(rids)
        by_rid = {r["rid"]: r for r in rows}
        for f in futures:
            row = by_rid[f.rid]
            if f.exception(timeout=0) is None:
                assert row["outcome"] == "ok"
                if row.get("retries"):
                    retried_ok += 1
                    assert "r1" in row["requeued_from"]
            else:
                assert row["outcome"] in ("deadline", "late", "aborted",
                                          "shutdown")
        assert retried_ok > 0, "the kill must have forced retried-ok rows"
    finally:
        faults.clear_plan()


# ------------------------------------------------------------- hot swap


def _swap_rig(run=None, *, features=None, replicas=3, **ctl_kw):
    reg = MetricsRegistry()
    rs = ReplicaSet(
        lambda i: StubEngine(i),
        run or (lambda eng, batch, metas: {"y": np.zeros(len(batch))}),
        replicas=replicas, max_batch=4, max_delay_ms=1.0, registry=reg,
        supervise_interval_s=0.02,
    )

    def default_features(eng, images):
        f = np.ones((len(images), 8))
        if isinstance(eng.version, str) and "bad" in eng.version:
            f[:, ::2] = -1.0  # direction flip: cosine collapses
        return f

    ctl_kw.setdefault("restore_fn", lambda p: (Path(p).name, None))
    ctl_kw.setdefault("features_fn", features or default_features)
    ctl_kw.setdefault("parity_images", np.zeros((4, 2, 2, 3), np.uint8))
    ctl_kw.setdefault("canary_requests", 2)
    ctl_kw.setdefault("canary_timeout_s", 3.0)
    ctl = WeightSwapController(rs, registry=reg, **ctl_kw)
    return rs, ctl, reg


def _bg_traffic(rs, stop, deadline_ms=None):
    def loop():
        while not stop.is_set():
            try:
                rs.submit(_img(), deadline_ms=deadline_ms).result(timeout=5)
            except Exception:
                pass
            time.sleep(0.005)

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return t


def test_swap_promotes_under_load_and_restarts_use_new_weights():
    promoted = []
    rs, ctl, reg = _swap_rig(on_promote=promoted.append)
    stop = threading.Event()
    t = _bg_traffic(rs, stop)
    try:
        rep = ctl.swap("/push/v1")
        assert rep["verdict"] == "promoted"
        assert rep["parity"]["within_tolerance"]
        assert rep["canary_eval"]["requests"] >= 2
        assert [rs.replica(i).engine.version for i in range(3)] == ["v1"] * 3
        assert promoted == ["/push/v1"]
        assert _counter(reg, "serve_swap_promoted_total") == 1
        assert _counter(reg, "serve_swap_rollbacks_total") == 0
    finally:
        stop.set()
        t.join(timeout=5)
        rs.close()


def test_swap_parity_failure_rolls_back_all_weights():
    rs, ctl, reg = _swap_rig()
    stop = threading.Event()
    t = _bg_traffic(rs, stop)
    try:
        rep = ctl.swap("/push/vbad")
        assert rep["verdict"] == "rolled_back"
        assert rep["stage"] == "parity"
        assert not rep["parity"]["within_tolerance"]
        # nothing kept the bad weights; traffic never saw them routable
        assert [rs.replica(i).engine.version for i in range(3)] == ["v0"] * 3
        assert _counter(reg, "serve_swap_rollbacks_total") == 1
        assert _counter(reg, "serve_swap_promoted_total") == 0
        # and the pool still serves after the rollback
        assert rs.submit(_img()).result(timeout=5) is not None
    finally:
        stop.set()
        t.join(timeout=5)
        rs.close()


def test_swap_canary_breach_rolls_back():
    """Parity passes (same feature direction) but the new weights are slow
    enough that canary traffic goes late — the burn-rate window must veto
    the promotion and restore the old weights."""

    def run(eng, batch, metas):
        if eng.version == "vslow":
            time.sleep(0.12)
        return {"y": np.zeros(len(batch))}

    rs, ctl, reg = _swap_rig(
        run, features=lambda eng, images: np.ones((len(images), 8)),
        canary_slo="success_rate>=0.99", canary_requests=4,
        canary_timeout_s=5.0,
    )
    stop = threading.Event()
    t = _bg_traffic(rs, stop, deadline_ms=60.0)
    try:
        rep = ctl.swap("/push/vslow")
        assert rep["verdict"] == "rolled_back"
        assert rep["stage"] == "canary"
        assert [rs.replica(i).engine.version for i in range(3)] == ["v0"] * 3
        assert _counter(reg, "serve_swap_rollbacks_total") == 1
    finally:
        stop.set()
        t.join(timeout=5)
        rs.close()


def test_swap_rejected_on_restore_error():
    def restore(path):
        raise FileNotFoundError(path)

    rs, ctl, reg = _swap_rig(restore_fn=restore)
    try:
        rep = ctl.swap("/push/missing")
        assert rep["verdict"] == "rejected"
        assert rep["stage"] == "restore"
        assert _counter(reg, "serve_swap_rejected_total") == 1
        assert _counter(reg, "serve_swap_rollbacks_total") == 0
        assert [rs.replica(i).engine.version for i in range(3)] == ["v0"] * 3
    finally:
        rs.close()


def test_swap_ckpt_load_corrupt_fault_site(fault_plan):
    """GRAFT_FAULTS ``ckpt.load:corrupt`` perturbs the restored tree, and
    the parity gate catches it — the CI chaos-smoke scenario in miniature.
    The stub features read the tree, so corruption shows up as a direction
    change."""
    fault_plan("ckpt.load:corrupt(4)")

    def restore(path):
        return {"w": {"kernel": np.ones((4, 2), np.float32)}}, None

    def features(eng, images):
        v = eng.version
        if isinstance(v, dict):
            leaf = np.asarray(v["w"]["kernel"], np.float64)
            return np.tile(leaf.reshape(-1), (len(images), 1))
        return np.ones((len(images), 8))

    rs, ctl, reg = _swap_rig(restore_fn=restore, features=features)
    # parity ref comes from the live stub (all-ones); the corrupted tree's
    # leaves are scaled to -3x-0.5 so the candidate direction flips
    ctl.parity_images = np.zeros((4, 2, 2, 3), np.uint8)
    try:
        rep = ctl.swap("/push/corrupt")
        assert rep["verdict"] == "rolled_back"
        assert rep["stage"] == "parity"
        assert _counter(reg, "serve_swap_rollbacks_total") == 1
    finally:
        rs.close()


# ------------------------------------------------------- real engine e2e


def tiny_cfg():
    from jumbo_mae_tpu_tpu.config import load_config

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    return load_config(
        recipe,
        [
            "model.overrides.dtype=float32",
            "model.dec_layers=1",
            "model.dec_dim=32",
            "model.dec_heads=2",
            "model.dec_dtype=float32",
        ],
    )


def _real_images(n, size=32):
    return (
        np.random.RandomState(0)
        .randint(0, 256, (n, size, size, 3))
        .astype(np.uint8)
    )


def test_real_engine_pool_crash_restart_warms_with_zero_compiles(
    tmp_path, fault_plan
):
    """Chaos proof on the real engine: kill r1's first predict through
    ``serve.replica``; every request still resolves ok, and the restarted
    replica comes up from the persistent executable cache with zero fresh
    compiles."""
    from jumbo_mae_tpu_tpu.infer import InferenceEngine

    cfg = tiny_cfg()
    wc = str(tmp_path / "wc")
    reg = MetricsRegistry()
    engines = {}

    def provider(idx):
        eng = InferenceEngine(cfg, max_batch=4, warm_cache=wc)
        eng.warmup(("features",))
        engines.setdefault(idx, []).append(eng)
        return eng

    fault_plan("serve.replica:raise(RuntimeError)@key~r1")

    def run(eng, batch, metas):
        return eng.predict(batch, task="features")

    rs = ReplicaSet(
        provider, run, replicas=2, max_batch=4, max_delay_ms=2.0,
        registry=reg, restart_backoff_s=0.05, supervise_interval_s=0.02,
    )
    try:
        futs = [rs.submit(img) for img in _real_images(8)]
        for f in futs:
            assert f.result(timeout=120) is not None
        faults.clear_plan()  # stop killing r1 so its restart sticks
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            st = rs.stats()["replicas"]["r1"]
            if st["state"] == "up" and rs.generation(1) >= 1:
                break
            time.sleep(0.05)
        assert rs.generation(1) >= 1
        restarted = engines[1][-1]
        assert len(engines[1]) >= 2
        # the warm restart compiled nothing: every executable came from disk
        assert sum(restarted.compile_counts.values()) == 0
        assert sum(restarted.warm_hits.values()) > 0
        # and it serves: force traffic through r1 only
        rs.pause(0)
        assert rs.submit(_real_images(1)[0]).result(timeout=120) is not None
    finally:
        rs.close()


def test_real_engine_hot_swap_good_promotes_corrupt_rolls_back(tmp_path):
    """End-to-end swap on the real engine: a faithful checkpoint push
    promotes with parity cosine ~1 and zero failed requests; a corrupt
    push (``ckpt.load:corrupt``) is rolled back at the parity gate."""
    from jumbo_mae_tpu_tpu.infer import InferenceEngine
    from jumbo_mae_tpu_tpu.train.checkpoint import export_params_msgpack

    cfg = tiny_cfg()
    reg = MetricsRegistry()

    def provider(idx):
        return InferenceEngine(cfg, max_batch=4, warm_cache=False)

    def run(eng, batch, metas):
        return eng.predict(batch, task="features")

    rs = ReplicaSet(
        provider, run, replicas=2, max_batch=4, max_delay_ms=2.0,
        registry=reg, supervise_interval_s=0.02,
    )
    try:
        eng0 = rs.replica(0).engine
        # build the features task, then export its live weights — the
        # "faithful push" is bit-identical to what is already serving
        eng0.predict(_real_images(1), task="features")
        ckpt = tmp_path / "push" / "weights.msgpack"
        ckpt.parent.mkdir()
        export_params_msgpack(
            eng0._tasks["features"]["variables"]["params"], ckpt
        )
        probe = _real_images(4)
        ctl = WeightSwapController(
            rs, parity_images=probe, canary_requests=2,
            canary_timeout_s=10.0, registry=reg,
        )
        stop = threading.Event()
        failures = []

        def loop():
            while not stop.is_set():
                try:
                    rs.submit(_real_images(1)[0]).result(timeout=60)
                except Exception as e:  # pragma: no cover - would fail below
                    failures.append(e)
                time.sleep(0.01)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        try:
            rep = ctl.swap(str(ckpt))
        finally:
            stop.set()
            t.join(timeout=30)
        assert rep["verdict"] == "promoted", rep
        assert rep["parity"]["cosine_min"] > 0.999
        assert not failures  # a good swap under load drops zero requests

        faults.install_plan("ckpt.load:corrupt(6)")
        try:
            rep2 = ctl.swap(str(ckpt))
        finally:
            faults.clear_plan()
        assert rep2["verdict"] == "rolled_back", rep2
        assert rep2["stage"] == "parity"
        assert _counter(reg, "serve_swap_rollbacks_total") == 1
        # the rolled-back pool still serves correct features
        assert rs.submit(_real_images(1)[0]).result(timeout=60) is not None
    finally:
        rs.close()


def test_swap_headroom_rejection():
    """A push whose double-buffer footprint (new tree + rollback snapshot)
    doesn't fit host memory is rejected up front — before any parity probe,
    canary pick, or weight flip — and a broken probe never blocks a swap."""
    calls = []

    def tight(need):
        calls.append(need)
        return "needs 512 KiB but only 1 KiB of host memory is safely available"

    params = {"w": np.zeros((256, 256), np.float32)}
    rs, ctl, reg = _swap_rig(
        restore_fn=lambda p: (params, None), headroom_fn=tight
    )
    try:
        rep = ctl.swap("/push/v1")
        assert rep["verdict"] == "rejected" and rep["stage"] == "headroom"
        assert "512 KiB" in rep["error"]
        assert calls == [2 * 256 * 256 * 4]  # double-buffered tree bytes
        assert [rs.replica(i).engine.version for i in range(3)] == ["v0"] * 3
        assert _counter(reg, "serve_swap_rejected_total") == 1
        # a probe that raises must not veto the swap
        rs2, ctl2, _ = _swap_rig(headroom_fn=lambda need: 1 / 0)
        try:
            assert ctl2.swap("/push/v1")["verdict"] == "promoted"
        finally:
            rs2.close()
    finally:
        rs.close()


# ------------------------------------------------------------- preemption


def test_preempt_drains_zero_dropped_then_restarts(tmp_path):
    """A preemption notice mid-traffic: the replica leaves routing, every
    request it held resolves ok (zero dropped), the journal carries
    ``replica_preempted``, the metric bumps, and the supervisor brings the
    capacity back without a failure-count penalty."""
    log = AccessLog(tmp_path / "access")
    reg = MetricsRegistry()
    tracer = RequestTracer(registry=reg, access_log=log)

    def run(eng, batch, metas):
        time.sleep(0.005)
        return {"y": batch[:, 0, 0, 0].astype(np.float64)}

    rs = ReplicaSet(
        lambda i: StubEngine(i), run, replicas=2, max_batch=4,
        max_delay_ms=1.0, supervise_interval_s=0.02,
        restart_backoff_s=0.05, registry=reg, tracer=tracer,
    )
    futs, stop = [], threading.Event()

    def pump():
        for i in range(150):
            if stop.is_set():
                return
            try:
                futs.append(rs.submit(_img(i)))
            except QueueFullError:
                pass
            time.sleep(0.002)

    t = threading.Thread(target=pump)
    t.start()
    time.sleep(0.05)
    assert rs.preempt(1) is True
    t.join()
    for f in futs:
        assert f.result(timeout=10) is not None  # zero dropped
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if rs.stats()["replicas"]["r1"]["state"] == "up":
            break
        time.sleep(0.02)
    st = rs.stats()["replicas"]["r1"]
    assert st["state"] == "up"
    assert st["gen"] == 1  # a fresh incarnation took the slot
    assert st["restarts"] == 0  # preemption is not a failure
    assert _counter(reg, "serve_replica_preempted_total",
                    labels=("replica",), replica="r1") == 1
    rs.close()
    events = [e["type"] for e in read_journal((tmp_path / "access"))]
    assert "replica_preempted" in events


def test_preempt_rejects_down_restarting_and_closed():
    rs, _ = _pool(replicas=2)
    assert rs.preempt(7) is False  # out of range
    with rs._state_lock:
        rs._slots[1].state = "down"
    assert rs.preempt(1) is False  # already down
    with rs._state_lock:
        rs._slots[1].state = "up"
    rs.close()
    assert rs.preempt(0) is False  # closed pool


def test_serve_preempt_fault_site_drains_via_supervisor(fault_plan, tmp_path):
    """``serve.preempt:raise@n=1`` fires on the supervisor's second site
    visit (r1 on the first tick): the replica drains exactly as a manual
    preempt() would, under the same zero-drop contract."""
    log = AccessLog(tmp_path / "access")
    reg = MetricsRegistry()
    tracer = RequestTracer(registry=reg, access_log=log)
    fault_plan("serve.preempt:raise@n=1")
    rs = ReplicaSet(
        lambda i: StubEngine(i), run_echo, replicas=2, max_batch=4,
        max_delay_ms=1.0, supervise_interval_s=0.02,
        restart_backoff_s=0.05, registry=reg, tracer=tracer,
    )
    futs = [rs.submit(_img(i)) for i in range(30)]
    for f in futs:
        assert f.result(timeout=10) is not None
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if _counter(reg, "serve_replica_preempted_total",
                    labels=("replica",), replica="r1") == 1:
            break
        time.sleep(0.02)
    assert _counter(reg, "serve_replica_preempted_total",
                    labels=("replica",), replica="r1") == 1
    rs.close()
    events = [e["type"] for e in read_journal(tmp_path / "access")]
    assert "replica_preempted" in events


def test_close_during_restart_never_respawns_slot():
    """Regression for the close/restart race: a restart thread past its
    pre-build check must NOT install a new incarnation once close() has
    latched shutdown — the old code checked ``_closed`` only before taking
    the state lock, so a slot could respawn (live thread, live engine)
    after the close sweep."""
    built = threading.Event()
    release = threading.Event()
    crashed = threading.Event()

    def provider(idx):
        if crashed.is_set():
            # the restart build: park here until close() has begun
            built.set()
            assert release.wait(10.0)
        return StubEngine(idx)

    victim = {}

    def run(eng, batch, metas):
        if not crashed.is_set():
            crashed.set()
            victim["idx"] = eng.idx
            raise RuntimeError("die once")
        return {"y": np.zeros(len(batch))}

    rs, _ = _pool(run, provider=provider, replicas=2,
                  restart_backoff_s=0.01, max_retries=1)
    rs.submit(_img()).result(timeout=5)  # retried onto the survivor
    assert built.wait(10.0)  # the restart thread is inside the provider
    closer = threading.Thread(target=rs.close)
    closer.start()
    time.sleep(0.1)  # close() is joining; the latch is set
    release.set()  # let the restart thread race the install
    closer.join(timeout=10.0)
    assert not closer.is_alive()
    # the slot must not have respawned: no running worker thread, and the
    # incarnation still the crashed gen-0 one (never replaced)
    rep = rs.replica(victim["idx"])
    assert rep.gen == 0
    assert rep.thread is None or not rep.thread.is_alive()
    with pytest.raises(ShutdownError):
        rs.submit(_img())


# ------------------------------------------------- atomic dispatch groups


def test_submit_group_boundaries_never_merge_in_one_flush():
    """The worker must not coalesce across ``submit_group`` boundaries:
    the occupancy (and, packed, the token geometry) the scheduler
    assembled is what the replica runs. Two groups queued back-to-back on
    one busy replica flush as two batches, never one merged batch — even
    though max_batch would allow the merge."""
    from concurrent.futures import wait

    gate = threading.Event()
    flushes = []

    def run_gated(eng, batch, metas):
        gate.wait(timeout=10)
        flushes.append([im.shape[0] for im in batch] if isinstance(
            batch, list) else [batch.shape[1]] * batch.shape[0])
        return {"y": np.zeros(len(metas) if isinstance(batch, list)
                              else batch.shape[0])}

    rs, reg = _pool(run_gated, replicas=1, max_batch=16, max_delay_ms=1.0)
    try:
        now = time.monotonic()
        # park the worker on a decoy so both groups are queued before any
        # coalescing loop runs
        decoy = rs.submit(_img())
        time.sleep(0.05)
        g1 = rs.submit_group([(np.full((4, 4, 3), 1.0, np.float32),
                               now + 30.0, None, None)] * 2)
        g2 = rs.submit_group([(np.full((4, 4, 3), 2.0, np.float32),
                               now + 30.0, None, None)] * 3)
        gate.set()
        done, _ = wait([decoy] + g1 + g2, timeout=10)
        assert len(done) == 6
    finally:
        rs.close()
    # three flushes: the decoy, then each group intact — never [2+3] merged
    assert [len(f) for f in flushes] == [1, 2, 3]


def test_worker_carry_lookahead_is_not_lost_on_exit():
    """A worker that peeked past a group boundary holds a carry record;
    close() (or a crash) must requeue/resolve it, never orphan it."""
    gate = threading.Event()

    def run_gated(eng, batch, metas):
        gate.wait(timeout=10)
        n = len(metas)
        return {"y": np.zeros(n)}

    rs, reg = _pool(run_gated, replicas=1, max_batch=16, max_delay_ms=1.0)
    now = time.monotonic()
    decoy = rs.submit(_img())
    time.sleep(0.05)
    g1 = rs.submit_group([(_img(1.0), now + 30.0, None, None)] * 2)
    g2 = rs.submit_group([(_img(2.0), now + 30.0, None, None)] * 2)
    gate.set()
    rs.close()  # drain: everything queued (carry included) must resolve
    for f in [decoy] + g1 + g2:
        assert f.done()
        # ok or shutdown are both legal under close(); lost/hung is not
        exc = f.exception()
        assert exc is None or isinstance(exc, ShutdownError)
