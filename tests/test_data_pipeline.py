"""Data pipeline tests: shard algebra, tar streaming, loader contracts.

Covers the contracts SURVEY §2.6 lists for the reference pipeline:
process/worker disjoint striping, deterministic shuffles, repeat
de-interleave, and the -1/valid eval padding consumed by the eval step.
"""

import io

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.data import (
    DataConfig,
    TrainLoader,
    batch_valid_samples,
    expand_shards,
    iter_tar_samples,
    shuffle_shards,
    split_shards,
    train_sample_stream,
    valid_loader,
    valid_sample_stream,
    write_tar_samples,
)
from jumbo_mae_tpu_tpu.data.tario import group_samples


def _jpeg_bytes(rng: np.random.Generator, h=64, w=64) -> bytes:
    from PIL import Image

    img = Image.fromarray(rng.integers(0, 256, (h, w, 3), dtype=np.uint8), "RGB")
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=90)
    return buf.getvalue()


@pytest.fixture(scope="module")
def shard_dir(tmp_path_factory):
    """4 shards × 8 samples with jpg + cls members."""
    root = tmp_path_factory.mktemp("shards")
    rng = np.random.default_rng(0)
    idx = 0
    for s in range(4):
        samples = []
        for _ in range(8):
            samples.append(
                {
                    "__key__": f"sample{idx:05d}",
                    "jpg": _jpeg_bytes(rng),
                    "cls": str(idx % 10).encode(),
                }
            )
            idx += 1
        write_tar_samples(str(root / f"train-{s:04d}.tar"), samples)
    return root


def test_expand_shards_brace_and_join():
    urls = expand_shards("pre-{0000..0003}.tar")
    assert urls == [f"pre-{i:04d}.tar" for i in range(4)]
    urls = expand_shards("a.tar::b-{01..02}.tar")
    assert urls == ["a.tar", "b-01.tar", "b-02.tar"]
    assert expand_shards(["x", "y"]) == ["x", "y"]


def test_shuffle_shards_deterministic_and_epoch_varying():
    shards = [f"s{i}" for i in range(20)]
    a = shuffle_shards(shards, seed=3, epoch=0)
    b = shuffle_shards(shards, seed=3, epoch=0)
    c = shuffle_shards(shards, seed=3, epoch=1)
    assert a == b and sorted(a) == sorted(shards)
    assert a != c and sorted(c) == sorted(shards)


def test_split_shards_disjoint_cover():
    shards = [f"s{i}" for i in range(13)]
    seen = []
    for p in range(2):
        for w in range(3):
            seen += split_shards(
                shards, process_index=p, process_count=2, worker_index=w, worker_count=3
            )
    assert sorted(seen) == sorted(shards)
    assert len(set(seen)) == len(seen)


def test_tar_roundtrip_and_grouping(shard_dir):
    samples = list(iter_tar_samples(str(shard_dir / "train-0000.tar")))
    assert len(samples) == 8
    assert {"__key__", "jpg", "cls"} <= set(samples[0])
    assert samples[0]["__key__"] == "sample00000"


def test_group_samples_multidot_extension():
    members = [("d/a.jpg", b"1"), ("d/a.seg.png", b"2"), ("d/b.jpg", b"3")]
    out = list(group_samples(iter(members)))
    assert len(out) == 2
    assert out[0]["seg.png"] == b"2"


def test_corrupt_tar_skipped(tmp_path, shard_dir):
    bad = tmp_path / "bad.tar"
    bad.write_bytes(b"this is not a tar file at all" * 10)
    assert list(iter_tar_samples(str(bad))) == []
    # and a missing shard doesn't raise either
    assert list(iter_tar_samples(str(tmp_path / "missing.tar"))) == []


def _cfg(shard_dir, **kw):
    defaults = dict(
        train_shards=str(shard_dir / "train-{0000..0003}.tar"),
        valid_shards=str(shard_dir / "train-{0000..0003}.tar"),
        image_size=32,
        workers=0,
        shuffle_buffer=8,
        seed=7,
    )
    defaults.update(kw)
    return DataConfig(**defaults)


def test_train_stream_deterministic(shard_dir):
    cfg = _cfg(shard_dir)
    a = [x for x, _ in zip(train_sample_stream(cfg), range(10))]
    b = [x for x, _ in zip(train_sample_stream(cfg), range(10))]
    for (ia, la), (ib, lb) in zip(a, b):
        assert la == lb
        np.testing.assert_array_equal(ia, ib)
    assert a[0][0].shape == (32, 32, 3) and a[0][0].dtype == np.uint8


def test_train_stream_process_split_disjoint_labels(shard_dir):
    cfg = _cfg(shard_dir, shuffle_buffer=0)
    # 2 processes: each sees only its stripe's shards in epoch 0
    keys0 = {l for (_, l), _ in zip(
        train_sample_stream(cfg, process_index=0, process_count=2), range(16)
    )}
    keys1 = {l for (_, l), _ in zip(
        train_sample_stream(cfg, process_index=1, process_count=2), range(16)
    )}
    assert keys0 and keys1  # both streams produce data


def test_train_loader_batches_and_repeats(shard_dir):
    cfg = _cfg(shard_dir, repeats=2)
    loader = TrainLoader(cfg, batch_size=8)
    batch = next(loader)
    assert batch["images"].shape == (8, 32, 32, 3)
    assert batch["images"].dtype == np.uint8
    assert batch["labels"].shape == (8,)
    # repeated augmentation: each source sample contributes `repeats` clones,
    # de-interleaved: clone pairs are batch[i] and batch[i + B//2]
    assert list(batch["labels"][:4]) == list(batch["labels"][4:])


def test_train_loader_start_epoch_resume(shard_dir):
    """Coarse data-cursor resume: a loader started at epoch 1 replays
    exactly the stream a fresh loader reaches after finishing epoch 0."""
    cfg = _cfg(shard_dir)
    n_samples = 32  # 4 shards × 8 samples, one process/worker sees all
    fresh = train_sample_stream(cfg)
    for _ in range(n_samples):  # drain epoch 0
        next(fresh)
    want = [next(fresh) for _ in range(8)]  # epoch 1 head

    resumed = TrainLoader(cfg, batch_size=8, start_epoch=1)
    got = next(resumed)
    np.testing.assert_array_equal(
        got["images"], np.stack([img for img, _ in want])
    )
    np.testing.assert_array_equal(
        got["labels"], np.array([l for _, l in want])
    )

    # and it differs from the epoch-0 head (shuffles are epoch-keyed)
    head0 = next(TrainLoader(cfg, batch_size=8))
    assert not np.array_equal(got["images"], head0["images"])


def test_train_loader_sample_exact_resume_inline(shard_dir):
    """Sample-exact resume (VERDICT #7): snapshot after batch k, rebuild a
    loader from the cursor, and the batch sequence continues bit-identically
    to the uninterrupted loader — including across an epoch boundary (32
    samples / batch 8 → epoch boundary at batch 4)."""
    cfg = _cfg(shard_dir)
    full = TrainLoader(cfg, batch_size=8)
    for _ in range(3):
        next(full)
    snap = full.snapshot()
    want = [next(full) for _ in range(4)]  # batches 4-7, crossing epoch 0→1

    resumed = TrainLoader(cfg, batch_size=8, cursor=snap)
    for w in want:
        got = next(resumed)
        np.testing.assert_array_equal(got["images"], w["images"])
        np.testing.assert_array_equal(got["labels"], w["labels"])
    assert resumed.snapshot() == full.snapshot()


def test_train_loader_sample_exact_resume_workers(shard_dir):
    """Same contract through the subprocess-worker path: strict round-robin
    makes the multi-worker batch sequence deterministic and resumable."""
    cfg = _cfg(shard_dir, workers=2, prefetch_batches=2)
    full = TrainLoader(cfg, batch_size=4)
    try:
        for _ in range(3):
            next(full)
        snap = full.snapshot()
        want = [next(full) for _ in range(4)]
    finally:
        full.close()

    assert snap["batches"] == 3 and len(snap["workers"]) == 2
    resumed = TrainLoader(cfg, batch_size=4, cursor=snap)
    try:
        for w in want:
            got = next(resumed)
            np.testing.assert_array_equal(got["images"], w["images"])
            np.testing.assert_array_equal(got["labels"], w["labels"])
    finally:
        resumed.close()


def test_train_loader_cursor_worker_mismatch_raises(shard_dir):
    cfg = _cfg(shard_dir)
    snap = {"workers": [[0, 8], [0, 8]], "batches": 4}
    with pytest.raises(ValueError, match="worker"):
        TrainLoader(cfg, batch_size=8, cursor=snap)


def test_native_loader_snapshot_records_substrate(shard_dir):
    """The native-IO substrate is sample-exactly resumable (deterministic
    per-thread shard ownership + round-robin merge, native/tario.cc), but
    only under the SAME thread count and substrate: a snapshot carries
    ``native_threads`` and a worker-path cursor is refused.
    Full resume equality: tests/test_native_loader.py."""
    cfg = _cfg(shard_dir, use_native=True)
    with pytest.raises(ValueError, match="subprocess-worker"):
        TrainLoader(cfg, batch_size=8, cursor={"workers": [[0, 8]], "batches": 1})
    loader = TrainLoader(cfg, batch_size=8)
    try:
        next(loader)
        snap = loader.snapshot()
        assert snap is not None
        assert snap["native_threads"] == cfg.native_io_threads
        assert snap["batches"] == 1
    finally:
        loader.close()


def test_prepare_dataset_tool_roundtrip(tmp_path):
    """tools/prepare_dataset.py: image folder → shards our loaders stream."""
    import json
    import subprocess
    import sys as _sys
    from pathlib import Path

    from PIL import Image

    rng = np.random.default_rng(0)
    for cls in ("cat", "dog"):
        d = tmp_path / "src" / cls
        d.mkdir(parents=True)
        for i in range(6):
            arr = rng.integers(0, 255, (40, 40, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.jpg")

    out = tmp_path / "shards"
    proc = subprocess.run(
        [
            _sys.executable, "tools/prepare_dataset.py",
            "--src", str(tmp_path / "src"), "--out", str(out),
            "--prefix", "train", "--shard-size", "5",
        ],
        capture_output=True, text=True, check=True,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    info = json.loads(proc.stdout.strip().splitlines()[-1])
    assert info["samples"] == 12 and info["classes"] == 2 and info["shards"] == 3
    assert json.loads((out / "classes.json").read_text()) == ["cat", "dog"]

    cfg = DataConfig(
        train_shards=info["spec"], image_size=32, workers=0, shuffle_buffer=0
    )
    batch = next(TrainLoader(cfg, batch_size=8))
    assert batch["images"].shape == (8, 32, 32, 3)
    assert set(batch["labels"].tolist()) <= {0, 1}


def test_valid_loader_pad_contract(shard_dir):
    cfg = _cfg(shard_dir)
    batches = list(valid_loader(cfg, batch_size=5))
    # 32 samples → 6 batches of 5, last has 2 valid
    assert len(batches) == 7
    for b in batches:
        assert b["images"].shape == (5, 32, 32, 3)
    assert b["valid"].sum() == 2
    assert (b["labels"][~b["valid"]] == -1).all()
    total = sum(b["valid"].sum() for b in batches)
    assert total == 32


def test_valid_cache_zero_shard_rereads(shard_dir, tmp_path, monkeypatch):
    """VERDICT #8 acceptance: with data.valid_cache set, the second
    evaluate-pass does ZERO shard reads (counted with a shim) and yields
    batches bit-identical to the uncached pipeline."""
    import jumbo_mae_tpu_tpu.data.loader as loader_mod

    cfg = _cfg(shard_dir, valid_cache=str(tmp_path / "vc"))
    reads = {"n": 0}
    real = loader_mod.iter_shards_samples

    def counting(shards, **kw):
        reads["n"] += 1
        return real(shards, **kw)

    monkeypatch.setattr(loader_mod, "iter_shards_samples", counting)

    uncached = list(valid_loader(_cfg(shard_dir), batch_size=5))
    reads["n"] = 0

    first = list(valid_loader(cfg, batch_size=5))
    assert reads["n"] > 0  # first pass streams the shards (and captures)
    reads["n"] = 0
    second = list(valid_loader(cfg, batch_size=5))
    assert reads["n"] == 0  # second pass is served entirely from the cache

    for u, a, b in zip(uncached, first, second):
        for k in ("images", "labels", "valid"):
            np.testing.assert_array_equal(u[k], a[k])
            np.testing.assert_array_equal(u[k], b[k])
    assert len(uncached) == len(first) == len(second)


def test_valid_cache_abandoned_capture_not_committed(shard_dir, tmp_path):
    """A partially-drained first pass must not poison the cache: the next
    loader recaptures from the shards and serves the full set."""
    cfg = _cfg(shard_dir, valid_cache=str(tmp_path / "vc2"))
    it = valid_loader(cfg, batch_size=5)
    next(it)
    it.close()  # abandon mid-pass — no meta commit
    batches = list(valid_loader(cfg, batch_size=5))
    assert sum(b["valid"].sum() for b in batches) == 32
    # and the recapture committed: third pass works from cache
    again = list(valid_loader(cfg, batch_size=5))
    assert sum(b["valid"].sum() for b in again) == 32


def test_valid_cache_empty_stripe_roundtrip(tmp_path):
    """A process whose stripe is empty (process_count > shards) must commit
    and re-read an empty cache without crashing."""
    from jumbo_mae_tpu_tpu.data.valcache import ValidSampleCache

    cache = ValidSampleCache(str(tmp_path / "vc"), {"k": 1}, image_size=32)
    assert list(cache.capture(iter([]))) == []
    assert cache.complete()
    assert list(cache.read()) == []


def test_valid_stream_covers_everything_once(shard_dir):
    cfg = _cfg(shard_dir)
    labels = [l for _, l in valid_sample_stream(cfg)]
    assert len(labels) == 32


def test_multiprocess_workers(shard_dir):
    cfg = _cfg(shard_dir, workers=2, prefetch_batches=2)
    loader = TrainLoader(cfg, batch_size=4)
    try:
        for _ in range(4):
            batch = next(loader)
            assert batch["images"].shape == (4, 32, 32, 3)
    finally:
        loader.close()
