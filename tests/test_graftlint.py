"""graftlint: fixture-corpus true positives, clean negatives, baseline
mechanics, CLI exit codes — plus the two runtime sentinels (lockwatch
order-inversion detection and the retrace shape-diff attribution path).

The fixture files under ``tests/graftlint_fixtures/`` are parsed, never
imported: each ``# TRCnnn`` / ``# LCKnnn`` / ``# CONnnn`` comment marks a
seeded violation the linter must report at that file:line, and
``clean_idioms.py`` holds repo idioms that must produce zero findings
(the false-positive budget is exactly 0).
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import warnings
from collections import Counter
from pathlib import Path

import pytest

from tools.graftlint import run_lint
from tools.graftlint.findings import (
    EXIT_CLEAN,
    EXIT_FINDINGS,
    Baseline,
    split_by_baseline,
)

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "graftlint_fixtures"


@pytest.fixture(scope="module")
def fixture_findings():
    return run_lint(REPO, [FIXTURES]).findings


def _per_file(findings):
    by = {}
    for f in findings:
        by.setdefault(Path(f.path).name, []).append(f)
    return by


# ---------------------------------------------------------------- corpus


class TestFixtureCorpus:
    def test_every_seeded_violation_fires(self, fixture_findings):
        """Each fixture file yields exactly its seeded rule multiset —
        an extra finding is a false positive, a missing one a false
        negative; both fail."""
        expected = {
            "trc_hazards.py": Counter(
                {"TRC001": 3, "TRC002": 3, "TRC003": 2, "TRC004": 1}
            ),
            "lck_discipline.py": Counter(
                {"LCK001": 1, "LCK002": 2, "LCK004": 1}
            ),
            "lck_cycle.py": Counter({"LCK003": 1}),
            "con_drift.py": Counter(
                {"CON001": 1, "CON002": 1, "CON003": 2, "CON004": 2}
            ),
        }
        by_file = {
            name: Counter(f.rule for f in fs)
            for name, fs in _per_file(fixture_findings).items()
        }
        assert by_file == expected

    def test_findings_carry_file_and_line(self, fixture_findings):
        marked = {}
        for name in ("trc_hazards.py", "lck_discipline.py", "con_drift.py"):
            for lineno, text in enumerate(
                (FIXTURES / name).read_text().splitlines(), start=1
            ):
                if "# TRC" in text or "# LCK" in text or "# CON" in text:
                    rule = text.split("# ")[-1].split(":")[0].split()[0]
                    marked[(name, rule, lineno)] = text
        for key in marked:
            name, rule, lineno = key
            hits = [
                f
                for f in fixture_findings
                if Path(f.path).name == name
                and f.rule == rule
                and f.line == lineno
            ]
            assert hits, f"no {rule} reported at {name}:{lineno}"
            assert hits[0].location().endswith(f"{name}:{lineno}")

    def test_round10_shape_is_named(self, fixture_findings):
        """The warmup-deadlock class that bit round 10 must be called out
        as such: callee re-acquiring a lock the frame already holds."""
        (f,) = [
            f
            for f in fixture_findings
            if f.rule == "LCK002" and f.scope == "Engine.warmup"
        ]
        assert "round-10" in f.message
        assert "_task" in f.message

    def test_cycle_names_both_locks(self, fixture_findings):
        (f,) = [f for f in fixture_findings if f.rule == "LCK003"]
        assert "_ALPHA" in f.message and "_BETA" in f.message

    def test_clean_idioms_zero_findings(self):
        res = run_lint(REPO, [FIXTURES / "clean_idioms.py"])
        assert res.findings == []
        assert res.files_scanned == 1


# --------------------------------------------------------------- baseline


class TestBaseline:
    def test_reason_is_mandatory(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(
            json.dumps({"findings": {"LCK001|a.py|f|0123456789ab": {}}})
        )
        with pytest.raises(ValueError, match="reason"):
            Baseline.load(p)

    def test_split_accepts_and_reports_stale(self, fixture_findings, tmp_path):
        lck = [f for f in fixture_findings if f.rule.startswith("LCK")]
        entries = {
            f.key: {"reason": "fixture: deliberately seeded"} for f in lck
        }
        entries["LCK001|gone.py|f|000000000000"] = {
            "reason": "stale: file was deleted"
        }
        p = tmp_path / "b.json"
        p.write_text(json.dumps({"findings": entries}))
        bl = Baseline.load(p)
        fresh, accepted = split_by_baseline(fixture_findings, bl)
        assert not any(f.rule.startswith("LCK") for f in fresh)
        assert {f.key for f in accepted} == {f.key for f in lck}
        assert bl.stale_keys(fixture_findings) == [
            "LCK001|gone.py|f|000000000000"
        ]

    def test_shipped_baseline_is_exact(self):
        """The checked-in baseline covers the tree with no fresh findings
        and no stale entries — the CI gate's exact precondition."""
        res = run_lint(REPO)
        bl = Baseline.load(REPO / ".graftlint-baseline.json")
        fresh, accepted = split_by_baseline(res.findings, bl)
        assert fresh == [], [f.location() for f in fresh]
        assert bl.stale_keys(res.findings) == []
        assert len(accepted) == 6


# -------------------------------------------------------------------- CLI


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftlint", *args],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )

    def test_fixture_corpus_exits_2(self):
        proc = self._run("tests/graftlint_fixtures", "--no-baseline")
        assert proc.returncode == EXIT_FINDINGS, proc.stdout + proc.stderr
        for rule in ("TRC001", "TRC004", "LCK002", "LCK003", "CON003"):
            assert rule in proc.stdout

    def test_shipped_tree_exits_0(self):
        proc = self._run()
        assert proc.returncode == EXIT_CLEAN, proc.stdout + proc.stderr
        assert "CLEAN" in proc.stdout

    def test_report_file(self, tmp_path):
        out = tmp_path / "report.md"
        proc = self._run(
            "tests/graftlint_fixtures/clean_idioms.py",
            "--no-baseline",
            "--report",
            str(out),
        )
        assert proc.returncode == EXIT_CLEAN
        assert "CLEAN" in out.read_text()


# -------------------------------------------------- runtime: lockwatch


class TestLockwatchRuntime:
    def test_order_inversion_detected_and_journaled(self):
        from jumbo_mae_tpu_tpu.obs import lockwatch

        events = []

        class _Journal:
            def event(self, etype, **payload):
                events.append((etype, payload))
                return payload

        lockwatch.reset()
        lockwatch.enable()
        lockwatch.attach_journal(_Journal())
        try:
            a = lockwatch.lock("fixture.A")
            b = lockwatch.lock("fixture.B")

            def a_then_b():
                with a:
                    with b:
                        pass

            def b_then_a():
                with b:
                    with a:
                        pass

            # Sequential threads: establishes edge A->B, then observes
            # B->A — an inversion, with zero actual deadlock risk.
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                for fn in (a_then_b, b_then_a):
                    t = threading.Thread(target=fn)
                    t.start()
                    t.join()

            vs = lockwatch.violations()
            assert len(vs) == 1
            assert vs[0]["held"] == "fixture.B"
            assert vs[0]["acquired"] == "fixture.A"
            journaled = [p for e, p in events if e == "lock_order_violation"]
            assert journaled and journaled[0]["held"] == "fixture.B"
        finally:
            lockwatch.attach_journal(None)
            lockwatch.disable()
            lockwatch.reset()

    def test_disabled_returns_plain_lock(self):
        from jumbo_mae_tpu_tpu.obs import lockwatch

        lockwatch.reset()
        lockwatch.disable()
        lk = lockwatch.lock("fixture.plain")
        assert not isinstance(lk, lockwatch.WatchedLock)
        with lk:
            pass
        assert lockwatch.violations() == []


# ---------------------------------------------------- runtime: retrace


class TestRetraceRuntime:
    def test_shape_change_is_attributed_and_journaled(self):
        import jax
        import jax.numpy as jnp

        from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry
        from jumbo_mae_tpu_tpu.obs.retrace import RetraceSentinel

        events = []

        class _Journal:
            def event(self, etype, **payload):
                events.append((etype, payload))
                return payload

        sentinel = RetraceSentinel(
            "fixture", journal=_Journal(), registry=MetricsRegistry()
        )
        try:
            fn = jax.jit(lambda t: t * 2 + 1)
            x = jnp.ones((2, 3))
            y = jnp.ones((4, 3))  # built pre-arm: its compile is warmup
            sentinel.note("step", x)
            fn(x).block_until_ready()  # warmup compile, unarmed
            sentinel.arm()

            sentinel.note("step", y)  # records the (2,3)->(4,3) change
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                fn(y).block_until_ready()  # recompile while armed

            assert sentinel.summary()["violations"] >= 1
            rows = [p for e, p in events if e == "retrace"]
            assert rows, "no retrace event journaled"
            row = rows[0]
            assert row["tag"] == "step"
            diff = row["diff"]
            assert diff and diff[0]["prev_shape"] == [2, 3]
            assert diff[0]["new_shape"] == [4, 3]
            with pytest.raises(AssertionError):
                sentinel.assert_steady()
        finally:
            sentinel.close()

    def test_expected_block_suppresses_violation(self):
        import jax
        import jax.numpy as jnp

        from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry
        from jumbo_mae_tpu_tpu.obs.retrace import RetraceSentinel

        sentinel = RetraceSentinel("fixture2", registry=MetricsRegistry())
        try:
            fn = jax.jit(lambda t: t - 1)
            x = jnp.ones((3,))
            y = jnp.ones((5,))
            sentinel.note("step", x)
            fn(x).block_until_ready()
            sentinel.arm()
            sentinel.note("step", y)
            with sentinel.expected("fixture growth"):
                fn(y).block_until_ready()
            summary = sentinel.summary()
            assert summary["violations"] == 0
            assert summary["expected"] >= 1
            sentinel.assert_steady()  # must NOT raise
        finally:
            sentinel.close()
