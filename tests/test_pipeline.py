"""Pipeline parallelism (GPipe over a ``pipe`` mesh axis): the pipelined
block chain must equal the sequential one — forward AND gradients — under
every stage/microbatch split, composed with data parallelism. (Beyond the
reference: SURVEY §2.10 lists PP as absent there.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.models.config import JumboViTConfig
from jumbo_mae_tpu_tpu.models.layers import PlainBlock
from jumbo_mae_tpu_tpu.parallel import (
    create_pipeline_mesh,
    gpipe,
    pipelined_blocks_apply,
    stack_block_params,
    unstack_block_params,
)

CFG = JumboViTConfig(layers=4, dim=32, heads=2, dtype="float32")
BLOCK = PlainBlock(CFG)
N_BLOCKS, BATCH, SEQ = 4, 8, 12


@pytest.fixture(scope="module")
def chain(devices):
    """4 PlainBlocks' params (under block_0..block_3) + an input batch."""
    x = jax.random.normal(jax.random.key(0), (BATCH, SEQ, CFG.dim))
    params = {}
    for i in range(N_BLOCKS):
        params[f"block_{i}"] = BLOCK.init(
            jax.random.key(10 + i), x, True
        )["params"]
    return params, x


def sequential(params, x):
    for i in range(N_BLOCKS):
        x = BLOCK.apply({"params": params[f"block_{i}"]}, x, True)
    return x


def test_stack_roundtrip(chain):
    params, _ = chain
    stacked, n = stack_block_params(params)
    assert n == N_BLOCKS
    back = unstack_block_params(stacked)
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(back)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("pipe,microbatches", [(2, 4), (4, 2), (4, 8), (2, 1)])
def test_gpipe_forward_matches_sequential(chain, pipe, microbatches):
    params, x = chain
    mesh = create_pipeline_mesh(data=1, pipe=pipe)
    want = sequential(params, x)
    got = pipelined_blocks_apply(
        BLOCK, params, x, mesh=mesh, microbatches=microbatches
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_gpipe_composes_with_data_parallel(chain):
    params, x = chain
    mesh = create_pipeline_mesh(data=2, pipe=4)
    got = pipelined_blocks_apply(BLOCK, params, x, mesh=mesh, microbatches=2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(sequential(params, x)), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow  # heavy compile; full suite covers it
def test_gpipe_gradients_match_sequential(chain):
    """ppermute transposes to the reverse hop, so jax.grad through the
    schedule IS the backward pipeline — it must equal sequential grads."""
    params, x = chain
    mesh = create_pipeline_mesh(data=1, pipe=4)
    stacked, _ = stack_block_params(params)

    def block_fn(p, h):
        return BLOCK.apply({"params": p}, h, True)

    def loss_pipe(stacked_p):
        out = gpipe(block_fn, stacked_p, x, mesh=mesh, microbatches=4)
        return (out**2).mean()

    def loss_seq(stacked_p):
        h = x
        for i in range(N_BLOCKS):
            h = block_fn(jax.tree_util.tree_map(lambda l, i=i: l[i], stacked_p), h)
        return (h**2).mean()

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_pipe), jax.tree_util.tree_leaves(g_seq)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_gpipe_jits_as_one_training_step(chain):
    """value_and_grad of a pipelined loss under jit — one XLA program, the
    shape the multichip dryrun certifies."""
    params, x = chain
    mesh = create_pipeline_mesh(data=2, pipe=4)
    stacked, _ = stack_block_params(params)

    def block_fn(p, h):
        return BLOCK.apply({"params": p}, h, True)

    @jax.jit
    def step(stacked_p):
        def loss(sp):
            out = gpipe(block_fn, sp, x, mesh=mesh, microbatches=4)
            return (out**2).mean()

        return jax.value_and_grad(loss)(stacked_p)

    val, grads = step(stacked)
    assert np.isfinite(float(val))
    assert all(
        np.isfinite(np.asarray(g)).all()
        for g in jax.tree_util.tree_leaves(grads)
    )


def test_gpipe_validates_divisibility(chain):
    params, x = chain
    mesh = create_pipeline_mesh(data=1, pipe=4)
    stacked, _ = stack_block_params(params)

    def block_fn(p, h):
        return h

    with pytest.raises(ValueError, match="microbatches"):
        gpipe(block_fn, stacked, x, mesh=mesh, microbatches=3)
    mesh3 = create_pipeline_mesh(data=1, pipe=3)
    with pytest.raises(ValueError, match="stages"):
        gpipe(block_fn, stacked, x, mesh=mesh3, microbatches=2)


def test_gpipe_validates_microbatch_vs_data_axis(chain):
    params, x = chain
    stacked, _ = stack_block_params(params)
    # data=8 can't split the size-2 microbatches of an 8-batch/4-microbatch run
    mesh = create_pipeline_mesh(data=8, pipe=1)
    with pytest.raises(ValueError, match="does not divide over the data"):
        gpipe(lambda p, h: h, stacked, x, mesh=mesh, microbatches=4)


@pytest.mark.slow  # heavy compile; full suite covers it
def test_gpipe_shared_params_jumbo_blocks(devices):
    """The signature JumboBlock chain — shared CLS MLP across every block —
    pipelines correctly: forward equals sequential, and the shared MLP's
    gradient comes back as the sum over stages (replicated-input psum)."""
    from jumbo_mae_tpu_tpu.models.layers import JumboBlock, Mlp
    from jumbo_mae_tpu_tpu.parallel import pipelined_jumbo_blocks_apply

    cfg = JumboViTConfig(
        layers=4, dim=32, heads=2, num_cls_tokens=3, dtype="float32"
    )
    k = cfg.num_cls_tokens
    jm = Mlp(k * cfg.dim, 4 * k * cfg.dim, 0.0, cfg.compute_dtype)
    block = JumboBlock(cfg, jm)
    x = jax.random.normal(jax.random.key(0), (8, k + 9, cfg.dim))

    v0 = block.init(jax.random.key(1), x, True)["params"]
    shared = v0.pop("jumbo_mlp")
    enc_params = {"jumbo_mlp": shared, "block_0": v0}
    for i in range(1, 4):
        vi = block.init(jax.random.key(1 + i), x, True)["params"]
        vi.pop("jumbo_mlp")
        enc_params[f"block_{i}"] = vi

    def sequential(params, x):
        h = x
        for i in range(4):
            h = block.apply(
                {"params": {**params[f"block_{i}"], "jumbo_mlp": params["jumbo_mlp"]}},
                h,
                True,
            )
        return h

    mesh = create_pipeline_mesh(data=2, pipe=4)
    got = pipelined_jumbo_blocks_apply(
        cfg, enc_params, x, mesh=mesh, microbatches=4
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(sequential(enc_params, x)), rtol=2e-5, atol=2e-5
    )

    # gradients, incl. the shared MLP's (summed over stages)
    g_pipe = jax.grad(
        lambda p: (
            pipelined_jumbo_blocks_apply(cfg, p, x, mesh=mesh, microbatches=4)
            ** 2
        ).mean()
    )(enc_params)
    g_seq = jax.grad(lambda p: (sequential(p, x) ** 2).mean())(enc_params)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_pipe), jax.tree_util.tree_leaves(g_seq)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5
        )


def test_gpipe_composes_with_remat_blocks(chain):
    """Depth-sharding and rematerialization together — the big-model
    configuration — must still match the sequential chain's gradients."""
    from jumbo_mae_tpu_tpu.models.config import maybe_remat

    params, x = chain
    remat_cfg = CFG.replace(grad_ckpt=True, remat_policy="dots")
    remat_block = maybe_remat(PlainBlock, remat_cfg)(remat_cfg)
    mesh = create_pipeline_mesh(data=1, pipe=4)
    stacked, _ = stack_block_params(params)

    def block_fn(p, h):
        return remat_block.apply({"params": p}, h, True)

    def loss_pipe(sp):
        return (
            gpipe(block_fn, sp, x, mesh=mesh, microbatches=4) ** 2
        ).mean()

    def loss_seq(sp):
        h = x
        for i in range(N_BLOCKS):
            h = block_fn(jax.tree_util.tree_map(lambda l, i=i: l[i], sp), h)
        return (h**2).mean()

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_seq = jax.jit(jax.grad(loss_seq))(stacked)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_pipe), jax.tree_util.tree_leaves(g_seq)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


# --------------------------------------------------------------------------
# Recipe-surface reachability: mesh.pipe trains the real MAE pretrain step
# --------------------------------------------------------------------------


@pytest.mark.slow  # heavy compile; full suite covers it
def test_mesh_pipe_full_train_step_matches_sequential(devices):
    """The mesh.pipe=2 train step (GPipe encoder via the blocks_override
    seam) must track the ordinary sequential step: same init, same batch,
    near-identical losses over several optimizer updates."""
    from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
    from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
        make_train_step,
    )

    enc = preset(
        "vit_t16", image_size=32, patch_size=8, mask_ratio=0.75, labels=None,
        dtype="float32", layers=4,
    )
    dec = DecoderConfig(layers=1, dim=32, heads=2, dtype="float32")
    batch = {
        "images": jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32, 32, 3)), jnp.uint8
        )
    }
    opt = OptimConfig(
        learning_rate=1e-3, lr_scaling="none", warmup_steps=1, training_steps=10
    )

    def run(pipe):
        module = MAEPretrainModel(enc, dec)
        tx = make_optimizer(opt, 256)
        mesh = (
            create_pipeline_mesh(data=1, pipe=2)
            if pipe
            else create_mesh(MeshConfig(data=1, fsdp=1))
        )
        state, sharding = create_sharded_state(
            module, tx, batch, mesh, mode="pretrain", init_seed=0, rng_seed=0
        )
        step = make_train_step(
            mesh, sharding, mode="pretrain",
            pipe_microbatches=2 if pipe else 0,
            encoder_cfg=enc if pipe else None,
        )
        losses = []
        for _ in range(4):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    seq, piped = run(False), run(True)
    np.testing.assert_allclose(piped, seq, rtol=2e-4)
    assert piped[-1] < piped[0]


@pytest.mark.slow
def test_mesh_pipe_reachable_from_recipe(tmp_path):
    """run.mode=pretrain mesh.pipe=2 trains end-to-end through the CLI on a
    virtual mesh (VERDICT r3 item 10: the capability must be reachable
    without writing code)."""
    from pathlib import Path

    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    cfg = load_config(
        recipe,
        [
            f"run.output_dir={tmp_path}",
            "mesh.pipe=2",
            "mesh.fsdp=1",
            "model.overrides={mask_ratio: 0.75, posemb: sincos2d, image_size: 32, patch_size: 4, dtype: float32, layers: 2}",
        ],
    )
    metrics = train(cfg)
    assert np.isfinite(metrics["val/loss"])


def test_mesh_pipe_rejects_fsdp_composition():
    from jumbo_mae_tpu_tpu.parallel import MeshConfig

    with pytest.raises(ValueError, match="pipe composes"):
        MeshConfig(data=1, fsdp=2, pipe=2).validate_pipe()
    MeshConfig(data=2, fsdp=1, pipe=2).validate_pipe()  # ok
    MeshConfig(data=1, fsdp=-1, pipe=2).validate_pipe()  # default fsdp ok


def test_gpipe_stochastic_droppath_rng_structure(chain, devices):
    """rng-bearing gpipe (round-5: droppath/dropout through the pipe):
    reproducible under a fixed key, sensitive to the key, and decorrelated
    across microbatches AND data shards (identical input rows must produce
    distinct stochastic outputs)."""
    cfg = CFG.replace(droppath=0.5)
    block = PlainBlock(cfg)
    params, x = chain  # DropPath adds no params: same init applies

    def block_fn(p, h, key):
        return block.apply({"params": p}, h, False, rngs={"dropout": key})

    mesh = create_pipeline_mesh(data=2, pipe=4)
    stacked, _ = stack_block_params(params)
    run = lambda key: np.asarray(
        gpipe(block_fn, stacked, x, mesh=mesh, microbatches=4, rng=key)
    )
    out1, out2, out3 = run(jax.random.key(1)), run(jax.random.key(1)), run(
        jax.random.key(2)
    )
    np.testing.assert_array_equal(out1, out2)
    assert not np.allclose(out1, out3)

    # identical rows through every (microbatch, data-shard) cell: the
    # deterministic schedule gives 8 equal outputs; the stochastic one must
    # draw an independent mask per cell. Fixed seed -> deterministic count.
    x_same = jnp.broadcast_to(x[:1], x.shape)
    out = np.asarray(
        gpipe(
            block_fn, stacked, x_same, mesh=mesh, microbatches=4,
            rng=jax.random.key(3),
        )
    )
    distinct = len({out[i].tobytes() for i in range(out.shape[0])})
    assert distinct >= 6, f"only {distinct} distinct stochastic outputs"

    # droppath=0 with an rng is numerically the deterministic path
    det_fn = lambda p, h: BLOCK.apply({"params": p}, h, True)
    zero_cfg_block = PlainBlock(CFG)  # droppath=0

    def zero_fn(p, h, key):
        return zero_cfg_block.apply(
            {"params": p}, h, False, rngs={"dropout": key}
        )

    a = gpipe(zero_fn, stacked, x, mesh=mesh, microbatches=4, rng=jax.random.key(4))
    b = gpipe(det_fn, stacked, x, mesh=mesh, microbatches=4)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


def test_mesh_pipe_train_step_with_droppath(devices):
    """The round-4 guard is gone: a mesh.pipe train step with droppath>0
    compiles, runs, and actually regularizes (loss stays finite; repeated
    steps on one batch still descend)."""
    from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
        make_train_step,
    )

    enc = preset(
        "vit_t16", image_size=32, patch_size=8, mask_ratio=0.75, labels=None,
        dtype="float32", layers=4, droppath=0.3,
    )
    dec = DecoderConfig(layers=1, dim=32, heads=2, dtype="float32")
    batch = {
        "images": jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32, 32, 3)), jnp.uint8
        )
    }
    module = MAEPretrainModel(enc, dec)
    tx = make_optimizer(
        OptimConfig(
            learning_rate=1e-3, lr_scaling="none", warmup_steps=1,
            training_steps=10,
        ),
        256,
    )
    mesh = create_pipeline_mesh(data=2, pipe=2)
    state, sharding = create_sharded_state(
        module, tx, batch, mesh, mode="pretrain", init_seed=0, rng_seed=0
    )
    step = make_train_step(
        mesh, sharding, mode="pretrain", pipe_microbatches=2, encoder_cfg=enc
    )
    losses = []
    for _ in range(4):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


@pytest.mark.slow  # heavy compile; full suite covers it
def test_mesh_pipe_classify_train_step_matches_sequential(devices):
    """Round 5: pipeline parallelism covers the classify/finetune mode too
    (the classifier shares the JumboViT encoder; blocks_override threads
    through ClassificationModel). Pipelined step ≡ sequential step."""
    from jumbo_mae_tpu_tpu.models import ClassificationModel, preset
    from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
        make_train_step,
    )

    enc = preset(
        "vit_t16", image_size=32, patch_size=8, mask_ratio=None, labels=10,
        dtype="float32", layers=4,
    )
    rs = np.random.RandomState(0)
    batch = {
        "images": jnp.asarray(rs.randint(0, 256, (8, 32, 32, 3)), jnp.uint8),
        "labels": jnp.asarray(rs.randint(0, 10, (8,)), jnp.int32),
    }
    opt = OptimConfig(
        learning_rate=1e-3, lr_scaling="none", warmup_steps=1, training_steps=10
    )

    def run(pipe):
        module = ClassificationModel(enc)
        tx = make_optimizer(opt, 256)
        mesh = (
            create_pipeline_mesh(data=1, pipe=2)
            if pipe
            else create_mesh(MeshConfig(data=1, fsdp=1))
        )
        state, sharding = create_sharded_state(
            module, tx, batch, mesh, mode="classify", init_seed=0, rng_seed=0
        )
        step = make_train_step(
            mesh, sharding, mode="classify",
            pipe_microbatches=2 if pipe else 0,
            encoder_cfg=enc if pipe else None,
        )
        losses = []
        for _ in range(4):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    seq, piped = run(False), run(True)
    np.testing.assert_allclose(piped, seq, rtol=2e-4)
    assert piped[-1] < piped[0]


@pytest.mark.slow  # heavy compile; full suite covers it
def test_mesh_pipe_decoder_stack_matches_sequential(devices):
    """Round 5: the MAE decoder stack is pipelinable too (its own
    blocks_override seam + make_plain_pipeline_apply). Encoder AND decoder
    pipelined ≡ fully sequential."""
    from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
    from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
        make_train_step,
    )

    enc = preset(
        "vit_t16", image_size=32, patch_size=8, mask_ratio=0.75, labels=None,
        dtype="float32", layers=4,
    )
    dec = DecoderConfig(layers=2, dim=32, heads=2, dtype="float32")
    batch = {
        "images": jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (8, 32, 32, 3)), jnp.uint8
        )
    }
    opt = OptimConfig(
        learning_rate=1e-3, lr_scaling="none", warmup_steps=1, training_steps=10
    )

    def run(pipe):
        module = MAEPretrainModel(enc, dec)
        tx = make_optimizer(opt, 256)
        mesh = (
            create_pipeline_mesh(data=1, pipe=2)
            if pipe
            else create_mesh(MeshConfig(data=1, fsdp=1))
        )
        state, sharding = create_sharded_state(
            module, tx, batch, mesh, mode="pretrain", init_seed=0, rng_seed=0
        )
        step = make_train_step(
            mesh, sharding, mode="pretrain",
            pipe_microbatches=2 if pipe else 0,
            encoder_cfg=enc if pipe else None,
            decoder_cfg=dec if pipe else None,
        )
        losses = []
        for _ in range(4):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    seq, piped = run(False), run(True)
    np.testing.assert_allclose(piped, seq, rtol=2e-4)
    assert piped[-1] < piped[0]


def test_decoder_pipelining_guards():
    from jumbo_mae_tpu_tpu.models import DecoderConfig, preset
    from jumbo_mae_tpu_tpu.train import make_train_step

    enc = preset("vit_t16", image_size=32, patch_size=8, mask_ratio=None,
                 labels=10, dtype="float32", layers=4)
    mesh = create_pipeline_mesh(data=1, pipe=2)
    with pytest.raises(ValueError, match="pretrain only"):
        make_train_step(
            mesh, None, mode="classify", pipe_microbatches=2,
            encoder_cfg=enc,
            decoder_cfg=DecoderConfig(layers=2, dim=32, heads=2),
        )
