"""RunEngine contracts (train/engine.py) + the extraction-equivalence golden.

The engine owns only driver logic — step counting, log-boundary metric
batching, eval/checkpoint arithmetic, rollback control flow, the stop-safe
preemption boundary, and the crash/shutdown ladder. Everything else is a
registered hook. Two layers of coverage:

- **Deviceless unit tests**: the driver runs with ``fetch=identity`` and
  pure-python dispatch, so hook ordering, boundary arithmetic, rollback
  resume, stop requests, and the crash ladder are pinned without JAX ever
  dispatching a step.
- **The extraction golden** (slow): a seeded 12-step ``cli/train.py`` run
  must emit the exact journal event sequence (types + steps) the
  pre-refactor monolithic loop emitted, with the identical final loss —
  the equivalence contract of the ISSUE-18 refactor. Rollback / SIGTERM /
  flightrec behavior is additionally pinned by ``tests/test_chaos.py``
  passing unmodified.
"""

import json

import pytest

from jumbo_mae_tpu_tpu.train.engine import RunEngine


def make_engine(
    *,
    steps=12,
    log_interval=2,
    eval_interval=4,
    should_stop=None,
    dispatch=None,
    process_count=1,
):
    def _dispatch(state, batch, step):
        return state + 1, {"loss": float(step)}

    return RunEngine(
        training_steps=steps,
        log_interval=log_interval,
        eval_interval=eval_interval,
        process_count=process_count,
        next_batch=lambda step: step,
        dispatch=dispatch or _dispatch,
        should_stop=should_stop,
        fetch=lambda ms: ms,  # deviceless: metrics are already host values
    )


def test_hook_order_and_boundaries():
    eng = make_engine()
    trace = []
    eng.pre_step(lambda e, s: trace.append(("pre", s)))
    eng.on_step(lambda e, ev: trace.append(("step", ev.step)))
    eng.on_log_window(
        lambda e, win: trace.append(("log", win.step, [s for s, _ in win.fetched]))
    )
    eng.on_eval(lambda e, s, st: trace.append(("eval", s)) or {"val/x": 1.0})
    eng.on_checkpoint(lambda e, cev: trace.append(("ckpt", cev.step, cev.reason)))
    eng.on_shutdown(lambda e, reason, s: trace.append(("shutdown", reason, s)))

    out = eng.run(0)
    assert out == 12  # dispatch incremented state once per step
    assert eng.exit_reason == "completed"
    # log windows batch exactly the steps since the previous boundary
    assert [t for t in trace if t[0] == "log"] == [
        ("log", 2, [1, 2]),
        ("log", 4, [3, 4]),
        ("log", 6, [5, 6]),
        ("log", 8, [7, 8]),
        ("log", 10, [9, 10]),
        ("log", 12, [11, 12]),
    ]
    assert [t for t in trace if t[0] == "eval"] == [
        ("eval", 4), ("eval", 8), ("eval", 12)
    ]
    assert [t for t in trace if t[0] == "ckpt"] == [
        ("ckpt", 4, "interval"), ("ckpt", 8, "interval"), ("ckpt", 12, "interval")
    ]
    assert trace[-1] == ("shutdown", "completed", 12)
    # within one step: pre before step; the eval at a boundary precedes
    # its checkpoint
    i_pre = trace.index(("pre", 4))
    i_step = trace.index(("step", 4))
    i_eval = trace.index(("eval", 4))
    i_ckpt = trace.index(("ckpt", 4, "interval"))
    assert i_pre < i_step < i_eval < i_ckpt


def test_eval_results_merge_into_checkpoint_event():
    eng = make_engine(steps=4, eval_interval=4)
    eng.on_eval(lambda e, s, st: {"val/a": 1.0})
    eng.on_eval(lambda e, s, st: {"val/b": 2.0})
    eng.on_eval(lambda e, s, st: None)  # a hook with nothing to add
    got = {}
    eng.on_checkpoint(lambda e, cev: got.update(cev.metrics))
    eng.run(0)
    assert got == {"val/a": 1.0, "val/b": 2.0}


def test_final_step_is_always_a_boundary():
    eng = make_engine(steps=7, log_interval=3, eval_interval=5)
    logs, ckpts = [], []
    eng.on_log_window(lambda e, win: logs.append(win.step))
    eng.on_checkpoint(lambda e, cev: ckpts.append(cev.step))
    eng.run(0)
    assert logs == [3, 6, 7]  # step 7 != 0 mod 3, but it's the last step
    assert ckpts == [5, 7]


def test_eval_interval_zero_checkpoints_only_at_the_end():
    eng = make_engine(steps=6, eval_interval=0)
    ckpts = []
    eng.on_checkpoint(lambda e, cev: ckpts.append(cev.step))
    eng.run(0)
    assert ckpts == [6]


def test_rollback_resumes_from_hook_returned_step():
    eng = make_engine(steps=8, log_interval=2, eval_interval=4)
    windows, rollbacks = [], []

    def window(e, win):
        windows.append(win.step)
        if win.step == 6 and not rollbacks:
            e.request_rollback()

    def rollback(e, step, win):
        rollbacks.append(step)
        e.state = 100  # the restore replaces the engine's state
        return 4

    eng.on_log_window(window)
    eng.on_rollback(rollback)
    out = eng.run(0)
    assert rollbacks == [6]
    # resumed from 4: steps 5..8 run again, so windows 6 and 8 repeat
    assert windows == [2, 4, 6, 6, 8]
    assert out == 100 + 4  # restored state + the 4 re-dispatched steps


def test_rollback_without_resume_step_raises():
    eng = make_engine(steps=2, log_interval=1)
    eng.on_log_window(lambda e, win: e.request_rollback())
    eng.on_rollback(lambda e, step, win: None)
    with pytest.raises(RuntimeError, match="no on_rollback hook"):
        eng.run(0)


def test_request_stop_checkpoints_then_exits(capsys):
    eng = make_engine(steps=100, log_interval=2, eval_interval=0)
    ckpts = []
    eng.on_log_window(
        lambda e, win: e.request_stop("drained") if win.step == 4 else None
    )
    eng.on_checkpoint(lambda e, cev: ckpts.append((cev.step, cev.reason)))
    eng.run(0)
    assert eng.exit_reason == "drained"
    assert ckpts == [(4, "preemption")]
    assert "preemption checkpoint at step 4" in capsys.readouterr().out


def test_should_stop_multi_host_waits_for_a_boundary():
    # multi-host: the stop flag set mid-window must not fire until the
    # next log boundary (agreement needs an allgather)
    stops = iter([False, True])
    eng = make_engine(
        steps=100,
        log_interval=3,
        eval_interval=0,
        process_count=2,
        should_stop=lambda: next(stops),
    )
    ckpts = []
    eng.on_checkpoint(lambda e, cev: ckpts.append(cev.step))
    eng.run(0)
    # should_stop consulted only at boundaries: step 3 (False), step 6 (True)
    assert eng.step == 6 and ckpts == [6]
    assert eng.exit_reason == "preempted"


def test_no_duplicate_checkpoint_when_stop_lands_on_eval_boundary():
    eng = make_engine(steps=100, log_interval=2, eval_interval=4)
    ckpts = []
    eng.on_log_window(
        lambda e, win: e.request_stop() if win.step == 4 else None
    )
    eng.on_checkpoint(lambda e, cev: ckpts.append((cev.step, cev.reason)))
    eng.run(0)
    assert ckpts == [(4, "interval")]  # saved_this_step suppresses the second


def test_crash_ladder_runs_crash_then_shutdown_hooks():
    def dispatch(state, batch, step):
        if step == 3:
            raise ValueError("boom")
        return state, {"loss": 0.0}

    eng = make_engine(steps=10, dispatch=dispatch)
    order = []
    eng.on_crash(lambda e, exc: order.append(("crash", type(exc).__name__)))
    eng.on_crash(lambda e, exc: (_ for _ in ()).throw(RuntimeError("hook")))
    eng.on_crash(lambda e, exc: order.append(("crash2", e.exit_reason)))
    eng.on_shutdown(lambda e, reason, s: order.append(("shutdown", reason, s)))
    with pytest.raises(ValueError, match="boom"):
        eng.run(0)
    # a throwing crash hook never masks the real failure or later hooks
    assert order == [
        ("crash", "ValueError"),
        ("crash2", "exception:ValueError"),
        ("shutdown", "exception:ValueError", 3),
    ]


def test_crash_hook_can_reclassify_exit_reason():
    def dispatch(state, batch, step):
        raise ValueError("diverged-ish")

    eng = make_engine(steps=2, dispatch=dispatch)
    reasons = []
    eng.on_crash(lambda e, exc: setattr(e, "exit_reason", "diverged"))
    eng.on_shutdown(lambda e, reason, s: reasons.append(reason))
    with pytest.raises(ValueError):
        eng.run(0)
    assert reasons == ["diverged"]


def test_step_event_metrics_are_mutable_before_buffering():
    eng = make_engine(steps=2, log_interval=2)

    def strip(e, ev):
        m = dict(ev.metrics)
        m.pop("loss")
        ev.metrics = m

    seen = []
    eng.on_step(strip)
    eng.on_log_window(lambda e, win: seen.extend(m for _, m in win.fetched))
    eng.run(0)
    assert seen == [{}, {}]


def test_start_step_resume_boundaries():
    eng = RunEngine(
        training_steps=6,
        start_step=4,
        log_interval=2,
        eval_interval=0,
        next_batch=lambda s: s,
        dispatch=lambda st, b, s: (st, {}),
        fetch=lambda ms: ms,
    )
    logs = []
    eng.on_log_window(lambda e, win: logs.append([s for s, _ in win.fetched]))
    eng.run(0)
    assert logs == [[5, 6]]


# ------------------------------------------------- extraction equivalence

# Captured from the pre-refactor monolithic while-loop (commit 8f63783) on
# the seeded config below: the journal event stream (type, step) and the
# window-mean final loss/grad_norm. The engine-driven loop must reproduce
# both exactly — same events, same order, same arithmetic.
GOLDEN_SEQUENCE = [
    ("run_start", None),
    ("compiled_program", None),
    ("step", 2),
    ("mem_sample", 2),
    ("step", 4),
    ("mem_sample", 4),
    ("checkpoint_save", 4),
    ("goodput_report", 4),
    ("step", 6),
    ("mem_sample", 6),
    ("step", 8),
    ("mem_sample", 8),
    ("checkpoint_save", 8),
    ("goodput_report", 8),
    ("step", 10),
    ("mem_sample", 10),
    ("step", 12),
    ("mem_sample", 12),
    ("checkpoint_save", 12),
    ("goodput_report", 12),
    ("goodput_report", 12),
    ("shutdown", 12),
]
GOLDEN_FINAL = {"train/loss": 1.0147541761398315, "train/grad_norm": 0.3212621212005615}


@pytest.mark.slow
def test_extracted_loop_matches_pre_refactor_golden(tmp_path):
    from jumbo_mae_tpu_tpu.cli.train import train
    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.obs.journal import read_journal

    cfg = load_config(
        "recipes/smoke_cpu.yaml",
        [
            f"run.output_dir={tmp_path}",
            "run.training_steps=12",
            "optim.training_steps=12",
            "run.sanity_eval=false",
            "run.log_interval=2",
            "run.eval_interval=4",
            "run.use_wandb=false",
            # the leak sentinel keys off machine-dependent RSS growth; its
            # events would make the stream nondeterministic
            "run.memwatch_leak_mb=100000",
        ],
    )
    final = train(cfg)
    events = read_journal(f"{tmp_path}/smoke_cpu/journal")
    seq = [(e["type"], e.get("step")) for e in events]
    assert seq == GOLDEN_SEQUENCE, (
        "journal stream diverged from the pre-refactor golden:\n"
        + json.dumps(seq)
    )
    for k, v in GOLDEN_FINAL.items():
        assert final[k] == pytest.approx(v, rel=1e-6), (k, final[k])
