import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.models import (
    ClassificationModel,
    DecoderConfig,
    JumboViT,
    MAEPretrainModel,
    preset,
)

TINY = preset(
    "vit_t16", image_size=32, patch_size=8, dtype="float32", labels=None
)
TINY_DEC = DecoderConfig(layers=1, dim=32, heads=2, dtype="float32")


def _images(n=2, size=32, key=0):
    return jax.random.randint(
        jax.random.key(key), (n, size, size, 3), 0, 256, dtype=jnp.int32
    ).astype(jnp.uint8)


class TestJumboViT:
    def test_mae_mode_shapes(self):
        cfg = TINY.replace(mask_ratio=0.75)
        model = JumboViT(cfg)
        imgs = jnp.zeros((2, 32, 32, 3), jnp.float32)
        vars_ = model.init(
            {"params": jax.random.key(0), "noise": jax.random.key(1)}, imgs
        )
        tokens, mask, ids = model.apply(
            vars_, imgs, rngs={"noise": jax.random.key(2)}
        )
        # 16 patches, keep 4, +3 CLS
        assert tokens.shape == (2, 3 + 4, cfg.dim)
        assert mask.shape == (2, 16)
        assert float(mask.sum(-1)[0]) == 12.0

    def test_classify_mode_logits(self):
        cfg = TINY.replace(labels=10)
        model = JumboViT(cfg)
        imgs = jnp.zeros((2, 32, 32, 3), jnp.float32)
        vars_ = model.init({"params": jax.random.key(0)}, imgs)
        logits = model.apply(vars_, imgs)
        assert logits.shape == (2, 10)

    def test_jumbo_mlp_is_shared_across_blocks(self):
        cfg = TINY.replace(labels=10, layers=3)
        model = JumboViT(cfg)
        vars_ = model.init(
            {"params": jax.random.key(0)}, jnp.zeros((1, 32, 32, 3))
        )
        params = vars_["params"]
        # exactly one jumbo_mlp parameter set, at the encoder level
        assert "jumbo_mlp" in params
        assert params["jumbo_mlp"]["fc1"]["kernel"].shape == (
            3 * cfg.dim,
            12 * cfg.dim,
        )
        for i in range(3):
            assert "jumbo_mlp" not in params[f"block_{i}"]

    def test_linear_probe_stops_gradient(self):
        cfg = TINY.replace(labels=10, linear_probing=True, batch_norm=True)
        model = JumboViT(cfg)
        # distinct random images: with identical samples BatchNorm collapses
        # its output to the zero-init bias and every grad is exactly 0
        imgs = jax.random.normal(jax.random.key(9), (2, 32, 32, 3))
        vars_ = model.init({"params": jax.random.key(0)}, imgs)

        def loss_fn(params):
            logits, _ = model.apply(
                {"params": params, "batch_stats": vars_["batch_stats"]},
                imgs,
                deterministic=False,
                mutable=["batch_stats"],
            )
            return (logits**2).sum()

        grads = jax.grad(loss_fn)(vars_["params"])
        flat = jax.tree_util.tree_leaves_with_path(grads)
        for path, g in flat:
            name = jax.tree_util.keystr(path)
            gnorm = float(jnp.abs(g).sum())
            if "head" in name:
                assert gnorm > 0, f"head grad unexpectedly zero: {name}"
            else:
                assert gnorm == 0, f"trunk grad leaked: {name}"

    def test_gap_pooling(self):
        cfg = TINY.replace(labels=10, pooling="gap")
        model = JumboViT(cfg)
        imgs = jnp.zeros((2, 32, 32, 3), jnp.float32)
        vars_ = model.init({"params": jax.random.key(0)}, imgs)
        assert model.apply(vars_, imgs).shape == (2, 10)

    @pytest.mark.slow  # heavy compile; full suite covers it
    def test_remat_matches_no_remat(self):
        imgs = jax.random.normal(jax.random.key(3), (2, 32, 32, 3))
        cfg = TINY.replace(labels=10)
        vars_ = JumboViT(cfg).init({"params": jax.random.key(0)}, imgs)

        def loss(params, cfg):
            out = JumboViT(cfg).apply({"params": params}, imgs)
            return (out**2).mean()

        g1 = jax.grad(loss)(vars_["params"], cfg)
        g2 = jax.grad(loss)(vars_["params"], cfg.replace(grad_ckpt=True))
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), g1, g2
        )

    def test_remat_policies_match_no_remat(self):
        """Every remat policy must only change WHAT is recomputed, never the
        gradient values (the dots policy is the ViT-H/14 bench default)."""
        imgs = jax.random.normal(jax.random.key(3), (2, 32, 32, 3))
        cfg = TINY.replace(labels=10)
        vars_ = JumboViT(cfg).init({"params": jax.random.key(0)}, imgs)

        def loss(params, cfg):
            out = JumboViT(cfg).apply({"params": params}, imgs)
            return (out**2).mean()

        g1 = jax.grad(loss)(vars_["params"], cfg)
        for policy in ("dots", "dots_no_batch"):
            g2 = jax.grad(loss)(
                vars_["params"], cfg.replace(grad_ckpt=True, remat_policy=policy)
            )
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5), g1, g2
            )


class TestMAEPretrainModel:
    def _build(self, **kw):
        cfg = TINY.replace(mask_ratio=0.75)
        model = MAEPretrainModel(cfg, TINY_DEC, **kw)
        imgs = _images()
        vars_ = model.init(
            {"params": jax.random.key(0), "noise": jax.random.key(1)}, imgs
        )
        return model, vars_, imgs

    def test_loss_finite_and_scalar(self):
        model, vars_, imgs = self._build()
        out = model.apply(vars_, imgs, rngs={"noise": jax.random.key(2)})
        assert out["loss"].shape == ()
        assert np.isfinite(float(out["loss"]))

    def test_norm_pix_loss(self):
        model, vars_, imgs = self._build(norm_pix_loss=True)
        out = model.apply(vars_, imgs, rngs={"noise": jax.random.key(2)})
        assert np.isfinite(float(out["loss"]))

    def test_reconstruction_shape(self):
        model, vars_, imgs = self._build()
        out = model.apply(
            vars_,
            imgs,
            rngs={"noise": jax.random.key(2)},
            return_reconstruction=True,
        )
        assert out["reconstruction"].shape == (2, 16, 8 * 8 * 3)

    def test_loss_only_depends_on_masked_patches(self):
        """Gradient of the loss w.r.t. predictions must be zero on visible
        patches — the loss contract of MAE."""
        model, vars_, imgs = self._build()

        out = model.apply(
            vars_,
            imgs,
            rngs={"noise": jax.random.key(5)},
            return_reconstruction=True,
        )
        mask = np.asarray(out["mask"])
        assert mask.sum() == 2 * 12  # 16 patches, keep 4


class TestClassificationModel:
    def test_metrics_shapes(self):
        cfg = TINY.replace(labels=10)
        model = ClassificationModel(cfg, label_smoothing=0.1)
        imgs, labels = _images(4), jnp.array([1, 2, 3, 4])
        vars_ = model.init({"params": jax.random.key(0)}, imgs, labels)
        out = model.apply(vars_, imgs, labels)
        assert out["loss"].shape == (4,)
        assert out["acc1"].shape == (4,)
        assert set(np.unique(np.asarray(out["acc5"]))) <= {0.0, 1.0}

    def test_train_path_with_mixup(self):
        cfg = TINY.replace(labels=10)
        model = ClassificationModel(
            cfg, mixup_alpha=0.8, cutmix_alpha=1.0, label_smoothing=0.1
        )
        imgs, labels = _images(4), jnp.array([1, 2, 3, 4])
        vars_ = model.init({"params": jax.random.key(0)}, imgs, labels)
        out = model.apply(
            vars_,
            imgs,
            labels,
            deterministic=False,
            rngs={"mixup": jax.random.key(1), "dropout": jax.random.key(2)},
        )
        assert np.isfinite(np.asarray(out["loss"])).all()

    def test_perfect_prediction_acc(self):
        cfg = TINY.replace(labels=10)
        model = ClassificationModel(cfg)
        imgs, labels = _images(2), jnp.array([0, 1])
        vars_ = model.init({"params": jax.random.key(0)}, imgs, labels)
        out = model.apply(vars_, imgs, labels)
        # with random init acc is whatever it is, but all values must be 0/1
        assert set(np.unique(np.asarray(out["acc1"]))) <= {0.0, 1.0}


class TestMixupOps:
    def test_identity_when_disabled(self):
        from jumbo_mae_tpu_tpu.ops.mixup import mixup_cutmix

        imgs = jax.random.normal(jax.random.key(0), (4, 8, 8, 3))
        labels = jax.nn.one_hot(jnp.array([0, 1, 2, 3]), 10)
        out_i, out_l = mixup_cutmix(jax.random.key(1), imgs, labels, 0.0, 0.0)
        np.testing.assert_array_equal(np.asarray(out_i), np.asarray(imgs))

    def test_label_mass_conserved(self):
        from jumbo_mae_tpu_tpu.ops.mixup import mixup_cutmix

        imgs = jax.random.normal(jax.random.key(0), (8, 16, 16, 3))
        labels = jax.nn.one_hot(jnp.arange(8) % 4, 10)
        for ma, ca in [(0.8, 0.0), (0.0, 1.0), (0.8, 1.0)]:
            _, out_l = mixup_cutmix(jax.random.key(2), imgs, labels, ma, ca)
            np.testing.assert_allclose(
                np.asarray(out_l.sum(-1)), np.ones(8), rtol=1e-5
            )


def test_config_rejects_indivisible_heads():
    """head_dim = dim // heads must not floor silently (advisor round-4):
    the recipe surface (--set model.dec_heads=...) lands on these configs."""
    from jumbo_mae_tpu_tpu.models.config import JumboViTConfig

    with pytest.raises(ValueError, match="divisible"):
        JumboViTConfig(dim=768, heads=7)
    with pytest.raises(ValueError, match="divisible"):
        DecoderConfig(dim=512, heads=7)
    with pytest.raises(ValueError, match="divisible"):
        DecoderConfig(dim=512, heads=16).replace(heads=3)
    # valid ones still construct
    assert JumboViTConfig(dim=768, heads=12).head_dim == 64
    assert DecoderConfig(dim=512, heads=2).head_dim == 256
