"""Attention implementations must agree: einsum (parity oracle) vs blockwise
XLA vs the Pallas kernel (interpreter mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.ops.blockwise_attention import blockwise_attention
from jumbo_mae_tpu_tpu.ops.flash_attention import xla_attention
from jumbo_mae_tpu_tpu.ops.pallas.attention import pallas_flash_attention


def qkv(b=2, s=128, h=4, d=32, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    q, k, v = (jax.random.normal(kk, shape, dtype) for kk in ks)
    return q * d**-0.5, k, v


class TestBlockwise:
    @pytest.mark.parametrize("block_k", [32, 64, 128])
    def test_matches_naive(self, block_k):
        q, k, v = qkv()
        ref = xla_attention(q, k, v)
        got = blockwise_attention(q, k, v, block_k=block_k)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)

    def test_ragged_seq_padding(self):
        q, k, v = qkv(s=100)  # not divisible by block
        ref = xla_attention(q, k, v)
        got = blockwise_attention(q, k, v, block_k=64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)

    def test_gradients_match_naive(self):
        q, k, v = qkv(s=64)

        def loss_naive(q, k, v):
            return (xla_attention(q, k, v) ** 2).sum()

        def loss_block(q, k, v):
            return (blockwise_attention(q, k, v, block_k=16) ** 2).sum()

        g_ref = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
        g_got = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_got, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_bias(self):
        q, k, v = qkv(s=64)
        bias = jax.random.normal(jax.random.key(7), (1, 1, 64, 64))
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) + bias
        probs = jax.nn.softmax(logits, -1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        got = blockwise_attention(q, k, v, block_k=16, bias=bias)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)

    def test_bias_with_padding(self):
        # full-length key axis bias + seq_k not divisible by block_k
        q, k, v = qkv(s=100)
        bias = jax.random.normal(jax.random.key(8), (1, 1, 100, 100))
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) + bias
        probs = jax.nn.softmax(logits, -1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        got = blockwise_attention(q, k, v, block_k=64, bias=bias)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5)


class TestPallasKernel:
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_forward_matches_naive_interpret(self, dtype):
        q, k, v = qkv(s=256, d=128, dtype=dtype)
        ref = xla_attention(q, k, v)
        got = pallas_flash_attention(q, k, v, 64, 64, True)
        atol = 2e-5 if dtype == jnp.float32 else 2e-2
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(ref, np.float32), atol=atol
        )

    @pytest.mark.parametrize("s", [199, 55, 130])
    def test_forward_ragged_seq_interpret(self, s):
        """MAE shapes (decoder 196+3, encoder 49+3·…) don't divide the block:
        the kernel pads internally and masks pad keys."""
        q, k, v = qkv(s=s, d=32)
        ref = xla_attention(q, k, v)
        got = pallas_flash_attention(q, k, v, 128, 128, True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), atol=2e-5
        )

    def test_backward_ragged_seq(self):
        q, k, v = qkv(s=199, d=32)

        def loss(q, k, v):
            return (pallas_flash_attention(q, k, v, 128, 128, True) ** 2).sum()

        def loss_ref(q, k, v):
            return (xla_attention(q, k, v) ** 2).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

    def test_backward_kernel_matches_naive(self):
        q, k, v = qkv(s=128, d=128)

        def loss(q, k, v):
            return (pallas_flash_attention(q, k, v, 64, 64, True) ** 2).sum()

        def loss_ref(q, k, v):
            return (xla_attention(q, k, v) ** 2).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.slow  # heavy compile; full suite covers it
def test_lane_128_fallback_env_knob():
    """JUMBO_PALLAS_LANE=128 (the documented escape hatch for TPU
    generations where Mosaic rejects sub-128 minor dims) must produce the
    same forward and gradients. LANE is bound at import, so run in a fresh
    interpreter."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["JUMBO_PALLAS_LANE"] = "128"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(repo) + os.pathsep + env.get("PYTHONPATH", "")
    code = """
import jax, jax.numpy as jnp, numpy as np
jax.config.update('jax_platforms', 'cpu')
from jumbo_mae_tpu_tpu.ops.pallas import attention as A
assert A.LANE == 128, A.LANE
k0 = jax.random.key(0)
q, k, v = (jax.random.normal(jax.random.fold_in(k0, i), (2, 199, 2, 32), jnp.float32) for i in range(3))
def ref(q, k, v):
    p = jax.nn.softmax(jnp.einsum('bqhd,bkhd->bhqk', q, k), -1)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v)
def flash(q, k, v):
    return A.pallas_flash_attention(q, k, v, 128, 128, True)
np.testing.assert_allclose(np.asarray(flash(q, k, v)), np.asarray(ref(q, k, v)), atol=2e-5)
g = jax.grad(lambda *a: (flash(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
gr = jax.grad(lambda *a: (ref(*a) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
for a, b in zip(g, gr):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=2e-4)
print('LANE128-OK')
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "LANE128-OK" in proc.stdout


def test_resolve_attn_impl_auto_policy():
    """The auto policy (round 5): flash on TPU at long sequence unless
    dropout is active in training; einsum otherwise; explicit impls pass
    through untouched."""
    from jumbo_mae_tpu_tpu.models.layers import (
        AUTO_FLASH_MIN_SEQ,
        resolve_attn_impl,
    )

    r = lambda **kw: resolve_attn_impl(
        kw.pop("impl", "auto"),
        backend=kw.pop("backend", "tpu"),
        seq_len=kw.pop("seq_len", AUTO_FLASH_MIN_SEQ),
        dropout=kw.pop("dropout", 0.0),
        deterministic=kw.pop("deterministic", False),
    )
    assert r() == "flash"                                   # long seq, tpu
    assert r(seq_len=AUTO_FLASH_MIN_SEQ - 1) == "einsum"    # short seq
    assert r(backend="cpu") == "einsum"                     # not tpu
    assert r(dropout=0.1) == "einsum"                       # train dropout
    assert r(dropout=0.1, deterministic=True) == "flash"    # eval dropout ok
    assert r(impl="einsum", seq_len=4096) == "einsum"       # explicit wins
    assert r(impl="flash", seq_len=8) == "flash"
    assert r(impl="ring", backend="cpu") == "ring"
