"""Tenant cost-accounting contracts (serve/costmeter.py + budget admission).

What the metering layer must guarantee:

- **conservation**: per-tenant device-seconds sum to the measured batch
  wall-times, and per-tenant FLOPs sum to executable FLOPs x batches —
  exactly in the unit tests, within 1% end-to-end through the continuous
  scheduler across aligned / partial / priority-jump dispatch paths and
  under replica crash faults;
- **attribution**: padded rows bill the *dispatching* tenants' waste
  accounts (waste is a split of the total, never on top of it); unknown
  tenants accrue to ``_default`` rather than vanishing;
- **stamping**: every ok access-log row carries the meter's ``device_ms``
  / ``cost_flops`` columns;
- **budgets**: an over-budget tenant degrades to scavenger-class pressure
  (shed at 0.5 with a typed :class:`TenantBudgetError`, ``reason=budget``
  metrics), is still admitted at low pressure, and never affects other
  tenants' admission;
- **visibility**: every configured tenant's ``serve_admit_*`` and
  ``serve_tenant_*`` children render (at zero) from construction, and the
  meter journals ``tenant_usage`` rows.
"""

import threading
import time
from concurrent.futures import wait

import numpy as np
import pytest

from jumbo_mae_tpu_tpu import faults
from jumbo_mae_tpu_tpu.obs import AccessLog, RequestTracer
from jumbo_mae_tpu_tpu.obs.costmodel import ProgramCost, lookup_cost
from jumbo_mae_tpu_tpu.obs.journal import read_journal
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry
from jumbo_mae_tpu_tpu.obs.reqtrace import RequestTrace
from jumbo_mae_tpu_tpu.infer import ReplicaSet
from jumbo_mae_tpu_tpu.serve import (
    AdmissionController,
    ContinuousScheduler,
    CostMeter,
    TenantBudgetError,
    parse_tenants,
)


@pytest.fixture
def fault_plan():
    yield faults.install_plan
    faults.clear_plan()


def _img(v=0.0):
    return np.full((2, 2, 3), v, np.float32)


def run_echo(eng, batch, metas):
    return {"y": batch[:, 0, 0, 0].astype(np.float64)}


class StubEngine:
    def __init__(self, idx):
        self.idx = idx


def make_pool(reg, tracer=None, *, replicas=2, run=run_echo, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_delay_ms", 1.0)
    kw.setdefault("supervise_interval_s", 0.02)
    kw.setdefault("restart_backoff_s", 0.05)
    return ReplicaSet(
        lambda i: StubEngine(i), run, replicas=replicas, registry=reg,
        tracer=tracer, **kw,
    )


def _trace(rid, tenant, tclass="batch", *, bucket=None, pad=None, task="t"):
    tr = RequestTrace(rid, task, None, tenant, tclass)
    tr.bucket = bucket
    tr.pad_fraction = pad
    return tr


class RecordingMeter(CostMeter):
    """CostMeter that also keeps the raw batch-level measurements the
    ledgers must reconcile against."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.observed: list[tuple[float, int]] = []

    def observe_batch(self, *, run_s, traces, batch, engine=None):
        if any(tr is not None for tr in traces):
            self.observed.append((float(run_s), int(batch)))
        super().observe_batch(
            run_s=run_s, traces=traces, batch=batch, engine=engine
        )


# ------------------------------------------------------------ unit: meter


def test_observe_batch_conserves_time_and_flops_exactly():
    reg = MetricsRegistry()
    meter = CostMeter(
        parse_tenants("a=interactive,b=batch"),
        registry=reg,
        cost_fn=lambda eng, task, bucket: {"flops": bucket * 100.0},
    )
    # batch of 3 occupied rows in a bucket of 4: pad fraction 0.25
    traces = [
        _trace(0, "a", "interactive", bucket=4, pad=0.25),
        _trace(1, "a", "interactive", bucket=4, pad=0.25),
        _trace(2, "b", "batch", bucket=4, pad=0.25),
    ]
    meter.observe_batch(run_s=0.9, traces=traces, batch=3)
    snap = meter.snapshot()
    a, b = snap["tenants"]["a"], snap["tenants"]["b"]
    # whole wall-time split across occupied rows: 0.3 each
    assert a["device_s"] == pytest.approx(0.6)
    assert b["device_s"] == pytest.approx(0.3)
    assert a["device_s"] + b["device_s"] == pytest.approx(0.9)
    # whole executable FLOPs (bucket x 100 = 400) split across 3 rows
    assert a["flops"] + b["flops"] == pytest.approx(400.0)
    assert snap["total_flops"] == pytest.approx(400.0)
    # waste is a split of the total: run_s x pad, equally per trace
    waste = a["waste_device_s"] + b["waste_device_s"]
    assert waste == pytest.approx(0.9 * 0.25)
    assert a["waste_device_s"] == pytest.approx(2 * waste / 3)
    # traces got stamped for the access-log row
    assert traces[0].device_s == pytest.approx(0.3)
    assert traces[0].cost_flops == pytest.approx(400.0 / 3)
    # counters rendered
    text = reg.render()
    assert 'serve_tenant_device_seconds_total{tenant="a",class="interactive"}' in text
    assert 'serve_tenant_requests_total{tenant="b",class="batch"} 1' in text


def test_observe_batch_unknown_tenant_accrues_to_default():
    meter = CostMeter(registry=MetricsRegistry(), cost_fn=None)
    meter.observe_batch(
        run_s=0.5, traces=[_trace(0, None, None)], batch=1
    )
    snap = meter.snapshot()
    assert snap["tenants"]["_default"]["device_s"] == pytest.approx(0.5)
    assert snap["tenants"]["_default"]["requests"] == 1


def test_observe_batch_survives_broken_cost_fn_and_bills_time():
    def boom(engine, task, bucket):
        raise RuntimeError("no cost table")

    meter = CostMeter(registry=MetricsRegistry(), cost_fn=boom)
    tr = _trace(0, "a", bucket=2, pad=0.5)
    meter.observe_batch(run_s=0.2, traces=[tr], batch=1)
    snap = meter.snapshot()
    assert snap["tenants"]["a"]["device_s"] == pytest.approx(0.2)
    assert snap["tenants"]["a"]["flops"] == 0.0
    assert tr.device_s == pytest.approx(0.2)
    assert tr.cost_flops is None  # no basis, no column


def test_window_usage_prunes_old_samples():
    t = {"now": 0.0}
    meter = CostMeter(
        parse_tenants("a=batch:budget=1:window=60"),
        registry=MetricsRegistry(),
        cost_fn=None,
        clock=lambda: t["now"],
    )
    meter.observe_batch(run_s=0.7, traces=[_trace(0, "a")], batch=1)
    t["now"] = 30.0
    meter.observe_batch(run_s=0.5, traces=[_trace(1, "a")], batch=1)
    assert meter.window_usage("a", 60.0) == pytest.approx(1.2)
    assert meter.over_budget("a")
    t["now"] = 80.0  # first sample ages out of the 60s window
    assert meter.window_usage("a", 60.0) == pytest.approx(0.5)
    assert not meter.over_budget("a")
    # lifetime ledger keeps both
    assert meter.snapshot()["tenants"]["a"]["device_s"] == pytest.approx(1.2)


def test_meter_journals_tenant_usage_rows(tmp_path):
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=MetricsRegistry(), access_log=log)
    meter = CostMeter(
        parse_tenants("a=batch:budget=0.1"),
        registry=MetricsRegistry(),
        cost_fn=None,
        tracer=tracer,
    )
    meter.observe_batch(run_s=0.4, traces=[_trace(0, "a")], batch=1)
    meter.flush()
    tracer.close()
    rows = [
        r for r in read_journal(tmp_path / "access")
        if r.get("type") == "tenant_usage"
    ]
    assert rows, "flush() must force a tenant_usage emission"
    last = rows[-1]
    assert last["tenant"] == "a" and last["class"] == "batch"
    assert last["device_s"] == pytest.approx(0.4)
    assert last["budget_device_s"] == pytest.approx(0.1)
    assert last["over_budget"] is True


def test_lookup_cost_resolves_exact_pooled_and_fallback_keys():
    c1 = ProgramCost("features", flops=10.0)
    c2 = ProgramCost("features/mean", flops=20.0)
    c3 = ProgramCost("recon", flops=30.0)
    table = {("features", 8): c1, ("features/mean", 16): c2, ("recon", 32): c3}
    assert lookup_cost(table, "features", 8) is c1       # exact
    assert lookup_cost(table, "features", 16) is c2      # pool-suffixed
    assert lookup_cost(table, "features", 32) is c3      # same-bucket fallback
    assert lookup_cost(table, "features", 64) is None    # bucket never built
    assert lookup_cost({}, "features", 8) is None
    assert lookup_cost(None, "features", 8) is None


# --------------------------------------------------- unit: budget admission


def test_admission_registers_metrics_for_all_tenants_eagerly():
    reg = MetricsRegistry()
    AdmissionController(
        parse_tenants("web=interactive:rate=5,bg=scavenger:budget=1"),
        registry=reg,
    )
    text = reg.render()
    # zero-valued children exist before any admit/shed event
    assert 'serve_admit_total{tenant="web",class="interactive"} 0' in text
    assert 'serve_admit_total{tenant="bg",class="scavenger"} 0' in text
    for reason in ("quota", "pressure", "budget"):
        assert (
            f'serve_admit_shed_total{{tenant="bg",class="scavenger",'
            f'reason="{reason}"}} 0' in text
        )
    assert (
        'serve_tenant_budget_remaining{tenant="bg",class="scavenger"} 1'
        in text
    )


def test_budget_exhaustion_degrades_to_scavenger_pressure():
    reg = MetricsRegistry()
    specs = parse_tenants("pay=batch:budget=1:window=60,free=batch")
    meter = CostMeter(specs, registry=MetricsRegistry(), cost_fn=None)
    pressure = {"v": 0.0}
    adm = AdmissionController(
        specs, meter=meter, registry=reg, pressure_fn=lambda: pressure["v"]
    )
    # under budget: admitted at any sub-class pressure
    assert adm.admit("pay").name == "pay"
    # spend past the budget
    meter.observe_batch(
        run_s=1.5, traces=[_trace(0, "pay")], batch=1
    )
    # over budget + zero pressure: still admitted (budgets don't hard-kill)
    assert adm.admit("pay").name == "pay"
    # over budget + scavenger-level pressure: typed budget shed...
    pressure["v"] = 0.6
    with pytest.raises(TenantBudgetError):
        adm.admit("pay")
    # ...while an unbudgeted batch-class tenant at the same pressure passes
    assert adm.admit("free").name == "free"
    assert adm.stats()["shed"] == {"pay:budget": 1}
    text = reg.render()
    assert (
        'serve_admit_shed_total{tenant="pay",class="batch",reason="budget"} 1'
        in text
    )
    assert (
        'serve_tenant_budget_remaining{tenant="pay",class="batch"} 0' in text
    )
    # window rolls -> budget restored (fresh meter models the rolled window)
    adm.set_meter(CostMeter(specs, registry=MetricsRegistry(), cost_fn=None))
    assert adm.admit("pay").name == "pay"


def test_parse_tenants_budget_grammar_and_errors():
    ts = parse_tenants("pay=batch:rate=5:budget=2.5:window=30")
    assert ts[0].budget == 2.5 and ts[0].budget_window_s == 30.0
    assert ts[0].rate == 5.0
    # defaults stay None so existing positional equality holds
    assert parse_tenants("a=batch")[0].budget is None
    with pytest.raises(ValueError, match="unknown tenant option"):
        parse_tenants("a=batch:budgit=2")
    with pytest.raises(ValueError, match="budget must be > 0"):
        parse_tenants("a=batch:budget=0")
    with pytest.raises(ValueError, match="window must be > 0"):
        parse_tenants("a=batch:budget=1:window=-5")


def test_scheduler_stamps_budget_shed_reason_in_access_row(tmp_path):
    reg = MetricsRegistry()
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=reg, access_log=log)
    specs = parse_tenants("pay=batch:budget=0.1:window=60")
    meter = CostMeter(specs, registry=reg, cost_fn=None)
    meter.observe_batch(run_s=1.0, traces=[_trace(0, "pay")], batch=1)
    adm = AdmissionController(
        specs, meter=meter, registry=reg, pressure_fn=lambda: 0.6
    )

    def dispatch(batch):  # never reached: the submit sheds
        raise AssertionError("budget shed must happen at admission")

    sched = ContinuousScheduler(
        dispatch, max_batch=4, max_delay_ms=5.0, admission=adm,
        tracer=tracer, registry=reg,
    )
    try:
        with pytest.raises(TenantBudgetError):
            sched.submit(_img(), tenant="pay")
    finally:
        sched.close()
        tracer.close()
    rows = [
        r for r in read_journal(tmp_path / "access")
        if r.get("type") == "request"
    ]
    assert len(rows) == 1
    assert rows[0]["outcome"] == "shed"
    assert rows[0]["err"] == "TenantBudgetError"


# ------------------------------------- end to end: conservation through serve


def _assert_conserved(meter, snap):
    """Ledger totals must reconcile with the recorded batch measurements
    within 1% (acceptance criterion), and per-tenant sums with the ledger
    totals to float precision."""
    measured_s = sum(s for s, _ in meter.observed)
    per_tenant_s = sum(b["device_s"] for b in snap["tenants"].values())
    per_tenant_f = sum(b["flops"] for b in snap["tenants"].values())
    assert snap["total_batches"] == len(meter.observed)
    assert per_tenant_s == pytest.approx(snap["total_device_s"], rel=1e-9)
    assert per_tenant_f == pytest.approx(snap["total_flops"], rel=1e-9)
    assert per_tenant_s == pytest.approx(measured_s, rel=0.01)


def test_cost_conservation_across_dispatch_paths(tmp_path):
    """Aligned full batches, bucket-aligned partial dispatch, and the
    priority queue-jump all land in the meter, and the ledgers reconcile
    with the per-batch wall-times."""
    reg = MetricsRegistry()
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=reg, access_log=log)
    specs = parse_tenants("vip=interactive,fill=scavenger")
    meter = RecordingMeter(
        specs,
        registry=reg,
        cost_fn=lambda eng, task, bucket: {"flops": bucket * 1e6},
    )

    def run(eng, batch, metas):
        time.sleep(0.003)
        return run_echo(eng, batch, metas)

    rs = make_pool(
        reg, tracer, replicas=2, run=run, max_batch=8, costmeter=meter
    )
    adm = AdmissionController(specs, registry=reg)
    sched = ContinuousScheduler(
        rs.submit_group, max_batch=8, max_delay_ms=10.0, admission=adm,
        tracer=tracer, registry=reg,
    )
    futs = []
    try:
        # aligned: one full batch of 8
        futs += [sched.submit(_img(i), tenant="fill") for i in range(8)]
        wait(futs, timeout=10)
        # partial: 3 due entries dispatch bucket-aligned
        futs += [sched.submit(_img(i), tenant="vip") for i in range(3)]
        wait(futs, timeout=10)
    finally:
        sched.close()
    # priority jump: a small gated scheduler whose accumulator overfills
    # while the dispatcher is blocked, so the vips jump the queue
    gate = threading.Event()

    def gated_dispatch(group):
        gate.wait(5.0)
        return rs.submit_group(group)

    sched2 = ContinuousScheduler(
        gated_dispatch, max_batch=2, max_delay_ms=5.0, admission=adm,
        tracer=tracer, registry=reg,
    )
    try:
        blockers = [sched2.submit(_img(0), tenant="fill") for _ in range(2)]
        time.sleep(0.05)
        late = [sched2.submit(_img(1), tenant="fill") for _ in range(2)]
        time.sleep(0.02)
        vips = [sched2.submit(_img(2), tenant="vip") for _ in range(2)]
        gate.set()
        futs += blockers + late + vips
        done, not_done = wait(futs, timeout=20)
        assert not not_done
    finally:
        sched2.close()
        rs.close()
        meter.flush()
        tracer.close()
    jumps = reg.snapshot()["serve_sched_priority_jumps_total"][""]
    assert jumps >= 2
    snap = meter.snapshot()
    _assert_conserved(meter, snap)
    # both tenants billed, all ok rows stamped
    assert snap["tenants"]["vip"]["device_s"] > 0
    assert snap["tenants"]["fill"]["device_s"] > 0
    rows = [
        r for r in read_journal(tmp_path / "access")
        if r.get("type") == "request" and r["outcome"] == "ok"
    ]
    assert rows
    assert all(r.get("device_ms", 0) > 0 for r in rows)
    assert all(r.get("cost_flops", 0) > 0 for r in rows)
    # per-tenant row sums reconcile with the ledger (every row traced)
    row_s = sum(r["device_ms"] for r in rows) / 1000.0
    assert row_s == pytest.approx(snap["total_device_s"], rel=0.01)


def test_cost_conservation_under_replica_crash_faults(tmp_path, fault_plan):
    """Acceptance: with serve.replica crash faults active, every ok row
    still carries nonzero device_ms/cost_flops and the ledgers reconcile
    within 1% — crashed batches are requeued, not billed."""
    fault_plan("serve.replica:raise(RuntimeError)@key~r1")
    reg = MetricsRegistry()
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=reg, access_log=log)
    specs = parse_tenants("vip=interactive,crawl=batch")
    meter = RecordingMeter(
        specs,
        registry=reg,
        cost_fn=lambda eng, task, bucket: {"flops": bucket * 1e6},
    )

    def run(eng, batch, metas):
        time.sleep(0.002)
        return run_echo(eng, batch, metas)

    rs = make_pool(
        reg, tracer, replicas=3, run=run, max_queue=None, costmeter=meter
    )
    adm = AdmissionController(specs, registry=reg)
    sched = ContinuousScheduler(
        rs.submit_group, max_batch=8, max_delay_ms=2.0, max_queue=None,
        admission=adm, tracer=tracer, registry=reg,
    )
    futs = []
    try:
        for i in range(60):
            futs.append(
                sched.submit(_img(i), tenant=("vip", "crawl")[i % 2])
            )
            if i % 7 == 0:
                time.sleep(0.004)  # vary batch sizes across buckets
        done, not_done = wait(futs, timeout=60)
        assert not not_done
    finally:
        sched.close()
        rs.close()
        meter.flush()
        tracer.close()
    ok = [f for f in futs if f.exception() is None]
    assert ok, "survivors must absorb the crash storm"
    snap = meter.snapshot()
    _assert_conserved(meter, snap)
    rows = [
        r for r in read_journal(tmp_path / "access")
        if r.get("type") == "request"
    ]
    ok_rows = [r for r in rows if r["outcome"] == "ok"]
    assert len(ok_rows) == len(ok)
    assert all(r.get("device_ms", 0) > 0 for r in ok_rows)
    assert all(r.get("cost_flops", 0) > 0 for r in ok_rows)
    # requeued-off-r1 requests were billed once, on the surviving replica
    assert all(r.get("replica") != "r1" for r in ok_rows)


def test_observe_batch_token_pro_rata_split():
    """A packed group carries per-trace token counts: the 96-token request
    did 3x the work of each 32-token one, so time/flops/waste split by
    token share — and the conservation law still holds exactly."""
    meter = CostMeter(
        parse_tenants("a=interactive,b=batch"),
        registry=MetricsRegistry(),
        cost_fn=lambda eng, task, bucket: {"flops": 1600.0},
    )
    traces = [
        _trace(0, "a", "interactive", bucket=160, pad=0.2),
        _trace(1, "b", "batch", bucket=160, pad=0.2),
        _trace(2, "b", "batch", bucket=160, pad=0.2),
    ]
    traces[0].tokens = 96
    traces[1].tokens = 32
    traces[2].tokens = 32
    meter.observe_batch(run_s=0.8, traces=traces, batch=3)
    snap = meter.snapshot()
    a, b = snap["tenants"]["a"], snap["tenants"]["b"]
    # 96/160 of the wall time to a, 64/160 to b — not an equal thirds split
    assert a["device_s"] == pytest.approx(0.8 * 96 / 160)
    assert b["device_s"] == pytest.approx(0.8 * 64 / 160)
    assert a["device_s"] + b["device_s"] == pytest.approx(0.8)
    assert a["flops"] == pytest.approx(1600.0 * 96 / 160)
    assert a["flops"] + b["flops"] == pytest.approx(1600.0)
    # waste (run_s x pad) splits by the same shares
    waste = a["waste_device_s"] + b["waste_device_s"]
    assert waste == pytest.approx(0.8 * 0.2)
    assert a["waste_device_s"] == pytest.approx(waste * 96 / 160)


def test_observe_batch_partial_tokens_falls_back_to_uniform():
    """Any trace missing its token count disables the token split for the
    whole group — a half-priced group would break conservation."""
    meter = CostMeter(
        parse_tenants("a=interactive,b=batch"),
        registry=MetricsRegistry(),
        cost_fn=None,
    )
    traces = [
        _trace(0, "a", "interactive", bucket=2, pad=0.0),
        _trace(1, "b", "batch", bucket=2, pad=0.0),
    ]
    traces[0].tokens = 96  # trace 1 has none
    meter.observe_batch(run_s=1.0, traces=traces, batch=2)
    snap = meter.snapshot()
    assert snap["tenants"]["a"]["device_s"] == pytest.approx(0.5)
    assert snap["tenants"]["b"]["device_s"] == pytest.approx(0.5)
