"""Inference engine + micro-batcher contracts.

The three properties the serving path stands on:

- padded-bucket inference is *provably inert*: valid rows are bit-identical
  (f32) whatever the padding holds, and bit-identical to an unpadded
  forward of the same rows;
- the executable cache compiles each (task, bucket) exactly once — the hot
  path never compiles (asserted through the compile-count hook);
- the micro-batcher respects ``max_batch``/``max_delay_ms`` and preserves
  request→response ordering under a concurrent thread storm.
"""

import threading
import time

import jax
import numpy as np
import pytest

from jumbo_mae_tpu_tpu.config import load_config
from jumbo_mae_tpu_tpu.infer import (
    InferenceEngine,
    MicroBatcher,
    OversizedBatchError,
    bucket_for,
)

RECIPE_OVERRIDES = [
    # tiny f32 config — the exact path the bit-identity contract runs on
    "model.overrides.dtype=float32",
    "model.dec_layers=1",
    "model.dec_dim=32",
    "model.dec_heads=2",
    "model.dec_dtype=float32",
]


def tiny_cfg(extra=()):
    from pathlib import Path

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    return load_config(recipe, RECIPE_OVERRIDES + list(extra))


@pytest.fixture(scope="module")
def engine():
    return InferenceEngine(tiny_cfg(), max_batch=8)


def _images(n, size=32, seed=0):
    return (
        np.random.RandomState(seed).randint(0, 256, (n, size, size, 3))
    ).astype(np.uint8)


# ---------------------------------------------------------------- engine


def test_bucket_for():
    assert [bucket_for(n, 8) for n in (1, 2, 3, 4, 5, 7, 8)] == [
        1, 2, 4, 4, 8, 8, 8,
    ]
    with pytest.raises(ValueError):
        bucket_for(0, 8)
    # regression: n > max_batch used to silently return max_batch, so an
    # admitted 9-row batch was served by the bucket-8 executable and rows
    # 8+ were silently DROPPED by the dispatch slice — now a typed error
    # the caller can map to HTTP 413 (predict() still chunks upstream)
    with pytest.raises(OversizedBatchError):
        bucket_for(9, 8)
    with pytest.raises(OversizedBatchError):
        bucket_for(100, 8)
    # non-power-of-two max_batch is the ladder's last rung, not rounded up
    # past the admission limit
    assert bucket_for(5, 6) == 6
    assert bucket_for(33, 48) == 48
    assert bucket_for(4, 6) == 4


def test_padded_bucket_bit_identical(engine):
    """Valid rows must not depend on the padding (zeros vs real images in
    the same bucket) and must equal the unpadded forward bit-for-bit on the
    f32 path."""
    imgs8 = _images(8)
    imgs5 = imgs8[:5]

    f5 = engine.features(imgs5)  # bucket 8, rows 5..7 zero-padded
    f8 = engine.features(imgs8)  # same bucket, rows 5..7 real images
    np.testing.assert_array_equal(f5, f8[:5])

    # unpadded forward through a plain jit of the same module
    from jumbo_mae_tpu_tpu.models import pool_tokens
    from jumbo_mae_tpu_tpu.ops.preprocess import normalize_images

    t = engine._task("features")
    model = t["model"]
    params = t["variables"]["params"]
    enc = engine._enc

    @jax.jit
    def raw(params, images):
        x = normalize_images(images, dtype=enc.compute_dtype)
        tokens = model.apply({"params": params}, x, True)
        return pool_tokens(tokens, enc.num_cls_tokens, "cls").astype(np.float32)

    # at the bucket's own shape the AOT executable IS the jit program —
    # bit-identical
    np.testing.assert_array_equal(f8, np.asarray(raw(params, imgs8)))
    # across batch shapes XLA may pick different kernels (f32 reduction
    # order), so the unpadded batch-5 program is equal to float32 eps —
    # the bit-level contract above already proves the padding itself can
    # never leak into a valid row
    np.testing.assert_allclose(
        f5, np.asarray(raw(params, imgs5)), rtol=1e-5, atol=1e-6
    )


def test_executable_cache_compiles_each_bucket_exactly_once():
    compiles = []
    eng = InferenceEngine(
        tiny_cfg(), max_batch=8, on_compile=lambda key, b: compiles.append((key, b))
    )
    for n in (3, 4, 2, 4, 3, 8, 5, 1, 7):
        eng.features(_images(n, seed=n))
    # buckets hit: 4, 4, 2, 4, 4, 8, 8, 1, 8 → {1, 2, 4, 8} once each
    assert sorted(b for _, b in compiles) == [1, 2, 4, 8]
    assert all(c == 1 for c in eng.compile_counts.values())
    before = list(compiles)
    eng.features(_images(6))  # bucket 8 again — cache hit, no compile
    assert compiles == before


def test_chunking_matches_direct(engine):
    """Requests larger than max_batch split into max_batch slabs and
    concatenate back in order."""
    imgs = _images(19, seed=3)  # 8 + 8 + 3 under max_batch=8
    out = engine.features(imgs)
    assert out.shape[0] == 19
    np.testing.assert_array_equal(out[:8], engine.features(imgs[:8]))
    np.testing.assert_array_equal(out[16:], engine.features(imgs[16:]))


def test_logits_and_reconstruct_tasks():
    eng = InferenceEngine(tiny_cfg(), max_batch=4, labels=11)
    imgs = _images(5, seed=4)
    lg = eng.logits(imgs)  # 5 > max_batch → chunks of 4 + 1
    assert lg.shape == (5, 11) and np.isfinite(lg).all()

    out = eng.reconstruct(imgs[:3], seed=0)
    n_patches = (32 // 4) ** 2  # smoke recipe: 32px, patch 4
    assert out["reconstruction"].shape == (3, n_patches, 4 * 4 * 3)
    assert out["mask"].shape == (3, n_patches)
    again = eng.reconstruct(imgs[:3], seed=0)
    np.testing.assert_array_equal(out["mask"], again["mask"])
    other = eng.reconstruct(imgs[:3], seed=1)
    assert not np.array_equal(out["mask"], other["mask"])
    # reseeding went through the traced scalar — no new executable
    assert eng.compile_counts[("reconstruct", 4)] == 1


def test_engine_rejects_bad_inputs(engine):
    with pytest.raises(ValueError, match="resize upstream"):
        engine.features(_images(2, size=16))
    with pytest.raises(ValueError, match="pool"):
        engine.features(_images(2), pool="bogus")
    with pytest.raises(ValueError, match="unknown task"):
        engine.predict(_images(2), task="bogus")
    with pytest.raises(ValueError, match="label count"):
        InferenceEngine(tiny_cfg(), max_batch=2).logits(_images(1))


def test_engine_restores_checkpoint(tmp_path):
    """A differently-seeded pretrain tree must change features; a junk tree
    must refuse (same require_loaded guard as the export tools); and the
    restore path reads params through restore_inference_state."""
    from jumbo_mae_tpu_tpu.cli.train import build_model
    from jumbo_mae_tpu_tpu.train.checkpoint import export_params_msgpack

    cfg = tiny_cfg()
    model, _, _ = build_model(cfg)
    rng = jax.random.PRNGKey(99)
    variables = model.init(
        {"params": rng, "noise": rng, "dropout": rng},
        np.zeros((1, 32, 32, 3), np.uint8),
    )
    path = tmp_path / "tree.msgpack"
    export_params_msgpack(variables["params"], str(path))

    cold = InferenceEngine(cfg, max_batch=4)
    warm = InferenceEngine(cfg, ckpt=str(path), max_batch=4)
    imgs = _images(4, seed=5)
    assert not np.allclose(cold.features(imgs), warm.features(imgs))
    assert warm.load_stats["features"]["loaded"]

    import flax.linen as fnn

    junk = fnn.Dense(3).init(rng, np.zeros((1, 2), np.float32))["params"]
    junk_path = tmp_path / "junk.msgpack"
    export_params_msgpack(junk, str(junk_path))
    with pytest.raises(SystemExit, match="0 params"):
        InferenceEngine(cfg, ckpt=str(junk_path), max_batch=4).features(imgs)


def test_restore_inference_state_skips_optimizer(tmp_path):
    """restore_inference_state returns the saved params (and no optimizer
    state) from a full-TrainState Checkpointer layout."""
    import jax.numpy as jnp

    from jumbo_mae_tpu_tpu.models import DecoderConfig, MAEPretrainModel, preset
    from jumbo_mae_tpu_tpu.parallel import MeshConfig, create_mesh
    from jumbo_mae_tpu_tpu.train import (
        OptimConfig,
        create_sharded_state,
        make_optimizer,
    )
    from jumbo_mae_tpu_tpu.train.checkpoint import (
        CheckpointConfig,
        Checkpointer,
        restore_inference_state,
    )

    enc = preset(
        "vit_t16", image_size=32, patch_size=8, mask_ratio=0.75, labels=None,
        dtype="float32",
    )
    module = MAEPretrainModel(enc, DecoderConfig(layers=1, dim=32, heads=2, dtype="float32"))
    tx = make_optimizer(
        OptimConfig(name="adamw", learning_rate=1e-3, lr_scaling="none",
                    warmup_steps=1, training_steps=4),
        global_batch_size=8,
    )
    batch = {"images": jnp.zeros((8, 32, 32, 3), jnp.uint8)}
    mesh = create_mesh(MeshConfig(data=1, fsdp=1))
    state, _ = create_sharded_state(module, tx, batch, mesh, mode="pretrain")
    ckpt = Checkpointer(CheckpointConfig(str(tmp_path), async_save=False))
    ckpt.save(0, state, metrics={"val/loss": 1.0})
    ckpt.close()

    params, batch_stats = restore_inference_state(str(tmp_path))
    assert batch_stats is None
    saved = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, state.params)
    )
    restored = jax.tree_util.tree_leaves(params)
    assert len(saved) == len(restored)
    for a, b in zip(saved, restored):
        np.testing.assert_array_equal(a, np.asarray(b))

    # to_device=True lands every leaf on a device (incrementally — one
    # host buffer in flight at a time) with identical values
    params_dev, _ = restore_inference_state(str(tmp_path), to_device=True)
    dev_leaves = jax.tree_util.tree_leaves(params_dev)
    assert len(dev_leaves) == len(saved)
    for a, b in zip(saved, dev_leaves):
        assert isinstance(b, jax.Array)
        np.testing.assert_array_equal(a, np.asarray(b))


# ----------------------------------------------------------- microbatcher


def test_microbatcher_orders_and_caps_batches():
    """Thread storm: every response must be the transform of ITS request
    (no cross-routing), and no flushed batch may exceed max_batch."""
    sizes = []

    def run_fn(batch):
        sizes.append(batch.shape[0])
        return batch.sum(axis=(1, 2, 3)).astype(np.int64)

    n, workers = 200, 16
    tags = np.arange(n)
    imgs = tags[:, None, None, None] * np.ones((1, 2, 2, 1), np.int64)
    results = [None] * n
    with MicroBatcher(run_fn, max_batch=7, max_delay_ms=2.0) as mb:
        def client(lo, hi):
            for i in range(lo, hi):
                results[i] = mb.submit(imgs[i]).result()

        step = -(-n // workers)  # ceil: every request gets a submitter
        threads = [
            threading.Thread(target=client, args=(w * step, min(n, (w + 1) * step)))
            for w in range(workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert max(sizes) <= 7
    np.testing.assert_array_equal(np.asarray(results), tags * 4)  # 2x2 image


def test_microbatcher_respects_deadline_without_full_batch():
    """A lone request must be served within ~max_delay_ms, not wait for
    max_batch co-travelers."""
    with MicroBatcher(
        lambda b: b.sum(axis=(1, 2, 3)), max_batch=64, max_delay_ms=30.0
    ) as mb:
        t0 = time.monotonic()
        mb.submit(np.ones((2, 2, 1))).result(timeout=5)
        elapsed = time.monotonic() - t0
    assert elapsed < 2.0  # deadline 30ms; generous bound for a loaded box
    assert mb.batch_sizes == [1]


def test_microbatcher_coalesces_within_window():
    """Requests that arrive inside one delay window ride one batch."""
    release = threading.Event()

    def run_fn(batch):
        release.wait(5)  # hold the first flush until both submits landed
        return batch.sum(axis=(1, 2, 3))

    with MicroBatcher(run_fn, max_batch=8, max_delay_ms=200.0) as mb:
        a = mb.submit(np.ones((2, 2, 1)))
        b = mb.submit(np.full((2, 2, 1), 2.0))
        release.set()
        assert a.result(timeout=5) == 4.0
        assert b.result(timeout=5) == 8.0
    # either both rode the first batch (collector saw both before its
    # window closed) — the coalescing contract — or the hold made them
    # flush as [1, 1]; with a 200ms window and an immediate second submit
    # the single-batch outcome is the expected one
    assert mb.batch_sizes[0] >= 1 and sum(mb.batch_sizes) == 2


def test_microbatcher_propagates_errors_per_batch():
    calls = []

    def run_fn(batch):
        calls.append(batch.shape[0])
        if len(calls) == 1:
            raise RuntimeError("boom")
        return batch.sum(axis=(1, 2, 3))

    with MicroBatcher(run_fn, max_batch=4, max_delay_ms=1.0) as mb:
        bad = mb.submit(np.ones((2, 2, 1)))
        with pytest.raises(RuntimeError, match="boom"):
            bad.result(timeout=5)
        good = mb.submit(np.ones((2, 2, 1)))
        assert good.result(timeout=5) == 4.0  # later batches unaffected
    with pytest.raises(RuntimeError, match="closed"):
        mb.submit(np.ones((2, 2, 1)))


def test_predict_cli_synthetic_serve(tmp_path):
    """cli.predict end to end: synthetic stream, --serve (engine behind the
    micro-batcher), npz output with one row per request."""
    from jumbo_mae_tpu_tpu.cli.predict import main as predict_main

    from pathlib import Path

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    out = predict_main(
        [
            "--config", str(recipe),
            "--synthetic", "5",
            "--task", "features",
            "--serve",
            "--max-batch", "4",
            "--max-delay-ms", "20",
            "--out", str(tmp_path / "f.npz"),
        ]
    )
    z = np.load(out)
    assert z["features"].shape[0] == 5
    assert np.isfinite(z["features"]).all()


def test_microbatcher_serves_engine_concurrently(engine):
    """End to end: concurrent single-image submits through the batcher
    reproduce the engine's direct batched output row-for-row."""
    imgs = _images(12, seed=8)
    direct = engine.features(imgs)
    with MicroBatcher(engine.features, max_batch=8, max_delay_ms=5.0) as mb:
        futs = [mb.submit(img) for img in imgs]
        rows = np.stack([f.result(timeout=30) for f in futs])
    np.testing.assert_array_equal(rows, direct)
    assert max(mb.batch_sizes) <= 8


def test_last_breakdown_thread_local(engine):
    """The compute/fetch/bucket/pad breakdown reflects the calling thread's
    most recent predict — the RequestTracer(breakdown=...) contract."""
    engine.features(_images(5, seed=9))  # bucket 8, 3 pad rows
    bd = engine.last_breakdown()
    assert bd["bucket"] == 8
    assert bd["pad_fraction"] == pytest.approx(3 / 8)
    assert bd["compute_s"] > 0.0
    assert bd["fetch_s"] >= 0.0
    # a thread that never predicted sees None, not another thread's batch
    seen = {}
    t = threading.Thread(
        target=lambda: seen.update(bd=engine.last_breakdown())
    )
    t.start()
    t.join()
    assert seen["bd"] is None


def test_warmup_first_does_not_deadlock():
    """warmup() as the very first engine touch must build the task outside
    the compile lock (regression: _executable used to re-enter _lock via
    _task and deadlock when nothing had predicted yet)."""
    eng = InferenceEngine(tiny_cfg(), max_batch=2)
    assert eng.warmup(("features",), buckets=(1, 2)) == 2
    assert eng.warmup(("features",), buckets=(1, 2)) == 0  # cached now


def test_warmup_parallel_compiles_each_bucket_exactly_once():
    """The threaded warmup (compiles release the GIL) must produce exactly
    one executable per (task, bucket) — the per-key locks serialize
    duplicate claims, not the pool."""
    compiles = []
    eng = InferenceEngine(
        tiny_cfg(), max_batch=8,
        on_compile=lambda key, b: compiles.append((key, b)),
    )
    n = eng.warmup(("features",), workers=4)
    assert n == 4 and sorted(b for _, b in compiles) == [1, 2, 4, 8]
    assert all(c == 1 for c in eng.compile_counts.values())
    # results must be served by those executables with zero extra compiles
    out = eng.features(_images(5, seed=11))
    assert out.shape[0] == 5 and len(compiles) == 4


def test_warmup_rejects_oversized_bucket():
    eng = InferenceEngine(tiny_cfg(), max_batch=4)
    with pytest.raises(OversizedBatchError):
        eng.warmup(("features",), buckets=(8,))


def test_predict_rejects_non_shared_encoder_cache():
    """per_sample masking draws per-row noise — encoder outputs depend on
    batch position, so caching them would silently change results."""
    with pytest.raises(ValueError, match="shared"):
        InferenceEngine(
            tiny_cfg(("model.overrides.mask_mode=per_sample",)),
            max_batch=4,
            encoder_cache=8,
        )


def test_encoder_cache_matches_fused_reconstruct():
    """encode-once/decode-many must reproduce the fused executable's output
    (same images, same seed) and hit on repeats."""
    cfg = tiny_cfg()
    fused = InferenceEngine(cfg, max_batch=4)
    cached = InferenceEngine(cfg, max_batch=4, encoder_cache=8)
    imgs = _images(3, seed=12)

    ref = fused.reconstruct(imgs, seed=0)
    out1 = cached.reconstruct(imgs, seed=0)
    np.testing.assert_allclose(
        np.asarray(out1["reconstruction"]),
        np.asarray(ref["reconstruction"]),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_array_equal(
        np.asarray(out1["mask"]), np.asarray(ref["mask"])
    )
    st = cached.encoder_cache_stats()
    assert st["misses"] == 3 and st["size"] == 3

    # second pass: all encoder work served from the cache, bit-identical
    out2 = cached.reconstruct(imgs, seed=0)
    np.testing.assert_array_equal(
        np.asarray(out1["reconstruction"]), np.asarray(out2["reconstruction"])
    )
    st = cached.encoder_cache_stats()
    assert st["hits"] == 3 and st["misses"] == 3

    # a different seed is a different mask — distinct cache entries, and
    # the output actually changes
    out3 = cached.reconstruct(imgs, seed=1)
    assert not np.array_equal(
        np.asarray(out1["mask"]), np.asarray(out3["mask"])
    )
    assert cached.encoder_cache_stats()["misses"] == 6


def test_encoder_cache_evicts_lru():
    eng = InferenceEngine(tiny_cfg(), max_batch=4, encoder_cache=2)
    a, b, c = (_images(1, seed=s) for s in (20, 21, 22))
    eng.reconstruct(a, seed=0)
    eng.reconstruct(b, seed=0)  # cache: {a, b}
    eng.reconstruct(c, seed=0)  # evicts a → {b, c}
    st = eng.encoder_cache_stats()
    assert st["size"] == 2 and st["misses"] == 3
    eng.reconstruct(b, seed=0)  # hit
    eng.reconstruct(a, seed=0)  # miss again (was evicted)
    st = eng.encoder_cache_stats()
    assert st["hits"] == 1 and st["misses"] == 4


def test_encoder_cache_byte_cap_evicts_before_entry_cap():
    """Regression: the byte cap is a real bound, not advisory — with a
    generous entry cap and a tight byte cap, eviction happens on bytes."""
    probe = InferenceEngine(tiny_cfg(), max_batch=4, encoder_cache=8)
    probe.reconstruct(_images(1, seed=30), seed=0)
    row_bytes = probe.encoder_cache_bytes()
    assert row_bytes > 0

    eng = InferenceEngine(
        tiny_cfg(),
        max_batch=4,
        encoder_cache=64,  # entry cap alone would keep all three rows
        encoder_cache_bytes=int(row_bytes * 1.5),  # byte cap holds one
    )
    for s in (30, 31, 32):
        eng.reconstruct(_images(1, seed=s), seed=0)
    st = eng.encoder_cache_stats()
    assert st["capacity"] == 64 and st["capacity_bytes"] == int(row_bytes * 1.5)
    assert st["size"] == 1 and st["misses"] == 3
    assert 0 < st["bytes"] <= st["capacity_bytes"]
    assert eng.encoder_cache_bytes() == row_bytes
    # the survivor is the most recent row (LRU order held under byte evicts)
    eng.reconstruct(_images(1, seed=32), seed=0)
    assert eng.encoder_cache_stats()["hits"] == 1


def test_encoder_cache_dedupes_within_batch():
    """Duplicate rows in ONE request encode once and decode per-row."""
    eng = InferenceEngine(tiny_cfg(), max_batch=4, encoder_cache=8)
    img = _images(1, seed=23)
    batch = np.concatenate([img, img, img])
    out = eng.reconstruct(batch, seed=0)
    assert out["reconstruction"].shape[0] == 3
    np.testing.assert_array_equal(
        np.asarray(out["reconstruction"][0]),
        np.asarray(out["reconstruction"][2]),
    )
    assert eng.encoder_cache_stats()["misses"] == 1


def test_microbatcher_pass_meta():
    """pass_meta=True hands run_fn the per-request metadata, batch-aligned —
    the hook a server uses to route per-request options through coalescing."""
    seen = []

    def run_fn(batch, metas):
        seen.append(list(metas))
        return batch.sum(axis=(1, 2, 3))

    with MicroBatcher(
        run_fn, max_batch=4, max_delay_ms=50.0, pass_meta=True
    ) as mb:
        futs = [
            mb.submit(np.full((2, 2, 1), i), meta={"req": i}) for i in range(3)
        ]
        vals = [f.result(timeout=5) for f in futs]
    assert vals == [0.0, 4.0, 8.0]
    flat = [m for batch in seen for m in batch]
    assert flat == [{"req": 0}, {"req": 1}, {"req": 2}]
