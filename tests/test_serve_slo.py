"""Request tracing, SLO tracking, and serve_doctor contracts.

The serving-observability invariants this PR stands on:

- every request that enters the micro-batcher finishes with exactly one
  terminal outcome, and (with an access log attached) exactly one access-
  log row — including under a concurrent submit/close storm with mixed
  deadlines (the one-to-one contract serve_doctor's offline analysis
  assumes);
- the SLO tracker's multi-window burn rates, the latched degraded flag,
  and the ``slo_*`` gauges behave deterministically under a fake clock;
- ``/healthz`` carries the degraded flag and live serving stats without
  flipping readiness, and ``/metrics`` runs every registered pre-scrape
  hook;
- ``serve_doctor`` names the violating request window and the dominant
  latency component from the access log alone.
"""

import json
import threading
import time
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.infer.batching import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    ShutdownError,
)
from jumbo_mae_tpu_tpu.obs import (
    AccessLog,
    HealthState,
    RequestTracer,
    SLOTracker,
    TelemetryServer,
    parse_slo,
)
from jumbo_mae_tpu_tpu.obs.doctor_common import contiguous_windows, spans_text
from jumbo_mae_tpu_tpu.obs.journal import read_journal
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry

# ------------------------------------------------------------- SLO parsing


def test_parse_slo_grammar():
    objs = parse_slo("p99_latency_ms<=250; success_rate>=0.99")
    assert [o.name for o in objs] == [
        "p99_latency_ms<=250",
        "success_rate>=0.99",
    ]
    assert objs[0].percentile == 99.0
    assert objs[0].budget == pytest.approx(0.01)
    assert objs[1].percentile is None
    assert objs[1].budget == pytest.approx(0.01)
    assert parse_slo("p50_latency_ms<=10")[0].budget == pytest.approx(0.5)


@pytest.mark.parametrize(
    "bad",
    [
        "",
        "p99_latency_ms>=250",     # latency wants <=
        "success_rate<=0.99",      # success wants >=
        "success_rate>=2",         # out of (0,1)
        "error_rate<=0.1",         # unknown metric
        "p99_latency_ms=250",      # bad operator
    ],
)
def test_parse_slo_rejects(bad):
    with pytest.raises(ValueError):
        parse_slo(bad)


# --------------------------------------------------- tracer + access log


def test_tracer_lifecycle_and_access_log(tmp_path):
    reg = MetricsRegistry()
    finished = []
    with AccessLog(tmp_path / "access") as log:
        tracer = RequestTracer(
            registry=reg, access_log=log, on_finish=finished.append
        )
        traces = [tracer.begin(task="features") for _ in range(3)]
        assert [t.rid for t in traces] == [0, 1, 2]  # monotonic rids
        for t in traces:
            tracer.admitted(t)
        tracer.flush_begin(traces)
        tracer.flush_end(traces, run_s=0.05, batch=3)
        for t in traces:
            tracer.finish(t, "ok")
        shed = tracer.begin()
        tracer.finish(shed, "shed")

    rows = [
        e for e in read_journal(tmp_path / "access") if e["type"] == "request"
    ]
    assert [r["rid"] for r in rows] == [0, 1, 2, 3]
    assert [r["outcome"] for r in rows] == ["ok", "ok", "ok", "shed"]
    ok = rows[0]
    # the full breakdown survives the round-trip
    assert ok["batch"] == 3
    assert ok["compute_ms"] == pytest.approx(50.0)  # run_s with no engine
    assert ok["lat_ms"] >= ok["queue_wait_ms"]
    # a never-admitted request's wait is its whole latency
    assert rows[3]["queue_wait_ms"] == rows[3]["lat_ms"]
    assert len(finished) == 4
    assert reg.counter(
        "request_outcomes_total", "x", labels=("outcome",)
    ).labels("ok").value == 3


def _traced_batcher(tmp_path, run_fn, **kw):
    reg = MetricsRegistry()
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=reg, access_log=log)
    mb = MicroBatcher(run_fn, registry=reg, tracer=tracer, **kw)
    return mb, log


def _rows(log):
    log.close()
    return [e for e in read_journal(log.path) if e["type"] == "request"]


def test_batcher_outcomes_ok_and_rid(tmp_path):
    mb, log = _traced_batcher(tmp_path, lambda b: b * 2.0, max_batch=4)
    with mb:
        futs = [mb.submit(np.full((2,), i, np.float32)) for i in range(6)]
        results = [f.result() for f in futs]
    rows = _rows(log)
    assert sorted(r["rid"] for r in rows) == sorted(f.rid for f in futs)
    assert all(r["outcome"] == "ok" for r in rows)
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r, np.full((2,), 2.0 * i))


def test_batcher_outcome_shed(tmp_path):
    release = threading.Event()

    def slow(batch):
        release.wait(5.0)
        return batch

    mb, log = _traced_batcher(
        tmp_path, slow, max_batch=1, max_delay_ms=1.0, max_queue=1
    )
    with mb:
        first = mb.submit(np.zeros(1))
        # wait for the collector to pop request 0 into a (blocked) flush,
        # then saturate the queue bound
        deadline = time.monotonic() + 5.0
        while not mb.batch_sizes and time.monotonic() < deadline:
            time.sleep(0.001)
        second = mb.submit(np.zeros(1))  # occupies the single queue slot
        with pytest.raises(QueueFullError):
            mb.submit(np.zeros(1))
        release.set()
        first.result(5.0)
        second.result(5.0)
    rows = {r["rid"]: r for r in _rows(log)}
    assert len(rows) == 3
    outcomes = sorted(r["outcome"] for r in rows.values())
    assert outcomes == ["ok", "ok", "shed"]
    shed_rid = next(r for r in rows.values() if r["outcome"] == "shed")["rid"]
    assert shed_rid not in (first.rid, second.rid)


def test_batcher_outcome_deadline(tmp_path):
    release = threading.Event()

    def slow(batch):
        release.wait(5.0)
        return batch

    mb, log = _traced_batcher(tmp_path, slow, max_batch=1, max_delay_ms=1.0)
    with mb:
        first = mb.submit(np.zeros(1))
        expiring = mb.submit(np.zeros(1), deadline_ms=5.0)
        time.sleep(0.05)  # let the deadline lapse while queued behind first
        release.set()
        first.result(5.0)
        with pytest.raises(DeadlineExceededError):
            expiring.result(5.0)
    rows = {r["rid"]: r for r in _rows(log)}
    assert rows[first.rid]["outcome"] == "ok"
    assert rows[expiring.rid]["outcome"] == "deadline"
    assert rows[expiring.rid]["deadline_ms"] == 5.0


def test_batcher_outcome_late_when_deadline_passes_after_admission(tmp_path):
    """Regression: a deadline that lapses AFTER admission — here because an
    injected ``serve.submit`` delay on a co-traveler held the batch open
    past it — must resolve ``late`` (typed failure + counter), never
    ``ok``."""
    from jumbo_mae_tpu_tpu import faults

    reg = MetricsRegistry()
    log = AccessLog(tmp_path / "access")
    tracer = RequestTracer(registry=reg, access_log=log)
    mb = MicroBatcher(
        lambda batch: batch, registry=reg, tracer=tracer,
        max_batch=2, max_delay_ms=2000.0,
    )
    faults.install_plan("serve.submit:delay(0.3)@n=1")
    try:
        with mb:
            # admitted immediately; the collector then waits for a second
            # rider to fill max_batch=2
            doomed = mb.submit(np.zeros(1), deadline_ms=100.0)
            # this submit is delayed 0.3s by the fault — by the time the
            # batch flushes, doomed's deadline has passed
            rider = mb.submit(np.zeros(1))
            assert rider.result(5.0) is not None
            with pytest.raises(DeadlineExceededError):
                doomed.result(5.0)
    finally:
        faults.clear_plan()
    rows = {r["rid"]: r for r in _rows(log)}
    assert rows[doomed.rid]["outcome"] == "late"
    assert rows[rider.rid]["outcome"] == "ok"
    assert reg.counter("infer_requests_late_total", "x").value == 1
    # late is not the pre-admission deadline path
    assert reg.counter("infer_deadline_exceeded_total", "x").value == 0


def test_batcher_outcome_aborted_on_run_fn_error(tmp_path):
    def boom(batch):
        raise RuntimeError("kaput")

    mb, log = _traced_batcher(tmp_path, boom, max_batch=4, max_delay_ms=1.0)
    with mb:
        fut = mb.submit(np.zeros(1))
        with pytest.raises(RuntimeError, match="kaput"):
            fut.result(5.0)
    rows = _rows(log)
    assert rows[0]["outcome"] == "aborted"
    assert "kaput" in rows[0]["err"]


def test_batcher_outcome_shutdown(tmp_path):
    release = threading.Event()

    def slow(batch):
        release.wait(5.0)
        return batch

    mb, log = _traced_batcher(tmp_path, slow, max_batch=1, max_delay_ms=1.0)
    first = mb.submit(np.zeros(1))
    # make sure first is in a (blocked) flush before closing, so it is the
    # one that completes and queued is the one close() sheds
    deadline = time.monotonic() + 5.0
    while not mb.batch_sizes and time.monotonic() < deadline:
        time.sleep(0.001)
    queued = mb.submit(np.zeros(1))
    release.set()
    mb.close()  # drain=True: the queued request is shed with ShutdownError
    first.result(5.0)
    with pytest.raises(ShutdownError):
        queued.result(5.0)
    with pytest.raises(RuntimeError):
        mb.submit(np.zeros(1))  # post-close submit traces as shutdown too
    rows = {r["rid"]: r for r in _rows(log)}
    assert rows[queued.rid]["outcome"] == "shutdown"
    assert sorted(r["outcome"] for r in rows.values()) == [
        "ok", "shutdown", "shutdown",
    ]


def test_batcher_stats_snapshot(tmp_path):
    release = threading.Event()

    def slow(batch):
        release.wait(5.0)
        return batch

    mb, log = _traced_batcher(
        tmp_path, slow, max_batch=2, max_delay_ms=1.0, max_queue=1
    )
    with mb:
        first = mb.submit(np.zeros(1))
        deadline = time.monotonic() + 5.0
        while not mb.batch_sizes and time.monotonic() < deadline:
            time.sleep(0.001)
        mb.submit(np.zeros(1))
        with pytest.raises(QueueFullError):
            mb.submit(np.zeros(1))
        s = mb.stats()
        assert s["queue_depth"] == 1
        assert s["requests_submitted"] == 3
        assert s["requests_shed"] == 1
        assert s["shed_rate"] == pytest.approx(1 / 3, abs=1e-4)
        release.set()
    s = mb.stats()
    assert s["queue_depth"] == 0
    assert s["queue_bytes"] == 0
    assert set(s) == {
        "queue_depth", "queue_bytes", "batch_occupancy",
        "last_batch_occupancy", "window_batch_occupancy",
        "mean_batch_occupancy", "requests_submitted", "requests_shed",
        "shed_rate",
    }
    log.close()


# ------------------------------------------- satellite: concurrent stress


def test_batcher_stress_every_future_exactly_one_outcome(tmp_path):
    """Concurrent submit/close with mixed deadlines: every future resolves
    with exactly one outcome and access-log rows match begun requests
    one-to-one (the crash-safe audit trail is complete)."""
    def run(batch):
        time.sleep(0.002)
        return batch

    mb, log = _traced_batcher(
        tmp_path, run, max_batch=8, max_delay_ms=1.0, max_queue=64
    )
    futures: list[Future] = []
    submit_errors: list[BaseException] = []
    lock = threading.Lock()
    n_threads, per_thread = 8, 40

    def client(tid):
        rs = np.random.RandomState(tid)
        for i in range(per_thread):
            dl = None if i % 3 else float(rs.uniform(0.1, 3.0))
            try:
                f = mb.submit(np.full((2,), tid, np.float32), deadline_ms=dl)
            except (QueueFullError, RuntimeError) as e:
                with lock:
                    submit_errors.append(e)
            else:
                with lock:
                    futures.append(f)
            if i % 10 == 9:
                time.sleep(0.001)

    threads = [
        threading.Thread(target=client, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    time.sleep(0.05)
    mb.close()  # races the submitting threads on purpose
    for t in threads:
        t.join()

    # every handed-out future resolved — exactly one outcome each
    outcomes = {"ok": 0, "deadline": 0, "shutdown": 0}
    for f in futures:
        assert f.done(), "close() left a future unresolved"
        exc = f.exception(timeout=0)
        if exc is None:
            outcomes["ok"] += 1
        elif isinstance(exc, DeadlineExceededError):
            outcomes["deadline"] += 1
        elif isinstance(exc, ShutdownError):
            outcomes["shutdown"] += 1
        else:  # pragma: no cover - any other exception is a bug
            raise AssertionError(f"unexpected outcome {exc!r}")

    rows = _rows(log)
    # one row per begun request: submitted futures + raising submits
    assert len(rows) == len(futures) + len(submit_errors)
    rids = [r["rid"] for r in rows]
    assert len(set(rids)) == len(rids)  # rids unique
    by_rid = {r["rid"]: r for r in rows}
    # resolved futures and rows agree outcome-for-outcome via fut.rid
    # (a DeadlineExceededError is "deadline" when caught before admission,
    # "late" when the deadline lapsed after — both are the same typed error)
    for f in futures:
        row = by_rid[f.rid]
        exc = f.exception(timeout=0)
        expect = (
            ("ok",) if exc is None
            else ("deadline", "late") if isinstance(exc, DeadlineExceededError)
            else ("shutdown",)
        )
        assert row["outcome"] in expect
    row_counts = {}
    for r in rows:
        row_counts[r["outcome"]] = row_counts.get(r["outcome"], 0) + 1
    for k, v in outcomes.items():
        assert row_counts.get(k, 0) >= v if k == "shutdown" else True
    assert row_counts.get("ok", 0) == outcomes["ok"]


# ------------------------------------------------------------ SLO tracker


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_slo_tracker_burn_and_latch():
    clock = FakeClock()
    reg = MetricsRegistry()
    depth = {"v": 7}
    tkr = SLOTracker(
        parse_slo("p99_latency_ms<=100;success_rate>=0.9"),
        window_s=60.0,
        fast_window_s=5.0,
        registry=reg,
        probes={"queue_depth": lambda: depth["v"]},
        clock=clock,
    )
    for _ in range(99):
        tkr.observe(0.01, "ok")
    rep = tkr.evaluate()
    assert not rep["degraded"]
    assert all(not o["breached"] for o in rep["objectives"])

    # 10 slow requests out of ~109 → ~9% violations vs a 1% budget
    for _ in range(10):
        tkr.observe(0.5, "ok")
    rep = tkr.evaluate()
    lat = rep["objectives"][0]
    assert lat["breached"] and rep["degraded"]
    assert lat["burn_slow"] == pytest.approx(10 / 109 / 0.01, rel=1e-3)
    assert rep["objectives"][1]["breached"] is False  # all ok so far
    g = reg.gauge("slo_degraded", "x")
    assert g.value == 1.0
    assert reg.gauge("slo_queue_depth", "x").value == 7.0
    assert (
        reg.gauge("slo_breached", "x", labels=("objective",))
        .labels("p99_latency_ms<=100").value == 1.0
    )

    # the degraded flag stays latched for window_s after the samples age out
    clock.t += 61.0
    rep = tkr.evaluate()
    assert rep["samples"] == 0
    assert not any(o["breached"] for o in rep["objectives"])
    assert tkr._degraded_at(clock()) is False  # 61s > window since breach
    assert rep["degraded"] is False
    assert reg.gauge("slo_degraded", "x").value == 0.0


def test_slo_tracker_degraded_latch_holds_within_window():
    clock = FakeClock()
    tkr = SLOTracker(
        parse_slo("success_rate>=0.9"),
        window_s=60.0,
        fast_window_s=5.0,
        registry=MetricsRegistry(),
        clock=clock,
    )
    for _ in range(5):
        tkr.observe(None, "shed")
    assert tkr.evaluate()["degraded"]
    # 30s later the incident is over (95 ok dilute the sheds below the 10%
    # budget) — no current breach, but the latch holds for window_s
    clock.t += 30.0
    for _ in range(95):
        tkr.observe(0.01, "ok")
    rep = tkr.evaluate()
    assert not any(o["breached"] for o in rep["objectives"])
    assert rep["degraded"] is True
    assert tkr.degraded() is True
    clock.t += 31.0  # 61s past the breach; the latch releases
    assert tkr.degraded() is False


def test_slo_tracker_empty_fast_window_confirms_breach():
    """A stalled request stream (empty fast window) must not mask a slow-
    window breach."""
    clock = FakeClock()
    tkr = SLOTracker(
        parse_slo("success_rate>=0.9"),
        window_s=60.0,
        fast_window_s=5.0,
        registry=MetricsRegistry(),
        clock=clock,
    )
    for _ in range(10):
        tkr.observe(None, "aborted")
    clock.t += 10.0  # breaches are now outside the fast window
    rep = tkr.evaluate()
    assert rep["objectives"][0]["burn_fast"] == 0.0
    assert rep["objectives"][0]["breached"] is True


def test_slo_shed_rate_gauge():
    reg = MetricsRegistry()
    tkr = SLOTracker(
        parse_slo("success_rate>=0.5"), window_s=60.0, registry=reg
    )
    tkr.observe(0.01, "ok")
    tkr.observe(None, "shed")
    rep = tkr.evaluate()
    assert rep["shed_rate"] == pytest.approx(0.5)
    assert reg.gauge("slo_shed_rate", "x").value == pytest.approx(0.5)


def test_slo_add_probe_publishes_gauge():
    reg = MetricsRegistry()
    tkr = SLOTracker(
        parse_slo("success_rate>=0.5"), window_s=60.0, registry=reg
    )
    depth = {"v": 7}
    tkr.add_probe("queue_depth", lambda: depth["v"])
    tkr.add_probe("broken", lambda: 1 / 0)  # must not break evaluation
    tkr.evaluate()
    assert reg.gauge("slo_queue_depth", "x").value == 7.0
    depth["v"] = 3
    tkr.evaluate()
    assert reg.gauge("slo_queue_depth", "x").value == 3.0


# --------------------------------------------------- exporter integration


def test_healthstate_degraded_when_does_not_flip_ok():
    h = HealthState(ready=True)
    flag = {"v": False}
    h.degraded_when(lambda: flag["v"])
    ok, body = h.report()
    assert ok and body["degraded"] is False
    flag["v"] = True
    ok, body = h.report()
    assert ok, "degraded must not flip the 503 readiness verdict"
    assert body["degraded"] is True
    # predicates compose via OR: the erroring probe's message surfaces only
    # while no other predicate already reports a real degraded verdict
    h.degraded_when(lambda: 1 / 0)
    ok, body = h.report()
    assert ok and body["degraded"] is True
    flag["v"] = False
    ok, body = h.report()
    assert ok and "probe error" in body["degraded"]


def test_exporter_pre_scrape_hooks_and_serving_probe():
    reg = MetricsRegistry()
    health = HealthState(ready=True)
    calls = {"n": 0}

    def bump():
        calls["n"] += 1
        reg.gauge("test_prescrape_runs", "x").set(calls["n"])

    mb = MicroBatcher(lambda b: b, registry=reg, max_batch=2)
    health.probe("serving", mb.stats)
    srv = TelemetryServer(registry=reg, health=health, host="127.0.0.1", port=0)
    srv.add_pre_scrape(bump)
    srv.add_pre_scrape(lambda: 1 / 0)  # a broken hook must not break scrapes
    with srv:
        mb.submit(np.zeros(1)).result(5.0)
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "test_prescrape_runs 1" in text
        assert "process_uptime_seconds" in text
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            body = json.loads(resp.read().decode())
        assert body["ok"]
        serving = body["info"]["serving"]
        assert serving["requests_submitted"] == 1
        assert serving["queue_depth"] == 0
    mb.close()
    assert calls["n"] == 1


# ------------------------------------------------------------ serve_doctor


def _write_access_log(tmp_path):
    """Synthetic access log: 20 fast ok, 10 slow queue-wait-dominated ok
    (rids 20-29), 4 shed — a textbook queue-pressure incident."""
    log = AccessLog(tmp_path / "access")
    t0 = 1_700_000_000.0
    for rid in range(20):
        log.event(
            "request", ts_override=None, rid=rid, outcome="ok", lat_ms=20.0,
            queue_wait_ms=4.0, admission_ms=2.0, compute_ms=12.0,
            fetch_ms=2.0, batch=8, bucket=8, pad=0.0,
        )
    for rid in range(20, 30):
        log.event(
            "request", rid=rid, outcome="ok", lat_ms=600.0,
            queue_wait_ms=520.0, admission_ms=30.0, compute_ms=40.0,
            fetch_ms=10.0, batch=2, bucket=2, pad=0.5,
        )
    for rid in range(30, 34):
        log.event(
            "request", rid=rid, outcome="shed", lat_ms=0.1,
            queue_wait_ms=0.1,
        )
    log.close()
    assert t0 > 0
    return tmp_path / "access"


def test_serve_doctor_names_window_and_component(tmp_path, capsys):
    from tools.serve_doctor import main as doctor_main

    path = _write_access_log(tmp_path)
    out = tmp_path / "diagnosis.md"
    rc = doctor_main(
        [str(path), "--slo", "p99_latency_ms<=150;success_rate>=0.9",
         "--out", str(out)]
    )
    assert rc == 0
    report = out.read_text()
    assert "breached" in report
    assert "requests 20–29" in report       # the violating rid cluster
    assert "queue_wait" in report           # dominant latency component
    assert "← dominant" in report
    assert "requests 30–33" in report       # the shed cluster
    assert "worst bucket by p99: **2**" in report


def test_serve_doctor_auto_threshold_without_slo(tmp_path):
    from tools.serve_doctor import main as doctor_main

    path = _write_access_log(tmp_path)
    out = tmp_path / "d.md"
    assert doctor_main([str(path), "--out", str(out)]) == 0
    report = out.read_text()
    assert "auto slow-request threshold" in report
    assert "requests 20–29" in report


def test_serve_doctor_exit_2_on_missing_or_empty(tmp_path):
    from tools.serve_doctor import main as doctor_main

    assert doctor_main([str(tmp_path / "nope")]) == 2
    log = AccessLog(tmp_path / "empty")
    log.event("slo_summary", report={})  # events, but no request rows
    log.close()
    assert doctor_main([str(tmp_path / "empty")]) == 2


def test_doctor_common_windows():
    assert contiguous_windows([7, 5, 6, 12, 5]) == [(5, 7), (12, 12)]
    assert spans_text([(5, 7), (12, 12)]) == "steps 5–7, step 12"
    assert spans_text([(3, 3)], noun="request") == "request 3"
