"""Goodput accounting suite (PR 20): every second of wall-clock lands in
exactly one bucket, and the ledger can prove it.

- ``GoodputLedger``: first-dispatch → compile, rollback watermark →
  recompute, idle as the residual, and the conservation invariant —
  attributed time may never exceed measured wall-clock (over-attribution
  is the falsifiable failure mode the residual-idle construction leaves).
- ``advise_ckpt_interval``: Young's √(2·save_cost·MTBF), the
  no-failures-observed MTBF lower bound, and the clamps.
- ``stitch_generations``: a killed-and-relaunched elastic run stitched
  from per-generation journals — inter-generation downtime split into
  hang-detection latency + restart downtime, lost steps = executed −
  committed, conservation across the stitch.
- ``tools/goodput_doctor.py``: exit codes, the attribution table, the
  restart-cost breakdown naming restart downtime, and a concrete
  ``run.ckpt_every`` recommendation.
- ``tools/run_doctor.py`` timeline: renders the elastic lifecycle events
  (restart/resize/rejoin, hang_detected, ckpt_fallback).
- Conservation property tests on real in-process ``train()`` runs —
  clean and under seeded fault plans (slow; the CI goodput chaos smoke
  runs them).
"""

import json
import math
from pathlib import Path

import pytest

from jumbo_mae_tpu_tpu import faults
from jumbo_mae_tpu_tpu.config import load_config
from jumbo_mae_tpu_tpu.data.tario import QUARANTINE
from jumbo_mae_tpu_tpu.obs.fleet import FleetAggregator, HostBeacon
from jumbo_mae_tpu_tpu.obs.goodput import (
    GOODPUT_BUCKETS,
    GoodputLedger,
    advise_ckpt_interval,
    bucket_display,
    stitch_generations,
)
from jumbo_mae_tpu_tpu.obs.journal import read_journal
from jumbo_mae_tpu_tpu.obs.metrics import MetricsRegistry

RECIPES = Path(__file__).resolve().parent.parent / "recipes"


@pytest.fixture
def fault_plan():
    """Install-and-always-clear: plans are process-global by design."""
    yield faults.install_plan
    faults.clear_plan()
    QUARANTINE.clear()


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def _ledger(clock, **kw):
    return GoodputLedger(registry=MetricsRegistry(), clock=clock, **kw)


def _gauge_value(reg, name, **labels):
    fam = reg._families[name]
    return fam._children[tuple(str(v) for v in labels.values())].value


# ------------------------------------------------------------------ ledger


class TestGoodputLedger:
    def test_first_dispatch_is_compile_not_productive(self):
        clock = FakeClock()
        led = _ledger(clock)
        clock.advance(5.0)
        led.note_step(1, 4.0)  # trace+compile rides the first dispatch
        led.note_step(2, 0.5)
        snap = led.snapshot()
        assert snap["compile"] == pytest.approx(4.0)
        assert snap["productive"] == pytest.approx(0.5)
        rep = led.report()
        assert rep["steps"] == 1  # the compile dispatch is not a step

    def test_rollback_window_routes_to_recompute(self):
        clock = FakeClock()
        led = _ledger(clock)
        led.note_step(1, 1.0)  # compile
        for s in (1, 2, 3, 4):
            led.note_step(s, 0.5)
        clock.advance(10.0)
        led.note_rollback(4, 2)  # rolled back from step 4 to the step-2 ckpt
        for s in (3, 4):  # re-trained ground: recompute, not progress
            led.note_step(s, 0.5)
        led.note_step(5, 0.5)  # new ground again
        snap = led.snapshot()
        assert snap["rollback_recompute"] == pytest.approx(1.0)
        assert snap["productive"] == pytest.approx(4 * 0.5 + 0.5)
        rep = led.report()
        assert rep["recompute_steps"] == 2 and rep["steps"] == 5

    def test_double_rollback_keeps_highest_watermark(self):
        clock = FakeClock()
        led = _ledger(clock)
        led.note_step(0, 1.0)  # compile
        led.note_rollback(6, 2)
        led.note_rollback(4, 2)  # older rollback must not lower the bar
        for s in (3, 4, 5, 6):
            led.note_step(s, 0.25)
        led.note_step(7, 0.25)
        snap = led.snapshot()
        assert snap["rollback_recompute"] == pytest.approx(1.0)
        assert snap["productive"] == pytest.approx(0.25)

    def test_idle_is_the_residual(self):
        clock = FakeClock()
        led = _ledger(clock)
        clock.advance(10.0)
        led.add("productive", 3.0)
        led.add("data_wait", 2.0)
        snap = led.snapshot()
        assert snap["idle"] == pytest.approx(5.0)
        assert sum(snap.values()) == pytest.approx(led.wall_s())
        assert led.fraction() == pytest.approx(0.3)
        assert led.conservation_error() == 0.0

    def test_over_attribution_is_detected(self):
        clock = FakeClock()
        led = _ledger(clock)
        clock.advance(1.0)
        led.add("productive", 3.0)  # charged more than the clock advanced
        assert led.conservation_error() == pytest.approx(2.0)
        assert led.snapshot()["idle"] == 0.0  # residual clamps at zero

    def test_unknown_bucket_rejected(self):
        led = _ledger(FakeClock())
        with pytest.raises(KeyError):
            led.add("coffee", 1.0)
        with pytest.raises(KeyError):
            led.add("idle", 1.0)  # idle is computed, never charged

    def test_negative_spans_clamped(self):
        clock = FakeClock()
        led = _ledger(clock)
        clock.advance(1.0)
        led.add("eval", -5.0)
        led.note_step(1, -2.0)
        assert led.snapshot()["eval"] == 0.0
        assert led.conservation_error() == 0.0

    def test_report_shape_and_conservation(self):
        clock = FakeClock()
        led = _ledger(clock, generation=3)
        clock.advance(4.0)
        led.note_step(1, 1.5)
        led.note_step(2, 0.5)
        led.add("ckpt_save", 0.25)
        rep = led.report(step=2, reason="interval")
        assert rep["generation"] == 3
        assert rep["step"] == 2 and rep["reason"] == "interval"
        assert set(rep["buckets"]) == set(GOODPUT_BUCKETS)
        assert rep["wall_s"] == pytest.approx(4.0)
        assert rep["attributed_s"] + rep["idle_s"] == pytest.approx(4.0)
        assert rep["conservation_error"] <= 0.01
        assert rep["goodput_fraction"] == pytest.approx(0.5 / 4.0)

    def test_publish_sets_gauges(self):
        reg = MetricsRegistry()
        clock = FakeClock()
        led = GoodputLedger(registry=reg, clock=clock)
        clock.advance(8.0)
        led.note_step(1, 1.0)
        led.note_step(2, 3.0)
        led.publish()
        assert _gauge_value(reg, "goodput_wall_seconds") == pytest.approx(8.0)
        assert _gauge_value(reg, "goodput_fraction") == pytest.approx(3.0 / 8.0)
        assert _gauge_value(
            reg, "goodput_bucket_seconds", bucket="compile"
        ) == pytest.approx(1.0)
        assert _gauge_value(
            reg, "goodput_bucket_seconds", bucket="idle"
        ) == pytest.approx(4.0)
        assert _gauge_value(reg, "goodput_recompute_steps") == 0.0

    def test_thread_safety_conserves_under_contention(self):
        import threading

        clock = FakeClock()
        led = _ledger(clock)
        led.note_step(0, 0.0)  # burn the compile dispatch

        def feed():
            for i in range(500):
                led.note_step(i, 0.001)
                led.add("data_wait", 0.001)

        threads = [threading.Thread(target=feed) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        clock.advance(10.0)  # wall comfortably exceeds attributed
        snap = led.snapshot()
        assert snap["productive"] == pytest.approx(2.0)
        assert snap["data_wait"] == pytest.approx(2.0)
        assert led.conservation_error() == 0.0


def test_bucket_display_names():
    assert bucket_display("restart_downtime") == "restart downtime"
    assert bucket_display("productive") == "productive step compute"
    assert bucket_display("not_a_bucket") == "not a bucket"


# ----------------------------------------------------------------- advisor


class TestCkptAdvisor:
    def test_youngs_formula(self):
        adv = advise_ckpt_interval(2.0, 10000.0, 0.5)
        assert adv["interval_s"] == pytest.approx(200.0)  # √(2·2·10000)
        assert adv["ckpt_every"] == 400
        assert adv["mtbf_is_bound"] is False

    def test_no_failures_uses_span_as_mtbf_bound(self):
        adv = advise_ckpt_interval(1.0, 0.0, 0.1, observed_span_s=800.0)
        assert adv["mtbf_is_bound"] is True
        assert adv["mtbf_s"] == pytest.approx(800.0)
        assert adv["interval_s"] == pytest.approx(40.0)
        assert adv["ckpt_every"] == 400

    def test_clamps_produce_a_sane_recommendation(self):
        adv = advise_ckpt_interval(0.0, 0.0, 0.0)
        assert adv["ckpt_every"] >= 1
        assert adv["interval_s"] > 0
        assert adv["mtbf_is_bound"] is True


# ---------------------------------------------------------------- stitcher


def _two_generation_events():
    """A killed-and-relaunched elastic run, as its merged journal reads.

    gen 0: launches at t=1000, compiles, trains to step 8, commits step 4,
    wedges; the watchdog fires after a 4 s stall; the supervisor restarts
    with 0.5 s backoff. gen 1: a fresh process whose ledger starts at
    t=1020 (12 s after gen 0's last step activity), resumes from step 4,
    re-trains to 6, reaches 10, commits 10, exits cleanly.
    """
    g0_buckets = {
        "productive": 6.0,
        "compile": 1.0,
        "data_wait": 0.5,
        "eval": 0.0,
        "ckpt_save": 0.4,
        "ckpt_restore": 0.0,
        "rollback_recompute": 0.0,
        "restart_downtime": 0.0,
        "hang_latency": 0.0,
        "idle": 4.6,
    }
    g1_buckets = {
        "productive": 4.0,
        "compile": 1.0,
        "data_wait": 0.3,
        "eval": 0.0,
        "ckpt_save": 0.4,
        "ckpt_restore": 0.5,
        "rollback_recompute": 1.0,
        "restart_downtime": 0.0,
        "hang_latency": 0.0,
        "idle": 2.8,
    }
    events = [
        {"ts": 1000.5, "type": "run_start", "generation": 0, "start_step": 0},
        *(
            {"ts": 1000.0 + s, "type": "step", "step": s}
            for s in range(1, 9)
        ),
        {
            "ts": 1004.5,
            "type": "checkpoint_save",
            "step": 4,
            "save_seconds": 0.4,
        },
        {
            "ts": 1012.0,
            "type": "hang_detected",
            "step": 8,
            "stalled_s": 4.0,
            "deadline_s": 4.0,
        },
        # cumulative report emitted by the hang handler: ts − wall_s
        # recovers the gen-0 ledger epoch t=1000
        {
            "ts": 1012.5,
            "type": "goodput_report",
            "generation": 0,
            "wall_s": 12.5,
            "steps": 7,
            "buckets": g0_buckets,
            "reason": "hang",
        },
        {
            "ts": 1016.0,
            "type": "elastic_restart",
            "role": "supervisor",
            "reason": "hang",
            "generation": 1,
            "old_world": 2,
            "new_world": 2,
            "backoff_s": 0.5,
            "restarts_used": 1,
        },
        {"ts": 1020.5, "type": "run_start", "generation": 1, "start_step": 4},
        *(
            {"ts": 1021.0 + i, "type": "step", "step": 5 + i}
            for i in range(6)
        ),
        {
            "ts": 1027.0,
            "type": "checkpoint_save",
            "step": 10,
            "save_seconds": 0.4,
        },
        # gen-1 ledger epoch: 1030 − 10 = 1020
        {
            "ts": 1030.0,
            "type": "goodput_report",
            "generation": 1,
            "wall_s": 10.0,
            "steps": 9,
            "buckets": g1_buckets,
            "reason": "completed",
        },
        {"ts": 1030.0, "type": "shutdown", "reason": "completed", "step": 10},
    ]
    return events


class TestStitchGenerations:
    def test_single_generation_passthrough(self):
        events = [
            {"ts": 10.0, "type": "run_start", "generation": 0, "start_step": 0},
            {"ts": 12.0, "type": "step", "step": 2},
            {
                "ts": 14.0,
                "type": "goodput_report",
                "generation": 0,
                "wall_s": 5.0,  # ledger epoch t=9
                "steps": 2,
                "buckets": {"productive": 3.0, "compile": 1.0, "idle": 1.0},
            },
            {"ts": 14.0, "type": "shutdown", "reason": "completed", "step": 2},
        ]
        g = stitch_generations(events)
        assert g["failures"] == 0 and g["restarts"] == []
        assert g["wall_s"] == pytest.approx(5.0)  # epoch 9 → last ts 14
        assert g["buckets"]["productive"] == pytest.approx(3.0)
        assert g["buckets"]["idle"] == pytest.approx(1.0)  # residual
        assert g["goodput_fraction"] == pytest.approx(0.6)
        assert g["conservation_error"] <= 0.01
        assert g["mtbf_s"] is None

    def test_restart_gap_split_and_lost_work(self):
        g = stitch_generations(_two_generation_events())
        assert g["failures"] == 1
        (r,) = g["restarts"]
        assert r["reason"] == "hang"
        assert r["backoff_s"] == pytest.approx(0.5)
        # gap = gen-1 ledger epoch (1020) − gen-0 last step activity (1008):
        # the watchdog's observed 4 s stall is detection latency, the
        # remaining 8 s is supervisor teardown + backoff + relaunch
        assert r["downtime_s"] == pytest.approx(12.0)
        assert r["detection_s"] == pytest.approx(4.0)
        assert g["buckets"]["hang_latency"] == pytest.approx(4.0)
        assert g["buckets"]["restart_downtime"] == pytest.approx(8.0)
        # lost work: gen 0 executed to step 8 but only step 4 was committed
        assert r["lost_steps"] == 4 and g["steps_lost"] == 4
        assert r["lost_seconds"] == pytest.approx(4 * g["step_time_s"], rel=0.01)
        assert g["steps_committed"] == 10

    def test_stitched_conservation_and_derived_rates(self):
        g = stitch_generations(_two_generation_events())
        wall = g["wall_s"]
        assert wall == pytest.approx(30.0)  # gen-0 epoch 1000 → shutdown 1030
        assert sum(g["buckets"].values()) == pytest.approx(wall, rel=1e-6)
        assert g["conservation_error"] <= 0.01
        # in-process idle is NOT summed (it would double-count the stall
        # the stitch charges to hang_latency); idle is the residual:
        # 30 − gen-0 non-idle 7.9 − gen-1 non-idle 7.2 − gap 12
        assert g["buckets"]["idle"] == pytest.approx(2.9, abs=0.01)
        assert g["buckets"]["productive"] == pytest.approx(10.0)
        assert g["goodput_fraction"] == pytest.approx(10.0 / 30.0, rel=1e-3)
        assert g["mtbf_s"] == pytest.approx(30.0)  # 1 failure over the span
        assert g["save_cost_s"] == pytest.approx(0.4)
        assert g["step_time_s"] == pytest.approx(10.0 / 16)  # 7 + 9 steps

    def test_non_host0_rows_ignored(self):
        events = _two_generation_events()
        # a host-1 report must not double the buckets
        events.append(
            {
                "ts": 1029.0,
                "type": "goodput_report",
                "host": 1,
                "generation": 1,
                "wall_s": 9.0,
                "steps": 9,
                "buckets": {"productive": 99.0},
            }
        )
        g = stitch_generations(events)
        assert g["buckets"]["productive"] == pytest.approx(10.0)

    def test_empty_journal(self):
        g = stitch_generations([])
        assert g["wall_s"] == 0.0 and g["failures"] == 0
        assert g["goodput_fraction"] == 0.0
        assert g["save_cost_s"] is None and g["step_time_s"] is None


# ----------------------------------------------------------- fleet rollup


class TestFleetGoodput:
    def test_fleet_goodput_is_mean_over_live_hosts(self, tmp_path):
        t0 = 1_700_000_000.0
        HostBeacon(tmp_path, host=0).write(
            step=10, now=t0, goodput_fraction=0.8, generation=1
        )
        HostBeacon(tmp_path, host=1).write(
            step=10, now=t0, goodput_fraction=0.6, generation=1
        )
        reg = MetricsRegistry()
        agg = FleetAggregator(tmp_path, expected_hosts=2, registry=reg)
        s = agg.scan(now=t0 + 1)
        assert s["goodput_fraction"] == pytest.approx(0.7)
        assert _gauge_value(reg, "fleet_goodput") == pytest.approx(0.7)
        assert _gauge_value(
            reg, "fleet_goodput_fraction", host="1"
        ) == pytest.approx(0.6)
        assert _gauge_value(reg, "fleet_generation", host="0") == 1.0

    def test_fleet_goodput_absent_without_beacon_field(self, tmp_path):
        t0 = 1_700_000_000.0
        HostBeacon(tmp_path, host=0).write(step=10, now=t0)
        agg = FleetAggregator(tmp_path, expected_hosts=1, registry=MetricsRegistry())
        s = agg.scan(now=t0 + 1)
        assert s["goodput_fraction"] is None


# ---------------------------------------------------------- goodput_doctor


def _write_journal(directory: Path, events: list[dict]) -> None:
    directory.mkdir(parents=True, exist_ok=True)
    with open(directory / "journal-00000.jsonl", "w") as f:
        for i, e in enumerate(events):
            f.write(json.dumps({"seq": i, **e}) + "\n")


class TestGoodputDoctor:
    def test_exit_zero_names_restart_downtime_and_recommends(self, tmp_path):
        import tools.goodput_doctor as doctor

        _write_journal(tmp_path / "journal", _two_generation_events())
        out = tmp_path / "goodput.md"
        assert doctor.main([str(tmp_path), "--out", str(out)]) == 0
        report = out.read_text()
        # the verdict prices the incident: restart downtime is the top
        # non-productive bucket of this stitched run
        assert "top non-productive bucket: **restart downtime**" in report
        assert "conservation: **OK**" in report
        assert "1 restart(s) observed" in report
        assert "stitched across 2 process generation(s)" in report
        # every bucket has a row in the attribution table
        for b in GOODPUT_BUCKETS:
            assert f"| {bucket_display(b)} |" in report
        # restart-cost breakdown: the hang restart with its lost work
        assert "| 1 | hang |" in report
        # and a concrete checkpoint-interval recommendation
        assert "run.ckpt_every=" in report
        assert "√(2·save_cost·MTBF)" in report

    def test_exit_two_without_journal(self, tmp_path):
        import tools.goodput_doctor as doctor

        assert doctor.main([str(tmp_path / "nothing")]) == 2

    def test_advisor_row_degrades_without_checkpoints(self, tmp_path):
        import tools.goodput_doctor as doctor

        events = [
            {"ts": 10.0, "type": "run_start", "generation": 0, "start_step": 0},
            {"ts": 12.0, "type": "shutdown", "reason": "completed", "step": 0},
        ]
        _write_journal(tmp_path / "journal", events)
        out = tmp_path / "goodput.md"
        assert doctor.main([str(tmp_path), "--out", str(out)]) == 0
        assert "not enough data" in out.read_text()


# ------------------------------------------------------ run_doctor timeline


class TestRunDoctorElasticTimeline:
    def test_elastic_lifecycle_events_rendered(self, tmp_path):
        import tools.run_doctor as doctor

        events = [
            {"ts": 1.0, "type": "run_start", "start_step": 0},
            {
                "ts": 2.0,
                "type": "hang_detected",
                "step": 8,
                "stalled_s": 4.0,
                "deadline_s": 4.0,
            },
            {
                "ts": 3.0,
                "type": "elastic_restart",
                "role": "supervisor",
                "reason": "hang",
                "generation": 1,
                "failed_hosts": [1],
                "old_world": 2,
                "new_world": 1,
                "backoff_s": 0.5,
                "restarts_used": 1,
            },
            {
                "ts": 4.0,
                "type": "elastic_resize",
                "cause": "shrink",
                "step": 4,
                "epoch": 0,
                "old_world": 2,
                "new_world": 1,
                "shards_total": 8,
                "shards_consumed": 3,
                "shards_remaining": 5,
            },
            {
                "ts": 5.0,
                "type": "ckpt_fallback",
                "from_step": 8,
                "to_step": 4,
                "error": "manifest truncated",
            },
            {
                "ts": 6.0,
                "type": "elastic_rejoin",
                "role": "supervisor",
                "generation": 2,
                "old_world": 1,
                "new_world": 2,
            },
            {"ts": 7.0, "type": "shutdown", "reason": "completed", "step": 10},
        ]
        _write_journal(tmp_path / "journal", events)
        out = tmp_path / "report.md"
        assert doctor.main([str(tmp_path), "--out", str(out)]) == 0
        report = out.read_text()
        assert "hang_detected" in report
        assert "no progress for 4.0s" in report
        assert "elastic_restart" in report
        assert "gen 1: hang, world 2 → 1" in report
        assert "elastic_resize" in report
        assert "shrink: world 2 → 1 at step 4" in report
        assert "5/8 shards unconsumed" in report
        assert "ckpt_fallback" in report
        assert "restore walked back step 8 → 4" in report
        assert "elastic_rejoin" in report
        assert "graceful restart back to full size" in report


# -------------------------------------- conservation on real train() runs
#
# Property: after any in-process run — clean or faulted — the journal's
# final goodput_report conserves wall-clock (attribution error ≤ 1%) and
# its buckets account for the failure mode the plan injected. Slow: the
# CI goodput chaos smoke runs these alongside the supervisor-level legs.


def _smoke_overrides(tmp_path, steps, extra=()):
    return [
        f"run.output_dir={tmp_path}",
        f"run.training_steps={steps}",
        f"optim.training_steps={steps}",
        "run.sanity_eval=false",
        "run.log_interval=2",
        "run.eval_interval=4",
        *extra,
    ]


def _final_report(run_dir: Path) -> dict:
    events = read_journal(run_dir / "journal")
    reports = [e for e in events if e["type"] == "goodput_report"]
    assert reports, "run emitted no goodput_report events"
    assert events[-1]["type"] == "shutdown"
    # the shutdown-adjacent report is the cumulative final word
    return reports[-1]


def _assert_conserved(rep: dict) -> None:
    assert rep["conservation_error"] <= 0.01, rep
    total = sum(rep["buckets"].values())
    assert total == pytest.approx(rep["wall_s"], rel=0.02, abs=0.05), rep
    assert rep["attributed_s"] + rep["idle_s"] == pytest.approx(
        rep["wall_s"], rel=0.01, abs=0.02
    )


@pytest.mark.slow
def test_conservation_clean_run(tmp_path):
    from jumbo_mae_tpu_tpu.cli.train import train

    train(
        load_config(
            RECIPES / "smoke_cpu.yaml", _smoke_overrides(tmp_path, 8)
        )
    )
    rep = _final_report(tmp_path / "smoke_cpu")
    _assert_conserved(rep)
    assert rep["steps"] == 7  # 8 dispatches − the compile dispatch
    assert rep["buckets"]["productive"] > 0
    assert rep["buckets"]["compile"] > 0  # first dispatch traced+compiled
    assert rep["buckets"]["ckpt_save"] > 0
    assert rep["buckets"]["rollback_recompute"] == 0.0
    assert rep["reason"] == "completed"
    assert rep["generation"] == 0
    # interval checkpoints at 4 and 8 each journaled a cumulative report,
    # monotone in wall-clock
    events = read_journal(tmp_path / "smoke_cpu" / "journal")
    walls = [
        e["wall_s"] for e in events if e["type"] == "goodput_report"
    ]
    assert len(walls) >= 3  # ckpt@4, ckpt@8, shutdown
    assert walls == sorted(walls)


@pytest.mark.slow
def test_conservation_under_nan_rollback(tmp_path, fault_plan):
    """NaN at steps 5-7 → sentinel rollback to step 4 → the re-trained
    ground is recompute, not productive — and the books still balance."""
    from jumbo_mae_tpu_tpu.cli.train import train

    final = train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(
                tmp_path,
                12,
                [
                    "run.faults=train.loss:nan@n=4..6",
                    "run.log_interval=1",
                    "run.sentinel_patience=3",
                ],
            ),
        )
    )
    assert math.isfinite(final["train/loss"])
    rep = _final_report(tmp_path / "smoke_cpu")
    _assert_conserved(rep)
    assert rep["recompute_steps"] > 0
    assert rep["buckets"]["rollback_recompute"] > 0
    assert rep["buckets"]["ckpt_restore"] > 0  # the rollback restored


@pytest.mark.slow
def test_conservation_under_slow_checkpoint(tmp_path, fault_plan):
    """An injected 0.5 s checkpoint-save delay lands in ckpt_save — the
    ledger prices the save, it does not vanish into idle."""
    from jumbo_mae_tpu_tpu.cli.train import train

    train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(
                tmp_path, 8, ["run.faults=ckpt.save:delay(0.5)@n<1"]
            ),
        )
    )
    rep = _final_report(tmp_path / "smoke_cpu")
    _assert_conserved(rep)
    assert rep["buckets"]["ckpt_save"] >= 0.5


@pytest.mark.slow
def test_conservation_under_fleet_wedge(tmp_path, fault_plan):
    """A 1 s collective wedge (no hangwatch — in-process) shows up as
    non-productive time and the invariant holds."""
    from jumbo_mae_tpu_tpu.cli.train import train

    train(
        load_config(
            RECIPES / "smoke_cpu.yaml",
            _smoke_overrides(
                tmp_path, 8, ["run.faults=fleet.wedge:delay(1.0)@n<1"]
            ),
        )
    )
    rep = _final_report(tmp_path / "smoke_cpu")
    _assert_conserved(rep)
    # the wedge second is real wall-clock somewhere non-productive
    nonprod = rep["wall_s"] - rep["buckets"]["productive"]
    assert nonprod >= 1.0
