"""Telemetry subsystem contracts (jumbo_mae_tpu_tpu/obs).

What the subsystem stands on:

- the registry is exact under concurrent writers (serving threads all hit
  the same counters/histograms);
- histogram buckets follow Prometheus ``le`` semantics bit-exactly (a
  scraper's histogram_quantile depends on it);
- the text exposition is stable (golden) and parseable;
- ``/metrics`` and ``/healthz`` work over a real socket, and health flips
  with readiness/liveness;
- spans aggregate into the registry and export chrome-trace JSON;
- engine + micro-batcher traffic populates the serving metrics the
  acceptance criteria name (request latency, batch occupancy, bucket-cache
  hits/misses).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.obs import (
    NULL_REGISTRY,
    HealthState,
    MetricsRegistry,
    TelemetryServer,
    get_registry,
    set_registry,
    span,
)
from jumbo_mae_tpu_tpu.obs.trace import (
    export_chrome_trace,
    span_timer,
    start_chrome_trace,
    stop_chrome_trace,
)

# ---------------------------------------------------------------- registry


def test_counter_exact_under_threads():
    reg = MetricsRegistry()
    c = reg.counter("hits_total", "x", labels=("who",))
    h = reg.histogram("lat_seconds", buckets=(0.5, 1.0))
    n_threads, n_incs = 8, 1000

    def worker(i):
        child = c.labels(str(i % 2))
        for _ in range(n_incs):
            child.inc()
            h.observe(0.25)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = c.labels("0").value + c.labels("1").value
    assert total == n_threads * n_incs
    assert h.count == n_threads * n_incs
    assert h.sum == pytest.approx(0.25 * n_threads * n_incs)


def test_histogram_bucket_edges():
    reg = MetricsRegistry()
    h = reg.histogram("h", buckets=(1.0, 2.0, 5.0))
    # Prometheus le semantics: value == bound lands IN that bucket
    for v in (0.5, 1.0, 1.5, 2.0, 5.0, 7.0):
        h.observe(v)
    cum = dict(h.cumulative())
    assert cum[1.0] == 2  # 0.5, 1.0
    assert cum[2.0] == 4  # + 1.5, 2.0
    assert cum[5.0] == 5  # + 5.0
    assert cum[float("inf")] == 6  # + 7.0
    assert h.quantile(0.5) == 2.0
    assert h.quantile(1.0) == float("inf")


def test_histogram_rejects_unsorted_buckets():
    with pytest.raises(ValueError):
        MetricsRegistry().histogram("bad", buckets=(2.0, 1.0))


def test_registry_type_and_label_conflicts():
    reg = MetricsRegistry()
    reg.counter("a_total")
    with pytest.raises(ValueError, match="already registered as counter"):
        reg.gauge("a_total")
    reg.counter("b_total", labels=("x",))
    with pytest.raises(ValueError, match="labels"):
        reg.counter("b_total", labels=("y",))
    # re-registration with the same schema returns the same family
    assert reg.counter("a_total") is reg.counter("a_total")


def test_prometheus_golden_output():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests served", labels=("task",)).labels(
        "features"
    ).inc(3)
    reg.gauge("depth", "queue depth").set(2)
    reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0)).observe(0.05)
    assert reg.render() == (
        "# HELP depth queue depth\n"
        "# TYPE depth gauge\n"
        "depth 2\n"
        "# HELP lat_seconds latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="1"} 1\n'
        'lat_seconds_bucket{le="+Inf"} 1\n'
        "lat_seconds_sum 0.05\n"
        "lat_seconds_count 1\n"
        "# HELP req_total requests served\n"
        "# TYPE req_total counter\n"
        'req_total{task="features"} 3\n'
    )


def test_label_escaping():
    reg = MetricsRegistry()
    reg.counter("c_total", labels=("p",)).labels('a"b\\c\nd').inc()
    assert 'c_total{p="a\\"b\\\\c\\nd"} 1' in reg.render()


def test_null_registry_and_swap():
    prev = set_registry(NULL_REGISTRY)
    try:
        c = get_registry().counter("dropped_total")
        c.inc(100)
        assert c.value == 0.0
        assert get_registry().render() == ""
    finally:
        set_registry(prev)
    # after restore, new handles record again
    get_registry().counter("kept_total").inc()
    assert get_registry().counter("kept_total").value >= 1


# ---------------------------------------------------------------- exporter


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_exporter_metrics_and_healthz_over_socket():
    reg = MetricsRegistry()
    reg.counter("served_total", "x").inc(7)
    health = HealthState()
    with TelemetryServer(reg, health, host="127.0.0.1", port=0) as srv:
        url = f"http://127.0.0.1:{srv.port}"
        # not ready yet → 503 with a JSON body
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{url}/healthz", timeout=10)
        assert e.value.code == 503
        assert json.loads(e.value.read().decode())["ready"] is False

        health.set_ready(True)
        status, body = _get(f"{url}/healthz")
        assert status == 200 and json.loads(body)["ok"] is True

        status, body = _get(f"{url}/metrics")
        assert status == 200
        assert "served_total 7" in body

        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{url}/nope", timeout=10)
        assert e.value.code == 404


def test_healthz_liveness_heartbeats():
    health = HealthState(ready=True)
    health.watch("step", max_age_s=0.2)
    ok, report = health.report()
    assert not ok  # watched but never beaten → not live
    assert report["checks"]["step"]["age_s"] is None
    health.beat("step")
    ok, report = health.report()
    assert ok and report["checks"]["step"]["ok"]
    time.sleep(0.25)
    ok, report = health.report()
    assert not ok  # stale heartbeat
    health.unwatch("step")
    ok, _ = health.report()
    assert ok


# ------------------------------------------------------------------- spans


def test_span_aggregates_into_registry():
    reg = MetricsRegistry()
    for _ in range(3):
        with span("stage_a", registry=reg):
            pass
    snap = reg.snapshot()
    assert snap["span_seconds"]["stage_a"]["count"] == 3
    assert snap["span_seconds"]["stage_a"]["sum"] >= 0


def test_span_timer_reuse_and_last_s():
    reg = MetricsRegistry()
    st = span_timer("loop", registry=reg)
    with st:
        time.sleep(0.01)
    assert st.last_s >= 0.01
    st.observe(0.5)
    snap = reg.snapshot()["span_seconds"]["loop"]
    assert snap["count"] == 2
    assert snap["sum"] >= 0.51


def test_chrome_trace_export(tmp_path):
    reg = MetricsRegistry()
    start_chrome_trace()
    try:
        with span("traced", registry=reg):
            pass
        path = export_chrome_trace(tmp_path / "trace.json")
    finally:
        stop_chrome_trace()
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    assert len(events) == 1
    (evt,) = events
    assert evt["name"] == "traced" and evt["ph"] == "X"
    assert evt["dur"] >= 0 and "ts" in evt and "pid" in evt
    # spans outside a capture window must not leak into a later export
    with span("untraced", registry=reg):
        pass


# ------------------------------------------------------- compat shims


def test_utils_shims_point_at_obs():
    from jumbo_mae_tpu_tpu.obs import metrics as obs_metrics
    from jumbo_mae_tpu_tpu.obs import mfu as obs_mfu
    from jumbo_mae_tpu_tpu.utils import meters, mfu, profiling

    assert meters.AverageMeter is obs_metrics.AverageMeter
    assert mfu.mfu_report is obs_mfu.mfu_report
    assert mfu.detect_peak_tflops is obs_mfu.detect_peak_tflops
    from jumbo_mae_tpu_tpu.obs.trace import trace as obs_trace

    assert profiling.trace is obs_trace


# --------------------------------------------- engine integration (serve)


@pytest.fixture(scope="module")
def served():
    """A tiny engine + micro-batcher driving real traffic into a fresh
    registry; returns (registry, engine, batch_sizes)."""
    from pathlib import Path

    from jumbo_mae_tpu_tpu.config import load_config
    from jumbo_mae_tpu_tpu.infer import InferenceEngine, MicroBatcher

    recipe = Path(__file__).resolve().parent.parent / "recipes" / "smoke_cpu.yaml"
    cfg = load_config(
        recipe,
        [
            "model.overrides.dtype=float32",
            "model.dec_layers=1",
            "model.dec_dim=32",
            "model.dec_heads=2",
            "model.dec_dtype=float32",
        ],
    )
    reg = MetricsRegistry()
    engine = InferenceEngine(cfg, max_batch=8, registry=reg)
    images = (
        np.random.RandomState(0).randint(0, 256, (24, 32, 32, 3)).astype(np.uint8)
    )
    with MicroBatcher(
        lambda b: engine.features(b), max_batch=8, max_delay_ms=20.0,
        registry=reg,
    ) as mb:
        futs = [mb.submit(img) for img in images]
        rows = [f.result() for f in futs]
        sizes = list(mb.batch_sizes)
    assert len(rows) == 24
    return reg, engine, sizes


def test_engine_traffic_populates_serving_metrics(served):
    reg, _, sizes = served
    snap = reg.snapshot()
    n_requests = 24
    # request latency: one observation per submitted request
    assert snap["infer_request_latency_seconds"][""]["count"] == n_requests
    assert snap["infer_request_latency_seconds"][""]["sum"] > 0
    # batch occupancy: one observation per flushed batch
    assert snap["infer_batch_occupancy"][""]["count"] == len(sizes) > 0
    assert snap["infer_requests_total"][""] == n_requests
    assert snap["infer_batches_total"][""] == len(sizes)
    # bucket-cache: first batch at each bucket compiles (miss), the rest hit
    hits = sum(snap["infer_bucket_cache_hits_total"].values())
    misses = sum(snap["infer_bucket_cache_misses_total"].values())
    assert misses >= 1
    assert hits + misses == len(sizes)
    assert snap["infer_images_total"]["features"] == n_requests
    assert snap["infer_predict_seconds"]["features"]["count"] == len(sizes)
    assert snap["infer_compile_seconds"]["features:cls"]["count"] == misses


def test_engine_metrics_render_for_scrape(served):
    reg, _, _ = served
    text = reg.render()
    for needle in (
        "infer_request_latency_seconds_bucket",
        "infer_request_latency_seconds_count",
        "infer_batch_occupancy_bucket",
        "infer_bucket_cache_misses_total",
        "infer_queue_depth",
    ):
        assert needle in text, f"{needle} missing from scrape"


def test_batcher_error_counts_failed_requests():
    from jumbo_mae_tpu_tpu.infer import MicroBatcher

    reg = MetricsRegistry()

    def boom(batch):
        raise RuntimeError("kaput")

    with MicroBatcher(boom, max_batch=4, max_delay_ms=1.0, registry=reg) as mb:
        fut = mb.submit(np.zeros((2, 2, 3), np.uint8))
        with pytest.raises(RuntimeError, match="kaput"):
            fut.result(timeout=10)
    snap = reg.snapshot()
    assert snap["infer_requests_failed_total"][""] == 1
    # no latency recorded for failed requests
    assert snap["infer_request_latency_seconds"][""]["count"] == 0
