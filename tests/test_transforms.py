"""Augmentation tests: distribution bounds, determinism, policy grammar."""

import numpy as np
import pytest

from jumbo_mae_tpu_tpu.data.randaugment import (
    AugMix,
    AutoAugment,
    RandAugment,
    auto_augment_factory,
)
from jumbo_mae_tpu_tpu.data.transforms import (
    adjust_brightness,
    center_crop,
    color_jitter,
    eval_transform,
    random_erasing,
    random_hflip,
    random_resized_crop,
    resize,
    simple_resize_crop,
)


def _img(h=48, w=64, seed=0):
    return np.random.default_rng(seed).integers(0, 256, (h, w, 3), dtype=np.uint8)


def test_resize_and_center_crop_shapes():
    img = _img()
    assert resize(img, (32, 32)).shape == (32, 32, 3)
    assert center_crop(img, 32).shape == (32, 32, 3)
    assert center_crop(_img(16, 16), 32).shape == (32, 32, 3)  # pad-to-fit


def test_eval_transform_matches_reference_geometry():
    # 224 target, crop ratio 0.875 → resize shorter side to 256 then crop
    out = eval_transform(_img(300, 400), 224, crop_ratio=0.875)
    assert out.shape == (224, 224, 3)


def test_random_resized_crop_deterministic_and_shaped():
    img = _img()
    a = random_resized_crop(np.random.default_rng(5), img, 32)
    b = random_resized_crop(np.random.default_rng(5), img, 32)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (32, 32, 3) and a.dtype == np.uint8


def test_src_mode_pads_and_crops():
    out = simple_resize_crop(np.random.default_rng(0), _img(), 32)
    assert out.shape == (32, 32, 3)


def test_hflip_probability_extremes():
    img = _img()
    np.testing.assert_array_equal(random_hflip(np.random.default_rng(0), img, 0.0), img)
    np.testing.assert_array_equal(
        random_hflip(np.random.default_rng(0), img, 1.0), img[:, ::-1]
    )


def test_brightness_identity_and_black():
    img = _img()
    np.testing.assert_array_equal(adjust_brightness(img, 1.0), img)
    assert adjust_brightness(img, 0.0).max() == 0


def test_color_jitter_zero_strength_is_identity():
    img = _img()
    np.testing.assert_array_equal(color_jitter(np.random.default_rng(0), img, 0.0), img)


def test_random_erasing_probability_and_noise():
    img = _img()
    np.testing.assert_array_equal(random_erasing(np.random.default_rng(0), img, 0.0), img)
    out = random_erasing(np.random.default_rng(0), img, 1.0)
    assert out.shape == img.shape
    assert (out != img).any()  # some rect was erased
    # input not mutated
    np.testing.assert_array_equal(img, _img())


def test_randaugment_runs_and_is_deterministic():
    aug = RandAugment(magnitude=9, num_layers=2, mstd=0.5, increasing=True)
    img = _img()
    a = aug(np.random.default_rng(3), img)
    b = aug(np.random.default_rng(3), img)
    np.testing.assert_array_equal(a, b)
    assert a.shape == img.shape and a.dtype == np.uint8


def test_augmix_and_autoaugment_run():
    img = _img()
    out = AugMix(magnitude=3, width=3)(np.random.default_rng(0), img)
    assert out.shape == img.shape and out.dtype == np.uint8
    out = AutoAugment()(np.random.default_rng(0), img)
    assert out.shape == img.shape


def test_policy_grammar():
    ra = auto_augment_factory("rand-m9-mstd0.5-inc1")
    assert isinstance(ra, RandAugment)
    assert ra.magnitude == 9 and ra.mstd == 0.5 and ra.increasing
    am = auto_augment_factory("augmix-m3-w4-d2")
    assert isinstance(am, AugMix) and am.width == 4 and am.depth == 2
    assert isinstance(auto_augment_factory("original"), AutoAugment)
    assert auto_augment_factory("none") is None
    assert auto_augment_factory("") is None
    with pytest.raises(ValueError):
        auto_augment_factory("rand-__bogus__")


def test_all_randaugment_ops_apply_at_extremes():
    """Every op in the table must run at level 0 and 10 without error."""
    from jumbo_mae_tpu_tpu.data.randaugment import _OPS, _apply_op
    from PIL import Image

    pil = Image.fromarray(_img())
    rng = np.random.default_rng(0)
    for name in _OPS:
        for level in (0.0, 10.0):
            for inc in (False, True):
                out = _apply_op(pil, name, rng, level, 0.0, inc)
                assert out.size == pil.size
